"""Device connected-components primitive (ops/components.py): partition
parity vs the host scipy oracle on randomized planted graphs (including
disconnected columns, empty membership, single-node and isolated-node
components), fused size/edge-stat correctness, backend equivalence of the
quality pipeline's discrete moves, and the device quality path's transfer
contract — at most ONE full-F download per repair round and zero
model.fit host round trips (ISSUE 2 acceptance)."""

import numpy as np
import pytest

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.models import BigClamModel
from bigclam_tpu.models.agm import sample_planted_graph
from bigclam_tpu.models.quality import (
    _graph_components,
    atomize_reassign,
    repair_communities,
)
from bigclam_tpu.ops.components import (
    column_component_stats,
    components_from_labels,
    device_edges,
    graph_components_device,
)
from bigclam_tpu.ops.extraction import delta_threshold


def _partition(comps):
    return {frozenset(int(x) for x in c) for c in comps}


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_labels_match_scipy_oracle_random_membership(seed):
    """Random thresholded-column memberships over a planted graph: the
    device labels must induce exactly the host oracle's partition per
    column, and the fused stats must equal brute-force counts."""
    rng = np.random.default_rng(seed)
    g, _ = sample_planted_graph(500, 20, p_in=0.3, rng=rng)
    n = g.num_nodes
    c_total = 12
    member = rng.random((c_total, n)) < rng.uniform(0.0, 0.4, (c_total, 1))
    member[0] = False                           # empty membership
    member[1] = False
    member[1, int(rng.integers(n))] = True      # single-node component
    member[2] = True                            # the whole graph
    labels, sizes, counts = column_component_stats(
        member, *device_edges(g), n
    )
    for c in range(c_total):
        mem = np.flatnonzero(member[c])
        host = _partition(_graph_components(mem, g.indptr, g.indices))
        dev = _partition(components_from_labels(labels[c], n))
        assert host == dev, c
        for comp in components_from_labels(labels[c], n):
            assert np.all(sizes[c][comp] == comp.size)
            cs = set(comp.tolist())
            cnt = sum(
                1
                for u in comp
                for v in g.indices[g.indptr[u]: g.indptr[u + 1]]
                if int(v) in cs
            )
            assert np.all(counts[c][comp] == cnt)
        out = np.setdiff1d(np.arange(n), mem)
        assert np.all(labels[c][out] == n)      # sentinel on non-members
        assert np.all(sizes[c][out] == 0)


def test_disconnected_graph_batching_and_singletons():
    """Disjoint cliques + isolated nodes: one component per clique,
    singleton components for isolated members, batched execution
    identical to the single-batch pass."""
    from bigclam_tpu.graph.ingest import graph_from_edges

    edges = []
    for b in range(6):                           # six disjoint 5-cliques
        base = b * 5
        for i in range(5):
            for j in range(i + 1, 5):
                edges.append((base + i, base + j))
    g = graph_from_edges(edges, num_nodes=32)    # nodes 30, 31 isolated
    n = g.num_nodes
    member = np.ones((4, n), bool)
    member[1, :10] = False                       # first two cliques out
    member[2] = False                            # empty column
    member[3] = False
    member[3, 30] = True                         # isolated singletons only
    member[3, 31] = True
    labels, sizes, counts = column_component_stats(
        member, *device_edges(g), n
    )
    want = {frozenset(range(b * 5, b * 5 + 5)) for b in range(6)}
    want |= {frozenset({30}), frozenset({31})}
    assert _partition(components_from_labels(labels[0], n)) == want
    assert _partition(components_from_labels(labels[2], n)) == set()
    assert labels[3][30] == 30 and labels[3][31] == 31
    assert sizes[3][30] == 1 and counts[3][31] == 0
    batched = column_component_stats(
        member, *device_edges(g), n, col_batch=3
    )
    for a, b_ in zip((labels, sizes, counts), batched):
        np.testing.assert_array_equal(a, b_)
    # single-set wrapper parity (the oracle-surface twin)
    mem = np.flatnonzero(member[1])
    assert _partition(graph_components_device(mem, g)) == _partition(
        _graph_components(mem, g.indptr, g.indices)
    )


def test_atomize_backends_agree():
    """atomize_reassign host vs device backends on a shifted partition:
    identical reassigned F (the deterministic (-size, min-id) atom order
    makes the greedy backend-independent)."""
    rng = np.random.default_rng(5)
    g, truth = sample_planted_graph(600, 25, p_in=0.4, rng=rng)
    k = len(truth)
    delta = delta_threshold(g.num_nodes, g.num_edges)
    F = np.zeros((g.num_nodes, k))
    for c in range(k):                  # shifted: block c + half of c+1
        nxt = truth[(c + 1) % k]
        F[truth[c], c] = 1.0
        F[nxt[: len(nxt) // 2], c] = 1.0
    F_h, n_h = atomize_reassign(F, g, delta, k, components="host")
    F_d, n_d = atomize_reassign(F, g, delta, k, components="device")
    assert n_h == n_d > 0
    np.testing.assert_allclose(F_h, F_d, rtol=0, atol=0)


def test_repair_backends_agree():
    """repair_communities host vs device backends on the constructed
    merge+fragment defect fixture: identical repaired F."""
    g, truth = sample_planted_graph(
        240, 10, p_in=0.5, rng=np.random.default_rng(3)
    )
    k = 10
    F = np.zeros((g.num_nodes, k))
    for c in range(3, 10):
        F[truth[c], c] = 1.0
    F[truth[0] + truth[1], 0] = 1.0      # merged blocks 0+1 on column 0
    half = len(truth[2]) // 2
    F[truth[2][:half], 1] = 1.0          # block 2 fragmented over 1 and 2
    F[truth[2][half:], 2] = 1.0
    delta = delta_threshold(g.num_nodes, g.num_edges)
    F_h, n_h = repair_communities(F, g, delta, k, components="host")
    F_d, n_d = repair_communities(F, g, delta, k, components="device")
    assert n_h == n_d == 1
    np.testing.assert_allclose(F_h, F_d, rtol=0, atol=0)


@pytest.fixture(scope="module")
def quality_fixture():
    rng = np.random.default_rng(7)
    g, truth = sample_planted_graph(600, 25, p_in=0.3, rng=rng)
    k = len(truth)
    cfg = BigClamConfig(
        num_communities=k, quality_mode=True, restart_cycles=2,
        restart_tol=0.0, use_pallas=False, use_pallas_csr=False,
    )
    from bigclam_tpu.ops import seeding

    seeds = seeding.conductance_seeds(g, cfg)
    F0 = seeding.init_F(g, seeds, cfg, np.random.default_rng(0))
    return g, cfg, F0


def test_device_quality_transfer_contract(quality_fixture):
    """The residency pin: fit_quality_device's discrete stage performs at
    most ONE full-F device->host download per repair round (plus the
    single final result fetch), never calls model.fit (the host F
    round-trip entry), and reports the same counts in its stage profile
    that the monkeypatched trainer observed."""
    from bigclam_tpu.models.quality import fit_quality_device

    g, cfg, F0 = quality_fixture
    model = BigClamModel(g, cfg)
    fetches = []
    orig_extract = model.extract_F

    def counting_extract(state):
        fetches.append(1)
        return orig_extract(state)

    model.extract_F = counting_extract

    def no_fit(*a, **kw):
        raise AssertionError(
            "device quality path must not call model.fit "
            "(host F upload + download per refit)"
        )

    model.fit = no_fit
    qres = fit_quality_device(model, F0)
    counts = qres.stages["counts"]
    rounds = counts.get("repair_rounds", 0)
    assert rounds >= 1                    # the discrete stage ran
    assert len(fetches) <= rounds + 1     # <=1/round + the result fetch
    assert counts["f_device_fetches"] == len(fetches)
    assert counts["f_host_uploads"] == 1  # the single init_state upload
    assert "anneal" in qres.stages["seconds"]
    assert "repair_detect" in qres.stages["seconds"]


def test_device_repair_checkpoint_resume(quality_fixture, tmp_path):
    """Repair-round checkpointing wired through fit_quality_device: a
    rerun on the same directory restores the completed stage (no discrete
    refits redone — only the deterministic annealing cycles re-run) and
    reproduces the result exactly."""
    from bigclam_tpu.models.quality import fit_quality_device
    from bigclam_tpu.utils.checkpoint import CheckpointManager

    g, cfg, F0 = quality_fixture
    model = BigClamModel(g, cfg)
    cm = CheckpointManager(str(tmp_path / "q"))
    r1 = fit_quality_device(model, F0, checkpoints=cm)

    calls = []
    orig_fit_state = model.fit_state

    def counting_fit_state(state, **kw):
        calls.append(1)
        return orig_fit_state(state, **kw)

    model.fit_state = counting_fit_state
    r2 = fit_quality_device(model, F0, checkpoints=cm)
    # run 2: only the annealing cycles re-ran; the repair stage restored
    # its 'done' checkpoint and scheduled zero refits
    assert len(calls) == r2.num_cycles
    assert r2.fit.llh == r1.fit.llh
    assert r2.num_repairs == r1.num_repairs
    np.testing.assert_array_equal(r2.fit.F, r1.fit.F)
