"""JAX single-chip core vs the NumPy spec interpreter (SURVEY.md §4.2):
the device kernels must reproduce the oracle's F and LLH trajectories
bit-tightly in float64 on CPU."""

import numpy as np
import pytest

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.models.bigclam import BigClamModel, prepare_graph
from bigclam_tpu.ops import linesearch as ls_ops
from bigclam_tpu.ops import objective as obj_ops
from bigclam_tpu.spec import interpreter as spec

CFG = BigClamConfig(num_communities=4, dtype="float64")


def _rand_F(seed, n, k):
    return np.random.default_rng(seed).uniform(0.1, 1.0, size=(n, k))


def _device_inputs(g, cfg, F):
    import jax.numpy as jnp

    edges, n_pad = prepare_graph(g, cfg, dtype=jnp.float64)
    assert n_pad == g.num_nodes
    Fd = jnp.asarray(F)
    return edges, Fd, Fd.sum(axis=0)


def test_grad_llh_matches_spec(toy_graphs):
    for name, g in toy_graphs.items():
        F = _rand_F(0, g.num_nodes, 4)
        edges, Fd, sumFd = _device_inputs(g, CFG, F)
        grad_j, node_llh_j = obj_ops.grad_llh(Fd, sumFd, edges, CFG)
        grad_s, node_llh_s = spec.grad_llh(F, F.sum(0), g, CFG)
        np.testing.assert_allclose(np.asarray(grad_j), grad_s, rtol=1e-12, err_msg=name)
        np.testing.assert_allclose(
            np.asarray(node_llh_j), node_llh_s, rtol=1e-12, err_msg=name
        )


def test_loglikelihood_matches_spec(toy_graphs):
    g = toy_graphs["two_cliques"]
    F = _rand_F(1, g.num_nodes, 4)
    edges, Fd, sumFd = _device_inputs(g, CFG, F)
    llh_j = float(obj_ops.loglikelihood(Fd, sumFd, edges, CFG))
    llh_s = spec.loglikelihood(F, F.sum(0), g, CFG)
    assert np.isclose(llh_j, llh_s, rtol=1e-12)


def test_single_step_matches_spec(toy_graphs):
    for name, g in toy_graphs.items():
        F = _rand_F(2, g.num_nodes, 4)
        edges, Fd, sumFd = _device_inputs(g, CFG, F)
        grad, node_llh = obj_ops.grad_llh(Fd, sumFd, edges, CFG)
        cand = ls_ops.candidates_pass(Fd, grad, edges, CFG)
        F1_j, sumF1_j = ls_ops.armijo_update(Fd, sumFd, grad, node_llh, cand, CFG)
        F1_s, sumF1_s, _ = spec.line_search_step(F, F.sum(0), g, CFG)
        np.testing.assert_allclose(np.asarray(F1_j), F1_s, rtol=1e-12, err_msg=name)
        np.testing.assert_allclose(np.asarray(sumF1_j), sumF1_s, rtol=1e-12)


def test_trajectory_matches_spec_chunked(toy_graphs):
    """Multi-iteration trajectory with a tiny edge_chunk to force chunked
    sweeps; F must track the oracle through several Jacobi updates."""
    g = toy_graphs["two_cliques"]
    cfg = CFG.replace(edge_chunk=8, max_iters=5, conv_tol=0.0)  # never converge
    F = _rand_F(3, g.num_nodes, 4)
    model = BigClamModel(g, cfg)
    state = model.init_state(F)
    Fs, sumFs = F.copy(), F.sum(0)
    for _ in range(5):
        state = model._step(state)
        Fs, sumFs, _ = spec.line_search_step(Fs, sumFs, g, cfg)
    np.testing.assert_allclose(np.asarray(state.F), Fs, rtol=1e-11)


def test_fit_matches_spec_facebook(facebook_graph):
    """BASELINE config-1-shaped run: facebook_combined K=25, few iterations,
    device trajectory vs oracle trajectory (SURVEY.md §4.2)."""
    g = facebook_graph
    cfg = BigClamConfig(num_communities=25, dtype="float64", max_iters=3)
    rng = np.random.default_rng(0)
    F0 = rng.integers(0, 2, size=(g.num_nodes, 25)).astype(np.float64)
    model = BigClamModel(g, cfg)
    res = model.fit(F0)
    st = spec.fit(F0, g, cfg)
    assert res.num_iters == st.num_iters
    np.testing.assert_allclose(res.F, st.F, rtol=1e-9)
    assert np.isclose(res.llh, st.llh, rtol=1e-12)


def test_padding_inert(toy_graphs):
    """Node and K padding must not change the trajectory (all-zero rows and
    columns are mathematically inert — ops/objective.py docstring)."""
    g = toy_graphs["two_cliques"]
    F = _rand_F(4, g.num_nodes, 4)
    plain = BigClamModel(g, CFG.replace(max_iters=3, conv_tol=0.0))
    padded = BigClamModel(
        g, CFG.replace(max_iters=3, conv_tol=0.0), node_multiple=16, k_multiple=8
    )
    assert padded.n_pad > g.num_nodes and padded.k_pad > 4
    s1, s2 = plain.init_state(F), padded.init_state(F)
    for _ in range(3):
        s1, s2 = plain._step(s1), padded._step(s2)
    np.testing.assert_allclose(
        np.asarray(s2.F[: g.num_nodes, :4]), np.asarray(s1.F), rtol=1e-12
    )
    # padded rows/cols stayed identically zero
    assert np.all(np.asarray(s2.F[g.num_nodes :]) == 0)
    assert np.all(np.asarray(s2.F[:, 4:]) == 0)


def test_fit_convergence_state_matches_spec(toy_graphs):
    """When the tolerance fires, fit must return the same final F and
    iteration count as the oracle (the speculative extra update discarded)."""
    g = toy_graphs["two_cliques"]
    cfg = CFG.replace(conv_tol=1e-4, max_iters=200)
    F0 = _rand_F(5, g.num_nodes, 4)
    res = BigClamModel(g, cfg).fit(F0)
    st = spec.fit(F0, g, cfg)
    assert res.num_iters == st.num_iters
    np.testing.assert_allclose(res.F, st.F, rtol=1e-10)
    assert np.isclose(res.llh, st.llh, rtol=1e-12)


def test_edge_terms_stable_below_f32_floor():
    """The -expm1 form of 1-p keeps full f32 RELATIVE precision for tiny
    edge dots — the regime where the naive 1 - exp(-x) collapses to 0 and
    froze the quality-mode MAX_P_ relaxation at amp 1e6 (VERDICT r4 item
    3; models/quality.py relaxation notes)."""
    import jax.numpy as jnp

    cfg = BigClamConfig(num_communities=4, max_p=1.0 - 1e-12)
    for x in (1e-10, 1e-8, 1e-5):
        omp, ell = obj_ops.edge_terms(jnp.float32(x), cfg)
        # naive f32: 1 - clip(exp(-x)) == 0 for x < 2^-24 — unusable
        np.testing.assert_allclose(float(omp), x, rtol=1e-5)
        np.testing.assert_allclose(float(ell), np.log(x) + x, rtol=1e-5)
    # the clip floor still binds: amp is capped at 1/(1-max_p)
    omp_clip, _ = obj_ops.edge_terms(jnp.float32(1e-14), cfg)
    np.testing.assert_allclose(float(omp_clip), 1e-12, rtol=1e-4)  # f32 repr
    # f64 path agrees with the spec's subtraction form at moderate x
    omp64, _ = obj_ops.edge_terms(jnp.float64(0.3), CFG)
    np.testing.assert_allclose(float(omp64), 1.0 - np.exp(-0.3), rtol=1e-14)


def test_fit_permutation_invariance(toy_graphs):
    """SURVEY §4.5 property: relabeling node ids permutes the fit result
    and leaves the LLH trajectory unchanged (float64; summation order
    differs across labelings, so exact-math equality holds to ~1e-9)."""
    g = toy_graphs["two_cliques"]
    n = g.num_nodes
    cfg = BigClamConfig(num_communities=4, dtype="float64", max_iters=3,
                        conv_tol=0.0)
    perm = np.random.default_rng(3).permutation(n)
    gp = g.permute(perm)
    F0 = _rand_F(5, n, 4)
    F0p = np.empty_like(F0)
    F0p[perm] = F0

    m = BigClamModel(g, cfg)
    mp = BigClamModel(gp, cfg)
    r = m.fit(F0)
    rp = mp.fit(F0p)
    np.testing.assert_allclose(rp.llh, r.llh, rtol=1e-9)
    np.testing.assert_allclose(
        rp.llh_history, r.llh_history, rtol=1e-9
    )
    np.testing.assert_allclose(rp.F[perm], r.F, rtol=1e-8, atol=1e-10)
