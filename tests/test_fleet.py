"""Sharded serving fleet (ISSUE 18): fleet publication monotonicity,
the router's scatter-gather members_of merge contract (cross-shard
dedup, sorted-by-raw-id under permuted caches, empty shards), the
barrier-free rollout's generation pinning, admission control in the
batcher and over TCP, and the preflight/ledger satellites."""

import json
import os
import threading
import time

import numpy as np
import pytest

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.models import BigClamModel
from bigclam_tpu.models.agm import sample_planted_graph
from bigclam_tpu.serve.batcher import OverloadedError, RequestBatcher
from bigclam_tpu.serve.fleet import (
    LocalReplica,
    ReplicaServer,
    ShardReplica,
)
from bigclam_tpu.serve.router import FleetRouter, RouterError, TcpReplica
from bigclam_tpu.serve.server import MembershipServer
from bigclam_tpu.serve.snapshot import (
    publish_fleet_snapshot,
    publish_snapshot,
)
from bigclam_tpu.utils.checkpoint import CheckpointManager

K = 6
N = 120


@pytest.fixture(scope="module")
def fitted():
    rng = np.random.default_rng(3)
    g, truth, = sample_planted_graph(N, K, p_in=0.8, rng=rng)
    cfg = BigClamConfig(num_communities=K, max_iters=300)
    model = BigClamModel(g, cfg)
    res = model.fit(model.random_init())
    return g, truth, cfg, model, res


def _equal_ranges(n, shards):
    return [(s * n // shards, (s + 1) * n // shards)
            for s in range(shards)]


@pytest.fixture()
def fleetdir(tmp_path, fitted):
    g, _, cfg, _, res = fitted
    d = str(tmp_path / "fleet")
    publish_fleet_snapshot(
        d, _equal_ranges(N, 3), F=res.F, raw_ids=g.raw_ids,
        num_edges=g.num_edges, cfg=cfg, meta={"llh": res.llh},
    )
    return d


def _fleet(directory, shards, replicas=1, **kw):
    """shards x replicas ShardReplicas behind LocalReplica transports +
    a router over them. Returns (router, replica_objects)."""
    reps = [
        ShardReplica(directory, s, **kw)
        for s in range(shards)
        for _ in range(replicas)
    ]
    router = FleetRouter(directory, [LocalReplica(r) for r in reps])
    return router, reps


# ------------------------------------------------------ fleet publication
def test_fleet_publish_monotonic_with_single_archives(tmp_path, fitted):
    """Fleet and single-archive publications share ONE strictly
    monotonic generation counter (the same publish lock): interleaving
    them can never reuse or regress a step."""
    g, _, cfg, _, res = fitted
    d = str(tmp_path / "snaps")
    s1, _ = publish_fleet_snapshot(
        d, _equal_ranges(N, 2), F=res.F, raw_ids=g.raw_ids,
        num_edges=g.num_edges, cfg=cfg,
    )
    p2 = publish_snapshot(
        d, step=None, F=res.F, raw_ids=g.raw_ids,
        num_edges=g.num_edges, cfg=cfg,
    )
    from bigclam_tpu.utils.checkpoint import published_step_of

    s2 = published_step_of(p2)
    s3, _ = publish_fleet_snapshot(
        d, _equal_ranges(N, 2), F=res.F, raw_ids=g.raw_ids,
        num_edges=g.num_edges, cfg=cfg,
    )
    assert s1 < s2 < s3
    assert CheckpointManager(d).latest_fleet() == s3


def test_fleet_manifest_shard_geometry(fleetdir):
    man = CheckpointManager(fleetdir).load_fleet_manifest()
    assert man["num_shards"] == 3
    assert man["n_global"] == N
    shards = man["shards"]
    assert [s["lo"] for s in shards] == [r[0] for r in _equal_ranges(N, 3)]
    assert [s["hi"] for s in shards] == [r[1] for r in _equal_ranges(N, 3)]


def test_sparse_fleet_publishes_member_lists_not_dense(tmp_path, fitted):
    """A sparse fleet publication stores M-sized slots per row, never a
    densified N*K block — the commodity-RAM contract of the 100M x 25K
    regime."""
    g, _, cfg, _, res = fitted
    from bigclam_tpu.ops.sparse_members import from_dense

    m = 4
    ids, w, _ = from_dense(res.F, m, K, N)
    d = str(tmp_path / "sfleet")
    step, _ = publish_fleet_snapshot(
        d, _equal_ranges(N, 2), ids=ids, w=w, raw_ids=g.raw_ids,
        num_edges=g.num_edges, cfg=cfg,
    )
    man = CheckpointManager(d).load_fleet_manifest()
    assert man["representation"] == "sparse"
    _, arrs, _ = CheckpointManager(d).load_fleet_shard(man, 0)
    assert "F" not in arrs
    assert arrs["ids"].shape == (N // 2, m)
    # and the shard still answers membership over its raw ids
    rep = ShardReplica(d, 0)
    ans = rep.answer({"family": "communities_of",
                      "u": int(g.raw_ids[0]), "gen": step})
    assert ans["gen"] == step and "communities" in ans


# ------------------------------------------- members_of scatter-gather
def test_members_of_merge_matches_single_process(tmp_path, fleetdir,
                                                 fitted):
    g, _, cfg, _, res = fitted
    single_dir = str(tmp_path / "single")
    publish_snapshot(
        single_dir, step=7, F=res.F, raw_ids=g.raw_ids,
        num_edges=g.num_edges, cfg=cfg,
    )
    server = MembershipServer(single_dir)
    router, _ = _fleet(fleetdir, 3)
    try:
        for c in range(K):
            want = server.run_queries(
                [{"family": "members_of", "c": c}]
            )[0]
            got = router.route({"family": "members_of", "c": c})
            assert got["members"] == want["members"]
            assert got["members"] == sorted(set(got["members"]))
    finally:
        router.close()
        server.close()


def test_members_cross_shard_dedup():
    """A raw id materialized on TWO shards (overlapping raw intervals —
    the balanced-cache world) appears ONCE in the merged answer."""
    n, k = 10, 2
    # every row gets an explicit above-delta home in community 1
    # (membership_mask's zero-row fallback would otherwise make orphan
    # rows members of EVERY community and drown the assertion)
    F = np.zeros((n, k))
    F[:, 1] = 1.0
    F[5, 0] = 1.0     # shard 0, raw id 100
    F[8, 0] = 1.0     # shard 1, raw id 100 again
    raw = np.array([0, 1, 2, 3, 4, 100, 6, 7, 100, 9], np.int64)
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        publish_fleet_snapshot(
            d, [(0, 6), (6, 10)], F=F, raw_ids=raw, num_edges=20,
            meta={"k": k},
        )
        router, _ = _fleet(d, 2)
        try:
            got = router.route({"family": "members_of", "c": 0})
            assert got["members"] == [100]
        finally:
            router.close()


def test_members_sorted_by_raw_id_under_permuted_cache():
    """Permuted raw ids (the balanced cache's shuffle): per-shard member
    lists arrive in arbitrary raw order and interleaved across shards —
    the merged answer is still globally sorted by raw id."""
    n, k = 12, 2
    rng = np.random.default_rng(0)
    raw = rng.permutation(np.arange(100, 100 + n)).astype(np.int64)
    F = np.zeros((n, k))
    F[:, 1] = 1.0                # explicit home for every row
    members_rows = [0, 3, 5, 7, 8, 11]
    F[members_rows, 0] = 1.0
    F[5, 1] = 0.0                # row 5 belongs to community 0 ONLY
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        publish_fleet_snapshot(
            d, [(0, 4), (4, 8), (8, 12)], F=F, raw_ids=raw,
            num_edges=30, meta={"k": k},
        )
        router, _ = _fleet(d, 3)
        try:
            got = router.route({"family": "members_of", "c": 0})
            want = sorted(int(raw[r]) for r in members_rows)
            assert got["members"] == want
            # and communities_of routes a raw id through the overlap
            # probe (raw intervals overlap under the permutation)
            u = int(raw[5])
            ans = router.route({"family": "communities_of", "u": u})
            assert [c for c, _ in ans["communities"]] == [0]
        finally:
            router.close()


def test_empty_and_zero_width_shards():
    """A community with members on one shard only: the other shards
    answer empty lists and the merge still stands. A zero-width row
    range (an empty shard) answers every family without tripping."""
    n, k = 8, 3
    F = np.zeros((n, k))
    F[:, 1] = 1.0                # explicit home for every row
    F[[0, 2], 0] = 1.0           # community 0 lives on shard 0 only
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        publish_fleet_snapshot(
            d, [(0, 4), (4, 4), (4, 8)], F=F,
            raw_ids=np.arange(n, dtype=np.int64), num_edges=16,
            meta={"k": k},
        )
        router, _ = _fleet(d, 3)
        try:
            got = router.route({"family": "members_of", "c": 0})
            assert got["members"] == [0, 2]
            assert router.route(
                {"family": "members_of", "c": 2}
            )["members"] == []
            ans = router.route({"family": "communities_of", "u": 6})
            assert [c for c, _ in ans["communities"]] == [1]
        finally:
            router.close()


# ------------------------------------------------- rollout + generations
def test_rollout_pins_common_generation(tmp_path, fitted):
    """One shard a generation behind: the fleet keeps serving the COMMON
    generation (never mixed); once the laggard loads, one refresh flips
    the whole fleet."""
    g, _, cfg, _, res = fitted
    d = str(tmp_path / "fleet")
    publish_fleet_snapshot(
        d, _equal_ranges(N, 2), F=res.F, raw_ids=g.raw_ids,
        num_edges=g.num_edges, cfg=cfg,
    )
    reps = [ShardReplica(d, s) for s in (0, 0, 1, 1)]
    router = FleetRouter(d, [LocalReplica(r) for r in reps])
    try:
        gen1 = router.stats()["serving_generation"]
        publish_fleet_snapshot(
            d, _equal_ranges(N, 2), F=res.F, raw_ids=g.raw_ids,
            num_edges=g.num_edges, cfg=cfg,
        )
        for r in reps[:3]:           # one replica of shard 1 lags
            assert r.maybe_load_next() is not None
        router.refresh()
        assert router.stats()["serving_generation"] == gen1
        ans = router.route({"family": "communities_of",
                            "u": int(g.raw_ids[0])})
        assert "error" not in ans
        assert router.stats()["rollouts"] == 0
        assert router.stats()["mixed_generation"] == 0
        assert reps[3].maybe_load_next() is not None
        router.refresh()
        st = router.stats()
        assert st["serving_generation"] == gen1 + 1
        assert st["rollouts"] == 1
        ans = router.route({"family": "members_of", "c": 0})
        assert "error" not in ans
        assert router.stats()["mixed_generation"] == 0
    finally:
        router.close()


def test_replica_holds_two_generations_and_answers_pinned(tmp_path,
                                                          fitted):
    g, _, cfg, _, res = fitted
    d = str(tmp_path / "fleet")
    s1, _ = publish_fleet_snapshot(
        d, _equal_ranges(N, 2), F=res.F, raw_ids=g.raw_ids,
        num_edges=g.num_edges, cfg=cfg,
    )
    rep = ShardReplica(d, 0)
    s2, _ = publish_fleet_snapshot(
        d, _equal_ranges(N, 2), F=res.F, raw_ids=g.raw_ids,
        num_edges=g.num_edges, cfg=cfg,
    )
    assert rep.maybe_load_next() == s2
    assert rep.generations == [s1, s2]
    old = rep.answer({"family": "communities_of",
                      "u": int(g.raw_ids[0]), "gen": s1})
    assert old["gen"] == s1
    gone = rep.answer({"family": "communities_of",
                       "u": int(g.raw_ids[0]), "gen": s2 + 99})
    assert gone["error"] == "unknown_generation"


def test_router_fails_over_on_unknown_generation(fleetdir):
    """A replica that already dropped the pinned generation answers
    unknown_generation — the router must retry the next replica of the
    shard, not surface an error."""
    rep0 = ShardReplica(fleetdir, 0)
    rep1 = ShardReplica(fleetdir, 1)
    rep2 = ShardReplica(fleetdir, 2)

    class _Amnesiac(LocalReplica):
        def request(self, q, timeout=None):
            if q.get("family") != "status":
                return {"error": "unknown_generation",
                        "gen": q.get("gen")}
            return super().request(q, timeout)

    healthy0 = LocalReplica(rep0)
    router = FleetRouter(
        fleetdir,
        [_Amnesiac(rep0), healthy0, LocalReplica(rep1),
         LocalReplica(rep2)],
    )
    try:
        for _ in range(4):
            ans = router.route({"family": "communities_of", "u": 0})
            assert "error" not in ans
        assert router.stats()["serve_errors"] == 0
    finally:
        router.close()


# ------------------------------------------------------ admission control
def test_batcher_depth_watermark_sheds_fast():
    """With the flusher wedged mid-batch, submits past max_depth fail
    their future IMMEDIATELY (no queue slot, no wait); admitted requests
    survive the burst and are served once the handler unblocks."""
    entered = threading.Event()
    release = threading.Event()

    def handler(batch):
        entered.set()
        release.wait(5.0)
        for r in batch:
            r.future.set_result(r.payload)

    b = RequestBatcher(handler, max_batch=1, budget_s=0.0, max_depth=2)
    b.start()
    first = b.submit("warm")
    assert entered.wait(2.0)     # handler wedged; queue now grows
    futs = [b.submit(i) for i in range(4)]   # 2 admitted, 2 shed
    assert futs[2].done() and futs[3].done()
    shed = 0
    for f in futs[2:]:
        try:
            f.result(0.0)
        except OverloadedError:
            shed += 1
    assert shed == 2 and b.shed_depth == 2
    assert b.depth_peak == 2
    release.set()
    assert first.result(2.0) == "warm"
    assert futs[0].result(2.0) == 0
    assert futs[1].result(2.0) == 1
    b.stop()
    assert b.shed == 2


def test_batcher_deadline_watermark_sheds_stale():
    """Requests that aged past shed_wait_s while the flusher was wedged
    are shed at flush; fresh work after the purge is served normally."""
    entered = threading.Event()
    release = threading.Event()

    def handler(batch):
        entered.set()
        release.wait(5.0)
        for r in batch:
            r.future.set_result("served")

    b = RequestBatcher(handler, max_batch=8, budget_s=0.0,
                       shed_wait_s=0.05)
    b.start()
    first = b.submit("warm")
    assert entered.wait(2.0)     # handler wedged with the warm batch
    futs = [b.submit(i) for i in range(3)]
    time.sleep(0.12)             # all three age past the watermark
    release.set()
    assert first.result(2.0) == "served"
    shed = 0
    for f in futs:
        try:
            f.result(2.0)
        except OverloadedError:
            shed += 1
    assert shed == 3
    assert b.shed_deadline == 3
    # fresh work after the purge is served normally
    assert b.submit("x").result(2.0) == "served"
    b.stop()


def test_replica_server_tcp_roundtrip_and_stop(fleetdir):
    rep = ShardReplica(fleetdir, 0)
    srv = ReplicaServer(rep, port=0, budget_s=0.001)
    t = TcpReplica(srv.host, srv.port, timeout_s=10.0)
    try:
        st = t.request({"family": "status"})
        assert st["shard"] == 0 and "depth" in st
        ans = t.request({"family": "communities_of", "u": 0,
                         "gen": rep.generations[-1]})
        assert ans["gen"] == rep.generations[-1]
        assert t.request({"family": "stop"})["ok"] is True
        assert srv.serve_until_stopped(10.0)
    finally:
        t.close()
        srv.close()


# ------------------------------------------------------------ satellites
def test_serve_preflight_prices_fleet():
    from bigclam_tpu.obs import memory as M

    dense = M.serve_preflight(1_000_000, 20_000_000, 1000, shards=4,
                              replicas=2)
    sparse = M.serve_preflight(1_000_000, 20_000_000, 1000, shards=4,
                               replicas=2, representation="sparse",
                               sparse_m=64)
    assert (sparse["per_replica"]["snapshot_bytes"]
            < dense["per_replica"]["snapshot_bytes"])
    assert dense["fleet_total_bytes"] == pytest.approx(
        8 * dense["per_replica"]["total_bytes"]
    )
    tight = M.serve_preflight(
        1_000_000, 20_000_000, 1000, shards=1, replicas=1,
        qps_target=1e9,
    )
    assert not tight["fits_qps"] and not tight["fits"]
    assert tight["knobs"]
    small = M.serve_preflight(
        1_000_000, 20_000_000, 1000, shards=4, replicas=2,
        qps_target=10_000.0, host_ram_bytes=64 << 30,
    )
    assert small["fits"]


def test_ledger_fleet_fields_and_shed_verdict():
    from bigclam_tpu.obs import ledger as L

    def rep(shed_rate, p99=0.002):
        return {
            "run": f"r{shed_rate}", "entry": "route", "pid": 0,
            "processes": 1, "wall_s": 1.0,
            "fingerprint": {"host": "h", "backend": "cpu",
                            "device_kind": "cpu", "platform": "cpu"},
            "compiles": {"count": 0, "by_key": {}},
            "spans": {"seconds": {}},
            "final": {
                "serve_queries": 1000,
                "serve_p50_s": 0.001,
                "serve_p99_s": p99,
                "serve_qps": 500.0,
                "serve_mix": "members_of:1.00",
                "serve_shards": 2,
                "serve_replicas": 2,
                "serve_shed": int(shed_rate * 1000),
                "serve_shed_rate": shed_rate,
            },
        }

    base = L.build_record(rep(0.01))
    assert base["serve_shards"] == 2 and base["serve_replicas"] == 2
    assert base["serve_shed_rate"] == 0.01
    # fleet geometry joins the match key: a 2x2 fleet never baselines a
    # single-process serve (both None) or a 4x2 fleet
    single = L.build_record(rep(0.01))
    single["serve_shards"] = single["serve_replicas"] = None
    assert L.match_key(base) != L.match_key(single)
    d = L.diff_records(base, L.build_record(rep(0.25)))
    bad = [c for c in d["checks"]
           if c["metric"] == "serve_shed_rate" and c["regression"]]
    assert bad and d["regression"]


def test_cli_parse_endpoints_rejects_garbage():
    from bigclam_tpu.cli import _parse_endpoints

    eps = _parse_endpoints("127.0.0.1:70,localhost:71", 5.0)
    assert [(e.host, e.port) for e in eps] == [
        ("127.0.0.1", 70), ("localhost", 71)
    ]
    with pytest.raises(SystemExit):
        _parse_endpoints("nope", 5.0)
    with pytest.raises(SystemExit):
        _parse_endpoints("", 5.0)


# --------------------------------------------------- suggest parity (jax)
def test_routed_suggest_matches_single_process(tmp_path, fitted):
    """suggest_for through the two-phase fleet protocol is bit-identical
    to the single-process fold-in on the same F (same padding, same
    global sumF, CSR neighbor order preserved by the row gather)."""
    g, _, cfg, _, res = fitted
    from bigclam_tpu.graph.store import compile_graph_cache

    etxt = tmp_path / "g.txt"
    with open(etxt, "w") as f:
        for u in range(N):
            for j in range(g.indptr[u], g.indptr[u + 1]):
                v = int(g.indices[j])
                if u < v:
                    f.write(f"{g.raw_ids[u]} {g.raw_ids[v]}\n")
    store = compile_graph_cache(
        str(etxt), str(tmp_path / "g.cache"), num_shards=4
    )

    single_dir = str(tmp_path / "single")
    publish_snapshot(
        single_dir, step=5, F=res.F, raw_ids=g.raw_ids,
        num_edges=g.num_edges, cfg=cfg,
    )
    fleet_dir = str(tmp_path / "fleetdir")
    publish_fleet_snapshot(
        fleet_dir, store.host_ranges(2), F=res.F, raw_ids=g.raw_ids,
        num_edges=g.num_edges, cfg=cfg,
    )
    server = MembershipServer(single_dir, store=store)
    router, _ = _fleet(fleet_dir, 2, store=store)
    try:
        nodes = [int(g.raw_ids[i]) for i in (0, 17, 63, 111)]
        want = server.run_queries(
            [{"family": "suggest_for", "u": u} for u in nodes]
        )
        for u, w in zip(nodes, want):
            got = router.route({"family": "suggest_for", "u": u})
            for key in ("u", "suggested", "llh", "iters"):
                assert got.get(key) == w.get(key), (u, key)
        assert router.stats()["serve_errors"] == 0
    finally:
        router.close()
        server.close()
