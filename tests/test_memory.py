"""Memory accounting (obs.memory, ISSUE 12): the static per-device HBM
model vs the live addressable-shard bytes (exact on the CPU fake) across
all four trainer families and the dense/sparse x csr-on/off x
rollback-on/off matrix, the drift (leak) anomaly, the host-RSS model's
dominant-stage flag, the preflight verdicts, the ledger's
hbm/host-rss fields + diff verdicts, and the report/watch rendering."""

import json
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.models import BigClamModel, SparseBigClamModel
from bigclam_tpu.models.agm import sample_planted_graph
from bigclam_tpu.obs import RunTelemetry, install, uninstall
from bigclam_tpu.obs import ledger as L
from bigclam_tpu.obs import memory as M
from bigclam_tpu.obs.report import load_events, render, render_json
from bigclam_tpu.obs.schema import validate_events_file
from bigclam_tpu.obs.telemetry import EVENTS_NAME
from bigclam_tpu.obs.watch import render_frame
from bigclam_tpu.parallel import (
    RingBigClamModel,
    ShardedBigClamModel,
    SparseShardedBigClamModel,
    make_mesh,
)


@pytest.fixture()
def planted():
    g, _ = sample_planted_graph(
        256, 4, p_in=0.3, rng=np.random.default_rng(0)
    )
    F0 = np.random.default_rng(1).uniform(0.1, 1.0, size=(g.num_nodes, 4))
    return g, F0


def _cfg(**kw):
    d = dict(num_communities=4, dtype="float64", max_iters=3,
             conv_tol=0.0)
    d.update(kw)
    return BigClamConfig(**d)


# --------------------------------------------------------- arithmetic
def test_health_len_matches_diagnostics():
    # memory.py is jax-free and mirrors the constant; the pack and the
    # model must never drift apart
    from bigclam_tpu.ops.diagnostics import HEALTH_LEN

    assert M.HEALTH_LEN == HEALTH_LEN


def test_dense_state_arithmetic_by_hand():
    # n_pad=128, k_pad=8, dp=2, tp=1, f64, 16 candidates, health off:
    # F = 64*8*8 = 4096, sumF = 8*8 = 64, scalars = 8 + 4 + 17*4 = 80
    bufs = M.dense_state_buffers(128, 8, 2, 1, 8, 16, False)
    by = {b.name: b.total_bytes for b in bufs}
    assert by["state/F"] == 4096.0
    assert by["state/sumF"] == 64.0
    assert by["state/scalars"] == 80.0
    # health on adds the (14,) f32 pack to the replicated scalars
    bufs_h = M.dense_state_buffers(128, 8, 2, 1, 8, 16, True)
    by_h = {b.name: b.total_bytes for b in bufs_h}
    assert by_h["state/scalars"] == 80.0 + M.HEALTH_LEN * 4


def test_scratch_and_category_accounting():
    state = M.dense_state_buffers(64, 4, 1, 1, 4, 16, False)
    mm = M.dense_memory_model(
        64, 4, 4, 16, {"graph/edges": 1000.0}, donate=True,
        rollback=True,
    )
    state_total = sum(b.total_bytes for b in state)
    cat = mm.category_bytes()
    # ping-pong twin + rollback snapshot are each one state copy
    assert cat["scratch"] == 2 * state_total
    assert cat["graph"] == 1000.0
    assert mm.addressable_bytes() == state_total + 1000.0
    assert mm.hbm_bytes() > mm.addressable_bytes()
    # donate/rollback off removes exactly those buffers
    mm_off = M.dense_memory_model(
        64, 4, 4, 16, {"graph/edges": 1000.0}, donate=False,
        rollback=False,
    )
    assert "scratch" not in mm_off.category_bytes()
    assert mm.hbm_bytes() - mm_off.hbm_bytes() == 2 * state_total


def test_collective_buffers_priced_from_comms_sites():
    from bigclam_tpu.obs import comms as C

    cm = C.sharded_step_model(
        n_pad=128, k_pad=8, dp=2, tp=1, itemsize=4, num_candidates=16
    )
    bufs = M.collective_buffers(cm)
    assert len(bufs) == 1
    # largest single-occurrence receive: the F all-gather, (p-1)*shard
    assert bufs[0].total_bytes == 64 * 8 * 4 * (2 - 1)
    assert "all_gather_F" in bufs[0].note
    assert M.collective_buffers(None) == []


# -------------------------------------- modeled == measured (exact)
def _reconcile_exact(model, state):
    recon = model.memory_reconcile(state)
    assert recon["ok"], recon
    assert recon["drift_frac"] == 0.0, recon
    assert recon["modeled_bytes"] == recon["measured_bytes"]
    return recon


@pytest.mark.parametrize("rollback", [0, 3])
@pytest.mark.parametrize("health", [0, 1])
def test_dense_single_chip_exact(planted, rollback, health):
    g, F0 = planted
    m = BigClamModel(
        g, _cfg(rollback_budget=rollback, health_every=health)
    )
    st = m.init_state(F0)
    _reconcile_exact(m, st)
    st = m._step(st)
    _reconcile_exact(m, st)
    # rollback only adds SCRATCH (model-side); the addressable target
    # is unchanged — the matrix still reconciles exactly either way
    if rollback:
        assert m.memory.category_bytes().get("scratch", 0) > 0


def test_dense_csr_interpret_exact(planted):
    g, F0 = planted
    m = BigClamModel(g, _cfg(
        use_pallas_csr=True, pallas_interpret=True,
        csr_block_b=64, csr_tile_t=64, dtype="float32",
    ))
    assert m.engaged_path == "csr_fused"
    st = m.init_state(F0)
    _reconcile_exact(m, st)
    st = m._step(st)
    _reconcile_exact(m, st)
    # the CSR model prices tiles, not EdgeChunks
    assert any(
        "tiles" in name for name in m.memory.buffer_bytes()
    )


@pytest.mark.parametrize("dp", [2, 4])
def test_sharded_exact(planted, dp):
    g, F0 = planted
    mesh = make_mesh((dp, 1), jax.devices()[:dp])
    m = ShardedBigClamModel(g, _cfg(health_every=1), mesh)
    st = m.init_state(F0)
    _reconcile_exact(m, st)
    st = m._step(st)
    _reconcile_exact(m, st)


def test_sharded_tp_exact(planted):
    g, F0 = planted
    mesh = make_mesh((2, 2), jax.devices()[:4])
    m = ShardedBigClamModel(g, _cfg(), mesh)
    st = m.init_state(F0)
    _reconcile_exact(m, st)
    st = m._step(st)
    _reconcile_exact(m, st)


def test_ring_exact(planted):
    g, F0 = planted
    mesh = make_mesh((2, 1), jax.devices()[:2])
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m = RingBigClamModel(g, _cfg(), mesh, balance=False)
    st = m.init_state(F0)
    _reconcile_exact(m, st)
    st = m._step(st)
    _reconcile_exact(m, st)
    # the ring model claims the rotation pair, never a full F gather
    names = m.memory.buffer_bytes()
    assert "transient/ring_rotation" in names
    assert "transient/F_allgather" not in names


def test_sparse_families_exact(planted):
    g, F0 = planted
    K = 64
    F0w = np.zeros((g.num_nodes, K))
    F0w[:, :4] = F0
    cfg = _cfg(num_communities=K, representation="sparse", sparse_m=8,
               sparse_comm_cap=16, health_every=1)
    ms = SparseBigClamModel(g, cfg)
    st = ms.init_state(F0w)
    _reconcile_exact(ms, st)
    st = ms._step(st)
    _reconcile_exact(ms, st)
    mesh = make_mesh((2, 1), jax.devices()[:2])
    msh = SparseShardedBigClamModel(g, cfg, mesh)
    sts = msh.init_state(F0w)
    _reconcile_exact(msh, sts)
    sts = msh._step(sts)
    _reconcile_exact(msh, sts)
    # M-not-K: the state buffers scale with M
    by = msh.memory.buffer_bytes()
    n_loc = msh.n_pad // 2
    assert by["state/weights"] == n_loc * msh.m * 8
    assert by["state/member_ids"] == n_loc * msh.m * 4


def test_ring_memory_smaller_than_allgather_at_scale():
    # the schedules' memory claims, in model numbers: at large N the
    # ring's rotating pair beats the all-gather's full per-device F
    g_kw = dict(n_pad=1 << 16, k_pad=256, dp=8, tp=1, itemsize=4,
                num_candidates=16, graph_bytes={})
    ag = M.sharded_memory_model(**g_kw)
    ring = M.ring_memory_model(**g_kw)
    assert ring.hbm_bytes() < ag.hbm_bytes()
    assert ag.buffer_bytes()["transient/F_allgather"] == (1 << 16) * 256 * 4


# ------------------------------------------------- drift / leak anomaly
def test_planted_leak_fires_exactly_the_drift_anomaly(planted, tmp_path):
    g, F0 = planted
    tel = install(RunTelemetry(str(tmp_path), entry="fit", quiet=True))
    try:
        m = BigClamModel(g, _cfg())
        st = m.init_state(F0)
        clean = m.memory_reconcile(st)
        assert clean["ok"]
        leak = jnp.array(np.asarray(st.F))     # a retained F-sized copy
        bad = m.memory_reconcile(st, extra=[leak])
        assert not bad["ok"] and bad["drift_frac"] > 0
        tel.finalize()
    finally:
        uninstall(tel)
    anomalies = [
        e for e in (load_events(str(tmp_path)) or [])
        if e.get("kind") == "anomaly"
    ]
    assert len(anomalies) == 1
    assert anomalies[0]["check"] == "memory_drift"
    assert anomalies[0]["iter"] == -1
    n, errors = validate_events_file(str(tmp_path / EVENTS_NAME))
    assert not errors, errors[:5]


# ------------------------------------------------------ host RSS model
def test_host_model_f0_is_dominant_and_flagged():
    hm = M.host_rss_model(
        100_000, 2_000_000, 1000, 4, n_pad=100_352, k_pad=1024
    )
    dom = hm.dominant()
    assert dom is not None and dom.stage == "f0_init"
    assert "ROADMAP 1a" in dom.note
    assert hm.peak_bytes() == dom.bytes


def test_host_model_store_native_shrinks_graph_and_f0():
    kw = dict(n=100_000, directed_edges=2_000_000, k=1000, itemsize=4,
              n_pad=100_352, k_pad=1024)
    host_global = M.host_rss_model(**kw)
    store = M.host_rss_model(**kw, store_native=True, processes=8,
                             num_shards=8)
    hg = {s.stage: s.bytes for s in host_global.stages}
    st = {s.stage: s.bytes for s in store.stages}
    assert st["shard_load"] < hg["graph_load"] / 4
    # ISSUE 15 satellite: store-native F0 is the PER-HOST row-keyed
    # counter init — O(N_loc*K), 1/processes of the padded staging;
    # the dominant flag MOVES off f0_init (to the still-host-global
    # extract stage, the next ROADMAP 1a frontier)
    assert st["f0_init"] < hg["f0_init"] / 4
    assert st["f0_init"] == M.rowkeyed_f0_rss_bytes(100_352, 1024, 4, 8)
    assert host_global.dominant().stage == "f0_init"
    assert store.dominant().stage == "extract"
    # explicit host-global F0 (conductance seeding) re-opens the term
    explicit = M.host_rss_model(**kw, store_native=True, processes=8,
                                num_shards=8, rowkeyed_f0=False)
    ex = {s.stage: s.bytes for s in explicit.stages}
    assert ex["f0_init"] == hg["f0_init"]


def test_ingest_stage_uses_the_gate_budget_formula():
    b = M.ingest_rss_bytes(64 << 20, 1000, 100_000, 8)
    assert b == 12 * (64 << 20) + 6 * (16 * 100_000 // 8) \
        + 4 * 8 * 1000 + (96 << 20)


# ------------------------------------------------------------ preflight
def test_preflight_verdicts_over_budget_and_sparse_relief():
    over = M.preflight(
        100_000, 4_000_000, 2048, dp=4, itemsize=4,
        device_hbm_bytes=256 << 20,
    )
    assert not over["fits"] and over["binding"] == "hbm"
    assert any("sparse" in k for k in over["knobs"])
    relaxed = M.preflight(
        100_000, 4_000_000, 2048, dp=4, itemsize=4,
        representation="sparse", sparse_m=32,
        device_hbm_bytes=256 << 20,
    )
    assert relaxed["fits"]
    assert relaxed["hbm_bytes_per_device"] < over["hbm_bytes_per_device"]


def test_preflight_host_binding_names_store_native_knob():
    p = M.preflight(
        50_000_000, 3_600_000_000, 100, dp=64, itemsize=4,
        device_hbm_bytes=16 << 30, host_ram_bytes=16 << 30,
    )
    assert not p["fits_host"]
    assert p["binding"] in ("host_rss", "hbm")
    assert any("--store-native" in k for k in p["knobs"])


def test_preflight_exact_shard_counts_beat_the_estimate():
    counts = [1000, 1000, 1000, 9000]          # skewed
    exact = M.preflight(1000, 12_000, 16, dp=4,
                        shard_edge_counts=counts)
    est = M.preflight(1000, 12_000, 16, dp=4)
    assert exact["workload"]["shard_counts_known"]
    assert not est["workload"]["shard_counts_known"]
    # the padded layout prices the max shard, which the estimate
    # cannot see
    assert exact["device"]["by_category"]["graph"] > \
        est["device"]["by_category"]["graph"]


def test_render_preflight_names_binding_and_knobs():
    p = M.preflight(
        100_000, 4_000_000, 2048, dp=4, itemsize=4,
        device_hbm_bytes=256 << 20,
    )
    text = M.render_preflight(p)
    assert "DOES NOT FIT (binding: hbm)" in text
    assert "knob:" in text
    assert "f0_init" in text and "dominant" in text


# ----------------------------------------------- ledger + report + watch
def _run_with_tel(tmp_path, g, F0, tag, **cfg_kw):
    tdir = str(tmp_path / tag)
    tel = install(RunTelemetry(tdir, entry="fit", quiet=True))
    try:
        mesh = make_mesh((2, 1), jax.devices()[:2])
        m = ShardedBigClamModel(g, _cfg(max_iters=4, **cfg_kw), mesh)
        from bigclam_tpu.utils.profiling import StageProfile

        with StageProfile().stage("fit"):
            res = m.fit(F0)
        tel.set_final({"llh": res.llh, "iters": res.num_iters,
                       "n": g.num_nodes, "edges": g.num_edges, "k": 4,
                       "mesh": "2x1",
                       "hbm_modeled_bytes": round(
                           m.memory.hbm_bytes(), 1)})
        rep = tel.finalize()
    finally:
        uninstall(tel)
    return tdir, rep, m, res


def test_report_carries_memory_model_and_renders(planted, tmp_path):
    g, F0 = planted
    tdir, rep, m, _ = _run_with_tel(tmp_path, g, F0, "run")
    modeled = rep["memory"]["modeled"]
    assert modeled is not None
    assert modeled["hbm_bytes_per_device"] == pytest.approx(
        m.memory.hbm_bytes()
    )
    assert modeled["addressable_bytes"] == pytest.approx(
        m.memory.addressable_bytes()
    )
    assert modeled["host_stages"].get("f0_init", 0) > 0
    # the flagged dominant stage is the arg-max stage (f0_init on real
    # K; at this toy K=4 the graph load wins — the flag must track it)
    assert modeled["host_dominant_stage"] == max(
        modeled["host_stages"], key=modeled["host_stages"].get
    )
    text, errors = render(tdir)
    assert errors == 0, text
    assert "memory model (per device, modeled):" in text
    assert "host RSS model" in text and "dominant" in text
    obj, errors = render_json(tdir)
    assert errors == 0
    assert obj["memory_model"]["hbm_bytes_per_device"] == pytest.approx(
        m.memory.hbm_bytes()
    )
    # watch renders the modeled headroom line from the same events
    frame = render_frame(tdir)
    assert "hbm modeled" in frame
    n, schema_errors = validate_events_file(str(
        tmp_path / "run" / EVENTS_NAME
    ))
    assert not schema_errors, schema_errors[:5]


def test_ledger_records_and_verdicts_memory(planted, tmp_path):
    g, F0 = planted
    tdir, rep, m, _ = _run_with_tel(tmp_path, g, F0, "base")
    rec = L.build_record(rep, [0.01] * 10, [100.0] * 10)
    assert rec["hbm_modeled_bytes"] == pytest.approx(m.memory.hbm_bytes())
    assert rec["host_rss_modeled_bytes"] is not None
    same = dict(rec, run="rerun", ts=rec["ts"] + 1)
    d = L.diff_records(rec, same)
    assert not d["regression"]
    inflated = dict(
        rec, run="leaky", ts=rec["ts"] + 2,
        hbm_modeled_bytes=rec["hbm_modeled_bytes"] * 2.0,
    )
    d = L.diff_records(rec, inflated)
    assert d["regression"]
    hbm_checks = [c for c in d["checks"]
                  if c["metric"] == "hbm_modeled_bytes"]
    assert hbm_checks and hbm_checks[0]["regression"]


def test_rebaked_model_replaces_not_accumulates(planted, tmp_path):
    # the sparse cap refinement re-emits the model (reset_model): the
    # report must hold ONE model's buffers, not the concatenation
    g, F0 = planted
    K = 64
    F0w = np.zeros((g.num_nodes, K))
    F0w[:, :4] = F0
    tel = install(RunTelemetry(str(tmp_path), entry="fit", quiet=True))
    try:
        mesh = make_mesh((2, 1), jax.devices()[:2])
        m = SparseShardedBigClamModel(
            g, _cfg(num_communities=K, representation="sparse",
                    sparse_m=8), mesh,
        )
        m.init_state(F0w)          # cap refinement may re-bake here
        rep = tel.finalize()
    finally:
        uninstall(tel)
    modeled = rep["memory"]["modeled"]
    assert modeled["hbm_bytes_per_device"] == pytest.approx(
        m.memory.hbm_bytes()
    )


def test_accounting_identity_and_stall_embeds_model(planted, tmp_path):
    # telemetry-on (models + events baked) vs telemetry-off
    # trajectories are bit-identical — the model is host arithmetic
    g, F0 = planted
    _, _, _, res_on = _run_with_tel(tmp_path, g, F0, "on")
    mesh = make_mesh((2, 1), jax.devices()[:2])
    res_off = ShardedBigClamModel(g, _cfg(max_iters=4), mesh).fit(F0)
    assert np.array_equal(res_on.F, res_off.F)
    assert res_on.llh_history == res_off.llh_history


def test_heartbeat_stall_carries_hbm_modeled(tmp_path):
    from bigclam_tpu.obs.heartbeat import Heartbeat

    tel = RunTelemetry(str(tmp_path), entry="fit", quiet=True,
                       heartbeat_s=0.0)
    install(tel)
    try:
        tel.event(
            "memory_model", model="M", family="dense", scope="device",
            reset_model=1, buffer="state/F", bytes=1234.0,
            category="state",
        )
        hb = Heartbeat(tel, deadline_s=0.05, echo=False, poll_s=0.01)
        hb.start()
        import time

        time.sleep(0.3)
        hb.stop()
        tel.finalize()
    finally:
        uninstall(tel)
    stalls = [
        e for e in (load_events(str(tmp_path)) or [])
        if e.get("kind") == "stall"
    ]
    assert stalls
    assert stalls[-1].get("hbm_modeled_bytes") == 1234.0


# ------------------------------------------------ 2D partition (ISSUE 16)
def test_twod_exact(planted):
    from bigclam_tpu.parallel import TwoDShardedBigClamModel, make_mesh_2d

    g, F0 = planted
    m = TwoDShardedBigClamModel(
        g, _cfg(health_every=1, partition="2d", replica_cols=2),
        make_mesh_2d((2, 2), jax.devices()[:4]),
    )
    st = m.init_state(F0)
    _reconcile_exact(m, st)
    st = m._step(st)
    _reconcile_exact(m, st)


def test_twod_memory_model_arithmetic_by_hand():
    # n_pad=128, rows=2, cols=2 -> p=4, n_blk=32; k_pad=8 f32 -> 32 B/row
    mm = M.twod_memory_model(
        128, 8, 2, 2, 4, 16, {"graph/edge_blocks": 1000.0},
        closure_cap=10,
    )
    buf = mm.buffer_bytes()
    assert buf["transient/F_rowgather"] == 2 * 32 * 32.0
    assert buf["transient/closure_recv"] == 2 * 10 * 32.0
    assert buf["transient/grad_row"] == 2 * 32 * 8 * 4
    assert buf["transient/candidates"] == 16 * 2 * 32 * 4
    assert buf["graph/edge_blocks"] == 1000.0
    assert mm.family == "twod"
    # C=1 holds its own src rows already: no row-gather transient at all
    c1 = M.twod_memory_model(128, 8, 4, 1, 4, 16, {}, closure_cap=10)
    assert "transient/F_rowgather" not in c1.buffer_bytes()


def test_preflight_2d_flips_the_friendster_verdict():
    # the ISSUE 16 acceptance numbers: Friendster (65.6M nodes, 1.8B
    # undirected edges), K=25000 sparse m=48, 64 v5e chips. 1D: the
    # O(N) member all-gather binds and the verdict names the 2d knob;
    # 2d at (8, 8): fits.
    kw = dict(dp=64, tp=1, itemsize=4, representation="sparse",
              sparse_m=48,
              device_hbm_bytes=M.DEVICE_HBM_BYTES["v5e"])
    n, e2, k = 65_608_366, 2 * 1_806_067_135, 25_000
    one_d = M.preflight(n, e2, k, **kw)
    assert not one_d["fits"] and one_d["binding"] == "hbm"
    assert any("--partition 2d" in kn for kn in one_d["knobs"])
    two_d = M.preflight(n, e2, k, partition="2d", replica_cols=8, **kw)
    assert two_d["fits"]
    assert two_d["workload"]["partition"] == "2d"
    assert two_d["workload"]["replica_cols"] == 8
    assert two_d["hbm_bytes_per_device"] < one_d["hbm_bytes_per_device"]
    # sparse x 2d is priced forward-looking only — the note says so
    assert any("forward-looking" in nt for nt in two_d["notes"])


def test_preflight_2d_exact_pair_counts_beat_the_estimate():
    est = M.preflight(1024, 4096, 16, dp=4, partition="2d")
    assert any("coupon-collector" in nt for nt in est["notes"])
    counts = [[10] * 4 for _ in range(4)]
    exact = M.preflight(1024, 4096, 16, dp=4, partition="2d",
                        closure_pair_counts=counts)
    assert not any("coupon-collector" in nt for nt in exact["notes"])
    # baked 10-row pairs undercut the 162-row coupon-collector estimate
    assert exact["comms_bytes_per_step"] < est["comms_bytes_per_step"]
    # a -1 overflow sentinel degrades that pair to the full block
    over = M.preflight(1024, 4096, 16, dp=4, partition="2d",
                       closure_pair_counts=[[-1] * 4] + [[10] * 4] * 3)
    assert over["comms_bytes_per_step"] > exact["comms_bytes_per_step"]


def test_preflight_2d_refusals():
    with pytest.raises(ValueError, match="closure-gather"):
        M.preflight(1000, 4000, 8, dp=4, partition="2d",
                    schedule="ring")
    with pytest.raises(ValueError, match="tp == 1"):
        M.preflight(1000, 4000, 8, dp=4, tp=2, partition="2d")
    with pytest.raises(ValueError, match="does not divide"):
        M.preflight(1000, 4000, 8, dp=4, partition="2d",
                    replica_cols=3)
    with pytest.raises(ValueError, match="unknown partition"):
        M.preflight(1000, 4000, 8, dp=4, partition="3d")
