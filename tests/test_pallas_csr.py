"""Blocked-CSR MXU kernel path (ops.csr_tiles + ops.pallas_csr) vs the XLA
edge path, in Pallas interpret mode on CPU.

The kernels are the performance rewrite of the hot loop (reference
Bigclamv2.scala:121-146); semantics must match ops.objective.grad_llh and
ops.linesearch.candidates_pass exactly (same clipping, same masked terms,
SURVEY.md §2.1)."""

import numpy as np
import jax.numpy as jnp
import pytest

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.graph.ingest import graph_from_edges
from bigclam_tpu.models.bigclam import BigClamModel, prepare_graph
from bigclam_tpu.ops.csr_tiles import build_block_tiles
from bigclam_tpu.ops.linesearch import armijo_select, armijo_update, candidates_pass
from bigclam_tpu.ops.objective import grad_llh
from bigclam_tpu.ops.pallas_csr import (
    candidates_csr,
    device_tiles,
    grad_llh_csr,
)


def _random_graph(rng, n=57, p=0.12):
    a = rng.random((n, n)) < p
    edges = [(i, j) for i in range(n) for j in range(i + 1, n) if a[i, j]]
    edges.append((0, n - 1))          # ensure the last node is connected
    return graph_from_edges(edges, num_nodes=n)


@pytest.fixture(scope="module")
def setup(rng):
    g = _random_graph(rng)
    cfg = BigClamConfig(num_communities=5, dtype="float32", edge_chunk=64)
    bt = build_block_tiles(g, block_b=16, tile_t=8)
    k_pad = 8
    n_pad = bt.n_blocks * bt.block_b
    F = np.zeros((n_pad, k_pad), np.float32)
    F[: g.num_nodes, :5] = rng.uniform(0.0, 1.5, (g.num_nodes, 5))
    F = jnp.asarray(F)
    edges, n_pad2 = prepare_graph(g, cfg, node_multiple=bt.block_b)
    assert n_pad2 == n_pad
    return g, cfg, bt, F, edges


class TestTileBuilder:
    def test_every_edge_exactly_once(self, rng):
        g = _random_graph(rng, n=41)
        bt = build_block_tiles(g, block_b=8, tile_t=4)
        m = bt.mask.astype(bool)
        src_global = bt.src_local + bt.block_id[:, None] * bt.block_b
        got = sorted(zip(src_global[m].tolist(), bt.dst[m].tolist()))
        want = sorted(zip(g.src.tolist(), g.dst.tolist()))
        assert got == want

    def test_src_local_in_range_and_blocks_monotonic(self, rng):
        g = _random_graph(rng, n=41)
        bt = build_block_tiles(g, block_b=8, tile_t=4)
        assert bt.src_local.min() >= 0 and bt.src_local.max() < bt.block_b
        assert (np.diff(bt.block_id) >= 0).all()
        # every block owns at least one tile (kernels must zero every output
        # block, even node blocks with no edges)
        assert set(bt.block_id.tolist()) == set(range(bt.n_blocks))

    def test_isolated_tail_nodes_get_tiles(self):
        # nodes 20..29 isolated -> last blocks empty but present
        g = graph_from_edges([(0, 1), (1, 2)], num_nodes=30)
        bt = build_block_tiles(g, block_b=4, tile_t=4)
        assert bt.n_blocks == 8
        assert set(bt.block_id.tolist()) == set(range(8))
        assert int(bt.mask.sum()) == g.num_directed_edges

    def test_padded_edges_accounting(self, rng):
        g = _random_graph(rng, n=41)
        bt = build_block_tiles(g, block_b=8, tile_t=4)
        assert bt.padded_edges == bt.src_local.size - g.num_directed_edges


class TestKernelsMatchXLA:
    def test_grad_llh_matches(self, setup):
        g, cfg, bt, F, edges = setup
        tiles = device_tiles(bt)
        sumF = F.sum(axis=0)
        grad_x, llh_x = grad_llh(F, sumF, edges, cfg)
        grad_p, llh_p = grad_llh_csr(F, sumF, tiles, cfg, interpret=True)
        np.testing.assert_allclose(grad_p, grad_x, rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(llh_p, llh_x, rtol=2e-5, atol=2e-5)

    def test_candidates_and_update_match(self, setup):
        g, cfg, bt, F, edges = setup
        tiles = device_tiles(bt)
        sumF = F.sum(axis=0)
        grad, node_llh = grad_llh(F, sumF, edges, cfg)
        cand_nbr = candidates_pass(F, grad, edges, cfg)
        F_x, sumF_x = armijo_update(F, sumF, grad, node_llh, cand_nbr, cfg)
        cand_full = candidates_csr(F, grad, sumF, tiles, cfg, interpret=True)
        F_p, sumF_p = armijo_select(F, grad, node_llh, cand_full, cfg)
        np.testing.assert_allclose(F_p, F_x, rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(sumF_p, sumF_x, rtol=2e-4, atol=2e-4)

    def test_model_step_csr_matches_xla(self, rng):
        g = _random_graph(rng, n=37)
        k = 6
        cfg = BigClamConfig(num_communities=k, dtype="float32", edge_chunk=64)
        F0 = rng.uniform(0.0, 1.0, size=(g.num_nodes, k))
        ref = BigClamModel(g, cfg.replace(use_pallas_csr=False))
        csr = BigClamModel(
            g,
            cfg.replace(
                use_pallas_csr=True,
                pallas_interpret=True,
                csr_block_b=8,
                csr_tile_t=8,
            ),
        )
        s_ref, s_csr = ref.init_state(F0), csr.init_state(F0)
        for _ in range(3):
            s_ref, s_csr = ref._step(s_ref), csr._step(s_csr)
        n = g.num_nodes
        np.testing.assert_allclose(
            np.asarray(s_csr.F)[:n, :k],
            np.asarray(s_ref.F)[:n, :k],
            rtol=3e-5,
            atol=3e-5,
        )
        np.testing.assert_allclose(
            float(s_csr.llh), float(s_ref.llh), rtol=1e-5
        )

    def test_model_step_csr_matches_xla_relaxed_clip(self, rng):
        """Quality mode's MAX_P_ relaxation runs the SAME kernels with
        max_p = 1-1e-6 (the f32 floor, models.quality.auto_quality_max_p);
        the f32 1-p arithmetic under the relaxed clip must still match the
        XLA path — near-zero dots now amplify by ~1e6 instead of 1e4."""
        g = _random_graph(rng, n=37)
        k = 6
        cfg = BigClamConfig(
            num_communities=k, dtype="float32", edge_chunk=64,
            max_p=1.0 - 1e-6,
        )
        # rows with near-zero noise entries exercise the clipped regime
        F0 = rng.uniform(0.0, 1e-4, size=(g.num_nodes, k))
        F0[:5] = rng.uniform(0.0, 1.0, size=(5, k))
        ref = BigClamModel(g, cfg.replace(use_pallas_csr=False))
        csr = BigClamModel(
            g,
            cfg.replace(
                use_pallas_csr=True, pallas_interpret=True,
                csr_block_b=8, csr_tile_t=8,
            ),
        )
        s_ref, s_csr = ref.init_state(F0), csr.init_state(F0)
        for _ in range(3):
            s_ref, s_csr = ref._step(s_ref), csr._step(s_csr)
        n = g.num_nodes
        assert np.isfinite(float(s_csr.llh))
        np.testing.assert_allclose(
            np.asarray(s_csr.F)[:n, :k],
            np.asarray(s_ref.F)[:n, :k],
            rtol=3e-5, atol=3e-5,
        )
        np.testing.assert_allclose(
            float(s_csr.llh), float(s_ref.llh), rtol=1e-5
        )

    def test_tp_kernel_suite_matches_fused(self, setup):
        """The split TP kernels (partial dots -> consume) composed WITHOUT a
        psum (single K shard) must reproduce the fused kernels exactly."""
        from bigclam_tpu.ops.pallas_csr import (
            cand_dots_csr,
            cand_nbr_from_x_csr,
            edge_dots_csr,
            gather_dst_rows,
            grad_nbr_from_x_csr,
        )

        g, cfg, bt, F, edges = setup
        tiles = device_tiles(bt)
        sumF = F.sum(axis=0)
        fd = gather_dst_rows(F, tiles)
        x = edge_dots_csr(F, tiles, fd, interpret=True)
        grad_nbr, llh_nbr = grad_nbr_from_x_csr(x, tiles, fd, cfg, interpret=True)
        grad_tp = grad_nbr - sumF[None, :] + F
        grad_f, llh_f = grad_llh_csr(F, sumF, tiles, cfg, fd=fd, interpret=True)
        np.testing.assert_allclose(grad_tp, grad_f, rtol=2e-5, atol=2e-5)
        from bigclam_tpu.ops.objective import node_tail

        node_llh_tp = llh_nbr + node_tail(F, sumF)
        np.testing.assert_allclose(node_llh_tp, llh_f, rtol=2e-5, atol=2e-5)
        xc = cand_dots_csr(F, grad_f, tiles, fd, cfg, interpret=True)
        cand_nbr = cand_nbr_from_x_csr(xc, tiles, cfg, interpret=True)
        # fused candidates include the Armijo tails; add them to compare
        etas = np.asarray(cfg.step_candidates, np.float32)
        Fn = np.asarray(F)
        Gn = np.asarray(grad_f)
        sF = np.asarray(sumF)
        tails = []
        for eta in etas:
            nf = np.clip(Fn + eta * Gn, cfg.min_f, cfg.max_f)
            tails.append((nf * (Fn - sF[None, :])).sum(axis=1))
        cand_tp_full = np.asarray(cand_nbr) + np.stack(tails)
        cand_fused = candidates_csr(
            F, grad_f, sumF, tiles, cfg, fd=fd, interpret=True
        )
        np.testing.assert_allclose(
            cand_tp_full, cand_fused, rtol=2e-5, atol=2e-5
        )

    def test_auto_mode_off_on_cpu(self, rng):
        g = _random_graph(rng, n=37)
        cfg = BigClamConfig(num_communities=6)
        model = BigClamModel(g, cfg)
        assert model._tiles is None


class TestShardedCSR:
    """Blocked-CSR kernels inside shard_map (DP-only), interpret mode."""

    def _models(self, rng, dp, balance=False):
        import jax
        from bigclam_tpu.parallel import ShardedBigClamModel, make_mesh

        g = _random_graph(rng, n=71)
        k = 6
        base = BigClamConfig(num_communities=k, edge_chunk=64)
        mesh = make_mesh((dp, 1), jax.devices()[: dp])
        csr_cfg = base.replace(
            use_pallas_csr=True, pallas_interpret=True,
            csr_block_b=8, csr_tile_t=8,
        )
        xla_cfg = base.replace(use_pallas_csr=False)
        m_csr = ShardedBigClamModel(g, csr_cfg, mesh, balance=balance)
        m_xla = ShardedBigClamModel(g, xla_cfg, mesh, balance=balance)
        return g, k, m_csr, m_xla

    def test_sharded_csr_matches_xla(self, rng):
        g, k, m_csr, m_xla = self._models(rng, dp=4)
        assert m_csr.edges is None          # CSR step built, no EdgeChunks
        F0 = rng.uniform(0.0, 1.0, size=(g.num_nodes, k))
        s_c, s_x = m_csr.init_state(F0), m_xla.init_state(F0)
        for _ in range(3):
            s_c, s_x = m_csr._step(s_c), m_xla._step(s_x)
        import numpy as np
        Fc = np.asarray(s_c.F)[: g.num_nodes, :k]
        Fx = np.asarray(s_x.F)[: g.num_nodes, :k]
        np.testing.assert_allclose(Fc, Fx, rtol=3e-5, atol=3e-5)
        np.testing.assert_allclose(float(s_c.llh), float(s_x.llh), rtol=1e-5)

    def test_sharded_csr_matches_single_chip(self, rng):
        g, k, m_csr, _ = self._models(rng, dp=2)
        F0 = rng.uniform(0.0, 1.0, size=(g.num_nodes, k))
        single = BigClamModel(
            g,
            BigClamConfig(
                num_communities=k, use_pallas_csr=True,
                pallas_interpret=True, csr_block_b=8, csr_tile_t=8,
            ),
        )
        s_m, s_s = m_csr.init_state(F0), single.init_state(F0)
        for _ in range(2):
            s_m, s_s = m_csr._step(s_m), single._step(s_s)
        Fm = np.asarray(s_m.F)[: g.num_nodes, :k]
        Fs = np.asarray(s_s.F)[: g.num_nodes, :k]
        np.testing.assert_allclose(Fm, Fs, rtol=3e-5, atol=3e-5)

    def test_sharded_csr_with_balance(self, rng):
        g, k, m_csr, m_xla = self._models(rng, dp=4, balance=True)
        F0 = rng.uniform(0.0, 1.0, size=(g.num_nodes, k))
        r_c = m_csr.fit(F0)
        r_x = m_xla.fit(F0)
        np.testing.assert_allclose(r_c.llh, r_x.llh, rtol=1e-4)

    @pytest.mark.parametrize("mesh_shape", [(2, 2), (4, 2), (2, 4)])
    def test_sharded_csr_tp_matches_xla(self, rng, mesh_shape):
        """CSR kernels under a SHARDED K axis: partial-dot kernels + psum
        over "k" (the TP suite) must match the XLA sharded step."""
        import jax
        from bigclam_tpu.parallel import ShardedBigClamModel, make_mesh

        dp, tp = mesh_shape
        g = _random_graph(rng, n=71)
        k = 6
        base = BigClamConfig(num_communities=k, edge_chunk=64)
        mesh = make_mesh(mesh_shape, jax.devices()[: dp * tp])
        m_csr = ShardedBigClamModel(
            g,
            base.replace(
                use_pallas_csr=True, pallas_interpret=True,
                # pin the SPLIT kernel suite (the fused superstep
                # is the default since r17; its parity lives in
                # tests/test_fused.py)
                csr_fused=False,
                csr_block_b=8, csr_tile_t=8,
            ),
            mesh,
        )
        m_xla = ShardedBigClamModel(
            g, base.replace(use_pallas_csr=False), mesh
        )
        assert m_csr.engaged_path == "csr"
        F0 = rng.uniform(0.0, 1.0, size=(g.num_nodes, k))
        s_c, s_x = m_csr.init_state(F0), m_xla.init_state(F0)
        for _ in range(3):
            s_c, s_x = m_csr._step(s_c), m_xla._step(s_x)
        n = g.num_nodes
        Fc = np.asarray(s_c.F)[:n, :k]
        Fx = np.asarray(s_x.F)[:n, :k]
        # same tolerance as the flat DP tests: fp32 reduction order differs
        # between the kernel partial-dot psum and XLA's einsum psum
        np.testing.assert_allclose(Fc, Fx, rtol=3e-5, atol=3e-5)
        np.testing.assert_allclose(float(s_c.llh), float(s_x.llh), rtol=1e-5)

    def test_sharded_csr_grouped_matches_xla(self, rng, monkeypatch):
        """Large-K grouped layout on the SHARDED trainer (round-1 gap: the
        trainer silently fell back to XLA when the flat fd gather exceeded
        budget)."""
        import jax
        import bigclam_tpu.parallel.sharded as ps
        from bigclam_tpu.parallel import ShardedBigClamModel, make_mesh

        monkeypatch.setattr(ps, "FLAT_FD_BUDGET", 0)     # force grouping
        monkeypatch.setattr(ps, "GROUP_FD_BUDGET", 40960)
        g = _random_graph(rng, n=71)
        k = 6
        base = BigClamConfig(num_communities=k, edge_chunk=64)
        for dp in (2, 4):
            mesh = make_mesh((dp, 1), jax.devices()[:dp])
            m_csr = ShardedBigClamModel(
                g,
                base.replace(
                    use_pallas_csr=True, pallas_interpret=True,
                # pin the SPLIT kernel suite (the fused superstep
                # is the default since r17; its parity lives in
                # tests/test_fused.py)
                csr_fused=False,
                    csr_block_b=8, csr_tile_t=8,
                ),
                mesh,
            )
            m_xla = ShardedBigClamModel(
                g, base.replace(use_pallas_csr=False), mesh
            )
            assert m_csr.engaged_path == "csr_grouped"
            assert m_csr._csr_nb is not None and m_csr._csr_nb >= 1
            F0 = rng.uniform(0.0, 1.0, size=(g.num_nodes, k))
            s_c, s_x = m_csr.init_state(F0), m_xla.init_state(F0)
            for _ in range(3):
                s_c, s_x = m_csr._step(s_c), m_xla._step(s_x)
            n = g.num_nodes
            np.testing.assert_allclose(
                np.asarray(s_c.F)[:n, :k], np.asarray(s_x.F)[:n, :k],
                rtol=1e-5, atol=1e-5,
            )
            np.testing.assert_allclose(
                float(s_c.llh), float(s_x.llh), rtol=1e-5
            )


    @pytest.mark.parametrize("mesh_shape", [(2, 2), (4, 2)])
    def test_sharded_csr_grouped_tp_matches_xla(
        self, rng, monkeypatch, mesh_shape
    ):
        """Grouped (large-K) layout under a SHARDED K axis: per group, the
        TP kernel split (VERDICT round-3 item 2 — the tp == 1 gate on the
        grouped layout is lifted)."""
        import jax
        import bigclam_tpu.parallel.sharded as ps
        from bigclam_tpu.parallel import ShardedBigClamModel, make_mesh

        monkeypatch.setattr(ps, "FLAT_FD_BUDGET", 0)     # force grouping
        monkeypatch.setattr(ps, "GROUP_FD_BUDGET", 40960)
        dp, tp = mesh_shape
        g = _random_graph(rng, n=71)
        k = 6
        base = BigClamConfig(num_communities=k, edge_chunk=64)
        mesh = make_mesh(mesh_shape, jax.devices()[: dp * tp])
        m_csr = ShardedBigClamModel(
            g,
            base.replace(
                use_pallas_csr=True, pallas_interpret=True,
                # pin the SPLIT kernel suite (the fused superstep
                # is the default since r17; its parity lives in
                # tests/test_fused.py)
                csr_fused=False,
                csr_block_b=8, csr_tile_t=8,
            ),
            mesh,
        )
        m_xla = ShardedBigClamModel(
            g, base.replace(use_pallas_csr=False), mesh
        )
        assert m_csr.engaged_path == "csr_grouped"
        assert m_csr._csr_nb is not None and m_csr._csr_nb >= 1
        F0 = rng.uniform(0.0, 1.0, size=(g.num_nodes, k))
        s_c, s_x = m_csr.init_state(F0), m_xla.init_state(F0)
        for _ in range(3):
            s_c, s_x = m_csr._step(s_c), m_xla._step(s_x)
        n = g.num_nodes
        np.testing.assert_allclose(
            np.asarray(s_c.F)[:n, :k], np.asarray(s_x.F)[:n, :k],
            rtol=3e-5, atol=3e-5,
        )
        np.testing.assert_allclose(float(s_c.llh), float(s_x.llh), rtol=1e-5)


    @pytest.mark.parametrize("mesh_shape", [(2, 1), (2, 2), (1, 2)])
    def test_sharded_csr_grouped_kblocked_matches_xla(
        self, rng, monkeypatch, mesh_shape
    ):
        """The last layout cell (PARITY round-4 deferred): K so large that
        even K_loc = K/tp exceeds the kernels' VMEM bound — grouped tiles +
        a K-block scan inside each group (train_pass_csr_grouped_kblocked_tp;
        psums over "k" are identity at tp == 1). csr_k_block is the
        interpret-mode hook standing in for the auto VMEM-refusal search."""
        import jax
        import bigclam_tpu.parallel.sharded as ps
        from bigclam_tpu.parallel import ShardedBigClamModel, make_mesh

        monkeypatch.setattr(ps, "GROUP_FD_BUDGET", 40960)
        dp, tp = mesh_shape
        g = _random_graph(rng, n=71)
        k = 12
        base = BigClamConfig(num_communities=k, edge_chunk=64)
        mesh = make_mesh(mesh_shape, jax.devices()[: dp * tp])
        m_csr = ShardedBigClamModel(
            g,
            base.replace(
                use_pallas_csr=True, pallas_interpret=True,
                # pin the SPLIT kernel suite (the fused superstep
                # is the default since r17; its parity lives in
                # tests/test_fused.py)
                csr_fused=False,
                csr_block_b=8, csr_tile_t=8, csr_k_block=3,
            ),
            mesh,
        )
        m_xla = ShardedBigClamModel(
            g, base.replace(use_pallas_csr=False), mesh
        )
        assert m_csr.engaged_path == "csr_grouped_kb"
        assert m_csr._csr_kc == 3
        assert m_csr._csr_nb is not None and m_csr._csr_nb >= 1
        F0 = rng.uniform(0.0, 1.0, size=(g.num_nodes, k))
        s_c, s_x = m_csr.init_state(F0), m_xla.init_state(F0)
        for _ in range(3):
            s_c, s_x = m_csr._step(s_c), m_xla._step(s_x)
        n = g.num_nodes
        np.testing.assert_allclose(
            np.asarray(s_c.F)[:n, :k], np.asarray(s_x.F)[:n, :k],
            rtol=3e-5, atol=3e-5,
        )
        np.testing.assert_allclose(float(s_c.llh), float(s_x.llh), rtol=1e-5)

    @pytest.mark.parametrize("mesh_shape", [(2, 1), (2, 2)])
    def test_ring_csr_kblocked_matches_xla(self, rng, mesh_shape):
        """Ring phases with the K axis processed in kc-column blocks
        (step_shard_kb): K_loc beyond the VMEM bound no longer falls the
        ring back to XLA. Must match the XLA ring step."""
        import jax
        from bigclam_tpu.parallel import RingBigClamModel, make_mesh

        dp, tp = mesh_shape
        # ER graph: the clique toy is too bucket-skewed for the ring
        # layout economy at tiny sizes (see __graft_entry__)
        g = _random_graph(np.random.default_rng(5), n=64, p=0.15)
        k = 12
        base = BigClamConfig(num_communities=k, edge_chunk=64)
        mesh = make_mesh(mesh_shape, jax.devices()[: dp * tp])
        m_csr = RingBigClamModel(
            g,
            base.replace(
                use_pallas_csr=True, pallas_interpret=True,
                # pin the SPLIT kernel suite (the fused superstep
                # is the default since r17; its parity lives in
                # tests/test_fused.py)
                csr_fused=False,
                csr_block_b=8, csr_tile_t=8, csr_k_block=3,
            ),
            mesh,
        )
        m_xla = RingBigClamModel(
            g, base.replace(use_pallas_csr=False), mesh
        )
        assert m_csr.engaged_path == "csr_ring_kb"
        assert m_csr._csr_kc == 3
        F0 = rng.uniform(0.0, 1.0, size=(g.num_nodes, k))
        s_c, s_x = m_csr.init_state(F0), m_xla.init_state(F0)
        for _ in range(3):
            s_c, s_x = m_csr._step(s_c), m_xla._step(s_x)
        n = g.num_nodes
        np.testing.assert_allclose(
            np.asarray(s_c.F)[:n, :k], np.asarray(s_x.F)[:n, :k],
            rtol=3e-5, atol=3e-5,
        )
        np.testing.assert_allclose(float(s_c.llh), float(s_x.llh), rtol=1e-5)


class TestGroupedCSR:
    """Large-K grouped layout: scan over block windows with per-group dst
    gathers. Must match the flat kernels (and therefore the XLA path)."""

    def test_group_tiles_covers_every_edge(self, rng):
        from bigclam_tpu.ops.csr_tiles import group_tiles

        g = _random_graph(rng, n=41)
        bt = build_block_tiles(g, block_b=8, tile_t=4)
        for nb in (1, 2, 3):
            gbt = group_tiles(bt, nb)
            m = gbt.mask.astype(bool)
            blk_global = (
                gbt.block_id[:, :, None]
                + np.arange(gbt.n_groups)[:, None, None] * nb
            )
            src_global = gbt.src_local + blk_global * gbt.block_b
            got = sorted(zip(src_global[m].tolist(), gbt.dst[m].tolist()))
            want = sorted(zip(g.src.tolist(), g.dst.tolist()))
            assert got == want, nb
            # block ids non-decreasing within every group
            assert (np.diff(gbt.block_id, axis=1) >= 0).all()

    def test_grouped_kernels_match_flat(self, rng):
        from bigclam_tpu.ops.csr_tiles import group_tiles
        from bigclam_tpu.ops.pallas_csr import (
            candidates_csr_grouped,
            device_grouped_tiles,
            grad_llh_csr_grouped,
        )

        g = _random_graph(rng, n=53)
        cfg = BigClamConfig(num_communities=5, dtype="float32")
        bt = build_block_tiles(g, block_b=8, tile_t=8)
        gbt = group_tiles(bt, nb=3)
        flat = device_tiles(bt)
        grp = device_grouped_tiles(gbt)
        k_pad = 8
        F = np.zeros((gbt.n_pad, k_pad), np.float32)
        F[: g.num_nodes, :5] = rng.uniform(0.0, 1.5, (g.num_nodes, 5))
        F = jnp.asarray(F)
        sumF = F.sum(axis=0)
        Ff = F[: flat.n_pad]
        grad_f, llh_f = grad_llh_csr(Ff, sumF, flat, cfg, interpret=True)
        grad_g, llh_g = grad_llh_csr_grouped(F, sumF, grp, cfg, interpret=True)
        n = g.num_nodes
        np.testing.assert_allclose(grad_g[:n], grad_f[:n], rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(llh_g[:n], llh_f[:n], rtol=2e-5, atol=2e-5)
        cand_f = candidates_csr(Ff, grad_f, sumF, flat, cfg, interpret=True)
        cand_g = candidates_csr_grouped(
            F, grad_g, sumF, grp, cfg, interpret=True
        )
        np.testing.assert_allclose(
            cand_g[:, :n], cand_f[:, :n], rtol=2e-5, atol=2e-5
        )

    def test_kblocked_matches_grouped(self, rng):
        """Single-chip large-K mode: the K-column-blocked grouped pass must
        reproduce the plain grouped pass (same kernels, K scanned in
        blocks; candidate terms neighbor-only + XLA tails)."""
        from bigclam_tpu.ops.csr_tiles import group_tiles
        from bigclam_tpu.ops.linesearch import armijo_select, armijo_update
        from bigclam_tpu.ops.pallas_csr import (
            device_grouped_tiles,
            train_pass_csr_grouped,
            train_pass_csr_grouped_kblocked,
        )

        g = _random_graph(rng, n=53)
        k_pad = 8
        cfg = BigClamConfig(num_communities=k_pad, dtype="float32")
        bt = build_block_tiles(g, block_b=8, tile_t=8)
        gbt = group_tiles(bt, nb=3)
        grp = device_grouped_tiles(gbt)
        grp_kb = device_grouped_tiles(gbt, kc=4)       # 2 K blocks
        F = np.zeros((gbt.n_pad, k_pad), np.float32)
        F[: g.num_nodes] = rng.uniform(0.0, 1.5, (g.num_nodes, k_pad))
        F = jnp.asarray(F)
        sumF = F.sum(axis=0)
        grad_g, llh_g, cand_full = train_pass_csr_grouped(
            F, sumF, grp, cfg, interpret=True
        )
        grad_b, llh_nbr_b, cand_nbr_b = train_pass_csr_grouped_kblocked(
            F, sumF, grp_kb, cfg, interpret=True
        )
        from bigclam_tpu.ops.objective import node_tail

        n = g.num_nodes
        np.testing.assert_allclose(
            grad_b[:n], grad_g[:n], rtol=2e-5, atol=2e-5
        )
        llh_b = llh_nbr_b + node_tail(F, sumF)
        np.testing.assert_allclose(llh_b[:n], llh_g[:n], rtol=2e-5, atol=2e-5)
        # end-to-end update equality: full-cands path vs nbr-cands + tails
        F1_g, s1_g = armijo_select(F, grad_g, llh_g, cand_full, cfg)
        F1_b, s1_b = armijo_update(F, sumF, grad_b, llh_b, cand_nbr_b, cfg)
        np.testing.assert_allclose(
            np.asarray(F1_b)[:n], np.asarray(F1_g)[:n], rtol=2e-5, atol=2e-5
        )

    def test_model_kblocked_step_matches_xla(self, rng, monkeypatch):
        """Model-level engagement of the K-blocked path (csr_k_block +
        interpret on CPU) against the XLA reference."""
        import bigclam_tpu.models.bigclam as mb

        monkeypatch.setattr(mb, "FLAT_FD_BUDGET", 0)
        monkeypatch.setattr(mb, "GROUP_FD_BUDGET", 40960)
        g = _random_graph(rng, n=37)
        k = 6
        cfg = BigClamConfig(num_communities=k, dtype="float32", edge_chunk=64)
        F0 = rng.uniform(0.0, 1.0, size=(g.num_nodes, k))
        ref = BigClamModel(g, cfg.replace(use_pallas_csr=False))
        kb = BigClamModel(
            g,
            cfg.replace(
                use_pallas_csr=True, pallas_interpret=True,
                # pin the SPLIT kernel suite (the fused superstep
                # is the default since r17; its parity lives in
                # tests/test_fused.py)
                csr_fused=False,
                csr_block_b=8, csr_tile_t=8, csr_k_block=3,
            ),
        )
        assert kb.engaged_path == "csr_grouped_kb"
        assert kb.k_pad % 3 == 0
        s_ref, s_kb = ref.init_state(F0), kb.init_state(F0)
        for _ in range(3):
            s_ref, s_kb = ref._step(s_ref), kb._step(s_kb)
        n = g.num_nodes
        np.testing.assert_allclose(
            np.asarray(s_kb.F)[:n, :k], np.asarray(s_ref.F)[:n, :k],
            rtol=3e-5, atol=3e-5,
        )
        np.testing.assert_allclose(
            float(s_kb.llh), float(s_ref.llh), rtol=1e-5
        )

    def test_model_grouped_step_matches_xla(self, rng, monkeypatch):
        import bigclam_tpu.models.bigclam as mb
        from bigclam_tpu.ops.pallas_csr import GroupedTilesDev

        monkeypatch.setattr(mb, "FLAT_FD_BUDGET", 0)     # force grouping
        # small enough for several groups (k_pad=128, T=8: ~10 tiles/group),
        # large enough that a single-block group stays within the 4x hub
        # allowance
        monkeypatch.setattr(mb, "GROUP_FD_BUDGET", 40960)
        g = _random_graph(rng, n=37)
        k = 6
        cfg = BigClamConfig(num_communities=k, dtype="float32", edge_chunk=64)
        F0 = rng.uniform(0.0, 1.0, size=(g.num_nodes, k))
        ref = BigClamModel(g, cfg.replace(use_pallas_csr=False))
        grp = BigClamModel(
            g,
            cfg.replace(
                use_pallas_csr=True, pallas_interpret=True,
                # pin the SPLIT kernel suite (the fused superstep
                # is the default since r17; its parity lives in
                # tests/test_fused.py)
                csr_fused=False,
                csr_block_b=8, csr_tile_t=8,
            ),
        )
        assert isinstance(grp._tiles, GroupedTilesDev)
        s_ref, s_grp = ref.init_state(F0), grp.init_state(F0)
        for _ in range(3):
            s_ref, s_grp = ref._step(s_ref), grp._step(s_grp)
        n = g.num_nodes
        np.testing.assert_allclose(
            np.asarray(s_grp.F)[:n, :k], np.asarray(s_ref.F)[:n, :k],
            rtol=3e-5, atol=3e-5,
        )
        np.testing.assert_allclose(
            float(s_grp.llh), float(s_ref.llh), rtol=1e-5
        )


def test_largest_fitting_kblock_policy():
    """The shared large-K policy: kc divides k_pad, is a 128-multiple, its
    shape fits VMEM, and no larger qualifying divisor exists."""
    from bigclam_tpu.ops.pallas_csr import (
        fit_tile_shape,
        largest_fitting_kblock,
    )

    for k_pad in (2560, 3072, 5120, 25600):
        if fit_tile_shape(256, 512, k_pad) is not None:
            continue                      # whole-K fits; policy not needed
        kc, shape = largest_fitting_kblock(256, 512, k_pad)
        assert kc % 128 == 0 and k_pad % kc == 0 and kc < k_pad
        assert fit_tile_shape(256, 512, kc) == shape
        for d in range(kc // 128 + 1, k_pad // 128):
            if (k_pad // 128) % d == 0:
                assert fit_tile_shape(256, 512, 128 * d) is None, (k_pad, d)


def test_sharded_auto_kblock_engagement(rng):
    """K_loc beyond the VMEM bound auto-engages csr_grouped_kb on the
    sharded trainer (construction-time decision; kernels run on TPU)."""
    import jax
    from bigclam_tpu.parallel import ShardedBigClamModel, make_mesh

    g = _random_graph(rng, n=71)
    for tp, expect_kloc in ((1, 3072), (2, 1536)):
        mesh = make_mesh((2, tp), jax.devices()[: 2 * tp])
        m = ShardedBigClamModel(
            g,
            BigClamConfig(
                num_communities=3000, use_pallas_csr=True,
                csr_fused=False,    # the split-path auto policy
            ),
            mesh,
        )
        k_loc = m.k_pad // tp
        assert k_loc == expect_kloc
        if tp == 1:
            # K_loc 3072 exceeds the VMEM bound -> K-blocked
            assert m.engaged_path == "csr_grouped_kb"
            assert m._csr_kc == 1536
        else:
            # K_loc 1536 fits whole -> plain grouped/flat TP, no K blocks
            assert m._csr_kc == 0
