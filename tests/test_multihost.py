"""Multi-host plumbing tests on the 8-device CPU fake (SURVEY.md §4.4):
single-process semantics of the distributed init gate, slice grouping,
DCN-aware mesh construction, and process-local array placement."""

import numpy as np
import pytest

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from bigclam_tpu.parallel import make_multihost_mesh, put_sharded
from bigclam_tpu.parallel.multihost import (
    addressable_row_bounds,
    initialize_distributed,
    put_process_local,
    slice_groups,
)


class _FakeDev:
    def __init__(self, slice_index):
        self.slice_index = slice_index


def test_initialize_distributed_noop_without_coordinator(monkeypatch):
    for k in ("JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS"):
        monkeypatch.delenv(k, raising=False)
    assert initialize_distributed() is False


def test_slice_groups_single_domain():
    groups = slice_groups(jax.devices())
    assert list(groups.keys()) == [0]
    assert len(groups[0]) == 8


def test_slice_groups_multi_slice():
    devs = [_FakeDev(i // 4) for i in range(8)]
    groups = slice_groups(devs)
    assert sorted(groups) == [0, 1]
    assert all(len(g) == 4 for g in groups.values())


@pytest.mark.parametrize("shape", [(8, 1), (4, 2), (2, 4)])
def test_make_multihost_mesh_single_slice(shape):
    mesh = make_multihost_mesh(shape)
    assert mesh.shape["nodes"] == shape[0]
    assert mesh.shape["k"] == shape[1]


def test_make_multihost_mesh_default_shape():
    mesh = make_multihost_mesh()
    assert mesh.shape["nodes"] == 8 and mesh.shape["k"] == 1


def test_make_multihost_mesh_bad_shape():
    with pytest.raises(ValueError):
        make_multihost_mesh((3, 2))


def test_addressable_row_bounds_full_in_single_process():
    mesh = make_multihost_mesh((4, 2))
    sharding = NamedSharding(mesh, P("nodes", "k"))
    assert addressable_row_bounds(sharding, (16, 4)) == (0, 16)


def test_put_process_local_matches_device_put():
    """The multi-process placement path, exercised single-process where the
    'local' rows are all rows: values and sharding must match device_put."""
    mesh = make_multihost_mesh((4, 2))
    rng = np.random.default_rng(0)
    x = rng.normal(size=(16, 4))
    sharding = NamedSharding(mesh, P("nodes", "k"))
    a = put_process_local(x, sharding)
    b = jax.device_put(x, sharding)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert a.sharding.is_equivalent_to(b.sharding, x.ndim)

    # edge-block layout: dim-0 sharded, trailing dims replicated
    e = rng.integers(0, 100, size=(4, 3, 8)).astype(np.int32)
    espec = NamedSharding(mesh, P("nodes", None, None))
    np.testing.assert_array_equal(
        np.asarray(put_process_local(e, espec)),
        np.asarray(jax.device_put(e, espec)),
    )


def test_put_sharded_single_process_is_device_put():
    mesh = make_multihost_mesh((8, 1))
    x = np.arange(32, dtype=np.float64).reshape(8, 4)
    sharding = NamedSharding(mesh, P("nodes", None))
    a = put_sharded(x, sharding)
    np.testing.assert_array_equal(np.asarray(a), x)


_WORKER = __import__("os").path.join(
    __import__("os").path.dirname(__file__), "_multihost_worker.py"
)


def _run_two_workers(out, mode=None, ckpt_root=None, timeout=300):
    """Spawn the two-process jax.distributed worker pair (fresh free
    coordinator port per call) and assert both exit 0 — the single harness
    for every true-multi-process test. On a timeout or first-worker crash
    the surviving child is killed so a wedged pair cannot hang pytest."""
    import os
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
                     "JAX_PROCESS_ID")
    }
    argv_tail = ([mode] if mode else []) + (
        [str(ckpt_root)] if ckpt_root else []
    )
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(port), str(i), str(out),
             *argv_tail],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        for i in range(2)
    ]
    try:
        outs = [p.communicate(timeout=timeout) for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, (so, se) in zip(procs, outs):
        assert p.returncode == 0, f"worker ({mode or 'fit'}) failed:\n{so}\n{se}"


def _worker_module():
    import importlib.util

    spec = importlib.util.spec_from_file_location("_mh_worker", _WORKER)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# the true two-process tests need cross-process CPU collectives, which
# jaxlib grew after the 0.4 line ("Multiprocess computations aren't
# implemented on the CPU backend" there) — skip, don't fail, on old jax
_needs_multiproc_cpu = pytest.mark.skipif(
    jax.__version_info__ < (0, 5, 0),
    reason="jaxlib 0.4.x CPU backend lacks multiprocess computations",
)


@_needs_multiproc_cpu
def test_true_two_process_fit(tmp_path):
    """Spawn TWO real processes (coordinator on 127.0.0.1) running the same
    sharded fit over a 4-device mesh (2 CPU devices per process): exercises
    initialize_distributed, put_process_local, and fetch_global with
    process_count() == 2 — the path round 1 never executed (VERDICT item 4).
    Trajectories must match the single-process run exactly (float64)."""
    out = tmp_path / "proc0.npz"
    _run_two_workers(out)
    assert out.exists()

    g, cfg, F0 = _worker_module().problem()
    from bigclam_tpu.models import BigClamModel

    ref = BigClamModel(g, cfg).fit(F0)
    got = np.load(out)
    np.testing.assert_allclose(got["F"], ref.F, rtol=1e-12)
    np.testing.assert_allclose(
        got["llh_history"], np.asarray(ref.llh_history), rtol=1e-12
    )


@_needs_multiproc_cpu
def test_true_two_process_checkpoint_single_writer_resume(tmp_path):
    """Kill-and-resume THROUGH a checkpoint with process_count() == 2 and
    exactly one writer (VERDICT round-3 item 3): round 1 writes checkpoints
    under max_iters=4 — each process handed its OWN directory, and the
    worker asserts only process 0's gets files (the is_primary gate);
    round 2 is a fresh pair of processes resuming from process 0's
    directory to max_iters=8. The resumed trajectory must equal the
    uninterrupted single-process run exactly (float64)."""
    import os

    out = tmp_path / "resumed.npz"
    ckpt_root = tmp_path / "ckpts"

    _run_two_workers(out, mode="ckpt-write", ckpt_root=ckpt_root)
    # the single-writer gate: p1's manager made its dir but wrote nothing
    assert any(
        f.endswith(".npz") for f in os.listdir(ckpt_root / "p0")
    )
    assert not any(
        f.endswith(".npz") for f in os.listdir(ckpt_root / "p1")
    )

    _run_two_workers(out, mode="ckpt-resume", ckpt_root=ckpt_root)
    assert out.exists()

    g, cfg, F0 = _worker_module().problem()
    from bigclam_tpu.models import BigClamModel

    ref = BigClamModel(g, cfg).fit(F0)          # uninterrupted, max_iters=8
    got = np.load(out)
    np.testing.assert_allclose(got["F"], ref.F, rtol=1e-12)
    np.testing.assert_allclose(
        got["llh_history"], np.asarray(ref.llh_history), rtol=1e-12
    )


def test_sharded_trainer_still_exact_after_put_sharded(toy_graphs):
    """End-to-end guard: the put_sharded refactor keeps trainer trajectories
    identical to the single-chip model."""
    from bigclam_tpu.config import BigClamConfig
    from bigclam_tpu.models import BigClamModel
    from bigclam_tpu.parallel import ShardedBigClamModel

    g = toy_graphs["two_cliques"]
    cfg = BigClamConfig(num_communities=2, dtype="float64", max_iters=20)
    rng = np.random.default_rng(5)
    F0 = rng.uniform(0.1, 1.0, size=(g.num_nodes, 2))
    mesh = make_multihost_mesh((4, 2))
    res_s = ShardedBigClamModel(g, cfg, mesh).fit(F0)
    res_1 = BigClamModel(g, cfg).fit(F0)
    np.testing.assert_allclose(res_s.F, res_1.F, rtol=1e-10)
    assert np.isclose(res_s.llh, res_1.llh, rtol=1e-12)


@_needs_multiproc_cpu
def test_true_two_process_store_shard_loading(tmp_path):
    """TWO real processes training from a compiled graph cache
    (StoreShardedBigClamModel): the worker asserts its HostShard covers
    exactly its own node ranges and that ONLY its own shard files were
    read (HostShard.files_read), and the per-host-loaded trajectory must
    equal the single-chip run exactly (float64) — no host ever saw the
    global CSR."""
    from bigclam_tpu.graph.store import compile_graph_cache

    g, cfg, F0 = _worker_module().problem()
    text = tmp_path / "g.txt"
    text.write_text(
        "\n".join(
            f"{u} {v}"
            for u, v in zip(g.src.tolist(), g.dst.tolist())
            if u < v
        )
    )
    cache = tmp_path / "cache"
    compile_graph_cache(
        str(text), str(cache), num_shards=4, chunk_bytes=256
    )

    out = tmp_path / "proc0.npz"
    _run_two_workers(out, mode="store", ckpt_root=cache)
    assert out.exists()

    from bigclam_tpu.models import BigClamModel

    ref = BigClamModel(g, cfg).fit(F0)
    got = np.load(out)
    np.testing.assert_allclose(got["F"], ref.F, rtol=1e-12)
    np.testing.assert_allclose(
        got["llh_history"], np.asarray(ref.llh_history), rtol=1e-12
    )


def _compiled_worker_cache(tmp_path):
    """The worker problem's text + 4-shard cache (seed scores baked)."""
    from bigclam_tpu.graph.store import compile_graph_cache

    g, cfg, F0 = _worker_module().problem()
    text = tmp_path / "g.txt"
    text.write_text(
        "\n".join(
            f"{u} {v}"
            for u, v in zip(g.src.tolist(), g.dst.tolist())
            if u < v
        )
    )
    cache = tmp_path / "cache"
    compile_graph_cache(
        str(text), str(cache), num_shards=4, chunk_bytes=256
    )
    return g, cfg, F0, cache


@_needs_multiproc_cpu
def test_true_two_process_store_csr_tiles(tmp_path):
    """ISSUE 9: TWO real processes running the store-backed trainer with
    use_pallas_csr=True (interpret kernels) — blocked-CSR tiles built from
    each host's OWN shard files (files_read asserted in the worker), baked
    seed scores loaded per host, trajectory equal to the in-memory sharded
    CSR run (float32, atol=0)."""
    g, cfg, F0, cache = _compiled_worker_cache(tmp_path)
    out = tmp_path / "proc0.npz"
    _run_two_workers(out, mode="store-csr", ckpt_root=cache)
    assert out.exists()

    import jax

    from bigclam_tpu.parallel import ShardedBigClamModel, make_mesh

    mod = _worker_module()
    mesh = make_mesh((4, 1), jax.devices()[:4])
    ref = ShardedBigClamModel(g, mod.store_csr_cfg(cfg), mesh).fit(F0)
    got = np.load(out)
    np.testing.assert_allclose(got["F"], ref.F, rtol=0, atol=0)
    np.testing.assert_allclose(
        got["llh_history"], np.asarray(ref.llh_history), rtol=0, atol=0
    )


@_needs_multiproc_cpu
def test_true_two_process_store_ring_buckets(tmp_path):
    """ISSUE 9: TWO real processes running StoreRingBigClamModel — ring
    (shard, phase) buckets built from each host's own shard files with the
    bucket pad agreed via the one-int cross-host exchange; trajectory
    equal to RingBigClamModel(balance=False) (float64, atol=0)."""
    g, cfg, F0, cache = _compiled_worker_cache(tmp_path)
    out = tmp_path / "proc0.npz"
    _run_two_workers(out, mode="store-ring", ckpt_root=cache)
    assert out.exists()

    import jax

    from bigclam_tpu.parallel import RingBigClamModel, make_mesh

    mesh = make_mesh((4, 1), jax.devices()[:4])
    ref = RingBigClamModel(
        g, cfg.replace(use_pallas_csr=False), mesh, balance=False
    ).fit(F0)
    got = np.load(out)
    np.testing.assert_allclose(got["F"], ref.F, rtol=0, atol=0)
    np.testing.assert_allclose(
        got["llh_history"], np.asarray(ref.llh_history), rtol=0, atol=0
    )


@_needs_multiproc_cpu
def test_true_two_process_quality_device(tmp_path):
    """Device-resident quality annealing across TWO real processes: the
    jitted kick + state-resident loop + single final fetch_global must
    reproduce the single-process device schedule (float64; identical
    threefry keys on an identical mesh shape)."""
    out = tmp_path / "proc0.npz"
    _run_two_workers(out, mode="quality-device")
    assert out.exists()

    mod = _worker_module()
    g, cfg, F0 = mod.problem()
    import jax

    from bigclam_tpu.models.quality import fit_quality_device
    from bigclam_tpu.parallel import ShardedBigClamModel, make_mesh

    mesh = make_mesh((4, 1), jax.devices()[:4])
    ref = fit_quality_device(
        ShardedBigClamModel(g, mod.quality_cfg(cfg), mesh), F0
    )
    got = np.load(out)
    np.testing.assert_allclose(
        got["cycles"], np.asarray(ref.cycles_llh), rtol=1e-12
    )
    np.testing.assert_allclose(got["F"], ref.fit.F, rtol=1e-12)
