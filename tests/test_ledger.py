"""Perf-ledger tests (ISSUE 6, bigclam_tpu.obs.ledger): record building +
schema, baseline matching, noise-banded diff verdicts, corrupt-line
resilience, the finalize-time env auto-append, `cli perf`
record/diff/show, and the end-to-end regression gate (identical re-run
passes, injected per-step delay fails) in-process."""

import json
import os

import numpy as np

from bigclam_tpu.obs import ledger as L
from bigclam_tpu.obs.ledger import (
    PerfLedger,
    build_record,
    diff_records,
    match_key,
    validate_record,
)


def _report(run="r1", entry="fit", host="h", backend="cpu", kind="cpu",
            keys=("BigClamModel:abc",), wall=3.0, llh=-1.0,
            spans=None):
    return {
        "run": run,
        "entry": entry,
        "wall_s": wall,
        "fingerprint": {
            "host": host, "platform": "linux", "backend": backend,
            "device_kind": kind, "devices": 1,
        },
        "compiles": {
            "count": 5, "by_key": {k: {"builds": 1} for k in keys},
        },
        "spans": {"seconds": dict(spans or {"fit": 2.5})},
        "final": {"llh": llh, "hbm_frac": None},
    }


def test_build_record_schema_and_percentiles():
    secs = [0.010, 0.011, 0.012, 0.013, 0.10]     # one outlier
    rec = build_record(_report(), secs, [100.0, 110.0, 120.0], note="n")
    assert validate_record(rec) == []
    assert rec["steps"] == 5
    assert rec["step_p50"] == 0.012
    assert rec["step_p99"] == 0.10          # nearest rank hits the outlier
    assert rec["eps_p50"] == 110.0
    assert rec["cfg_digest"] != "none" and rec["note"] == "n"
    assert rec["spans"] == {"fit": 2.5}
    # no steps at all (ingest-style runs): percentiles are None, steps 0
    rec0 = build_record(_report())
    assert rec0["steps"] == 0 and rec0["step_p50"] is None
    assert validate_record(rec0) == []


def test_validate_record_catches_drift():
    rec = build_record(_report(), [0.01])
    assert validate_record({**rec, "lv": 99})
    bad = dict(rec)
    del bad["cfg_digest"]
    assert validate_record(bad)
    assert validate_record({**rec, "steps": "3"})
    assert validate_record([1])


def test_baseline_matching_rules(tmp_path):
    led = PerfLedger(str(tmp_path / "ledger.jsonl"))
    a = led.append(build_record(_report(run="a"), [0.01]))
    led.append(build_record(_report(run="other-k", keys=("K:zzz",)), [0.01]))
    led.append(build_record(_report(run="other-host", host="h2"), [0.01]))
    led.append(build_record(_report(run="other-dev", kind="tpu v5"), [0.01]))
    b = led.append(build_record(_report(run="b"), [0.011]))
    c = led.append(build_record(_report(run="c"), [0.012]))
    recs = led.load()
    assert len(recs) == 6
    # c's baseline is b (most recent earlier match), never itself/later
    assert led.baseline_for(recs[-1], recs)["run"] == "b"
    assert led.baseline_for(recs[4], recs)["run"] == "a"
    assert led.baseline_for(recs[0], recs) is None
    # differing entry/config/host/device all break the match
    assert match_key(a) == match_key(b) == match_key(c)
    for i in (1, 2, 3):
        assert match_key(recs[i]) != match_key(a)
        assert led.baseline_for(recs[i], recs) is None


def test_rerecorded_run_never_its_own_baseline(tmp_path):
    """`perf record` on an already-auto-appended run stamps a fresh ts;
    the duplicate must baseline against the PREVIOUS run, not against its
    own earlier record (which would make every diff ratio 1.0)."""
    led = PerfLedger(str(tmp_path / "ledger.jsonl"))
    led.append(build_record(_report(run="a"), [0.01]))
    led.append(build_record(_report(run="b"), [0.02]))
    dup = build_record(_report(run="b"), [0.02])    # re-record, new ts
    dup["ts"] += 60.0                               # force a distinct ts
    led.append(dup)
    recs = led.load()
    assert [r["run"] for r in recs] == ["a", "b", "b"]
    assert led.baseline_for(recs[-1], recs)["run"] == "a"


def test_ledger_skips_corrupt_lines(tmp_path):
    path = tmp_path / "ledger.jsonl"
    led = PerfLedger(str(path))
    led.append(build_record(_report(run="a"), [0.01]))
    with open(path, "a") as f:
        f.write("NOT JSON\n[1,2]\n")
    led.append(build_record(_report(run="b"), [0.01]))
    recs = led.load()
    assert [r["run"] for r in recs] == ["a", "b"]
    assert led.load_errors == 2
    assert PerfLedger(str(tmp_path / "missing.jsonl")).load() == []


def test_diff_verdicts_and_noise_bands():
    base = build_record(_report(run="a"), [0.010] * 20, [1000.0] * 20)
    same = build_record(_report(run="b"), [0.011] * 20, [980.0] * 20)
    d = diff_records(base, same, tolerance=0.25)
    assert d["regression"] is False
    # 5x step time: flagged on p50 AND on eps
    slow = build_record(_report(run="c"), [0.050] * 20, [200.0] * 20)
    d = diff_records(base, slow, tolerance=0.25)
    assert d["regression"] is True
    flagged = {c["metric"] for c in d["checks"] if c.get("regression")}
    assert "step_p50" in flagged and "eps_p50" in flagged
    assert L.render_diff(d).count("REGRESSION") >= 2
    # a noisy baseline WIDENS the band: p90 3x p50 -> 200% band, so a 2x
    # p50 shift cannot fail the gate
    noisy = build_record(
        _report(run="n1"), [0.010] * 12 + [0.030] * 8
    )
    assert L._rel_spread(noisy) >= 1.0
    shifted = build_record(_report(run="n2"), [0.020] * 20)
    assert diff_records(noisy, shifted, 0.25)["regression"] is False
    # p99 alone (single-sample tail) never verdicts
    tail = build_record(_report(run="t"), [0.010] * 19 + [0.2])
    d = diff_records(base, tail, tolerance=0.25)
    p99 = next(c for c in d["checks"] if c["metric"] == "step_p99")
    assert p99["regression"] and not p99["verdicted"]
    assert d["regression"] is False


def test_diff_steploss_runs_fall_back_to_wall():
    base = build_record(_report(run="a", wall=10.0))
    slow = build_record(_report(run="b", wall=20.0))
    d = diff_records(base, slow, tolerance=0.25)
    assert [c["metric"] for c in d["checks"] if not c.get("skipped")] == [
        "wall_s"
    ]
    assert d["regression"] is True


def test_span_deltas_reported(tmp_path):
    base = build_record(
        _report(run="a", spans={"fit": 1.0, "fit/fit_loop/sync": 0.2}),
        [0.01] * 5,
    )
    new = build_record(
        _report(run="b", spans={"fit": 3.0, "fit/fit_loop/sync": 2.4}),
        [0.01] * 5,
    )
    d = diff_records(base, new)
    assert d["span_deltas"][0]["path"] == "fit/fit_loop/sync"
    assert d["span_deltas"][0]["ratio"] == 12.0
    assert "slowest-growing spans" in L.render_diff(d)


# --------------------------------------------------- end-to-end with jax

def _tiny_fit(root, tag, delay_s=None, iters=12, k=2, toy=None):
    from bigclam_tpu.config import BigClamConfig
    from bigclam_tpu.models import BigClamModel
    from bigclam_tpu.obs import RunTelemetry, install, uninstall
    from bigclam_tpu.resilience import FaultPlan, install_plan
    from bigclam_tpu.utils.metrics import MetricsLogger
    from bigclam_tpu.utils.profiling import StageProfile

    g = toy["two_cliques"]
    cfg = BigClamConfig(
        num_communities=k, dtype="float64", max_iters=iters, conv_tol=0.0
    )
    F0 = np.random.default_rng(5).uniform(0.1, 1.0, size=(g.num_nodes, k))
    tel = install(
        RunTelemetry(os.path.join(root, tag), entry="fit", quiet=True)
    )
    try:
        if delay_s is not None:
            install_plan(
                FaultPlan(
                    [
                        {"kind": "delay", "site": "fit.step", "at": i,
                         "seconds": delay_s}
                        for i in range(iters + 1)
                    ]
                )
            )
        prof = StageProfile()
        with prof.stage("model_build"):
            model = BigClamModel(g, cfg)
        with prof.stage("fit"), MetricsLogger(None, echo=False) as ml:
            model.fit(
                F0,
                callback=ml.step_callback(
                    g.num_directed_edges, num_nodes=g.num_nodes
                ),
            )
        tel.finalize()
    finally:
        install_plan(None)
        uninstall(tel)


def test_env_auto_append_and_cli_perf_gate(
    toy_graphs, tmp_path, monkeypatch, capsys
):
    """The acceptance flow in-process: two identical runs auto-append via
    BIGCLAM_PERF_LEDGER at finalize, `cli perf diff` passes; a third run
    with an injected per-step delay (the resilience `delay` site) is
    flagged with a nonzero exit; `cli perf record` rebuilds a record from
    the telemetry dir; `cli perf show` lists records."""
    from bigclam_tpu.cli import main as cli_main

    ledger_path = str(tmp_path / "perf" / "ledger.jsonl")
    monkeypatch.setenv("BIGCLAM_PERF_LEDGER", ledger_path)

    _tiny_fit(str(tmp_path), "a", toy=toy_graphs)
    assert cli_main(["perf", "diff", "--ledger", ledger_path]) == 1

    # huge tolerance: this test pins the WIRING (auto-append, baseline
    # match, exit codes), not the band arithmetic — that lives in the
    # pure diff_records tests above. A tiny ~5ms-step fit wobbles well
    # past any realistic band on a loaded CI box (a 2x p50 shift was
    # observed), so the pass check tolerates 5x and the injected delay
    # below is sized to clear even that decisively.
    wide = ["--tolerance", "5.0"]
    _tiny_fit(str(tmp_path), "b", toy=toy_graphs)
    assert cli_main(["perf", "diff", "--ledger", ledger_path] + wide) == 0
    out = capsys.readouterr().out
    assert "verdict: PASS" in out

    recs = PerfLedger(ledger_path).load()
    assert len(recs) == 2
    assert all(validate_record(r) == [] for r in recs)
    assert recs[0]["steps"] > 0 and recs[0]["step_p50"] > 0
    assert "fit/fit_loop/dispatch" in recs[0]["spans"]

    # injected slowdown: sized from the SLOWER of the two measured runs
    # (the diff compares c against b, and the band is max(5.0, either
    # run's own p50->p90 spread)) — 20x the worse p50 with a 0.1s floor
    # beats a 6x threshold with a wide margin even if a spread of ~10
    # sneaks in
    worse_p50 = max(recs[0]["step_p50"], recs[1]["step_p50"])
    delay = max(20.0 * worse_p50, 0.1)
    _tiny_fit(str(tmp_path), "c", delay_s=delay, toy=toy_graphs)
    assert cli_main(["perf", "diff", "--ledger", ledger_path] + wide) == 2
    out = capsys.readouterr().out
    assert "REGRESSION" in out

    # post-hoc record from the telemetry dir agrees with the auto record
    assert cli_main([
        "perf", "record", "--telemetry-dir", str(tmp_path / "b"),
        "--ledger", ledger_path, "--note", "manual",
    ]) == 0
    capsys.readouterr()                  # drain the record echo
    recs = PerfLedger(ledger_path).load()
    assert len(recs) == 4 and recs[-1]["note"] == "manual"
    assert recs[-1]["run"] == recs[1]["run"]
    assert recs[-1]["steps"] == recs[1]["steps"]
    assert recs[-1]["cfg_digest"] == recs[1]["cfg_digest"]

    assert cli_main(["perf", "show", "--ledger", ledger_path, "-n", "2"]) == 0
    shown = [json.loads(x) for x in capsys.readouterr().out.splitlines()]
    assert len(shown) == 2


def test_no_ledger_env_no_append(toy_graphs, tmp_path, monkeypatch):
    monkeypatch.delenv("BIGCLAM_PERF_LEDGER", raising=False)
    _tiny_fit(str(tmp_path), "a", toy=toy_graphs)
    assert not (tmp_path / "perf").exists()


def test_cli_perf_diff_missing_ledger(tmp_path, capsys):
    from bigclam_tpu.cli import main as cli_main

    assert cli_main(
        ["perf", "diff", "--ledger", str(tmp_path / "nope.jsonl")]
    ) == 1


def test_cli_perf_ledger_flag_does_not_leak_env(
    toy_graphs, tmp_path, monkeypatch
):
    """--perf-ledger is wired through the RunTelemetry, NOT os.environ:
    a later run in the same process without the flag must not keep
    appending to the first run's ledger."""
    import os as _os

    from bigclam_tpu.cli import main as cli_main

    monkeypatch.delenv("BIGCLAM_PERF_LEDGER", raising=False)
    graph = tmp_path / "g.txt"
    g = toy_graphs["two_cliques"]
    graph.write_text(
        "\n".join(f"{u} {v}" for u, v in zip(g.src, g.dst) if u < v)
    )
    ledger = str(tmp_path / "ledger.jsonl")
    args = ["fit", "--graph", str(graph), "--k", "2", "--dtype", "float64",
            "--max-iters", "3", "--conv-tol", "0", "--init", "random",
            "--quiet"]
    assert cli_main(
        args + ["--telemetry-dir", str(tmp_path / "t1"),
                "--perf-ledger", ledger]
    ) == 0
    assert len(PerfLedger(ledger).load()) == 1
    assert "BIGCLAM_PERF_LEDGER" not in _os.environ
    # same process, no flag: nothing appended
    assert cli_main(args + ["--telemetry-dir", str(tmp_path / "t2")]) == 0
    assert len(PerfLedger(ledger).load()) == 1


def test_cli_profile_rejects_zero_steps(tmp_path, capsys):
    from bigclam_tpu.cli import main as cli_main

    graph = tmp_path / "g.txt"
    graph.write_text("0 1\n1 2\n2 0\n")
    rc = cli_main(
        ["profile", "--graph", str(graph), "--k", "2", "--steps", "0"]
    )
    assert rc == 2
    assert "--steps" in capsys.readouterr().err


def test_maybe_append_env_primary_only(tmp_path, monkeypatch):
    path = str(tmp_path / "l.jsonl")
    monkeypatch.setenv("BIGCLAM_PERF_LEDGER", path)
    rep = _report()
    assert L.maybe_append_env({**rep, "pid": 1}, [0.01]) is None
    assert not os.path.exists(path)
    assert L.maybe_append_env({**rep, "pid": 0}, [0.01]) is not None
    assert len(PerfLedger(path).load()) == 1


def test_partition_breaks_the_match(tmp_path):
    """ISSUE 16: a 2d run never baselines against a 1d run — the two
    layouts move different bytes for the same config, so a cross-
    partition diff would verdict the schedule change as a regression."""
    led = PerfLedger(str(tmp_path / "ledger.jsonl"))
    one_d = _report(run="one-d")
    one_d["final"]["partition"] = "1d"
    led.append(build_record(one_d, [0.01]))
    for run, secs in (("two-d-a", 0.01), ("two-d-b", 0.011)):
        rep = _report(run=run)
        rep["final"]["partition"] = "2d"
        led.append(build_record(rep, [secs]))
    recs = led.load()
    assert recs[0]["partition"] == "1d"
    assert recs[1]["partition"] == "2d"
    assert match_key(recs[0]) != match_key(recs[1])
    # the later 2d run baselines the earlier 2d run, never the 1d one
    assert led.baseline_for(recs[-1], recs)["run"] == "two-d-a"
    assert led.baseline_for(recs[1], recs) is None
    # legacy records carry no partition stamp and keep matching each
    # other (None == None), not either stamped partition
    legacy = build_record(_report(run="legacy"), [0.01])
    assert legacy["partition"] is None
    assert match_key(legacy) != match_key(recs[0])
