"""Sparse top-M affiliation representation tests (ISSUE 7): dense parity
at M >= K, the M < K LLH band, the sparse allreduce == dense psum
contract, exchange-volume counters, M-not-K memory scaling, the two-array
checkpoint/rollback satellites, and the perf-ledger representation axis.

All single-process on the 8-device CPU fake (conftest) — the collective
equivalence tests run despite the jax 0.4.37 two-process skip.
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.models import BigClamModel, SparseBigClamModel
from bigclam_tpu.models.agm import sample_planted_graph
from bigclam_tpu.models.bigclam import step_cfg_key
from bigclam_tpu.ops import sparse_members as sm
from bigclam_tpu.parallel import SparseShardedBigClamModel, make_mesh
from bigclam_tpu.parallel.sparse_collectives import (
    auto_cap,
    sparse_allreduce_sum,
    static_mode,
)
from bigclam_tpu.parallel.sparse_sharded import shard_touched_counts
from bigclam_tpu.utils import CheckpointManager
from bigclam_tpu.utils.compat import shard_map


def _cfg(k, **kw):
    kw.setdefault("dtype", "float64")
    kw.setdefault("max_iters", 6)
    kw.setdefault("conv_tol", 0.0)
    kw.setdefault("use_pallas", False)
    kw.setdefault("use_pallas_csr", False)
    return BigClamConfig(num_communities=k, **kw)


def _sparse_cfg(k, m, **kw):
    return _cfg(k, representation="sparse", sparse_m=m, **kw)


@pytest.fixture(scope="module")
def planted():
    """Planted AGM blocks + a community-localized init: each node starts
    in exactly its planted community (the power-law-sparse membership
    regime the representation targets)."""
    g, truth = sample_planted_graph(
        1024, 256, p_in=0.6, rng=np.random.default_rng(11)
    )
    F0 = np.zeros((g.num_nodes, 256))
    for c, nodes in enumerate(truth):
        F0[nodes, c] = 1.0
    return g, F0


@pytest.fixture(scope="module")
def small(toy_graphs):
    g = toy_graphs["two_cliques"]
    F0 = np.random.default_rng(5).uniform(0.1, 1.0, size=(g.num_nodes, 4))
    return g, F0


# --------------------------------------------------------------------------
# representation primitives
# --------------------------------------------------------------------------


def test_from_dense_to_dense_roundtrip():
    rng = np.random.default_rng(0)
    F = rng.uniform(0.0, 1.0, size=(13, 9))
    F[F < 0.4] = 0.0                           # sparse rows
    ids, w, truncated = sm.from_dense(F, m=9, k_pad=9, n_pad=16)
    assert truncated == 0
    assert ids.shape == (16, 9) and w.shape == (16, 9)
    # ids sorted ascending per row, sentinels (== k_pad) last
    assert np.all(np.diff(ids, axis=1) >= 0)
    back = sm.to_dense(ids, w, 13, 9)
    np.testing.assert_array_equal(back, F)


def test_from_dense_truncation_keeps_top_m():
    F = np.array([[0.9, 0.1, 0.5, 0.3]])
    ids, w, truncated = sm.from_dense(F, m=2, k_pad=4, n_pad=1)
    assert truncated == 2
    back = sm.to_dense(ids, w, 1, 4)
    np.testing.assert_array_equal(back, [[0.9, 0.0, 0.5, 0.0]])


def test_sparse_sumf_and_presence_match_dense():
    rng = np.random.default_rng(1)
    F = rng.uniform(0.0, 1.0, size=(40, 12))
    F[F < 0.6] = 0.0
    ids, w, _ = sm.from_dense(F, m=12, k_pad=12, n_pad=40)
    sumF = np.asarray(sm.sparse_sumF(jnp.asarray(ids), jnp.asarray(w), 12))
    np.testing.assert_allclose(sumF, F.sum(axis=0), rtol=1e-6)
    pres = np.asarray(sm.presence(jnp.asarray(ids), 12))
    np.testing.assert_array_equal(pres, (F > 0).any(axis=0))


def test_support_update_admits_neighbor_communities(toy_graphs):
    """A node whose neighbor holds community c gains a slot for c (at
    weight 0 — its first gradient step then matches the dense path)."""
    g = toy_graphs["star"]                     # 0 -- {1,2,3,4}
    k_pad, m = 6, 4
    F = np.zeros((g.num_nodes, k_pad))
    F[1, 2] = 0.7                              # only node 1 has mass, in c=2
    ids, w, _ = sm.from_dense(F, m, k_pad, 8)
    blocks = sm.build_support_blocks(g, 8, 8)
    ids2, w2 = sm.support_update(
        jnp.asarray(ids), jnp.asarray(w), blocks, m, k_pad
    )
    ids2, w2 = np.asarray(ids2), np.asarray(w2)
    assert 2 in ids2[0]                        # hub admitted c=2
    assert w2[0][ids2[0] == 2] == 0.0          # at zero weight
    assert 2 in ids2[1] and w2[1][ids2[1] == 2] == 0.7   # kept exactly
    assert 2 not in ids2[3]                    # leaves 2..4 see no mass at
    # their own row BUT their neighbor (the hub) has none either — only
    # node 1's neighbors (the hub) admit


# --------------------------------------------------------------------------
# parity: M >= K reproduces the dense trajectory
# --------------------------------------------------------------------------


def test_m_ge_k_trajectory_matches_dense(small):
    g, F0 = small
    iters = 8
    dm = BigClamModel(g, _cfg(4, max_iters=iters))
    ds = dm.init_state(F0)
    sp = SparseBigClamModel(g, _sparse_cfg(4, 4, max_iters=iters))
    ss = sp.init_state(F0)
    for _ in range(iters):
        ds = dm._step(ds)
        ss = sp._step(ss)
        np.testing.assert_allclose(
            float(ss.llh), float(ds.llh), rtol=1e-11
        )
    np.testing.assert_allclose(
        sp.extract_F(ss), dm.extract_F(ds), rtol=1e-10, atol=1e-12
    )


def test_m_ge_k_fit_parity_and_convergence(small):
    g, F0 = small
    cfg_d = _cfg(4, max_iters=60, conv_tol=1e-6)
    rd = BigClamModel(g, cfg_d).fit(F0)
    rs = SparseBigClamModel(
        g, _sparse_cfg(4, 7, max_iters=60, conv_tol=1e-6)   # M > K clamps
    ).fit(F0)
    assert rs.num_iters == rd.num_iters
    np.testing.assert_allclose(rs.llh, rd.llh, rtol=1e-11)
    np.testing.assert_allclose(rs.F, rd.F, rtol=1e-9, atol=1e-12)
    np.testing.assert_allclose(
        rs.llh_history, rd.llh_history, rtol=1e-11
    )


def test_m_lt_k_llh_band(planted):
    """Capacity-bounded M < K on the planted-anchor graph: the sparse
    fit's LLH stays within a few percent of the dense fit's."""
    g, F0 = planted
    k = 256
    cfg_d = _cfg(k, dtype="float32", max_iters=10)
    rd = BigClamModel(g, cfg_d).fit(F0)
    rs = SparseBigClamModel(
        g, _sparse_cfg(k, 8, dtype="float32", max_iters=10)
    ).fit(F0)
    assert np.isfinite(rs.llh)
    assert abs(1.0 - rs.llh / rd.llh) < 0.05


def test_effective_m_clamps_to_k():
    from bigclam_tpu.models.sparse import effective_m

    assert effective_m(_sparse_cfg(4, 64)) == 4
    assert effective_m(_sparse_cfg(100, 64)) == 64


def test_sparse_requires_min_f_zero(small):
    g, _ = small
    with pytest.raises(ValueError, match="min_f"):
        SparseBigClamModel(g, _sparse_cfg(4, 4).replace(min_f=0.1))
    with pytest.raises(ValueError, match="representation"):
        SparseBigClamModel(g, _cfg(4))


def test_donation_bit_identity(small):
    g, F0 = small
    r_on = SparseBigClamModel(
        g, _sparse_cfg(4, 4, donate_state=True, max_iters=10)
    ).fit(F0)
    r_off = SparseBigClamModel(
        g, _sparse_cfg(4, 4, donate_state=False, max_iters=10)
    ).fit(F0)
    np.testing.assert_array_equal(r_on.F, r_off.F)
    assert r_on.llh_history == r_off.llh_history


# --------------------------------------------------------------------------
# memory: HBM scales with M, not K
# --------------------------------------------------------------------------


def test_affiliation_state_bytes_scale_with_m_not_k():
    g, _ = sample_planted_graph(
        10_000, 1000, p_in=0.6, rng=np.random.default_rng(2)
    )
    sizes = {}
    for k in (1000, 5000):
        cfg = _sparse_cfg(k, 64, dtype="float32")
        model = SparseBigClamModel(g, cfg)
        F0 = np.zeros((g.num_nodes, k), np.float32)
        F0[:, :8] = np.random.default_rng(0).uniform(
            0.1, 1.0, size=(g.num_nodes, 8)
        )
        state = model.init_state(F0)
        assert state.F.shape[1] == 64 and state.ids.shape[1] == 64
        sizes[k] = model.state_nbytes(state)
        # shape-based figure (what bench quotes without materializing a
        # state) must agree with the measured one
        assert model.state_nbytes() == sizes[k]
    # ids+w are K-independent; only the (K,) sumF grows — 16 KB on MBs
    assert sizes[5000] / sizes[1000] < 1.05, sizes
    dense_ratio = (10_000 * 5000 * 4) / (10_000 * 1000 * 4)
    assert dense_ratio == 5.0


# --------------------------------------------------------------------------
# sparse allreduce == dense psum
# --------------------------------------------------------------------------


def _run_allreduce(vals, pres, cap, k_pad, dp=4):
    mesh = Mesh(np.asarray(jax.devices()[:dp]).reshape(dp, 1),
                ("nodes", "k"))

    def body(v, p):
        out, cnt, fb = sparse_allreduce_sum(
            v[0], p[0], cap, "nodes", k_pad
        )
        return out, cnt, fb

    f = shard_map(
        body, mesh=mesh,
        in_specs=(P("nodes", None), P("nodes", None)),
        out_specs=(P(), P(), P()),
        check_vma=False,
    )
    out, cnt, fb = jax.jit(f)(jnp.asarray(vals), jnp.asarray(pres))
    return np.asarray(out), int(cnt), int(fb)


def test_sparse_allreduce_matches_psum_exactly():
    rng = np.random.default_rng(3)
    dp, k_pad, cap = 4, 64, 24
    # integer-valued floats: addition is exact, so == is meaningful
    vals = np.zeros((dp, k_pad))
    pres = np.zeros((dp, k_pad), bool)
    for i in range(dp):
        touched = rng.choice(k_pad, size=10, replace=False)
        pres[i, touched] = True
        vals[i, touched] = rng.integers(1, 100, size=10).astype(float)
    out, cnt, fb = _run_allreduce(vals, pres, cap, k_pad)
    np.testing.assert_array_equal(out, vals.sum(axis=0))
    assert cnt == 10 and fb == 0


def test_sparse_allreduce_overflow_falls_back_dense():
    rng = np.random.default_rng(4)
    dp, k_pad, cap = 4, 64, 8            # cap < touched: must overflow
    vals = rng.integers(0, 50, size=(dp, k_pad)).astype(float)
    pres = vals > 0
    out, cnt, fb = _run_allreduce(vals, pres, cap, k_pad)
    np.testing.assert_array_equal(out, vals.sum(axis=0))   # still exact
    assert fb == 1 and cnt > cap


def test_auto_cap_and_static_mode():
    assert auto_cap(10, 1000, 2.0, 64) == 64      # never below one M row
    assert auto_cap(100, 1000, 2.0, 64) == 200
    assert auto_cap(900, 1000, 2.0, 64) == 1000   # clamped to K
    assert static_mode(200, 1000, 0.5) == "sparse"
    assert static_mode(600, 1000, 0.5) == "dense"
    assert static_mode(16, 16, 0.5) == "dense"


# --------------------------------------------------------------------------
# sharded trainer
# --------------------------------------------------------------------------


def test_sharded_matches_single_chip(planted):
    g, F0 = planted
    k = 256
    cfg = _sparse_cfg(k, 16, max_iters=4)
    single = SparseBigClamModel(g, cfg)
    rs1 = single.fit(F0)
    mesh = make_mesh((8, 1), jax.devices())
    sharded = SparseShardedBigClamModel(g, cfg, mesh)
    rs8 = sharded.fit(F0)
    assert sharded.comm_mode == "sparse"           # the collective engaged
    np.testing.assert_allclose(rs8.llh, rs1.llh, rtol=1e-11)
    np.testing.assert_allclose(rs8.F, rs1.F, rtol=1e-9, atol=1e-12)


def test_sharded_exchange_volume_much_less_than_k(planted):
    """The sparse allreduce exchanges only touched community ids: the
    counter stays well under K on the planted workload, with no dense
    fallback."""
    g, F0 = planted
    k = 256
    mesh = make_mesh((8, 1), jax.devices())
    model = SparseShardedBigClamModel(g, _sparse_cfg(k, 16), mesh)
    state = model.init_state(F0)
    assert model.comm_mode == "sparse"
    for _ in range(3):
        state = model._step(state)
    exchanged, fell_back = model.last_comm(state)
    assert not fell_back
    assert 0 < exchanged <= model.comm_cap
    assert exchanged < k // 2, (exchanged, k)


def test_sharded_collective_paths_bit_identical(planted):
    """Forcing the dense psum (sparse_dense_fallback=0) changes the wire
    pattern, not the math."""
    g, F0 = planted
    k = 256
    mesh = make_mesh((4, 1), jax.devices()[:4])
    cfg = _sparse_cfg(k, 16, max_iters=3)
    m_sp = SparseShardedBigClamModel(g, cfg, mesh)
    m_ps = SparseShardedBigClamModel(
        g, cfg.replace(sparse_dense_fallback=0.0), mesh
    )
    assert m_sp.engaged_path == "sparse_xla_spall"
    assert m_ps.engaged_path == "sparse_xla_psum"
    r_sp, r_ps = m_sp.fit(F0), m_ps.fit(F0)
    np.testing.assert_array_equal(r_sp.F, r_ps.F)
    assert r_sp.llh_history == r_ps.llh_history


def test_sharded_refuses_k_axis_and_balance(planted):
    g, F0 = planted
    with pytest.raises(ValueError, match="K axis"):
        SparseShardedBigClamModel(
            g, _sparse_cfg(256, 16), make_mesh((4, 2), jax.devices())
        )
    with pytest.raises(ValueError, match="balance"):
        SparseShardedBigClamModel(
            g, _sparse_cfg(256, 16),
            make_mesh((4, 1), jax.devices()[:4]), balance=True,
        )


def test_shard_touched_counts():
    ids = np.array(
        [[0, 1, 8], [1, 2, 8], [4, 8, 8], [4, 5, 6]], dtype=np.int32
    )
    np.testing.assert_array_equal(
        shard_touched_counts(ids, 2, 8), [3, 3]
    )
    np.testing.assert_array_equal(
        shard_touched_counts(ids, 4, 8), [2, 2, 1, 3]
    )


# --------------------------------------------------------------------------
# checkpoint / rollback satellites (two-array sparse state)
# --------------------------------------------------------------------------


def test_checkpoint_resume_bit_identity(small, tmp_path):
    g, F0 = small
    cfg = _sparse_cfg(4, 4, max_iters=10, checkpoint_every=3)
    full = SparseBigClamModel(g, cfg).fit(
        F0, checkpoints=CheckpointManager(str(tmp_path / "a"))
    )
    # interrupted twin: run to iter 6, then a FRESH model resumes from
    # the saved two-array state and finishes — bit-identical F
    ckpt = CheckpointManager(str(tmp_path / "b"))
    SparseBigClamModel(g, cfg.replace(max_iters=6)).fit(F0, checkpoints=ckpt)
    assert ckpt.latest_valid_step() == 6
    resumed = SparseBigClamModel(g, cfg).fit(F0, checkpoints=ckpt)
    np.testing.assert_array_equal(resumed.F, full.F)
    assert resumed.llh == full.llh


def test_checkpoint_sidecar_crcs_cover_both_arrays(small, tmp_path):
    g, F0 = small
    cfg = _sparse_cfg(4, 4, max_iters=4, checkpoint_every=2)
    ckpt = CheckpointManager(str(tmp_path / "c"))
    SparseBigClamModel(g, cfg).fit(F0, checkpoints=ckpt)
    step = ckpt.latest_step()
    with open(ckpt._path(step) + ".json") as f:
        sidecar = json.load(f)
    assert {"F", "ids", "sumF"} <= set(sidecar["array_crc32"])
    assert sidecar["representation"] == "sparse"
    assert sidecar["sparse_m"] == 4


def test_corrupted_newest_checkpoint_falls_back(small, tmp_path):
    g, F0 = small
    cfg = _sparse_cfg(4, 4, max_iters=8, checkpoint_every=2)
    ckpt = CheckpointManager(str(tmp_path / "d"))
    SparseBigClamModel(g, cfg).fit(F0, checkpoints=ckpt)
    newest = ckpt.latest_step()
    # flip bytes mid-payload: the per-array crc catches it and restore
    # falls back to the next-older checkpoint
    path = ckpt._path(newest)
    blob = bytearray(open(path, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    open(path, "wb").write(bytes(blob))
    ckpt2 = CheckpointManager(str(tmp_path / "d"))
    restored = ckpt2.restore()
    assert restored is not None
    assert restored[0] < newest


def test_dense_checkpoint_refuses_sparse_resume(small, tmp_path):
    g, F0 = small
    dense_ckpt = CheckpointManager(str(tmp_path / "e"))
    BigClamModel(g, _cfg(4, max_iters=4, checkpoint_every=2)).fit(
        F0, checkpoints=dense_ckpt
    )
    with pytest.raises(ValueError, match="representation|member-id"):
        SparseBigClamModel(g, _sparse_cfg(4, 4, max_iters=6)).fit(
            F0, checkpoints=dense_ckpt
        )


def test_sparse_checkpoint_refuses_different_m(small, tmp_path):
    g, F0 = small
    ckpt = CheckpointManager(str(tmp_path / "f"))
    SparseBigClamModel(
        g, _sparse_cfg(4, 4, max_iters=4, checkpoint_every=2)
    ).fit(F0, checkpoints=ckpt)
    with pytest.raises(ValueError, match="sparse_m"):
        SparseBigClamModel(
            g, _sparse_cfg(4, 2, max_iters=6)
        ).fit(F0, checkpoints=ckpt)


def test_sharded_checkpoint_roundtrip(planted, tmp_path):
    g, F0 = planted
    cfg = _sparse_cfg(256, 16, max_iters=4, checkpoint_every=2)
    mesh = make_mesh((4, 1), jax.devices()[:4])
    ckpt = CheckpointManager(str(tmp_path / "g"))
    full = SparseShardedBigClamModel(g, cfg, mesh).fit(
        F0, checkpoints=CheckpointManager(str(tmp_path / "h"))
    )
    SparseShardedBigClamModel(g, cfg.replace(max_iters=2), mesh).fit(
        F0, checkpoints=ckpt
    )
    resumed = SparseShardedBigClamModel(g, cfg, mesh).fit(
        F0, checkpoints=ckpt
    )
    np.testing.assert_array_equal(resumed.F, full.F)


@pytest.mark.chaos
def test_nan_rollback_recovers_sparse_fit(small):
    """The in-HBM rollback snapshot ping-pong handles the two-array
    sparse state: an injected NaN rolls back and the fit converges
    finitely."""
    from bigclam_tpu.resilience import FaultPlan, install_plan

    g, F0 = small
    cfg = _sparse_cfg(
        4, 4, max_iters=12,
        rollback_budget=3, rollback_snapshot_every=2,
    )
    from bigclam_tpu.obs import RunTelemetry, install, uninstall

    import tempfile

    tdir = tempfile.mkdtemp(prefix="sparse_rb_")
    tel = install(RunTelemetry(tdir, entry="test", quiet=True))
    install_plan(
        FaultPlan([{"kind": "nan_inject", "site": "fit.step", "at": 5}])
    )
    try:
        res = SparseBigClamModel(g, cfg).fit(F0)
    finally:
        install_plan(None)
        tel.finalize()
        uninstall(tel)
    assert np.isfinite(res.llh)
    assert np.isfinite(res.F).all()
    from bigclam_tpu.obs.telemetry import EVENTS_NAME

    events = [
        json.loads(line)
        for line in open(os.path.join(tdir, EVENTS_NAME))
        if line.strip()
    ]
    rb = [e for e in events if e["kind"] == "rollback"]
    assert len(rb) == 1 and rb[0]["rollbacks"] == 1
    # the rollback's cut Armijo ladder changes the replayed trajectory —
    # no clean-run bit comparison; the contract is finite recovery on the
    # TWO-ARRAY state (F + ids both restored from the snapshot ping-pong)


# --------------------------------------------------------------------------
# step identity + perf-ledger representation axis
# --------------------------------------------------------------------------


def test_step_cfg_key_carries_representation_knobs():
    base = _cfg(8)
    assert step_cfg_key(base) != step_cfg_key(
        base.replace(representation="sparse")
    )
    sp = _sparse_cfg(8, 16)
    assert step_cfg_key(sp) != step_cfg_key(sp.replace(sparse_m=32))
    assert step_cfg_key(sp) != step_cfg_key(sp.replace(support_every=4))
    # host-only fields still normalize away
    assert step_cfg_key(sp) == step_cfg_key(sp.replace(max_iters=99))


def test_ledger_refuses_cross_representation_baseline():
    from bigclam_tpu.obs import ledger as L

    def rec(representation=None, sparse_m=None, run="r"):
        report = {
            "run": run, "entry": "fit", "wall_s": 1.0,
            "fingerprint": {"host": "h", "backend": "cpu",
                            "device_kind": "cpu"},
            "compiles": {"count": 1, "by_key": {"X:abc": 1}},
            "final": {
                "n": 100, "edges": 300, "k": 16,
                "representation": representation, "sparse_m": sparse_m,
            },
        }
        return L.build_record(report, [0.01] * 4)

    dense = rec("dense", run="a")
    sparse = rec("sparse", 8, run="b")
    old = rec(None, run="c")        # pre-field record (always dense)
    assert dense["representation"] == "dense"
    assert sparse["representation"] == "sparse" and sparse["sparse_m"] == 8
    assert L.match_key(dense) != L.match_key(sparse)
    assert L.match_key(dense) == L.match_key(old)      # dense continuity
    led = L.PerfLedger(os.devnull)
    assert led.baseline_for(sparse, [dense, sparse]) is None
    assert led.baseline_for(dense, [sparse, dense]) is None
    assert led.baseline_for(dense, [old, dense]) is old


def test_cli_sparse_fit_records_representation(tmp_path):
    from bigclam_tpu.cli import main as cli_main

    rng = np.random.default_rng(0)
    edges = set()
    while len(edges) < 200:
        u, v = rng.integers(0, 64, 2)
        if u != v:
            edges.add((min(int(u), int(v)), max(int(u), int(v))))
    gpath = tmp_path / "g.txt"
    gpath.write_text(
        "".join(f"{u}\t{v}\n" for u, v in sorted(edges))
    )
    tdir = str(tmp_path / "telem")
    import contextlib
    import io

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = cli_main([
            "fit", "--graph", str(gpath), "--k", "8",
            "--representation", "sparse", "--sparse-m", "4",
            "--max-iters", "4", "--init", "random", "--quiet",
            "--telemetry-dir", tdir,
        ])
    assert rc == 0
    out = json.loads(buf.getvalue().strip().splitlines()[-1])
    assert out["representation"] == "sparse" and out["sparse_m"] == 4
    report = json.load(open(os.path.join(tdir, "run_report.json")))
    assert report["final"]["representation"] == "sparse"


def test_cli_sparse_refuses_csr_kernels_on(tmp_path):
    # --csr-kernels on means REQUIRE the MXU path; the sparse trainers
    # only have the XLA member-list merge, so the contract is an error,
    # not a silent fallback
    from bigclam_tpu.cli import main as cli_main

    gpath = tmp_path / "g.txt"
    gpath.write_text("0\t1\n1\t2\n2\t0\n")
    with pytest.raises(SystemExit, match="csr-kernels on"):
        cli_main([
            "fit", "--graph", str(gpath), "--k", "4",
            "--representation", "sparse", "--csr-kernels", "on",
            "--max-iters", "2", "--init", "random", "--quiet",
        ])
