"""Ring-pass schedule tests: trajectories must equal the single-chip and
all-gather trainers for every mesh shape (SURVEY.md §4.4)."""

import numpy as np
import pytest

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.models import BigClamModel
from bigclam_tpu.models.agm import planted_partition_F, sample_graph
from bigclam_tpu.parallel import make_mesh
from bigclam_tpu.parallel.ring import RingBigClamModel, ring_shard_edges


CFG = BigClamConfig(num_communities=4, dtype="float64", max_iters=4, conv_tol=0.0)


@pytest.fixture(scope="module")
def agm_graph():
    rng = np.random.default_rng(7)
    Fp, _ = planted_partition_F(48, 4, strength=1.5)
    return sample_graph(Fp, rng=rng)


@pytest.mark.parametrize("mesh_shape", [(1, 1), (2, 1), (4, 1), (8, 1), (2, 2), (4, 2)])
def test_ring_matches_single_chip(agm_graph, mesh_shape):
    import jax

    g = agm_graph
    rng = np.random.default_rng(0)
    F0 = rng.uniform(0.1, 1.0, size=(g.num_nodes, 4))

    ref_model = BigClamModel(g, CFG)
    ref_state = ref_model.init_state(F0)
    ref_llh = []
    for _ in range(4):
        ref_state = ref_model._step(ref_state)
        ref_llh.append(float(ref_state.llh))

    mesh = make_mesh(mesh_shape, jax.devices()[: mesh_shape[0] * mesh_shape[1]])
    ring = RingBigClamModel(g, CFG, mesh)
    state = ring.init_state(F0)
    llhs = []
    for _ in range(4):
        state = ring._step(state)
        llhs.append(float(state.llh))
    n = g.num_nodes
    np.testing.assert_allclose(
        np.asarray(state.F)[:n, :4], np.asarray(ref_state.F)[:n, :4],
        rtol=1e-11, err_msg=f"mesh {mesh_shape}",
    )
    np.testing.assert_allclose(llhs, ref_llh, rtol=1e-11)


def test_ring_bucket_partition(agm_graph):
    """Every directed edge lands in exactly one (src-shard, phase) bucket
    with correctly rebased local indices."""
    g = agm_graph
    dp, n_pad = 4, 48
    e = ring_shard_edges(g, CFG, dp, n_pad, np.float64)
    shard_rows = n_pad // dp
    seen = []
    for i in range(dp):
        for r in range(dp):
            s = e.src[i, r].reshape(-1)
            d = e.dst[i, r].reshape(-1)
            m = e.mask[i, r].reshape(-1) > 0
            j = (i + r) % dp
            seen.append(
                np.stack([s[m] + i * shard_rows, d[m] + j * shard_rows], axis=1)
            )
    seen = np.concatenate(seen, axis=0)
    ref = np.stack([g.src, g.dst], axis=1)
    order = np.lexsort((seen[:, 1], seen[:, 0]))
    np.testing.assert_array_equal(seen[order], ref)


def test_ring_fit_converges(toy_graphs):
    import jax

    g = toy_graphs["two_cliques"]
    cfg = BigClamConfig(num_communities=2, dtype="float64", max_iters=50)
    rng = np.random.default_rng(3)
    F0 = rng.uniform(0.1, 1.0, size=(g.num_nodes, 2))
    mesh = make_mesh((4, 2), jax.devices())
    res_r = RingBigClamModel(g, cfg, mesh).fit(F0)
    res_1 = BigClamModel(g, cfg).fit(F0)
    assert res_r.num_iters == res_1.num_iters
    np.testing.assert_allclose(res_r.F, res_1.F, rtol=1e-10)
