"""Ring-pass schedule tests: trajectories must equal the single-chip and
all-gather trainers for every mesh shape (SURVEY.md §4.4)."""

import numpy as np
import pytest

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.models import BigClamModel
from bigclam_tpu.models.agm import planted_partition_F, sample_graph
from bigclam_tpu.parallel import make_mesh
from bigclam_tpu.parallel.ring import RingBigClamModel, ring_shard_edges


CFG = BigClamConfig(num_communities=4, dtype="float64", max_iters=4, conv_tol=0.0)


@pytest.fixture(scope="module")
def agm_graph():
    rng = np.random.default_rng(7)
    Fp, _ = planted_partition_F(48, 4, strength=1.5)
    return sample_graph(Fp, rng=rng)


@pytest.mark.parametrize("mesh_shape", [(1, 1), (2, 1), (4, 1), (8, 1), (2, 2), (4, 2)])
def test_ring_matches_single_chip(agm_graph, mesh_shape):
    import jax

    g = agm_graph
    rng = np.random.default_rng(0)
    F0 = rng.uniform(0.1, 1.0, size=(g.num_nodes, 4))

    ref_model = BigClamModel(g, CFG)
    ref_state = ref_model.init_state(F0)
    ref_llh = []
    for _ in range(4):
        ref_state = ref_model._step(ref_state)
        ref_llh.append(float(ref_state.llh))

    mesh = make_mesh(mesh_shape, jax.devices()[: mesh_shape[0] * mesh_shape[1]])
    # balance=False: this test pins the ring SCHEDULE's math on the fixed
    # layout (raw state.F compare); the auto-balance default is pinned by
    # test_ring_auto_balance_engages_on_imbalance
    ring = RingBigClamModel(g, CFG, mesh, balance=False)
    state = ring.init_state(F0)
    llhs = []
    for _ in range(4):
        state = ring._step(state)
        llhs.append(float(state.llh))
    n = g.num_nodes
    np.testing.assert_allclose(
        np.asarray(state.F)[:n, :4], np.asarray(ref_state.F)[:n, :4],
        rtol=1e-11, err_msg=f"mesh {mesh_shape}",
    )
    np.testing.assert_allclose(llhs, ref_llh, rtol=1e-11)


@pytest.mark.parametrize("mesh_shape", [(2, 1), (4, 1), (8, 1), (4, 2)])
def test_ring_overlap_matches_serial(agm_graph, mesh_shape):
    """The double-buffered (overlapped) rotation schedule — the default —
    must produce the IDENTICAL float64 LLH trajectory and final F as the
    serialized schedule on the planted fixture: rotate_scan moves the hop
    off the compute timeline, never the math."""
    import jax

    g = agm_graph
    rng = np.random.default_rng(0)
    F0 = rng.uniform(0.1, 1.0, size=(g.num_nodes, 4))
    mesh = make_mesh(mesh_shape, jax.devices()[: mesh_shape[0] * mesh_shape[1]])
    assert CFG.ring_overlap          # overlapped is the default schedule
    m_ov = RingBigClamModel(g, CFG, mesh)
    m_se = RingBigClamModel(g, CFG.replace(ring_overlap=False), mesh)
    s_ov, s_se = m_ov.init_state(F0), m_se.init_state(F0)
    llh_ov, llh_se = [], []
    for _ in range(4):
        s_ov, s_se = m_ov._step(s_ov), m_se._step(s_se)
        llh_ov.append(float(s_ov.llh))
        llh_se.append(float(s_se.llh))
    assert llh_ov == llh_se, f"mesh {mesh_shape}"
    np.testing.assert_array_equal(
        np.asarray(s_ov.F), np.asarray(s_se.F),
        err_msg=f"mesh {mesh_shape}",
    )


def test_ring_overlap_permutation_invariance(agm_graph):
    """The permutation-invariance property (SURVEY §4.5) holds under the
    overlapped schedule: relabeling node ids permutes the fit result and
    leaves the LLH trajectory unchanged (float64; summation order differs
    across labelings, so exact-math equality holds to ~1e-9)."""
    import jax

    g = agm_graph
    n = g.num_nodes
    perm = np.random.default_rng(3).permutation(n)
    gp = g.permute(perm)
    rng = np.random.default_rng(5)
    F0 = rng.uniform(0.1, 1.0, size=(n, 4))
    F0p = np.empty_like(F0)
    F0p[perm] = F0
    mesh = make_mesh((4, 1), jax.devices()[:4])
    r = RingBigClamModel(g, CFG, mesh).fit(F0)
    rp = RingBigClamModel(gp, CFG, mesh).fit(F0p)
    np.testing.assert_allclose(rp.llh, r.llh, rtol=1e-9)
    np.testing.assert_allclose(rp.llh_history, r.llh_history, rtol=1e-9)
    np.testing.assert_allclose(rp.F[perm], r.F, rtol=1e-8, atol=1e-10)


def test_ring_bucket_partition(agm_graph):
    """Every directed edge lands in exactly one (src-shard, phase) bucket
    with correctly rebased local indices."""
    g = agm_graph
    dp, n_pad = 4, 48
    e = ring_shard_edges(g, CFG, dp, n_pad, np.float64)
    shard_rows = n_pad // dp
    seen = []
    for i in range(dp):
        for r in range(dp):
            s = e.src[i, r].reshape(-1)
            d = e.dst[i, r].reshape(-1)
            m = e.mask[i, r].reshape(-1) > 0
            j = (i + r) % dp
            seen.append(
                np.stack([s[m] + i * shard_rows, d[m] + j * shard_rows], axis=1)
            )
    seen = np.concatenate(seen, axis=0)
    ref = np.stack([g.src, g.dst], axis=1)
    order = np.lexsort((seen[:, 1], seen[:, 0]))
    np.testing.assert_array_equal(seen[order], ref)


def _random_graph(seed, n=71, p=0.12):
    from bigclam_tpu.graph.ingest import graph_from_edges

    rng = np.random.default_rng(seed)
    a = rng.random((n, n)) < p
    edges = [(i, j) for i in range(n) for j in range(i + 1, n) if a[i, j]]
    edges.append((0, n - 1))
    return graph_from_edges(edges, num_nodes=n)


class TestRingCSR:
    """Ring schedule on the blocked-CSR MXU kernels: per-(shard, phase)
    tile buckets, kernel outputs accumulated across rotations. Must match
    the all-gather trainer and the XLA ring (round-1 deferral, VERDICT
    item 2)."""

    @pytest.mark.parametrize("dp", [2, 4])
    def test_ring_csr_matches_allgather(self, dp):
        import jax
        from bigclam_tpu.parallel import ShardedBigClamModel

        g = _random_graph(0)
        k = 6
        base = BigClamConfig(num_communities=k, edge_chunk=64)
        mesh = make_mesh((dp, 1), jax.devices()[:dp])
        ring = RingBigClamModel(
            g,
            base.replace(
                use_pallas_csr=True, pallas_interpret=True,
                csr_block_b=8, csr_tile_t=8,
            ),
            mesh,
        )
        assert ring.engaged_path == "csr_ring_fused"
        assert ring.edges is None           # CSR step built, no EdgeChunks
        xla = ShardedBigClamModel(
            g, base.replace(use_pallas_csr=False), mesh
        )
        rng = np.random.default_rng(1)
        F0 = rng.uniform(0.0, 1.0, size=(g.num_nodes, k))
        s_r, s_x = ring.init_state(F0), xla.init_state(F0)
        for _ in range(3):
            s_r, s_x = ring._step(s_r), xla._step(s_x)
        n = g.num_nodes
        np.testing.assert_allclose(
            np.asarray(s_r.F)[:n, :k], np.asarray(s_x.F)[:n, :k],
            rtol=3e-5, atol=3e-5,
        )
        np.testing.assert_allclose(float(s_r.llh), float(s_x.llh), rtol=1e-5)

    @pytest.mark.parametrize("mesh_shape", [(2, 2), (4, 2)])
    def test_ring_csr_tp_matches_xla_ring(self, mesh_shape):
        """Ring schedule x SHARDED K axis x CSR kernels — the last cell of
        the schedule x kernel matrix (VERDICT round-3 item 2): per ring
        phase, partial-dot kernels + psum over "k" + consume kernels."""
        import jax

        dp, tp = mesh_shape
        g = _random_graph(0)
        k = 6
        base = BigClamConfig(num_communities=k, edge_chunk=64)
        mesh = make_mesh(mesh_shape, jax.devices()[: dp * tp])
        ring_csr = RingBigClamModel(
            g,
            base.replace(
                use_pallas_csr=True, pallas_interpret=True,
                csr_block_b=8, csr_tile_t=8,
            ),
            mesh,
        )
        assert ring_csr.engaged_path == "csr_ring_fused"
        ring_xla = RingBigClamModel(
            g, base.replace(use_pallas_csr=False), mesh
        )
        rng = np.random.default_rng(1)
        F0 = rng.uniform(0.0, 1.0, size=(g.num_nodes, k))
        s_r, s_x = ring_csr.init_state(F0), ring_xla.init_state(F0)
        for _ in range(3):
            s_r, s_x = ring_csr._step(s_r), ring_xla._step(s_x)
        n = g.num_nodes
        np.testing.assert_allclose(
            np.asarray(s_r.F)[:n, :k], np.asarray(s_x.F)[:n, :k],
            rtol=3e-5, atol=3e-5,
        )
        np.testing.assert_allclose(float(s_r.llh), float(s_x.llh), rtol=1e-5)

    @pytest.mark.parametrize(
        "mesh_shape,kb", [((4, 1), 0), ((2, 2), 0), ((2, 2), 3)]
    )
    def test_ring_csr_overlap_matches_serial(self, mesh_shape, kb):
        """Overlap parity on the kernel-path rotation sites (interpret
        mode): csr_ring, the TP split, and the K-blocked phases must all
        compute identical results under both rotation schedules."""
        import jax

        dp, tp = mesh_shape
        g = _random_graph(0)
        k = 12 if kb else 6
        base = BigClamConfig(
            num_communities=k, edge_chunk=64, use_pallas_csr=True,
            pallas_interpret=True, csr_block_b=8, csr_tile_t=8,
            csr_k_block=kb,
        )
        mesh = make_mesh(mesh_shape, jax.devices()[: dp * tp])
        m_ov = RingBigClamModel(g, base, mesh)
        m_se = RingBigClamModel(
            g, base.replace(ring_overlap=False), mesh
        )
        assert m_ov.engaged_path == (
            "csr_ring_fused_kb" if kb else "csr_ring_fused"
        )
        rng = np.random.default_rng(1)
        F0 = rng.uniform(0.0, 1.0, size=(g.num_nodes, k))
        s_o, s_s = m_ov.init_state(F0), m_se.init_state(F0)
        for _ in range(3):
            s_o, s_s = m_ov._step(s_o), m_se._step(s_s)
        assert float(s_o.llh) == float(s_s.llh)
        np.testing.assert_array_equal(
            np.asarray(s_o.F), np.asarray(s_s.F)
        )

    def test_ring_tile_bucket_partition(self):
        """Every directed edge lands in exactly one (shard, phase) tile
        bucket with correctly rebased src/dst local indices."""
        from bigclam_tpu.ops.csr_tiles import ring_block_tiles

        g = _random_graph(2, n=41)
        dp, block_b, tile_t = 4, 4, 4
        n_pad = 48
        rbt = ring_block_tiles(g, dp, n_pad, block_b, tile_t)
        shard_rows = n_pad // dp
        seen = []
        for i in range(dp):
            for r in range(dp):
                m = rbt.mask[i, r].astype(bool)
                src_g = (
                    rbt.src_local[i, r]
                    + rbt.block_id[i, r][:, None] * block_b
                    + i * shard_rows
                )
                dst_g = rbt.dst_local[i, r] + ((i + r) % dp) * shard_rows
                seen.append(
                    np.stack([src_g[m], dst_g[m]], axis=1)
                )
        seen = np.concatenate(seen, axis=0)
        ref = np.stack([g.src, g.dst], axis=1)
        order = np.lexsort((seen[:, 1], seen[:, 0]))
        np.testing.assert_array_equal(seen[order], ref)

    def test_ring_csr_fit_matches_xla_ring(self):
        import jax

        g = _random_graph(3)
        k = 4
        cfg = BigClamConfig(num_communities=k, max_iters=6, edge_chunk=64)
        mesh = make_mesh((4, 1), jax.devices()[:4])
        rng = np.random.default_rng(4)
        F0 = rng.uniform(0.1, 1.0, size=(g.num_nodes, k))
        res_csr = RingBigClamModel(
            g,
            cfg.replace(
                use_pallas_csr=True, pallas_interpret=True,
                csr_block_b=8, csr_tile_t=8,
            ),
            mesh,
        ).fit(F0)
        res_xla = RingBigClamModel(
            g, cfg.replace(use_pallas_csr=False), mesh
        ).fit(F0)
        assert res_csr.num_iters == res_xla.num_iters
        np.testing.assert_allclose(res_csr.llh, res_xla.llh, rtol=1e-5)
        np.testing.assert_allclose(res_csr.F, res_xla.F, rtol=2e-4, atol=2e-4)


def test_ring_fit_converges(toy_graphs):
    import jax

    g = toy_graphs["two_cliques"]
    cfg = BigClamConfig(num_communities=2, dtype="float64", max_iters=50)
    rng = np.random.default_rng(3)
    F0 = rng.uniform(0.1, 1.0, size=(g.num_nodes, 2))
    mesh = make_mesh((4, 2), jax.devices())
    # balance=False: bitwise-level trajectory compare on the fixed layout
    res_r = RingBigClamModel(g, cfg, mesh, balance=False).fit(F0)
    res_1 = BigClamModel(g, cfg).fit(F0)
    assert res_r.num_iters == res_1.num_iters
    np.testing.assert_allclose(res_r.F, res_1.F, rtol=1e-10)


def test_ring_auto_balance_engages_on_imbalance(toy_graphs):
    """Contiguous planted blocks make ~every edge shard-local — the
    ring's bucket-padding worst case (measured dp x padded work,
    RINGMEM_r05.json). The DEFAULT build (balance=None) must auto-engage
    the balance relabeling on the warning heuristic and stay silent
    (VERDICT r5 Next #6); balance=False is the escape hatch that keeps
    the raw layout and the warning; balance=True forces the relabeling;
    and on an id-shuffled (already balanced) graph the auto rule must
    NOT engage."""
    import warnings

    import jax

    from bigclam_tpu.models.agm import sample_planted_graph
    from bigclam_tpu.parallel import RingBigClamModel, make_mesh

    g, _ = sample_planted_graph(
        1024, 16, p_in=0.5, rng=np.random.default_rng(2)
    )
    cfg = BigClamConfig(
        num_communities=4, use_pallas=False, use_pallas_csr=False
    )
    mesh = make_mesh((4, 1), jax.devices()[:4])
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        auto = RingBigClamModel(g, cfg, mesh)
    assert auto._perm is not None          # relabeling engaged by default
    assert not any("imbalanced" in str(w.message) for w in rec), [
        str(w.message) for w in rec
    ]
    # escape hatch: the raw layout plus the warning (the measurement mode)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        raw = RingBigClamModel(g, cfg, mesh, balance=False)
    assert raw._perm is None
    assert any("imbalanced" in str(w.message) for w in rec), [
        str(w.message) for w in rec
    ]
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        forced = RingBigClamModel(g, cfg, mesh, balance=True)
    assert forced._perm is not None
    assert not any("imbalanced" in str(w.message) for w in rec), [
        str(w.message) for w in rec
    ]
    # an id-shuffled twin spreads edges over shard pairs: auto stays off
    shuffled = g.permute(np.random.default_rng(3).permutation(g.num_nodes))
    quiet = RingBigClamModel(shuffled, cfg, mesh)
    assert quiet._perm is None
