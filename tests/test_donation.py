"""End-to-end TrainState buffer donation (models.bigclam.attach_donating
+ run_fit_loop's ping-pong scratch): the donated step path must reproduce
the non-donated path's trajectory EXACTLY, and every step builder must
accept donation without buffer-reuse failures on CPU — where this jax
honors donation for real (donated inputs are deleted), so these tests
exercise the actual invalidation semantics, not a no-op."""

import numpy as np
import pytest

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.models import BigClamModel
from bigclam_tpu.models.bigclam import donation_scratch
from bigclam_tpu.parallel import (
    RingBigClamModel,
    ShardedBigClamModel,
    make_mesh,
)

CFG = BigClamConfig(num_communities=4, dtype="float64", max_iters=6)


@pytest.fixture(scope="module")
def graph():
    from bigclam_tpu.models.agm import planted_partition_F, sample_graph

    rng = np.random.default_rng(11)
    Fp, _ = planted_partition_F(48, 4, strength=1.5)
    return sample_graph(Fp, rng=rng)


def _rand_F(g, seed=0):
    rng = np.random.default_rng(seed)
    return rng.uniform(0.1, 1.0, size=(g.num_nodes, 4))


def _assert_fits_equal(r_don, r_off):
    assert r_don.num_iters == r_off.num_iters
    assert r_don.llh == r_off.llh
    assert r_don.llh_history == r_off.llh_history
    np.testing.assert_array_equal(r_don.F, r_off.F)


def _spy_donating(model):
    """Wrap the step's donating entry with a call counter (proves the fit
    loop actually drives donation rather than silently falling back)."""
    calls = {"n": 0}
    orig = model._step.donating

    def spy(scratch, state):
        calls["n"] += 1
        return orig(scratch, state)

    model._step.donating = spy
    return calls


def test_single_chip_donated_matches_non_donated(graph):
    F0 = _rand_F(graph)
    m_don = BigClamModel(graph, CFG)            # donate_state default True
    assert CFG.donate_state
    calls = _spy_donating(m_don)
    r_don = m_don.fit(F0)
    assert calls["n"] == r_don.num_iters + 1    # every step donated
    m_off = BigClamModel(graph, CFG.replace(donate_state=False))
    _assert_fits_equal(r_don, m_off.fit(F0))


@pytest.mark.parametrize(
    "cls,mesh_shape",
    [(ShardedBigClamModel, (4, 2)), (RingBigClamModel, (4, 1)),
     (RingBigClamModel, (2, 2))],
)
def test_sharded_donated_matches_non_donated(graph, cls, mesh_shape):
    import jax

    F0 = _rand_F(graph)
    mesh = make_mesh(
        mesh_shape, jax.devices()[: mesh_shape[0] * mesh_shape[1]]
    )
    m_don = cls(graph, CFG, mesh)
    calls = _spy_donating(m_don)
    r_don = m_don.fit(F0)
    assert calls["n"] == r_don.num_iters + 1
    m_off = cls(graph, CFG.replace(donate_state=False), mesh)
    _assert_fits_equal(r_don, m_off.fit(F0))


def test_csr_kernel_step_accepts_donation(graph):
    """The blocked-CSR builders (interpret mode on CPU) thread donation
    through make_train_step's kernel variants."""
    cfg = BigClamConfig(
        num_communities=4, max_iters=4, use_pallas_csr=True,
        pallas_interpret=True, csr_block_b=8, csr_tile_t=8, edge_chunk=64,
    )
    F0 = _rand_F(graph)
    m_don = BigClamModel(graph, cfg)
    assert m_don.engaged_path == "csr_fused"
    calls = _spy_donating(m_don)
    r_don = m_don.fit(F0)
    assert calls["n"] == r_don.num_iters + 1
    r_off = BigClamModel(graph, cfg.replace(donate_state=False)).fit(F0)
    _assert_fits_equal(r_don, r_off)


def test_donating_entry_semantics(graph):
    """The donating entry's contract: the OUTPUT equals the plain step's,
    the current INPUT survives (the convergence protocol returns it), and
    only the scratch is consumed."""
    import jax

    m = BigClamModel(graph, CFG)
    state = m.init_state(_rand_F(graph))
    ref = m._step(state)
    scratch = donation_scratch(state)
    snap = np.asarray(state.F).copy()
    out = m._step.donating(scratch, state)
    # input state survives: its buffers were NOT donated
    np.testing.assert_array_equal(np.asarray(state.F), snap)
    np.testing.assert_array_equal(np.asarray(out.F), np.asarray(ref.F))
    assert float(out.llh) == float(ref.llh)
    # the scratch was donated: on backends honoring donation (CPU included
    # on this jax) its buffers are deleted; it must never be read again
    if jax.default_backend() == "cpu":
        with pytest.raises(RuntimeError, match="deleted"):
            np.asarray(scratch.F)


def test_caller_state_never_donated(graph):
    """fit_state must not donate the caller-provided initial state — the
    caller may still hold it (quality annealing does across cycles)."""
    m = BigClamModel(graph, CFG)
    state = m.init_state(_rand_F(graph))
    F0_snapshot = np.asarray(state.F).copy()
    final, llh, iters, hist = m.fit_state(state)
    # both the initial state and the returned final state are readable
    np.testing.assert_array_equal(np.asarray(state.F), F0_snapshot)
    assert np.isfinite(np.asarray(final.F)).all()
    assert len(hist) == iters + 1
