"""Dataset-scale trajectory tests (SURVEY.md §4.2; VERDICT item 6), slow-
marked: spec-interpreter-vs-device matching on the reference's SHIPPED
datasets, catching chunking/padding bugs that toy graphs cannot.

Run with `pytest -m slow`; the default suite excludes them (pytest.ini).
"""

import numpy as np
import pytest

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.graph.ingest import build_graph
from bigclam_tpu.models import BigClamModel
from bigclam_tpu.spec import interpreter as spec

REFERENCE_DATA = "/root/reference/data"


@pytest.mark.slow
def test_facebook_k25_device_matches_spec_float64(facebook_graph):
    """facebook_combined (4,039 N / 88,234 E), K=25, float64: the device
    step must match the NumPy spec interpreter's F AND LLH trajectory to
    1e-10 over 5 iterations (BASELINE config 1 scale)."""
    g = facebook_graph
    k = 25
    cfg = BigClamConfig(
        num_communities=k, dtype="float64", max_iters=5, conv_tol=0.0
    )
    rng = np.random.default_rng(0)
    F0 = rng.integers(0, 2, size=(g.num_nodes, k)).astype(np.float64)

    model = BigClamModel(g, cfg)
    state = model.init_state(F0)

    F_s = F0.copy()
    sumF_s = F_s.sum(axis=0)
    for it in range(5):
        state = model._step(state)
        F_s, sumF_s, post_llh = spec.line_search_step(F_s, sumF_s, g, cfg)
        # device llh is the LLH of the step's INPUT F; compare post-update F
        np.testing.assert_allclose(
            np.asarray(state.F)[: g.num_nodes, :k], F_s,
            rtol=1e-10, atol=1e-10, err_msg=f"iter {it}",
        )
    # one more device step reports the LLH of the final F
    final_llh = float(model._step(state).llh)
    np.testing.assert_allclose(final_llh, post_llh, rtol=1e-10)


@pytest.mark.slow
def test_enron_k100_float32_llh_trajectory():
    """Email-Enron (36,692 N / 367,662 directed E), K=100: the float32
    device trajectory's LLH must track the float64 spec interpreter within
    float32 tolerance over 5 iterations (BASELINE config 2 scale — the
    benchmark configuration itself)."""
    g = build_graph(f"{REFERENCE_DATA}/Email-Enron.txt")
    k = 100
    cfg = BigClamConfig(num_communities=k, max_iters=5, conv_tol=0.0)
    rng = np.random.default_rng(0)
    F0 = rng.integers(0, 2, size=(g.num_nodes, k)).astype(np.float64)

    model = BigClamModel(g, cfg, k_multiple=128)
    assert str(np.dtype(model.dtype)) == "float32"

    F_s = F0.copy()
    sumF_s = F_s.sum(axis=0)
    llh_spec = []
    cfg64 = cfg.replace(dtype="float64")
    for _ in range(5):
        F_s, sumF_s, post_llh = spec.line_search_step(F_s, sumF_s, g, cfg64)
        llh_spec.append(post_llh)

    # the device step's llh is the LLH of its INPUT F, so steps 2..6 report
    # the post-update LLHs of steps 1..5 — aligned with the spec sequence
    llh_dev = []
    state = model.init_state(F0)
    for i in range(6):
        state = model._step(state)
        if i >= 1:
            llh_dev.append(float(state.llh))
    np.testing.assert_allclose(llh_dev, llh_spec, rtol=5e-4)
    # monotone ascent on the real dataset
    assert all(b >= a for a, b in zip(llh_dev, llh_dev[1:]))
