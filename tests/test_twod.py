"""2D edge-block partition tests (ISSUE 16) on the 8-device CPU fake.

The degeneration contract is BIT-identity: at replica_cols=1 the 2D
closure-gather schedule must reproduce the 1D all-gather trainer's
trajectory exactly — the closure table changes which rows ride the wire,
never what the step computes. The (R, C>1) grids trade the full-F gather
for partial-group collectives and must stay inside the 1D LLH band.
"""

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.graph.store import compile_graph_cache
from bigclam_tpu.models.agm import sample_planted_graph
from bigclam_tpu.parallel import (
    ShardedBigClamModel,
    StoreTwoDShardedBigClamModel,
    TwoDShardedBigClamModel,
    make_mesh,
    make_mesh_2d,
    twod_mesh_shape,
)
from bigclam_tpu.parallel.mesh import COLS_AXIS, K_AXIS, ROWS_AXIS

K = 8


def _cfg(**kw):
    d = dict(num_communities=K, max_iters=6, conv_tol=0.0,
             health_every=2, seed=0)
    d.update(kw)
    return BigClamConfig(**d)


@pytest.fixture(scope="module")
def planted():
    rng = np.random.default_rng(0)
    g, _ = sample_planted_graph(240, 4, p_in=0.3, rng=rng)
    F0 = np.abs(rng.standard_normal((g.num_nodes, K))).astype(np.float32)
    return g, F0


@pytest.fixture(scope="module")
def fit_1d(planted):
    g, F0 = planted
    m = ShardedBigClamModel(g, _cfg(), make_mesh((4, 1), jax.devices()[:4]))
    return m.fit(F0.copy())


@pytest.fixture(scope="module")
def cache_v3(planted, tmp_path_factory):
    g, _ = planted
    tmp = tmp_path_factory.mktemp("twod_cache")
    txt = str(tmp / "g.txt")
    src, dst = np.asarray(g.src), np.asarray(g.dst)
    with open(txt, "w") as f:
        for s, d in zip(src.tolist(), dst.tolist()):
            if s < d:
                f.write(f"{s}\t{d}\n")
    return txt, compile_graph_cache(txt, str(tmp / "cache"), num_shards=4)


# ----------------------------------------------------------- mesh factoring
def test_mesh_shape_from_cfg():
    assert twod_mesh_shape(_cfg(partition="2d", replica_cols=2), 8) == (4, 2)
    assert twod_mesh_shape(_cfg(partition="2d"), 4) == (4, 1)
    with pytest.raises(ValueError, match="does not divide"):
        twod_mesh_shape(_cfg(partition="2d", replica_cols=3), 8)


# ----------------------------------------------------- trajectory contracts
def test_c1_bit_identical_to_1d(planted, fit_1d):
    g, F0 = planted
    m = TwoDShardedBigClamModel(
        g, _cfg(partition="2d", replica_cols=1),
        make_mesh_2d((4, 1), jax.devices()[:4]),
    )
    assert m.engaged_path == "xla_2d"
    r = m.fit(F0.copy())
    assert r.llh == fit_1d.llh
    assert np.array_equal(np.asarray(r.F), np.asarray(fit_1d.F))


def test_2x2_within_llh_band(planted, fit_1d):
    g, F0 = planted
    m = TwoDShardedBigClamModel(
        g, _cfg(partition="2d", replica_cols=2),
        make_mesh_2d((2, 2), jax.devices()[:4]),
    )
    r = m.fit(F0.copy())
    assert r.num_iters == fit_1d.num_iters
    assert r.llh == pytest.approx(fit_1d.llh, rel=5e-3)


def test_comms_model_prices_capped_closure(planted):
    g, _ = planted
    m = TwoDShardedBigClamModel(
        g, _cfg(partition="2d", replica_cols=1),
        make_mesh_2d((4, 1), jax.devices()[:4]),
    )
    assert m.comms.family == "twod"
    sites = m.comms.site_bytes()
    assert "twod/alltoall_closure" in sites
    # C=1: the col-group gather and the partial-group reductions are
    # free — only the closure exchange and the mesh-wide scalars pay
    assert sites["twod/allgather_srcF"] == 0.0
    assert sites["twod/psum_scatter_cand"] == 0.0
    assert m._pad_stats["closure_cap"] <= m.n_pad // m.p


# -------------------------------------------------------------- store-native
def test_store_native_matches_in_memory(planted, cache_v3):
    g, F0 = planted
    _, store = cache_v3
    assert store.manifest["closure"]["baked"]
    for shape, cols in (((4, 1), 1), ((2, 2), 2)):
        cfg = _cfg(partition="2d", replica_cols=cols)
        mesh = make_mesh_2d(shape, jax.devices()[:4])
        r_mem = TwoDShardedBigClamModel(g, cfg, mesh).fit(F0.copy())
        r_st = StoreTwoDShardedBigClamModel(store, cfg, mesh).fit(F0.copy())
        assert r_st.llh == r_mem.llh, shape
        assert np.array_equal(np.asarray(r_st.F), np.asarray(r_mem.F))


def test_v2_cache_streams_closure_fallback(planted, cache_v3,
                                           tmp_path):
    """A cache compiled without the closure bake (the v2 layout) still
    trains — the gather lists stream from the host's own CSR, the path
    reason says so, and the trajectory is unchanged."""
    g, F0 = planted
    txt, _ = cache_v3
    store2 = compile_graph_cache(txt, str(tmp_path / "c2"),
                                 num_shards=4, closure_bake=False)
    assert not store2.manifest["closure"]["baked"]
    cfg = _cfg(partition="2d", replica_cols=2)
    mesh = make_mesh_2d((2, 2), jax.devices()[:4])
    m = StoreTwoDShardedBigClamModel(store2, cfg, mesh)
    assert "streamed from the cached CSR" in m.path_reason
    r = m.fit(F0.copy())
    r_mem = TwoDShardedBigClamModel(g, cfg, mesh).fit(F0.copy())
    assert np.array_equal(np.asarray(r.F), np.asarray(r_mem.F))


# ------------------------------------------------------------------ refusals
def test_build_refusals(planted):
    g, _ = planted
    devs = jax.devices()
    cfg2 = _cfg(partition="2d", replica_cols=1)
    with pytest.raises(ValueError, match="rows, cols"):
        TwoDShardedBigClamModel(g, cfg2, make_mesh((4, 1), devs[:4]))
    with pytest.raises(ValueError, match="partition-baked"):
        TwoDShardedBigClamModel(
            g, _cfg(), make_mesh_2d((4, 1), devs[:4])
        )
    with pytest.raises(ValueError, match="replica_cols"):
        TwoDShardedBigClamModel(
            g, _cfg(partition="2d", replica_cols=2),
            make_mesh_2d((4, 1), devs[:4]),
        )
    # ISSUE 17: the fused superstep now ENGAGES on 2d — use_pallas_csr
    # no longer refuses on partition; on this toy graph with the default
    # (TPU-sized) tile shape the refusal is the economy gate's
    with pytest.raises(ValueError, match="uneconomical"):
        TwoDShardedBigClamModel(
            g, _cfg(partition="2d", replica_cols=1, use_pallas_csr=True),
            make_mesh_2d((4, 1), devs[:4]),
        )
    # the split/grouped kernel suites stay 1d-only — an explicit
    # csr_fused=False override refuses with the pointer to 1d
    with pytest.raises(ValueError, match="partition 1d"):
        TwoDShardedBigClamModel(
            g, _cfg(partition="2d", replica_cols=1, use_pallas_csr=True,
                    csr_fused=False),
            make_mesh_2d((4, 1), devs[:4]),
        )
    with pytest.raises(ValueError, match="'k' axis must be 1"):
        TwoDShardedBigClamModel(
            g, cfg2,
            Mesh(np.asarray(devs[:4]).reshape(2, 1, 2),
                 (ROWS_AXIS, COLS_AXIS, K_AXIS)),
        )


def test_store_shard_grid_mismatch_refused(planted, cache_v3, tmp_path):
    txt, _ = cache_v3
    store2 = compile_graph_cache(txt, str(tmp_path / "c2s"), num_shards=2)
    with pytest.raises(ValueError, match="--shards 4"):
        StoreTwoDShardedBigClamModel(
            store2, _cfg(partition="2d", replica_cols=2),
            make_mesh_2d((2, 2), jax.devices()[:4]),
        )


def test_cli_refuses_2d_without_mesh(planted, tmp_path):
    g, _ = planted
    txt = str(tmp_path / "g.txt")
    src, dst = np.asarray(g.src), np.asarray(g.dst)
    with open(txt, "w") as f:
        for s, d in zip(src.tolist(), dst.tolist()):
            if s < d:
                f.write(f"{s}\t{d}\n")
    from bigclam_tpu.cli import main as cli_main

    with pytest.raises(SystemExit, match="needs --mesh"):
        cli_main(["fit", "--graph", txt, "--k", str(K),
                  "--partition", "2d", "--max-iters", "1"])
    with pytest.raises(SystemExit, match="closure-gather"):
        cli_main(["fit", "--graph", txt, "--k", str(K),
                  "--partition", "2d", "--mesh", "4,1",
                  "--schedule", "ring", "--max-iters", "1"])
