"""Graph store tests: streaming parse, out-of-core compile, manifest
validation, per-host shard loading, and the store-backed sharded trainer.

The round-trip contract is BIT-identity: text -> cache -> load_graph must
reproduce build_graph's indptr/indices/raw_ids exactly (the store changes
where the graph lives, never what it is)."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from bigclam_tpu.graph.ingest import build_graph, graph_from_edges
from bigclam_tpu.graph.store import (
    MANIFEST_NAME,
    MANIFEST_VERSION,
    GraphStore,
    compile_graph_cache,
    is_cache_dir,
)
from bigclam_tpu.graph.stream import (
    byte_ranges,
    load_edge_list_streaming,
    stream_edge_list,
)


def _write_edges(path, pairs, header=True):
    with open(path, "w") as f:
        if header:
            f.write("# synthetic\n# Nodes: ? Edges: ?\n\n")
        for u, v in np.asarray(pairs).tolist():
            f.write(f"{u} {v}\n")
    return str(path)


@pytest.fixture()
def messy_text(tmp_path):
    """Sparse raw ids, duplicate edges (both directions), self-loops."""
    rng = np.random.default_rng(7)
    pairs = rng.integers(0, 300, size=(2000, 2)) * 11 + 5
    pairs = np.concatenate([pairs, pairs[:50, ::-1], pairs[:20]])
    loops = np.stack([pairs[:15, 0], pairs[:15, 0]], axis=1)
    pairs = np.concatenate([pairs, loops])
    return _write_edges(tmp_path / "g.txt", pairs)


# --------------------------------------------------------------------------
# streaming parse
# --------------------------------------------------------------------------


def test_byte_ranges_partition_and_snap(messy_text):
    size = os.path.getsize(messy_text)
    with open(messy_text, "rb") as f:
        data = f.read()
    for chunk in (17, 256, 4096, size + 10):
        spans = byte_ranges(messy_text, chunk)
        assert spans[0][0] == 0 and spans[-1][1] == size
        for (_, e1), (s2, _) in zip(spans, spans[1:]):
            assert e1 == s2                       # exact partition
            assert data[s2 - 1 : s2] == b"\n"     # snapped to newline


def test_stream_parity_with_bulk_parse(messy_text):
    from bigclam_tpu.graph.ingest import load_edge_list

    ref = load_edge_list(messy_text)
    for chunk in (64, 1000, 1 << 30):
        got = load_edge_list_streaming(messy_text, chunk_bytes=chunk)
        np.testing.assert_array_equal(got, ref)


def test_stream_chunks_in_file_order(messy_text):
    parts = list(stream_edge_list(messy_text, chunk_bytes=256))
    assert len(parts) > 3
    np.testing.assert_array_equal(
        np.concatenate([p for p in parts if p.size]),
        load_edge_list_streaming(messy_text),
    )


@pytest.mark.slow
def test_stream_parity_with_workers(messy_text):
    """Spawn-pool parse matches serial (slow: pool startup dominates)."""
    ref = load_edge_list_streaming(messy_text, chunk_bytes=512)
    got = load_edge_list_streaming(messy_text, chunk_bytes=512, workers=2)
    np.testing.assert_array_equal(got, ref)


def test_parse_rejects_odd_tokens(tmp_path):
    p = tmp_path / "bad.txt"
    p.write_text("0 1\n2\n")
    with pytest.raises(ValueError, match="even number"):
        load_edge_list_streaming(str(p))


# --------------------------------------------------------------------------
# compile -> load round trip
# --------------------------------------------------------------------------


def _assert_graphs_identical(a, b):
    np.testing.assert_array_equal(a.indptr, b.indptr)
    np.testing.assert_array_equal(a.indices, b.indices)
    np.testing.assert_array_equal(a.raw_ids, b.raw_ids)


@pytest.mark.parametrize("num_shards,chunk", [(1, 1 << 20), (4, 300), (7, 64)])
def test_roundtrip_bit_identical(messy_text, tmp_path, num_shards, chunk):
    ref = build_graph(messy_text)
    store = compile_graph_cache(
        messy_text, str(tmp_path / "cache"), num_shards=num_shards,
        chunk_bytes=chunk,
    )
    g = store.load_graph()
    _assert_graphs_identical(g, ref)
    g.validate()
    assert store.num_nodes == ref.num_nodes
    assert store.num_directed_edges == ref.num_directed_edges
    # build_graph dispatches the cache dir transparently
    assert is_cache_dir(store.directory)
    _assert_graphs_identical(build_graph(store.directory), ref)


def test_roundtrip_toy_graphs(toy_graphs, tmp_path):
    for name, g in toy_graphs.items():
        pairs = np.stack([g.src, g.dst], axis=1)
        pairs = pairs[pairs[:, 0] < pairs[:, 1]]        # undirected listing
        text = _write_edges(tmp_path / f"{name}.txt", pairs, header=False)
        store = compile_graph_cache(
            text, str(tmp_path / f"{name}.cache"), num_shards=2,
            chunk_bytes=16,
        )
        _assert_graphs_identical(store.load_graph(), build_graph(text))


def test_roundtrip_agm_graph(tmp_path):
    from bigclam_tpu.models.agm import sample_planted_graph

    g, _ = sample_planted_graph(
        400, 8, p_in=0.2, rng=np.random.default_rng(3)
    )
    pairs = np.stack([g.src, g.dst], axis=1)
    pairs = pairs[pairs[:, 0] < pairs[:, 1]]
    text = _write_edges(tmp_path / "agm.txt", pairs, header=False)
    store = compile_graph_cache(
        text, str(tmp_path / "agm.cache"), num_shards=8, chunk_bytes=2048,
    )
    got = store.load_graph()
    ref = build_graph(text)
    _assert_graphs_identical(got, ref)
    # the AGM fixture's ids are already contiguous, so the cache reproduces
    # the original graph object too
    np.testing.assert_array_equal(got.indptr, g.indptr)
    np.testing.assert_array_equal(got.indices, g.indices)


def test_facebook_golden_roundtrip(facebook_graph, tmp_path):
    from tests.conftest import require_reference_data

    text = require_reference_data("facebook_combined.txt")
    store = compile_graph_cache(
        text, str(tmp_path / "fb.cache"), num_shards=8, chunk_bytes=1 << 20,
    )
    _assert_graphs_identical(store.load_graph(), facebook_graph)
    assert store.num_nodes == 4039
    assert store.num_directed_edges == 2 * 88234


def test_compile_refuses_overwrite(messy_text, tmp_path):
    cache = str(tmp_path / "cache")
    compile_graph_cache(messy_text, cache, num_shards=4)
    with pytest.raises(FileExistsError):
        compile_graph_cache(messy_text, cache, num_shards=4)
    # overwrite=True rebuilds cleanly, dropping the old manifest and blobs
    # first (a crash mid-rebuild must never leave the old manifest
    # validating over mixed files) — shrinking shards strands no strays
    store = compile_graph_cache(
        messy_text, cache, num_shards=2, overwrite=True
    )
    assert store.num_shards == 2
    assert not os.path.exists(os.path.join(cache, "shard_00003.indices.npy"))
    _assert_graphs_identical(store.load_graph(), build_graph(messy_text))


def test_balanced_cache_matches_balance_graph(messy_text, tmp_path):
    """balance=True bakes exactly the permutation the sharded trainers
    would compute (parallel/balance.py) into the shard layout."""
    from bigclam_tpu.parallel.balance import balance_permutation

    S = 4
    ref = build_graph(messy_text)
    n_pad = -(-max(ref.num_nodes, S) // S) * S
    perm = balance_permutation(ref.degrees, S, n_pad)
    expected = ref.permute(perm)

    store = compile_graph_cache(
        messy_text, str(tmp_path / "bal.cache"), num_shards=S,
        chunk_bytes=500, balance=True,
    )
    assert store.balanced
    _assert_graphs_identical(store.load_graph(), expected)
    np.testing.assert_array_equal(store.load_perm(), perm)


# --------------------------------------------------------------------------
# manifest validation
# --------------------------------------------------------------------------


def test_stale_format_version_rejected(messy_text, tmp_path):
    cache = str(tmp_path / "cache")
    compile_graph_cache(messy_text, cache, num_shards=2)
    mpath = os.path.join(cache, MANIFEST_NAME)
    with open(mpath) as f:
        manifest = json.load(f)
    manifest["format_version"] = MANIFEST_VERSION + 1
    with open(mpath, "w") as f:
        json.dump(manifest, f)
    with pytest.raises(ValueError, match="format version"):
        GraphStore.open(cache)


def test_corrupted_checksum_rejected(messy_text, tmp_path):
    cache = str(tmp_path / "cache")
    store = compile_graph_cache(messy_text, cache, num_shards=4)
    _, indices_path = store.shard_files(1)
    with open(indices_path, "r+b") as f:
        f.seek(os.path.getsize(indices_path) - 3)
        b = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([b[0] ^ 0xFF]))
    store = GraphStore.open(cache)                 # manifest itself is fine
    with pytest.raises(ValueError, match="checksum"):
        store.load_graph()
    with pytest.raises(ValueError, match="checksum"):
        store.load_shard(0, 2)                     # shard 1 is host 0's
    # the corruption is localized: the other host's shards still load
    hs = store.load_shard(1, 2)
    assert hs.lo == 2 * store.rows_per_shard
    # verify=False is the explicit escape hatch
    store.load_graph(verify=False)


def test_missing_manifest_rejected(tmp_path):
    with pytest.raises(ValueError, match="not a graph cache"):
        GraphStore.open(str(tmp_path))
    assert not is_cache_dir(str(tmp_path))


# --------------------------------------------------------------------------
# per-host shard loading
# --------------------------------------------------------------------------


def test_load_shard_two_host_fake(messy_text, tmp_path):
    """2-host fake: each host gets its contiguous node range, concatenation
    reassembles the full graph bit-identically, and a host's load touches
    ONLY its own shard files (proved by deleting the other host's)."""
    ref = build_graph(messy_text)
    store = compile_graph_cache(
        messy_text, str(tmp_path / "cache"), num_shards=4, chunk_bytes=400,
    )
    rows = store.rows_per_shard
    s0 = store.load_shard(0, 2)
    s1 = store.load_shard(1, 2)
    assert (s0.lo, s0.hi) == (0, min(2 * rows, ref.num_nodes))
    assert (s1.lo, s1.hi) == (min(2 * rows, ref.num_nodes), ref.num_nodes)
    assert s0.shard_ids == (0, 1) and s1.shard_ids == (2, 3)

    # reassembly == build_graph, bit for bit
    indptr = np.concatenate([s0.indptr, s1.indptr[1:] + s0.indptr[-1]])
    np.testing.assert_array_equal(indptr, ref.indptr)
    np.testing.assert_array_equal(
        np.concatenate([s0.indices, s1.indices]), ref.indices
    )
    # local indptr agrees with the global CSR over the host's range
    np.testing.assert_array_equal(
        np.diff(s0.indptr), ref.degrees[s0.lo : s0.hi]
    )

    # files_read is exactly the host's own blobs
    own0 = {os.path.basename(p) for s in (0, 1) for p in store.shard_files(s)}
    assert set(s0.files_read) == own0

    # hard isolation: delete host 1's blobs, host 0 still loads
    for s in (2, 3):
        for p in store.shard_files(s):
            os.unlink(p)
    s0_again = store.load_shard(0, 2)
    np.testing.assert_array_equal(s0_again.indices, s0.indices)
    with pytest.raises(FileNotFoundError):
        store.load_shard(1, 2)


def test_load_shard_bad_host_counts(messy_text, tmp_path):
    store = compile_graph_cache(
        messy_text, str(tmp_path / "cache"), num_shards=4
    )
    with pytest.raises(ValueError, match="divisible"):
        store.load_shard(0, 3)
    with pytest.raises(ValueError, match="outside"):
        store.load_shard(4, 4)


def test_host_shard_ids_process_mapping():
    from bigclam_tpu.parallel.multihost import host_shard_ids

    assert list(host_shard_ids(8, 0, 2)) == [0, 1, 2, 3]
    assert list(host_shard_ids(8, 1, 2)) == [4, 5, 6, 7]
    with pytest.raises(ValueError, match="divisible"):
        host_shard_ids(8, 0, 3)


# --------------------------------------------------------------------------
# store-backed sharded trainer
# --------------------------------------------------------------------------


def _two_clique_problem(tmp_path):
    edges = []
    for base in (0, 12):
        for i in range(12):
            for j in range(i + 1, 12):
                edges.append((base + i, base + j))
    edges.append((11, 12))
    g = graph_from_edges(edges, num_nodes=24)
    text = _write_edges(tmp_path / "mh.txt", edges, header=False)
    return g, text


def test_store_sharded_model_matches_sharded(tmp_path):
    """Single-process equality: the store-backed trainer (per-host shard
    loading + put_host_local edge placement) reproduces ShardedBigClamModel
    EXACTLY (float64, atol=0) — the sharding changes where the edges come
    from, not the math."""
    import jax

    from bigclam_tpu.config import BigClamConfig
    from bigclam_tpu.parallel import (
        ShardedBigClamModel,
        StoreShardedBigClamModel,
        make_mesh,
    )

    g, text = _two_clique_problem(tmp_path)
    store = compile_graph_cache(
        text, str(tmp_path / "cache"), num_shards=4, chunk_bytes=64,
    )
    cfg = BigClamConfig(
        num_communities=2, dtype="float64", max_iters=8, conv_tol=0.0,
        use_pallas_csr=False,
    )
    F0 = np.random.default_rng(5).uniform(0.1, 1.0, size=(24, 2))
    mesh = make_mesh((4, 1), jax.devices()[:4])
    ref = ShardedBigClamModel(g, cfg, mesh).fit(F0)
    model = StoreShardedBigClamModel(store, cfg, mesh)
    assert model.engaged_path == "xla"
    got = model.fit(F0)
    np.testing.assert_allclose(got.F, ref.F, rtol=0, atol=0)
    assert got.llh_history == ref.llh_history
    # the trainer loaded all 4 shards (single process owns the whole mesh)
    assert model.host_shard.shard_ids == (0, 1, 2, 3)


def test_store_sharded_model_refuses_mismatch(tmp_path):
    import jax

    from bigclam_tpu.config import BigClamConfig
    from bigclam_tpu.parallel import StoreShardedBigClamModel, make_mesh

    _, text = _two_clique_problem(tmp_path)
    store = compile_graph_cache(
        text, str(tmp_path / "cache"), num_shards=2, chunk_bytes=64,
    )
    cfg = BigClamConfig(num_communities=2, dtype="float64", max_iters=2)
    mesh = make_mesh((4, 1), jax.devices()[:4])
    with pytest.raises(ValueError, match="--shards 4"):
        StoreShardedBigClamModel(store, cfg, mesh)
    # the ISSUE 9 lift: use_pallas_csr=True is no longer refused outright —
    # it goes through the SAME static policy as the in-memory sharded
    # trainer (float64 F still refuses, with the shared wording)
    with pytest.raises(ValueError, match="float32"):
        StoreShardedBigClamModel(
            store, cfg.replace(use_pallas_csr=True),
            make_mesh((2, 1), jax.devices()[:2]),
        )


def test_store_graph_view_refuses_global_csr(tmp_path):
    """Touching global CSR arrays on the store-backed trainer's graph view
    is a loud error, not a silent materialization."""
    import jax

    from bigclam_tpu.config import BigClamConfig
    from bigclam_tpu.parallel import StoreShardedBigClamModel, make_mesh

    _, text = _two_clique_problem(tmp_path)
    store = compile_graph_cache(
        text, str(tmp_path / "cache"), num_shards=4, chunk_bytes=64,
    )
    cfg = BigClamConfig(num_communities=2, dtype="float64", max_iters=2)
    model = StoreShardedBigClamModel(
        store, cfg, make_mesh((4, 1), jax.devices()[:4])
    )
    assert model.g.num_nodes == 24
    with pytest.raises(AttributeError, match="no global CSR"):
        model.g.src


# --------------------------------------------------------------------------
# CLI
# --------------------------------------------------------------------------


def _run_cli(*argv):
    return subprocess.run(
        [sys.executable, "-m", "bigclam_tpu.cli", *argv],
        capture_output=True, text=True, timeout=600, cwd="/root/repo",
    )


def test_cli_ingest_then_fit_from_cache(tmp_path):
    g, text = _two_clique_problem(tmp_path)
    cache = str(tmp_path / "cache")
    r = _run_cli(
        "ingest", "--graph", text, "--cache-dir", cache, "--shards", "2",
        "--chunk-bytes", "128",
    )
    assert r.returncode == 0, r.stderr
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["n"] == 24 and rec["shards"] == 2
    assert rec["edges"] == g.num_edges
    assert "edges_per_sec" in rec
    assert rec["rss"]["peak_sampled_bytes"] >= rec["rss"]["baseline_bytes"]
    assert set(rec["seconds"]) >= {"scan", "scatter", "dedup", "shards"}

    # re-ingest without --overwrite refuses
    r2 = _run_cli("ingest", "--graph", text, "--cache-dir", cache)
    assert r2.returncode == 1 and "already compiled" in r2.stderr

    # fit straight from the cache dir
    r3 = _run_cli(
        "fit", "--graph", cache, "--k", "2", "--dtype", "float64",
        "--max-iters", "10", "--init", "random", "--quiet",
        "--platform", "cpu",
    )
    assert r3.returncode == 0, r3.stderr
    rec3 = json.loads(r3.stdout.strip().splitlines()[-1])
    assert rec3["n"] == 24 and rec3["edges"] == g.num_edges


def test_cli_fit_autocompiles_cache_dir(tmp_path):
    g, text = _two_clique_problem(tmp_path)
    cache = str(tmp_path / "auto.cache")
    r = _run_cli(
        "fit", "--graph", text, "--cache-dir", cache, "--k", "2",
        "--dtype", "float64", "--max-iters", "5", "--init", "random",
        "--quiet", "--platform", "cpu",
    )
    assert r.returncode == 0, r.stderr
    assert "compiling graph cache" in r.stderr
    assert is_cache_dir(cache)
    # second run reloads from the cache (no compile note)
    r2 = _run_cli(
        "fit", "--graph", text, "--cache-dir", cache, "--k", "2",
        "--dtype", "float64", "--max-iters", "5", "--init", "random",
        "--quiet", "--platform", "cpu",
    )
    assert r2.returncode == 0, r2.stderr
    assert "compiling graph cache" not in r2.stderr


# ----------------------------------------------------------------------
# ingest-baked closure gather lists (ISSUE 16)
# ----------------------------------------------------------------------

def _expected_closure(store):
    """Recompute every shard's closure lists from the full CSR — the
    oracle the baked blobs must match."""
    from bigclam_tpu.graph.store import closure_pair_lists

    g = store.load_graph(mmap=False)
    ip, dx = np.asarray(g.indptr), np.asarray(g.indices)
    cap = int(store.manifest["closure"].get("cap", 0))
    out = {}
    for s in range(store.num_shards):
        lo, hi = store.node_range(s)
        out[s] = closure_pair_lists(
            lo, ip[lo:hi + 1] - ip[lo], dx[ip[lo]:ip[hi]],
            store.rows_per_shard, store.num_shards, cap=cap,
        )
    return out


def test_closure_bake_matches_recompute_and_symmetry(messy_text, tmp_path):
    store = compile_graph_cache(messy_text, str(tmp_path / "c"),
                                num_shards=4)
    assert store.manifest["format_version"] == MANIFEST_VERSION
    assert store.manifest["closure"]["baked"]
    lists = store.load_closure_lists()
    want = _expected_closure(store)
    for s in range(4):
        out_w, in_w, cnt_w = want[s]
        sc = lists.shards[s]
        assert list(sc.edge_counts) == cnt_w
        for b in range(4):
            np.testing.assert_array_equal(sc.out_ids[b], out_w[b])
            np.testing.assert_array_equal(sc.in_ids[b], in_w[b])
    # undirected symmetry: what s gathers FROM b (out) is exactly what
    # b's own blob says it sends TO s (in) — both sides of the 2D
    # exchange derive the same array from their OWN shard's blob
    for s in range(4):
        for b in range(4):
            np.testing.assert_array_equal(
                lists.shards[s].out_ids[b], lists.shards[b].in_ids[s]
            )


def test_closure_lists_files_read_isolation(messy_text, tmp_path):
    store = compile_graph_cache(messy_text, str(tmp_path / "c"),
                                num_shards=4)
    lists = store.load_closure_lists(1, 2)
    assert set(lists.shards) == {1}
    assert len(lists.files_read) == 1
    assert "shard_00001" in os.path.basename(lists.files_read[0])


def test_closure_cap_overflow_sentinel(messy_text, tmp_path):
    store = compile_graph_cache(messy_text, str(tmp_path / "cc"),
                                num_shards=4, closure_cap=2)
    lists = store.load_closure_lists()
    assert lists.cap == 2
    flat = [x for sc in lists.shards.values()
            for x in sc.out_ids + sc.in_ids]
    # a capped pair is the None sentinel (manifest count -1, list
    # omitted from the blob), never a silently truncated list
    assert any(x is None for x in flat)
    assert all(x is None or len(x) <= 2 for x in flat)


def test_v2_cache_refuses_closure_with_reingest_hint(messy_text,
                                                    tmp_path):
    store = compile_graph_cache(messy_text, str(tmp_path / "c2"),
                                num_shards=4, closure_bake=False)
    assert not store.manifest.get("closure", {}).get("baked")
    with pytest.raises(ValueError, match="re-ingest to bake closures"):
        store.load_closure_lists()


def test_quarantine_rebuild_keeps_closure_valid(messy_text, tmp_path):
    store = compile_graph_cache(messy_text, str(tmp_path / "cq"),
                                num_shards=4)
    before = store.load_closure_lists()
    _, dx_path = store.shard_files(2)
    size = os.path.getsize(dx_path)
    with open(dx_path, "r+b") as f:
        f.seek(size // 2)
        f.write(b"\xff" * 8)
    store.quarantine_and_rebuild(2, reason="test corruption")
    after = GraphStore.open(store.directory).load_closure_lists()
    for b in range(4):
        np.testing.assert_array_equal(
            after.shards[2].out_ids[b], before.shards[2].out_ids[b]
        )
        np.testing.assert_array_equal(
            after.shards[2].in_ids[b], before.shards[2].in_ids[b]
        )
