"""Checkpoint/resume, metrics, and CLI tests."""

import json
import subprocess
import sys

import numpy as np

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.models import BigClamModel
from bigclam_tpu.utils import CheckpointManager, MetricsLogger


def test_checkpoint_roundtrip(tmp_path):
    cm = CheckpointManager(str(tmp_path), keep=2)
    cm.save(5, {"F": np.ones((3, 2))}, meta={"llh_history": [-10.0]})
    cm.save(10, {"F": np.zeros((3, 2))}, meta={"llh_history": [-10.0, -5.0]})
    cm.save(15, {"F": np.full((3, 2), 7.0)}, meta={"llh_history": [-1.0]})
    assert cm.steps() == [10, 15]          # rotation keeps newest 2
    step, arrays, meta = cm.restore()
    assert step == 15
    np.testing.assert_array_equal(arrays["F"], np.full((3, 2), 7.0))
    assert meta["llh_history"] == [-1.0]
    step, arrays, _ = cm.restore(10)
    np.testing.assert_array_equal(arrays["F"], np.zeros((3, 2)))


def test_checkpoint_truncated_restore_falls_back(tmp_path, capsys):
    """Satellite: a preempted write can never leave restore() crashing on a
    truncated .npz — saves are fsync'd tmp+rename, and restore falls back
    past an unreadable newest checkpoint to the next older one."""
    import os

    import pytest

    cm = CheckpointManager(str(tmp_path), keep=3)
    cm.save(1, {"F": np.ones((4, 3))}, meta={"llh_history": [-5.0]})
    cm.save(2, {"F": np.full((4, 3), 2.0)}, meta={"llh_history": [-4.0]})
    path2 = cm._path(2)
    size = os.path.getsize(path2)
    with open(path2, "r+b") as f:        # simulate a lost writeback
        f.truncate(size // 2)

    step, arrays, meta = cm.restore()
    assert step == 1
    np.testing.assert_array_equal(arrays["F"], np.ones((4, 3)))
    assert meta["llh_history"] == [-5.0]
    assert "unreadable" in capsys.readouterr().err

    # an explicitly requested corrupt step propagates its error
    import zipfile
    import zlib

    with pytest.raises(
        (OSError, ValueError, EOFError, KeyError, zipfile.BadZipFile,
         zlib.error)
    ):
        cm.restore(2)

    # every checkpoint unreadable -> None (fresh start), not a crash
    with open(cm._path(1), "r+b") as f:
        f.truncate(4)
    assert cm.restore() is None


def test_fit_resume_matches_uninterrupted(toy_graphs, tmp_path):
    """Fit with mid-run checkpointing, then resume from the checkpoint: the
    final state must equal an uninterrupted run (SURVEY.md §5)."""
    g = toy_graphs["two_cliques"]
    cfg = BigClamConfig(
        num_communities=2, dtype="float64", max_iters=6, conv_tol=0.0,
        checkpoint_every=3,
    )
    rng = np.random.default_rng(5)
    F0 = rng.uniform(0.1, 1.0, size=(g.num_nodes, 2))

    full = BigClamModel(g, cfg).fit(F0)

    cm = CheckpointManager(str(tmp_path))
    partial_cfg = cfg.replace(max_iters=3)
    BigClamModel(g, partial_cfg).fit(F0, checkpoints=cm)   # stops at iter 3
    assert cm.latest_step() == 3
    resumed = BigClamModel(g, cfg).fit(
        np.zeros_like(F0), checkpoints=cm                  # F0 ignored on resume
    )
    np.testing.assert_allclose(resumed.F, full.F, rtol=1e-12)
    assert resumed.llh_history == full.llh_history


def test_metrics_logger(tmp_path):
    p = tmp_path / "m.jsonl"
    with MetricsLogger(str(p), echo=False) as ml:
        cb = ml.step_callback(num_directed_edges=1000)
        cb(0, -100.0)
        cb(1, -90.0)
    lines = [json.loads(x) for x in p.read_text().splitlines()]
    assert lines[0]["iter"] == 0 and lines[0]["llh"] == -100.0
    assert "rel_dllh" in lines[1] and "edges_per_sec_per_chip" in lines[1]


def test_metrics_logger_non_primary_writes_nothing(tmp_path, monkeypatch):
    """Single-writer gating: on a non-primary process the logger must not
    open the shared JSONL (gated lazily at first log, so constructing the
    logger before jax.distributed init stays safe)."""
    import bigclam_tpu.utils.metrics as um

    monkeypatch.setattr(
        "bigclam_tpu.utils.dist.is_primary", lambda: False
    )
    p = tmp_path / "m.jsonl"
    with MetricsLogger(str(p), echo=True) as ml:
        ml.log({"x": 1})
    assert not p.exists()
    # primary_only=False opts out (per-process logs at distinct paths)
    with MetricsLogger(str(p), echo=False, primary_only=False) as ml:
        ml.log({"x": 1})
    assert p.exists()


def test_metrics_accept_histogram(toy_graphs, tmp_path):
    """SURVEY §5 line-search observability: a real fit's metrics JSONL must
    carry the accepted-step histogram and acceptance rate each iteration,
    with accepted counts over real nodes only (padding rows can only land
    in the rejected slot)."""
    g = toy_graphs["two_cliques"]
    cfg = BigClamConfig(
        num_communities=2, dtype="float64", max_iters=5, conv_tol=0.0,
    )
    rng = np.random.default_rng(5)
    F0 = rng.uniform(0.1, 1.0, size=(g.num_nodes, 2))
    model = BigClamModel(g, cfg)
    p = tmp_path / "m.jsonl"
    with MetricsLogger(str(p), echo=False) as ml:
        cb = ml.step_callback(
            g.num_directed_edges, num_nodes=g.num_nodes,
        )
        model.fit(F0, callback=cb)
    lines = [json.loads(x) for x in p.read_text().splitlines()]
    num_s = len(cfg.step_candidates)
    for rec in lines:
        hist = rec["accept_hist"]
        assert len(hist) == num_s + 1
        accepted = sum(hist[:-1])
        assert 0 <= accepted <= g.num_nodes
        assert sum(hist) == model.n_pad     # every padded row counted once
        assert rec["accept_rate"] == round(accepted / g.num_nodes, 4)
    # a healthy early fit accepts steps for most nodes
    assert sum(lines[0]["accept_hist"][:-1]) > 0


def test_accept_stats_hand_mask():
    import jax.numpy as jnp

    from bigclam_tpu.ops.linesearch import accept_stats

    # 3 candidates (descending eta), 4 nodes: node0 accepts cand 0 and 2
    # (chosen = 0), node1 accepts cand 1, node2 rejects all, node3 accepts
    # cand 2 only
    ok = jnp.asarray(
        [
            [True, False, False, False],
            [False, True, False, False],
            [True, False, False, True],
        ]
    )
    np.testing.assert_array_equal(
        np.asarray(accept_stats(ok)), [1, 1, 1, 1]
    )


def _run_cli(*argv):
    return subprocess.run(
        [sys.executable, "-m", "bigclam_tpu.cli", *argv],
        capture_output=True, text=True, timeout=600,
        cwd="/root/repo",
    )


def test_cli_fit_and_eval(tmp_path):
    graph = tmp_path / "g.txt"
    edges = []
    for base in (0, 8):
        for i in range(8):
            for j in range(i + 1, 8):
                edges.append((base + i, base + j))
    edges.append((7, 8))
    graph.write_text("# toy\n" + "\n".join(f"{u} {v}" for u, v in edges))
    out = tmp_path / "pred.cmty"
    # random init: with K=2 the conductance seeds tie inside one clique
    # (faithful reference behavior) and the symmetric seeded solution merges
    # the communities — covered in test_seeding; here we smoke the CLI
    r = _run_cli(
        "fit", "--graph", str(graph), "--k", "2", "--dtype", "float64",
        "--max-iters", "60", "--init", "random", "--out", str(out),
        "--quiet", "--platform", "cpu",
    )
    assert r.returncode == 0, r.stderr
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["n"] == 16 and rec["k"] == 2 and out.exists()

    truth = tmp_path / "truth.cmty"
    truth.write_text("0\t1\t2\t3\t4\t5\t6\t7\n8\t9\t10\t11\t12\t13\t14\t15\n")
    r2 = _run_cli("eval", "--pred", str(out), "--truth", str(truth))
    assert r2.returncode == 0, r2.stderr
    scores = json.loads(r2.stdout.strip())
    assert scores["f1"] > 0.85, scores


def test_cli_quality_fit(tmp_path):
    """--quality end to end through the CLI (small planted graph): the JSON
    reports cycle info; quality knobs without --quality warn and are
    ignored. (LLH-quality itself is asserted in tests/test_quality.py.)"""
    import numpy as np

    from bigclam_tpu.models.agm import sample_planted_graph

    g, _ = sample_planted_graph(240, 4, p_in=0.3, rng=np.random.default_rng(0))
    graph = tmp_path / "g.txt"
    graph.write_text(
        "\n".join(f"{u} {v}" for u, v in zip(g.src.tolist(), g.dst.tolist())
                  if u < v)
    )
    r = _run_cli(
        "fit", "--graph", str(graph), "--k", "4", "--max-iters", "40",
        "--quality", "--restart-cycles", "4", "--quiet", "--platform", "cpu",
    )
    assert r.returncode == 0, r.stderr
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["quality_cycles"] >= 1
    assert len(rec["cycles_llh"]) == rec["quality_cycles"]

    # quality knobs without --quality warn and change nothing
    r2 = _run_cli(
        "fit", "--graph", str(graph), "--k", "4", "--max-iters", "5",
        "--restart-cycles", "4", "--quiet", "--platform", "cpu",
    )
    assert r2.returncode == 0, r2.stderr
    assert "no effect without" in r2.stderr
    assert "quality_cycles" not in json.loads(
        r2.stdout.strip().splitlines()[-1]
    )


def test_cli_sweep(tmp_path):
    graph = tmp_path / "g.txt"
    edges = []
    for base in (0, 6, 12):
        for i in range(6):
            for j in range(i + 1, 6):
                edges.append((base + i, base + j))
    edges += [(5, 6), (11, 12)]
    graph.write_text("\n".join(f"{u} {v}" for u, v in edges))
    r = _run_cli(
        "sweep", "--graph", str(graph), "--min-com", "2", "--max-com", "6",
        "--div-com", "3", "--dtype", "float64", "--max-iters", "20", "--quiet",
        "--platform", "cpu",
    )
    assert r.returncode == 0, r.stderr
    rec = json.loads(r.stdout.strip())
    assert rec["kset"][0] == 2 and rec["kset"][-1] == 6
    assert rec["chosen_k"] >= 2


def test_checkpoint_mismatch_refused(toy_graphs, tmp_path):
    """Resuming with a different graph/K must raise, not silently corrupt."""
    import pytest

    g = toy_graphs["two_cliques"]
    cfg = BigClamConfig(
        num_communities=2, dtype="float64", max_iters=2, conv_tol=0.0,
        checkpoint_every=1,
    )
    rng = np.random.default_rng(1)
    F0 = rng.uniform(0.1, 1.0, size=(g.num_nodes, 2))
    cm = CheckpointManager(str(tmp_path))
    BigClamModel(g, cfg).fit(F0, checkpoints=cm)
    assert cm.latest_step() is not None
    # different K -> refuse
    cfg3 = cfg.replace(num_communities=3)
    F03 = rng.uniform(0.1, 1.0, size=(g.num_nodes, 3))
    with pytest.raises(ValueError, match="checkpoint incompatible"):
        BigClamModel(g, cfg3).fit(F03, checkpoints=cm)
    # different graph -> refuse
    g2 = toy_graphs["star"]
    F05 = rng.uniform(0.1, 1.0, size=(g2.num_nodes, 2))
    with pytest.raises(ValueError, match="checkpoint incompatible"):
        BigClamModel(g2, cfg).fit(F05, checkpoints=cm)


def test_sweep_state_resume(tmp_path):
    """sweep_k journals per-K LLHs and skips them on restart."""
    import json as _json

    from bigclam_tpu.graph.ingest import graph_from_edges
    from bigclam_tpu.models.model_selection import sweep_k

    edges = []
    for base in (0, 6):
        for i in range(6):
            for j in range(i + 1, 6):
                edges.append((base + i, base + j))
    edges.append((5, 6))
    g = graph_from_edges(edges)
    cfg = BigClamConfig(
        num_communities=4, dtype="float64", max_iters=15,
        min_com=2, max_com=4, div_com=2, ksweep_tol=1e-3,
    )
    r1 = sweep_k(g, cfg, state_dir=str(tmp_path))
    journal = _json.loads((tmp_path / "sweep_state.json").read_text())
    assert set(int(k) for k in journal) == set(r1.llh_by_k)
    r2 = sweep_k(g, cfg, state_dir=str(tmp_path))   # all Ks from journal
    assert r2.chosen_k == r1.chosen_k
    assert r2.llh_by_k == r1.llh_by_k


def test_rerun_with_checkpoints_is_idempotent(toy_graphs, tmp_path):
    """checkpoint_every=1 with max_iters hit: the speculative final state is
    never persisted, so re-running the same fit returns the identical
    result instead of drifting an extra iteration per run."""
    g = toy_graphs["two_cliques"]
    cfg = BigClamConfig(
        num_communities=2, dtype="float64", max_iters=6, conv_tol=0.0,
        checkpoint_every=1,
    )
    rng = np.random.default_rng(9)
    F0 = rng.uniform(0.1, 1.0, size=(g.num_nodes, 2))
    cm = CheckpointManager(str(tmp_path))
    r1 = BigClamModel(g, cfg).fit(F0, checkpoints=cm)
    assert cm.latest_step() <= cfg.max_iters
    r2 = BigClamModel(g, cfg).fit(F0, checkpoints=cm)
    assert r2.num_iters == r1.num_iters
    np.testing.assert_array_equal(r2.F, r1.F)


def test_export_gexf(tmp_path, toy_graphs):
    import xml.etree.ElementTree as ET

    import numpy as np

    from bigclam_tpu.utils.viz import export_gexf

    g = toy_graphs["two_cliques"]
    F = np.zeros((g.num_nodes, 2))
    F[:4, 0] = 1.0
    F[4:, 1] = 2.0
    coms = {0: [0, 1, 2, 3], 1: [4, 5, 6, 7], 2: [3, 4]}
    path = str(tmp_path / "g.gexf")
    export_gexf(path, g, communities=coms, F=F)
    root = ET.parse(path).getroot()
    ns = {"g": "http://gexf.net/1.2"}
    nodes = root.findall(".//g:node", ns)
    edges = root.findall(".//g:edge", ns)
    assert len(nodes) == g.num_nodes
    assert len(edges) == g.num_directed_edges // 2
    # node 3: argmax F -> community 0; overlap count 2 (communities 0 and 2)
    n3 = [n for n in nodes if n.get("id") == "3"][0]
    vals = {a.get("for"): a.get("value") for a in n3.findall(".//g:attvalue", ns)}
    assert vals["0"] == "0" and vals["1"] == "2"


def test_cli_csr_and_cap_flags(tmp_path):
    from conftest import require_reference_data

    out = tmp_path / "c.txt"
    gexf = tmp_path / "g.gexf"
    r = _run_cli(
        "fit",
        "--graph", require_reference_data("facebook_combined.txt"),
        "--k", "8", "--max-iters", "3", "--platform", "cpu",
        "--csr-kernels", "off", "--seeding-degree-cap", "32",
        "--out", str(out), "--export-gexf", str(gexf), "--quiet",
    )
    assert r.returncode == 0, r.stderr[-800:]
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["communities"] >= 1 and out.exists() and gexf.exists()
