"""Weak-scaling harness smoke (slow suite): the dp=1/2/4/8 relative step
times must exist for both schedules and stay within a loose regression
bound on the CPU fake (SURVEY.md §5 / BASELINE scaling-efficiency
headline; scripts/weak_scaling.py is the journaling entry point)."""

import pytest


@pytest.mark.slow
def test_weak_scaling_harness(tmp_path):
    import os
    import sys

    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    from scripts.weak_scaling import run

    rec = run(per_shard=512, steps=2, out_path=str(tmp_path / "w.json"))
    assert set(rec["sec_per_step"]) == {"1", "2", "4", "8"}
    for dp in ("1", "2", "4", "8"):
        for sched in ("allgather", "ring"):
            assert rec["sec_per_step"][dp][sched] > 0
    # loose bound: per-shard work is constant, so even on the shared-core
    # fake an 8x shard count must not cost 30x per step (a collective-
    # schedule regression — e.g. a per-phase all-gather — would)
    for sched in ("allgather", "ring"):
        assert rec["rel_step_time"]["8"][sched] < 30.0
