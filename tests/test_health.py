"""Model-health diagnostics tests (ISSUE 8): device health-pack schema +
cadence, health-off bit-identity, anomaly detectors (pure + planted-run
integration), heartbeat health embedding, strict-JSON non-finite health
payloads, `cli report --json` / `cli watch`, perf-ledger convergence
fields, and the <2% health-on overhead pin at the default CLI cadence."""

import io
import json
import math
import os
import time

import numpy as np
import pytest

import jax

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.models import BigClamModel
from bigclam_tpu.models.agm import sample_planted_graph
from bigclam_tpu.obs import (
    RunTelemetry,
    install,
    uninstall,
    validate_event,
    validate_events_file,
)
from bigclam_tpu.obs.health import DEFAULTS, HealthMonitor, run_detectors
from bigclam_tpu.obs.telemetry import EVENTS_NAME
from bigclam_tpu.ops.diagnostics import HEALTH_FIELDS, HEALTH_INDEX, NA


def _graph():
    g, _ = sample_planted_graph(
        240, 4, p_in=0.3, rng=np.random.default_rng(0)
    )
    return g


def _F0(g, k=4):
    return np.random.default_rng(1).uniform(
        0.1, 1.0, size=(g.num_nodes, k)
    )


def _cfg(**kw):
    base = dict(
        num_communities=4, dtype="float64", max_iters=8, conv_tol=0.0
    )
    base.update(kw)
    return BigClamConfig(**base)


def _events(directory):
    with open(os.path.join(directory, EVENTS_NAME)) as f:
        return [json.loads(line) for line in f if line.strip()]


@pytest.fixture
def telem(tmp_path):
    tel = install(RunTelemetry(str(tmp_path / "telem"), entry="test"))
    try:
        yield tel
    finally:
        tel.finalize()
        uninstall(tel)


# ---------------------------------------------------------------- schema
def test_health_anomaly_sparse_comm_schema_kinds():
    base = {"v": 2, "run": "r", "pid": 0, "t": 0.1, "ts": 1.0,
            "elapsed_s": 0.1}
    assert validate_event(
        {**base, "kind": "health", "iter": 3, "grad_norm": 1.0}
    ) == []
    assert validate_event(
        {**base, "kind": "anomaly", "check": "divergence", "iter": 3}
    ) == []
    assert validate_event(
        {**base, "kind": "sparse_comm", "comm_cap": 8, "comm_mode": "sparse"}
    ) == []
    # required fields enforced
    assert any(
        "iter" in e for e in validate_event({**base, "kind": "health"})
    )
    assert any(
        "check" in e
        for e in validate_event({**base, "kind": "anomaly", "iter": 1})
    )
    assert any(
        "comm_mode" in e
        for e in validate_event(
            {**base, "kind": "sparse_comm", "comm_cap": 8}
        )
    )
    # strict-JSON stringified non-finite payloads must stay VALID: only
    # `iter` is numeric-required on health events
    assert validate_event(
        {**base, "kind": "health", "iter": 3, "grad_norm": "inf",
         "llh": "nan"}
    ) == []


def test_health_off_is_bit_identical_and_packless():
    g = _graph()
    F0 = _F0(g)
    m_off = BigClamModel(g, _cfg())
    m_on = BigClamModel(g, _cfg(health_every=2))
    r_off = m_off.fit(F0)
    r_on = m_on.fit(F0)
    assert np.array_equal(r_off.F, r_on.F)
    assert r_off.llh_history == r_on.llh_history
    # off path carries literally nothing
    s = m_off._step(m_off.init_state(F0))
    assert s.health is None
    s = m_on._step(m_on.init_state(F0))
    assert s.health is not None and s.health.shape == (len(HEALTH_FIELDS),)


def test_health_events_cadence_fields_and_report(telem):
    g = _graph()
    every = 3
    model = BigClamModel(g, _cfg(health_every=every, max_iters=9))
    model.fit(_F0(g))
    telem.finalize()
    events = _events(telem.directory)
    health = [e for e in events if e["kind"] == "health"]
    assert [e["iter"] for e in health] == [0, 3, 6, 9]
    n, errors = validate_events_file(
        os.path.join(telem.directory, EVENTS_NAME)
    )
    assert errors == [], errors
    first, later = health[0], health[-1]
    for key in ("grad_norm", "update_norm", "step_eff", "accept_frac",
                "active_comms", "top_share", "f_max", "dead_comms",
                "dead_frac", "llh"):
        assert key in first, key
    # NA sparse slots are dropped on the dense trainer
    for key in ("support_churn", "cap_occupancy", "dense_fallback"):
        assert key not in first
    # window derivatives + rolling churn exist from the second sample on
    for key in ("llh_delta", "llh_slope", "llh_rel_change", "churn"):
        assert key in later, key
    # telemetry tracked the snapshot for the heartbeat / ledger / report
    assert telem.last_health is not None
    rep = telem.report()
    assert rep["health"]["samples"] == len(health)
    assert rep["health"]["last"]["iter"] == 9
    from bigclam_tpu.obs.report import render

    text, errors = render(telem.directory)
    assert errors == 0, text
    assert "model health:" in text and "anomalies: none" in text


def test_sparse_health_support_churn_and_na_slots(telem):
    from bigclam_tpu.models import SparseBigClamModel

    g = _graph()
    cfg = _cfg(
        representation="sparse", sparse_m=2, health_every=1, max_iters=6
    )
    model = SparseBigClamModel(g, cfg)
    model.fit(_F0(g))
    health = [
        e for e in _events(telem.directory) if e["kind"] == "health"
    ]
    assert health
    assert all("support_churn" in e for e in health)
    # single chip: no collectives, the cap slots stay NA and are dropped
    assert all("cap_occupancy" not in e for e in health)
    assert all("dense_fallback" not in e for e in health)
    # M < K admission: the support actually churns at least once
    assert any(e["support_churn"] > 0 for e in health)


def test_health_on_compiles_once():
    # fresh states seed an NA pack (ops.diagnostics.init_health) so the
    # TrainState pytree structure never changes mid-fit: without it the
    # first step's None->array health transition retraces and every fit
    # pays a duplicate XLA compile of the train step
    g = _graph()
    m = BigClamModel(g, _cfg(health_every=5))
    st = m.init_state(_F0(g))
    assert st.health is not None and st.health.shape == (len(HEALTH_FIELDS),)
    for _ in range(7):
        st = m._step(st)
    assert m._step.jitted._cache_size() == 1


def test_sparse_latch_carries_off_cadence_churn():
    from bigclam_tpu.models import SparseBigClamModel

    g = _graph()
    # support updates on it % 3 == 0, health samples on it % 4 == 0: the
    # iter-4 sample can only show churn if the latch carried it from the
    # off-cadence support pass at iter 3 (no admission runs at iter 4)
    cfg = _cfg(
        representation="sparse", sparse_m=2, support_every=3,
        health_every=4, max_iters=12,
    )
    m = SparseBigClamModel(g, cfg)
    st = m.init_state(_F0(g))
    packs = {}
    for _ in range(10):
        st = m._step(st)
        vec = np.asarray(st.health)
        if vec[HEALTH_INDEX["iter"]] >= 0:
            packs[int(vec[HEALTH_INDEX["iter"]])] = float(
                vec[HEALTH_INDEX["support_churn"]]
            )
    assert 4 in packs and 8 in packs
    assert packs[4] > 0 and packs[8] > 0


def test_monitor_churn_divides_by_live_rows():
    class _Tel:
        def __init__(self):
            self.events = []

        def event(self, kind, **fields):
            self.events.append((kind, fields))

    tel = _Tel()
    n_live, n_pad = 240, 512
    sigs = iter([
        np.zeros(n_pad, np.int32),
        # every LIVE row flips its top community; padding rows never can
        np.concatenate([
            np.ones(n_live, np.int32), np.zeros(n_pad - n_live, np.int32)
        ]),
    ])
    mon = HealthMonitor(
        _cfg(health_every=1), tel,
        sig_fn=lambda state: next(sigs), n_live=n_live,
    )
    vec = np.full(len(HEALTH_FIELDS), NA, np.float64)
    vec[HEALTH_INDEX["iter"]] = 0.0
    vec[HEALTH_INDEX["active_comms"]] = 4.0
    mon.observe(0, -100.0, vec, state=None)
    vec2 = vec.copy()
    vec2[HEALTH_INDEX["iter"]] = 1.0
    mon.observe(1, -99.0, vec2, state=None)
    health = [f for k, f in tel.events if k == "health"]
    # a full live-set flip is churn 1.0, not n_live / n_pad
    assert health[-1]["churn"] == 1.0


def test_sharded_pack_matches_single_chip():
    from bigclam_tpu.parallel import ShardedBigClamModel, make_mesh

    g = _graph()
    F0 = _F0(g)
    cfg = _cfg(health_every=1)
    single = BigClamModel(g, cfg)
    mesh = make_mesh((2, 2), jax.devices()[:4])
    sharded = ShardedBigClamModel(g, cfg, mesh)
    h1 = np.asarray(single._step(single.init_state(F0)).health)
    h2 = np.asarray(sharded._step(sharded.init_state(F0)).health)
    # identical math, float-summation-order differences only (the llh
    # slot is host-stamped NaN on both)
    keep = [i for i, name in enumerate(HEALTH_FIELDS) if name != "llh"]
    np.testing.assert_allclose(h1[keep], h2[keep], rtol=1e-4)


def test_ring_and_sparse_sharded_emit_health(telem):
    from bigclam_tpu.parallel import (
        RingBigClamModel,
        SparseShardedBigClamModel,
        make_mesh,
    )

    g = _graph()
    cfg = _cfg(health_every=1, max_iters=2)
    ring = RingBigClamModel(
        g, cfg, make_mesh((4, 1), jax.devices()[:4]), balance=False
    )
    ring.fit(_F0(g))
    scfg = cfg.replace(representation="sparse", sparse_m=4)
    sp = SparseShardedBigClamModel(
        g, scfg, make_mesh((2, 1), jax.devices()[:2])
    )
    sp.fit(_F0(g))
    events = _events(telem.directory)
    assert sum(1 for e in events if e["kind"] == "health") >= 6
    # sparse_comm satellite: the collective layout reached the event log
    comm = [e for e in events if e["kind"] == "sparse_comm"]
    assert comm and comm[-1]["comm_cap"] >= 1
    assert comm[-1]["comm_mode"] in ("sparse", "dense")
    n, errors = validate_events_file(
        os.path.join(telem.directory, EVENTS_NAME)
    )
    assert errors == [], errors


# ------------------------------------------------------------- detectors
def test_detector_divergence_fires_on_slope_blowup():
    s = [{"iter": i, "llh": -1e4 * (30.0 ** i)} for i in range(6)]
    checks = [a["check"] for a in run_detectors(s, -1e4, 1e-4)]
    assert checks == ["divergence"]


def test_detector_divergence_needs_patience():
    s = [{"iter": 0, "llh": -100.0}, {"iter": 1, "llh": -200.0}]
    assert run_detectors(s, -100.0, 1e-4) == []


def test_detector_plateau_fires_before_tol():
    s = [{"iter": i, "llh": -100.0 * (1 + 1e-9 * i)} for i in range(10)]
    out = run_detectors(s, None, 0.0)
    assert [a["check"] for a in out] == ["plateau"]
    assert out[0]["samples"] >= DEFAULTS["plateau_patience"]


def test_detector_plateau_quiet_on_healthy_decay():
    # geometric convergence: rel change halves each sample, crossing the
    # band briefly — too few flat samples to fire
    llh, s = -1000.0, []
    rel = 0.5
    for i in range(12):
        llh *= 1 - rel
        rel /= 2
        s.append({"iter": i, "llh": llh})
    assert all(
        a["check"] != "plateau" for a in run_detectors(s, None, 1e-4)
    )


def test_detector_oscillation():
    s = [
        {"iter": i, "llh": -100.0 + (1.0 if i % 2 else -1.0)}
        for i in range(10)
    ]
    assert "oscillation" in [
        a["check"] for a in run_detectors(s, None, 1e-4)
    ]


def test_detector_dead_and_cap_pressure():
    s = [{
        "iter": 4, "llh": -10.0, "dead_frac": 0.8,
        "cap_occupancy": 0.9, "dense_fallback": 0.0,
    }]
    checks = {a["check"] for a in run_detectors(s, None, 1e-4)}
    assert checks == {"dead_communities", "cap_pressure"}
    s[0]["dead_frac"] = 0.1
    s[0]["cap_occupancy"] = 0.2
    s[0]["dense_fallback"] = 1.0       # runtime fallback alone fires
    checks = {a["check"] for a in run_detectors(s, None, 1e-4)}
    assert checks == {"cap_pressure"}


def test_planted_divergence_run_fires_anomaly_nan_free(telem):
    """The health_gate recipe in tier-1: a sign-flipped single-candidate
    Armijo ladder walks downhill — LLH worsens geometrically, all finite
    (no nonfinite sentinel), and the divergence detector fires exactly
    once despite many degraded samples (per-check dedup)."""
    g = _graph()
    cfg = _cfg(
        alpha=1e9, max_backtracks=0, step_scale=-0.02,
        rollback_budget=0, health_every=1, max_iters=8,
    )
    model = BigClamModel(g, cfg)
    res = model.fit(_F0(g))
    assert all(math.isfinite(v) for v in res.llh_history)
    events = _events(telem.directory)
    assert not any(e["kind"] == "nonfinite" for e in events)
    anomalies = [e for e in events if e["kind"] == "anomaly"]
    assert [a["check"] for a in anomalies] == ["divergence"]
    assert telem.anomaly_counts == {"divergence": 1}


def test_planted_plateau_run_fires_anomaly(telem):
    g = _graph()
    model = BigClamModel(g, _cfg(health_every=1, max_iters=40))
    model.fit(_F0(g))
    anomalies = [
        e for e in _events(telem.directory) if e["kind"] == "anomaly"
    ]
    assert [a["check"] for a in anomalies] == ["plateau"]


def test_healthy_fit_fires_no_anomaly(telem):
    g = _graph()
    model = BigClamModel(
        g, _cfg(conv_tol=1e-4, max_iters=100, health_every=1)
    )
    model.fit(_F0(g))
    assert not any(
        e["kind"] == "anomaly" for e in _events(telem.directory)
    )


# ------------------------------------------- heartbeat / strict JSON
def test_heartbeat_stall_embeds_last_health(tmp_path):
    from bigclam_tpu.obs.heartbeat import Heartbeat

    tel = RunTelemetry(str(tmp_path / "t"), entry="test")
    tel.event("health", iter=4, grad_norm=12.5, llh=-10.0)
    hb = Heartbeat(tel, deadline_s=0.05, echo=False, poll_s=0.01)
    hb.start()
    time.sleep(0.3)
    hb.stop()
    tel.finalize()
    stalls = [
        e for e in _events(tel.directory) if e["kind"] == "stall"
    ]
    assert stalls
    assert stalls[0]["health"]["grad_norm"] == 12.5
    assert stalls[0]["health"]["iter"] == 4


def test_nonfinite_health_payload_is_strict_json(tmp_path):
    tel = RunTelemetry(str(tmp_path / "t"), entry="test")
    tel.event(
        "health", iter=3, grad_norm=float("inf"), llh=float("nan"),
        update_norm=float("-inf"),
    )
    tel.finalize()
    path = os.path.join(tel.directory, EVENTS_NAME)
    with open(path) as f:
        for line in f:
            json.loads(line, parse_constant=lambda c: pytest.fail(
                f"non-strict JSON constant {c} in {line!r}"
            ))
    n, errors = validate_events_file(path)
    assert errors == [], errors
    ev = [e for e in _events(tel.directory) if e["kind"] == "health"][0]
    assert ev["grad_norm"] == "inf" and ev["llh"] == "nan"


# ------------------------------------------------------ watch / report
def test_watch_renders_sparklines_and_anomalies(telem):
    from bigclam_tpu.obs.watch import render_frame, sparkline, watch

    assert sparkline([1, 2, 3], width=3)[-1] == "█"
    assert "!" in sparkline([1.0, float("nan")], width=4)
    g = _graph()
    cfg = _cfg(
        alpha=1e9, max_backtracks=0, step_scale=-0.02,
        rollback_budget=0, health_every=1, max_iters=8,
    )
    BigClamModel(g, cfg).fit(_F0(g))
    frame = render_frame(telem.directory)
    assert "llh" in frame and "grad_norm" in frame
    assert "ANOMALY divergence" in frame
    out = io.StringIO()
    assert watch(telem.directory, once=True, out=out) == 0
    assert "grad_norm" in out.getvalue()
    assert watch(str(telem.directory) + "_missing", once=True,
                 out=io.StringIO()) == 1


def test_report_json_machine_readable(telem):
    g = _graph()
    BigClamModel(g, _cfg(health_every=2)).fit(_F0(g))
    telem.set_final({"llh": -1.0, "iters": 8, "n": g.num_nodes,
                     "edges": g.num_edges, "k": 4})
    telem.finalize()
    from bigclam_tpu.obs.report import render, render_json

    obj, errors = render_json(telem.directory)
    assert errors == 0
    # strict JSON end to end
    decoded = json.loads(json.dumps(obj))
    assert decoded["health"]["samples"] == 5
    assert decoded["events"]["kinds"]["health"] == 5
    assert decoded["merged"]["final"]["iters"] == 8
    assert decoded["anomalies"] == []
    # exit-code contract unchanged: same error count as the human render
    _, render_errors = render(telem.directory)
    assert errors == render_errors


def test_cli_watch_and_report_json_subprocess(tmp_path):
    """End-to-end: cli fit --health-every leaves health events; report
    --json exits 0 with a parsable object; watch --once renders."""
    import subprocess
    import sys

    graph = tmp_path / "g.txt"
    edges = []
    for base in (0, 8):
        for i in range(8):
            for j in range(i + 1, 8):
                edges.append((base + i, base + j))
    edges.append((7, 8))
    graph.write_text("\n".join(f"{u} {v}" for u, v in edges))
    tdir = tmp_path / "telem"
    r = subprocess.run(
        [sys.executable, "-m", "bigclam_tpu.cli", "fit",
         "--graph", str(graph), "--k", "2", "--dtype", "float64",
         "--max-iters", "6", "--conv-tol", "0", "--init", "random",
         "--quiet", "--platform", "cpu", "--telemetry-dir", str(tdir),
         "--health-every", "2"],
        capture_output=True, text=True, timeout=600, cwd="/root/repo",
    )
    assert r.returncode == 0, r.stderr
    r2 = subprocess.run(
        [sys.executable, "-m", "bigclam_tpu.cli", "report", str(tdir),
         "--json"],
        capture_output=True, text=True, timeout=120, cwd="/root/repo",
    )
    assert r2.returncode == 0, r2.stdout + r2.stderr
    obj = json.loads(r2.stdout)
    assert obj["health"]["samples"] >= 3
    r3 = subprocess.run(
        [sys.executable, "-m", "bigclam_tpu.cli", "watch", str(tdir),
         "--once"],
        capture_output=True, text=True, timeout=120, cwd="/root/repo",
    )
    assert r3.returncode == 0, r3.stdout + r3.stderr
    assert "[finalized]" in r3.stdout and "llh" in r3.stdout


# ---------------------------------------------------------------- ledger
def test_ledger_records_convergence_figures_and_diffs_them():
    from bigclam_tpu.obs.ledger import build_record, diff_records

    def report(iters, gn, run):
        return {
            "run": run, "entry": "fit", "wall_s": 10.0,
            "fingerprint": {"host": "h", "platform": "linux",
                            "backend": "cpu", "device_kind": "cpu",
                            "devices": 1},
            "compiles": {"count": 2, "by_key": {"k1": {
                "builds": 1, "compiles": 2}}},
            "final": {"llh": -1.0, "iters": iters, "n": 100,
                      "edges": 200, "k": 4},
            "health": {"samples": 3, "last": {"grad_norm": gn},
                       "anomalies": {}},
            "spans": {"seconds": {"fit": 9.0}},
            "pid": 0,
        }

    secs = [0.1] * 10
    base = build_record(report(10, 1.5, "a"), secs, [])
    new = build_record(report(30, 40.0, "b"), secs, [])
    assert base["iters_to_tol"] == 10 and new["iters_to_tol"] == 30
    assert base["final_grad_norm"] == 1.5
    d = diff_records(base, new, tolerance=0.25)
    by_metric = {c["metric"]: c for c in d["checks"]}
    assert by_metric["iters_to_tol"]["regression"] is True
    assert d["regression"] is True          # convergence regression GATES
    assert by_metric["final_grad_norm"]["verdicted"] is False
    # flat-iteration runs pass
    d2 = diff_records(base, build_record(report(10, 1.5, "c"), secs, []),
                      tolerance=0.25)
    assert d2["regression"] is False


def test_ledger_nonfinite_grad_norm_stays_strict_json():
    # finalize auto-append hands build_record the IN-MEMORY report: a
    # blow-up's inf/nan grad_norm must become None (matching what `cli
    # perf record` reads from the finite-safed on-disk report), not a
    # literal Infinity that breaks the JSONL ledger for strict parsers
    from bigclam_tpu.obs.ledger import build_record

    for gn in (float("inf"), float("nan")):
        rec = build_record({
            "run": "r", "entry": "fit", "wall_s": 1.0,
            "fingerprint": {}, "final": {},
            "health": {"samples": 1, "last": {"grad_norm": gn},
                       "anomalies": {}},
            "pid": 0,
        })
        assert rec["final_grad_norm"] is None
        json.loads(json.dumps(rec, allow_nan=False))


def test_ledger_handles_missing_health(telem):
    from bigclam_tpu.obs.ledger import build_record, validate_record

    rec = build_record(telem.report())
    assert rec["final_grad_norm"] is None
    assert rec["iters_to_tol"] is None
    assert validate_record(rec) == []


# -------------------------------------------------------- overhead pin
def test_health_on_overhead_under_2pct(tmp_path):
    """Acceptance pin (mirrors the telemetry/trace pins): the HOST-side
    health bookkeeping at the default CLI cadence (10) — the off-cadence
    modulo check plus the on-cadence pack fetch + signature churn +
    event write — stays under 2% of the real compiled step time. The
    device-side pack itself is a handful of reductions lax.cond-gated to
    cadence iterations, invisible next to the step's 17 edge sweeps."""
    from bigclam_tpu.utils.profiling import step_time

    g = _graph()
    cfg = _cfg(health_every=10)
    model = BigClamModel(g, cfg)
    state = model.init_state(_F0(g))
    stepped = model._step(state)           # carries a real health pack
    sec_per_step = step_time(model._step, state, steps=15, warmup=2)

    tel = install(RunTelemetry(str(tmp_path / "t"), entry="pin"))
    try:
        monitor = HealthMonitor(cfg, tel, sig_fn=model.health_sig)
        iters = 2000
        t0 = time.perf_counter()
        for i in range(iters):
            monitor.maybe_observe(i, -123.456, stepped)
        overhead_per_iter = (time.perf_counter() - t0) / iters
    finally:
        tel.finalize()
        uninstall(tel)
    assert monitor.samples                  # the cadence path actually ran
    assert overhead_per_iter < 0.02 * sec_per_step, (
        f"health-on overhead {overhead_per_iter:.3e}s/iter vs "
        f"step {sec_per_step:.3e}s"
    )


def test_health_pack_na_slots_and_index():
    assert len(HEALTH_FIELDS) == len(set(HEALTH_FIELDS))
    assert HEALTH_INDEX["iter"] == 0
    assert NA == -1.0
