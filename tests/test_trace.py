"""Span-tracer tests (ISSUE 6, bigclam_tpu.obs.trace): nesting/path
invariants (exception-safe close, orphan repair), the zero-cost-off and
<2%-overhead-on pins, heartbeat span-stack embedding, fit-loop phase
spans, profiler-capture gating, report merge ordering (numeric pids,
stable elapsed_s event sort), and the bench cpu-fallback env propagation
satellite."""

import json
import math
import os
import threading
import time

import numpy as np
import pytest

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.models import BigClamModel
from bigclam_tpu.obs import (
    RunTelemetry,
    current,
    install,
    uninstall,
    validate_events_file,
)
from bigclam_tpu.obs import trace
from bigclam_tpu.obs.report import (
    _event_order,
    load_events,
    load_reports,
    render,
    run_duration_s,
    span_coverage,
)
from bigclam_tpu.obs.telemetry import EVENTS_NAME


def _events(directory):
    with open(os.path.join(directory, EVENTS_NAME)) as f:
        return [json.loads(line) for line in f if line.strip()]


def _problem(toy_graphs, k=2, max_iters=5):
    g = toy_graphs["two_cliques"]
    cfg = BigClamConfig(
        num_communities=k, dtype="float64", max_iters=max_iters,
        conv_tol=0.0,
    )
    F0 = np.random.default_rng(5).uniform(0.1, 1.0, size=(g.num_nodes, k))
    return g, cfg, F0


@pytest.fixture
def telem(tmp_path):
    tel = install(RunTelemetry(str(tmp_path / "telem"), entry="test"))
    try:
        yield tel
    finally:
        tel.finalize()
        uninstall(tel)


# ------------------------------------------------------------ invariants

def test_span_off_is_shared_noop():
    """Zero-cost contract: with telemetry off span() returns ONE shared
    no-op object — no Span construction, no stack mutation, no event."""
    assert current() is None
    s = trace.span("anything", field=1)
    assert s is trace.span("other") is trace.NULL_SPAN
    with s:
        assert trace.open_spans() == []
    trace.add_span("x", 1.0)           # also a no-op off


def test_span_nesting_paths_totals_and_events(telem):
    with trace.span("outer"):
        time.sleep(0.01)
        with trace.span("inner", tag="a"):
            time.sleep(0.01)
            assert trace.current_path() == "outer/inner"
            assert trace.open_spans() == ["outer", "outer/inner"]
    assert trace.open_spans() == []
    assert set(telem.span_seconds) == {"outer", "outer/inner"}
    assert telem.span_seconds["outer"] >= telem.span_seconds["outer/inner"]
    assert telem.span_counts == {"outer": 1, "outer/inner": 1}
    spans = [e for e in telem.report()["events"].items() if e[0] == "span"]
    assert spans and spans[0][1] == 2
    telem.finalize()
    events = [e for e in _events(telem.directory) if e["kind"] == "span"]
    inner = next(e for e in events if e["path"] == "outer/inner")
    assert inner["name"] == "inner" and inner["tag"] == "a"
    assert inner["seconds"] >= 0.01
    n, errors = validate_events_file(
        os.path.join(telem.directory, EVENTS_NAME)
    )
    assert errors == [], errors


def test_span_exception_safe_close(telem):
    """A raise inside nested spans closes BOTH (stack empty afterwards),
    records their intervals, and marks the events ok=False."""
    with pytest.raises(RuntimeError):
        with trace.span("outer"):
            with trace.span("inner"):
                raise RuntimeError("boom")
    assert trace.open_spans() == []
    assert set(telem.span_seconds) == {"outer", "outer/inner"}
    assert telem.span_orphans == 0
    telem.finalize()
    events = [e for e in _events(telem.directory) if e["kind"] == "span"]
    assert all(e.get("ok") is False for e in events)


def test_span_orphan_close_repaired_and_flagged(telem, tmp_path):
    """A span entered and abandoned (no exit) must not corrupt the stack:
    the enclosing close repairs it, the orphan is counted, and `cli
    report` flags it as a problem."""
    with trace.span("outer"):
        trace.span("abandoned").__enter__()     # never exited
    assert trace.open_spans() == []             # repaired
    assert telem.span_orphans == 1
    assert "outer" in telem.span_seconds
    rep = telem.finalize()
    assert rep["spans"]["orphans"] == 1
    text, errors = render(telem.directory)
    assert errors >= 1 and "SPAN ORPHANS" in text


def test_add_span_lands_at_current_stack_position(telem):
    with trace.span("parent"):
        trace.add_span("timed", 1.25, emit=False)
    assert telem.span_seconds["parent/timed"] == 1.25


def test_span_thread_stacks_are_independent(telem):
    seen = {}

    def worker():
        with trace.span("worker_phase"):
            seen["path"] = trace.current_path()
            seen["open"] = sorted(trace.open_spans())
            time.sleep(0.02)

    with trace.span("main_phase"):
        t = threading.Thread(target=worker)
        t.start()
        t.join()
    assert seen["path"] == "worker_phase"    # no cross-thread nesting
    assert "main_phase" in seen["open"] and "worker_phase" in seen["open"]


# ------------------------------------------------------- stage/loop wiring

def test_stage_opens_matching_span(telem):
    from bigclam_tpu.utils.profiling import StageProfile

    prof = StageProfile()
    with prof.stage("outer_stage"):
        with prof.stage("inner_stage"):
            time.sleep(0.005)
    prof.add_seconds("self_timed", 0.5)
    assert "outer_stage" in telem.span_seconds
    assert "outer_stage/inner_stage" in telem.span_seconds
    assert telem.span_seconds["self_timed"] == 0.5
    # stage buckets unchanged (flat, not a tree)
    assert set(prof.seconds) == {"outer_stage", "inner_stage", "self_timed"}


def test_fit_loop_phase_spans(toy_graphs, telem, tmp_path):
    """Every iteration contributes to the fit_loop phase spans; checkpoint
    saves get their own emitted span; totals land in the report."""
    from bigclam_tpu.utils.checkpoint import CheckpointManager

    g, cfg, F0 = _problem(toy_graphs, max_iters=6)
    cfg = cfg.replace(checkpoint_every=2)
    model = BigClamModel(g, cfg)
    model.fit(F0, checkpoints=CheckpointManager(str(tmp_path / "ck")))
    spans = telem.span_seconds
    for phase in ("fit_loop/dispatch", "fit_loop/sync",
                  "fit_loop/extract_F"):
        assert phase in spans, spans
    # one dispatch/sync per iteration (max_iters+1 loop entries)
    assert telem.span_counts["fit_loop/dispatch"] == cfg.max_iters + 1
    assert telem.span_counts["fit_loop/dispatch"] == telem.span_counts[
        "fit_loop/sync"
    ]
    assert telem.span_counts["fit_loop/checkpoint"] >= 2
    telem.finalize()
    ck_events = [
        e for e in _events(telem.directory)
        if e["kind"] == "span" and e["path"] == "fit_loop/checkpoint"
    ]
    assert ck_events and all("it" in e for e in ck_events)
    n, errors = validate_events_file(
        os.path.join(telem.directory, EVENTS_NAME)
    )
    assert errors == [], errors


def test_overlap_report_folds_into_spans(toy_graphs, telem):
    """overlap_report (the ring wait-vs-compute probe) records one parent
    span carrying the verdict fields plus a child span per schedule."""
    from bigclam_tpu.utils.profiling import overlap_report

    g, cfg, F0 = _problem(toy_graphs, max_iters=3)
    model = BigClamModel(g, cfg)
    rep = overlap_report(model, model.init_state(F0), steps=2, warmup=1)
    assert set(rep["sec_per_step"]) == {"overlap", "serial"}
    spans = telem.span_seconds
    assert "ring_overlap_probe" in spans
    assert "ring_overlap_probe/overlap" in spans
    assert "ring_overlap_probe/serial" in spans
    telem.finalize()
    probe = next(
        e for e in _events(telem.directory)
        if e["kind"] == "span" and e["path"] == "ring_overlap_probe"
    )
    assert "comm_hidden_fraction" in probe and "sec_per_step" in probe


def test_heartbeat_stall_reports_open_span_stack(tmp_path):
    """Satellite: a stall emitted while a span is open answers 'stuck in
    which phase' — the stall event carries the open span stack."""
    tel = install(
        RunTelemetry(str(tmp_path / "t"), entry="test", heartbeat_s=0.08,
                     quiet=True)
    )
    try:
        with trace.span("fit"):
            with trace.span("wedged_collective", emit=False):
                time.sleep(0.5)
    finally:
        tel.finalize()
        uninstall(tel)
    stalls = [e for e in _events(tel.directory) if e["kind"] == "stall"]
    assert stalls, "no stall fired"
    assert stalls[0]["spans"] == ["fit", "fit/wedged_collective"]
    n, errors = validate_events_file(
        os.path.join(tel.directory, EVENTS_NAME)
    )
    assert errors == [], errors


# ------------------------------------------------------------------ cost

def test_tracing_overhead_under_2pct_with_spans_on(tmp_path):
    """Acceptance pin: the fit loop's per-iteration span set (3 emit=False
    spans), telemetry ON, NO profiler capture, costs <2% of the step time
    of a small-but-real model. (The 16-node toy step sits below the jit
    dispatch floor — per-span cost is fixed ~2us, so the fraction only
    shrinks on real configs.)"""
    from bigclam_tpu.models.agm import sample_planted_graph
    from bigclam_tpu.utils.profiling import step_time

    g, _ = sample_planted_graph(
        240, 4, p_in=0.3, rng=np.random.default_rng(0)
    )
    cfg = BigClamConfig(
        num_communities=4, dtype="float64", max_iters=5, conv_tol=0.0
    )
    F0 = np.random.default_rng(1).uniform(0.1, 1.0, size=(g.num_nodes, 4))
    model = BigClamModel(g, cfg)
    sec_per_step = step_time(
        model._step, model.init_state(F0), steps=15, warmup=2
    )

    tel = install(RunTelemetry(str(tmp_path / "t"), entry="t", quiet=True))
    try:
        assert not trace.capture_active()
        iters = 20000
        t0 = time.perf_counter()
        for _ in range(iters):
            with trace.span("fit_loop/dispatch", emit=False):
                pass
            with trace.span("fit_loop/sync", emit=False):
                pass
            with trace.span("fit_loop/callback", emit=False):
                pass
        per_iter = (time.perf_counter() - t0) / iters
    finally:
        tel.finalize()
        uninstall(tel)
    assert per_iter < 0.02 * sec_per_step, (
        f"span overhead {per_iter:.3e}s/iter vs step {sec_per_step:.3e}s "
        f"({100 * per_iter / sec_per_step:.2f}%)"
    )
    # and no per-iteration event lines were written (emit=False)
    events = _events(str(tmp_path / "t"))
    assert not [e for e in events if e["kind"] == "span"]


def test_emit_false_spans_skip_annotations_outside_capture(telem):
    """emit=False spans must not construct TraceAnnotations unless a
    profiler capture is live (utils.profiling.trace flips the flag)."""
    with trace.span("hot", emit=False) as sp:
        assert sp._ann is None
    trace.capture_started()
    try:
        with trace.span("hot", emit=False) as sp:
            captured_ann = sp._ann
    finally:
        trace.capture_stopped()
    # under capture the annotation engages (when jax.profiler has the API)
    if trace._ANN["cls"] is not None:
        assert captured_ann is not None
    assert not trace.capture_active()


# ------------------------------------------- report ordering (satellite)

def test_load_reports_numeric_pid_order(tmp_path):
    """run_report.p10 must sort AFTER p2 (lexical sort scrambled >= 10
    processes)."""
    for name, pid in (
        ("run_report.json", 0),
        ("run_report.p1.json", 1),
        ("run_report.p2.json", 2),
        ("run_report.p10.json", 10),
    ):
        (tmp_path / name).write_text(json.dumps({"pid": pid}))
    reports = load_reports(str(tmp_path))
    assert [r["pid"] for r in reports] == [0, 1, 2, 10]


def test_load_events_stable_merge_on_interleaved_and_equal_times(tmp_path):
    """Events are ordered by MONOTONIC elapsed_s; equal timestamps keep
    file order (stable) — the heartbeat-thread interleave contract."""
    base = {"v": 2, "run": "r", "pid": 0, "ts": 1.0}
    lines = [
        {**base, "t": 0.3, "elapsed_s": 0.3, "kind": "note", "i": 2},
        {**base, "t": 0.1, "elapsed_s": 0.1, "kind": "note", "i": 0},
        {**base, "t": 0.2, "elapsed_s": 0.2, "kind": "note", "i": 1},
        # equal elapsed_s: file order must be preserved
        {**base, "t": 0.2, "elapsed_s": 0.2, "kind": "note", "i": 1.5},
    ]
    with open(tmp_path / EVENTS_NAME, "w") as f:
        for rec in lines:
            f.write(json.dumps(rec) + "\n")
    events = load_events(str(tmp_path))
    assert [e["i"] for e in events] == [0, 1, 1.5, 2]
    # ordering key is monotonic time, never the wall clock
    assert _event_order({"elapsed_s": 5.0, "ts": 1.0, "t": 2.0}) == 5.0


def test_run_duration_ignores_wall_clock_jumps():
    """Satellite: durations derive from elapsed_s — a wall-clock jump
    (NTP step) between events cannot corrupt the figure."""
    events = [
        {"elapsed_s": 0.0, "ts": 1000.0, "kind": "start"},
        {"elapsed_s": 2.5, "ts": 5000000.0, "kind": "end"},  # ts jumped
    ]
    assert run_duration_s(events) == 2.5
    assert run_duration_s([{"kind": "x"}]) is None


def test_span_coverage_top_level_only():
    rep = {
        "wall_s": 10.0,
        "spans": {"seconds": {"a": 6.0, "b": 3.5, "a/child": 5.9}},
    }
    assert math.isclose(span_coverage(rep), 0.95)
    assert span_coverage({"wall_s": 0, "spans": {"seconds": {}}}) is None


# ------------------------------------------------- bench env (satellite)

def test_bench_cpu_fallback_env_propagates_observability():
    """Satellite: the cpu-fallback re-exec must carry the telemetry dir,
    perf ledger, and fault-plan env through to the child — dropping any
    would silently strip the fallback run's observability."""
    import bench

    parent = {
        "BIGCLAM_TELEMETRY_DIR": "/tmp/t",
        "BIGCLAM_PERF_LEDGER": "/tmp/ledger.jsonl",
        "BIGCLAM_FAULTS": '{"faults": []}',
        "XLA_FLAGS": "--xla_foo=1",
        "PATH": "/usr/bin",
    }
    env = bench._fallback_child_env(parent)
    for key in bench.PROPAGATED_ENV:
        assert env[key] == parent[key], key
    assert env["PATH"] == "/usr/bin"
    assert env["JAX_PLATFORMS"] == "cpu"
    assert env[bench.FALLBACK_ENV] == "1"
    assert "--xla_foo=1" in env["XLA_FLAGS"]
    assert "--xla_force_host_platform_device_count=8" in env["XLA_FLAGS"]
    assert "JAX_PLATFORMS" not in parent      # input not mutated
