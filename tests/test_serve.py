"""Membership serving (ISSUE 14): fold-in correctness, snapshot
publish/hot-swap, the query families, the request batcher, the
Zipf-aware cache, and the serving ledger fields."""

import json
import os
import threading
import time

import numpy as np
import pytest

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.models import BigClamModel
from bigclam_tpu.models.agm import sample_planted_graph
from bigclam_tpu.ops import extraction
from bigclam_tpu.serve.batcher import RequestBatcher
from bigclam_tpu.serve.server import (
    FoldInEngine,
    HotCommunityCache,
    MembershipServer,
)
from bigclam_tpu.serve.snapshot import (
    ServingSnapshot,
    SnapshotError,
    pad_neighbor_batch,
    publish_snapshot,
)
from bigclam_tpu.utils.checkpoint import CheckpointManager

K = 6
N = 120


@pytest.fixture(scope="module")
def fitted():
    """One small planted fit shared by the module (trainer correctness
    is pinned elsewhere; serving tests only need a realistic F)."""
    rng = np.random.default_rng(3)
    g, truth = sample_planted_graph(N, K, p_in=0.8, rng=rng)
    cfg = BigClamConfig(num_communities=K, max_iters=300)
    model = BigClamModel(g, cfg)
    res = model.fit(model.random_init())
    return g, truth, cfg, model, res


@pytest.fixture()
def snapdir(tmp_path, fitted):
    g, truth, cfg, model, res = fitted
    d = str(tmp_path / "snaps")
    publish_snapshot(
        d, step=res.num_iters, F=res.F, raw_ids=g.raw_ids,
        num_edges=g.num_edges, cfg=cfg, meta={"llh": res.llh},
    )
    return d


# ---------------------------------------------------------- fold-in ops
def test_foldin_pass_matches_trainer_per_node(fitted):
    """The sharpest correctness pin: the fold-in objective/gradient of a
    row batch equals the trainer's own per-node grad/LLH slice."""
    import jax.numpy as jnp

    from bigclam_tpu.ops import foldin as fi
    from bigclam_tpu.ops.objective import grad_llh

    g, _, cfg, model, res = fitted
    state = model.init_state(res.F)
    grad_full, node_llh = grad_llh(state.F, state.sumF, model.edges, cfg)
    nodes = [0, 7, 33, 77]
    nbr_ids, nbr_mask, _ = pad_neighbor_batch(g.indptr, g.indices, nodes)
    rows = state.F[jnp.asarray(nodes)]
    nbr_rows = fi.gather_neighbor_rows(state.F, jnp.asarray(nbr_ids))
    mask = jnp.asarray(nbr_mask, state.F.dtype)
    sumF_others = state.sumF[None, :] - rows
    grad, llh = fi.foldin_pass(rows, nbr_rows, mask, sumF_others, cfg)
    np.testing.assert_allclose(
        np.asarray(grad), np.asarray(grad_full)[nodes], atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(llh), np.asarray(node_llh)[nodes], atol=1e-5
    )


def test_foldin_recovers_trained_row_dense(fitted):
    """A node present during training: its trained row is a fixed point
    of the fold-in objective (init='own' recovers it within the band)."""
    g, _, cfg, model, res = fitted
    state = model.init_state(res.F)
    nodes = list(range(0, N, 11))
    rows, llh, iters = model.foldin_rows(
        state, nodes, conv_tol=1e-8, max_iters=500
    )
    np.testing.assert_allclose(rows, res.F[nodes], atol=1e-3)
    assert np.all(np.isfinite(llh))


def test_foldin_recovers_trained_row_sparse(fitted):
    """Sparse twin at M >= K (no truncation): fold-in against the frozen
    member lists recovers the trained rows of the sparse fit."""
    from bigclam_tpu.models.sparse import SparseBigClamModel

    g, _, cfg, model, res = fitted
    scfg = cfg.replace(representation="sparse", sparse_m=K)
    smodel = SparseBigClamModel(g, scfg)
    state, llh, iters, _ = smodel.fit_state(
        smodel.init_state(smodel.random_init())
    )
    F_tr = smodel.extract_F(state)
    nodes = list(range(0, N, 13))
    rows, rl, ri = smodel.foldin_rows(
        state, nodes, conv_tol=1e-8, max_iters=500
    )
    # the sparse fit stops at the JOINT conv_tol, so fold-in may refine
    # a row slightly past it — the band is the recovery tolerance
    np.testing.assert_allclose(rows, F_tr[nodes], atol=5e-3)


@pytest.mark.parametrize("init", ["own", "mean"])
def test_foldin_batched_equals_sequential_dense(fitted, init):
    g, _, cfg, model, res = fitted
    state = model.init_state(res.F)
    nodes = [2, 19, 45, 101]
    rows_b, llh_b, it_b = model.foldin_rows(
        state, nodes, conv_tol=1e-8, max_iters=400, init=init
    )
    for i, u in enumerate(nodes):
        rows_1, llh_1, it_1 = model.foldin_rows(
            state, [u], conv_tol=1e-8, max_iters=400, init=init
        )
        np.testing.assert_allclose(rows_1[0], rows_b[i], rtol=1e-6,
                                   atol=1e-7)
        assert int(it_1[0]) == int(it_b[i])


def test_foldin_batched_equals_sequential_sparse(fitted):
    from bigclam_tpu.models.sparse import SparseBigClamModel

    g, _, cfg, model, res = fitted
    scfg = cfg.replace(representation="sparse", sparse_m=K)
    smodel = SparseBigClamModel(g, scfg)
    state, _, _, _ = smodel.fit_state(
        smodel.init_state(smodel.random_init())
    )
    nodes = [5, 28, 61]
    rows_b, _, it_b = smodel.foldin_rows(
        state, nodes, conv_tol=1e-8, max_iters=400, init="mean"
    )
    for i, u in enumerate(nodes):
        rows_1, _, it_1 = smodel.foldin_rows(
            state, [u], conv_tol=1e-8, max_iters=400, init="mean"
        )
        np.testing.assert_allclose(rows_1[0], rows_b[i], rtol=1e-6,
                                   atol=1e-7)
        assert int(it_1[0]) == int(it_b[i])


def test_pad_neighbor_batch_shapes_and_truncation(fitted):
    g, *_ = fitted
    nodes = [0, 1, 2]
    ids, mask, trunc = pad_neighbor_batch(g.indptr, g.indices, nodes)
    degs = [len(g.neighbors(u)) for u in nodes]
    assert trunc == 0 and ids.shape == mask.shape
    assert [int(r.sum()) for r in mask] == degs
    for i, u in enumerate(nodes):
        np.testing.assert_array_equal(
            ids[i, : degs[i]], g.neighbors(u)
        )
    ids2, mask2, trunc2 = pad_neighbor_batch(
        g.indptr, g.indices, nodes, max_deg=2
    )
    assert ids2.shape[1] == 2 and trunc2 == sum(d - 2 for d in degs if d > 2)


# ------------------------------------------------- snapshots + publish
def test_publish_latest_and_roundtrip(tmp_path, fitted):
    g, _, cfg, model, res = fitted
    d = str(tmp_path / "s")
    mgr = CheckpointManager(d)
    assert mgr.latest() is None
    publish_snapshot(d, step=5, F=res.F, raw_ids=g.raw_ids,
                     num_edges=g.num_edges, cfg=cfg)
    assert mgr.latest() == 5
    publish_snapshot(d, step=9, F=res.F + 0.25, raw_ids=g.raw_ids,
                     num_edges=g.num_edges, cfg=cfg)
    assert mgr.latest() == 9
    assert mgr.published_steps() == [5, 9]
    step, arrays, meta = mgr.load_published()
    assert step == 9 and meta["representation"] == "dense"
    np.testing.assert_array_equal(arrays["F"], res.F + 0.25)
    # checkpoints and snapshots never collide: rotation ignores snap_
    mgr.save(1, {"F": res.F})
    assert mgr.published_steps() == [5, 9]
    assert mgr.steps() == [1]


def test_corrupt_latest_snapshot_falls_back(tmp_path, fitted, capsys):
    g, _, cfg, model, res = fitted
    d = str(tmp_path / "s")
    publish_snapshot(d, step=1, F=res.F, raw_ids=g.raw_ids,
                     num_edges=g.num_edges, cfg=cfg)
    publish_snapshot(d, step=2, F=res.F + 1.0, raw_ids=g.raw_ids,
                     num_edges=g.num_edges, cfg=cfg)
    # flip bytes inside the newest archive (silent corruption)
    path = os.path.join(d, "snap_000000002.npz")
    data = bytearray(open(path, "rb").read())
    data[len(data) // 2] ^= 0xFF
    open(path, "wb").write(bytes(data))
    snap = ServingSnapshot.load(d)
    assert snap.step == 1
    np.testing.assert_array_equal(snap.F, res.F)


def test_snapshot_refuses_wrong_store(tmp_path, fitted):
    g, _, cfg, model, res = fitted

    class FakeStore:
        num_nodes = N + 1
        num_directed_edges = 2 * g.num_edges

    d = str(tmp_path / "s")
    publish_snapshot(d, step=1, F=res.F, raw_ids=g.raw_ids,
                     num_edges=g.num_edges, cfg=cfg)
    with pytest.raises(SnapshotError, match="does not match the store"):
        ServingSnapshot.load(d, store=FakeStore())


def test_snapshot_membership_index_matches_extraction(snapdir, fitted):
    g, _, cfg, model, res = fitted
    snap = ServingSnapshot.load(snapdir)
    comms = extraction.extract_communities(res.F, g)
    for c in range(K):
        assert snap.members_of(c).tolist() == comms.get(c, [])
    delta = extraction.delta_threshold(g.num_nodes, g.num_edges)
    assert snap.delta == pytest.approx(delta)
    mask = extraction.membership_mask(res.F, delta)
    for u in range(0, N, 17):
        cids, weights = snap.communities_of(snap.row_of(u))
        assert sorted(cids.tolist()) == np.nonzero(mask[u])[0].tolist()
        # ranked by weight descending
        assert list(weights) == sorted(weights, reverse=True)


def test_sparse_snapshot_membership(tmp_path, fitted):
    from bigclam_tpu.ops import sparse_members as sm

    g, _, cfg, model, res = fitted
    ids, w, truncated = sm.from_dense(res.F, K, K, N)
    assert truncated == 0          # M == K: nothing dropped
    d = str(tmp_path / "s")
    publish_snapshot(d, step=3, ids=ids, w=w, raw_ids=g.raw_ids,
                     num_edges=g.num_edges, cfg=cfg)
    snap = ServingSnapshot.load(d)
    assert snap.representation == "sparse"
    comms = extraction.extract_communities(res.F, g)
    delta = snap.delta
    mask = extraction.membership_mask(res.F, delta)
    nonzero_rows = np.asarray(res.F).max(axis=1) > 0
    for c in range(K):
        want = [
            u for u in comms.get(c, []) if nonzero_rows[snap.row_of(u)]
        ]
        assert snap.members_of(c).tolist() == want
    np.testing.assert_allclose(
        snap.sumF, res.F.sum(axis=0), rtol=1e-6
    )


def test_snapshot_members_sorted_by_raw_id_under_permutation(tmp_path):
    """Balanced caches permute rows, so raw_ids is not monotone in row
    index: members_of must still return RAW-id-sorted lists (the
    ops.extraction._group_pairs contract)."""
    rng = np.random.default_rng(2)
    n, k = 30, 3
    F = rng.uniform(0.0, 1.0, size=(n, k))
    raw = rng.permutation(np.arange(100, 100 + n))
    d = str(tmp_path / "s")
    publish_snapshot(
        d, step=1, F=F, raw_ids=raw, num_edges=40,
        cfg=BigClamConfig(num_communities=k),
    )
    snap = ServingSnapshot.load(d)
    delta = snap.delta
    mask = extraction.membership_mask(F, delta)
    for c in range(k):
        want = sorted(int(raw[u]) for u in np.nonzero(mask[:, c])[0])
        assert snap.members_of(c).tolist() == want
    # row_of inverts the permutation
    for u in (0, 7, 29):
        assert snap.row_of(int(raw[u])) == u


def test_snapshot_stamps_conv_tol_for_foldin(tmp_path):
    """The fold-in engine must stop at the TRAINER's tolerance — the
    snapshot carries conv_tol (a fit at 1e-6 must not serve suggests
    converged only to the class default 1e-4)."""
    cfg = BigClamConfig(num_communities=3, conv_tol=1e-6, alpha=0.07)
    d = str(tmp_path / "s")
    F = np.random.default_rng(0).uniform(size=(10, 3))
    publish_snapshot(d, step=1, F=F, num_edges=12, cfg=cfg)
    snap = ServingSnapshot.load(d)
    assert snap.meta["conv_tol"] == 1e-6
    engine = FoldInEngine(snap)
    assert engine.cfg.conv_tol == 1e-6
    assert engine.cfg.alpha == 0.07


def test_maybe_reload_survives_corrupt_newest_publication(tmp_path,
                                                          fitted):
    g, _, cfg, model, res = fitted
    d = str(tmp_path / "s")
    publish_snapshot(d, step=1, F=res.F, raw_ids=g.raw_ids,
                     num_edges=g.num_edges, cfg=cfg)
    with MembershipServer(d, budget_s=0.001) as server:
        publish_snapshot(d, step=2, F=np.roll(res.F, 1, axis=1),
                         raw_ids=g.raw_ids, num_edges=g.num_edges,
                         cfg=cfg)
        # newest publication lost a writeback: the fallback load
        # resolves to the snapshot already serving -> NO swap, no error
        open(os.path.join(d, "snap_000000002.npz"), "wb").write(b"torn")
        assert server.maybe_reload() is None
        assert server.generation == 1
        r = server.query({"family": "members_of", "c": 0})
        assert "members" in r
        # the publisher retries; now the swap goes through
        publish_snapshot(d, step=3, F=np.roll(res.F, 1, axis=1),
                         raw_ids=g.raw_ids, num_edges=g.num_edges,
                         cfg=cfg)
        assert server.maybe_reload() == 3
        assert server.generation == 3


def test_malformed_query_does_not_lose_batch_telemetry(tmp_path,
                                                       snapdir):
    from bigclam_tpu.obs import RunTelemetry, install, uninstall

    tdir = str(tmp_path / "telem")
    tel = install(RunTelemetry(tdir, entry="serve", quiet=True,
                               device_memory=False))
    try:
        with MembershipServer(snapdir, budget_s=0.01,
                              max_batch=8) as server:
            results = server.run_queries(
                [{"family": "members_of", "c": 0},
                 {"u": 1},                      # family missing
                 {"family": 12, "c": 0},        # family not a string
                 "not even a dict"]
            )
    finally:
        tel.finalize()
        uninstall(tel)
    assert "members" in results[0]
    assert all("error" in r for r in results[1:])
    with open(os.path.join(tdir, "events.jsonl")) as f:
        serve_events = [
            json.loads(ln) for ln in f
            if ln.strip() and json.loads(ln)["kind"] == "serve"
        ]
    # the batch's serve event survived the malformed entries
    assert sum(e["batch"] for e in serve_events) == 4


def test_cli_query_spec_errors_are_clean(snapdir):
    from bigclam_tpu.cli import _parse_query_spec

    assert _parse_query_spec("members_of:3") == {"family": "members_of",
                                                 "c": 3}
    for bad in ("members_of:abc", "members_of", "nope:1", "{not json"):
        with pytest.raises(SystemExit, match="error: --query"):
            _parse_query_spec(bad)


# ------------------------------------------------------------ batcher
def test_batcher_full_and_deadline_flush():
    seen = []

    def handler(batch):
        seen.append(len(batch))
        for req in batch:
            req.future.set_result(req.payload)

    b = RequestBatcher(handler, max_batch=4, budget_s=0.05).start()
    try:
        futs = [b.submit(i) for i in range(8)]
        assert [f.result(5.0) for f in futs] == list(range(8))
        assert sum(seen) == 8
        t0 = time.perf_counter()
        lone = b.submit(99)
        assert lone.result(5.0) == 99
        # the lone request waits ~the budget, not forever
        assert time.perf_counter() - t0 < 2.0
        b.drain()
        assert b.flushed_deadline >= 1
    finally:
        b.stop()


def test_batcher_handler_exception_fails_futures_not_thread():
    calls = {"n": 0}

    def handler(batch):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("boom")
        for req in batch:
            req.future.set_result("ok")

    b = RequestBatcher(handler, max_batch=1, budget_s=0.0).start()
    try:
        with pytest.raises(RuntimeError, match="boom"):
            b.submit(1).result(5.0)
        assert b.submit(2).result(5.0) == "ok"   # thread survived
    finally:
        b.stop()


# ---------------------------------------------------- cache (Zipf-aware)
def test_hot_cache_prewarm_and_mass_share_admission(snapdir):
    snap = ServingSnapshot.load(snapdir)
    cache = HotCommunityCache(slots=2)
    cache.reset(snap)
    top = snap.top_mass_communities(2)
    for c in top:
        assert cache.get(int(c)) is not None        # pre-warmed: hits
    order = np.argsort(-snap.mass_share, kind="stable")
    coldest = int(order[-1])
    assert cache.get(coldest) is None               # miss
    cache.put(coldest, snap.members_of(coldest))
    # the long tail never evicts the hot head
    assert coldest not in cache.data
    assert cache.hits == 2 and cache.misses == 1
    assert cache.hit_rate == pytest.approx(2 / 3)


# ------------------------------------------------------------- server
def test_server_three_families(snapdir, fitted):
    g, _, cfg, model, res = fitted
    with MembershipServer(
        snapdir, graph=g, budget_s=0.001, max_batch=16
    ) as server:
        snap = ServingSnapshot.load(snapdir)
        u = 7
        r = server.query({"family": "communities_of", "u": int(g.raw_ids[u])})
        cids, weights = snap.communities_of(u)
        assert [c for c, _ in r["communities"]] == cids.tolist()
        r = server.query({"family": "members_of", "c": 0})
        assert r["members"] == snap.members_of(0).tolist()
        r = server.query({"family": "suggest_for", "u": int(g.raw_ids[u])})
        assert r["suggested"], "fold-in suggested nothing"
        # an existing node's suggestion leads with its trained community
        assert r["suggested"][0][0] == cids[0]
        stats = server.stats()
        assert stats["serve_queries"] == 3 and stats["serve_errors"] == 0
        assert stats["serve_p99_s"] > 0 and stats["serve_qps"] > 0


def test_server_new_node_suggest_via_neighbors(snapdir, fitted):
    """A brand-new node described only by its neighbor list lands in the
    community its neighbors share (the live-graph fold-in path)."""
    g, truth, cfg, model, res = fitted
    snap = ServingSnapshot.load(snapdir)
    # pick the community with the most members; its trained members are
    # the new node's neighbors
    c = int(np.argmax(np.diff(snap.comm_indptr)))
    members = snap.members_of(c).tolist()[:10]
    with MembershipServer(snapdir, budget_s=0.001) as server:
        r = server.query(
            {"family": "suggest_for", "neighbors": members}
        )
        assert r["suggested"][0][0] == c
        assert r["iters"] >= 1


def test_server_suggest_for_frozen_zero_row_uses_neighbor_mean(tmp_path):
    """A node whose trained row froze all-zero (the faithful dynamics'
    known failure mode) must still get a real suggestion: the engine
    falls back to the neighbor-mean cold start for empty own rows."""
    from bigclam_tpu.graph.csr import Graph

    # star: node 0 (zero row) linked to 4 nodes all in community 1
    n = 6
    indptr = np.array([0, 4, 5, 6, 7, 8, 8], np.int64)
    indices = np.array([1, 2, 3, 4, 0, 0, 0, 0], np.int32)
    g = Graph(indptr=indptr, indices=indices,
              raw_ids=np.arange(n, dtype=np.int64))
    F = np.zeros((n, 3))
    F[1:5, 1] = 0.9
    d = str(tmp_path / "s")
    publish_snapshot(d, step=1, F=F, raw_ids=g.raw_ids, num_edges=4,
                     cfg=BigClamConfig(num_communities=3))
    with MembershipServer(d, graph=g, budget_s=0.001) as server:
        r = server.query({"family": "suggest_for", "u": 0})
        assert r["suggested"][0][0] == 1
        assert r["suggested"][0][1] > 0


def test_server_per_query_errors_do_not_kill_batch(snapdir):
    with MembershipServer(snapdir, budget_s=0.001) as server:
        results = server.run_queries(
            [
                {"family": "members_of", "c": 0},
                {"family": "members_of", "c": 999},       # out of range
                {"family": "communities_of", "u": 10 ** 9},  # unknown id
                {"family": "nope"},                        # unknown family
                {"family": "suggest_for", "u": 0},  # no adjacency wired
            ]
        )
        assert "members" in results[0]
        assert all("error" in r for r in results[1:])
        assert server.stats()["serve_errors"] == 4


def test_hot_swap_changes_members_and_drops_nothing(tmp_path, fitted):
    g, _, cfg, model, res = fitted
    d = str(tmp_path / "s")
    publish_snapshot(d, step=1, F=res.F, raw_ids=g.raw_ids,
                     num_edges=g.num_edges, cfg=cfg)
    with MembershipServer(d, budget_s=0.0005, max_batch=8) as server:
        before = server.query({"family": "members_of", "c": 0})
        assert server.generation == 1
        # a column-rolled F: every community's member list changes
        publish_snapshot(d, step=2, F=np.roll(res.F, 1, axis=1),
                         raw_ids=g.raw_ids, num_edges=g.num_edges,
                         cfg=cfg)
        # fire queries from a background thread WHILE swapping
        n_load = 60
        results = []

        def load():
            results.extend(
                server.run_queries(
                    [{"family": "members_of", "c": i % K}
                     for i in range(n_load)]
                )
            )

        t = threading.Thread(target=load)
        t.start()
        new_step = server.hot_swap()
        t.join(timeout=30.0)
        assert not t.is_alive()
        assert new_step == 2 and server.generation == 2
        # zero drops: every query answered, none errored
        assert len(results) == n_load
        assert all("members" in r for r in results)
        after = server.query({"family": "members_of", "c": 0})
        snap2 = ServingSnapshot.load(d)
        assert after["members"] == snap2.members_of(0).tolist()
        assert snap2.step == 2
        assert server.stats()["snapshot_swaps"] == 1
        # maybe_reload is a no-op when already at latest
        assert server.maybe_reload() is None
        assert before["members"] != after["members"]


def test_serve_telemetry_events_and_report(tmp_path, snapdir, fitted):
    from bigclam_tpu.obs import (
        RunTelemetry,
        install,
        uninstall,
        validate_events_file,
    )
    from bigclam_tpu.obs.report import render

    g, *_ = fitted
    tdir = str(tmp_path / "telem")
    tel = install(RunTelemetry(tdir, entry="serve", quiet=True,
                               device_memory=False))
    try:
        with MembershipServer(snapdir, graph=g, budget_s=0.001) as server:
            server.run_queries(
                [{"family": "members_of", "c": i % K} for i in range(10)]
                + [{"family": "communities_of",
                    "u": int(g.raw_ids[i])} for i in range(5)]
            )
            publish_snapshot(
                snapdir, step=999, F=np.asarray(fitted[4].F),
                raw_ids=g.raw_ids, num_edges=g.num_edges, cfg=fitted[2],
            )
            server.hot_swap()
            tel.set_final(server.stats())
    finally:
        tel.finalize()
        uninstall(tel)
    n, errors = validate_events_file(os.path.join(tdir, "events.jsonl"))
    assert not errors, errors
    with open(os.path.join(tdir, "events.jsonl")) as f:
        kinds = [json.loads(ln)["kind"] for ln in f if ln.strip()]
    assert "serve" in kinds and "snapshot_swap" in kinds
    text, report_errors = render(tdir)
    assert report_errors == 0
    assert "serving: 15 queries" in text
    assert "hot-swaps: 1" in text


# ------------------------------------------------------------- ledger
def _serve_report(p99=0.002, qps=500.0, mix="members_of:1.00"):
    return {
        "run": "r1", "entry": "serve", "pid": 0, "processes": 1,
        "wall_s": 1.0,
        "fingerprint": {"host": "h", "backend": "cpu",
                        "device_kind": "cpu", "platform": "cpu"},
        "compiles": {"count": 0, "by_key": {}},
        "spans": {"seconds": {}},
        "final": {
            "serve_queries": 100, "serve_p50_s": p99 / 2,
            "serve_p99_s": p99, "serve_qps": qps,
            "cache_hit_rate": 0.9, "serve_mix": mix,
        },
    }


def test_ledger_serve_fields_and_p99_verdict():
    from bigclam_tpu.obs import ledger as L

    base = L.build_record(_serve_report())
    assert base["serve_p99_s"] == pytest.approx(0.002)
    assert base["serve_qps"] == pytest.approx(500.0)
    assert base["serve_queries"] == 100
    assert base["cache_hit_rate"] == pytest.approx(0.9)
    assert base["serve_mix"] == "members_of:1.00"
    assert not L.validate_record(base)
    # identical run: PASS
    same = L.build_record(_serve_report())
    d = L.diff_records(base, same)
    assert not d["regression"]
    # 2x p99: REGRESSION (serve p99 IS verdicted, unlike step_p99)
    slow = L.build_record(_serve_report(p99=0.004))
    d = L.diff_records(base, slow)
    assert d["regression"]
    assert any(
        c["metric"] == "serve_p99_s" and c["regression"] and c["verdicted"]
        for c in d["checks"]
    )
    # halved throughput: REGRESSION
    d = L.diff_records(base, L.build_record(_serve_report(qps=200.0)))
    assert d["regression"]


def test_ledger_serve_never_baselines_fit():
    from bigclam_tpu.obs import ledger as L

    serve_rec = L.build_record(_serve_report())
    fit_report = dict(_serve_report())
    fit_report["entry"] = "fit"
    fit_report["final"] = {"llh": -1.0, "n": 10, "edges": 20, "k": 4}
    fit_rec = L.build_record(fit_report)
    assert L.match_key(serve_rec) != L.match_key(fit_rec)
    # different query mixes never cross-baseline either
    other_mix = L.build_record(
        _serve_report(mix="members_of:0.50|suggest_for:0.50")
    )
    assert L.match_key(serve_rec) != L.match_key(other_mix)


# ---------------------------------------------------------------- cli
def test_cli_serve_one_shot(tmp_path, snapdir, fitted, capsys):
    from bigclam_tpu.cli import main

    g, *_ = fitted
    edges = tmp_path / "g.txt"
    with open(edges, "w") as f:
        for u, v in zip(g.src, g.dst):
            if u < v:
                f.write(f"{g.raw_ids[u]}\t{g.raw_ids[v]}\n")
    rc = main(
        [
            "serve", "--snapshots", snapdir, "--graph", str(edges),
            "--query", f"communities_of:{int(g.raw_ids[3])}",
            "--query", "members_of:0",
            "--query", f"suggest_for:{int(g.raw_ids[3])}",
            "--latency-budget-ms", "1",
        ]
    )
    out = capsys.readouterr().out.strip().splitlines()
    assert rc == 0
    stats = json.loads(out[-1])
    assert stats["serve_queries"] == 3 and stats["serve_errors"] == 0
    answers = [json.loads(ln) for ln in out[:-1]]
    assert any("communities" in a for a in answers)
    assert any("members" in a for a in answers)
    assert any("suggested" in a for a in answers)


def test_cli_fit_publishes_snapshot(tmp_path, fitted, capsys):
    from bigclam_tpu.cli import main

    g, *_ = fitted
    edges = tmp_path / "g.txt"
    with open(edges, "w") as f:
        for u, v in zip(g.src, g.dst):
            if u < v:
                f.write(f"{g.raw_ids[u]}\t{g.raw_ids[v]}\n")
    pub = str(tmp_path / "pub")
    rc = main(
        [
            "fit", "--graph", str(edges), "--k", "4", "--max-iters", "30",
            "--init", "random", "--publish-dir", pub, "--quiet",
            "--health-every", "0",
        ]
    )
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["published"].endswith(".npz")
    snap = ServingSnapshot.load(pub)
    assert snap.n == g.num_nodes and snap.k == 4
    # fit publishes the NEXT generation (publish_next, ISSUE 15), not
    # the iteration count — a faster re-fit must still be served
    assert CheckpointManager(pub).latest() == out["generation"] == 1
    rc = main(
        [
            "fit", "--graph", str(edges), "--k", "4", "--max-iters", "10",
            "--init", "random", "--publish-dir", pub, "--quiet",
            "--health-every", "0",
        ]
    )
    assert rc == 0
    out2 = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out2["generation"] == 2
    assert CheckpointManager(pub).latest() == 2


# ------------------------------------- rapid republish (ISSUE 15 sat.)
def test_publish_next_generations_strictly_monotonic_concurrent(
    tmp_path,
):
    """Concurrent publishers (the follow loop racing a manual `cli fit
    --publish-dir`) must take distinct, strictly increasing generations
    — publish_next serializes the step choice under the publish lock."""
    d = str(tmp_path / "snaps")
    steps = []
    lock = threading.Lock()
    errors = []

    def publisher(i):
        try:
            for j in range(5):
                # a fresh manager per call = independent publishers
                s, path = CheckpointManager(d).publish_next(
                    {"F": np.full(3, i * 10 + j, np.float64)}
                )
                with lock:
                    steps.append(s)
        except Exception as e:   # noqa: BLE001
            errors.append(e)

    threads = [
        threading.Thread(target=publisher, args=(i,)) for i in range(4)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    assert len(steps) == 20
    assert len(set(steps)) == 20            # no duplicated generation
    assert sorted(steps) == list(range(1, 21))
    cm = CheckpointManager(d)
    assert cm.latest() == 20
    assert cm.load_published()[0] == 20


def test_publish_pointer_never_moves_backward(tmp_path):
    d = str(tmp_path / "snaps")
    cm = CheckpointManager(d)
    cm.publish(7, {"F": np.ones(2)})
    # a slow publisher losing the race writes an OLDER generation:
    # the archive lands, the pointer must not roll back
    cm.publish(5, {"F": np.zeros(2)})
    assert cm.latest() == 7
    assert 5 in cm.published_steps()        # archive still published
    cm.publish(9, {"F": np.ones(2)})
    assert cm.latest() == 9


def test_serve_watcher_never_swaps_backward(tmp_path, fitted):
    """latest.json racing a newer snap_ archive (or a pointer rolled
    back by a crashed publisher) must never swap a serving generation
    backward."""
    g, truth, cfg, model, res = fitted
    d = str(tmp_path / "snaps")
    for step in (5, 7):
        publish_snapshot(
            d, step=step, F=res.F, raw_ids=g.raw_ids,
            num_edges=g.num_edges, cfg=cfg,
        )
    server = MembershipServer(d, graph=g)
    try:
        assert server.generation == 7
        # simulate the race: pointer names the OLDER generation
        with open(os.path.join(d, "latest.json"), "w") as f:
            json.dump({"step": 5}, f)
        assert server.maybe_reload() is None
        assert server.generation == 7       # never backward
        # a genuinely newer publication still swaps forward
        publish_snapshot(
            d, step=9, F=res.F, raw_ids=g.raw_ids,
            num_edges=g.num_edges, cfg=cfg,
        )
        assert server.maybe_reload() == 9
        assert server.generation == 9
    finally:
        server.close()
