"""Degree-balanced relabeling (parallel/balance.py): permutation validity,
skew reduction on a real power-law graph, and end-to-end invisibility (same
converged model, original-id rows) through the sharded trainer."""

import numpy as np
import pytest

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.models import BigClamModel
from bigclam_tpu.parallel import ShardedBigClamModel, make_mesh
from bigclam_tpu.parallel.balance import (
    balance_graph,
    balance_permutation,
    shard_edge_counts,
)


def test_balance_permutation_is_shard_capacity_respecting(facebook_graph):
    g = facebook_graph
    dp, n_pad = 8, 4040
    perm = balance_permutation(g.degrees, dp, n_pad)
    # a permutation of [0, N)
    assert np.array_equal(np.sort(perm), np.arange(g.num_nodes))
    # per-shard node counts match the contiguous id ranges exactly
    rows = n_pad // dp
    counts = np.bincount(perm // rows, minlength=dp)
    expected = np.minimum(np.arange(1, dp + 1) * rows, g.num_nodes) - np.minimum(
        np.arange(dp) * rows, g.num_nodes
    )
    np.testing.assert_array_equal(counts, expected)


def test_balance_reduces_edge_skew(facebook_graph):
    """facebook_combined is an ego-net union: hubs sit at low ids, so
    contiguous sharding is badly skewed; LPT must flatten it."""
    g = facebook_graph
    dp, n_pad = 8, 4040
    before = shard_edge_counts(g, dp, n_pad)
    g_bal, _ = balance_graph(g, dp, n_pad)
    after = shard_edge_counts(g_bal, dp, n_pad)
    assert after.sum() == before.sum() == g.num_directed_edges
    skew_before = before.max() / before.mean()
    skew_after = after.max() / after.mean()
    assert skew_before > 1.5          # the problem is real on this graph
    assert skew_after < 1.05          # and LPT solves it
    assert skew_after < skew_before


def test_permute_roundtrip_preserves_structure(toy_graphs):
    g = toy_graphs["two_cliques"]
    rng = np.random.default_rng(0)
    perm = rng.permutation(g.num_nodes)
    gp = g.permute(perm)
    gp.validate()
    np.testing.assert_array_equal(gp.degrees[perm], g.degrees)
    np.testing.assert_array_equal(gp.raw_ids[perm], g.raw_ids)
    for u in range(g.num_nodes):
        np.testing.assert_array_equal(
            np.sort(perm[g.neighbors(u)]), gp.neighbors(perm[u])
        )


def test_balanced_trainer_matches_unbalanced(agm_graph_mod):
    """balance=True must be invisible: same trajectory (up to float summation
    order) with rows returned in original ids."""
    import jax

    g = agm_graph_mod
    cfg = BigClamConfig(
        num_communities=4, dtype="float64", max_iters=6, conv_tol=0.0
    )
    rng = np.random.default_rng(1)
    F0 = rng.uniform(0.1, 1.0, size=(g.num_nodes, 4))
    mesh = make_mesh((4, 2), jax.devices())
    res_plain = ShardedBigClamModel(g, cfg, mesh).fit(F0)
    res_bal = ShardedBigClamModel(g, cfg, mesh, balance=True).fit(F0)
    np.testing.assert_allclose(res_bal.F, res_plain.F, rtol=1e-9, atol=1e-12)
    np.testing.assert_allclose(res_bal.llh, res_plain.llh, rtol=1e-11)


def test_balanced_ring_matches_single_chip(agm_graph_mod):
    import jax

    from bigclam_tpu.parallel import RingBigClamModel

    g = agm_graph_mod
    cfg = BigClamConfig(
        num_communities=4, dtype="float64", max_iters=4, conv_tol=0.0
    )
    rng = np.random.default_rng(2)
    F0 = rng.uniform(0.1, 1.0, size=(g.num_nodes, 4))
    res_1 = BigClamModel(g, cfg).fit(F0)
    mesh = make_mesh((8, 1), jax.devices())
    res_r = RingBigClamModel(g, cfg, mesh, balance=True).fit(F0)
    np.testing.assert_allclose(res_r.F, res_1.F, rtol=1e-9, atol=1e-12)


def test_balanced_checkpoint_mismatch_rejected(tmp_path, agm_graph_mod):
    """A checkpoint written by a balanced run stores internal row order; a
    non-balanced run must refuse to restore it."""
    import jax

    from bigclam_tpu.utils import CheckpointManager

    g = agm_graph_mod
    cfg = BigClamConfig(
        num_communities=4, dtype="float64", max_iters=3, conv_tol=0.0,
        checkpoint_every=1,
    )
    rng = np.random.default_rng(3)
    F0 = rng.uniform(0.1, 1.0, size=(g.num_nodes, 4))
    mesh = make_mesh((4, 1), jax.devices()[:4])
    ckpt = CheckpointManager(str(tmp_path))
    ShardedBigClamModel(g, cfg, mesh, balance=True).fit(F0, checkpoints=ckpt)
    with pytest.raises(ValueError, match="balanced"):
        ShardedBigClamModel(g, cfg, mesh, balance=False).fit(
            F0, checkpoints=CheckpointManager(str(tmp_path))
        )


def test_balanced_checkpoint_dp_mismatch_rejected(tmp_path, agm_graph_mod):
    """Balanced internal row order depends on the node-shard count; resuming
    a balanced checkpoint on a different dp (same n_pad/k_pad) must fail
    rather than restore scrambled rows."""
    import jax

    from bigclam_tpu.utils import CheckpointManager

    g = agm_graph_mod
    cfg = BigClamConfig(
        num_communities=4, dtype="float64", max_iters=2, conv_tol=0.0,
        checkpoint_every=1,
    )
    rng = np.random.default_rng(4)
    F0 = rng.uniform(0.1, 1.0, size=(g.num_nodes, 4))
    # dp=4 and dp=8 both give n_pad=48 here, so only node_shards differs
    mesh4 = make_mesh((4, 1), jax.devices()[:4])
    ckpt = CheckpointManager(str(tmp_path))
    ShardedBigClamModel(g, cfg, mesh4, balance=True).fit(F0, checkpoints=ckpt)
    mesh8 = make_mesh((8, 1), jax.devices())
    with pytest.raises(ValueError, match="node_shards"):
        ShardedBigClamModel(g, cfg, mesh8, balance=True).fit(
            F0, checkpoints=CheckpointManager(str(tmp_path))
        )


def test_checkpoint_missing_falsy_meta_key_accepted(tmp_path, agm_graph_mod):
    """Checkpoints written before a falsy meta key existed must still
    restore (missing key == implicit default)."""
    import jax

    from bigclam_tpu.utils import CheckpointManager

    g = agm_graph_mod
    cfg = BigClamConfig(
        num_communities=4, dtype="float64", max_iters=2, conv_tol=0.0,
        checkpoint_every=1,
    )
    rng = np.random.default_rng(6)
    F0 = rng.uniform(0.1, 1.0, size=(g.num_nodes, 4))
    mesh = make_mesh((4, 1), jax.devices()[:4])
    ckpt = CheckpointManager(str(tmp_path))
    ShardedBigClamModel(g, cfg, mesh).fit(F0, checkpoints=ckpt)
    # simulate an old checkpoint: strip the newer meta keys
    import json, pathlib

    for meta_file in pathlib.Path(tmp_path).glob("*.json"):
        meta = json.loads(meta_file.read_text())
        meta.pop("balanced", None)
        meta.pop("node_shards", None)
        meta_file.write_text(json.dumps(meta))
    res = ShardedBigClamModel(g, cfg, mesh).fit(
        F0, checkpoints=CheckpointManager(str(tmp_path))
    )
    assert res.num_iters >= 2


@pytest.fixture(scope="module")
def agm_graph_mod():
    from bigclam_tpu.models.agm import planted_partition_F, sample_graph

    rng = np.random.default_rng(11)
    Fp, _ = planted_partition_F(48, 4, strength=1.5)
    return sample_graph(Fp, rng=rng)
