"""Shard-count invariance tests (SURVEY.md §4.4) on the 8-device CPU fake:
the sharded trajectory must equal the single-chip trajectory for every mesh
shape — sharding changes the schedule, not the math."""

import numpy as np
import pytest

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.models import BigClamModel
from bigclam_tpu.models.agm import planted_partition_F, sample_graph
from bigclam_tpu.parallel import ShardedBigClamModel, make_mesh


CFG = BigClamConfig(num_communities=4, dtype="float64", max_iters=4, conv_tol=0.0)


@pytest.fixture(scope="module")
def agm_graph():
    rng = np.random.default_rng(7)
    Fp, _ = planted_partition_F(48, 4, strength=1.5)
    return sample_graph(Fp, rng=rng)


def _reference_run(g, cfg, F0, iters):
    model = BigClamModel(g, cfg)
    state = model.init_state(F0)
    llhs = []
    for _ in range(iters):
        state = model._step(state)
        llhs.append(float(state.llh))
    return np.asarray(state.F), llhs


@pytest.mark.parametrize("mesh_shape", [(1, 1), (2, 1), (4, 1), (8, 1), (2, 2), (1, 4), (4, 2)])
def test_shard_invariance(agm_graph, mesh_shape):
    import jax

    g = agm_graph
    rng = np.random.default_rng(0)
    F0 = rng.uniform(0.1, 1.0, size=(g.num_nodes, 4))
    F_ref, llh_ref = _reference_run(g, CFG, F0, 4)

    mesh = make_mesh(mesh_shape, jax.devices()[: mesh_shape[0] * mesh_shape[1]])
    sharded = ShardedBigClamModel(g, CFG, mesh)
    state = sharded.init_state(F0)
    llhs = []
    for _ in range(4):
        state = sharded._step(state)
        llhs.append(float(state.llh))
    n = g.num_nodes
    np.testing.assert_allclose(
        np.asarray(state.F)[:n, :4], F_ref[:n, :4], rtol=1e-11,
        err_msg=f"mesh {mesh_shape}",
    )
    np.testing.assert_allclose(llhs, llh_ref, rtol=1e-11)


def test_sharded_fit_matches_single_chip(toy_graphs):
    g = toy_graphs["two_cliques"]
    cfg = BigClamConfig(num_communities=2, dtype="float64", max_iters=50)
    rng = np.random.default_rng(3)
    F0 = rng.uniform(0.1, 1.0, size=(g.num_nodes, 2))
    import jax

    mesh = make_mesh((4, 2), jax.devices())
    res_s = ShardedBigClamModel(g, cfg, mesh).fit(F0)
    res_1 = BigClamModel(g, cfg).fit(F0)
    assert res_s.num_iters == res_1.num_iters
    np.testing.assert_allclose(res_s.F, res_1.F, rtol=1e-10)
    assert np.isclose(res_s.llh, res_1.llh, rtol=1e-12)


def test_edge_sharding_partition(agm_graph):
    """Every real directed edge appears exactly once across shards with a
    correctly rebased local src."""
    from bigclam_tpu.parallel.sharded import shard_edges

    g = agm_graph
    dp = 4
    n_pad = 48
    e = shard_edges(g, CFG, dp, n_pad, np.float64)
    shard_rows = n_pad // dp
    seen = []
    for i in range(dp):
        s = e.src[i].reshape(-1)
        d = e.dst[i].reshape(-1)
        m = e.mask[i].reshape(-1) > 0
        seen.append(
            np.stack([s[m] + i * shard_rows, d[m]], axis=1)
        )
    seen = np.concatenate(seen, axis=0)
    ref = np.stack([g.src, g.dst], axis=1)
    order = np.lexsort((seen[:, 1], seen[:, 0]))
    np.testing.assert_array_equal(seen[order], ref)
