"""Seeding tests (SURVEY.md §4.3): hand-computed conductance on toy graphs,
locally-minimal ranking order, isolated-node sentinel, init_F structure."""

import numpy as np
import pytest

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.graph.ingest import graph_from_edges
from bigclam_tpu.ops import seeding


CFG = BigClamConfig()


def test_conductance_triangle(toy_graphs):
    # ego-net of every node is the whole triangle: cut=0, vol_T=0 -> phi=1
    phi = seeding.conductance(toy_graphs["triangle"], backend="numpy")
    np.testing.assert_allclose(phi, [1.0, 1.0, 1.0])


def test_conductance_star(toy_graphs):
    # center: ego = whole graph -> vol_T=0 -> 1; leaf u: S={u,center},
    # z = {center} + 4 leaves, cut=3, vol_S=2, vol_T=8-2-6=0 -> phi=1
    phi = seeding.conductance(toy_graphs["star"], backend="numpy")
    np.testing.assert_allclose(phi, [1.0, 1.0, 1.0, 1.0, 1.0])


def test_conductance_two_cliques(toy_graphs):
    # hand-derived (see closed forms in ops/seeding.py docstring):
    # interior clique node: cut=1 (bridge), vol_S=12, vol_T=12 -> 1/12
    # bridge endpoint (deg 4): cut=3, vol_S=14, vol_T=6 -> 3/6 = 0.5
    phi = seeding.conductance(toy_graphs["two_cliques"], backend="numpy")
    expect = [1 / 12, 1 / 12, 1 / 12, 0.5, 0.5, 1 / 12, 1 / 12, 1 / 12]
    np.testing.assert_allclose(phi, expect)


def test_dense_device_backend_matches_numpy(toy_graphs, facebook_graph):
    for g in [*toy_graphs.values(), facebook_graph]:
        tri_np = seeding.triangle_counts(g)
        tri_dev = seeding.triangle_counts_dense_device(g)
        np.testing.assert_array_equal(tri_np, tri_dev)


def test_rank_seeds_two_cliques(toy_graphs):
    g = toy_graphs["two_cliques"]
    phi = seeding.conductance(g, backend="numpy")
    seeds = seeding.rank_seeds(g, phi, CFG)
    # nominees: clique interiors nominate each other's minima -> {0,1,5,6},
    # ranked by (phi, id)
    np.testing.assert_array_equal(seeds, [0, 1, 5, 6])


def test_rank_seeds_isolated_sentinel():
    # node 2 exists (explicit num_nodes) but has no edges: nominates itself
    # at sentinel phi=10 and ranks last (bigclamv3-7.scala:51)
    g = graph_from_edges([(0, 1)], num_nodes=3)
    phi = seeding.conductance(g, backend="numpy")
    seeds = seeding.rank_seeds(g, phi, CFG)
    assert seeds[-1] == 2
    assert set(seeds.tolist()) <= {0, 1, 2}


def test_init_F_ego_indicator(toy_graphs):
    g = toy_graphs["two_cliques"]
    cfg = CFG.replace(num_communities=3, seed=7)
    F = seeding.init_F(g, np.array([0, 5]), cfg)
    # column 0 = ego-net of 0 = {0,1,2,3}; column 1 = ego-net of 5 = {4..7}
    np.testing.assert_array_equal(F[:, 0], [1, 1, 1, 1, 0, 0, 0, 0])
    np.testing.assert_array_equal(F[:, 1], [0, 0, 0, 0, 1, 1, 1, 1])
    # padded column is Bernoulli {0,1}
    assert set(np.unique(F[:, 2]).tolist()) <= {0.0, 1.0}


def test_init_F_v3_variant(toy_graphs):
    g = toy_graphs["star"]
    cfg = CFG.replace(num_communities=1, seed_include_self=False)
    F = seeding.init_F(g, np.array([0]), cfg)
    # neighbor-only indicator: center excluded
    np.testing.assert_array_equal(F[:, 0], [0, 1, 1, 1, 1])


def test_init_F_truncates_seeds(toy_graphs):
    g = toy_graphs["triangle"]
    cfg = CFG.replace(num_communities=2)
    F = seeding.init_F(g, np.array([0, 1, 2]), cfg)  # 3 seeds, K=2
    assert F.shape == (3, 2)


def test_seeded_fit_beats_random_init(toy_graphs):
    """Integration: conductance-seeded init on two_cliques recovers the two
    planted communities after thresholding-free inspection of F columns."""
    from bigclam_tpu.models import BigClamModel

    g = toy_graphs["two_cliques"]
    # seeds rank [0,1,5,6]: 0,1 seed the left clique's ego-net, 5,6 the
    # right's — K=4 gives each clique at least one dedicated column
    cfg = BigClamConfig(num_communities=4, dtype="float64", max_iters=30)
    seeds = seeding.conductance_seeds(g, cfg, backend="numpy")
    F0 = seeding.init_F(g, seeds, cfg)
    res = BigClamModel(g, cfg).fit(F0)
    left = set(res.F[:4].argmax(axis=1).tolist())
    right = set(res.F[4:].argmax(axis=1).tolist())
    assert left <= {0, 1} and right <= {2, 3}


class TestSampledTriangles:
    """Degree-capped conductance estimator (SURVEY.md §7 'Seeding at
    Friendster scale'): exact when cap >= max degree, rank-preserving
    approximation below it."""

    def test_exact_when_cap_covers_max_degree(self, facebook_graph):
        g = facebook_graph
        exact = seeding.triangle_counts(g)
        cap = int(g.degrees.max())
        samp = seeding.triangle_counts_sampled(g, cap, np.random.default_rng(1))
        np.testing.assert_allclose(samp, exact.astype(float), rtol=0, atol=1e-9)

    def test_exact_small_chunks(self, toy_graphs):
        # the NumPy fallback path, chunked: chunking must not change results
        g = toy_graphs["two_cliques"]
        exact = seeding.triangle_counts(g)
        samp = seeding.triangle_counts_sampled(
            g, 10, np.random.default_rng(0), chunk_entries=4, use_native=False
        )
        np.testing.assert_allclose(samp, exact.astype(float), atol=1e-9)

    def test_numpy_fallback_exact_when_uncapped(self, facebook_graph):
        g = facebook_graph
        exact = seeding.triangle_counts(g)
        cap = int(g.degrees.max())
        samp = seeding.triangle_counts_sampled(
            g, cap, np.random.default_rng(1), use_native=False
        )
        np.testing.assert_allclose(samp, exact.astype(float), atol=1e-9)

    def test_sampled_ranking_correlates(self, facebook_graph):
        g = facebook_graph
        phi_exact = seeding.conductance(g, backend="numpy")
        phi_samp = seeding.conductance(
            g, backend="sampled", degree_cap=64, rng=np.random.default_rng(2)
        )
        # Spearman rank correlation over all nodes
        def ranks(x):
            r = np.empty_like(x)
            r[np.argsort(x, kind="stable")] = np.arange(len(x))
            return r
        rx, ry = ranks(phi_exact), ranks(phi_samp)
        rho = np.corrcoef(rx, ry)[0, 1]
        assert rho > 0.9, rho

    def test_deterministic_given_seed(self, facebook_graph):
        g = facebook_graph
        a = seeding.triangle_counts_sampled(g, 32, np.random.default_rng(7))
        b = seeding.triangle_counts_sampled(g, 32, np.random.default_rng(7))
        np.testing.assert_array_equal(a, b)

    def test_auto_backend_uses_cap(self, facebook_graph):
        cfg = BigClamConfig(seeding_degree_cap=32, num_communities=10)
        seeds = seeding.conductance_seeds(facebook_graph, cfg)
        assert len(np.unique(seeds)) == len(seeds) > 0

    def test_sampled_phi_stays_in_domain(self, monkeypatch):
        # estimator noise must not push phi out of [0, 1]-ish domain —
        # exercised on BOTH the native and the NumPy fallback estimator
        rng = np.random.default_rng(3)
        n = 300
        a = rng.random((n, n)) < 0.05
        edges = [(i, j) for i in range(n) for j in range(i + 1, n) if a[i, j]]
        g = graph_from_edges(edges, num_nodes=n)
        for use_native in (True, False):
            if not use_native:
                try:
                    import bigclam_tpu.graph.native as native_mod
                except ImportError:
                    pass            # no toolchain: both legs are NumPy
                else:
                    monkeypatch.delattr(native_mod, "triangle_counts_capped")
            phi = seeding.conductance(
                g, backend="sampled", degree_cap=4,
                rng=np.random.default_rng(4),
            )
            assert (phi >= 0).all(), (use_native, phi.min())

    def test_native_and_numpy_backends_agree_under_cap(self, facebook_graph):
        """Backend independence (ADVICE rounds 1-2): with the cap BINDING
        (cap < max degree), the native and NumPy estimators must see the
        same splitmix64-sampled capped lists and return the same estimates
        — same config can never yield different seed rankings depending on
        whether the .so built."""
        pytest.importorskip("bigclam_tpu.graph.native")
        from bigclam_tpu.graph import native as native_mod

        if not hasattr(native_mod, "_lib") or native_mod._lib is None:
            pytest.skip("native library not built")
        g = facebook_graph
        cap = 32
        assert int(g.degrees.max()) > cap
        a = seeding.triangle_counts_sampled(
            g, cap, np.random.default_rng(7), use_native=True
        )
        b = seeding.triangle_counts_sampled(
            g, cap, np.random.default_rng(7), use_native=False
        )
        # same multiset of hit weights, different summation order
        np.testing.assert_allclose(a, b, rtol=1e-9, atol=1e-9)
        # and therefore identical rankings
        cfg = BigClamConfig(num_communities=10, seeding_degree_cap=cap)
        phi_a = seeding.conductance(
            g, backend="sampled", degree_cap=cap, rng=np.random.default_rng(7)
        )
        ra = seeding.rank_seeds(g, phi_a, cfg)
        import bigclam_tpu.graph.native as nm

        tc = nm.triangle_counts_capped
        try:
            del nm.triangle_counts_capped
            phi_b = seeding.conductance(
                g, backend="sampled", degree_cap=cap,
                rng=np.random.default_rng(7),
            )
        finally:
            nm.triangle_counts_capped = tc
        rb = seeding.rank_seeds(g, phi_b, cfg)
        np.testing.assert_array_equal(ra, rb)

    def test_device_backend_matches_host(self, facebook_graph):
        """The device two-hop sweep (C5 past the 16K dense bound) shares
        the host estimator's capped lists and weights: same estimates (to
        f32 weight rounding), same rankings; exact when cap >= max deg."""
        g = facebook_graph
        cap = 32
        host = seeding.triangle_counts_sampled(
            g, cap, np.random.default_rng(7), use_native=False
        )
        seed = int(np.random.default_rng(7).integers(2**63))
        dev = seeding.triangle_counts_sampled_device(g, cap, seed)
        np.testing.assert_allclose(dev, host, rtol=2e-5, atol=2e-5)
        phi_h = seeding.conductance(
            g, backend="sampled", degree_cap=cap,
            rng=np.random.default_rng(7),
        )
        phi_d = seeding.conductance(
            g, backend="sampled_device", degree_cap=cap,
            rng=np.random.default_rng(7),
        )
        cfg = BigClamConfig(num_communities=10)
        np.testing.assert_array_equal(
            seeding.rank_seeds(g, phi_h, cfg),
            seeding.rank_seeds(g, phi_d, cfg),
        )
        # exactness flag: cap >= max degree reduces to the exact counts
        # (small graph — the facebook hub degree of 1045 makes this leg
        # O(N * maxdeg^2) and minutes-slow on the CPU fake)
        rng = np.random.default_rng(3)
        ns = 300
        a = rng.random((ns, ns)) < 0.08
        gs = graph_from_edges(
            [(i, j) for i in range(ns) for j in range(i + 1, ns) if a[i, j]],
            num_nodes=ns,
        )
        cap_full = int(gs.degrees.max())
        exact = seeding.triangle_counts(gs)
        dev_full = seeding.triangle_counts_sampled_device(gs, cap_full, 0)
        np.testing.assert_allclose(dev_full, exact.astype(float), atol=1e-6)

    def test_conductance_accepts_precomputed_tri(self, toy_graphs):
        g = toy_graphs["two_cliques"]
        tri = seeding.triangle_counts(g)
        a = seeding.conductance(g, backend="numpy")
        b = seeding.conductance(g, tri=tri.astype(np.float64))
        np.testing.assert_allclose(a, b, rtol=0, atol=0)

    def test_chunk_of_isolated_tail_nodes(self):
        # chunk boundary landing after the last edge-bearing node (NumPy path)
        g = graph_from_edges(
            [(0, 1), (0, 2), (0, 3), (1, 2), (1, 3), (2, 3)], num_nodes=6
        )
        out = seeding.triangle_counts_sampled(
            g, 10, np.random.default_rng(0), chunk_entries=6, use_native=False
        )
        np.testing.assert_allclose(out[:4], 3.0)
        np.testing.assert_allclose(out[4:], 0.0)


class TestSeedExclusion:
    """Coverage-aware seed selection (select_seeds_covering; quality mode's
    seeding rule — not reference behavior, which takes the raw top-K
    nominee ranking, Bigclamv2.scala:56)."""

    @pytest.fixture(scope="class")
    def planted(self):
        from bigclam_tpu.models.agm import sample_planted_graph

        rng = np.random.default_rng(7)
        n, k = 1200, 50                       # 24-node blocks, p_in=0.3
        g, truth = sample_planted_graph(n, k, p_in=0.3, rng=rng)
        return g, truth, n, k

    def _coverage(self, seeds, k, size):
        return len(set(int(s) // size for s in np.asarray(seeds)[:k]))

    def test_covers_more_blocks_than_raw_ranking(self, planted):
        g, truth, n, k = planted
        phi = seeding.conductance(g, backend="numpy")
        raw = seeding.rank_seeds(g, phi, CFG)
        cov = seeding.select_seeds_covering(g, phi, k, CFG, hops=2)
        size = n // k
        c_raw = self._coverage(raw, k, size)
        c_cov = self._coverage(cov, k, size)
        assert len(cov) == k
        assert c_cov > c_raw, (c_cov, c_raw)
        assert c_cov >= int(0.85 * k), (c_cov, k)

    def test_hops1_exclusion_invariant(self, planted):
        # at hops=1 no chosen seed may lie inside an earlier seed's ego-net
        g, truth, n, k = planted
        phi = seeding.conductance(g, backend="numpy")
        sel = seeding.select_seeds_covering(g, phi, k, CFG, hops=1)
        covered = np.zeros(n, dtype=bool)
        for s in sel:
            assert not covered[s]
            covered[s] = True
            covered[g.neighbors(int(s))] = True

    def test_falls_back_past_nominees(self):
        # a path graph nominates few locally-minimal nodes; the covering
        # walk must continue over non-nominees to reach k seeds
        g = graph_from_edges([(i, i + 1) for i in range(11)], num_nodes=12)
        phi = seeding.conductance(g, backend="numpy")
        sel = seeding.select_seeds_covering(g, phi, 4, CFG, hops=1)
        assert len(sel) == 4
        assert len(set(sel.tolist())) == 4

    def test_auto_on_iff_quality_mode(self, planted):
        g, truth, n, k = planted
        cfg_q = BigClamConfig(num_communities=k, quality_mode=True)
        cfg_p = BigClamConfig(num_communities=k)
        phi = seeding.conductance(
            g, degree_cap=cfg_q.seeding_degree_cap,
            rng=np.random.default_rng(cfg_q.seed),
        )
        np.testing.assert_array_equal(
            seeding.conductance_seeds(g, cfg_q),
            seeding.select_seeds_covering(g, phi, k, cfg_q, hops=2),
        )
        np.testing.assert_array_equal(
            seeding.conductance_seeds(g, cfg_p), seeding.rank_seeds(g, phi, cfg_p)
        )
        # and the flag overrides the auto rule in both directions
        np.testing.assert_array_equal(
            seeding.conductance_seeds(g, cfg_p.replace(seed_exclusion=True)),
            seeding.select_seeds_covering(g, phi, k, cfg_p, hops=2),
        )
        np.testing.assert_array_equal(
            seeding.conductance_seeds(g, cfg_q.replace(seed_exclusion=False)),
            seeding.rank_seeds(g, phi, cfg_q),
        )
