"""Seeding tests (SURVEY.md §4.3): hand-computed conductance on toy graphs,
locally-minimal ranking order, isolated-node sentinel, init_F structure."""

import numpy as np

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.graph.ingest import graph_from_edges
from bigclam_tpu.ops import seeding


CFG = BigClamConfig()


def test_conductance_triangle(toy_graphs):
    # ego-net of every node is the whole triangle: cut=0, vol_T=0 -> phi=1
    phi = seeding.conductance(toy_graphs["triangle"], backend="numpy")
    np.testing.assert_allclose(phi, [1.0, 1.0, 1.0])


def test_conductance_star(toy_graphs):
    # center: ego = whole graph -> vol_T=0 -> 1; leaf u: S={u,center},
    # z = {center} + 4 leaves, cut=3, vol_S=2, vol_T=8-2-6=0 -> phi=1
    phi = seeding.conductance(toy_graphs["star"], backend="numpy")
    np.testing.assert_allclose(phi, [1.0, 1.0, 1.0, 1.0, 1.0])


def test_conductance_two_cliques(toy_graphs):
    # hand-derived (see closed forms in ops/seeding.py docstring):
    # interior clique node: cut=1 (bridge), vol_S=12, vol_T=12 -> 1/12
    # bridge endpoint (deg 4): cut=3, vol_S=14, vol_T=6 -> 3/6 = 0.5
    phi = seeding.conductance(toy_graphs["two_cliques"], backend="numpy")
    expect = [1 / 12, 1 / 12, 1 / 12, 0.5, 0.5, 1 / 12, 1 / 12, 1 / 12]
    np.testing.assert_allclose(phi, expect)


def test_dense_device_backend_matches_numpy(toy_graphs, facebook_graph):
    for g in [*toy_graphs.values(), facebook_graph]:
        tri_np = seeding.triangle_counts(g)
        tri_dev = seeding.triangle_counts_dense_device(g)
        np.testing.assert_array_equal(tri_np, tri_dev)


def test_rank_seeds_two_cliques(toy_graphs):
    g = toy_graphs["two_cliques"]
    phi = seeding.conductance(g, backend="numpy")
    seeds = seeding.rank_seeds(g, phi, CFG)
    # nominees: clique interiors nominate each other's minima -> {0,1,5,6},
    # ranked by (phi, id)
    np.testing.assert_array_equal(seeds, [0, 1, 5, 6])


def test_rank_seeds_isolated_sentinel():
    # node 2 exists (explicit num_nodes) but has no edges: nominates itself
    # at sentinel phi=10 and ranks last (bigclamv3-7.scala:51)
    g = graph_from_edges([(0, 1)], num_nodes=3)
    phi = seeding.conductance(g, backend="numpy")
    seeds = seeding.rank_seeds(g, phi, CFG)
    assert seeds[-1] == 2
    assert set(seeds.tolist()) <= {0, 1, 2}


def test_init_F_ego_indicator(toy_graphs):
    g = toy_graphs["two_cliques"]
    cfg = CFG.replace(num_communities=3, seed=7)
    F = seeding.init_F(g, np.array([0, 5]), cfg)
    # column 0 = ego-net of 0 = {0,1,2,3}; column 1 = ego-net of 5 = {4..7}
    np.testing.assert_array_equal(F[:, 0], [1, 1, 1, 1, 0, 0, 0, 0])
    np.testing.assert_array_equal(F[:, 1], [0, 0, 0, 0, 1, 1, 1, 1])
    # padded column is Bernoulli {0,1}
    assert set(np.unique(F[:, 2]).tolist()) <= {0.0, 1.0}


def test_init_F_v3_variant(toy_graphs):
    g = toy_graphs["star"]
    cfg = CFG.replace(num_communities=1, seed_include_self=False)
    F = seeding.init_F(g, np.array([0]), cfg)
    # neighbor-only indicator: center excluded
    np.testing.assert_array_equal(F[:, 0], [0, 1, 1, 1, 1])


def test_init_F_truncates_seeds(toy_graphs):
    g = toy_graphs["triangle"]
    cfg = CFG.replace(num_communities=2)
    F = seeding.init_F(g, np.array([0, 1, 2]), cfg)  # 3 seeds, K=2
    assert F.shape == (3, 2)


def test_seeded_fit_beats_random_init(toy_graphs):
    """Integration: conductance-seeded init on two_cliques recovers the two
    planted communities after thresholding-free inspection of F columns."""
    from bigclam_tpu.models import BigClamModel

    g = toy_graphs["two_cliques"]
    # seeds rank [0,1,5,6]: 0,1 seed the left clique's ego-net, 5,6 the
    # right's — K=4 gives each clique at least one dedicated column
    cfg = BigClamConfig(num_communities=4, dtype="float64", max_iters=30)
    seeds = seeding.conductance_seeds(g, cfg, backend="numpy")
    F0 = seeding.init_F(g, seeds, cfg)
    res = BigClamModel(g, cfg).fit(F0)
    left = set(res.F[:4].argmax(axis=1).tolist())
    right = set(res.F[4:].argmax(axis=1).tolist())
    assert left <= {0, 1} and right <= {2, 3}
