"""Fault-tolerant fit orchestration tests (ISSUE 5, bigclam_tpu/resilience):
deterministic fault injection, classified retry/backoff, non-finite
rollback, checkpoint payload integrity + corruption-safe rotation, shard
quarantine + re-ingest, heartbeat escalation, resume lineage in `cli
report`, and the kill -9 -> `--resume auto` bit-identity contract."""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import jax

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.graph.ingest import build_graph
from bigclam_tpu.graph.store import (
    GraphStore,
    ShardCorruption,
    compile_graph_cache,
)
from bigclam_tpu.models import BigClamModel
from bigclam_tpu.obs import RunTelemetry, install, uninstall
from bigclam_tpu.obs.telemetry import EVENTS_NAME
from bigclam_tpu.obs.schema import validate_events_file
from bigclam_tpu.resilience import (
    FatalError,
    FaultPlan,
    RetryPolicy,
    Supervisor,
    TransientError,
    call_with_retry,
    classify,
    install_plan,
    record_resume,
)
from bigclam_tpu.utils import CheckpointManager

pytestmark = pytest.mark.chaos


def _problem(toy_graphs, k=2, max_iters=8, **kw):
    g = toy_graphs["two_cliques"]
    cfg = BigClamConfig(
        num_communities=k, dtype="float64", max_iters=max_iters,
        conv_tol=0.0, **kw,
    )
    F0 = np.random.default_rng(5).uniform(0.1, 1.0, size=(g.num_nodes, k))
    return g, cfg, F0


def _events(directory):
    with open(os.path.join(directory, EVENTS_NAME)) as f:
        return [json.loads(line) for line in f if line.strip()]


@pytest.fixture
def telem(tmp_path):
    tel = install(RunTelemetry(str(tmp_path / "telem"), entry="test"))
    try:
        yield tel
    finally:
        tel.finalize()
        uninstall(tel)


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    yield
    install_plan(None)


# --------------------------------------------------------------------------
# fault harness
# --------------------------------------------------------------------------


def test_fault_plan_matching_is_deterministic_and_consumed():
    plan = FaultPlan(
        [
            {"kind": "delay", "site": "fit.step", "at": 2, "seconds": 0.0},
            {"kind": "corrupt_shard", "site": "store.load_shard",
             "shard": 1},
        ]
    )
    assert plan.fire("fit.step", it=0) is None
    assert plan.fire("fit.step", it=1) is None
    fired = plan.fire("fit.step", it=2)
    assert fired["kind"] == "delay"
    assert plan.fire("fit.step", it=2) is None          # consumed
    # context-key matching: shard 0 passes untouched, shard 1 fires
    assert plan.fire("store.load_shard", shard=0) is None
    assert plan.fire("store.load_shard", shard=1)["kind"] == "corrupt_shard"


def test_fault_plan_env_round_trip(tmp_path, monkeypatch):
    spec = {"seed": 7, "faults": [{"kind": "kill", "site": "fit.step",
                                   "at": 3}]}
    p = tmp_path / "plan.json"
    p.write_text(json.dumps(spec))
    monkeypatch.setenv("BIGCLAM_FAULTS", f"@{p}")
    plan = FaultPlan.from_env()
    assert plan.seed == 7 and plan.faults[0]["at"] == 3
    monkeypatch.setenv("BIGCLAM_FAULTS", json.dumps(spec))
    assert FaultPlan.from_env().faults == plan.faults


def test_file_faults_truncate_and_corrupt(tmp_path):
    p = tmp_path / "blob.bin"
    p.write_bytes(bytes(range(256)))
    plan = FaultPlan([])
    plan.apply_to_file({"kind": "truncate_checkpoint", "frac": 0.25},
                       str(p))
    assert os.path.getsize(p) == 64
    before = p.read_bytes()
    plan.apply_to_file({"kind": "corrupt_shard", "offset": 10}, str(p))
    after = p.read_bytes()
    assert after[10] == before[10] ^ 0xFF
    assert after[:10] == before[:10] and after[11:] == before[11:]


# --------------------------------------------------------------------------
# retry / classification
# --------------------------------------------------------------------------


def test_classify_taxonomy():
    assert classify(OSError("disk hiccup")) == "transient"
    assert classify(TransientError("wrapped")) == "transient"
    assert classify(ValueError("shape mismatch")) == "fatal"
    assert classify(FloatingPointError("nan")) == "fatal"
    assert classify(FatalError("no")) == "fatal"
    assert classify(ShardCorruption("crc", shard=1)) == "fatal"


def test_retry_recovers_and_emits_events(telem):
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError(f"transient #{calls['n']}")
        return "ok"

    slept = []
    out = call_with_retry(
        flaky, "unit", RetryPolicy(transient_attempts=5, base_s=0.01),
        sleep=slept.append,
    )
    assert out == "ok" and calls["n"] == 3
    assert len(slept) == 2 and slept[1] > slept[0] > 0
    kinds = [e["kind"] for e in _events(telem.directory)]
    assert kinds.count("retry") == 2 and kinds.count("recovered") == 1


def test_retry_gives_up_after_budget_and_never_retries_fatal(telem):
    def always(exc):
        def fn():
            raise exc
        return fn

    with pytest.raises(OSError):
        call_with_retry(
            always(OSError("down")), "unit-t",
            RetryPolicy(transient_attempts=3, base_s=0.0),
            sleep=lambda s: None,
        )
    calls = {"n": 0}

    def fatal():
        calls["n"] += 1
        raise ValueError("config mismatch")

    with pytest.raises(ValueError):
        call_with_retry(fatal, "unit-f", RetryPolicy(), sleep=lambda s: None)
    assert calls["n"] == 1                       # fatal: exactly one attempt
    gave = [e for e in _events(telem.directory) if e["kind"] == "gave_up"]
    assert {e["site"] for e in gave} == {"unit-t", "unit-f"}
    assert gave[0]["attempts"] == 3


def test_retry_backoff_is_deterministic():
    slept_a, slept_b = [], []
    for slept in (slept_a, slept_b):
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 4:
                raise OSError("x")
            return 1

        call_with_retry(
            flaky, "same-site",
            RetryPolicy(transient_attempts=5, base_s=0.01, seed=3),
            sleep=slept.append,
        )
    assert slept_a == slept_b and len(slept_a) == 3


def test_supervisor_run_fit_retries_with_resume(toy_graphs, tmp_path):
    """A fit attempt that dies transiently mid-run is retried and RESUMES
    from its checkpoints — the retried attempt's final state equals the
    uninterrupted run's exactly."""
    g, cfg, F0 = _problem(toy_graphs, max_iters=6)
    cfg = cfg.replace(checkpoint_every=2)
    full = BigClamModel(g, cfg).fit(F0)

    cm = CheckpointManager(str(tmp_path / "ck"))
    model = BigClamModel(g, cfg)
    state = {"attempt": 0}

    def fit_attempt_dying():
        state["attempt"] += 1
        if state["attempt"] == 1:
            partial = BigClamModel(g, cfg.replace(max_iters=3))
            partial.fit(F0, checkpoints=cm)
            raise OSError("simulated I/O loss mid-fit")
        return model.fit(F0, checkpoints=cm)

    sup = Supervisor(RetryPolicy(transient_attempts=2, base_s=0.0))
    res = sup.run_fit(fit_attempt_dying)
    assert state["attempt"] == 2
    assert cm.latest_step() is not None          # resumed, not restarted
    np.testing.assert_array_equal(res.F, full.F)
    assert res.llh_history == full.llh_history


# --------------------------------------------------------------------------
# non-finite rollback
# --------------------------------------------------------------------------


def test_nan_injection_recovers_via_rollback(toy_graphs, telem):
    """Acceptance (b): an injected NaN at iteration t recovers via
    rollback within budget and the fit converges finitely — no
    FloatingPointError — emitting schema-valid rollback telemetry."""
    g, cfg, F0 = _problem(toy_graphs, max_iters=10)
    install_plan(
        FaultPlan([{"kind": "nan_inject", "site": "fit.step", "at": 4}])
    )
    res = BigClamModel(g, cfg).fit(F0)
    assert np.isfinite(res.llh)
    assert np.isfinite(res.F).all()
    assert res.num_iters == cfg.max_iters        # ran to completion
    rb = [e for e in _events(telem.directory) if e["kind"] == "rollback"]
    assert len(rb) == 1
    assert rb[0]["rollbacks"] == 1
    assert rb[0]["resume_iter"] <= rb[0]["iter"] == 4
    assert isinstance(rb[0]["llh"], str)         # non-finite serialized
    fi = [e for e in _events(telem.directory)
          if e["kind"] == "fault_injected"]
    assert fi and fi[0]["fault"] == "nan_inject"
    n, errors = validate_events_file(
        os.path.join(telem.directory, EVENTS_NAME)
    )
    assert errors == [], errors


def test_rollback_cuts_step_scale_and_restores_model_cfg(toy_graphs):
    g, cfg, F0 = _problem(toy_graphs, max_iters=8)
    model = BigClamModel(g, cfg)
    install_plan(
        FaultPlan([{"kind": "nan_inject", "site": "fit.step", "at": 3}])
    )
    res = model.fit(F0)
    assert np.isfinite(res.llh)
    # the shrunken ladder never leaks out of the fit
    assert model.cfg.step_scale == 1.0
    assert model.cfg == cfg
    # a scaled config compiles a DIFFERENT step (baked, not host-only)
    from bigclam_tpu.models.bigclam import step_cfg_key

    assert step_cfg_key(cfg) != step_cfg_key(cfg.replace(step_scale=0.1))
    assert step_cfg_key(cfg) == step_cfg_key(
        cfg.replace(rollback_budget=7, rollback_snapshot_every=2)
    )
    assert cfg.replace(step_scale=0.5).step_candidates[0] == 0.5


def test_rollback_budget_exhaustion_escalates_to_abort(toy_graphs, telem):
    """A persistently-poisoned state (NaN in F0 itself: every rollback
    target is poisoned too) burns the budget then aborts through the
    existing diagnostic path."""
    g, cfg, F0 = _problem(toy_graphs, max_iters=20, rollback_budget=2)
    bad = F0.copy()
    bad[3, 1] = np.nan
    with pytest.raises(FloatingPointError, match="rollback budget"):
        BigClamModel(g, cfg).fit(bad)
    ev = _events(telem.directory)
    assert len([e for e in ev if e["kind"] == "rollback"]) == 2
    nf = [e for e in ev if e["kind"] == "nonfinite"]
    assert len(nf) == 1 and nf[0]["rollbacks"] == 2


def test_rollback_disabled_keeps_abort_only_semantics(toy_graphs):
    g, cfg, F0 = _problem(toy_graphs, max_iters=20, rollback_budget=0)
    bad = F0.copy()
    bad[0, 0] = np.inf
    with pytest.raises(FloatingPointError, match="non-finite LLH"):
        BigClamModel(g, cfg).fit(bad)


def test_rollback_trajectory_unchanged_without_faults(toy_graphs):
    """The snapshot machinery on the happy path is pure observation: fits
    with rollback on/off are bit-identical (copies move storage, not
    math), donation included."""
    g, cfg, F0 = _problem(toy_graphs, max_iters=6)
    r_on = BigClamModel(g, cfg).fit(F0)            # budget default 3
    r_off = BigClamModel(g, cfg.replace(rollback_budget=0)).fit(F0)
    np.testing.assert_array_equal(r_on.F, r_off.F)
    assert r_on.llh_history == r_off.llh_history


def test_rollback_in_sharded_trainer(toy_graphs, telem):
    """run_fit_loop recovery is trainer-agnostic: the sharded trainer
    rolls back an injected NaN too (same loop, same hook surface)."""
    from bigclam_tpu.parallel import ShardedBigClamModel, make_mesh

    g, cfg, F0 = _problem(toy_graphs, max_iters=8)
    mesh = make_mesh((4, 1), jax.devices()[:4])
    install_plan(
        FaultPlan([{"kind": "nan_inject", "site": "fit.step", "at": 3}])
    )
    model = ShardedBigClamModel(g, cfg, mesh)
    res = model.fit(F0)
    assert np.isfinite(res.llh)
    assert model.cfg == cfg
    assert [e["kind"] for e in _events(telem.directory)].count(
        "rollback"
    ) == 1


# --------------------------------------------------------------------------
# checkpoint payload integrity + rotation
# --------------------------------------------------------------------------


def test_checkpoint_sidecar_stamps_per_array_crc(tmp_path):
    cm = CheckpointManager(str(tmp_path))
    cm.save(1, {"F": np.ones((3, 2)), "it": np.asarray(1)})
    side = json.load(open(cm._path(1) + ".json"))
    assert set(side["array_crc32"]) == {"F", "it"}
    step, arrays, meta = cm.restore()
    assert step == 1 and "array_crc32" in meta


def test_checkpoint_silent_corruption_detected_and_skipped(tmp_path, capsys):
    """A crc mismatch (simulated via a tampered sidecar stamp — byte flips
    in the zip payload are additionally caught by the container) reads as
    SILENT CORRUPTION: explicit restore raises CheckpointCorruption,
    newest-first restore falls back past it."""
    from bigclam_tpu.utils.checkpoint import CheckpointCorruption

    cm = CheckpointManager(str(tmp_path))
    cm.save(1, {"F": np.ones((4, 3))}, meta={"llh_history": [-5.0]})
    cm.save(2, {"F": np.full((4, 3), 2.0)}, meta={"llh_history": [-4.0]})
    side_path = cm._path(2) + ".json"
    side = json.load(open(side_path))
    side["array_crc32"]["F"] ^= 0xFFFF
    json.dump(side, open(side_path, "w"))

    with pytest.raises(CheckpointCorruption, match="checksum mismatch"):
        cm.restore(2)
    step, arrays, _ = cm.restore()
    assert step == 1
    np.testing.assert_array_equal(arrays["F"], np.ones((4, 3)))
    assert "silently corrupted" in capsys.readouterr().err


def test_rotation_never_deletes_newest_valid_checkpoint(tmp_path):
    """Satellite: with the NEWEST checkpoints corrupt, rotation must keep
    the newest VALID one alive no matter how many corrupt saves follow."""
    cm = CheckpointManager(str(tmp_path), keep=2)
    cm.save(1, {"F": np.full((4, 3), 1.0)})
    cm.save(2, {"F": np.full((4, 3), 2.0)})
    # corrupt every LATER save as it lands (simulated flaky device)
    install_plan(
        FaultPlan(
            [
                {"kind": "truncate_checkpoint", "site": "checkpoint.save",
                 "step": 3, "frac": 0.3},
                {"kind": "corrupt_checkpoint", "site": "checkpoint.save",
                 "step": 4},
            ]
        )
    )
    cm.save(3, {"F": np.full((4, 3), 3.0)})
    cm.save(4, {"F": np.full((4, 3), 4.0)})
    install_plan(None)
    # steps 3/4 are corrupt; the valid cutoff is {2, 1} -> nothing older
    # than 1 exists, and 1/2 MUST both survive
    assert set(cm.steps()) >= {1, 2}
    step, arrays, _ = cm.restore()
    assert step == 2
    np.testing.assert_array_equal(arrays["F"], np.full((4, 3), 2.0))
    # once valid saves resume, normal rotation kicks back in
    cm.save(5, {"F": np.full((4, 3), 5.0)})
    cm.save(6, {"F": np.full((4, 3), 6.0)})
    assert cm.restore()[0] == 6
    assert 1 not in cm.steps()                  # old ones finally rotated


def test_latest_valid_step_skips_corrupt_newest(tmp_path):
    """The resume lineage records the step restore() will USE, not the
    newest filename: latest_valid_step walks past corrupt files."""
    cm = CheckpointManager(str(tmp_path))
    cm.save(2, {"F": np.ones((3, 2))})
    cm.save(4, {"F": np.ones((3, 2)) * 2})
    assert cm.latest_valid_step() == 4
    p4 = cm._path(4)
    with open(p4, "r+b") as f:
        f.truncate(os.path.getsize(p4) // 2)
    assert cm.latest_step() == 4                 # filename says 4...
    assert cm.latest_valid_step() == 2           # ...restore will use 2


def test_quality_resume_never_cold_starts(toy_graphs, tmp_path):
    """fit_quality(resume=False) ignores an existing cycle checkpoint
    (cold start) while still saving — the --resume never contract on the
    quality path."""
    from bigclam_tpu.models.quality import fit_quality

    g, cfg, F0 = _problem(toy_graphs, max_iters=6)
    qcfg = cfg.replace(
        quality_mode=True, restart_cycles=2, restart_tol=0.0,
        quality_repair=False,
    )
    cm = CheckpointManager(str(tmp_path / "q"))
    model = BigClamModel(g, qcfg)

    def counting_cb(counter):
        def cb(it, llh):
            counter["n"] += 1
        return cb

    c1 = {"n": 0}
    q1 = fit_quality(model, F0, callback=counting_cb(c1), checkpoints=cm)
    assert cm.latest_step() is not None and c1["n"] > 0
    # resumed run restores the journaled schedule: NO fit work re-runs
    c2 = {"n": 0}
    fit_quality(model, F0, callback=counting_cb(c2), checkpoints=cm)
    assert c2["n"] == 0
    # cold start re-runs the full schedule and reproduces it
    c3 = {"n": 0}
    q3 = fit_quality(
        model, F0, callback=counting_cb(c3), checkpoints=cm, resume=False
    )
    assert c3["n"] == c1["n"]
    assert q3.cycles_llh == q1.cycles_llh


def test_sweep_resume_never_retrains(tmp_path):
    from bigclam_tpu.graph.ingest import graph_from_edges
    from bigclam_tpu.models.model_selection import sweep_k

    edges = []
    for base in (0, 6):
        for i in range(6):
            for j in range(i + 1, 6):
                edges.append((base + i, base + j))
    edges.append((5, 6))
    g = graph_from_edges(edges)
    cfg = BigClamConfig(
        num_communities=4, dtype="float64", max_iters=10,
        min_com=2, max_com=4, div_com=2, ksweep_tol=1e-3,
    )
    r1 = sweep_k(g, cfg, state_dir=str(tmp_path))
    # poison the journal: a resumed sweep would trust it, a cold sweep
    # must retrain and overwrite it
    bogus = {str(k): 123.0 for k in r1.llh_by_k}
    (tmp_path / "sweep_state.json").write_text(json.dumps(bogus))
    r2 = sweep_k(g, cfg, state_dir=str(tmp_path), resume=False)
    assert r2.llh_by_k == r1.llh_by_k
    journal = json.loads((tmp_path / "sweep_state.json").read_text())
    assert journal != bogus


def test_multi_corrupt_fallback_resume_bit_identical(toy_graphs, tmp_path):
    """Satellite: restore past TWO bad newest checkpoints and resume a
    trajectory BIT-identical to the uninterrupted run."""
    g, cfg, F0 = _problem(toy_graphs, max_iters=8)
    cfg = cfg.replace(checkpoint_every=1)
    full = BigClamModel(g, cfg).fit(F0)

    cm = CheckpointManager(str(tmp_path), keep=10)
    BigClamModel(g, cfg.replace(max_iters=5)).fit(F0, checkpoints=cm)
    steps = cm.steps()
    assert len(steps) >= 3
    # newest two checkpoints: one truncated, one silently corrupted
    p_new = cm._path(steps[-1])
    with open(p_new, "r+b") as f:
        f.truncate(os.path.getsize(p_new) // 2)
    side_path = cm._path(steps[-2]) + ".json"
    side = json.load(open(side_path))
    side["array_crc32"]["F"] ^= 0x1
    json.dump(side, open(side_path, "w"))

    resumed = BigClamModel(g, cfg).fit(np.zeros_like(F0), checkpoints=cm)
    np.testing.assert_array_equal(resumed.F, full.F)
    assert resumed.llh_history == full.llh_history


_needs_multiproc_cpu = pytest.mark.skipif(
    jax.__version_info__ < (0, 5, 0),
    reason="jaxlib 0.4.x CPU backend lacks multiprocess computations",
)


@_needs_multiproc_cpu
def test_true_two_process_multi_corrupt_resume(tmp_path):
    """2-proc variant of the multi-corrupt fallback: every process falls
    back past the corrupted newest checkpoints to the shared valid one,
    and the resumed 2-process trajectory matches the uninterrupted
    single-process run."""
    from test_multihost import _run_two_workers, _worker_module

    out = tmp_path / "resumed.npz"
    ckpt_root = tmp_path / "ckpts"
    _run_two_workers(out, mode="ckpt-write", ckpt_root=ckpt_root)
    shared = ckpt_root / "p0"
    cm = CheckpointManager(str(shared))
    assert cm.steps() == [2, 4]
    p4 = cm._path(4)
    with open(p4, "r+b") as f:                   # corrupt newest
        f.truncate(os.path.getsize(p4) // 2)
    # plant a second, even newer, bogus checkpoint
    (shared / "ckpt_000000006.npz").write_bytes(b"PK\x03\x04 bogus")

    _run_two_workers(out, mode="corrupt-resume", ckpt_root=ckpt_root)
    g, cfg, F0 = _worker_module().problem()
    ref = BigClamModel(g, cfg).fit(F0)
    got = np.load(out)
    np.testing.assert_allclose(got["F"], ref.F, rtol=1e-12)


# --------------------------------------------------------------------------
# shard quarantine + re-ingest
# --------------------------------------------------------------------------


def _planted_cache(tmp_path, num_shards=4, balance=False):
    rng = np.random.default_rng(7)
    pairs = rng.integers(0, 300, size=(2000, 2)) * 11 + 5
    text = tmp_path / "g.txt"
    with open(text, "w") as f:
        for u, v in pairs.tolist():
            f.write(f"{u} {v}\n")
    cache = str(tmp_path / ("bal.cache" if balance else "g.cache"))
    store = compile_graph_cache(
        str(text), cache, num_shards=num_shards, chunk_bytes=2048,
        balance=balance,
    )
    return str(text), cache, store


def _flip_byte(path, offset=None):
    size = os.path.getsize(path)
    offset = size // 2 if offset is None else offset
    with open(path, "r+b") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ 0xFF]))


@pytest.mark.parametrize("balance", [False, True])
def test_corrupt_shard_quarantined_and_rebuilt(tmp_path, telem, balance):
    """Acceptance (c): a corrupted shard is quarantined, re-ingested from
    the source edge list, and the reload completes with the rebuilt shard
    crc-valid — bit-identical to the clean graph, balanced caches
    included (the rebuild maps raw ids through the baked permutation)."""
    text, cache, store = _planted_cache(tmp_path, balance=balance)
    ref = store.load_graph()
    _flip_byte(store.shard_files(1)[1])          # indices blob of shard 1

    healing = GraphStore.open(cache, self_heal=True)
    g = healing.load_graph()
    np.testing.assert_array_equal(g.indptr, ref.indptr)
    np.testing.assert_array_equal(g.indices, ref.indices)
    np.testing.assert_array_equal(g.raw_ids, ref.raw_ids)
    # the bad blob was preserved in quarantine/
    qdir = os.path.join(cache, "quarantine")
    assert os.listdir(qdir)
    # the rebuilt cache is crc-valid under a STRICT (non-healing) open
    strict = GraphStore.open(cache)
    strict.load_graph()
    q = [e for e in _events(telem.directory) if e["kind"] == "quarantine"]
    assert len(q) == 1 and q[0]["shard"] == 1
    n, errors = validate_events_file(
        os.path.join(telem.directory, EVENTS_NAME)
    )
    assert errors == [], errors


def test_strict_store_still_rejects_without_self_heal(tmp_path):
    text, cache, store = _planted_cache(tmp_path)
    _flip_byte(store.shard_files(2)[1])
    with pytest.raises(ShardCorruption, match="checksum"):
        GraphStore.open(cache).load_graph()


def test_self_heal_without_source_raises_and_leaves_cache_intact(tmp_path):
    """A heal that CANNOT succeed must not make things worse: the corrupt
    blobs stay in place (diagnosable checksum error on the next strict
    open, not FileNotFoundError on files the manifest references)."""
    text, cache, store = _planted_cache(tmp_path)
    _flip_byte(store.shard_files(0)[1])
    os.unlink(text)
    with pytest.raises(ShardCorruption, match="source edge list"):
        GraphStore.open(cache, self_heal=True).load_graph()
    for path in store.shard_files(0):
        assert os.path.exists(path)              # nothing was quarantined
    assert not os.path.isdir(os.path.join(cache, "quarantine"))
    with pytest.raises(ShardCorruption, match="checksum"):
        GraphStore.open(cache).load_graph()


def test_self_heal_detects_changed_source(tmp_path):
    """A source file that no longer matches the manifest must refuse the
    rebuild (edge-count mismatch), not silently splice a different graph
    into the cache."""
    text, cache, store = _planted_cache(tmp_path)
    _flip_byte(store.shard_files(1)[1])
    with open(text, "a") as f:
        f.write("1 2\n")     # ids the cache's raw-id table never saw
    with pytest.raises(ShardCorruption, match="source changed"):
        GraphStore.open(cache, self_heal=True).load_graph()


def test_corrupt_shard_fault_site_drives_heal(tmp_path, telem):
    """The harness's corrupt_shard fault fires inside load_shard_range
    itself, and the healing store recovers in the same pass."""
    text, cache, store = _planted_cache(tmp_path)
    ref = store.load_graph()
    install_plan(
        FaultPlan(
            [{"kind": "corrupt_shard", "site": "store.load_shard",
              "shard": 2}]
        )
    )
    g = GraphStore.open(cache, self_heal=True).load_graph()
    np.testing.assert_array_equal(g.indices, ref.indices)
    ev = _events(telem.directory)
    assert [e["kind"] for e in ev].count("fault_injected") == 1
    assert [e["kind"] for e in ev].count("quarantine") == 1


def test_build_graph_passes_self_heal(tmp_path):
    text, cache, store = _planted_cache(tmp_path)
    ref = store.load_graph()
    _flip_byte(store.shard_files(3)[1])
    with pytest.raises(ShardCorruption):
        build_graph(cache)
    g = build_graph(cache, self_heal=True)
    np.testing.assert_array_equal(g.indices, ref.indices)


# --------------------------------------------------------------------------
# heartbeat escalation
# --------------------------------------------------------------------------


def test_heartbeat_escalates_after_consecutive_stalls(tmp_path):
    hits = []
    tel = RunTelemetry(
        str(tmp_path / "t"), entry="test", heartbeat_s=0.05, quiet=True,
        heartbeat_escalate=2,
    )
    tel.heartbeat.on_escalate = hits.append
    time.sleep(0.5)
    tel.finalize()
    ev = _events(tel.directory)
    stalls = [e for e in ev if e["kind"] == "stall"]
    esc = [e for e in ev if e["kind"] == "stall_escalated"]
    assert len(stalls) >= 2
    assert len(esc) == 1 and esc[0]["stalls"] == 2   # once per episode
    assert len(hits) == 1 and hits[0]["stalls"] == 2
    assert tel.report()["heartbeat"]["escalations"] == 1
    n, errors = validate_events_file(
        os.path.join(tel.directory, EVENTS_NAME)
    )
    assert errors == [], errors


def test_heartbeat_beat_rearms_escalation(tmp_path):
    tel = RunTelemetry(
        str(tmp_path / "t"), entry="test", heartbeat_s=0.06, quiet=True,
        heartbeat_escalate=3,
    )
    t0 = time.monotonic()
    while time.monotonic() - t0 < 0.45:
        tel.heartbeat.beat(iter=1)
        time.sleep(0.01)
    tel.finalize()
    assert not [
        e for e in _events(tel.directory) if e["kind"] == "stall_escalated"
    ]


def test_supervisor_escalation_aborts_and_classifies_transient(tmp_path):
    """abort_on_stall: the escalation interrupt surfaces as a transient
    StallEscalation that run_fit retries (resuming)."""
    tel = install(
        RunTelemetry(
            str(tmp_path / "t"), entry="test", heartbeat_s=0.05,
            quiet=True, heartbeat_escalate=1,
        )
    )
    sup = Supervisor(
        RetryPolicy(transient_attempts=2, base_s=0.0),
        abort_on_stall=True,
    ).attach(tel)
    state = {"attempt": 0}

    def wedged_then_fine():
        state["attempt"] += 1
        if state["attempt"] == 1:
            time.sleep(1.0)                      # host-side stall, no beats
            raise AssertionError("interrupt_main never landed")
        return "done"

    try:
        assert sup.run_fit(wedged_then_fine) == "done"
    finally:
        tel.finalize()
        uninstall(tel)
    assert state["attempt"] == 2
    kinds = [e["kind"] for e in _events(tel.directory)]
    assert "stall_escalated" in kinds and "retry" in kinds


# --------------------------------------------------------------------------
# resume lineage + cli report recovery section
# --------------------------------------------------------------------------


def test_record_resume_lineage_and_report(tmp_path, telem):
    from bigclam_tpu.obs.report import render
    from bigclam_tpu.resilience import read_lineage

    record_resume(telem.directory, 40)
    record_resume(telem.directory, 90)
    lineage = read_lineage(telem.directory)
    assert [a["resumed_step"] for a in lineage] == [40, 90]
    assert all(a["run"] == telem.run_id for a in lineage)
    assert len({a["attempt_id"] for a in lineage}) == 2
    ev = [e for e in _events(telem.directory) if e["kind"] == "resume"]
    assert [e["step"] for e in ev] == [40, 90]
    assert ev[1]["prev_attempts"] == 1
    telem.finalize()
    text, errors = render(telem.directory)
    assert errors == 0
    assert "resume lineage: 2 resumed attempt(s)" in text


def test_report_exits_nonzero_on_gave_up(tmp_path, telem):
    from bigclam_tpu.obs.report import render

    with pytest.raises(OSError):
        call_with_retry(
            lambda: (_ for _ in ()).throw(OSError("dead disk")),
            "fit", RetryPolicy(transient_attempts=2, base_s=0.0),
            sleep=lambda s: None,
        )
    telem.finalize()
    text, errors = render(telem.directory)
    assert errors >= 1
    assert "run ended in gave_up" in text
    assert "GAVE UP at fit" in text


def test_report_renders_recovery_counts(tmp_path, telem):
    from bigclam_tpu.obs.report import render

    call_with_retry(
        _flaky_once(), "load",
        RetryPolicy(transient_attempts=3, base_s=0.0),
        sleep=lambda s: None,
    )
    telem.finalize()
    text, errors = render(telem.directory)
    assert errors == 0
    assert "recovery:" in text and '"recovered": 1' in text


def _flaky_once():
    state = {"n": 0}

    def fn():
        state["n"] += 1
        if state["n"] == 1:
            raise OSError("once")
        return True

    return fn


# --------------------------------------------------------------------------
# kill -9 -> --resume auto, end to end through the CLI (acceptance a)
# --------------------------------------------------------------------------


def _write_cli_graph(tmp_path):
    graph = tmp_path / "g.txt"
    edges = []
    for base in (0, 10):
        for i in range(10):
            for j in range(i + 1, 10):
                edges.append((base + i, base + j))
    edges.append((9, 10))
    graph.write_text("\n".join(f"{u} {v}" for u, v in edges))
    return graph


def _run_cli(*argv, env_extra=None, check=True):
    env = {k: v for k, v in os.environ.items() if k != "BIGCLAM_FAULTS"}
    env.update(env_extra or {})
    r = subprocess.run(
        [sys.executable, "-m", "bigclam_tpu.cli", *argv],
        capture_output=True, text=True, timeout=600, cwd="/root/repo",
        env=env,
    )
    if check:
        assert r.returncode == 0, r.stderr
    return r


def test_cli_kill9_then_resume_auto_bit_identical(tmp_path):
    """Acceptance (a): kill -9 mid-fit, then `--resume auto` yields a
    bit-identical final F vs the uninterrupted run, with the resume
    recorded in telemetry lineage and `cli report` exiting 0."""
    graph = _write_cli_graph(tmp_path)
    base = [
        "fit", "--graph", str(graph), "--k", "2", "--dtype", "float64",
        "--max-iters", "12", "--conv-tol", "0", "--init", "random",
        "--quiet", "--platform", "cpu", "--checkpoint-every", "3",
    ]
    # uninterrupted reference
    _run_cli(
        *base, "--checkpoint-dir", str(tmp_path / "ck_ref"),
        "--save-f", str(tmp_path / "ref.npy"),
    )
    # killed run: SIGKILL at iteration 8 (checkpoints at 3 and 6 survive)
    tdir = str(tmp_path / "telem")
    r = _run_cli(
        *base, "--checkpoint-dir", str(tmp_path / "ck"),
        "--telemetry-dir", tdir,
        env_extra={
            "BIGCLAM_FAULTS": json.dumps(
                {"faults": [{"kind": "kill", "site": "fit.step", "at": 8}]}
            )
        },
        check=False,
    )
    assert r.returncode != 0                     # SIGKILL'd
    assert "FAULT kill" in r.stderr
    ck = CheckpointManager(str(tmp_path / "ck"))
    assert ck.latest_step() == 6
    # resume (default --resume auto): must complete and match bit for bit
    _run_cli(
        *base, "--checkpoint-dir", str(tmp_path / "ck"),
        "--telemetry-dir", tdir,
        "--save-f", str(tmp_path / "resumed.npy"),
    )
    ref = np.load(tmp_path / "ref.npy")
    resumed = np.load(tmp_path / "resumed.npy")
    np.testing.assert_array_equal(resumed, ref)

    from bigclam_tpu.resilience import read_lineage

    lineage = read_lineage(tdir)
    assert len(lineage) == 1 and lineage[0]["resumed_step"] == 6
    r2 = _run_cli("report", tdir)
    assert "resume lineage" in r2.stdout
    n, errors = validate_events_file(os.path.join(tdir, EVENTS_NAME))
    assert errors == [], errors


def test_cli_resume_never_cold_starts(tmp_path):
    """--resume never ignores existing checkpoints (cold start from F0 —
    NOT the journaled step-6 state a default run would restore) while
    still saving new ones."""
    graph = _write_cli_graph(tmp_path)

    def base(iters):
        return [
            "fit", "--graph", str(graph), "--k", "2", "--dtype",
            "float64", "--max-iters", str(iters), "--conv-tol", "0",
            "--init", "random", "--quiet", "--platform", "cpu",
            "--checkpoint-every", "2",
            "--checkpoint-dir", str(tmp_path / "ck"),
        ]

    r1 = _run_cli(*base(6))
    rec1 = json.loads(r1.stdout.strip().splitlines()[-1])
    assert rec1["iters"] == 6
    # a 4-iter rerun WITH resume would report iters=6 (restored past its
    # own max); --resume never must cold-start and stop at 4
    r2 = _run_cli(*base(4), "--resume", "never")
    rec2 = json.loads(r2.stdout.strip().splitlines()[-1])
    assert rec2["iters"] == 4
    # and the checkpoints written by the cold run are usable
    assert CheckpointManager(str(tmp_path / "ck")).latest_step() == 6
