"""Pallas candidate kernel: interpret-mode equivalence vs the XLA path
(hardware execution is exercised by bench.py on the real chip)."""

import numpy as np
import pytest

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.models.agm import planted_partition_F, sample_graph
from bigclam_tpu.models.bigclam import BigClamModel
from bigclam_tpu.ops import linesearch as ls_ops
from bigclam_tpu.ops import objective as obj_ops
from bigclam_tpu.ops.pallas_kernels import candidates_pass_pallas


@pytest.fixture(scope="module")
def fixture_graph():
    rng = np.random.default_rng(7)
    Fp, _ = planted_partition_F(48, 4, strength=1.5)
    return sample_graph(Fp, rng=rng)


def test_pallas_candidates_match_xla(fixture_graph):
    import jax.numpy as jnp

    g = fixture_graph
    cfg = BigClamConfig(num_communities=4, dtype="float64")
    model = BigClamModel(g, cfg, k_multiple=128)   # K padded to lane width
    rng = np.random.default_rng(0)
    F0 = rng.uniform(0.1, 1.0, size=(g.num_nodes, 4))
    state = model.init_state(F0)
    F, sumF = state.F, state.sumF
    grad, node_llh = obj_ops.grad_llh(F, sumF, model.edges, cfg)
    ref = ls_ops.candidates_pass(F, grad, model.edges, cfg)
    got = candidates_pass_pallas(F, grad, model.edges, cfg, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-12)


def test_pallas_trajectory_matches_xla():
    """Full fit with the pallas kernel forced on (interpret) vs forced off.
    Needs a graph whose edge chunk reaches the 1024-tile hardware bound."""
    rng = np.random.default_rng(2)
    Fp, _ = planted_partition_F(120, 4, strength=1.5)
    g = sample_graph(Fp, rng=rng)
    assert g.num_directed_edges >= 1024
    rng = np.random.default_rng(1)
    F0 = rng.uniform(0.1, 1.0, size=(g.num_nodes, 4))

    cfg_off = BigClamConfig(
        num_communities=4, dtype="float64", max_iters=4, conv_tol=0.0,
        use_pallas=False,
    )
    res_off = BigClamModel(g, cfg_off, k_multiple=128).fit(F0)

    # interpret-mode pallas: monkeypatch the dispatch to interpret=True
    import bigclam_tpu.ops.pallas_kernels as pk

    orig = pk.candidates_pass_pallas

    def interp(F, grad, edges, cfg, interpret=False):
        return orig(F, grad, edges, cfg, interpret=True)

    pk.candidates_pass_pallas = interp
    try:
        cfg_on = cfg_off.replace(use_pallas=True)
        res_on = BigClamModel(g, cfg_on, k_multiple=128).fit(F0)
    finally:
        pk.candidates_pass_pallas = orig
    np.testing.assert_allclose(res_on.F, res_off.F, rtol=1e-12)
    assert res_on.llh_history == res_off.llh_history
