"""Quality-mode tests (models/quality.py): planted recovery at a K where
the faithful dynamics freeze, resume exactness, and the parity guarantee
(flag off = byte-identical schedule; covered by every existing trajectory
test since quality_mode defaults to False and touches no kernel)."""

import numpy as np
import pytest

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.evaluation import avg_f1
from bigclam_tpu.models import BigClamModel
from bigclam_tpu.models.agm import sample_planted_graph
from bigclam_tpu.models.quality import fit_quality
from bigclam_tpu.ops import extraction, seeding


@pytest.fixture(scope="module")
def planted():
    """Planted-partition AGM big enough for the coverage failure: the
    conductance top-K seeds cover only a subset of blocks, and unseeded
    blocks' all-zero rows are frozen under faithful dynamics."""
    rng = np.random.default_rng(7)
    g, truth = sample_planted_graph(2400, 12, p_in=0.15, rng=rng)
    return g, truth


def _score(F, g, truth):
    com = extraction.extract_communities(np.asarray(F), g)
    return avg_f1(list(com.values()), truth)


def test_quality_mode_recovers_planted(planted):
    g, truth = planted
    k = len(truth)
    cfg = BigClamConfig(
        num_communities=k, quality_mode=True, restart_cycles=8,
        use_pallas=False, use_pallas_csr=False,
    )
    # PARITY baseline: reference seeding (raw top-K nominees) + faithful
    # dynamics — the documented coverage failure
    cfg_ref = cfg.replace(quality_mode=False, seed_exclusion=False)
    seeds_ref = seeding.conductance_seeds(g, cfg_ref)
    F0_ref = seeding.init_F(g, seeds_ref, cfg_ref, np.random.default_rng(0))
    model = BigClamModel(g, cfg)
    res_faithful = model.fit(F0_ref)
    f1_faithful = _score(res_faithful.F, g, truth)

    # quality mode: coverage-aware seeds + noise annealing
    seeds = seeding.conductance_seeds(g, cfg)
    F0 = seeding.init_F(g, seeds, cfg, np.random.default_rng(0))
    qres = fit_quality(model, F0)
    f1_quality = _score(qres.fit.F, g, truth)

    # the quality schedule must clear the recovery gate AND beat faithful
    # semantics by a wide margin (the whole point of the flag)
    assert f1_quality >= 0.8, (f1_quality, f1_faithful)
    assert f1_quality > f1_faithful + 0.2, (f1_quality, f1_faithful)
    assert qres.fit.llh > res_faithful.llh
    # kept LLH is non-decreasing across cycles by construction; an
    # accepted repair round may push the final LLH ABOVE the cycle max
    kept = np.maximum.accumulate(qres.cycles_llh)
    if qres.num_repairs:
        assert qres.fit.llh > kept[-1]
    else:
        assert qres.fit.llh == pytest.approx(kept[-1])


def test_quality_resume_exact(planted, tmp_path):
    """Kill-and-resume at cycle granularity: per-cycle noise streams make
    the resumed schedule reproduce the uninterrupted one exactly."""
    from bigclam_tpu.utils.checkpoint import CheckpointManager

    g, truth = planted
    k = len(truth)

    def make(cycles):
        cfg = BigClamConfig(
            num_communities=k, quality_mode=True, restart_cycles=cycles,
            restart_tol=0.0,               # run every cycle deterministically
            use_pallas=False, use_pallas_csr=False,
        )
        return BigClamModel(g, cfg), cfg

    seeds = seeding.conductance_seeds(g, BigClamConfig(num_communities=k))
    F0 = seeding.init_F(
        g, seeds, BigClamConfig(num_communities=k), np.random.default_rng(0)
    )

    model4, _ = make(4)
    ref = fit_quality(model4, F0)
    assert ref.num_cycles == 4

    # interrupted: run 2 cycles with a checkpoint manager, then resume
    model2, _ = make(2)
    cm = CheckpointManager(str(tmp_path / "q"))
    part = fit_quality(model2, F0, checkpoints=cm)
    assert part.num_cycles == 2
    resumed = fit_quality(model4, F0, checkpoints=cm)

    assert resumed.num_cycles == 4
    np.testing.assert_allclose(resumed.cycles_llh, ref.cycles_llh, rtol=0)
    np.testing.assert_allclose(resumed.fit.F, ref.fit.F, rtol=0, atol=0)


def test_quality_resume_after_patience_stop(planted, tmp_path):
    """A run that ended via restart_patience must not anneal further when
    re-invoked on its checkpoint — the restored patience state stops the
    loop before any new cycle runs."""
    from bigclam_tpu.utils.checkpoint import CheckpointManager

    g, truth = planted
    k = len(truth)
    cfg = BigClamConfig(
        num_communities=k, quality_mode=True, restart_cycles=20,
        restart_tol=1.0, restart_patience=2,   # every cycle is "gainless"
        use_pallas=False, use_pallas_csr=False,
    )
    model = BigClamModel(g, cfg)
    F0 = np.zeros((g.num_nodes, k))
    cm = CheckpointManager(str(tmp_path / "q"))
    ref = fit_quality(model, F0, checkpoints=cm)
    assert ref.num_cycles == 3                  # cycle 0 + 2 gainless
    rerun = fit_quality(model, F0, checkpoints=cm)
    assert rerun.num_cycles == ref.num_cycles
    np.testing.assert_allclose(rerun.fit.F, ref.fit.F, rtol=0, atol=0)
    np.testing.assert_allclose(rerun.cycles_llh, ref.cycles_llh, rtol=0)


def test_quality_composes_with_sharded_trainer(planted):
    """fit_quality only calls model.fit, so the annealing schedule must
    work unchanged over a sharded trainer — and reproduce the single-chip
    quality trajectory exactly in float64 (shard-count invariance)."""
    import jax

    from bigclam_tpu.parallel import ShardedBigClamModel, make_mesh

    g, truth = planted
    k = len(truth)
    cfg = BigClamConfig(
        num_communities=k, quality_mode=True, restart_cycles=3,
        restart_tol=0.0, dtype="float64",
        use_pallas=False, use_pallas_csr=False,
    )
    seeds = seeding.conductance_seeds(g, cfg)
    F0 = seeding.init_F(g, seeds, cfg, np.random.default_rng(0))
    mesh = make_mesh((4, 1), jax.devices()[:4])
    q_sharded = fit_quality(ShardedBigClamModel(g, cfg, mesh), F0)
    q_single = fit_quality(BigClamModel(g, cfg), F0)
    np.testing.assert_allclose(
        q_sharded.cycles_llh, q_single.cycles_llh, rtol=1e-12
    )
    # F agreement is not bitwise: 1e-15-level psum-order differences can
    # flip an Armijo acceptance exactly at threshold, diverging single rows
    # discretely. The LLH trail pins the trajectory; here we bound the
    # fraction of discretely-diverged entries.
    frac = (np.abs(q_sharded.fit.F - q_single.fit.F) > 1e-8).mean()
    assert frac < 0.01, frac


def test_quality_checkpoint_shape_mismatch_refused(planted, tmp_path):
    from bigclam_tpu.utils.checkpoint import CheckpointManager

    g, truth = planted
    k = len(truth)
    cfg = BigClamConfig(
        num_communities=k, quality_mode=True, restart_cycles=1,
        use_pallas=False, use_pallas_csr=False,
    )
    model = BigClamModel(g, cfg)
    cm = CheckpointManager(str(tmp_path / "q"))
    F0 = np.zeros((g.num_nodes, k))
    fit_quality(model, F0, checkpoints=cm)
    cfg2 = cfg.replace(num_communities=k - 1)
    model2 = BigClamModel(g, cfg2)
    with pytest.raises(ValueError, match="incompatible"):
        fit_quality(
            model2, np.zeros((g.num_nodes, k - 1)), checkpoints=cm
        )


def test_max_p_relaxation_rescues_frozen_annealing():
    """The MAX_P_ clip bounds the gradient's 1/(1-p) amplification; a
    noise-level column entry grows only when deg(u)*amp > N. With amp
    pinned at 10 every kick is frozen dead (the K=5000 gate's failure mode,
    QUALITY_K5000_r04.json: 4 gainless cycles, F1 0.001); the auto
    relaxation (amp = 16*N/avg_deg) recovers the planted partition."""
    g, truth = sample_planted_graph(
        600, 25, p_in=0.3, rng=np.random.default_rng(7)
    )
    k = len(truth)

    def run(**kw):
        cfg = BigClamConfig(
            num_communities=k, quality_mode=True,
            use_pallas=False, use_pallas_csr=False, **kw,
        )
        seeds = seeding.conductance_seeds(g, cfg)
        F0 = seeding.init_F(g, seeds, cfg, np.random.default_rng(0))
        model = BigClamModel(g, cfg)
        qres = fit_quality(model, F0)
        # the parity cfg (and its step) must be restored afterwards
        assert model.cfg.max_p == cfg.max_p
        assert model.cfg.conv_tol == cfg.conv_tol
        return _score(qres.fit.F, g, truth)

    f1_pinned = run(quality_max_p=0.9)
    f1_auto = run()
    assert f1_auto >= 0.8, (f1_auto, f1_pinned)
    assert f1_auto > f1_pinned + 0.3, (f1_auto, f1_pinned)


def test_step_cache_reused_across_quality_calls(planted):
    """fit_quality swaps conv_tol/max_p around every schedule; the step
    cache (models.bigclam.step_cfg_key) must make the relax/restore pair
    compile once — repeated fit_quality calls reuse both steps."""
    g, truth = planted
    k = len(truth)
    cfg = BigClamConfig(
        num_communities=k, quality_mode=True, restart_cycles=2,
        # force a real relaxation at this small N so BOTH steps exist
        quality_max_p=1.0 - 1e-6,
        use_pallas=False, use_pallas_csr=False,
    )
    model = BigClamModel(g, cfg)
    F0 = np.zeros((g.num_nodes, k))
    fit_quality(model, F0)
    assert len(model._step_cache) == 2, model._step_cache.keys()
    steps = {id(s) for s, _ in model._step_cache.values()}
    fit_quality(model, F0)
    assert len(model._step_cache) == 2
    assert {id(s) for s, _ in model._step_cache.values()} == steps


def test_quality_kick_cols_keeps_padding_inert(planted):
    """With kick_cols=k0 < K, columns >= k0 must stay identically zero all
    the way through the annealing schedule (the K-sweep's masking
    contract)."""
    g, truth = planted
    k = len(truth)
    k0 = k - 4
    cfg = BigClamConfig(
        num_communities=k, quality_mode=True, restart_cycles=3,
        use_pallas=False, use_pallas_csr=False,
    )
    model = BigClamModel(g, cfg)
    F0 = np.zeros((g.num_nodes, k))
    qres = fit_quality(model, F0, kick_cols=k0)
    F = np.asarray(qres.fit.F)
    assert np.all(F[:, k0:] == 0.0)
    assert np.any(F[:, :k0] > 0.0)
    with pytest.raises(ValueError, match="kick_cols"):
        fit_quality(model, F0, kick_cols=k + 1)


def test_quality_within_cycle_checkpoint_resume(planted, tmp_path):
    """With cfg.checkpoint_every > 0, a crash DEEP INSIDE a cycle resumes
    inside that cycle (checkpoints.directory/cycle_<c>/) and reproduces
    the uninterrupted schedule exactly; journaled cycles delete their
    within-cycle dirs."""
    import os

    from bigclam_tpu.utils.checkpoint import CheckpointManager

    g, truth = planted
    k = len(truth)
    cfg = BigClamConfig(
        num_communities=k, quality_mode=True, restart_cycles=3,
        restart_tol=0.0, checkpoint_every=2,
        # pin the relaxed clip at parity so the manual partial-cycle fit
        # below (plain model.fit) runs the identical step
        quality_max_p=0.9999,
        use_pallas=False, use_pallas_csr=False,
    )
    seeds = seeding.conductance_seeds(g, cfg)
    F0 = seeding.init_F(g, seeds, cfg, np.random.default_rng(0))
    model = BigClamModel(g, cfg)

    ref = fit_quality(model, F0, checkpoints=CheckpointManager(
        str(tmp_path / "ref")))

    # simulate a crash 3 iterations into cycle 0: run the cycle's fit by
    # hand with a small max_iters, leaving its within-cycle checkpoint
    cm = CheckpointManager(str(tmp_path / "q"))
    avg_deg = g.num_directed_edges / g.num_nodes
    eps = min(0.02, cfg.init_noise_mass * (avg_deg + 1.0) / g.num_nodes)
    kick = np.random.default_rng([cfg.seed, 0x5EED, 0]).uniform(
        0.0, eps, size=F0.shape
    )
    F_try = np.clip(F0 + kick, cfg.min_f, cfg.max_f)
    partial = BigClamModel(
        g, cfg.replace(conv_tol=cfg.quality_conv_tol, max_iters=3)
    )
    partial.fit(F_try, checkpoints=CheckpointManager(
        str(tmp_path / "q" / "cycle_00000")))
    assert os.path.exists(str(tmp_path / "q" / "cycle_00000"))

    resumed = fit_quality(model, F0, checkpoints=cm)
    np.testing.assert_allclose(resumed.cycles_llh, ref.cycles_llh, rtol=0)
    np.testing.assert_allclose(resumed.fit.F, ref.fit.F, rtol=0, atol=0)
    # journaled cycles cleaned their within-cycle dirs
    assert not os.path.exists(str(tmp_path / "q" / "cycle_00000"))
    assert not os.path.exists(str(tmp_path / "q" / "cycle_00002"))


def test_fit_state_matches_fit(planted):
    """The state-resident loop (fit_state) must converge to the same F and
    LLH as fit() from the same init — it IS fit() minus the host fetch."""
    g, truth = planted
    k = len(truth)
    cfg = BigClamConfig(
        num_communities=k, use_pallas=False, use_pallas_csr=False,
    )
    model = BigClamModel(g, cfg)
    F0 = np.random.default_rng(0).uniform(0.0, 1.0, (g.num_nodes, k))
    res = model.fit(F0)
    final, llh, iters, hist = model.fit_state(model.init_state(F0))
    assert llh == res.llh
    assert iters == res.num_iters
    assert hist == res.llh_history
    np.testing.assert_array_equal(model.extract_F(final), res.F)


def test_quality_device_recovers_planted(planted):
    """Device-resident annealing (fit_quality_device): state never leaves
    the devices between cycles; recovery quality must match the host
    schedule's (same stop rule/relaxation, different noise stream)."""
    g, truth = planted
    k = len(truth)
    cfg = BigClamConfig(
        num_communities=k, quality_mode=True, restart_cycles=8,
        use_pallas=False, use_pallas_csr=False,
    )
    seeds = seeding.conductance_seeds(g, cfg)
    F0 = seeding.init_F(g, seeds, cfg, np.random.default_rng(0))
    model = BigClamModel(g, cfg)
    from bigclam_tpu.models.quality import fit_quality_device

    qres = fit_quality_device(model, F0)
    assert model.cfg.max_p == cfg.max_p          # parity cfg restored
    f1 = _score(qres.fit.F, g, truth)
    assert f1 >= 0.8, f1
    kept = np.maximum.accumulate(qres.cycles_llh)
    # round 5: the discrete stage (repair/atomize) also runs on the device
    # path, so the final LLH may exceed the best CYCLE's (never fall below)
    assert qres.fit.llh >= kept[-1] - abs(kept[-1]) * 1e-6
    if qres.num_repairs == 0:
        assert qres.fit.llh == pytest.approx(kept[-1])


def test_quality_device_sharded_padding_inert(planted):
    """On a sharded mesh the on-device kick must leave padding rows and
    columns exactly zero (mask correctness under sharding) and K-sweep
    style kick_cols masking must hold."""
    import jax

    from bigclam_tpu.models.quality import fit_quality_device
    from bigclam_tpu.parallel import ShardedBigClamModel, make_mesh

    g, truth = planted
    k = len(truth)
    k0 = k - 4
    cfg = BigClamConfig(
        num_communities=k, quality_mode=True, restart_cycles=3,
        restart_tol=0.0, use_pallas=False, use_pallas_csr=False,
    )
    mesh = make_mesh((4, 1), jax.devices()[:4])
    model = ShardedBigClamModel(g, cfg, mesh)
    F0 = np.zeros((g.num_nodes, k))
    qres = fit_quality_device(model, F0, kick_cols=k0)
    F = np.asarray(qres.fit.F)
    assert np.all(F[:, k0:] == 0.0)
    assert np.any(F[:, :k0] > 0.0)


def test_quality_recovers_overlapping_communities():
    """The AGM's defining capability: OVERLAPPING membership. Planted
    blocks sharing `overlap` nodes with the next block; quality mode must
    recover both the communities (F1) and the dual-membership structure
    (overlap node count in the right ballpark). Calibration at the larger
    N=2400/K=100 probe: F1 0.867, 600 true / 628 predicted dual members;
    this CI-sized config (N=1200/K=50, 300 true dual members) recovers
    F1 ~ 0.87 as well."""
    g, truth = sample_planted_graph(
        1200, 50, p_in=0.3, overlap=6, rng=np.random.default_rng(7)
    )
    k = len(truth)
    cfg = BigClamConfig(
        num_communities=k, quality_mode=True,
        use_pallas=False, use_pallas_csr=False,
    )
    seeds = seeding.conductance_seeds(g, cfg)
    F0 = seeding.init_F(g, seeds, cfg, np.random.default_rng(0))
    qres = fit_quality(BigClamModel(g, cfg), F0)
    com = extraction.extract_communities(np.asarray(qres.fit.F), g)
    f1 = avg_f1(list(com.values()), truth)
    assert f1 >= 0.75, f1
    n = g.num_nodes
    pred_member = np.zeros(n)
    for c in com.values():
        for u in c:
            pred_member[u] += 1
    true_member = np.zeros(n)
    for t in truth:
        for u in t:
            true_member[u] += 1
    n_true = int((true_member >= 2).sum())
    n_pred = int((pred_member >= 2).sum())
    # dual membership must be detected at roughly the right rate (not
    # collapsed to disjoint, not blanket-overlapped)
    assert 0.5 * n_true <= n_pred <= 2.0 * n_true, (n_true, n_pred)


def test_repair_communities_fixes_constructed_defects():
    """repair_communities on a hand-built defect: column 0 merged over two
    disconnected blocks, columns 1+2 fragmenting one block; the repair
    must free a fragment column and re-seed it on the merged column's
    extra component."""
    from bigclam_tpu.models.quality import repair_communities
    from bigclam_tpu.ops.extraction import delta_threshold

    g, truth = sample_planted_graph(
        240, 10, p_in=0.5, rng=np.random.default_rng(3)
    )
    k = 10
    s = 1.0
    F = np.zeros((g.num_nodes, k))
    # ideal columns for blocks 3..9 on columns 3..9
    for c in range(3, 10):
        F[truth[c], c] = s
    F[truth[0] + truth[1], 0] = s          # merged: blocks 0+1 on column 0
    half = len(truth[2]) // 2
    F[truth[2][:half], 1] = s              # fragments: block 2 split
    F[truth[2][half:], 2] = s              # over columns 1 and 2
    delta = delta_threshold(g.num_nodes, g.num_edges)
    F_rep, nrep = repair_communities(F, g, delta, k)
    assert nrep == 1
    mask = F_rep >= delta
    # block 2 now united in one column; blocks 0 and 1 separated
    cols_b2 = {int(c) for u in truth[2] for c in np.flatnonzero(mask[u])}
    assert len(cols_b2) == 1
    cols_b0 = {int(c) for u in truth[0] for c in np.flatnonzero(mask[u])}
    cols_b1 = {int(c) for u in truth[1] for c in np.flatnonzero(mask[u])}
    assert cols_b0.isdisjoint(cols_b1), (cols_b0, cols_b1)
    # padding columns beyond k_active are never touched
    F_pad = np.zeros((g.num_nodes, k + 4))
    F_pad[:, :k] = F
    F_rep2, nrep2 = repair_communities(F_pad, g, delta, k)
    assert nrep2 == 1
    assert np.all(F_rep2[:, k:] == 0.0)


def test_atomize_reassign_retiles_shifted_partition():
    """atomize_reassign on a hand-built SHIFTED partition (each column =
    one block + half the next — the midscale plateau's defect class,
    PARITY.md): shattering to graph components and re-seeding must
    produce one column per planted block, at the block's AGM-consistent
    strength."""
    from bigclam_tpu.models.quality import atomize_reassign
    from bigclam_tpu.ops.extraction import delta_threshold

    g, truth = sample_planted_graph(
        240, 10, p_in=0.8, rng=np.random.default_rng(5)
    )
    k = 10
    F = np.zeros((g.num_nodes, k))
    for c in range(k):                     # shifted: block c + half of c+1
        nxt = truth[(c + 1) % k]
        F[truth[c], c] = 1.0
        F[nxt[: len(nxt) // 2], c] = 1.0
    delta = delta_threshold(g.num_nodes, g.num_edges)
    F_at, n_atoms = atomize_reassign(F, g, delta, k)
    assert n_atoms == k
    mask = F_at >= delta
    # every planted block ends up whole in exactly one column
    for blk in truth:
        cols = {int(c) for u in blk for c in np.flatnonzero(mask[u])}
        assert len(cols) == 1, cols
    # per-atom strength tracks the MEASURED block density: the sampler
    # dedups uniform pairs, so nominal p_in=0.8 lands at d ~ 1-e^-0.8
    # ~ 0.55 and s = sqrt(-log(1-d)) ~ 0.87 — the adaptation must follow
    # the data, not the nominal parameter
    vals = F_at[F_at > 0]
    assert 0.7 <= vals.min() and vals.max() <= 1.1, (vals.min(), vals.max())
    # padding columns beyond k_active stay zero
    F_pad = np.zeros((g.num_nodes, k + 4))
    F_pad[:, :k] = F
    F_at2, n2 = atomize_reassign(F_pad, g, delta, k)
    assert n2 == k
    assert np.all(F_at2[:, k:] == 0.0)


def test_quality_reassign_llh_gated(planted):
    """The discrete stage with atomize enabled can only improve the kept
    LLH over the same schedule without it (every move is refit + gated),
    and the improvement path stays deterministic."""
    from bigclam_tpu.models.quality import fit_quality

    g, truth = planted
    k = len(truth)
    base = dict(num_communities=k, quality_mode=True, restart_cycles=2,
                use_pallas=False, use_pallas_csr=False)
    m_off = BigClamModel(g, BigClamConfig(**base, quality_reassign=False))
    m_on = BigClamModel(g, BigClamConfig(**base))
    F0 = np.zeros((g.num_nodes, k))
    r_off = fit_quality(m_off, F0)
    r_on = fit_quality(m_on, F0)
    # each run's discrete stage may only improve ITS OWN annealed best
    # (cross-schedule ordering is not guaranteed: an accepted atomize
    # changes what the same round's merge/split sees)
    for r in (r_off, r_on):
        best_cycle = max(r.cycles_llh)
        assert r.fit.llh >= best_cycle - abs(best_cycle) * 1e-6


def test_repair_stage_checkpoint_resume_and_invalidation(planted, tmp_path):
    """VERDICT r4 item 7: a completed discrete stage short-circuits on
    resume (no refits redone), and the post-annealing LLH stamp discards
    stale repair checkpoints when the annealing outcome changes."""
    from bigclam_tpu.models.quality import _repair_stage
    from bigclam_tpu.models.bigclam import FitResult
    from bigclam_tpu.utils.checkpoint import CheckpointManager

    g, truth = planted
    k = len(truth)
    cfg = BigClamConfig(
        num_communities=k, quality_mode=True,
        use_pallas=False, use_pallas_csr=False,
    )
    model = BigClamModel(g, cfg)
    seeds = seeding.conductance_seeds(g, cfg)
    F0 = seeding.init_F(g, seeds, cfg, np.random.default_rng(0))
    base = model.fit(F0)

    calls = []
    orig_fit = model.fit

    def counting_fit(F, **kw):
        calls.append(1)
        return orig_fit(F, **kw)

    model.fit = counting_fit
    cm = CheckpointManager(str(tmp_path / "q"))
    eps = 0.001
    best1, nrep1, it1 = _repair_stage(model, base, k, eps, None,
                                      checkpoints=cm)
    first_calls = len(calls)
    # non-vacuity: the fixture is deterministic and the stage performs
    # refits today (2); zero would hollow out BOTH assertions below
    assert first_calls > 0

    # resume on the same stamp: the stage must return the SAME result
    # without re-running any fits (the 'done' checkpoint short-circuits)
    calls.clear()
    best2, nrep2, it2 = _repair_stage(model, base, k, eps, None,
                                      checkpoints=cm)
    assert len(calls) == 0
    assert (best2.llh, nrep2, it2) == (best1.llh, nrep1, it1)
    np.testing.assert_array_equal(best2.F, best1.F)
    assert best2.num_iters == best1.num_iters

    # a DIFFERENT annealing outcome invalidates the stamp: the stale
    # checkpoint is discarded and the stage re-runs from the new state
    bumped = FitResult(
        F=base.F, sumF=base.sumF, llh=base.llh + 1.0,
        num_iters=base.num_iters, llh_history=base.llh_history,
    )
    calls.clear()
    _repair_stage(model, bumped, k, eps, None, checkpoints=cm)
    assert len(calls) > 0          # stale stamp discarded, stage re-ran

    # a DIFFERENT polish kick scale (an init_noise change reaching
    # _relax_params) also invalidates: the kick schedule differs, so the
    # stale checkpoint must not be resumed (ADVICE round-5)
    _repair_stage(model, base, k, eps, None, checkpoints=cm)
    calls.clear()
    _repair_stage(model, base, k, eps * 2, None, checkpoints=cm)
    assert len(calls) > 0          # eps stamp mismatch -> stage re-ran

    # and a DIFFERENT component floor likewise
    _repair_stage(model, base, k, eps, None, checkpoints=cm)
    calls.clear()
    _repair_stage(model, base, k, eps, None, checkpoints=cm, min_comp=7)
    assert len(calls) > 0          # min_comp stamp mismatch -> re-ran
