"""Fused Pallas superstep on the 2D path + closure grad exchange
(ISSUE 17) on the 8-device CPU fake.

Anchors: at replica_cols=1 the fused 2D trainer (kernel_path
csr_fused_2d[_kb]) must be BIT-identical to the 1D fused trainer — the
closure positions feeding the kernel's dst stream are a relabeling of
the same gathered rows, never different math. At C>1 the closure grad
exchange must equal the dense cols-psum it replaces bit-exactly when no
row's contribution count changes (every touched row's partials arrive
in block order either way), and degrade to the dense psum PER STEP on
cap overflow with the same counters the sparse allreduce surfaces.
"""

import io
import json
import os

import numpy as np
import pytest

import jax

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.graph.store import compile_graph_cache
from bigclam_tpu.models.agm import sample_planted_graph
from bigclam_tpu.obs import RunTelemetry, install, uninstall
from bigclam_tpu.parallel import (
    ShardedBigClamModel,
    StoreTwoDShardedBigClamModel,
    TwoDShardedBigClamModel,
    make_mesh,
    make_mesh_2d,
)

K = 8
# tile shape sized to the toy: n_pad=240 at p=4 -> n_blk=60, block_b=30
# divides it on both the (4,1) and (2,2) grids
_FUSED = dict(use_pallas_csr=True, pallas_interpret=True,
              csr_block_b=30, csr_tile_t=64)


def _cfg(**kw):
    d = dict(num_communities=K, max_iters=4, conv_tol=0.0,
             health_every=2, seed=0)
    d.update(kw)
    return BigClamConfig(**d)


@pytest.fixture
def telem(tmp_path):
    tel = install(RunTelemetry(str(tmp_path / "telem"), entry="test"))
    try:
        yield tel
    finally:
        tel.finalize()
        uninstall(tel)


@pytest.fixture(scope="module")
def planted():
    rng = np.random.default_rng(0)
    g, _ = sample_planted_graph(240, 4, p_in=0.3, rng=rng)
    F0 = np.abs(rng.standard_normal((g.num_nodes, K))).astype(np.float32)
    return g, F0


@pytest.fixture(scope="module")
def fit_1d_fused(planted):
    g, F0 = planted
    m = ShardedBigClamModel(
        g, _cfg(**_FUSED), make_mesh((4, 1), jax.devices()[:4])
    )
    assert m.engaged_path == "csr_fused"
    return m.fit(F0.copy())


@pytest.fixture(scope="module")
def cache_v3(planted, tmp_path_factory):
    g, _ = planted
    tmp = tmp_path_factory.mktemp("fused2d_cache")
    txt = str(tmp / "g.txt")
    src, dst = np.asarray(g.src), np.asarray(g.dst)
    with open(txt, "w") as f:
        for s, d in zip(src.tolist(), dst.tolist()):
            if s < d:
                f.write(f"{s}\t{d}\n")
    return txt, compile_graph_cache(txt, str(tmp / "cache"), num_shards=4)


# --------------------------------------------------- C=1 degeneration
def test_c1_flat_bit_identical_to_1d_fused(planted, fit_1d_fused):
    g, F0 = planted
    m = TwoDShardedBigClamModel(
        g, _cfg(partition="2d", replica_cols=1, **_FUSED),
        make_mesh_2d((4, 1), jax.devices()[:4]),
    )
    assert m.engaged_path == "csr_fused_2d"
    assert m.grad_exchange == "dense"      # C=1: nothing to exchange
    r = m.fit(F0.copy())
    assert r.llh == fit_1d_fused.llh
    assert np.array_equal(np.asarray(r.F), np.asarray(fit_1d_fused.F))


def test_c1_kblocked_bit_identical_to_1d_fused(planted):
    g, F0 = planted
    m1 = ShardedBigClamModel(
        g, _cfg(csr_k_block=4, **_FUSED),
        make_mesh((4, 1), jax.devices()[:4]),
    )
    assert m1.engaged_path == "csr_fused_kb"
    r1 = m1.fit(F0.copy())
    m2 = TwoDShardedBigClamModel(
        g, _cfg(partition="2d", replica_cols=1, csr_k_block=4, **_FUSED),
        make_mesh_2d((4, 1), jax.devices()[:4]),
    )
    assert m2.engaged_path == "csr_fused_2d_kb"
    r2 = m2.fit(F0.copy())
    assert r1.llh == r2.llh
    assert np.array_equal(np.asarray(r1.F), np.asarray(r2.F))


# ------------------------------------------- C>1: band + grad exchange
def test_2x2_closure_equals_dense_inside_band(planted, fit_1d_fused):
    g, F0 = planted
    mesh = make_mesh_2d((2, 2), jax.devices()[:4])
    fits = {}
    for gx in ("closure", "dense"):
        m = TwoDShardedBigClamModel(
            g, _cfg(partition="2d", replica_cols=2, grad_exchange=gx,
                    **_FUSED),
            mesh,
        )
        assert m.engaged_path == "csr_fused_2d"
        assert m.grad_exchange == gx
        st = m.init_state(F0)
        for _ in range(2):
            st = m._step(st)
        ids, fell_back = m.last_comm(st)
        if gx == "closure":
            assert 0 < ids <= m._grad_cap
            assert not fell_back
        else:
            assert (ids, fell_back) == (0, False)
        fits[gx] = m.fit(F0.copy())
    # the exchange reorders nothing: every touched row's partials are
    # summed in block order either way -> bit-exact agreement
    assert fits["closure"].llh == fits["dense"].llh
    assert np.array_equal(
        np.asarray(fits["closure"].F), np.asarray(fits["dense"].F)
    )
    assert fits["closure"].num_iters == fit_1d_fused.num_iters
    assert fits["closure"].llh == pytest.approx(fit_1d_fused.llh, rel=5e-3)


def test_all_pairs_overflow_falls_back_dense_per_step(planted, telem):
    """closure_grad_cap=1 sits below every chip's true pair size: every
    step must take the dense-psum branch of the SAME compiled step
    (counters latch the fallback, health events surface it) and the
    trajectory must equal the grad_exchange=dense run bit-exactly."""
    from bigclam_tpu.obs.report import load_events

    g, F0 = planted
    mesh = make_mesh_2d((2, 2), jax.devices()[:4])
    m = TwoDShardedBigClamModel(
        g, _cfg(partition="2d", replica_cols=2, grad_exchange="closure",
                closure_grad_cap=1, **_FUSED),
        mesh,
    )
    assert m._grad_cap == 1
    assert m._grad_pair_max > 1      # the cap genuinely truncates
    st = m.init_state(F0)
    for _ in range(2):
        st = m._step(st)
    ids, fell_back = m.last_comm(st)
    assert fell_back
    assert ids > m._grad_cap
    r = m.fit(F0.copy())
    m_dense = TwoDShardedBigClamModel(
        g, _cfg(partition="2d", replica_cols=2, grad_exchange="dense",
                **_FUSED),
        mesh,
    )
    r_dense = m_dense.fit(F0.copy())
    assert r.llh == r_dense.llh
    assert np.array_equal(np.asarray(r.F), np.asarray(r_dense.F))
    telem.finalize()
    health = [
        e for e in (load_events(telem.directory) or [])
        if e.get("kind") == "health" and "dense_fallback" in e
    ]
    assert health, "no health events carried the exchange counters"
    assert any(e["dense_fallback"] >= 1.0 for e in health)


# -------------------------------------------------------- store-native
def test_store_native_fused_matches_in_memory(planted, cache_v3):
    g, F0 = planted
    _, store = cache_v3
    for shape, cols in (((4, 1), 1), ((2, 2), 2)):
        cfg = _cfg(partition="2d", replica_cols=cols, **_FUSED)
        mesh = make_mesh_2d(shape, jax.devices()[:4])
        m_mem = TwoDShardedBigClamModel(g, cfg, mesh)
        m_st = StoreTwoDShardedBigClamModel(store, cfg, mesh)
        assert m_mem.engaged_path == "csr_fused_2d"
        assert m_st.engaged_path == "csr_fused_2d"
        r_mem = m_mem.fit(F0.copy())
        r_st = m_st.fit(F0.copy())
        assert r_st.llh == r_mem.llh, shape
        assert np.array_equal(np.asarray(r_st.F), np.asarray(r_mem.F))


# --------------------------------------------------- pricing honesty
def test_closure_grad_priced_below_dense_and_reconciles():
    """On a uniform sparse toy (avg degree 4, like the comms2d gate's)
    the baked grad cap sits well below the block size at (2,2), so the
    modeled closure exchange must undercut the dense psum it replaces;
    the live remeasure agrees within the same 2% band the 1D families
    gate on. (The planted fixture is the opposite regime — its cliques
    touch whole blocks — covered by the honest-curve test below.)"""
    from bigclam_tpu.graph.ingest import graph_from_edges

    rng = np.random.default_rng(3)
    n = 1024
    pairs = rng.integers(0, n, size=(6144, 2))
    pairs = pairs[pairs[:, 0] != pairs[:, 1]]
    key = pairs.min(1).astype(np.int64) * n + pairs.max(1)
    _, idx = np.unique(key, return_index=True)
    g = graph_from_edges(pairs[idx[:2048]], num_nodes=n)
    F0 = np.abs(rng.standard_normal((n, K))).astype(np.float32)
    mesh = make_mesh_2d((2, 2), jax.devices()[:4])
    m_cl = TwoDShardedBigClamModel(
        g, _cfg(partition="2d", replica_cols=2, grad_exchange="closure"),
        mesh,
    )
    m_dn = TwoDShardedBigClamModel(
        g, _cfg(partition="2d", replica_cols=2, grad_exchange="dense"),
        mesh,
    )
    assert m_cl._grad_cap < m_cl.n_pad // 4   # spread graph: cap < n_blk
    s_cl, s_dn = m_cl.comms.site_bytes(), m_dn.comms.site_bytes()
    cl_bytes = (
        s_cl["twod/alltoall_grad_closure"]
        + s_cl["twod/pmax_grad_count"]
        + s_cl["twod/pmax_grad_count_rows"]
    )
    assert "twod/psum_grad" not in s_cl
    assert "twod/alltoall_grad_closure" not in s_dn
    assert cl_bytes < s_dn["twod/psum_grad"]
    st = m_cl.init_state(F0)
    st = m_cl._step(st)
    modeled = m_cl.comms.bytes_per_step()
    measured = m_cl.comms_measured(st).bytes_per_step()
    assert abs(measured - modeled) / modeled <= 0.02
    # params carry the mode for the artifact/report records
    assert m_cl.comms.params["grad_exchange"] == "closure"
    assert m_dn.comms.params["grad_exchange"] == "dense"


def test_overflow_remeasure_swaps_to_dense_psum_site(planted):
    g, F0 = planted
    m = TwoDShardedBigClamModel(
        g, _cfg(partition="2d", replica_cols=2, grad_exchange="closure",
                closure_grad_cap=1, **_FUSED),
        make_mesh_2d((2, 2), jax.devices()[:4]),
    )
    st = m._step(m.init_state(F0))
    meas = m.comms_measured(st)
    (site,) = [
        s for s in meas.sites if s.site == "twod/alltoall_grad_closure"
    ]
    # the fallback fired: that step's exchange was the dense psum, and
    # the measured model prices it as one (same site name, psum op)
    assert site.op == "psum"
    assert meas.bytes_per_step() > m.comms.bytes_per_step()


def test_zero_touched_closure_priced_zero_bytes():
    """grad_cap=0 (no touched rows baked) mirrors the trainer's
    trace-time skip: the closure branch emits NO grad collectives, so
    the model prices the grad phase at exactly 0 bytes — not a dense
    psum, not an empty all_to_all."""
    from bigclam_tpu.obs.comms import twod_step_model

    m0 = twod_step_model(
        240, K, 2, 2, 4, 17, closure_cap=10,
        grad_exchange="closure", grad_cap=0,
    )
    sites = m0.site_bytes()
    assert "twod/psum_grad" not in sites
    assert "twod/alltoall_grad_closure" not in sites
    assert "twod/pmax_grad_count" not in sites
    grad_bytes = sum(
        s.bytes_per_step for s in m0.sites if s.phase == "exchange"
        and "grad" in s.site
    )
    assert grad_bytes == 0.0


def test_diagonal_planted_partition_honest_curve():
    """Block-diagonal cliques aligned to the (2,2) node blocks: every
    chip's edges touch ~every row of their own blocks, the baked grad
    cap rises to the full block size, and the priced closure exchange
    must NOT undercut the dense psum — the model reflects the baked
    counts, not a uniform-graph assumption."""
    rng = np.random.default_rng(1)
    from bigclam_tpu.graph.ingest import graph_from_edges

    n, blk = 240, 60
    pairs = []
    for b in range(4):
        lo = b * blk
        for u in range(lo, lo + blk):
            for v in rng.choice(
                np.arange(lo, lo + blk), size=8, replace=False
            ):
                if u != int(v):
                    pairs.append((u, int(v)))
    g = graph_from_edges(np.asarray(pairs), num_nodes=n)
    m = TwoDShardedBigClamModel(
        g, _cfg(partition="2d", replica_cols=2),
        make_mesh_2d((2, 2), jax.devices()[:4]),
    )
    n_blk = m.n_pad // m.p
    assert m._grad_pair_max >= int(0.9 * n_blk)
    s = m.comms.site_bytes()
    cl_bytes = (
        s["twod/alltoall_grad_closure"]
        + s["twod/pmax_grad_count"]
        + s["twod/pmax_grad_count_rows"]
    )
    m_dense = TwoDShardedBigClamModel(
        g, _cfg(partition="2d", replica_cols=2, grad_exchange="dense"),
        make_mesh_2d((2, 2), jax.devices()[:4]),
    )
    assert cl_bytes >= m_dense.comms.site_bytes()["twod/psum_grad"]


# ------------------------------------------------------ perf ledger
def test_ledger_refuses_cross_grad_exchange_baselines():
    from bigclam_tpu.obs import ledger as L

    rep = {
        "run": "a", "entry": "fit", "wall_s": 1.0,
        "fingerprint": {"host": "h", "backend": "cpu",
                        "device_kind": "cpu"},
        "final": {"n": 240, "edges": 3668, "k": K, "partition": "2d",
                  "mesh": "2x2", "grad_exchange": "closure",
                  "kernel_path": "csr_fused_2d"},
    }
    rec_cl = L.build_record(rep, [0.01] * 4)
    assert rec_cl["grad_exchange"] == "closure"
    rep2 = dict(rep, final=dict(rep["final"], grad_exchange="dense"))
    rec_dn = L.build_record(rep2, [0.01] * 4)
    assert L.match_key(rec_cl) != L.match_key(rec_dn)
    assert L.match_key(rec_cl) == L.match_key(dict(rec_cl, run="b"))


# -------------------------------------------- refusal wording (cli)
def test_refusal_wording_consistency(planted, tmp_path):
    """The 2d x sparse and 2d x ring refusals follow the shared shape:
    an `error:` prefix, the RATIONALE (why the layouts cannot compose),
    and an explicit alternative knob — and the ring wording keeps the
    closure-gather anchor the 2d family is documented under."""
    g, _ = planted
    txt = str(tmp_path / "g.txt")
    src, dst = np.asarray(g.src), np.asarray(g.dst)
    with open(txt, "w") as f:
        for s, d in zip(src.tolist(), dst.tolist()):
            if s < d:
                f.write(f"{s}\t{d}\n")
    from bigclam_tpu.cli import main as cli_main

    def refusal(args):
        with pytest.raises(SystemExit) as ei:
            cli_main(args)
        return str(ei.value)

    base = ["fit", "--graph", txt, "--k", str(K), "--partition", "2d",
            "--mesh", "4,1", "--max-iters", "1"]
    msgs = {
        "sparse": refusal(base + ["--representation", "sparse"]),
        "ring": refusal(base + ["--schedule", "ring"]),
    }
    for name, msg in msgs.items():
        assert msg.startswith("error:"), (name, msg)
        assert "Alternatives:" in msg, (name, msg)
        assert "closure-gather" in msg, (name, msg)
    assert "--representation sparse" in msgs["sparse"]
    assert "--schedule ring" in msgs["ring"]
    # the fused-path refusals on the trainer side carry their knob too
    with pytest.raises(ValueError, match="partition 1d"):
        TwoDShardedBigClamModel(
            g, _cfg(partition="2d", replica_cols=1, use_pallas_csr=True,
                    csr_fused=False),
            make_mesh_2d((4, 1), jax.devices()[:4]),
        )


# ------------------------------------------------- preflight knob
def test_preflight_replica_cols_knob_from_baked_counts(cache_v3):
    """With baked closure pair counts in the manifest and a 1d verdict
    that does not fit, the --replica-cols recommendation must come from
    pricing the baked counts at every divisor grid — named as such —
    instead of the sqrt heuristic."""
    import contextlib

    from bigclam_tpu.cli import main as cli_main

    _, store = cache_v3
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = cli_main([
            "preflight", "--graph", store.directory, "--k", "4096",
            "--mesh", "4,1", "--hbm-gb", "0.001", "--json",
        ])
    assert rc == 2
    p = json.loads(buf.getvalue())
    (knob,) = [k for k in p["knobs"] if "--replica-cols" in k]
    assert "baked closure pair counts" in knob
    # the 2d preflight names the combined fused + closure-grad config
    buf2 = io.StringIO()
    with contextlib.redirect_stdout(buf2):
        cli_main([
            "preflight", "--graph", store.directory, "--k", "4096",
            "--mesh", "4,1", "--partition", "2d", "--replica-cols", "2",
            "--json",
        ])
    p2 = json.loads(buf2.getvalue())
    assert p2["workload"]["kernel_path"] == "csr_fused_2d"
    assert p2["workload"]["grad_exchange"] == "closure"
    assert any("csr_fused_2d" in n for n in p2["notes"])
