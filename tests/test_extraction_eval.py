"""Extraction + evaluation tests (SURVEY.md §4.7) and the AGM recovery
integration test."""

import numpy as np

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.evaluation import avg_f1, overlapping_nmi
from bigclam_tpu.graph.ingest import graph_from_edges
from bigclam_tpu.models.agm import planted_partition_F, sample_graph
from bigclam_tpu.ops import extraction


def test_delta_threshold_formula():
    # eps = 2*3/(3*2) = 1 -> clipped; realistic case: N=100, E=50
    d = extraction.delta_threshold(100, 50)
    eps = 2 * 50 / (100 * 99)
    assert np.isclose(d, np.sqrt(-np.log(1 - eps)))


def test_membership_mask_threshold_and_fallback():
    F = np.array(
        [
            [0.9, 0.1, 0.0],   # above delta in col 0
            [0.1, 0.2, 0.1],   # all below: fallback to argmax col 1
            [0.2, 0.2, 0.1],   # fallback tie: cols 0 AND 1 (reference ==Fmax)
            [0.0, 0.0, 0.0],   # zero row: every column ties at max -> all
        ]
    )
    mask = extraction.membership_mask(F, delta=0.5)
    np.testing.assert_array_equal(
        mask,
        [
            [True, False, False],
            [False, True, False],
            [True, True, False],
            [True, True, True],
        ],
    )


def test_extract_communities_raw_ids():
    # graph with non-contiguous raw ids: output must use raw ids
    g = graph_from_edges([(10, 20), (20, 30)])
    F = np.array([[1.0, 0.0], [1.0, 0.0], [0.0, 1.0]])
    com = extraction.extract_communities(F, g, delta=0.5)
    assert com[0] == [10, 20]
    assert com[1] == [30]


def test_save_load_roundtrip(tmp_path):
    com = {0: [1, 2, 3], 1: [4, 5]}
    p = str(tmp_path / "cmty.txt")
    extraction.save_communities(p, com)
    loaded = extraction.load_communities(p)
    assert loaded == [[1, 2, 3], [4, 5]]


def test_f1_perfect_and_disjoint():
    a = [[1, 2, 3], [4, 5]]
    assert avg_f1(a, a) == 1.0
    assert avg_f1([[1, 2]], [[3, 4]]) == 0.0
    # partial overlap, hand-computed: f1({1,2,3},{2,3,4}) = 2*(2/3)*(2/3)/(4/3)=2/3
    assert np.isclose(avg_f1([[1, 2, 3]], [[2, 3, 4]]), 2 / 3)


def test_nmi_perfect_and_independent():
    a = [[0, 1, 2, 3], [4, 5, 6, 7]]
    assert np.isclose(overlapping_nmi(a, a), 1.0)
    # identical single community vs its complement-ish unrelated cover
    b = [[0, 2, 4, 6], [1, 3, 5, 7]]
    v = overlapping_nmi(a, b)
    assert 0.0 <= v < 0.2


def test_nmi_permutation_invariant():
    a = [[0, 1, 2], [3, 4, 5]]
    b = [[3, 4, 5], [0, 1, 2]]
    assert np.isclose(overlapping_nmi(a, b), 1.0)


def test_agm_recovery_end_to_end():
    """Plant 3 strong communities, sample a graph from the AGM, fit from a
    conductance-seeded init, extract, and score: F1 and NMI near 1."""
    from bigclam_tpu.models import BigClamModel
    from bigclam_tpu.ops import seeding

    rng = np.random.default_rng(42)
    Fp, truth = planted_partition_F(60, 3, strength=2.5)
    g = sample_graph(Fp, rng=rng)
    cfg = BigClamConfig(num_communities=3, dtype="float64", max_iters=60)
    # one seed per planted block (conductance ranking itself is covered by
    # test_seeding; with near-clique blocks its top-K nominees tie within a
    # single block, which is faithful to the reference but not a recovery
    # fixture)
    F0 = seeding.init_F(g, np.array([0, 20, 40]), cfg)
    res = BigClamModel(g, cfg).fit(F0)
    com = extraction.extract_communities(res.F, g)
    pred = list(com.values())
    f1 = avg_f1(pred, truth)
    nmi = overlapping_nmi(pred, truth)
    assert f1 > 0.85, (f1, nmi)
    assert nmi > 0.7, (f1, nmi)
