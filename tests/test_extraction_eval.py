"""Extraction + evaluation tests (SURVEY.md §4.7) and the AGM recovery
integration test."""

import numpy as np

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.evaluation import avg_f1, overlapping_nmi
from bigclam_tpu.graph.ingest import graph_from_edges
from bigclam_tpu.models.agm import planted_partition_F, sample_graph
from bigclam_tpu.ops import extraction


def test_delta_threshold_formula():
    # eps = 2*3/(3*2) = 1 -> clipped; realistic case: N=100, E=50
    d = extraction.delta_threshold(100, 50)
    eps = 2 * 50 / (100 * 99)
    assert np.isclose(d, np.sqrt(-np.log(1 - eps)))


def test_membership_mask_threshold_and_fallback():
    F = np.array(
        [
            [0.9, 0.1, 0.0],   # above delta in col 0
            [0.1, 0.2, 0.1],   # all below: fallback to argmax col 1
            [0.2, 0.2, 0.1],   # fallback tie: cols 0 AND 1 (reference ==Fmax)
            [0.0, 0.0, 0.0],   # zero row: every column ties at max -> all
        ]
    )
    mask = extraction.membership_mask(F, delta=0.5)
    np.testing.assert_array_equal(
        mask,
        [
            [True, False, False],
            [False, True, False],
            [True, True, False],
            [True, True, True],
        ],
    )


def test_extract_communities_raw_ids():
    # graph with non-contiguous raw ids: output must use raw ids
    g = graph_from_edges([(10, 20), (20, 30)])
    F = np.array([[1.0, 0.0], [1.0, 0.0], [0.0, 1.0]])
    com = extraction.extract_communities(F, g, delta=0.5)
    assert com[0] == [10, 20]
    assert com[1] == [30]


def test_save_load_roundtrip(tmp_path):
    com = {0: [1, 2, 3], 1: [4, 5]}
    p = str(tmp_path / "cmty.txt")
    extraction.save_communities(p, com)
    loaded = extraction.load_communities(p)
    assert loaded == [[1, 2, 3], [4, 5]]


def test_f1_perfect_and_disjoint():
    a = [[1, 2, 3], [4, 5]]
    assert avg_f1(a, a) == 1.0
    assert avg_f1([[1, 2]], [[3, 4]]) == 0.0
    # partial overlap, hand-computed: f1({1,2,3},{2,3,4}) = 2*(2/3)*(2/3)/(4/3)=2/3
    assert np.isclose(avg_f1([[1, 2, 3]], [[2, 3, 4]]), 2 / 3)


def test_nmi_perfect_and_independent():
    a = [[0, 1, 2, 3], [4, 5, 6, 7]]
    assert np.isclose(overlapping_nmi(a, a), 1.0)
    # identical single community vs its complement-ish unrelated cover
    b = [[0, 2, 4, 6], [1, 3, 5, 7]]
    v = overlapping_nmi(a, b)
    assert 0.0 <= v < 0.2


def test_nmi_permutation_invariant():
    a = [[0, 1, 2], [3, 4, 5]]
    b = [[3, 4, 5], [0, 1, 2]]
    assert np.isclose(overlapping_nmi(a, b), 1.0)


def test_agm_recovery_end_to_end():
    """Plant 3 strong communities, sample a graph from the AGM, fit from a
    conductance-seeded init, extract, and score: F1 and NMI near 1."""
    from bigclam_tpu.models import BigClamModel
    from bigclam_tpu.ops import seeding

    rng = np.random.default_rng(42)
    Fp, truth = planted_partition_F(60, 3, strength=2.5)
    g = sample_graph(Fp, rng=rng)
    cfg = BigClamConfig(num_communities=3, dtype="float64", max_iters=60)
    # one seed per planted block (conductance ranking itself is covered by
    # test_seeding; with near-clique blocks its top-K nominees tie within a
    # single block, which is faithful to the reference but not a recovery
    # fixture)
    F0 = seeding.init_F(g, np.array([0, 20, 40]), cfg)
    res = BigClamModel(g, cfg).fit(F0)
    com = extraction.extract_communities(res.F, g)
    pred = list(com.values())
    f1 = avg_f1(pred, truth)
    nmi = overlapping_nmi(pred, truth)
    assert f1 > 0.85, (f1, nmi)
    assert nmi > 0.7, (f1, nmi)


class TestDeviceExtraction:
    """extract_communities_device: identical output to the host path from
    a device-resident (padded / sharded) F, fetching only membership
    pairs."""

    def _graph(self, n):
        rng = np.random.default_rng(3)
        a = rng.random((n, n)) < 0.05
        edges = [
            (i, j) for i in range(n) for j in range(i + 1, n) if a[i, j]
        ]
        edges.append((0, n - 1))
        from bigclam_tpu.graph.ingest import graph_from_edges

        return graph_from_edges(edges, num_nodes=n)

    def test_matches_host_padded(self):
        import jax.numpy as jnp

        from bigclam_tpu.ops.extraction import (
            extract_communities,
            extract_communities_device,
        )

        g = self._graph(97)
        k = 7
        rng = np.random.default_rng(0)
        F = rng.uniform(0.0, 0.3, size=(g.num_nodes, k))
        F[5] = 0.0                      # all-zero row: Q13 every-community
        F[11] = 0.2                     # uniform row below delta: all ties
        host = extract_communities(F, g)
        # padded device array (rows AND columns), odd chunk size so the
        # last chunk is ragged
        F_pad = np.zeros((128, 16))
        F_pad[: g.num_nodes, :k] = F
        dev = extract_communities_device(
            jnp.asarray(F_pad), g, num_communities=k, chunk_rows=13
        )
        assert dev == host

    def test_matches_host_from_sharded_state(self):
        import jax

        from bigclam_tpu.config import BigClamConfig
        from bigclam_tpu.ops.extraction import (
            extract_communities,
            extract_communities_device,
        )
        from bigclam_tpu.parallel import ShardedBigClamModel, make_mesh

        g = self._graph(96)
        k = 6
        cfg = BigClamConfig(
            num_communities=k, use_pallas=False, use_pallas_csr=False,
        )
        mesh = make_mesh((4, 1), jax.devices()[:4])
        model = ShardedBigClamModel(g, cfg, mesh)
        F0 = np.random.default_rng(1).uniform(0.0, 1.0, (g.num_nodes, k))
        final, _llh, _it, _h = model.fit_state(model.init_state(F0))
        host = extract_communities(model.extract_F(final), g)
        dev = extract_communities_device(
            final.F, g, num_communities=k, chunk_rows=17
        )
        assert dev == host

    def test_empty_f_no_pairs(self):
        import jax.numpy as jnp

        from bigclam_tpu.ops.extraction import extract_communities_device

        g = self._graph(8)
        # delta > everything and no zero rows -> fallback ties only
        F = jnp.full((8, 3), 0.5)
        out = extract_communities_device(F, g, delta=2.0)
        # uniform rows below delta tie on the row max -> every community
        assert set(out) == {0, 1, 2}

    def test_matches_host_with_balance_relabeling(self):
        """balance=True permutes device row order; BOTH supported routes
        must agree with the host path: (a) the trainer's own relabeled
        graph (raw_ids carried by Graph.permute), (b) the original graph
        plus internal_row_to_node()."""
        import jax

        from bigclam_tpu.config import BigClamConfig
        from bigclam_tpu.ops.extraction import (
            extract_communities,
            extract_communities_device,
        )
        from bigclam_tpu.parallel import ShardedBigClamModel, make_mesh

        g = self._graph(96)
        k = 6
        cfg = BigClamConfig(
            num_communities=k, use_pallas=False, use_pallas_csr=False,
        )
        mesh = make_mesh((4, 1), jax.devices()[:4])
        model = ShardedBigClamModel(g, cfg, mesh, balance=True)
        assert model._perm is not None      # relabeling actually happened
        F0 = np.random.default_rng(1).uniform(0.0, 1.0, (g.num_nodes, k))
        final, _llh, _it, _h = model.fit_state(model.init_state(F0))
        host = extract_communities(model.extract_F(final), g)
        via_trainer_graph = extract_communities_device(
            final.F, model.g, num_communities=k, chunk_rows=17
        )
        via_row_map = extract_communities_device(
            final.F, g, num_communities=k, chunk_rows=17,
            row_to_node=model.internal_row_to_node(),
        )
        assert via_trainer_graph == host
        assert via_row_map == host
