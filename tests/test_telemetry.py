"""Run-telemetry subsystem tests (ISSUE 4, bigclam_tpu.obs): event-log
schema, compile-counter flatness on re-fit, heartbeat stall trigger,
non-finite LLH sentinel, MetricsLogger/IngestProfile satellite fixes, the
<2% telemetry-off overhead pin, and the true two-process single-writer /
report-merge contract."""

import json
import math
import os
import time

import numpy as np
import pytest

import jax

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.models import BigClamModel
from bigclam_tpu.obs import (
    RunTelemetry,
    current,
    install,
    uninstall,
    validate_event,
    validate_events_file,
)
from bigclam_tpu.obs.report import load_reports, merge_reports, render
from bigclam_tpu.obs.telemetry import EVENTS_NAME
from bigclam_tpu.utils import MetricsLogger


def _problem(toy_graphs, k=2, max_iters=5):
    g = toy_graphs["two_cliques"]
    cfg = BigClamConfig(
        num_communities=k, dtype="float64", max_iters=max_iters,
        conv_tol=0.0,
    )
    F0 = np.random.default_rng(5).uniform(0.1, 1.0, size=(g.num_nodes, k))
    return g, cfg, F0


def _events(directory):
    with open(os.path.join(directory, EVENTS_NAME)) as f:
        return [json.loads(line) for line in f if line.strip()]


@pytest.fixture
def telem(tmp_path):
    tel = install(RunTelemetry(str(tmp_path / "telem"), entry="test"))
    try:
        yield tel
    finally:
        tel.finalize()
        uninstall(tel)


def test_fit_event_log_validates_and_report_written(toy_graphs, telem):
    """A fit with telemetry installed leaves a schema-valid events.jsonl
    (start / stage / step / model_build / memory / compile / end) and a
    run report carrying stage seconds, watermark structure, and a compile
    count — the acceptance-criterion artifact, in-process."""
    g, cfg, F0 = _problem(toy_graphs)
    model = BigClamModel(g, cfg)
    with MetricsLogger(None, echo=False) as ml:
        res = model.fit(
            F0,
            callback=ml.step_callback(
                g.num_directed_edges, num_nodes=g.num_nodes
            ),
        )
    telem.set_final({"llh": res.llh})
    rep = telem.finalize()

    n, errors = validate_events_file(
        os.path.join(telem.directory, EVENTS_NAME)
    )
    assert errors == [], errors
    kinds = {e["kind"] for e in _events(telem.directory)}
    assert {"start", "step", "model_build", "memory", "end"} <= kinds
    steps = [e for e in _events(telem.directory) if e["kind"] == "step"]
    assert len(steps) == cfg.max_iters + 1
    assert all(e["pid"] == 0 for e in _events(telem.directory))
    # accept histogram rides the step events into the unified log
    assert "accept_hist" in steps[1]

    assert rep["final"]["llh"] == res.llh
    assert rep["compiles"]["count"] > 0
    assert rep["events"]["step"] == len(steps)
    # device watermarks: structure always present; values are null on the
    # CPU backend (its allocator doesn't track) but the devices were seen
    assert rep["memory"]["watermark_tags"]
    assert rep["memory"]["device_peak"]

    text, render_errors = render(telem.directory)
    assert render_errors == 0
    assert telem.run_id in text and "stage seconds" in text


def test_compile_count_flat_across_refit(toy_graphs, tmp_path):
    """Acceptance: the compile count must stay FLAT across a 3-step re-fit
    with an unchanged cfg (warm jit caches — no silent retrace storm), and
    must visibly GROW when a sweep-style cfg change compiles a new step."""
    g, cfg, F0 = _problem(toy_graphs, max_iters=3)
    with RunTelemetry(str(tmp_path / "t"), entry="test") as tel:
        model = BigClamModel(g, cfg)
        model.fit(F0)
        c1 = tel.compile_count()
        builds1 = tel.compiles["step_builds"]
        assert c1 > 0 and builds1 == 1
        model.fit(F0)              # 3-step re-fit, unchanged cfg
        assert tel.compile_count() == c1
        assert tel.compiles["step_builds"] == builds1
        # a per-K recompile (new model at a different K) is visible
        cfg3 = cfg.replace(num_communities=3)
        F3 = np.random.default_rng(6).uniform(
            0.1, 1.0, size=(g.num_nodes, 3)
        )
        BigClamModel(g, cfg3).fit(F3)
        assert tel.compile_count() > c1
        assert tel.compiles["step_builds"] == builds1 + 1
        assert len(tel.compiles["by_key"]) == 2


def test_heartbeat_stall_fires_deterministically(tmp_path, capsys):
    """No beat within the deadline -> a `stall` event with silence
    duration, RSS, and last progress; repeated silence re-emits."""
    tel = RunTelemetry(
        str(tmp_path / "t"), entry="test", heartbeat_s=0.08
    )
    tel.heartbeat.beat(iter=7)
    time.sleep(0.5)
    tel.finalize()
    stalls = [e for e in _events(tel.directory) if e["kind"] == "stall"]
    assert stalls, "heartbeat never fired"
    assert stalls[0]["silent_s"] >= 0.08
    assert stalls[0]["rss_bytes"] > 0
    assert stalls[0]["progress"] == {"iter": 7}
    assert "STALL" in capsys.readouterr().err
    n, errors = validate_events_file(
        os.path.join(tel.directory, EVENTS_NAME)
    )
    assert errors == [], errors


def test_heartbeat_beats_suppress_stall(tmp_path):
    tel = RunTelemetry(
        str(tmp_path / "t"), entry="test", heartbeat_s=0.15
    )
    t0 = time.monotonic()
    while time.monotonic() - t0 < 0.5:
        tel.heartbeat.beat(iter=1)
        time.sleep(0.01)
    tel.finalize()
    assert not [e for e in _events(tel.directory) if e["kind"] == "stall"]


def test_quiet_suppresses_heartbeat_stderr_not_jsonl(tmp_path, capsys):
    """Satellite: --quiet silences the heartbeat's stderr echo while the
    JSONL stays complete."""
    tel = RunTelemetry(
        str(tmp_path / "t"), entry="test", heartbeat_s=0.08, quiet=True
    )
    time.sleep(0.4)
    tel.finalize()
    assert [e for e in _events(tel.directory) if e["kind"] == "stall"]
    assert "STALL" not in capsys.readouterr().err


def test_nonfinite_llh_sentinel(toy_graphs, tmp_path):
    """A poisoned F aborts the fit loop with diagnostics instead of
    silently iterating on NaN to max_iters (the convergence test can never
    fire on NaN). With telemetry: a `nonfinite` event + dump file."""
    g, cfg, F0 = _problem(toy_graphs, max_iters=50)
    bad = F0.copy()
    bad[3, 1] = np.nan
    # without telemetry: still aborts (the sentinel is a safety feature,
    # not an observability feature)
    with pytest.raises(FloatingPointError, match="non-finite LLH"):
        BigClamModel(g, cfg).fit(bad)

    tel = install(RunTelemetry(str(tmp_path / "t"), entry="test"))
    try:
        with pytest.raises(FloatingPointError, match="non-finite LLH"):
            BigClamModel(g, cfg).fit(bad)
    finally:
        uninstall(tel)
    events = [
        e for e in _events(tel.directory) if e["kind"] == "nonfinite"
    ]
    assert len(events) == 1
    assert events[0]["iter"] == 0
    assert events[0]["f_nonfinite"] >= 1
    assert "accept_hist" in events[0]
    assert os.path.exists(
        os.path.join(tel.directory, "nonfinite_dump.npz")
    )
    # the abort path finalized the report too
    assert load_reports(tel.directory)
    n, errors = validate_events_file(
        os.path.join(tel.directory, EVENTS_NAME)
    )
    assert errors == [], errors


def test_metrics_logger_t0_lazy_and_load_s(tmp_path):
    """Satellite: "t" counts from the FIRST log, with construction->first-
    log time (graph load etc.) reported once as load_s."""
    p = tmp_path / "m.jsonl"
    ml = MetricsLogger(str(p), echo=False)
    time.sleep(0.08)
    ml.log({"iter": 0, "llh": -1.0})
    ml.log({"iter": 1, "llh": -0.5})
    ml.close()
    recs = [json.loads(x) for x in p.read_text().splitlines()]
    assert recs[0]["t"] < 0.05, "t still includes pre-first-log time"
    assert recs[0]["load_s"] >= 0.08
    assert "load_s" not in recs[1]


def test_ingest_profile_reports_parse_and_end_to_end_rates():
    """Satellite: the old single edges/sec divided raw_edges by ALL stage
    buckets; now both the parse-stage and end-to-end rates are explicit."""
    from bigclam_tpu.utils.profiling import IngestProfile

    prof = IngestProfile()
    prof.seconds = {"scan": 2.0, "scatter": 1.0, "dedup": 0.5,
                    "shards": 0.5}
    prof.counts = {"raw_edges": 1000}
    rep = prof.report()
    assert rep["edges_per_sec_parse"] == 500.0
    assert rep["edges_per_sec_end_to_end"] == 250.0
    assert rep["edges_per_sec"] == 250.0       # back-compat alias


def test_stage_profile_forwards_to_telemetry(telem):
    from bigclam_tpu.utils.profiling import StageProfile

    prof = StageProfile()
    with prof.stage("quality_stage"):
        time.sleep(0.01)
    prof.add_seconds("anneal", 1.5)
    assert "quality_stage" in telem.stage_seconds
    assert telem.stage_seconds["anneal"] == 1.5
    stage_events = [
        e for e in telem.report()["events"].items() if e[0] == "stage"
    ]
    assert stage_events and stage_events[0][1] == 2


def test_telemetry_off_overhead_under_2pct():
    """Acceptance pin: with telemetry OFF the fit loop's added work is one
    current()-is-None check + math.isfinite + three no-op span entries per
    iteration (obs.trace returns the shared NULL_SPAN) — measured here
    against the real compiled step time of a tiny model (the worst case:
    bigger models make the overhead fraction smaller)."""
    from bigclam_tpu.models.agm import sample_planted_graph
    from bigclam_tpu.obs import telemetry as obs_telemetry
    from bigclam_tpu.obs import trace as obs_trace
    from bigclam_tpu.utils.profiling import step_time

    assert current() is None
    # small-but-real model (the 16-node toy step sits below the jit
    # dispatch floor, where the fixed ~2us of loop bookkeeping reads as a
    # spurious percentage of an unrepresentative step)
    g, _ = sample_planted_graph(
        240, 4, p_in=0.3, rng=np.random.default_rng(0)
    )
    cfg = BigClamConfig(
        num_communities=4, dtype="float64", max_iters=5, conv_tol=0.0
    )
    F0 = np.random.default_rng(1).uniform(0.1, 1.0, size=(g.num_nodes, 4))
    model = BigClamModel(g, cfg)
    sec_per_step = step_time(
        model._step, model.init_state(F0), steps=15, warmup=2
    )

    iters = 20000
    llh = -123.456
    t0 = time.perf_counter()
    for _ in range(iters):
        tel = obs_telemetry.current()
        if tel is not None:
            tel.step_beat(0, llh)
        math.isfinite(llh)
        with obs_trace.span("fit_loop/dispatch", emit=False):
            pass
        with obs_trace.span("fit_loop/sync", emit=False):
            pass
        with obs_trace.span("fit_loop/callback", emit=False):
            pass
    overhead_per_iter = (time.perf_counter() - t0) / iters
    assert overhead_per_iter < 0.02 * sec_per_step, (
        f"telemetry-off overhead {overhead_per_iter:.3e}s/iter vs "
        f"step {sec_per_step:.3e}s"
    )


def test_schema_validator_catches_bad_events(tmp_path):
    good = {"v": 2, "run": "r", "pid": 0, "t": 0.1, "ts": 1700000000.0,
            "elapsed_s": 0.1, "kind": "step", "iter": 3, "llh": -1.0}
    assert validate_event(good) == []
    assert validate_event({**good, "v": 1})         # wrong (old) version
    assert validate_event({**good, "kind": "nope"})  # unknown kind
    missing = dict(good)
    del missing["llh"]
    assert validate_event(missing)                  # kind field missing
    # v2 base fields: monotonic elapsed_s + wall ts are REQUIRED
    for base_field in ("elapsed_s", "ts"):
        m = dict(good)
        del m[base_field]
        assert validate_event(m), base_field
    assert validate_event({**good, "iter": "3"})    # wrong type
    assert validate_event([1, 2])                   # not an object

    p = tmp_path / "e.jsonl"
    p.write_text(json.dumps(good) + "\nnot json\n")
    n, errors = validate_events_file(str(p))
    assert n == 2 and len(errors) == 1 and "line 2" in errors[0]


def test_quality_device_cycle_events(toy_graphs, telem):
    """The quality annealing schedules emit one `cycle` event per restart
    cycle (device loop exercised; the host loop shares _cycle_event)."""
    from bigclam_tpu.models.quality import fit_quality_device

    g, cfg, F0 = _problem(toy_graphs, max_iters=6)
    qcfg = cfg.replace(
        quality_mode=True, restart_cycles=3, restart_tol=0.0,
        quality_repair=False,
    )
    model = BigClamModel(g, qcfg)
    qres = fit_quality_device(model, F0)
    cycles = [
        e for e in _events(telem.directory) if e["kind"] == "cycle"
    ]
    assert len(cycles) == qres.num_cycles
    assert [c["cycle"] for c in cycles] == list(range(len(cycles)))
    assert all("kept" in c for c in cycles)
    # the quality StageProfile stages forwarded too
    assert "anneal" in telem.stage_seconds


def test_merge_reports_cross_process_rules():
    r0 = {
        "run": "r", "pid": 0, "processes": 2, "entry": "fit",
        "wall_s": 4.0,
        "stages": {"seconds": {"fit": 3.0}},
        "memory": {"device_peak": {"d0": {"bytes_in_use": 10,
                                          "peak_bytes_in_use": 20}}},
        "compiles": {"count": 3, "backend_compiles": 3, "step_builds": 1,
                     "backend_compile_s": 1.0,
                     "by_key": {"a": {"builds": 1, "compiles": 3}}},
        "heartbeat": {"stalls": 1},
        "events": {"step": 5},
        "final": {"llh": -1.0},
    }
    r1 = {
        **r0, "pid": 1, "wall_s": 5.0,
        "memory": {"device_peak": {"d0": {"bytes_in_use": 30,
                                          "peak_bytes_in_use": 15},
                                   "d1": {"bytes_in_use": 7,
                                          "peak_bytes_in_use": 7}}},
        "heartbeat": {"stalls": 0},
    }
    m = merge_reports([r0, r1])
    assert m["processes_reported"] == 2 and m["processes_expected"] == 2
    assert m["wall_s"] == 5.0
    assert m["stages_by_pid"] == {"0": {"fit": 3.0}, "1": {"fit": 3.0}}
    assert m["device_peak"]["d0"]["bytes_in_use"] == 30
    assert m["device_peak"]["d0"]["peak_bytes_in_use"] == 20
    assert "d1" in m["device_peak"]
    assert m["compiles"]["count"] == 6
    assert m["compiles"]["by_key"]["a"] == {"builds": 2, "compiles": 6}
    assert m["stalls"] == 1 and m["events"]["step"] == 10


def test_cli_fit_telemetry_and_report(tmp_path):
    """End-to-end acceptance: `cli fit --telemetry-dir` leaves events.jsonl
    + run_report.json with per-stage seconds, watermark structure, and a
    compile count; `cli report <dir>` renders it and exits 0."""
    import subprocess
    import sys

    graph = tmp_path / "g.txt"
    edges = []
    for base in (0, 8):
        for i in range(8):
            for j in range(i + 1, 8):
                edges.append((base + i, base + j))
    edges.append((7, 8))
    graph.write_text("\n".join(f"{u} {v}" for u, v in edges))
    tdir = tmp_path / "telem"
    r = subprocess.run(
        [sys.executable, "-m", "bigclam_tpu.cli", "fit",
         "--graph", str(graph), "--k", "2", "--dtype", "float64",
         "--max-iters", "5", "--init", "random", "--quiet",
         "--platform", "cpu", "--telemetry-dir", str(tdir)],
        capture_output=True, text=True, timeout=600, cwd="/root/repo",
    )
    assert r.returncode == 0, r.stderr

    n, errors = validate_events_file(str(tdir / EVENTS_NAME))
    assert errors == [] and n > 0, errors
    rep = json.load(open(tdir / "run_report.json"))
    for stage in ("graph_load", "model_build", "seeding", "fit"):
        assert stage in rep["stages"]["seconds"], rep["stages"]
    assert rep["compiles"]["count"] > 0
    assert rep["memory"]["device_peak"]       # watermarks sampled
    assert rep["final"]["k"] == 2
    assert rep["heartbeat"]["deadline_s"] == 300.0

    r2 = subprocess.run(
        [sys.executable, "-m", "bigclam_tpu.cli", "report", str(tdir)],
        capture_output=True, text=True, timeout=120, cwd="/root/repo",
    )
    assert r2.returncode == 0, r2.stdout + r2.stderr
    assert "stage seconds" in r2.stdout and "compiles:" in r2.stdout


def test_telemetry_does_not_initialize_jax_backend(tmp_path):
    """Regression: constructing RunTelemetry and emitting events must NOT
    initialize the jax backend — jax.distributed.initialize afterwards
    would raise ('must be called before any JAX computations'). Run in a
    fresh process (conftest already initialized this one's backend); the
    deferred gate then commits through initialize_distributed's
    already-initialized path and flushes the buffered events."""
    import subprocess
    import sys

    tdir = str(tmp_path / "t")
    code = f"""
import socket, sys
sys.path.insert(0, "/root/repo")
from bigclam_tpu.obs import RunTelemetry, install
tel = install(RunTelemetry({tdir!r}, entry="fit", heartbeat_s=0,
                           auto_gate=False))
tel.event("note", msg="buffered pre-init")
import jax
from jax._src import xla_bridge
inited = (xla_bridge.backends_are_initialized()
          if hasattr(xla_bridge, "backends_are_initialized")
          else bool(xla_bridge._backends))
assert not inited, "telemetry initialized the backend"
jax.config.update("jax_platforms", "cpu")
with socket.socket() as s:
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
jax.distributed.initialize(f"127.0.0.1:{{port}}", num_processes=1,
                           process_id=0)
from bigclam_tpu.parallel.multihost import initialize_distributed
assert initialize_distributed() is True
tel.finalize()
"""
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS",
                     "JAX_NUM_PROCESSES", "JAX_PROCESS_ID")
    }
    r = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=300, env=env,
    )
    assert r.returncode == 0, r.stderr
    events = _events(tdir)
    assert [e["kind"] for e in events if e["kind"] == "note"] == ["note"]
    n, errors = validate_events_file(os.path.join(tdir, EVENTS_NAME))
    assert errors == [], errors


def test_nonfinite_event_line_is_strict_json(toy_graphs, tmp_path):
    """The nonfinite sentinel's own event carries the NaN LLH — that line
    must still be STRICT JSON (no literal NaN; jq-parseable)."""
    g, cfg, F0 = _problem(toy_graphs)
    bad = F0.copy()
    bad[0, 0] = np.inf
    tel = install(RunTelemetry(str(tmp_path / "t"), entry="test"))
    try:
        with pytest.raises(FloatingPointError):
            BigClamModel(g, cfg).fit(bad)
    finally:
        uninstall(tel)
    raw = open(os.path.join(tel.directory, EVENTS_NAME)).read()
    assert "NaN" not in raw and "Infinity" not in raw
    nf = [e for e in _events(tel.directory) if e["kind"] == "nonfinite"]
    assert nf and isinstance(nf[0]["llh"], str)   # "nan"/"-inf" repr


# --- true two-process contract (pattern of tests/test_multihost.py) ------

_needs_multiproc_cpu = pytest.mark.skipif(
    jax.__version_info__ < (0, 5, 0),
    reason="jaxlib 0.4.x CPU backend lacks multiprocess computations",
)


@_needs_multiproc_cpu
def test_true_two_process_single_writer_and_report_merge(tmp_path):
    """TWO real processes sharing one telemetry dir: only process 0 writes
    events.jsonl (the worker asserts the file handle gate in-process), and
    each process leaves its own run report — merged at read time."""
    from test_multihost import _run_two_workers

    tdir = tmp_path / "telem"
    tdir.mkdir()
    out = tmp_path / "proc0.npz"
    _run_two_workers(out, mode="telemetry", ckpt_root=tdir)
    assert out.exists()

    n, errors = validate_events_file(str(tdir / EVENTS_NAME))
    assert errors == [], errors
    events = _events(str(tdir))
    assert events and all(e["pid"] == 0 for e in events)
    assert {"start", "step", "model_build", "end"} <= {
        e["kind"] for e in events
    }

    assert (tdir / "run_report.json").exists()
    assert (tdir / "run_report.p1.json").exists()
    reports = load_reports(str(tdir))
    assert [r["pid"] for r in reports] == [0, 1]
    assert all(r["processes"] == 2 for r in reports)
    # both processes resolved ONE run id through the dir claim file
    assert len({r["run"] for r in reports}) == 1
    merged = merge_reports(reports)
    assert merged["processes_reported"] == 2
    assert merged["final"] == reports[0]["final"]
    text, render_errors = render(str(tdir))
    assert render_errors == 0 and "processes 2/2" in text
