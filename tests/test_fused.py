"""Fused Pallas edge superstep (ops.pallas_fused, ISSUE 13), interpret
mode on CPU: trajectory parity for the fused dense kernels across all four
trainer families, the sparse member-merge kernel vs the searchsorted
merge, the fused/split/xla step-identity pin, the double-buffer-aware
VMEM estimate, the re-priced memory transients, and the perf-ledger
kernel-path refusal.

Parity bands: the fused superstep reorders the node-tail/acceptance
accumulations relative to the split two-kernel schedule (VMEM-resident
finalization instead of XLA array ops), so fused-vs-split is allclose at
a few f32 ULPs, not bitwise — the documented "LLH-band where fusion
reorders accumulation" regime; store-built vs in-memory FUSED runs stay
bit-identical (same kernels, same tiles)."""

import json
import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.graph.ingest import graph_from_edges
from bigclam_tpu.models.bigclam import BigClamModel, step_cfg_key


def _random_graph(seed, n=57, p=0.12):
    rng = np.random.default_rng(seed)
    a = rng.random((n, n)) < p
    edges = [(i, j) for i in range(n) for j in range(i + 1, n) if a[i, j]]
    edges.append((0, n - 1))
    return graph_from_edges(edges, num_nodes=n)


def _cfg(**kw):
    base = dict(
        num_communities=6, dtype="float32", edge_chunk=64,
        use_pallas_csr=True, pallas_interpret=True,
        csr_block_b=8, csr_tile_t=8,
    )
    base.update(kw)
    return BigClamConfig(**base)


def _run_steps(model, F0, steps=3):
    s = model.init_state(F0)
    for _ in range(steps):
        s = model._step(s)
    return s


# --------------------------------------------------------------------------
# single-chip: fused superstep vs split kernels vs XLA
# --------------------------------------------------------------------------


class TestFusedSingleChip:
    def test_fused_matches_split_and_xla(self, rng):
        g = _random_graph(0)
        k = 6
        F0 = rng.uniform(0.0, 1.0, size=(g.num_nodes, k))
        m_x = BigClamModel(g, _cfg(use_pallas_csr=False))
        m_s = BigClamModel(g, _cfg(csr_fused=False))
        m_f = BigClamModel(g, _cfg())
        assert m_x.engaged_path == "xla"
        assert m_s.engaged_path == "csr"
        assert m_f.engaged_path == "csr_fused"
        s_x = _run_steps(m_x, F0)
        s_s = _run_steps(m_s, F0)
        s_f = _run_steps(m_f, F0)
        n = g.num_nodes
        Ff = np.asarray(s_f.F)[:n, :k]
        np.testing.assert_allclose(
            Ff, np.asarray(s_s.F)[:n, :k], rtol=3e-5, atol=3e-5
        )
        np.testing.assert_allclose(
            Ff, np.asarray(s_x.F)[:n, :k], rtol=3e-5, atol=3e-5
        )
        np.testing.assert_allclose(float(s_f.llh), float(s_x.llh), rtol=1e-5)
        # the accepted-step histogram (acceptance decisions) agrees
        np.testing.assert_array_equal(
            np.asarray(s_f.accept_hist), np.asarray(s_s.accept_hist)
        )

    def test_fused_first_step_bitwise_vs_split(self, rng):
        """From identical inputs, ONE fused step reproduces the split
        step's update bit-for-bit on this box (same accumulation order by
        construction: tails seeded first, per-tile adds in tile order) —
        later steps may drift a ULP through XLA fusion differences, which
        the allclose trajectory test above covers."""
        g = _random_graph(1, n=41)
        k = 5
        F0 = np.random.default_rng(2).uniform(0.0, 1.0, (g.num_nodes, k))
        m_s = BigClamModel(g, _cfg(num_communities=k, csr_fused=False))
        m_f = BigClamModel(g, _cfg(num_communities=k))
        s_s = m_s._step(m_s.init_state(F0))
        s_f = m_f._step(m_f.init_state(F0))
        np.testing.assert_array_equal(np.asarray(s_f.F), np.asarray(s_s.F))

    def test_fused_kblocked_matches_xla(self, rng):
        """Single-chip K-blocked fused (flat tiles, kc columns per
        kernel, in-kernel column-window DMA) vs XLA."""
        g = _random_graph(3, n=37)
        k = 6
        F0 = rng.uniform(0.0, 1.0, size=(g.num_nodes, k))
        m_x = BigClamModel(g, _cfg(use_pallas_csr=False))
        m_f = BigClamModel(g, _cfg(csr_k_block=3))
        assert m_f.engaged_path == "csr_fused_kb"
        assert m_f.k_pad % 3 == 0
        s_x, s_f = _run_steps(m_x, F0), _run_steps(m_f, F0)
        n = g.num_nodes
        np.testing.assert_allclose(
            np.asarray(s_f.F)[:n, :k], np.asarray(s_x.F)[:n, :k],
            rtol=3e-5, atol=3e-5,
        )
        np.testing.assert_allclose(float(s_f.llh), float(s_x.llh), rtol=1e-5)

    def test_fused_layout_skips_fd_budget(self, rng, monkeypatch):
        """A zero fd budget forces the SPLIT path into the grouped layout;
        the fused path has no fd to budget and stays on flat tiles."""
        import bigclam_tpu.models.bigclam as mb
        from bigclam_tpu.ops.pallas_csr import GroupedTilesDev, TilesDev

        monkeypatch.setattr(mb, "FLAT_FD_BUDGET", 0)
        monkeypatch.setattr(mb, "GROUP_FD_BUDGET", 40960)
        g = _random_graph(4, n=37)
        m_s = BigClamModel(g, _cfg(csr_fused=False))
        m_f = BigClamModel(g, _cfg())
        assert isinstance(m_s._tiles, GroupedTilesDev)
        assert isinstance(m_f._tiles, TilesDev)
        assert m_f._tiles.seq is not None
        assert m_f.engaged_path == "csr_fused"


# --------------------------------------------------------------------------
# sharded / ring / store-native families
# --------------------------------------------------------------------------


class TestFusedFamilies:
    @pytest.mark.parametrize(
        "mesh_shape,kb,want",
        [
            ((2, 1), 0, "csr_fused"),
            ((2, 2), 0, "csr_fused"),       # fused TP kernel split
            ((2, 1), 3, "csr_fused_kb"),
            ((2, 2), 3, "csr_fused_kb"),
        ],
    )
    def test_sharded_fused_matches_xla(self, rng, mesh_shape, kb, want):
        from bigclam_tpu.parallel import ShardedBigClamModel, make_mesh

        dp, tp = mesh_shape
        g = _random_graph(5, n=71)
        k = 12 if kb else 6
        cfg = _cfg(num_communities=k, csr_k_block=kb)
        mesh = make_mesh(mesh_shape, jax.devices()[: dp * tp])
        m_f = ShardedBigClamModel(g, cfg, mesh)
        m_x = ShardedBigClamModel(
            g, cfg.replace(use_pallas_csr=False), mesh
        )
        assert m_f.engaged_path == want, m_f.path_reason
        F0 = rng.uniform(0.0, 1.0, size=(g.num_nodes, k))
        s_f, s_x = _run_steps(m_f, F0), _run_steps(m_x, F0)
        n = g.num_nodes
        np.testing.assert_allclose(
            np.asarray(s_f.F)[:n, :k], np.asarray(s_x.F)[:n, :k],
            rtol=3e-5, atol=3e-5,
        )
        np.testing.assert_allclose(float(s_f.llh), float(s_x.llh), rtol=1e-5)

    @pytest.mark.parametrize(
        "mesh_shape,kb,want",
        [
            ((2, 1), 0, "csr_ring_fused"),
            ((2, 2), 0, "csr_ring_fused"),  # fused TP phases
            ((2, 1), 3, "csr_ring_fused_kb"),
        ],
    )
    def test_ring_fused_matches_xla(self, mesh_shape, kb, want):
        from bigclam_tpu.parallel import RingBigClamModel, make_mesh

        dp, tp = mesh_shape
        g = _random_graph(6, n=64, p=0.15)
        k = 12 if kb else 6
        cfg = _cfg(num_communities=k, csr_k_block=kb)
        mesh = make_mesh(mesh_shape, jax.devices()[: dp * tp])
        m_f = RingBigClamModel(g, cfg, mesh)
        m_x = RingBigClamModel(g, cfg.replace(use_pallas_csr=False), mesh)
        assert m_f.engaged_path == want, m_f.path_reason
        rng = np.random.default_rng(7)
        F0 = rng.uniform(0.0, 1.0, size=(g.num_nodes, k))
        s_f, s_x = _run_steps(m_f, F0), _run_steps(m_x, F0)
        n = g.num_nodes
        np.testing.assert_allclose(
            np.asarray(s_f.F)[:n, :k], np.asarray(s_x.F)[:n, :k],
            rtol=3e-5, atol=3e-5,
        )
        np.testing.assert_allclose(float(s_f.llh), float(s_x.llh), rtol=1e-5)


@pytest.fixture(scope="module")
def store_problem(tmp_path_factory):
    from bigclam_tpu.graph.store import compile_graph_cache

    tmp = tmp_path_factory.mktemp("fused_store")
    edges = []
    for base in (0, 12):
        for i in range(12):
            for j in range(i + 1, 12):
                edges.append((base + i, base + j))
    edges.append((11, 12))
    g = graph_from_edges(edges, num_nodes=24)
    text = tmp / "g.txt"
    with open(text, "w") as f:
        for a, b in edges:
            f.write(f"{a}\t{b}\n")
    store = compile_graph_cache(
        str(text), str(tmp / "cache"), num_shards=4, chunk_bytes=64
    )
    F0 = np.random.default_rng(5).uniform(0.1, 1.0, size=(24, 2))
    return g, store, F0


@pytest.mark.parametrize("kb", [0, 1])
def test_store_fused_bitidentical_and_kb_gap_closed(store_problem, kb):
    """Store-built fused runs == in-memory fused runs, bit for bit — and
    kb=1 is the previously-refused K-blocked large-K store layout, now
    engaging the fused kernels on flat store tiles (no XLA fallback)."""
    from bigclam_tpu.parallel import (
        ShardedBigClamModel,
        StoreShardedBigClamModel,
        make_mesh,
    )

    g, store, F0 = store_problem
    cfg = _cfg(
        num_communities=2, csr_block_b=3, max_iters=6, conv_tol=0.0,
        csr_k_block=kb,
    )
    mesh = make_mesh((4, 1), jax.devices()[:4])
    want = "csr_fused_kb" if kb else "csr_fused"
    refm = ShardedBigClamModel(g, cfg, mesh)
    assert refm.engaged_path == want, refm.path_reason
    ref = refm.fit(F0)
    m = StoreShardedBigClamModel(store, cfg, mesh)
    assert m.engaged_path == want, m.path_reason    # no XLA fallback
    got = m.fit(F0)
    np.testing.assert_allclose(got.F, ref.F, rtol=0, atol=0)
    assert got.llh_history == ref.llh_history


# --------------------------------------------------------------------------
# sparse member-merge kernel
# --------------------------------------------------------------------------


def _member_rows(rng, e, m, k, fill=0.6):
    """Sorted unique member-id rows with sentinel (k) padding + weights."""
    ids = np.full((e, m), k, np.int32)
    w = np.zeros((e, m), np.float32)
    for r in range(e):
        cnt = int(rng.integers(0, m + 1) * fill) if fill < 1 else m
        pick = rng.choice(k, size=min(cnt, k), replace=False)
        pick = np.sort(pick)
        ids[r, : pick.size] = pick
        w[r, : pick.size] = rng.random(pick.size).astype(np.float32)
    return ids, w


class TestSparseMergeKernel:
    def test_merge_exact_vs_searchsorted(self):
        from bigclam_tpu.ops.sparse_members import (
            member_lookup,
            member_lookup_pallas,
        )

        rng = np.random.default_rng(11)
        e, m, k = 53, 8, 20          # e deliberately not a block multiple
        iv, wv = _member_rows(rng, e, m, k)
        iu, _ = _member_rows(rng, e, m, k)
        ref = member_lookup(
            jnp.asarray(iv), jnp.asarray(wv), jnp.asarray(iu), k
        )
        got = member_lookup_pallas(
            jnp.asarray(iv), jnp.asarray(wv), jnp.asarray(iu), k,
            interpret=True,
        )
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))

    def test_merge_all_sentinel_rows(self):
        """Sentinel-only rows (empty member lists) produce exact zeros on
        both sides — incl. the sentinel==sentinel id collision the k_pad
        guard must exclude."""
        from bigclam_tpu.ops.sparse_members import (
            member_lookup,
            member_lookup_pallas,
        )

        e, m, k = 9, 4, 7
        iv = np.full((e, m), k, np.int32)
        wv = np.zeros((e, m), np.float32)
        iu = np.full((e, m), k, np.int32)
        got = member_lookup_pallas(
            jnp.asarray(iv), jnp.asarray(wv), jnp.asarray(iu), k,
            interpret=True,
        )
        assert np.all(np.asarray(got) == 0.0)
        np.testing.assert_array_equal(
            np.asarray(got),
            np.asarray(member_lookup(
                jnp.asarray(iv), jnp.asarray(wv), jnp.asarray(iu), k
            )),
        )

    def test_sparse_trajectory_bitidentical_incl_truncation(self):
        """Full sparse fits, merge kernel vs searchsorted, M < K (the
        truncation regime: init drops entries beyond top-M): bit-identical
        state — the merge is exact, not merely close."""
        from bigclam_tpu.models.sparse import SparseBigClamModel

        rng = np.random.default_rng(12)
        g = _random_graph(13, n=40, p=0.2)
        k = 8
        cfg = BigClamConfig(
            num_communities=k, representation="sparse", sparse_m=4,
            dtype="float32", edge_chunk=64,
        )
        F0 = rng.uniform(0.0, 1.0, size=(g.num_nodes, k))
        m_x = SparseBigClamModel(g, cfg.replace(sparse_pallas_merge=False))
        m_p = SparseBigClamModel(
            g, cfg.replace(sparse_pallas_merge=True, pallas_interpret=True)
        )
        assert m_x.engaged_path == "sparse_xla"
        assert m_p.engaged_path == "sparse_merge_pallas"
        s_x, s_p = _run_steps(m_x, F0, 4), _run_steps(m_p, F0, 4)
        np.testing.assert_array_equal(np.asarray(s_p.F), np.asarray(s_x.F))
        np.testing.assert_array_equal(
            np.asarray(s_p.ids), np.asarray(s_x.ids)
        )
        assert float(s_p.llh) == float(s_x.llh)


# --------------------------------------------------------------------------
# step identity, VMEM estimate, memory transients, ledger refusal
# --------------------------------------------------------------------------


def test_fused_split_xla_never_share_a_step_key():
    """fused / split / xla configs compile distinct steps: their
    step_cfg_keys are pairwise distinct (the in-model step cache and the
    obs compile counters key on it), and the sparse merge flag is
    step-baked the same way."""
    xla = _cfg(use_pallas_csr=False)
    split = _cfg(csr_fused=False)
    fused = _cfg()
    keys = {step_cfg_key(c) for c in (xla, split, fused)}
    assert len(keys) == 3
    s_x = BigClamConfig(representation="sparse", sparse_pallas_merge=False)
    s_p = BigClamConfig(representation="sparse", sparse_pallas_merge=True)
    assert step_cfg_key(s_x) != step_cfg_key(s_p)


def test_fused_step_cache_never_mixes(rng):
    """One model's rebuild_step cache: flipping a HOST-ONLY field reuses
    the compiled step; the fused/split axis is not host-only (sanity on
    the cache keying the pin above relies on)."""
    g = _random_graph(20, n=37)
    m = BigClamModel(g, _cfg())
    step0 = m._step
    m.cfg = m.cfg.replace(conv_tol=0.5)          # host-only field
    m.rebuild_step()
    assert m._step is step0                       # cache hit


def test_vmem_estimate_counts_double_buffered_streams():
    from bigclam_tpu.ops.pallas_csr import (
        VMEM_BUDGET,
        fit_tile_shape,
        kernel_vmem_bytes,
        largest_fitting_kblock,
    )

    b, t, k = 256, 512, 1024
    # the pipeline holds TWO copies of the (t, k) fd stream and two of
    # each (b, k) input block — the estimate must charge at least those
    assert kernel_vmem_bytes(b, t, k) >= 4 * (2 * t * k + 4 * b * k)
    assert kernel_vmem_bytes(b, t, k, fused=True) >= 4 * (2 * t * k)
    # auto-shrink respects the budget under both estimates
    for fused in (False, True):
        shape = fit_tile_shape(b, t, 2048, fused=fused)
        if shape is not None:
            assert kernel_vmem_bytes(
                *shape, 2048, fused=fused
            ) <= VMEM_BUDGET
        found = largest_fitting_kblock(b, t, 25600, fused=fused)
        assert found is not None
        kc, shape = found
        assert kc % 128 == 0 and 25600 % kc == 0
        assert kernel_vmem_bytes(*shape, kc, fused=fused) <= VMEM_BUDGET


def test_memory_transients_repriced_for_fused(rng):
    """Fused engagement re-prices the dst-row transient: the HBM fd
    gather disappears from the model, the (2, T, Kc) DMA double buffer
    appears — and modeled==measured stays EXACT on the CPU fake."""
    g = _random_graph(21, n=37)
    m_s = BigClamModel(g, _cfg(csr_fused=False))
    m_f = BigClamModel(g, _cfg())
    bs, bf = m_s.memory.buffer_bytes(), m_f.memory.buffer_bytes()
    assert "transient/fd_gather" in bs
    assert "transient/fd_gather" not in bf
    assert "transient/fd_dma_scratch" in bf
    isz = 4
    assert bf["transient/fd_dma_scratch"] == 2 * m_f._tiles.tile_t * (
        m_f.k_pad
    ) * isz
    # the fd elimination: the fused transient is smaller than the split
    # fd gather it replaces
    assert bf["transient/fd_dma_scratch"] < bs["transient/fd_gather"]
    # reconciliation stays exact (state+graph addressable target)
    st = m_f.init_state(
        rng.uniform(0.0, 1.0, size=(g.num_nodes, 6))
    )
    recon = m_f.memory_reconcile(st, emit=False)
    assert recon["ok"] and recon["drift_frac"] == 0.0


def test_roofline_fused_drops_fd_bytes():
    """bench.roofline_model_fused: no fd round-trip — modeled bytes per
    edge-iteration ≤ 0.6x the split model at the K=128 bench point (the
    ISSUE 13 acceptance bound)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(os.path.dirname(__file__), "..", "bench.py")
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    split = bench.roofline_model(128)["bytes_per_edge_iter"]
    fused = bench.roofline_model_fused(128)["bytes_per_edge_iter"]
    assert fused <= 0.6 * split
    assert bench.roofline_model_fused(128)["variant"] == "fused"


def test_ledger_kernel_path_refuses_cross_baseline():
    """fused / split / xla records never share a perf-ledger baseline:
    kernel_path joins the match key."""
    from bigclam_tpu.obs.ledger import build_record, match_key

    def rep(path):
        return {
            "run": f"r-{path}", "entry": "fit", "wall_s": 1.0,
            "fingerprint": {
                "host": "h", "platform": "linux", "backend": "cpu",
                "device_kind": "cpu", "devices": 1,
            },
            "compiles": {"count": 1, "by_key": {"BigClamModel:a": {}}},
            "spans": {"seconds": {"fit": 1.0}},
            "final": {"llh": -1.0, "kernel_path": path},
        }

    fused = build_record(rep("csr_fused"), [0.01])
    split = build_record(rep("csr"), [0.01])
    xla = build_record(rep("xla"), [0.01])
    fused2 = build_record(rep("csr_fused"), [0.01])
    assert fused["kernel_path"] == "csr_fused"
    assert match_key(fused) == match_key(fused2)
    assert match_key(fused) != match_key(split)
    assert match_key(fused) != match_key(xla)
    assert match_key(split) != match_key(xla)


def test_report_renders_kernel_paths(tmp_path, rng):
    """`cli report` surfaces the resolved kernel path of every model
    build (satellite: a silent fallback must be visible in the report)."""
    from bigclam_tpu.obs.report import render, render_json
    from bigclam_tpu.obs.telemetry import RunTelemetry, install, uninstall

    g = _random_graph(22, n=37)
    tel = install(RunTelemetry(str(tmp_path / "t"), entry="fit"))
    try:
        BigClamModel(g, _cfg())                      # fused build
        BigClamModel(g, _cfg(use_pallas_csr=False))  # xla fallback build
    finally:
        tel.finalize()
        uninstall(tel)
    text, errors = render(str(tmp_path / "t"))
    assert errors == 0
    assert "kernel paths" in text
    assert "csr_fused" in text
    obj, _ = render_json(str(tmp_path / "t"))
    paths = {e["path"] for e in obj["kernel_paths"]}
    assert {"csr_fused", "xla"} <= paths
    reasons = {
        e["path"]: e["reason"] for e in obj["kernel_paths"]
    }
    assert "use_pallas_csr=False" in reasons["xla"]
