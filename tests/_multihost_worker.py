"""Worker for the TRUE multi-process jax.distributed tests (SURVEY.md §4.4).

Launched as `python _multihost_worker.py <port> <process_id> <out.npz>
[mode] [ckpt_root]` by tests/test_multihost.py, twice per round: each
process contributes 2 CPU devices to a 4-device (nodes=4, k=1) mesh, joins
the process group through initialize_distributed's env-var resolution path,
runs a short sharded fit (put_process_local placement, fetch_global
readback), and process 0 writes the trajectory for the parent to compare
against the single-process run.

Modes:
  fit (default)  full fit, process 0 writes F + llh_history to out.npz
  ckpt-write     fit max_iters=4 with checkpoint_every=2, each process
                 handed a CheckpointManager at ckpt_root/p<pid>; asserts
                 the single-writer gate (only process 0's dir gets files)
  ckpt-resume    fit max_iters=8 resuming from the SHARED ckpt_root/p0
                 (all processes read; only process 0 keeps writing);
                 process 0 writes the resumed trajectory to out.npz
  corrupt-resume ckpt-resume minus the latest-step assert: the parent
                 corrupted the newest checkpoint(s), so restore must fall
                 back to the newest VALID one on every process and the
                 resumed trajectory must still match the uninterrupted
                 run (ISSUE 5 multi-corrupt fallback, 2-proc variant)
  store          fit through StoreShardedBigClamModel from the graph cache
                 at ckpt_root (compiled by the parent): asserts this
                 process loaded ONLY its own shard files and its own node
                 ranges, then process 0 writes the trajectory
  store-csr      ISSUE 9: store-backed fit with use_pallas_csr=True
                 (interpret mode) — tiles built from THIS host's shard
                 files only (files_read), baked seed scores loaded per
                 host (load_host_seed_scores isolation), trajectory
                 written for the parent to compare against the in-memory
                 sharded CSR run
  store-ring     ISSUE 9: StoreRingBigClamModel — ring (shard, phase)
                 buckets built from this host's shard files only, bucket
                 pad agreed via the one-int cross-host max exchange;
                 trajectory must match RingBigClamModel(balance=False)
  telemetry      fit with RunTelemetry pointed at the SHARED dir ckpt_root:
                 asserts the single-writer event-log gate (only process 0
                 may hold the events.jsonl handle) while every process
                 writes its own run_report(.p<i>).json for the parent to
                 merge
"""

import os
import sys

import numpy as np

# repo root on sys.path: the package is run from a checkout, not installed
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def problem():
    """Deterministic (graph, cfg, F0) shared by worker and parent test."""
    from bigclam_tpu.config import BigClamConfig
    from bigclam_tpu.graph.ingest import graph_from_edges

    edges = []
    for base in (0, 12):                 # two 12-cliques + one bridge
        for i in range(12):
            for j in range(i + 1, 12):
                edges.append((base + i, base + j))
    edges.append((11, 12))
    g = graph_from_edges(edges, num_nodes=24)
    cfg = BigClamConfig(
        num_communities=2, dtype="float64", max_iters=8, conv_tol=0.0
    )
    F0 = np.random.default_rng(5).uniform(0.1, 1.0, size=(24, 2))
    return g, cfg, F0


def quality_cfg(cfg):
    """The quality-device schedule — single source for worker AND parent
    (the test compares the two runs' annealing trajectories)."""
    return cfg.replace(
        quality_mode=True, restart_cycles=3, restart_tol=0.0, max_iters=6
    )


def store_csr_cfg(cfg):
    """Interpret-mode blocked-CSR config for the store-backed trainers —
    single source for worker AND parent (rows_per_shard=6 on the 24-node
    problem at 4 shards, so block_b=3 divides it)."""
    return cfg.replace(
        dtype="float32", max_iters=6, use_pallas_csr=True,
        pallas_interpret=True, csr_block_b=3, csr_tile_t=8,
    )


def main() -> None:
    port, pid, out_path = sys.argv[1], sys.argv[2], sys.argv[3]
    mode = sys.argv[4] if len(sys.argv) > 4 else "fit"
    ckpt_root = sys.argv[5] if len(sys.argv) > 5 else None
    import jax

    # the outer env may pin a TPU platform; config updates before first
    # backend use are the reliable override (see tests/conftest.py)
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    from bigclam_tpu.utils.dist import request_cpu_devices

    request_cpu_devices(2)
    os.environ["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
    os.environ["JAX_NUM_PROCESSES"] = "2"
    os.environ["JAX_PROCESS_ID"] = pid

    from bigclam_tpu.parallel.multihost import (
        fetch_global,
        initialize_distributed,
        make_multihost_mesh,
    )

    assert initialize_distributed() is True
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 4, jax.devices()

    from bigclam_tpu.parallel import ShardedBigClamModel

    g, cfg, F0 = problem()
    mesh = make_multihost_mesh((4, 1))

    if mode == "ckpt-write":
        from bigclam_tpu.utils.checkpoint import CheckpointManager

        cfg_w = cfg.replace(max_iters=4, checkpoint_every=2)
        my_dir = os.path.join(ckpt_root, f"p{pid}")
        model = ShardedBigClamModel(g, cfg_w, mesh)
        model.fit(F0, checkpoints=CheckpointManager(my_dir))
        files = [f for f in os.listdir(my_dir) if f.endswith(".npz")]
        if jax.process_index() == 0:
            assert files, "primary process wrote no checkpoints"
        else:
            assert not files, (
                f"non-primary process wrote checkpoints: {files}"
            )
        jax.distributed.shutdown()
        return

    if mode in ("ckpt-resume", "corrupt-resume"):
        from bigclam_tpu.utils.checkpoint import CheckpointManager

        cfg_r = cfg.replace(checkpoint_every=2)
        shared = os.path.join(ckpt_root, "p0")   # every process READS p0's
        model = ShardedBigClamModel(g, cfg_r, mesh)
        ckpt = CheckpointManager(shared)
        if mode == "ckpt-resume":
            assert ckpt.latest_step() == 4, ckpt.steps()
        else:
            # the parent corrupted newer checkpoints: restore must fall
            # back past them (crc/zip validation) on EVERY process
            assert ckpt.latest_step() > 2, ckpt.steps()
            assert ckpt.restore()[0] == 2, "fallback did not engage"
        res = model.fit(F0, checkpoints=ckpt)
        if jax.process_index() == 0:
            np.savez(
                out_path, F=res.F, llh_history=np.asarray(res.llh_history)
            )
        jax.distributed.shutdown()
        return

    if mode == "store":
        from bigclam_tpu.graph.store import GraphStore
        from bigclam_tpu.parallel.sharded import StoreShardedBigClamModel

        store = GraphStore.open(ckpt_root)
        model = StoreShardedBigClamModel(
            store, cfg.replace(use_pallas_csr=False), mesh
        )
        hs = model.host_shard
        # per-host isolation: with 4 shards over 2 processes, this process
        # owns exactly shards [2*pid, 2*pid+2) and read ONLY their blobs
        p = jax.process_index()
        assert hs.shard_ids == (2 * p, 2 * p + 1), hs.shard_ids
        rows = store.rows_per_shard
        assert (hs.lo, hs.hi) == (
            2 * p * rows, min((2 * p + 2) * rows, store.num_nodes)
        ), (hs.lo, hs.hi)
        own = {
            os.path.basename(path)
            for s in hs.shard_ids
            for path in store.shard_files(s)
        }
        assert set(hs.files_read) == own, (hs.files_read, own)

        res = model.fit(F0)
        if jax.process_index() == 0:
            np.savez(
                out_path, F=res.F, llh_history=np.asarray(res.llh_history)
            )
        jax.distributed.shutdown()
        return

    if mode in ("store-csr", "store-ring"):
        from bigclam_tpu.graph.store import GraphStore
        from bigclam_tpu.parallel.multihost import load_host_seed_scores
        from bigclam_tpu.parallel.ring import StoreRingBigClamModel
        from bigclam_tpu.parallel.sharded import StoreShardedBigClamModel

        store = GraphStore.open(ckpt_root)
        p = jax.process_index()
        if mode == "store-csr":
            model = StoreShardedBigClamModel(store, store_csr_cfg(cfg), mesh)
            assert model.engaged_path in ("csr", "csr_fused"), model.path_reason
        else:
            model = StoreRingBigClamModel(
                store, cfg.replace(use_pallas_csr=False), mesh
            )
            assert model.engaged_path == "xla", model.path_reason
        hs = model.host_shard
        assert hs.shard_ids == (2 * p, 2 * p + 1), hs.shard_ids
        own = {
            os.path.basename(path)
            for s in hs.shard_ids
            for path in store.shard_files(s)
        }
        # tile/bucket builds consumed ONLY this host's shard blobs
        assert set(hs.files_read) == own, (hs.files_read, own)
        # baked-seed loading is per-host too: only this host's phi blobs
        ss = load_host_seed_scores(store)
        assert (ss.lo, ss.hi) == (hs.lo, hs.hi), (ss.lo, ss.hi)
        assert set(ss.files_read) == {
            f"shard_{s:05d}.phi.npy" for s in hs.shard_ids
        }, ss.files_read

        res = model.fit(F0)
        if jax.process_index() == 0:
            np.savez(
                out_path, F=res.F, llh_history=np.asarray(res.llh_history)
            )
        jax.distributed.shutdown()
        return

    if mode == "telemetry":
        from bigclam_tpu.obs import RunTelemetry, install, uninstall
        from bigclam_tpu.utils.metrics import MetricsLogger

        # constructed BEFORE the gate decision would be safe (the process
        # group is already up here, but auto_gate=False + commit_gate is
        # the production CLI sequence — exercise it)
        tel = install(
            RunTelemetry(
                ckpt_root, entry="worker-fit", heartbeat_s=60.0,
                auto_gate=False,
            )
        )
        tel.commit_gate()
        model = ShardedBigClamModel(g, cfg, mesh)
        with MetricsLogger(None, echo=False) as ml:
            res = model.fit(
                F0,
                callback=ml.step_callback(
                    g.num_directed_edges, num_nodes=g.num_nodes
                ),
            )
        tel.set_final({"llh": res.llh, "iters": res.num_iters})
        # the single-writer gate: only process 0 holds the events handle
        if jax.process_index() == 0:
            assert tel._fh is not None
        else:
            assert tel._fh is None
        tel.finalize()
        uninstall(tel)
        if jax.process_index() == 0:
            np.savez(
                out_path, F=res.F, llh_history=np.asarray(res.llh_history)
            )
        jax.distributed.shutdown()
        return

    if mode == "quality-device":
        from bigclam_tpu.models.quality import fit_quality_device
        from bigclam_tpu.ops.extraction import (
            extract_communities,
            extract_communities_device,
        )

        model = ShardedBigClamModel(g, quality_cfg(cfg), mesh)
        qres = fit_quality_device(model, F0)
        # device-side extraction must survive process_count() == 2: the
        # membership pairs come off a globally sharded state (fetch_global
        # inside), identical to the host extraction of the fetched F
        final, _llh, _it, _hist = model.fit_state(model.init_state(F0))
        dev = extract_communities_device(
            final.F, model.g,
            num_communities=model.cfg.num_communities, chunk_rows=7,
        )
        host = extract_communities(model.extract_F(final), g)
        assert dev == host, (dev, host)
        if jax.process_index() == 0:
            np.savez(
                out_path, F=qres.fit.F,
                cycles=np.asarray(qres.cycles_llh),
            )
        jax.distributed.shutdown()
        return

    model = ShardedBigClamModel(g, cfg, mesh)
    res = model.fit(F0)

    # exercise fetch_global on a live sharded array too (fit already used it
    # for the result, but assert the round trip explicitly)
    state = model.init_state(F0)
    F_rt = fetch_global(state.F)[: g.num_nodes, : cfg.num_communities]
    np.testing.assert_allclose(F_rt, F0, rtol=0, atol=0)

    if jax.process_index() == 0:
        np.savez(
            out_path, F=res.F, llh_history=np.asarray(res.llh_history)
        )
    jax.distributed.shutdown()


if __name__ == "__main__":
    main()
