"""Self-healing serving fleet (ISSUE 20): typed fail-fast batcher
shutdown and the drain door, wire-fault recovery (torn frame, garbage
line, stall, connect refuse) through the router's bounded reader and
failover, per-query deadlines, tail-latency hedging, elastic membership
reload, the FleetSupervisor's restart/quarantine ladder over real
subprocesses, the router daemon wire, and the `route --stop` idempotent
teardown. Everything here is fast, localhost, and seeded — chaos-marked
but part of tier-1."""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.resilience.faults import FaultPlan, install_plan
from bigclam_tpu.resilience.retry import RetryPolicy
from bigclam_tpu.serve.batcher import (
    BatcherStopped,
    OverloadedError,
    RequestBatcher,
)
from bigclam_tpu.serve.fleet import LocalReplica, ReplicaServer, ShardReplica
from bigclam_tpu.serve.router import FleetRouter, RouterServer, TcpReplica
from bigclam_tpu.serve.snapshot import publish_fleet_snapshot
from bigclam_tpu.serve.supervise import FleetSupervisor

pytestmark = pytest.mark.chaos

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
N, K = 24, 3


def _wait_for(pred, timeout=30.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


@pytest.fixture(scope="module")
def fleet1(tmp_path_factory):
    """Single-shard fleet publication (numpy-only): every replica covers
    the whole row range, so shard-0 replica sets of any size are valid."""
    rng = np.random.default_rng(7)
    F = rng.uniform(0.0, 1.0, size=(N, K))
    d = str(tmp_path_factory.mktemp("fleet1") / "snaps")
    publish_fleet_snapshot(
        d, [(0, N)], F=F, num_edges=40,
        cfg=BigClamConfig(num_communities=K),
    )
    return d


@pytest.fixture()
def faults():
    """install_plan with guaranteed cleanup (the plan is process-global)."""

    def _install(*specs, seed=0):
        return install_plan(
            FaultPlan.from_spec({"seed": seed, "faults": list(specs)})
        )

    yield _install
    install_plan(None)


# ------------------------------------------------ batcher shutdown (sat 3)
def test_batcher_stop_fails_queued_futures_fast_and_typed():
    """stop() with a wedged handler: every still-QUEUED future fails
    IMMEDIATELY with BatcherStopped (no hang, no silent drop) — the
    join happens after the strand sweep, so a stuck batch can't hold
    them hostage. Submits after stop raise the same typed error."""
    entered = threading.Event()
    release = threading.Event()

    def handler(batch):
        entered.set()
        release.wait(10.0)
        for r in batch:
            r.future.set_result(r.payload)

    b = RequestBatcher(handler, max_batch=1, budget_s=0.0)
    b.start()
    first = b.submit("warm")
    assert entered.wait(2.0)          # handler wedged; queue grows
    queued = [b.submit(i) for i in range(3)]
    t0 = time.perf_counter()
    b.stop(timeout=0.2)               # flusher still wedged: join times out
    for f in queued:
        with pytest.raises(BatcherStopped):
            f.result(0.5)
    assert time.perf_counter() - t0 < 2.0
    with pytest.raises(BatcherStopped):
        b.submit("late")
    release.set()                     # in-flight batch finishes normally
    assert first.result(2.0) == "warm"


def test_batcher_drain_then_stop_strands_nothing():
    """The zero-drop ordering: close_door() sheds NEW submits fast with
    OverloadedError, already-admitted work completes, drain() observes a
    quiescent batcher, and stop() finds nothing to strand."""
    b = RequestBatcher(lambda batch: [r.future.set_result(r.payload * 2)
                                      for r in batch],
                       max_batch=4, budget_s=0.001)
    b.start()
    futs = [b.submit(i) for i in range(8)]
    b.close_door()
    assert b.draining
    shed = b.submit("rejected")
    assert shed.done()
    with pytest.raises(OverloadedError):
        shed.result(0.0)
    assert b.shed_door == 1 and b.shed == 1
    b.drain(timeout=5.0)
    assert [f.result(2.0) for f in futs] == [i * 2 for i in range(8)]
    b.stop()                          # nothing queued: nothing stranded


# ------------------------------------------------------ replica drain op
def test_replica_drain_wire_op_acks_then_exits(fleet1):
    srv = ReplicaServer(ShardReplica(fleet1, 0), port=0)
    t = TcpReplica(srv.host, srv.port, timeout_s=10.0)
    try:
        st = t.request({"family": "status"})
        assert "draining" not in st
        ack = t.request({"family": "drain"})
        assert ack["ok"] is True and ack["draining"] is True
        assert srv.serve_until_stopped(10.0)
        # the listener is gone: a fresh connection cannot be served
        with pytest.raises((ConnectionError, TimeoutError, OSError)):
            TcpReplica(srv.host, srv.port, timeout_s=0.5).request(
                {"family": "status"}
            )
    finally:
        t.close()
        srv.close()


# --------------------------------------------------- wire faults (sat 2)
def test_torn_frame_recovered_on_fresh_connection(fleet1, faults):
    """A peer killed mid-write leaves half a frame with no newline: the
    bounded reader must classify it as a transport failure (never hand
    it to the json decoder) and the retry on a fresh connection wins."""
    faults({"kind": "torn_frame", "site": "replica.answer_write", "at": 0})
    srv = ReplicaServer(ShardReplica(fleet1, 0), port=0)
    t = TcpReplica(srv.host, srv.port, timeout_s=10.0)
    try:
        t0 = time.perf_counter()
        ans = t.request({"family": "communities_of", "u": 0})
        assert "communities" in ans and "error" not in ans
        assert time.perf_counter() - t0 < 5.0   # no wedged reader
    finally:
        t.close()
        srv.close()


def test_garbage_line_recovered_on_fresh_connection(fleet1, faults):
    faults({"kind": "garbage_line", "site": "replica.answer_write",
            "at": 0})
    srv = ReplicaServer(ShardReplica(fleet1, 0), port=0)
    t = TcpReplica(srv.host, srv.port, timeout_s=10.0)
    try:
        ans = t.request({"family": "communities_of", "u": 1})
        assert "communities" in ans and "error" not in ans
    finally:
        t.close()
        srv.close()


def test_connect_refuse_consumed_once_then_reaches_replica(fleet1, faults):
    faults({"kind": "connect_refuse", "site": "wire.connect", "at": 0})
    srv = ReplicaServer(ShardReplica(fleet1, 0), port=0)
    t = TcpReplica(srv.host, srv.port, timeout_s=10.0)
    try:
        st = t.request({"family": "status"})
        assert st["shard"] == 0
    finally:
        t.close()
        srv.close()


def test_stalled_replica_bounded_then_failover(fleet1, faults):
    """A stall longer than the request timeout on one replica: the
    router's read is BOUNDED (timeout, socket closed), the sub-query
    fails over to the healthy replica, and the client sees a correct
    retried answer — never an error, never an unbounded wait."""
    faults({"kind": "stall", "site": "replica.answer_write",
            "seconds": 3.0, "at": 0})
    srvs = [ReplicaServer(ShardReplica(fleet1, 0), port=0)
            for _ in range(2)]
    eps = [TcpReplica(s.host, s.port, timeout_s=10.0) for s in srvs]
    router = FleetRouter(fleet1, eps, request_timeout_s=0.4)
    try:
        t0 = time.perf_counter()
        ans = router.route({"family": "communities_of", "u": 2})
        assert "error" not in ans and "communities" in ans
        assert time.perf_counter() - t0 < 3.0
        st = router.stats()
        assert st["transport_failovers"] >= 1
        assert st["router_retries"] >= 1
    finally:
        router.close()
        for s in srvs:
            s.close()


# --------------------------------------------------- deadline + hedging
def test_router_deadline_exceeded_is_typed_and_counted(fleet1,
                                                       monkeypatch):
    """A single wedged replica and a 150ms query deadline: the answer is
    {"error": "deadline_exceeded"} within the budget (plus slack), and
    the counter + rate ride stats() for the ledger."""
    monkeypatch.setenv(
        "BIGCLAM_QTRACE_FAULT",
        json.dumps({"hop": "decode", "delay_s": 5.0}),
    )
    srv = ReplicaServer(ShardReplica(fleet1, 0), port=0)
    monkeypatch.delenv("BIGCLAM_QTRACE_FAULT")
    router = FleetRouter(
        fleet1, [TcpReplica(srv.host, srv.port, timeout_s=10.0)],
        request_timeout_s=10.0, deadline_s=0.15, retry_rounds=1,
    )
    try:
        t0 = time.perf_counter()
        ans = router.route({"family": "communities_of", "u": 0})
        assert ans == {"error": "deadline_exceeded"}
        assert time.perf_counter() - t0 < 3.0
        st = router.stats()
        assert st["deadline_exceeded"] == 1
        assert st["deadline_exceeded_rate"] > 0
    finally:
        router.close()
        srv.close()


def test_hedged_read_wins_on_slow_primary(fleet1):
    """Tail-latency hedging: the duplicate fired after the explicit
    delay beats a slow primary; the hedge is counted, the winner's
    answer is correct, and the loser's eventual return is not punished
    as a failure."""

    class _Slow(LocalReplica):
        def request(self, q, timeout=None, handle=None):
            if q.get("family") != "status":
                time.sleep(0.25)
            return super().request(q, timeout=timeout, handle=handle)

    rep = ShardReplica(fleet1, 0)
    router = FleetRouter(
        fleet1, [_Slow(rep), LocalReplica(rep)],
        hedge=True, hedge_delay_s=0.02,
    )
    try:
        for u in range(3):
            ans = router.route({"family": "communities_of", "u": u})
            assert "error" not in ans and "communities" in ans
        st = router.stats()
        assert st["hedged"] >= 1
        assert st["hedge_wins"] >= 1
        assert st["hedged_rate"] > 0
        assert st["serve_errors"] == 0
    finally:
        router.close()


# ----------------------------------------------------- elastic membership
def _write_members(path, seq, members):
    doc = {"version": 1, "seq": seq, "control": "127.0.0.1:0",
           "members": members}
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)


def test_membership_file_reload_reconciles_endpoints(fleet1, tmp_path):
    """The router's endpoint set is the watched membership file: only
    state == "up" members are admitted, a seq bump with a drained member
    drops (and closes) its transport, and serving continues on the
    survivors."""
    srvs = [ReplicaServer(ShardReplica(fleet1, 0), port=0)
            for _ in range(2)]
    members_path = str(tmp_path / "members.json")

    def entry(i, state):
        return {"id": f"s0r{i}", "shard": 0,
                "endpoint": f"{srvs[i].host}:{srvs[i].port}",
                "state": state, "pid": 0, "restarts": 0}

    _write_members(members_path, 1, [entry(0, "up"), entry(1, "up")])
    router = FleetRouter(fleet1, members_file=members_path)
    try:
        assert len(router.endpoints) == 2
        assert router.membership_reloads == 1
        ans = router.route({"family": "members_of", "c": 0})
        assert "error" not in ans
        _write_members(members_path, 2,
                       [entry(0, "up"), entry(1, "draining")])
        router.refresh()
        assert len(router.endpoints) == 1
        assert router.membership_reloads == 2
        ans = router.route({"family": "members_of", "c": 0})
        assert "error" not in ans
        # a torn/unchanged file keeps the current set
        with open(members_path, "w") as f:
            f.write("{not json")
        router.refresh()
        assert len(router.endpoints) == 1
    finally:
        router.close()
        for s in srvs:
            s.close()


# ------------------------------------------------- supervisor subprocesses
@pytest.fixture()
def child_env(monkeypatch):
    monkeypatch.setenv(
        "PYTHONPATH",
        REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
    )


def test_supervisor_restarts_killed_replica(fleet1, tmp_path, child_env):
    """kill -9 a supervised replica: the monitor respawns it with the
    RetryPolicy backoff, the membership file republishes the new
    endpoint, and the rejoined replica answers at the newest
    generation."""
    members = str(tmp_path / "members.json")
    sup = FleetSupervisor(
        fleet1, members, shards=1, replicas=1,
        policy=RetryPolicy(base_s=0.05, max_s=0.2, seed=0),
        stable_s=30.0, poll_s=0.05, hello_timeout_s=60.0,
    )
    sup.up()
    try:
        assert sup.wait_all_up(timeout=60.0)
        with open(members) as f:
            doc = json.load(f)
        (m,) = doc["members"]
        assert m["state"] == "up" and m["endpoint"]
        pid0 = m["pid"]
        os.kill(pid0, signal.SIGKILL)

        def healed():
            st = sup.status()
            (mm,) = st["members"]
            return (st["replica_restarts"] >= 1 and mm["state"] == "up"
                    and mm["pid"] not in (None, pid0))

        assert _wait_for(healed, timeout=60.0)
        st = sup.status()
        (m2,) = st["members"]
        t = TcpReplica(*m2["endpoint"].rsplit(":", 1), timeout_s=10.0)
        try:
            ans = t.request({"family": "status"})
            assert ans["shard"] == 0 and ans["generations"]
        finally:
            t.close()
        with open(members) as f:
            doc2 = json.load(f)
        assert doc2["seq"] > doc["seq"]
        assert doc2["members"][0]["restarts"] >= 1
    finally:
        sup.down()


def test_supervisor_quarantines_crash_loop(fleet1, tmp_path, monkeypatch,
                                           child_env):
    """A replica killed at replica.start on EVERY spawn (the env fault
    plan re-fires in each fresh process): after quarantine_after
    consecutive failures the slot is parked "quarantined" instead of
    burning CPU on a doomed respawn loop."""
    monkeypatch.setenv(
        "BIGCLAM_FAULTS",
        json.dumps({"faults": [
            {"kind": "kill", "site": "replica.start", "at": 0},
        ]}),
    )
    members = str(tmp_path / "members.json")
    sup = FleetSupervisor(
        fleet1, members, shards=1, replicas=1,
        policy=RetryPolicy(base_s=0.02, max_s=0.05, seed=0),
        quarantine_after=2, stable_s=30.0, poll_s=0.05,
    )
    sup.up()
    try:
        assert _wait_for(
            lambda: sup.status()["quarantined"] >= 1, timeout=60.0
        )
        st = sup.status()
        (m,) = st["members"]
        assert m["state"] == "quarantined"
        assert st["replica_restarts"] == 2   # quarantine_after respawns
        with open(members) as f:
            doc = json.load(f)
        assert doc["members"][0]["state"] == "quarantined"
    finally:
        out = sup.down()
        assert out["quarantined"] == 1


def test_supervisor_drain_and_add_replica(fleet1, tmp_path, child_env):
    """Elastic membership: add_replica grows the roster with a fresh
    member id; drain flips the member through draining -> stopped with
    the replica exiting clean (rc 0, not a kill)."""
    members = str(tmp_path / "members.json")
    sup = FleetSupervisor(
        fleet1, members, shards=1, replicas=1,
        policy=RetryPolicy(base_s=0.05, max_s=0.2, seed=0),
        stable_s=30.0, poll_s=0.05, drain_grace_s=0.05,
    )
    sup.up()
    try:
        assert sup.wait_all_up(timeout=60.0)
        entry = sup.add_replica(0)
        assert entry["id"] == "s0r1"
        assert sup.wait_all_up(timeout=60.0)
        assert sup.drain("s0r0", timeout=30.0)
        st = sup.status()
        states = {m["id"]: m["state"] for m in st["members"]}
        assert states == {"s0r0": "stopped", "s0r1": "up"}
        with open(members) as f:
            doc = json.load(f)
        # stopped members leave the published roster
        assert [m["id"] for m in doc["members"]] == ["s0r1"]
        # draining an already-stopped member is a clean refusal
        assert not sup.drain("s0r0")
    finally:
        sup.down()


# ------------------------------------------------------- router daemon
def test_router_server_wire_roundtrip_status_and_stop(fleet1):
    rep = ShardReplica(fleet1, 0)
    server = RouterServer(FleetRouter(fleet1, [LocalReplica(rep)]))
    try:
        with socket.create_connection(
            (server.host, server.port), timeout=10.0
        ) as sock:
            sock.settimeout(10.0)
            f = sock.makefile("rb")

            def ask(q):
                sock.sendall((json.dumps(q) + "\n").encode())
                return json.loads(f.readline())

            st = ask({"family": "status"})
            assert st["serving_generation"] is not None
            ans = ask({"family": "communities_of", "u": 0})
            assert "communities" in ans and "error" not in ans
            assert ask({"family": "not_a_family"}).get("error")
            assert ask({"family": "stop"})["ok"] is True
        assert server.serve_until_stopped(10.0)
    finally:
        server.close()


def test_route_stop_with_dead_endpoint_exits_zero(fleet1, capsys):
    """`route --stop` against a fleet where one endpoint is ALREADY
    gone: the survivor is torn down, the dead endpoint is a note (not a
    failure), and the exit code is 0 — teardown is idempotent."""
    from bigclam_tpu.cli import main

    srv = ReplicaServer(ShardReplica(fleet1, 0), port=0)
    # a port with nothing behind it
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    dead_port = probe.getsockname()[1]
    probe.close()
    try:
        rc = main([
            "route", "--fleet", fleet1,
            "--endpoints",
            f"{srv.host}:{srv.port},127.0.0.1:{dead_port}",
            "--stop",
        ])
        assert rc == 0
        cap = capsys.readouterr()
        out = json.loads(cap.out.strip().splitlines()[-1])
        assert out == {"stopped": 1, "already_down": 1, "of": 2}
        assert "already down" in cap.err
        assert srv.serve_until_stopped(10.0)
    finally:
        srv.close()


# ------------------------------------------------------------ perf ledger
def test_ledger_self_healing_fields_and_verdicts():
    from bigclam_tpu.obs import ledger as L

    def rep(entry="route", **final):
        base_final = {
            "serve_queries": 1000, "serve_p50_s": 0.001,
            "serve_p99_s": 0.002, "serve_qps": 500.0,
            "serve_mix": "members_of:1.00",
        }
        base_final.update(final)
        return {
            "run": "r", "entry": entry, "pid": 0, "processes": 1,
            "wall_s": 1.0,
            "fingerprint": {"host": "h", "backend": "cpu",
                            "device_kind": "cpu", "platform": "cpu"},
            "compiles": {"count": 0, "by_key": {}},
            "spans": {"seconds": {}},
            "final": base_final,
        }

    base = L.build_record(rep(
        router_retries=2, hedged_rate=0.01, deadline_exceeded_rate=0.001,
    ))
    assert base["router_retries"] == 2
    assert base["hedged_rate"] == 0.01
    assert base["deadline_exceeded_rate"] == 0.001
    worse = L.build_record(rep(
        router_retries=40, hedged_rate=0.5, deadline_exceeded_rate=0.2,
    ))
    d = L.diff_records(base, worse)
    flagged = {c["metric"] for c in d["checks"] if c.get("regression")}
    assert {"router_retries", "hedged_rate",
            "deadline_exceeded_rate"} <= flagged
    # the supervisor's fleet entry has no serve percentiles — the
    # replica_restarts verdict stands on its own
    fb = L.build_record(rep(
        entry="fleet", replica_restarts=1, serve_p99_s=None,
    ))
    assert fb["replica_restarts"] == 1
    fw = L.build_record(rep(
        entry="fleet", replica_restarts=30, serve_p99_s=None,
    ))
    d2 = L.diff_records(fb, fw)
    bad = [c for c in d2["checks"]
           if c["metric"] == "replica_restarts" and c.get("regression")]
    assert bad and d2["regression"]
