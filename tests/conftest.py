"""Test environment: 8 virtual CPU devices + float64.

Must run before jax is imported anywhere (SURVEY.md §4.4): multi-device
sharding tests use XLA's host-platform device-count fake, and trajectory
tests compare against the float64 NumPy spec interpreter.
"""

# The outer environment pins JAX_PLATFORMS to the real TPU and pre-imports
# jaxlib at interpreter startup, so env vars are too late here — jax.config
# before any backend is initialized is the mechanism that actually works
# (request_cpu_devices falls back to the XLA env flag on jax 0.4.x, where
# the config option does not exist and the flag IS still read at init).
import jax  # noqa: E402

from bigclam_tpu.utils.dist import request_cpu_devices  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
request_cpu_devices(8)

import os  # noqa: E402

import numpy as np  # noqa: E402
import pytest  # noqa: E402

REFERENCE_DATA = "/root/reference/data"


def require_reference_data(filename: str) -> str:
    """Path to a shipped reference dataset, or pytest.skip when the file
    is absent — CI containers without the datasets must skip the
    golden-file tests, not error out of their fixtures."""
    path = os.path.join(REFERENCE_DATA, filename)
    if not os.path.exists(path):
        pytest.skip(f"reference dataset not present: {path}")
    return path


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(0)


@pytest.fixture(scope="session")
def toy_graphs():
    """Small hand-checkable graphs: triangle, star, two cliques + bridge."""
    from bigclam_tpu.graph.ingest import graph_from_edges

    triangle = graph_from_edges([(0, 1), (1, 2), (2, 0)])
    star = graph_from_edges([(0, 1), (0, 2), (0, 3), (0, 4)])
    # two 4-cliques {0..3} and {4..7} joined by the bridge 3-4
    cliq = []
    for base in (0, 4):
        for i in range(4):
            for j in range(i + 1, 4):
                cliq.append((base + i, base + j))
    cliq.append((3, 4))
    two_cliques = graph_from_edges(cliq)
    return {"triangle": triangle, "star": star, "two_cliques": two_cliques}


@pytest.fixture(scope="session")
def facebook_graph():
    from bigclam_tpu.graph.ingest import build_graph

    return build_graph(require_reference_data("facebook_combined.txt"))
