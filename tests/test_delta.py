"""Incremental graph deltas (ISSUE 15): touched-range delta re-ingest,
warm-start incremental refit, the per-host row-keyed init, the continuous
follow loop, and the refit ledger fields."""

import json
import os
import threading

import numpy as np
import pytest

import jax

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.graph import build_graph
from bigclam_tpu.graph.store import GraphStore, compile_graph_cache
from bigclam_tpu.graph.stream import scan_edge_files
from bigclam_tpu.models import BigClamModel, SparseBigClamModel
from bigclam_tpu.models.bigclam import (
    rowkeyed_init_F,
    rowkeyed_init_rows,
)
from bigclam_tpu.models.refit import (
    expand_halo,
    follow_deltas,
    touched_rows_from_delta,
    warm_start_refit,
)
from bigclam_tpu.obs import RunTelemetry, install, uninstall
from bigclam_tpu.obs import ledger as L
from bigclam_tpu.obs.schema import validate_events_file
from bigclam_tpu.obs.telemetry import EVENTS_NAME
from bigclam_tpu.utils.checkpoint import CheckpointManager

N = 200
SHARDS = 4


def _write_edges(path, edges):
    with open(path, "w") as f:
        for u, v in edges:
            f.write(f"{u}\t{v}\n")


def _base_edges(n=N, extra=500, seed=0):
    """Ring (every id present => internal row == raw id) + random."""
    rng = np.random.default_rng(seed)
    edges = [(i, (i + 1) % n) for i in range(n)]
    edges += [
        (int(u), int(v))
        for u, v in rng.integers(0, n, (extra, 2))
        if u != v
    ]
    return edges


def _delta_edges(lo=0, hi=50, stride=2, shift=9):
    """Edges confined to rows [lo, hi) — touches only their shard."""
    return [
        (i, lo + (i + shift - lo) % (hi - lo))
        for i in range(lo, hi, stride)
        if i != lo + (i + shift - lo) % (hi - lo)
    ]


@pytest.fixture()
def cache(tmp_path):
    text = str(tmp_path / "g.txt")
    _write_edges(text, _base_edges())
    store = compile_graph_cache(
        text, str(tmp_path / "cache"), num_shards=SHARDS
    )
    return store, text


# --------------------------------------------------- delta re-ingest
def test_apply_delta_bit_identical_to_full_build(tmp_path, cache):
    store, text = cache
    delta = str(tmp_path / "delta.txt")
    _write_edges(delta, _delta_edges())
    info = store.apply_delta(delta)
    assert info["delta_seq"] == 1
    assert info["edges_added"] > 0
    combined = str(tmp_path / "combined.txt")
    with open(combined, "w") as f:
        f.write(open(text).read())
        f.write(open(delta).read())
    g_delta = GraphStore.open(store.directory).load_graph()
    g_full = build_graph(combined)
    np.testing.assert_array_equal(
        np.asarray(g_delta.indptr), np.asarray(g_full.indptr)
    )
    np.testing.assert_array_equal(
        np.asarray(g_delta.indices), np.asarray(g_full.indices)
    )
    np.testing.assert_array_equal(g_delta.raw_ids, g_full.raw_ids)


def test_apply_delta_untouched_blobs_and_files_read(tmp_path, cache):
    store, _ = cache
    before = {}
    for s in range(SHARDS):
        ip, dx = store.shard_files(s)
        phi = os.path.join(store.directory, f"shard_{s:05d}.phi.npy")
        before[s] = (
            open(ip, "rb").read(), open(dx, "rb").read(),
            open(phi, "rb").read(),
        )
    delta = str(tmp_path / "delta.txt")
    _write_edges(delta, _delta_edges())     # rows [0, 50): shard 0 only
    info = store.apply_delta(delta)
    assert info["touched_shards"] == [0]
    # only the touched shard's blobs (+ raw_ids) were read
    assert set(info["files_read"]) == {
        "raw_ids.npy", "shard_00000.indptr.npy",
        "shard_00000.indices.npy",
    }
    for s in range(1, SHARDS):
        ip, dx = store.shard_files(s)
        phi = os.path.join(store.directory, f"shard_{s:05d}.phi.npy")
        now = (
            open(ip, "rb").read(), open(dx, "rb").read(),
            open(phi, "rb").read(),
        )
        assert now == before[s], f"untouched shard {s} changed"
    ip0, dx0 = store.shard_files(0)
    assert open(dx0, "rb").read() != before[0][1]


def test_apply_delta_phi_touched_matches_fresh_ingest(tmp_path, cache):
    store, text = cache
    delta = str(tmp_path / "delta.txt")
    _write_edges(delta, _delta_edges())
    info = store.apply_delta(delta)
    assert info["phi_rebaked_shards"] == info["touched_shards"]
    combined = str(tmp_path / "combined.txt")
    with open(combined, "w") as f:
        f.write(open(text).read())
        f.write(open(delta).read())
    fresh = compile_graph_cache(
        combined, str(tmp_path / "cache2"), num_shards=SHARDS
    )
    for s in info["touched_shards"]:
        a = np.load(
            os.path.join(store.directory, f"shard_{s:05d}.phi.npy")
        )
        b = np.load(
            os.path.join(fresh.directory, f"shard_{s:05d}.phi.npy")
        )
        np.testing.assert_array_equal(a, b)


def test_apply_delta_refuses_new_nodes(tmp_path, cache):
    store, _ = cache
    delta = str(tmp_path / "delta.txt")
    _write_edges(delta, [(0, N + 7)])       # N+7 never ingested
    with pytest.raises(ValueError, match="cannot grow N"):
        store.apply_delta(delta)
    assert store.delta_seq == 0             # nothing applied


def test_apply_delta_idempotent_duplicates(tmp_path, cache):
    store, _ = cache
    delta = str(tmp_path / "delta.txt")
    _write_edges(delta, [(0, 1), (1, 2)])   # already in the ring
    info = store.apply_delta(delta)
    assert info["edges_added"] == 0
    assert info["delta_seq"] == 1           # still recorded


def test_apply_delta_empty_file_is_a_noop(tmp_path, cache):
    """An empty/self-loop-only delta must not mutate the manifest:
    recording it would make every future quarantine rebuild depend on
    a file that contributes nothing."""
    store, _ = cache
    delta = str(tmp_path / "empty.txt")
    with open(delta, "w") as f:
        f.write("# nothing\n3 3\n")          # comment + self-loop only
    before = json.load(
        open(os.path.join(store.directory, "manifest.json"))
    )
    info = store.apply_delta(delta)
    assert info["edges_added"] == 0
    assert info["delta_seq"] == 0
    assert info["touched_shards"] == []
    after = json.load(
        open(os.path.join(store.directory, "manifest.json"))
    )
    assert after == before


def test_quarantine_rebuild_replays_deltas(tmp_path, cache):
    store, _ = cache
    delta = str(tmp_path / "delta.txt")
    _write_edges(delta, _delta_edges())
    store.apply_delta(delta)
    good = GraphStore.open(store.directory).load_graph()
    # corrupt the touched shard's indices blob
    _, dx = store.shard_files(0)
    raw = bytearray(open(dx, "rb").read())
    raw[-1] ^= 0xFF
    open(dx, "wb").write(bytes(raw))
    healed = GraphStore.open(store.directory, self_heal=True).load_graph()
    np.testing.assert_array_equal(
        np.asarray(healed.indices), np.asarray(good.indices)
    )


def test_quarantine_rebuild_refuses_changed_delta(tmp_path, cache):
    from bigclam_tpu.graph.store import ShardCorruption

    store, _ = cache
    delta = str(tmp_path / "delta.txt")
    _write_edges(delta, _delta_edges())
    store.apply_delta(delta)
    _write_edges(delta, _delta_edges() + [(3, 17)])   # mutate the file
    with pytest.raises(ShardCorruption, match="delta file changed"):
        GraphStore.open(store.directory).rebuild_shard(0)


# ----------------------------------------------- row-keyed counter init
def test_rowkeyed_rows_match_global_slice():
    full = rowkeyed_init_rows(0, 500, 16, seed=7)
    np.testing.assert_array_equal(
        full[123:456], rowkeyed_init_rows(123, 456, 16, seed=7)
    )
    assert set(np.unique(full)) <= {0.0, 1.0}
    assert 0.4 < full.mean() < 0.6           # Bernoulli(0.5)
    assert not np.array_equal(
        full, rowkeyed_init_rows(0, 500, 16, seed=8)
    )


def test_store_native_per_host_init_bit_identical_trajectory(tmp_path):
    from bigclam_tpu.parallel import (
        ShardedBigClamModel,
        StoreShardedBigClamModel,
        make_mesh,
    )

    text = str(tmp_path / "g.txt")
    _write_edges(text, _base_edges(n=96, extra=200, seed=2))
    store = compile_graph_cache(
        text, str(tmp_path / "cache"), num_shards=2
    )
    g = store.load_graph()
    cfg = BigClamConfig(num_communities=6, max_iters=25, seed=11)
    mesh = make_mesh((2, 1), jax.devices()[:2])
    m_store = StoreShardedBigClamModel(store, cfg, mesh)
    m_mem = ShardedBigClamModel(g, cfg, mesh)
    s_store = m_store.init_state(None)       # per-host generation
    s_mem = m_mem.init_state(None)           # host-global twin
    np.testing.assert_array_equal(
        np.asarray(s_store.F), np.asarray(s_mem.F)
    )
    st1, llh1, it1, h1 = m_store.fit_state(s_store)
    st2, llh2, it2, h2 = m_mem.fit_state(s_mem)
    assert it1 == it2 and h1 == h2
    np.testing.assert_array_equal(
        np.asarray(st1.F), np.asarray(st2.F)
    )


def test_rowkeyed_init_matches_single_chip(tmp_path):
    text = str(tmp_path / "g.txt")
    _write_edges(text, _base_edges(n=64, extra=100, seed=4))
    g = build_graph(text)
    cfg = BigClamConfig(num_communities=4, max_iters=5, seed=5)
    model = BigClamModel(g, cfg)
    state = model.init_state(None)
    np.testing.assert_array_equal(
        np.asarray(state.F)[: g.num_nodes, :4],
        rowkeyed_init_F(g, cfg),
    )


# --------------------------------------------------- warm-start refit
@pytest.fixture(scope="module")
def refit_world(tmp_path_factory):
    """Cache + converged fit + applied delta, shared by refit tests."""
    tmp = tmp_path_factory.mktemp("refit")
    text = str(tmp / "g.txt")
    _write_edges(text, _base_edges(n=150, extra=450, seed=3))
    store = compile_graph_cache(
        text, str(tmp / "cache"), num_shards=SHARDS
    )
    cfg = BigClamConfig(num_communities=6, max_iters=200, seed=0)
    g0 = store.load_graph()
    model0 = BigClamModel(g0, cfg)
    res0 = model0.fit(model0.random_init())
    delta = str(tmp / "delta.txt")
    _write_edges(delta, _delta_edges(lo=0, hi=40, stride=3, shift=11))
    info = store.apply_delta(delta)
    g1 = store.load_graph()
    return store, cfg, res0, delta, info, g1


def test_expand_halo(refit_world):
    _, _, _, _, _, g = refit_world
    touched = np.asarray([0, 5])
    h0 = expand_halo(g.indptr, g.indices, touched, 0)
    np.testing.assert_array_equal(h0, touched)
    h1 = expand_halo(g.indptr, g.indices, touched, 1)
    assert set(touched) < set(h1.tolist())
    nbrs = set(
        np.asarray(g.indices)[g.indptr[0]: g.indptr[1]].tolist()
    )
    assert nbrs <= set(h1.tolist())


def test_touched_rows_from_delta(refit_world):
    _, _, _, delta, info, g = refit_world
    rows = touched_rows_from_delta(g.raw_ids, delta)
    np.testing.assert_array_equal(rows, info["touched_rows"])


def test_warm_start_refit_tracks_scratch_fit(refit_world):
    import jax.numpy as jnp  # noqa: F401

    from bigclam_tpu.ops.objective import loglikelihood

    store, cfg, res0, delta, info, g = refit_world
    model = BigClamModel(g, cfg)
    r = warm_start_refit(
        model, res0.F, info["touched_rows"], halo=1, max_rounds=10
    )
    assert r.converged and not r.escalated
    assert 0 < r.touched_frac < 1.0
    assert r.refit_nodes >= r.touched
    scratch = model.fit(model.random_init())
    st = model.init_state(r.F)
    llh_refit = float(loglikelihood(st.F, st.sumF, model.edges, cfg))
    rel = abs(1.0 - llh_refit / scratch.llh)
    assert rel < 0.05, (llh_refit, scratch.llh, rel)
    # restricted work: far fewer sweeps than the full fit's iterations
    assert r.rounds < scratch.num_iters


def test_warm_start_refit_fixed_point_without_delta(refit_world):
    """On an UNCHANGED graph the previous F is near a fixed point: the
    refit converges in a couple of rounds and barely moves the rows."""
    store, cfg, res0, _, _, g1 = refit_world
    model = BigClamModel(g1, cfg)
    base = model.fit(model.random_init())
    r = warm_start_refit(
        model, base.F, np.arange(0, 30), halo=0, max_rounds=8
    )
    assert r.converged
    np.testing.assert_allclose(r.F, base.F, atol=2e-2)


def test_refit_escalates_on_plateau(refit_world):
    store, cfg, res0, _, info, g = refit_world
    model = BigClamModel(g, cfg)
    r = warm_start_refit(
        model, res0.F, info["touched_rows"], halo=1, max_rounds=10,
        conv_tol=1e-12,
        thresholds={"plateau_floor": 0.5, "plateau_patience": 2},
    )
    assert r.escalated
    assert any(a["check"] == "plateau" for a in r.anomalies)


def test_warm_start_refit_sparse(refit_world):
    store, cfg, res0, _, info, g = refit_world
    scfg = cfg.replace(representation="sparse", sparse_m=6)
    smodel = SparseBigClamModel(g, scfg)
    r = smodel.warm_start_refit(
        res0.F, info["touched_rows"], halo=0, max_rounds=4
    )
    assert r.F.shape == (g.num_nodes, 6)
    assert np.isfinite(r.llh)
    assert r.rounds >= 1


def test_delta_and_refit_events_schema_valid(tmp_path):
    text = str(tmp_path / "g.txt")
    _write_edges(text, _base_edges(n=80, extra=150, seed=6))
    store = compile_graph_cache(
        text, str(tmp_path / "cache"), num_shards=2
    )
    cfg = BigClamConfig(num_communities=4, max_iters=40, seed=0)
    tdir = str(tmp_path / "tel")
    tel = install(RunTelemetry(tdir, entry="refit", device_memory=False))
    try:
        delta = str(tmp_path / "delta.txt")
        _write_edges(delta, _delta_edges(lo=0, hi=30, stride=4))
        info = store.apply_delta(delta)
        g = store.load_graph()
        model = BigClamModel(g, cfg)
        warm_start_refit(
            model, model.random_init(), info["touched_rows"],
            halo=0, max_rounds=3,
        )
    finally:
        tel.finalize()
        uninstall(tel)
    n, errors = validate_events_file(os.path.join(tdir, EVENTS_NAME))
    assert not errors, errors[:5]
    kinds = [
        json.loads(ln)["kind"]
        for ln in open(os.path.join(tdir, EVENTS_NAME))
    ]
    assert "delta_ingest" in kinds and "refit" in kinds


# ------------------------------------------------ the continuous loop
def test_scan_edge_files_order_and_filters(tmp_path):
    d = tmp_path / "deltas"
    d.mkdir()
    (d / "b.txt").write_text("0 1\n")
    (d / "a.txt").write_text("0 1\n")
    (d / "c.tmp").write_text("")
    (d / ".hidden").write_text("")
    got = scan_edge_files(str(d))
    assert [os.path.basename(p) for p in got] == ["a.txt", "b.txt"]
    got2 = scan_edge_files(str(d), seen=got[:1])
    assert [os.path.basename(p) for p in got2] == ["b.txt"]
    assert scan_edge_files(str(tmp_path / "missing")) == []


def test_follow_deltas_publishes_monotonic_generations(tmp_path):
    text = str(tmp_path / "g.txt")
    _write_edges(text, _base_edges(n=100, extra=250, seed=9))
    store = compile_graph_cache(
        text, str(tmp_path / "cache"), num_shards=2
    )
    cfg = BigClamConfig(num_communities=4, max_iters=80, seed=0)
    g = store.load_graph()
    model = BigClamModel(g, cfg)
    res = model.fit(model.random_init())
    snaps = str(tmp_path / "snaps")
    from bigclam_tpu.serve.snapshot import publish_snapshot

    publish_snapshot(
        snaps, step=res.num_iters, F=res.F, raw_ids=g.raw_ids,
        num_edges=g.num_edges, cfg=cfg, meta={"fit_wall_s": 1.0},
    )
    g0 = CheckpointManager(snaps).latest()
    ddir = tmp_path / "deltas"
    ddir.mkdir()
    _write_edges(
        str(ddir / "delta_000.txt"), _delta_edges(lo=0, hi=30, stride=4)
    )
    _write_edges(
        str(ddir / "delta_001.txt"),
        _delta_edges(lo=0, hi=40, stride=5, shift=13),
    )
    # an empty delta must be SKIPPED: no refit, no generation churn
    # (named to sort FIRST, so the loop meets it before the real ones)
    (ddir / "a_empty.txt").write_text("# nothing\n")
    # a POISON delta (unknown node id) must be refused and skipped —
    # never crash the loop (also sorts before the real deltas)
    (ddir / "b_poison.txt").write_text("0\t999999\n")
    out = follow_deltas(
        store, cfg, res.F, snaps, str(ddir),
        max_deltas=2, timeout_s=30, interval_s=0.05, quiet=True,
    )
    assert out["generations"] == 2
    assert len(out["processed"]) == 2
    assert len(out["skipped_empty"]) == 1
    assert len(out["failed"]) == 1
    assert out["failed"][0].endswith("b_poison.txt")
    steps = CheckpointManager(snaps).published_steps()
    assert steps[-2:] == [g0 + 1, g0 + 2]
    assert CheckpointManager(snaps).latest() == g0 + 2
    assert store.delta_seq == 2
    # the from-scratch cost baseline propagates through loop-published
    # generations (a later `cli refit` needs it for refit_cost_ratio)
    _, _, meta = CheckpointManager(snaps).load_published()
    assert meta.get("fit_wall_s") == 1.0
    # a restarted loop skips already-recorded deltas
    out2 = follow_deltas(
        store, cfg, res.F, snaps, str(ddir),
        max_deltas=1, timeout_s=0.2, interval_s=0.05, quiet=True,
    )
    assert out2["generations"] == 0


# ------------------------------------------------------- ledger fields
def _report(final, entry="refit"):
    return {
        "run": final.get("run", "r1"),
        "entry": entry,
        "pid": 0,
        "wall_s": 2.0,
        "processes": 1,
        "fingerprint": {
            "host": "h", "platform": "cpu", "backend": "cpu",
            "device_kind": "cpu", "devices": 1,
        },
        "final": final,
    }


def test_ledger_records_refit_fields_and_verdicts():
    final = {
        "n": 150, "edges": 700, "k": 6,
        "refit_cost_ratio": 0.2, "touched_frac": 0.3,
        "refit_rounds": 3,
    }
    base = L.build_record(_report(final))
    assert base["refit_cost_ratio"] == 0.2
    assert base["touched_frac"] == 0.3
    assert base["refit_rounds"] == 3
    # identical re-run: PASS
    d = L.diff_records(base, L.build_record(_report(final)))
    assert not d["regression"]
    # cost ratio blowing past the band: REGRESSION
    worse = dict(final, refit_cost_ratio=0.9)
    d = L.diff_records(base, L.build_record(_report(worse)))
    assert d["regression"]
    assert any(
        c["metric"] == "refit_cost_ratio" and c["regression"]
        for c in d["checks"]
    )
    # touched_frac creeping up: REGRESSION too
    wider = dict(final, touched_frac=0.8)
    d = L.diff_records(base, L.build_record(_report(wider)))
    assert d["regression"]


def test_ledger_refit_never_baselines_fit(tmp_path):
    final = {"n": 150, "edges": 700, "k": 6}
    fit_rec = L.build_record(_report(dict(final, run="fit1"), "fit"))
    refit_rec = L.build_record(
        _report(
            dict(final, run="refit1", refit_cost_ratio=0.2,
                 touched_frac=0.3),
            "refit",
        )
    )
    led = L.PerfLedger(str(tmp_path / "ledger.jsonl"))
    led.append(fit_rec)
    led.append(refit_rec)
    assert led.baseline_for(refit_rec) is None
    assert L.match_key(fit_rec) != L.match_key(refit_rec)


# ------------------------------------------------------------ cli e2e
def test_cli_refit_end_to_end(tmp_path, capsys):
    from bigclam_tpu.cli import main

    text = str(tmp_path / "g.txt")
    _write_edges(text, _base_edges(n=100, extra=250, seed=12))
    cache = str(tmp_path / "cache")
    assert main(
        ["ingest", "--graph", text, "--cache-dir", cache,
         "--shards", "2", "--quiet"]
    ) == 0
    snaps = str(tmp_path / "snaps")
    assert main(
        ["fit", "--graph", cache, "--k", "4", "--max-iters", "80",
         "--publish-dir", snaps, "--quiet"]
    ) == 0
    delta = str(tmp_path / "delta.txt")
    _write_edges(delta, _delta_edges(lo=0, hi=30, stride=4))
    assert main(
        ["ingest", "--delta", delta, "--cache-dir", cache, "--quiet"]
    ) == 0
    capsys.readouterr()
    rc = main(
        ["refit", "--graph", cache, "--snapshots", snaps,
         "--delta", delta, "--quiet"]
    )
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    assert out["touched"] > 0
    assert out["refit_cost_ratio"] is not None
    assert out["generation"] > out["from_generation"]
    # refit stamps the engaged kernel path like fit/profile do (round
    # 21 backfill): the warm-start steps run the same compiled step
    assert out["kernel_path"]
    # the published refit snapshot is loadable and is the latest
    assert CheckpointManager(snaps).latest() == out["generation"]


def test_apply_delta_rebakes_touched_closure(tmp_path, cache):
    """ISSUE 16: the delta re-ingest rebakes the touched shards' closure
    blobs exactly — the updated cache's gather lists must be byte-equal
    to a fresh full ingest of the combined edge list."""
    store, text = cache
    delta = str(tmp_path / "delta.txt")
    _write_edges(delta, _delta_edges())     # rows [0, 50): shard 0 only
    info = store.apply_delta(delta)
    assert info["touched_shards"] == [0]
    combined = str(tmp_path / "combined.txt")
    with open(combined, "w") as f:
        f.write(open(text).read())
        f.write(open(delta).read())
    fresh = compile_graph_cache(
        combined, str(tmp_path / "fresh_cache"), num_shards=SHARDS
    )
    after = GraphStore.open(store.directory).load_closure_lists()
    want = fresh.load_closure_lists()
    for s in range(SHARDS):
        assert after.shards[s].edge_counts == want.shards[s].edge_counts
        for b in range(SHARDS):
            np.testing.assert_array_equal(
                after.shards[s].out_ids[b], want.shards[s].out_ids[b]
            )
            np.testing.assert_array_equal(
                after.shards[s].in_ids[b], want.shards[s].in_ids[b]
            )
