"""The jax-free contract of `cli ingest` / `cli report` / `cli watch`
(ISSUE 10 satellite): these entries run on data-prep hosts where the jax
import costs RSS + seconds — until now the contract was a convention in
docstrings, not a test. Each entry runs in a FRESH subprocess and
asserts `jax` never entered sys.modules."""

import json
import os
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_jaxfree(argv, cwd):
    """Run cli.main(argv) in a fresh interpreter; the child asserts jax
    stayed unimported AFTER the command finished (an import during the
    run would persist in sys.modules)."""
    code = textwrap.dedent(
        f"""
        import sys
        from bigclam_tpu.cli import main
        rc = main({argv!r})
        assert "jax" not in sys.modules, "cli entry imported jax"
        sys.exit(rc)
        """
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, "-c", code],
        env=env, cwd=cwd, capture_output=True, text=True, timeout=120,
    )


def test_cli_ingest_stays_jax_free(tmp_path):
    edges = tmp_path / "g.txt"
    edges.write_text(
        "".join(
            f"{u}\t{v}\n"
            for u, v in [(0, 1), (1, 2), (2, 0), (2, 3), (3, 0), (1, 3)]
        )
    )
    r = _run_jaxfree(
        ["ingest", "--graph", str(edges), "--cache-dir",
         str(tmp_path / "cache"), "--shards", "2", "--quiet"],
        str(tmp_path),
    )
    assert r.returncode == 0, (r.stdout, r.stderr)
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["shards"] == 2 and out["n"] == 4


def test_cli_ingest_delta_stays_jax_free(tmp_path):
    """ISSUE 15 satellite: the delta re-ingest is part of the jax-free
    ingest entry — it runs on data-prep hosts next to the full compile."""
    edges = tmp_path / "g.txt"
    edges.write_text(
        "".join(
            f"{u}\t{v}\n"
            for u, v in [(i, (i + 1) % 8) for i in range(8)]
        )
    )
    cache = str(tmp_path / "cache")
    r = _run_jaxfree(
        ["ingest", "--graph", str(edges), "--cache-dir", cache,
         "--shards", "2", "--quiet"],
        str(tmp_path),
    )
    assert r.returncode == 0, (r.stdout, r.stderr)
    delta = tmp_path / "delta.txt"
    delta.write_text("0\t3\n1\t5\n")
    r = _run_jaxfree(
        ["ingest", "--delta", str(delta), "--cache-dir", cache,
         "--quiet"],
        str(tmp_path),
    )
    assert r.returncode == 0, (r.stdout, r.stderr)
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["delta_seq"] == 1
    assert out["edges_added"] > 0
    assert out["touched_shards"]


def test_cli_report_and_watch_stay_jax_free(tmp_path):
    # the telemetry dir is produced here (jax loaded in THIS process is
    # irrelevant — the contract is about the reading entries), rendered
    # in fresh jax-free subprocesses
    from bigclam_tpu.obs import RunTelemetry

    tdir = str(tmp_path / "telem")
    tel = RunTelemetry(tdir, entry="t", quiet=True)
    tel.event("step", iter=0, llh=-1.0)
    tel.event("comms", site="sharded/all_gather_F", op="all_gather",
              bytes_per_step=1024.0)
    tel.finalize()

    r = _run_jaxfree(["report", tdir], str(tmp_path))
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "collective traffic (modeled)" in r.stdout

    r = _run_jaxfree(["report", tdir, "--json"], str(tmp_path))
    assert r.returncode == 0, (r.stdout, r.stderr)
    obj = json.loads(r.stdout.strip().splitlines()[-1])
    assert obj["comms"]["sites"]["sharded/all_gather_F"] == 1024.0

    r = _run_jaxfree(["watch", tdir, "--once"], str(tmp_path))
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "comms" in r.stdout


def test_cli_preflight_stays_jax_free_on_manifest(tmp_path):
    # the capacity preflight (ISSUE 12) is the go/no-go tool for hosts
    # that may not even have an accelerator stack installed: it must
    # answer from the cache MANIFEST alone, jax-free, with the verdict
    # in the exit code (0 fits / 2 does not)
    edges = tmp_path / "g.txt"
    edges.write_text(
        "".join(
            f"{u}\t{v}\n"
            for u in range(16) for v in range(u + 1, 16)
        )
    )
    r = _run_jaxfree(
        ["ingest", "--graph", str(edges), "--cache-dir",
         str(tmp_path / "cache"), "--shards", "2", "--quiet"],
        str(tmp_path),
    )
    assert r.returncode == 0, (r.stdout, r.stderr)
    r = _run_jaxfree(
        ["preflight", "--graph", str(tmp_path / "cache"), "--k", "8",
         "--mesh", "2,1", "--hbm-gb", "16", "--json"],
        str(tmp_path),
    )
    assert r.returncode == 0, (r.stdout, r.stderr)
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["fits"] and out["workload"]["shard_counts_known"]
    assert out["hbm_bytes_per_device"] > 0
    assert out["host"]["stages"]
    # an absurd budget flips the verdict to exit 2, still jax-free
    r = _run_jaxfree(
        ["preflight", "--graph", str(tmp_path / "cache"), "--k", "8",
         "--mesh", "2,1", "--hbm-bytes", "1024"],
        str(tmp_path),
    )
    assert r.returncode == 2, (r.stdout, r.stderr)
    assert "DOES NOT FIT" in r.stdout


def test_cli_serve_help_stays_jax_free(tmp_path):
    # `serve --help` must answer on boxes with no accelerator stack
    # (argparse exits via SystemExit, so the jax assertion runs first)
    code = textwrap.dedent(
        """
        import sys
        from bigclam_tpu.cli import main
        try:
            main(["serve", "--help"])
        except SystemExit as e:
            assert e.code in (0, None), e.code
        assert "jax" not in sys.modules, "serve --help imported jax"
        """
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, "-c", code],
        env=env, cwd=str(tmp_path), capture_output=True, text=True,
        timeout=120,
    )
    assert r.returncode == 0, (r.stdout, r.stderr)


def test_cli_serve_read_queries_and_report_stay_jax_free(tmp_path):
    # the ISSUE 14 satellite: membership READ families (communities_of /
    # members_of) answer from the snapshot + inverted index with no jax
    # import — only the fold-in family may pull jax, lazily. The
    # snapshot is published in-parent (publish_snapshot is numpy-only).
    import numpy as np

    from bigclam_tpu.config import BigClamConfig
    from bigclam_tpu.serve.snapshot import publish_snapshot

    rng = np.random.default_rng(0)
    F = rng.uniform(0.0, 1.0, size=(12, 3))
    snapdir = str(tmp_path / "snaps")
    publish_snapshot(
        snapdir, step=1, F=F, num_edges=20,
        cfg=BigClamConfig(num_communities=3),
    )
    queries = tmp_path / "q.jsonl"
    queries.write_text(
        "".join(
            json.dumps(q) + "\n"
            for q in (
                [{"family": "communities_of", "u": u} for u in range(12)]
                + [{"family": "members_of", "c": c} for c in range(3)]
            )
        )
    )
    tdir = str(tmp_path / "telem")
    r = _run_jaxfree(
        ["serve", "--snapshots", snapdir, "--queries", str(queries),
         "--results", str(tmp_path / "ans.jsonl"),
         "--telemetry-dir", tdir, "--latency-budget-ms", "1", "--quiet"],
        str(tmp_path),
    )
    assert r.returncode == 0, (r.stdout, r.stderr)
    stats = json.loads(r.stdout.strip().splitlines()[-1])
    assert stats["serve_queries"] == 15 and stats["serve_errors"] == 0
    assert stats["serve_p99_s"] > 0
    # the serve report path stays jax-free too, and renders the section
    r = _run_jaxfree(["report", tdir], str(tmp_path))
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "serving: 15 queries" in r.stdout


def _spawn_jaxfree(argv, cwd):
    """Popen cli.main(argv) in a fresh interpreter for BLOCKING entries
    (`fleet up`, `route --daemon`): the caller drives the hello-line +
    control-socket protocol, then waits; the child asserts jax stayed
    unimported after main() returned."""
    code = textwrap.dedent(
        f"""
        import sys
        from bigclam_tpu.cli import main
        rc = main({argv!r})
        assert "jax" not in sys.modules, "cli entry imported jax"
        sys.exit(rc)
        """
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [sys.executable, "-c", code],
        env=env, cwd=cwd, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True,
    )


def _wire_op(endpoint, op, timeout=30.0):
    import socket

    host, port = endpoint.rsplit(":", 1)
    with socket.create_connection((host, int(port)),
                                  timeout=timeout) as sock:
        sock.settimeout(timeout)
        sock.sendall((json.dumps(op) + "\n").encode())
        return json.loads(sock.makefile("rb").readline())


def _tiny_fleet(tmp_path):
    import numpy as np

    from bigclam_tpu.config import BigClamConfig
    from bigclam_tpu.serve.snapshot import publish_fleet_snapshot

    rng = np.random.default_rng(0)
    F = rng.uniform(0.0, 1.0, size=(12, 3))
    snapdir = str(tmp_path / "snaps")
    publish_fleet_snapshot(
        snapdir, [(0, 12)], F=F, num_edges=20,
        cfg=BigClamConfig(num_communities=3),
    )
    return snapdir


def test_cli_fleet_up_down_stays_jax_free(tmp_path):
    # ISSUE 20 tentpole: the supervisor is a process-herding parent on a
    # serving host — it must never drag jax in. `fleet up` parks until
    # the control wire's `down` op; the test drives the whole lifecycle
    # over that wire: hello line -> status -> down -> final counters.
    snapdir = _tiny_fleet(tmp_path)
    members = str(tmp_path / "members.json")
    p = _spawn_jaxfree(
        ["fleet", "up", "--fleet", snapdir, "--shards", "1",
         "--replicas", "2", "--members", members,
         "--up-timeout-s", "60", "--quiet"],
        str(tmp_path),
    )
    try:
        hello = json.loads(p.stdout.readline())
        assert hello["all_up"] is True
        assert hello["fleet_members"] == ["s0r0", "s0r1"]
        st = _wire_op(hello["control"], {"op": "status"})
        assert {m["state"] for m in st["members"]} == {"up"}
        with open(members) as f:
            doc = json.load(f)
        assert doc["seq"] >= 1 and len(doc["members"]) == 2
        assert _wire_op(hello["control"], {"op": "down"})["ok"] is True
        out, err = p.communicate(timeout=60)
        assert p.returncode == 0, (out, err)
        final = json.loads(out.strip().splitlines()[-1])
        assert final["replica_restarts"] == 0
        assert final["quarantined"] == 0
        assert {m["state"] for m in final["fleet_members"].values()} == {
            "stopped"
        }
    finally:
        if p.poll() is None:
            p.kill()
        p.stdout.close()
        p.stderr.close()


def test_cli_route_daemon_stays_jax_free(tmp_path):
    # ISSUE 20 tentpole: the router daemon is a long-lived query-front
    # tier — a pure socket/JSON process. One replica subprocess behind
    # it; the daemon answers queries + stats over the wire, and the
    # `stop` op shuts it down clean (rc 0, jax never imported).
    snapdir = _tiny_fleet(tmp_path)
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    rep = subprocess.Popen(
        [sys.executable, "-m", "bigclam_tpu.cli", "serve",
         "--fleet", snapdir, "--fleet-shard", "0",
         "--listen", "127.0.0.1:0", "--quiet"],
        env=env, cwd=str(tmp_path), stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True,
    )
    daemon = None
    try:
        endpoint = json.loads(rep.stdout.readline())["listening"]
        daemon = _spawn_jaxfree(
            ["route", "--fleet", snapdir, "--endpoints", endpoint,
             "--daemon", "--listen", "127.0.0.1:0", "--quiet"],
            str(tmp_path),
        )
        hello = json.loads(daemon.stdout.readline())
        routing = hello["routing"]
        ans = _wire_op(routing, {"family": "communities_of", "u": 0})
        assert "communities" in ans and "error" not in ans
        st = _wire_op(routing, {"family": "status"})
        assert st["serve_queries"] == 1 and st["serve_errors"] == 0
        assert st["router_retries"] == 0 and st["hedged"] == 0
        assert _wire_op(routing, {"family": "stop"})["ok"] is True
        out, err = daemon.communicate(timeout=60)
        assert daemon.returncode == 0, (out, err)
        final = json.loads(out.strip().splitlines()[-1])
        assert final["serve_queries"] == 1
        _wire_op(endpoint, {"family": "stop"})
        rep.wait(timeout=30)
    finally:
        for p in (rep, daemon):
            if p is None:
                continue
            if p.poll() is None:
                p.kill()
            p.stdout.close()
            p.stderr.close()


def test_cli_perf_show_stays_jax_free(tmp_path):
    # the perf-ledger tooling shares the data-prep-host contract (the
    # module docstring promises it; now the test does)
    ledger = tmp_path / "ledger.jsonl"
    ledger.write_text("")
    r = _run_jaxfree(
        ["perf", "show", "--ledger", str(ledger)], str(tmp_path)
    )
    assert r.returncode == 0, (r.stdout, r.stderr)


def test_cli_route_stays_jax_free(tmp_path):
    # ISSUE 19 satellite: the fleet router is a pure socket/JSON client —
    # it must run on a query-front host with no accelerator stack. Fleet
    # publication is numpy-only and runs in-parent; two shard replicas
    # run as subprocesses (`serve --fleet` read families are jax-free
    # too); the routing entry itself runs under the jax assertion.
    import numpy as np

    from bigclam_tpu.config import BigClamConfig
    from bigclam_tpu.serve.snapshot import publish_fleet_snapshot

    rng = np.random.default_rng(0)
    F = rng.uniform(0.0, 1.0, size=(12, 3))
    snapdir = str(tmp_path / "snaps")
    publish_fleet_snapshot(
        snapdir, [(0, 6), (6, 12)], F=F, num_edges=20,
        cfg=BigClamConfig(num_communities=3),
    )
    fleetroot = tmp_path / "telem"
    fleetroot.mkdir()
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs, endpoints = [], []
    try:
        for shard in range(2):
            p = subprocess.Popen(
                [sys.executable, "-m", "bigclam_tpu.cli", "serve",
                 "--fleet", snapdir, "--fleet-shard", str(shard),
                 "--listen", "127.0.0.1:0",
                 "--telemetry-dir", str(fleetroot / f"rep{shard}"),
                 "--quiet"],
                env=env, cwd=str(tmp_path), stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, text=True,
            )
            procs.append(p)
            hello = json.loads(p.stdout.readline())
            endpoints.append(hello["listening"])
        queries = tmp_path / "q.jsonl"
        queries.write_text(
            "".join(
                json.dumps(q) + "\n"
                for q in (
                    [{"family": "communities_of", "u": u}
                     for u in range(12)]
                    + [{"family": "members_of", "c": c} for c in range(3)]
                )
            )
        )
        r = _run_jaxfree(
            ["route", "--fleet", snapdir,
             "--endpoints", ",".join(endpoints),
             "--queries", str(queries),
             "--results", str(tmp_path / "ans.jsonl"),
             "--telemetry-dir", str(fleetroot / "router"), "--quiet"],
            str(tmp_path),
        )
        assert r.returncode == 0, (r.stdout, r.stderr)
        stats = json.loads(r.stdout.strip().splitlines()[-1])
        assert stats["serve_queries"] == 15
        assert stats["serve_errors"] == 0
        assert stats["traced_queries"] == 15
        assert stats["serve_hop_execute_s"] > 0
        r = _run_jaxfree(
            ["route", "--fleet", snapdir,
             "--endpoints", ",".join(endpoints), "--stop"],
            str(tmp_path),
        )
        assert r.returncode == 0, (r.stdout, r.stderr)
        for p in procs:
            p.wait(timeout=30)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
            p.stdout.close()
            p.stderr.close()

    # the fleet observability plane reads those telemetry dirs back,
    # still jax-free: one merged report + one watch frame over the root
    r = _run_jaxfree(
        ["report", "--fleet", str(fleetroot)], str(tmp_path)
    )
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "3 member dir(s)" in r.stdout
    assert "router:" in r.stdout and "per-hop mean" in r.stdout

    r = _run_jaxfree(
        ["report", "--fleet", str(fleetroot), "--json"], str(tmp_path)
    )
    assert r.returncode == 0, (r.stdout, r.stderr)
    obj = json.loads(r.stdout.strip().splitlines()[-1])
    assert obj["router"]["serve_queries"] == 15
    assert sorted(obj["replicas"]) == ["0", "1"]

    r = _run_jaxfree(
        ["watch", "--fleet", str(fleetroot), "--once"], str(tmp_path)
    )
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "3 member(s)" in r.stdout
