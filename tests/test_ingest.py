"""Graph ingest tests, golden-anchored to the shipped SNAP datasets
(SURVEY.md §7.2): header counts from /root/reference/data."""

import numpy as np
import pytest

from bigclam_tpu.graph.ingest import (
    build_graph,
    dedup_directed,
    graph_from_edges,
    load_edge_list,
)


def test_triangle_csr(toy_graphs):
    g = toy_graphs["triangle"]
    assert g.num_nodes == 3
    assert g.num_edges == 3
    assert g.num_directed_edges == 6
    np.testing.assert_array_equal(g.degrees, [2, 2, 2])
    np.testing.assert_array_equal(g.neighbors(0), [1, 2])
    g.validate()


def test_dedup_selfloop_and_both_directions():
    # duplicates, reverse duplicates and self-loops all collapse
    g = graph_from_edges([(1, 2), (2, 1), (1, 2), (1, 1), (3, 2)])
    assert g.num_nodes == 3  # ids {1,2,3} remapped to [0,3)
    assert g.num_edges == 2
    np.testing.assert_array_equal(g.raw_ids, [1, 2, 3])
    g.validate()


def test_remap_noncontiguous_ids():
    g = graph_from_edges([(10, 500), (500, 99)])
    assert g.num_nodes == 3
    np.testing.assert_array_equal(g.raw_ids, [10, 99, 500])
    # node 500 -> index 2 has degree 2
    np.testing.assert_array_equal(g.degrees, [1, 1, 2])


def test_src_dst_alignment(toy_graphs):
    g = toy_graphs["two_cliques"]
    g.validate()
    src, dst = g.src, g.dst
    assert src.shape == dst.shape == (g.num_directed_edges,)
    # bridge 3-4 present in both directions
    pairs = set(zip(src.tolist(), dst.tolist()))
    assert (3, 4) in pairs and (4, 3) in pairs


def test_facebook_golden(facebook_graph):
    # header-documented scale: 4,039 nodes / 88,234 undirected edges
    assert facebook_graph.num_nodes == 4039
    assert facebook_graph.num_edges == 88234
    facebook_graph.validate()


@pytest.mark.slow
def test_enron_golden():
    from tests.conftest import require_reference_data

    g = build_graph(require_reference_data("Email-Enron.txt"))
    # header: Nodes: 36692 Edges: 367662 (file lists both directions;
    # dedup halves it to 183,831 undirected edges)
    assert g.num_nodes == 36692
    assert g.num_directed_edges == 367662
    g.validate()


def _packed_key_dedup(both: np.ndarray, n: int):
    """The SEED dedup path (single int64 key = src * n + dst, n < 2^31
    assumed) — kept here as the parity oracle for the lexsort rewrite."""
    key = np.unique(both[:, 0] * np.int64(n) + both[:, 1])
    return key // n, key % n


def test_lexsort_dedup_matches_packed_key():
    """Satellite: the lexsort dedup (no node-count ceiling) must reproduce
    the old packed-key path bit for bit wherever the old path was valid."""
    rng = np.random.default_rng(11)
    for trial in range(8):
        m = int(rng.integers(1, 400))
        n = int(rng.integers(2, 40))
        both = rng.integers(0, n, size=(m, 2)).astype(np.int64)
        src_new, dst_new = dedup_directed(both)
        src_old, dst_old = _packed_key_dedup(both, n)
        np.testing.assert_array_equal(src_new, src_old)
        np.testing.assert_array_equal(dst_new, dst_old)
    # empty input stays empty
    src, dst = dedup_directed(np.empty((0, 2), np.int64))
    assert src.size == 0 and dst.size == 0


def test_dedup_no_key_packing_overflow():
    """Ids near int64-overflow territory for the packed key (src * n + dst
    would wrap): the lexsort path must stay exact. (A true n >= 2^31 graph
    does not fit test RAM; this pins the arithmetic, not the scale.)"""
    big = np.int64(2**32 + 7)          # key packing at n=2^32 would overflow
    both = np.array(
        [[big, 1], [1, big], [big, 1], [0, big - 1], [0, big - 1]],
        dtype=np.int64,
    )
    src, dst = dedup_directed(both)
    np.testing.assert_array_equal(
        np.stack([src, dst], 1),
        [[0, big - 1], [1, big], [big, 1]],
    )


def test_parse_skips_comments(tmp_path):
    p = tmp_path / "g.txt"
    p.write_text("# comment\n# another\n0 1\n1 2\n")
    pairs = load_edge_list(str(p))
    np.testing.assert_array_equal(pairs, [[0, 1], [1, 2]])
