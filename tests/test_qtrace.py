"""Distributed query tracing + fleet observability plane (ISSUE 19).

Tentpole pins: the router stamps a trace context on every sub-query,
replicas echo per-hop timing blocks, and the router assembles them into
schema'd `qtrace` slow-query exemplars plus per-hop latency means in
stats — while the OFF path stays bit-identical (the `hops` block never
reaches a client answer, traced and untraced answers serialize the
same). Satellites: `freshness` events, the router-process heartbeat's
in-flight trace registry embedding, and the fleet aggregation layer
(`report --fleet` / `watch --fleet`) under torn, empty, and missing
member telemetry dirs — all single-process, LocalReplica transports,
mirroring the PR 10 fake-host pattern."""

import io
import json
import os
import time

import numpy as np
import pytest

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.models import BigClamModel
from bigclam_tpu.models.agm import sample_planted_graph
from bigclam_tpu.obs.schema import validate_event
from bigclam_tpu.obs.telemetry import (
    EVENTS_NAME,
    RunTelemetry,
    install,
    uninstall,
)
from bigclam_tpu.serve.fleet import LocalReplica, ShardReplica
from bigclam_tpu.serve.router import FleetRouter
from bigclam_tpu.serve.snapshot import publish_fleet_snapshot

K = 6
N = 120
SHARDS = 3


@pytest.fixture(scope="module")
def fitted():
    rng = np.random.default_rng(3)
    g, truth = sample_planted_graph(N, K, p_in=0.8, rng=rng)
    cfg = BigClamConfig(num_communities=K, max_iters=150)
    model = BigClamModel(g, cfg)
    res = model.fit(model.random_init())
    return g, cfg, res


@pytest.fixture()
def fleetdir(tmp_path, fitted):
    g, cfg, res = fitted
    d = str(tmp_path / "fleet")
    ranges = [(s * N // SHARDS, (s + 1) * N // SHARDS)
              for s in range(SHARDS)]
    publish_fleet_snapshot(
        d, ranges, F=res.F, raw_ids=g.raw_ids,
        num_edges=g.num_edges, cfg=cfg,
    )
    return d


def _router(fleetdir):
    reps = [LocalReplica(ShardReplica(fleetdir, s))
            for s in range(SHARDS)]
    return FleetRouter(fleetdir, reps)


QUERIES = [
    {"family": "communities_of", "u": 5},
    {"family": "members_of", "c": 2},
    {"family": "communities_of", "u": 77},
] * 4


def _events(directory):
    out = []
    with open(os.path.join(directory, EVENTS_NAME)) as f:
        for line in f:
            if line.strip():
                out.append(json.loads(line))
    return out


# ------------------------------------------------------- trace assembly
def test_traced_run_emits_schema_valid_qtrace_and_freshness(
    tmp_path, fleetdir
):
    tel = install(RunTelemetry(str(tmp_path / "t"), entry="route",
                               device_memory=False))
    try:
        router = _router(fleetdir)
        router.run_queries(QUERIES)
        st = router.stats()
        router.close()          # flushes the part-filled exemplar window
        tel.set_final(st)
    finally:
        tel.finalize()
        uninstall(tel)
    evs = _events(tel.directory)
    errs = [e2 for e in evs for e2 in validate_event(e)]
    assert errs == [], errs

    qt = [e for e in evs if e["kind"] == "qtrace"]
    assert qt, "no qtrace exemplars emitted"
    for rec in qt:
        assert rec["trace_id"]
        assert rec["family"] in ("communities_of", "members_of")
        assert rec["hops"], "exemplar carries no hop breakdown"
        for hop in rec["hops"]:
            assert set(hop) >= {"shard", "wire_s", "decode_s",
                                "queue_s", "batch_wait_s",
                                "execute_s", "replica_s"}
        # decomposition identity: sequential sub-sends mean
        # total = sum(wire) + merge exactly (rounding noise only)
        acct = sum(h["wire_s"] for h in rec["hops"]) + rec["merge_s"]
        assert abs(rec["total_s"] - acct) < 5e-5
    # the exemplar log is slowest-first within each flush
    totals = [r["total_s"] for r in qt]
    assert totals == sorted(totals, reverse=True)

    fresh = [e for e in evs if e["kind"] == "freshness"]
    assert fresh, "no freshness events emitted"
    for f in fresh:
        assert f["generation_age_s"] >= 0.0
        assert f["step"] >= 1

    # stats carry the per-hop means + tripwire counters
    assert st["traced_queries"] == len(QUERIES)
    for hop in ("transport", "decode", "queue", "batch_wait",
                "execute", "merge"):
        assert f"serve_hop_{hop}_s" in st
    assert st["pruned_generation"] == 0
    assert st["transport_failovers"] == 0
    for sst in st["serve_shard_stats"].values():
        assert "hops" in sst and "execute" in sst["hops"]


def test_trace_off_answers_bit_identical_and_hops_never_leak(
    tmp_path, fleetdir
):
    """The off-path contract: the same queries with telemetry installed
    and without serialize to byte-identical answer streams — the trace
    marker changes NOTHING a client sees, and no `hops` block survives
    the router's merge."""
    router_off = _router(fleetdir)
    res_off = router_off.run_queries(QUERIES)
    router_off.close()

    tel = install(RunTelemetry(str(tmp_path / "t"), entry="route",
                               device_memory=False))
    try:
        router_on = _router(fleetdir)
        res_on = router_on.run_queries(QUERIES)
        assert router_on.stats()["traced_queries"] == len(QUERIES)
        router_on.close()
    finally:
        tel.finalize()
        uninstall(tel)

    assert json.dumps(res_on, sort_keys=True) == \
        json.dumps(res_off, sort_keys=True)
    for r in res_on:
        assert "hops" not in r


def test_untraced_run_records_no_trace_state(fleetdir):
    """No telemetry installed -> zero traced queries, no hop means, no
    exemplar heap growth (the off path never touches the accumulators)."""
    router = _router(fleetdir)
    router.run_queries(QUERIES)
    st = router.stats()
    router.close()
    assert st["traced_queries"] == 0
    assert not any(k.startswith("serve_hop_") for k in st)


def test_reset_stats_clears_trace_accumulators(tmp_path, fleetdir):
    """Warmup-pass contract: reset_stats() drops traced counts and hop
    means so a measured pass starts clean (fleet/qtrace gate idiom)."""
    tel = install(RunTelemetry(str(tmp_path / "t"), entry="route",
                               device_memory=False))
    try:
        router = _router(fleetdir)
        router.run_queries(QUERIES)
        assert router.stats()["traced_queries"] == len(QUERIES)
        router.reset_stats()
        st = router.stats()
        assert st["traced_queries"] == 0
        assert not any(k.startswith("serve_hop_") for k in st)
        router.run_queries(QUERIES[:3])
        assert router.stats()["traced_queries"] == 3
        router.close()
    finally:
        tel.finalize()
        uninstall(tel)


def test_inflight_registry_tracks_open_traces(tmp_path, fleetdir):
    tel = install(RunTelemetry(str(tmp_path / "t"), entry="route",
                               device_memory=False))
    try:
        router = _router(fleetdir)
        assert router.open_trace_count() == 0
        assert router.oldest_inflight_s() == 0.0
        router.run_queries(QUERIES)
        # synchronous local transports: everything settled by return
        assert router.open_trace_count() == 0
        router.close()
    finally:
        tel.finalize()
        uninstall(tel)


# -------------------------------------------------- heartbeat satellite
def test_router_stall_embeds_open_trace_registry(tmp_path):
    """Satellite: a stall on the router process carries the in-flight
    trace registry — open trace count + oldest in-flight age — so a
    wedged replica hop is attributable from the stall event alone."""
    tel = install(
        RunTelemetry(str(tmp_path / "t"), entry="route",
                     heartbeat_s=0.08, quiet=True, device_memory=False)
    )
    tel.open_traces = lambda: 3
    tel.oldest_inflight_s = lambda: 1.5
    try:
        time.sleep(0.5)          # no beats -> the watchdog fires
    finally:
        tel.finalize()
        uninstall(tel)
    stalls = [e for e in _events(tel.directory) if e["kind"] == "stall"]
    assert stalls, "no stall fired"
    assert stalls[0]["open_traces"] == 3
    assert stalls[0]["oldest_inflight_s"] == 1.5


# ------------------------------------------------- fleet report / watch
def _write_member(root, name, entry, final, events, finalized=True):
    d = os.path.join(root, name)
    os.makedirs(d, exist_ok=True)
    if finalized:
        with open(os.path.join(d, "run_report.json"), "w") as f:
            json.dump({"run_id": "r1", "entry": entry, "final": final,
                       "ok": True}, f)
    base = {"v": 2, "run": "r1", "pid": 0, "ts": 1.0, "t": 0.1,
            "elapsed_s": 0.1}
    with open(os.path.join(d, EVENTS_NAME), "w") as f:
        for e in events:
            f.write(json.dumps(dict(base, **e)) + "\n")
    return d


def _synth_fleet(root):
    """Single-process synthesized multi-dir fleet root (the PR 10
    fake-host pattern): a router dir + two replica dirs, one of them
    torn mid-write, plus an empty-events member."""
    _write_member(
        root, "router", "route",
        {"serve_queries": 100, "serve_p50_s": 0.001,
         "serve_p99_s": 0.004, "serve_qps": 900.0,
         "serve_shed_rate": 0.0, "serving_generation": 3,
         "generation_age_s": 4.2, "rollouts": 1, "mixed_generation": 0,
         "pruned_generation": 1, "transport_failovers": 2,
         "traced_queries": 100, "serve_hop_execute_s": 0.0005,
         "serve_hop_transport_s": 0.0001,
         "serve_shard_stats": {
             "0": {"queries": 60, "p50_s": 0.001, "p99_s": 0.003,
                   "qps": 500.0, "hops": {"execute": 0.0004}},
             "1": {"queries": 40, "p50_s": 0.001, "p99_s": 0.005,
                   "qps": 400.0}}},
        [{"kind": "start", "entry": "route"},
         {"kind": "freshness", "generation_age_s": 4.2, "step": 3},
         {"kind": "qtrace", "trace_id": "a-1", "family": "members_of",
          "total_s": 0.004, "merge_s": 0.001, "hops": []},
         {"kind": "end", "ok": True}])
    _write_member(
        root, "rep0", "serve",
        {"shard": 0, "queries": 60, "errors": 0, "shed": 2,
         "depth_peak": 9, "generations": [2, 3], "gen_age_s": 4.0},
        [{"kind": "start", "entry": "serve"}, {"kind": "end", "ok": True}])
    d = _write_member(
        root, "rep1", "serve",
        {"shard": 1, "queries": 40, "errors": 1, "shed": 0,
         "generations": [3], "gen_age_s": 4.1},
        [{"kind": "start", "entry": "serve"}])
    with open(os.path.join(d, EVENTS_NAME), "a") as f:
        f.write('{"kind": "sta')          # torn last line
    empty = os.path.join(root, "rep2")
    os.makedirs(empty, exist_ok=True)
    open(os.path.join(empty, EVENTS_NAME), "w").close()


def test_report_fleet_merges_member_dirs(tmp_path):
    from bigclam_tpu.obs.report import render_fleet, render_fleet_json

    root = str(tmp_path / "fl")
    os.makedirs(root)
    _synth_fleet(root)
    text, errors = render_fleet(root)
    assert errors == 0
    assert "4 member dir(s)" in text
    assert "router: 100 queries" in text
    assert "serving 3, age 4.2s" in text
    assert "1 pruned-gen failover(s), 2 transport failover(s)" in text
    assert "per-hop mean" in text and "execute 0.5ms" in text
    assert "replica rep0: 60 queries" in text and "shed 2" in text
    assert "replica rep1: 40 queries, 1 error(s)" in text

    obj, errors = render_fleet_json(root)
    assert errors == 0
    assert [m["name"] for m in obj["members"]] == [
        "rep0", "rep1", "rep2", "router"]
    assert obj["router"]["serve_queries"] == 100
    assert sorted(obj["replicas"]) == ["0", "1"]
    assert obj["replicas"]["0"][0]["depth_peak"] == 9
    # the torn replica still merged (decoder skips the torn line)
    assert obj["replicas"]["1"][0]["queries"] == 40


def test_report_fleet_missing_and_empty_members(tmp_path):
    """A member dir deleted mid-run is simply not a member; an empty
    events.jsonl renders as a not-yet-started member; an empty root is
    an error (exit-1 contract)."""
    from bigclam_tpu.obs.report import fleet_dirs, render_fleet

    root = str(tmp_path / "fl")
    os.makedirs(root)
    _synth_fleet(root)
    import shutil
    shutil.rmtree(os.path.join(root, "rep0"))
    assert [os.path.basename(d) for d in fleet_dirs(root)] == [
        "rep1", "rep2", "router"]
    text, errors = render_fleet(root)
    assert errors == 0 and "3 member dir(s)" in text

    empty_root = str(tmp_path / "empty")
    os.makedirs(empty_root)
    text, errors = render_fleet(empty_root)
    assert errors == 1 and "no member telemetry dirs" in text


def test_watch_fleet_frame_and_once(tmp_path):
    from bigclam_tpu.obs.watch import render_fleet_frame, watch_fleet

    root = str(tmp_path / "fl")
    os.makedirs(root)
    _synth_fleet(root)
    frame = render_fleet_frame(root)
    assert "4 member(s)" in frame
    assert "router [route]" in frame and "gen 3 age 4.2s" in frame
    assert "slow traces" in frame       # the router's qtrace sparkline
    assert "rep2 [?]: no events" not in frame   # empty file != missing

    buf = io.StringIO()
    assert watch_fleet(root, once=True, out=buf) == 0
    assert "4 member(s)" in buf.getvalue()

    empty_root = str(tmp_path / "empty")
    os.makedirs(empty_root)
    buf = io.StringIO()
    assert watch_fleet(empty_root, once=True, out=buf) == 1
    assert "no member telemetry dirs" in buf.getvalue()


def test_watch_fleet_loop_exits_when_all_members_end(tmp_path):
    """The live loop's exit contract, bounded by max_frames: every
    member carries an `end` event -> the loop returns on its own."""
    from bigclam_tpu.obs.watch import watch_fleet

    root = str(tmp_path / "fl")
    os.makedirs(root)
    _write_member(root, "router", "route", {"serve_queries": 1},
                  [{"kind": "start", "entry": "route"},
                   {"kind": "end", "ok": True}])
    _write_member(root, "rep0", "serve", {"shard": 0, "queries": 1},
                  [{"kind": "start", "entry": "serve"},
                   {"kind": "end", "ok": True}])
    buf = io.StringIO()
    rc = watch_fleet(root, interval=0.01, max_frames=50, out=buf)
    assert rc == 0
    assert buf.getvalue().count("fleet ") == 1   # exited on frame one


# --------------------------------------------------------- perf ledger
def test_ledger_verdicts_hops_and_freshness(tmp_path, fleetdir):
    """generation_age_s + per-hop means land in the ledger record and
    are VERDICTED by diff_records on the serve branch (ISSUE 19 / 3a)."""
    from bigclam_tpu.obs.ledger import build_record, diff_records

    tel = install(RunTelemetry(str(tmp_path / "t"), entry="route",
                               device_memory=False))
    try:
        router = _router(fleetdir)
        router.run_queries(QUERIES)
        st = router.stats()
        router.close()
        tel.set_final(st)
    finally:
        tel.finalize()
        uninstall(tel)

    rec = build_record(tel.report())
    assert rec["generation_age_s"] is not None
    assert rec["serve_hop_execute_s"] is not None
    assert rec["serve_hop_merge_s"] is not None

    base = dict(rec)
    new = dict(rec)
    new["serve_hop_execute_s"] = rec["serve_hop_execute_s"] * 50 + 1.0
    new["generation_age_s"] = rec["generation_age_s"] * 100 + 500.0
    diff = diff_records(base, new, tolerance=0.25)
    by_metric = {c["metric"]: c for c in diff["checks"]}
    assert by_metric["serve_hop_execute_s"]["regression"] is True
    assert by_metric["generation_age_s"]["regression"] is True
    assert diff["regression"] is True

    same = diff_records(base, dict(rec), tolerance=0.25)
    by_metric = {c["metric"]: c for c in same["checks"]}
    assert by_metric["serve_hop_execute_s"]["regression"] is False
    assert by_metric["generation_age_s"]["regression"] is False
    assert same["regression"] is False


# -------------------------------------------------------------- schema
def test_schema_rejects_malformed_qtrace_and_freshness():
    base = {"v": 2, "kind": "qtrace", "run": "r", "pid": 0, "ts": 1.0,
            "t": 0.1, "elapsed_s": 0.1, "trace_id": "a-1",
            "family": "members_of", "total_s": 0.01}
    assert validate_event(base) == []
    bad = dict(base, total_s="slow")
    assert validate_event(bad)
    missing = dict(base)
    del missing["trace_id"]
    assert validate_event(missing)

    f = {"v": 2, "kind": "freshness", "run": "r", "pid": 0, "ts": 1.0,
         "t": 0.1, "elapsed_s": 0.1, "generation_age_s": 3.5}
    assert validate_event(f) == []
    assert validate_event({k: v for k, v in f.items()
                           if k != "generation_age_s"})
