"""Collective-traffic accounting + host-skew observability (ISSUE 10):
the static bytes-per-step model vs the live buffers, balance/imbalance
events, the report-time straggler detector (single-process fake-host
path), the ledger's execution-shape match key + comms/overlap verdicts,
and the span-coverage band as a tier-1 unit check."""

import json
import os
import threading
import time

import numpy as np
import pytest

import jax

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.models.agm import sample_planted_graph
from bigclam_tpu.obs import RunTelemetry, install, uninstall
from bigclam_tpu.obs import comms as comms
from bigclam_tpu.obs.report import (
    load_events,
    render,
    render_json,
    span_coverage,
)
from bigclam_tpu.obs.schema import validate_events_file
from bigclam_tpu.obs.telemetry import EVENTS_NAME
from bigclam_tpu.parallel import (
    RingBigClamModel,
    ShardedBigClamModel,
    SparseShardedBigClamModel,
    make_mesh,
)


@pytest.fixture()
def planted():
    g, _ = sample_planted_graph(
        240, 4, p_in=0.3, rng=np.random.default_rng(0)
    )
    F0 = np.random.default_rng(1).uniform(0.1, 1.0, size=(g.num_nodes, 4))
    return g, F0


def _events(tdir):
    return load_events(tdir) or []


# ------------------------------------------------------------ conventions
def test_wire_byte_conventions():
    # all_gather: receive everyone else's shard
    assert comms.wire_bytes("all_gather", 100.0, 4) == 300.0
    # psum: ring allreduce reduce-scatter + all-gather
    assert comms.wire_bytes("psum", 100.0, 4) == pytest.approx(150.0)
    # ppermute: one hop
    assert comms.wire_bytes("ppermute", 100.0, 4) == 100.0
    # size-1 axis compiles to identity
    for op in ("all_gather", "psum", "ppermute", "pmax"):
        assert comms.wire_bytes(op, 100.0, 1) == 0.0
    with pytest.raises(ValueError):
        comms.wire_bytes("alltoall", 1.0, 2)


def test_sharded_model_arithmetic_by_hand():
    # n_pad=128, k_pad=8, dp=2, tp=1, f32: shard = 64*8*4 = 2048 B
    cm = comms.sharded_step_model(
        n_pad=128, k_pad=8, dp=2, tp=1, itemsize=4, num_candidates=16,
    )
    sites = cm.site_bytes()
    assert sites["sharded/all_gather_F"] == 2048.0      # (p-1)*shard
    # psum of (8,) f32 x2: 2 * (2*32*1/2) = 64
    assert sites["sharded/psum_sumF"] == 64.0
    # tp=1: no "k"-axis sites
    assert not any("edge_dots" in s for s in sites)
    assert cm.bytes_per_step() == sum(sites.values())


def test_ring_rotation_pays_dp_hops_per_pass():
    # rotate_scan does dp ppermute hops per pass (each device also
    # re-receives its own shard on the closing hop) and the candidate
    # pass re-rotates: 2 * dp * shard bytes/step, a dp/(dp-1) premium
    # per pass over the all-gather — the model must price what the scan
    # actually moves, not the idealized (dp-1)-hop exchange
    kw = dict(n_pad=256, k_pad=16, dp=4, tp=1, itemsize=4,
              num_candidates=16)
    ring = comms.ring_step_model(**kw)
    shard = (256 // 4) * 16 * 4
    assert ring.site_bytes()["ring/ppermute_F_rot"] == shard * 2 * 4
    ag = comms.sharded_step_model(**kw)
    assert ring.site_bytes()["ring/ppermute_F_rot"] == pytest.approx(
        2 * ag.site_bytes()["sharded/all_gather_F"] * 4 / 3
    )


def test_remeasure_replaces_named_payloads_only():
    cm = comms.sharded_step_model(
        n_pad=128, k_pad=8, dp=2, tp=1, itemsize=4, num_candidates=16,
    )
    doubled = cm.remeasure({"sharded/all_gather_F": 4096.0})
    assert doubled.site_bytes()["sharded/all_gather_F"] == 4096.0
    assert (
        doubled.site_bytes()["sharded/psum_sumF"]
        == cm.site_bytes()["sharded/psum_sumF"]
    )


# ------------------------------------------------- model vs live buffers
@pytest.mark.parametrize("dp", [2, 4])
def test_sharded_model_agrees_with_measured(planted, dp):
    g, F0 = planted
    cfg = BigClamConfig(num_communities=4, dtype="float64", max_iters=2)
    mesh = make_mesh((dp, 1), jax.devices()[:dp])
    m = ShardedBigClamModel(g, cfg, mesh)
    state = m.init_state(F0)
    modeled = m.comms.bytes_per_step()
    measured = m.comms_measured(state).bytes_per_step()
    assert modeled > 0
    assert measured == pytest.approx(modeled, rel=0.01)


@pytest.mark.filterwarnings("ignore:ring phase buckets")
def test_ring_model_agrees_with_measured(planted):
    g, F0 = planted
    cfg = BigClamConfig(num_communities=4, dtype="float64", max_iters=2)
    mesh = make_mesh((2, 1), jax.devices()[:2])
    m = RingBigClamModel(g, cfg, mesh, balance=False)
    state = m.init_state(F0)
    assert m.comms.family == "ring"
    assert m.comms_measured(state).bytes_per_step() == pytest.approx(
        m.comms.bytes_per_step(), rel=0.01
    )


def test_sparse_runtime_counters_reconcile(planted):
    g, F0 = planted
    K = 64
    F0w = np.zeros((g.num_nodes, K))
    F0w[:, :4] = F0
    cfg = BigClamConfig(
        num_communities=K, dtype="float64", max_iters=3,
        representation="sparse", sparse_m=8, sparse_comm_cap=16,
    )
    mesh = make_mesh((2, 1), jax.devices()[:2])
    m = SparseShardedBigClamModel(g, cfg, mesh)
    assert m.comm_mode == "sparse"
    state = m._step(m.init_state(F0w))
    rec = m.comms_measured(state)
    assert rec["cap"] == m.comm_cap
    if not rec["dense_fallback"]:
        assert rec["exchanged_ids"] <= rec["cap"]
        assert rec["exchange_bytes_per_step"] == pytest.approx(
            m.comms.site_bytes()["sparse/allreduce_touched"], rel=0.01
        )
    # member-gather payload from the live buffers matches the model
    assert rec["payloads"]["sparse/all_gather_members"] == pytest.approx(
        m.comms.sites[0].payload_bytes, rel=0.01
    )


# ------------------------------------------------------- events + report
def test_comms_and_balance_events_land_in_report(planted, tmp_path):
    g, F0 = planted
    cfg = BigClamConfig(
        num_communities=4, dtype="float64", max_iters=3, conv_tol=0.0
    )
    tdir = str(tmp_path / "telem")
    tel = install(RunTelemetry(tdir, entry="fit", quiet=True))
    try:
        mesh = make_mesh((2, 1), jax.devices()[:2])
        m = ShardedBigClamModel(g, cfg, mesh)
        m.fit(F0)
        rep = tel.finalize()
    finally:
        uninstall(tel)
    n, errors = validate_events_file(os.path.join(tdir, EVENTS_NAME))
    assert not errors, errors[:5]
    events = _events(tdir)
    kinds = {e["kind"] for e in events}
    assert "comms" in kinds and "balance" in kinds
    # every comms event names a site with modeled bytes
    for e in events:
        if e["kind"] == "comms":
            assert e["site"].startswith("sharded/")
            assert e["bytes_per_step"] >= 0
    bal = next(e for e in events if e["kind"] == "balance")
    assert bal["what"] == "shard_edges"
    assert bal["skew"] >= 1.0
    assert "pad_frac" in bal            # csr_tiles.tile_pad_stats rode in
    # run report + renderers carry the accumulated model
    assert rep["comms"]["sites"]
    assert rep["comms"]["bytes_per_step"] == pytest.approx(
        m.comms.bytes_per_step(), rel=0.01
    )
    text, errs = render(tdir)
    assert errs == 0
    assert "collective traffic (modeled)" in text
    obj, jerrs = render_json(tdir)
    assert jerrs == 0
    assert obj["comms"]["sites"]


def test_imbalance_anomaly_fires_on_locality_ordered_ring(tmp_path):
    # strongly diagonal planted graph, balance=False: the old stderr
    # warning now also fires the imbalance anomaly event
    g, _ = sample_planted_graph(
        256, 8, p_in=0.9, rng=np.random.default_rng(2)
    )
    cfg = BigClamConfig(num_communities=8, dtype="float64", max_iters=2)
    mesh = make_mesh((4, 1), jax.devices()[:4])
    tdir = str(tmp_path / "imb")
    tel = install(RunTelemetry(tdir, entry="fit", quiet=True))
    try:
        with pytest.warns(UserWarning, match="imbalanced"):
            RingBigClamModel(g, cfg, mesh, balance=False)
        tel.finalize()
    finally:
        uninstall(tel)
    events = _events(tdir)
    fired = [e for e in events if e.get("kind") == "anomaly"]
    assert fired and all(e["check"] == "imbalance" for e in fired)
    ring_anoms = [e for e in fired if e.get("what") == "ring_buckets"]
    assert ring_anoms and ring_anoms[0]["iter"] == -1
    assert ring_anoms[0]["factor"] > comms.IMBALANCE_FACTOR
    # balanced build: no anomaly
    tdir2 = str(tmp_path / "bal")
    tel = install(RunTelemetry(tdir2, entry="fit", quiet=True))
    try:
        RingBigClamModel(g, cfg, mesh, balance=True)
        tel.finalize()
    finally:
        uninstall(tel)
    assert not [
        e for e in _events(tdir2) if e.get("kind") == "anomaly"
    ]


def test_accounting_on_trajectory_bit_identical(planted):
    g, F0 = planted
    cfg = BigClamConfig(
        num_communities=4, dtype="float64", max_iters=5, conv_tol=0.0
    )
    mesh = make_mesh((2, 1), jax.devices()[:2])
    r_off = ShardedBigClamModel(g, cfg, mesh).fit(F0)
    import tempfile

    tel = install(
        RunTelemetry(tempfile.mkdtemp(), entry="fit", quiet=True)
    )
    try:
        r_on = ShardedBigClamModel(g, cfg, mesh).fit(F0)
    finally:
        tel.finalize()
        uninstall(tel)
    assert np.array_equal(r_on.F, r_off.F)
    assert r_on.llh_history == r_off.llh_history


def test_reemitted_model_replaces_its_site_set(tmp_path):
    # the sparse cap refinement can flip the collective MODE: the
    # re-emitted model must REPLACE its previous sites everywhere, or a
    # stale allreduce site keeps inflating bytes/step (report, ledger,
    # watch) for a layout the compiled step abandoned
    from bigclam_tpu.obs.watch import render_frame

    tdir = str(tmp_path / "re")
    tel = install(RunTelemetry(tdir, entry="t", quiet=True))
    try:
        kw = dict(n_pad=128, m=8, k_pad=64, dp=2, itemsize=4,
                  num_candidates=16)
        comms.emit_model(
            comms.sparse_step_model(cap=16, mode="sparse", **kw)
        )
        dense = comms.sparse_step_model(cap=64, mode="dense", **kw)
        comms.emit_model(dense)
        rep = tel.finalize()
    finally:
        uninstall(tel)
    sites = rep["comms"]["sites"]
    assert "sparse/allreduce_touched" not in sites
    assert "sparse/psum_sumF" in sites
    assert rep["comms"]["bytes_per_step"] == pytest.approx(
        dense.bytes_per_step(), rel=0.01
    )
    # the watch fold applies the same replacement
    frame = render_frame(tdir)
    assert f"over {len(dense.sites)} site(s)" in frame


# ------------------------------------------------- host-skew detector
def _fake_report(pid, sync_s, fit_s, host="hostA", dispatch_s=0.2):
    spans = {
        "fit": fit_s,
        "fit/fit_loop/dispatch": dispatch_s,
        "fit/fit_loop/sync": sync_s,
        "fit/fit_loop/callback": 0.05,
    }
    return {
        "v": 2, "run": "r", "pid": pid, "processes": 2, "entry": "fit",
        "started_unix": 0.0, "wall_s": fit_s + 0.5,
        "stages": {"seconds": {"fit": fit_s}, "counts": {"fit": 1}},
        "spans": {
            "seconds": spans,
            "counts": {k: 1 for k in spans},
            "orphans": 0,
        },
        "steps_timed": 0,
        "health": {"samples": 0, "last": None, "anomalies": {}},
        "comms": {"bytes_per_step": 0.0, "sites": {}},
        "fingerprint": {"host": host, "platform": "linux",
                        "backend": None, "device_kind": None,
                        "devices": 0},
        "memory": {"host_rss_bytes": 0, "host_rss_peak_bytes": 0,
                   "device_peak": {}, "watermark_tags": {}},
        "compiles": {"backend_compiles": 0, "backend_compile_s": 0.0,
                     "retraces": 0, "by_key": {}, "step_builds": 0,
                     "monitor": False, "count": 0},
        "heartbeat": {"deadline_s": None, "stalls": 0, "escalations": 0},
        "events": {"start": 1}, "final": {},
    }


def test_detector_waiters_rule_names_min_sync_pid():
    # p1 is the straggler: everyone ELSE sits in sync waiting on it
    reports = [
        _fake_report(0, sync_s=6.0, fit_s=6.5),
        _fake_report(1, sync_s=0.4, fit_s=6.5, host="hostB"),
    ]
    found = comms.detect_host_skew(reports)
    assert len(found) == 1
    f = found[0]
    assert f["check"] == "straggler" and f["rule"] == "waiters"
    assert f["pid"] == 1 and f["host"] == "hostB"


def test_detector_overhead_rule_names_delayed_pid():
    # syncs agree; p1 burned 4s OUTSIDE the loop phases (planted delay)
    reports = [
        _fake_report(0, sync_s=0.5, fit_s=1.0),
        _fake_report(1, sync_s=0.5, fit_s=5.0, host="hostB"),
    ]
    found = comms.detect_host_skew(reports)
    assert len(found) == 1
    f = found[0]
    assert f["rule"] == "overhead" and f["pid"] == 1
    assert f["overhead_s"] > f["peers_overhead_s"]


def test_detector_clean_and_single_process_fire_nothing():
    balanced = [
        _fake_report(0, sync_s=1.0, fit_s=1.5),
        _fake_report(1, sync_s=1.1, fit_s=1.6),
    ]
    assert comms.detect_host_skew(balanced) == []
    assert comms.detect_host_skew(
        [_fake_report(0, sync_s=1.0, fit_s=1.5)]
    ) == []


def test_fake_host_merged_dir_surfaces_straggler(tmp_path):
    # the single-process fake-host path (ISSUE 10 satellite): two
    # per-pid reports synthesized into one telemetry dir — the tier-1
    # detector coverage on jax versions whose 2-proc worker modes skip
    tdir = tmp_path / "merged"
    tdir.mkdir()
    (tdir / "run_report.json").write_text(
        json.dumps(_fake_report(0, sync_s=6.0, fit_s=6.5))
    )
    (tdir / "run_report.p1.json").write_text(
        json.dumps(_fake_report(1, sync_s=0.4, fit_s=6.5, host="hostB"))
    )
    text, errors = render(str(tdir))
    assert errors == 0
    assert "STRAGGLER: p1 (host hostB)" in text
    assert "per-iteration sync totals" in text
    obj, jerrs = render_json(str(tdir))
    assert jerrs == 0
    stragglers = [
        a for a in obj["anomalies"] if a.get("check") == "straggler"
    ]
    assert len(stragglers) == 1
    assert stragglers[0]["pid"] == 1
    assert stragglers[0]["source"] == "report"
    assert obj["sync_by_pid"] == {"0": 6.0, "1": 0.4}


# ---------------------------------------------------------------- ledger
def test_ledger_match_key_gains_processes_and_mesh():
    from bigclam_tpu.obs.ledger import build_record, match_key

    def rep(processes=1, mesh=None):
        r = _fake_report(0, sync_s=0.1, fit_s=0.2)
        r["processes"] = processes
        r["final"] = {"n": 10, "edges": 20, "k": 4, "mesh": mesh}
        r["compiles"]["by_key"] = {"K:4": {"builds": 1, "compiles": 1}}
        return r

    one = build_record(rep(processes=1))
    one2 = build_record(rep(processes=1))
    two = build_record(rep(processes=2))
    mesh41 = build_record(rep(processes=1, mesh="4x1"))
    mesh22 = build_record(rep(processes=1, mesh="2x2"))
    assert match_key(one) == match_key(one2)
    # a 2-proc run can no longer baseline against a single-proc run
    assert match_key(one) != match_key(two)
    assert match_key(mesh41) != match_key(mesh22)
    assert one["processes"] == 1 and two["processes"] == 2


def test_perf_diff_verdicts_comms_bytes_and_overlap():
    from bigclam_tpu.obs.ledger import build_record, diff_records

    r = _fake_report(0, sync_s=0.1, fit_s=0.2)
    r["comms"] = {
        "bytes_per_step": 1000.0,
        "sites": {"ring/ppermute_F_rot": 900.0, "ring/psum_sumF": 100.0},
    }
    r["final"] = {"overlap_frac": 0.6}
    base = build_record(r, [0.01] * 20, [100.0] * 20)
    assert base["comms_bytes_per_step"] == 1000.0
    assert base["overlap_frac"] == 0.6
    # injected bytes/step regression: same run, 3x the modeled traffic
    worse = dict(base, run="injected", ts=base["ts"] + 1,
                 comms_bytes_per_step=3000.0,
                 comms_sites={"ring/ppermute_F_rot": 2900.0,
                              "ring/psum_sumF": 100.0})
    d = diff_records(base, worse)
    assert d["regression"]
    flagged = [c for c in d["checks"]
               if c["metric"] == "comms_bytes_per_step"]
    assert flagged and flagged[0]["regression"]
    assert d["comms_deltas"][0]["site"] == "ring/ppermute_F_rot"
    # overlap collapse is a regression too
    stale = dict(base, run="stale", ts=base["ts"] + 2, overlap_frac=0.05)
    d2 = diff_records(base, stale)
    flagged = [c for c in d2["checks"] if c["metric"] == "overlap_frac"]
    assert flagged and flagged[0]["regression"] and d2["regression"]
    # identical re-run passes
    same = dict(base, run="same", ts=base["ts"] + 3)
    assert not diff_records(base, same)["regression"]


# ------------------------------------------------- span coverage (tier-1)
def test_span_coverage_band_over_synthetic_reports():
    # the 0.95 <= cov <= 1.05 acceptance previously asserted only in
    # scripts/telemetry_smoke.py (ISSUE 10 satellite): in-band, a gap
    # (unattributed time), and a double-count all classify correctly
    ok = {"wall_s": 10.0, "spans": {"seconds": {
        "load": 2.0, "fit": 7.8, "fit/fit_loop/sync": 5.0}}}
    cov = span_coverage(ok)
    assert 0.95 <= cov <= 1.05            # children never double-count
    gap = {"wall_s": 10.0, "spans": {"seconds": {"fit": 5.0}}}
    assert span_coverage(gap) < 0.95
    dbl = {"wall_s": 10.0, "spans": {"seconds": {"a": 6.0, "b": 6.0}}}
    assert span_coverage(dbl) > 1.05
    assert span_coverage({"wall_s": 0, "spans": {"seconds": {}}}) is None


def test_span_coverage_band_over_live_event_stream(tmp_path):
    from bigclam_tpu.obs import trace as obs_trace

    tel = install(
        RunTelemetry(str(tmp_path / "cov"), entry="cov", quiet=True)
    )
    try:
        with obs_trace.span("main"):
            time.sleep(0.6)
        rep = tel.finalize()
    finally:
        uninstall(tel)
    cov = span_coverage(rep)
    assert cov is not None and 0.95 <= cov <= 1.05, cov


# ------------------------------------------------- heartbeat sync context
def test_stall_event_embeds_last_sync_duration(tmp_path):
    from bigclam_tpu.obs.heartbeat import Heartbeat

    tel = RunTelemetry(str(tmp_path / "hb"), entry="t", quiet=True)
    tel.span_complete("fit/fit_loop/sync", 0.123, emit=False)
    hb = Heartbeat(tel, deadline_s=0.05, echo=False, poll_s=0.01).start()
    deadline = time.monotonic() + 3.0
    while tel.event_counts.get("stall", 0) == 0:
        assert time.monotonic() < deadline, "no stall fired"
        time.sleep(0.01)
    hb.stop()
    tel.finalize()
    events = _events(str(tmp_path / "hb"))
    stall = next(e for e in events if e["kind"] == "stall")
    assert stall["sync_s"] == pytest.approx(0.123)


def test_sync_tracking_is_thread_safe_and_cheap():
    import tempfile

    tel = RunTelemetry(tempfile.mkdtemp(), entry="t", quiet=True)

    def spam():
        for _ in range(200):
            tel.span_complete("fit/fit_loop/sync", 0.001, emit=False)

    threads = [threading.Thread(target=spam) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert tel.last_sync_s == pytest.approx(0.001)
    tel.finalize()


# ------------------------------------------------ 2D partition (ISSUE 16)
def test_wire_byte_scatter_conventions():
    # reduce_scatter / psum_scatter / all_to_all: each participant keeps
    # its own 1/p slice off the wire
    for op in ("reduce_scatter", "psum_scatter", "all_to_all"):
        assert comms.wire_bytes(op, 100.0, 4) == 75.0
        assert comms.wire_bytes(op, 100.0, 1) == 0.0


def test_twod_model_arithmetic_by_hand():
    # n_pad=128, rows=2, cols=2 -> p=4, n_blk=32; k_pad=8 f32 -> 32 B/row
    cm = comms.twod_step_model(
        n_pad=128, k_pad=8, rows=2, cols=2, itemsize=4,
        num_candidates=16, closure_cap=10,
    )
    sites = cm.site_bytes()
    # src-row gather over cols only: (cols-1) * 32*32 = 1024
    assert sites["twod/allgather_srcF"] == 1024.0
    # capped closure all_to_all over rows: (2*10*32) * (2-1)/2 = 320
    assert sites["twod/alltoall_closure"] == 320.0
    # partial-group grad psum of the (cols*n_blk, k) row group
    assert sites["twod/psum_grad"] == 2048.0
    # candidate/LLH accumulators reduced AND scattered: keep 1/cols
    assert sites["twod/psum_scatter_cand"] == 16 * 64 * 4 / 2
    assert sites["twod/psum_scatter_nbr_llh"] == 64 * 4 / 2
    # sumF reduces over the WHOLE mesh, twice a step: 2 * 2*32*(3/4)
    assert sites["twod/psum_sumF"] == 96.0
    assert cm.family == "twod"
    assert cm.bytes_per_step() == pytest.approx(sum(sites.values()))


def test_twod_model_undercuts_1d_iff_cap_below_block():
    kw = dict(n_pad=1024, k_pad=16, itemsize=4, num_candidates=16)
    one_d = comms.sharded_step_model(dp=4, tp=1, **kw)
    capped = comms.twod_step_model(rows=4, cols=1, closure_cap=64, **kw)
    full = comms.twod_step_model(rows=4, cols=1, closure_cap=256, **kw)
    assert capped.bytes_per_step() < one_d.bytes_per_step()
    assert full.bytes_per_step() > capped.bytes_per_step()
    # at cap == n_blk the closure exchange pays exactly the 1D gather
    assert full.site_bytes()["twod/alltoall_closure"] == \
        one_d.site_bytes()["sharded/all_gather_F"]


def test_twod_model_agrees_with_measured(planted):
    from bigclam_tpu.parallel import TwoDShardedBigClamModel, make_mesh_2d

    g, F0 = planted
    cfg = BigClamConfig(num_communities=4, dtype="float64", max_iters=2,
                        partition="2d", replica_cols=2)
    m = TwoDShardedBigClamModel(
        g, cfg, make_mesh_2d((2, 2), jax.devices()[:4])
    )
    state = m.init_state(F0)
    assert m.comms.family == "twod"
    assert m.comms_measured(state).bytes_per_step() == pytest.approx(
        m.comms.bytes_per_step(), rel=0.01
    )


def test_health_psum_prices_full_mesh():
    # the health-pack psums run OUTSIDE shard_map on the global arrays:
    # the reduction spans dp*tp, not just the node axis
    base = dict(n_pad=128, k_pad=8, itemsize=4, num_candidates=16,
                health_every=1)
    dp_only = comms.sharded_step_model(dp=2, tp=2, **base)
    mesh_wide = comms.sharded_step_model(dp=2, tp=2,
                                         health_participants=4, **base)
    h = next(s for s in mesh_wide.sites
             if s.site == "sharded/psum_health")
    assert h.participants == 4
    assert mesh_wide.site_bytes()["sharded/psum_health"] > \
        dp_only.site_bytes()["sharded/psum_health"]
