"""Model-selection tests (SURVEY.md §4.6): the K-grid golden artifact and the
sweep's stop rule."""

import numpy as np

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.models.agm import planted_partition_F, sample_graph
from bigclam_tpu.models.model_selection import build_kset, sweep_k


def test_kset_golden_artifact():
    """The pasted run artifact at bigclam4-7.scala:268 — Kset for a (50, 200)
    grid: reproduced exactly with div_com=15."""
    assert build_kset(50, 200, 15) == [
        50, 54, 59, 64, 70, 76, 83, 91, 99, 108, 118, 129, 141, 154, 168,
        184, 200,
    ]


def test_kset_default_grid_properties():
    ks = build_kset(1000, 9000, 100)
    assert ks[0] == 1000 and ks[-1] == 9000
    assert all(b > a for a, b in zip(ks, ks[1:]))


def test_kset_stuck_bump():
    # tiny ratio: conGap so small the walk must bump by +1 each time
    ks = build_kset(5, 10, 1000)
    assert ks == [5, 6, 7, 8, 9, 10]


def test_kset_degenerate_ratio():
    # max_com // min_com == 0 cannot happen (max>=min), but ratio 1 gives
    # log(1)=0 -> conGap=1 -> pure +1 walk
    ks = build_kset(7, 9, 100)
    assert ks == [7, 8, 9]


def test_sweep_resumes_mid_k(tmp_path):
    """Kill-and-resume INSIDE a K (VERDICT item 7): a sweep crashed partway
    through one K's fit must resume from that K's periodic checkpoint and
    reproduce the uninterrupted sweep exactly."""
    from bigclam_tpu.models.bigclam import BigClamModel

    rng = np.random.default_rng(11)
    Fp, _ = planted_partition_F(48, 4, strength=2.0)
    g = sample_graph(Fp, rng=rng)
    cfg = BigClamConfig(
        num_communities=6, dtype="float64", max_iters=10, conv_tol=0.0,
        min_com=2, max_com=6, div_com=2, ksweep_tol=0.0,
        checkpoint_every=2,
    )
    # conv_tol/ksweep_tol 0.0: every K runs exactly max_iters (deterministic
    # step counts for crash placement), the sweep walks the whole grid

    ref = sweep_k(g, cfg)                      # uninterrupted reference

    # crash partway through the SECOND K's fit: each fit makes max_iters+1
    # step calls (the loop evaluates one extra speculative step)
    crash_at = (cfg.max_iters + 1) + 5
    calls = {"n": 0}

    def crashy_factory(cfg_max):
        m = BigClamModel(g, cfg_max)
        orig = m._step

        def step(st):
            calls["n"] += 1
            if calls["n"] == crash_at:
                raise RuntimeError("simulated crash")
            return orig(st)

        m._step = step
        return m

    state_dir = str(tmp_path / "sweep")
    try:
        sweep_k(g, cfg, model_factory=crashy_factory, state_dir=state_dir)
        raise AssertionError("crash did not fire")
    except RuntimeError:
        pass
    import json
    import os

    # first K journaled; the crashed K left mid-fit checkpoints behind
    with open(os.path.join(state_dir, "sweep_state.json")) as f:
        journal = {int(k): v for k, v in json.load(f).items()}
    assert list(journal) == [ref.kset[0]]
    k2_dir = os.path.join(state_dir, f"k_{ref.kset[1]:06d}")
    assert os.path.isdir(k2_dir) and os.listdir(k2_dir)

    resumed = sweep_k(g, cfg, state_dir=state_dir)
    assert resumed.chosen_k == ref.chosen_k
    assert resumed.kset == ref.kset
    for k in ref.llh_by_k:
        np.testing.assert_allclose(
            resumed.llh_by_k[k], ref.llh_by_k[k], rtol=1e-12
        )
    # spent within-K checkpoints were cleaned up
    assert not os.path.isdir(k2_dir) or not os.listdir(k2_dir)


def test_sweep_resume_rng_invariant_with_random_padding(tmp_path):
    """ADVICE round-2 medium bug: when |seeds| < K every K pads F0 with
    Bernoulli columns; journaled Ks skip init_F on restart, so a SHARED
    generator would leave later Ks at a different stream position than the
    uninterrupted run. The per-K streams must make resumed llh_by_k exact."""
    import json
    import os

    from bigclam_tpu.graph.ingest import graph_from_edges

    # one 10-clique: conductance nominees are only {0, 1}, so seeds = 2 and
    # every K in the grid below consumes the Bernoulli padding stream
    edges = [(i, j) for i in range(10) for j in range(i + 1, 10)]
    g = graph_from_edges(edges, num_nodes=10)
    cfg = BigClamConfig(
        num_communities=6, dtype="float64", max_iters=6, conv_tol=0.0,
        min_com=3, max_com=6, div_com=2, ksweep_tol=0.0,
    )
    from bigclam_tpu.ops import seeding

    assert len(seeding.conductance_seeds(g, cfg)) < cfg.min_com

    ref = sweep_k(g, cfg)                       # uninterrupted reference

    # simulate a resume where the first K is already journaled
    state_dir = tmp_path / "sweep"
    os.makedirs(state_dir)
    k0 = ref.kset[0]
    with open(state_dir / "sweep_state.json", "w") as f:
        json.dump({str(k0): ref.llh_by_k[k0]}, f)
    resumed = sweep_k(g, cfg, state_dir=str(state_dir))

    assert resumed.chosen_k == ref.chosen_k
    for k in ref.llh_by_k:
        np.testing.assert_allclose(
            resumed.llh_by_k[k], ref.llh_by_k[k], rtol=0, atol=0
        )


def test_sweep_on_planted_graph():
    """Sweep K over a graph with 4 planted blocks: LLH improves sharply up
    to ~4 and the sweep stops early with a sensible KforC."""
    rng = np.random.default_rng(11)
    Fp, _ = planted_partition_F(48, 4, strength=2.0)
    g = sample_graph(Fp, rng=rng)
    cfg = BigClamConfig(
        num_communities=8, dtype="float64", max_iters=40,
        min_com=2, max_com=8, div_com=4, ksweep_tol=1e-3,
    )
    res = sweep_k(g, cfg)
    assert res.kset[0] == 2 and res.kset[-1] == 8
    assert res.chosen_k in res.llh_by_k
    # every trained K got a finite LLH and the sweep trained at least 2 Ks
    assert len(res.llh_by_k) >= 2
    assert all(np.isfinite(v) for v in res.llh_by_k.values())
    # LLH at the largest trained K is no worse than at the smallest
    trained = sorted(res.llh_by_k)
    assert res.llh_by_k[trained[-1]] >= res.llh_by_k[trained[0]]


def test_quality_sweep(tmp_path):
    """sweep_k under cfg.quality_mode: each K trains with the annealing
    schedule, the kick restricted to the active K columns; the sweep walks
    the same grid and journals/resumes identically."""
    from bigclam_tpu.models.agm import sample_planted_graph
    from bigclam_tpu.models.model_selection import sweep_k

    g, truth = sample_planted_graph(
        600, 25, p_in=0.3, rng=np.random.default_rng(7)
    )
    cfg = BigClamConfig(
        num_communities=25, quality_mode=True, restart_cycles=4,
        min_com=10, max_com=30, div_com=3,
        use_pallas=False, use_pallas_csr=False,
    )
    res = sweep_k(g, cfg, state_dir=str(tmp_path / "s"))
    assert res.kset[0] == 10 and res.kset[-1] == 30
    assert set(res.llh_by_k) <= set(res.kset)
    # annealed LLH at larger K must not be worse than at tiny K
    ks = sorted(res.llh_by_k)
    assert res.llh_by_k[ks[-1]] > res.llh_by_k[ks[0]]
    # resume from the journal is a no-op (all trained Ks skip)
    res2 = sweep_k(g, cfg, state_dir=str(tmp_path / "s"))
    assert res2.llh_by_k == res.llh_by_k
    assert res2.chosen_k == res.chosen_k


def test_quality_sweep_device_annealing():
    """sweep_k(device_annealing=True): per-K device-resident annealing,
    padding columns >= k stay inert (kick_cols), same grid walk."""
    from bigclam_tpu.models.agm import sample_planted_graph
    from bigclam_tpu.models.model_selection import sweep_k

    g, truth = sample_planted_graph(
        600, 25, p_in=0.3, rng=np.random.default_rng(7)
    )
    cfg = BigClamConfig(
        num_communities=25, quality_mode=True, restart_cycles=3,
        restart_tol=0.0, min_com=10, max_com=25, div_com=2,
        use_pallas=False, use_pallas_csr=False,
    )
    res = sweep_k(g, cfg, device_annealing=True)
    assert res.kset[-1] == 25
    ks = sorted(res.llh_by_k)
    assert res.llh_by_k[ks[-1]] > res.llh_by_k[ks[0]]
    # grid-max F buffer: columns beyond the last trained K stayed zero
    assert res.best_fit is not None
    F = np.asarray(res.best_fit.F)
    assert F.shape[1] == 25
