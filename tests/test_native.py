"""Native C++ layer tests: parser and triangle counts must agree with the
NumPy fallbacks bit-for-bit."""

import numpy as np
import pytest

try:
    from bigclam_tpu.graph import native
except ImportError:
    native = None

needs_native = pytest.mark.skipif(native is None, reason="native lib unavailable")


@needs_native
def test_parser_matches_numpy(tmp_path):
    from bigclam_tpu.graph.stream import load_edge_list_streaming

    p = tmp_path / "g.txt"
    p.write_text("# header\n# another\n0 1\n1\t2\n  3   4\n\n5 6\n")
    np.testing.assert_array_equal(
        native.parse_edge_list(str(p)), load_edge_list_streaming(str(p))
    )


@needs_native
def test_parser_malformed(tmp_path):
    p = tmp_path / "bad.txt"
    p.write_text("0 1\n2\n")
    with pytest.raises(ValueError):
        native.parse_edge_list(str(p))


@needs_native
def test_parser_missing_file():
    with pytest.raises(OSError):
        native.parse_edge_list("/nonexistent/file.txt")


@needs_native
def test_parser_empty(tmp_path):
    p = tmp_path / "empty.txt"
    p.write_text("# nothing\n")
    assert native.parse_edge_list(str(p)).shape == (0, 2)


@needs_native
def test_triangles_match_numpy(toy_graphs, facebook_graph):
    import bigclam_tpu.ops.seeding as sd

    for g in [*toy_graphs.values(), facebook_graph]:
        # call the NumPy path directly (bypassing the native fast path)
        n = g.num_nodes
        indptr, indices = g.indptr, g.indices
        flags = np.zeros(n, dtype=bool)
        tri_np = np.zeros(n, dtype=np.int64)
        for u in range(n):
            nbrs = indices[indptr[u] : indptr[u + 1]]
            if nbrs.size == 0:
                continue
            flags[nbrs] = True
            z = np.concatenate([indices[indptr[v] : indptr[v + 1]] for v in nbrs])
            tri_np[u] = np.count_nonzero(flags[z]) // 2
            flags[nbrs] = False
        np.testing.assert_array_equal(native.triangle_counts(g), tri_np)


@needs_native
def test_enron_known_triangle_count():
    """SNAP's published statistic for email-Enron: 727,044 triangles.
    sum_u tri(u) counts each triangle three times."""
    from bigclam_tpu.graph.ingest import build_graph

    from tests.conftest import require_reference_data

    g = build_graph(require_reference_data("Email-Enron.txt"))
    assert int(native.triangle_counts(g).sum()) == 3 * 727044


def test_select_seeds_covering_matches_numpy(facebook_graph):
    """The native covering walk must choose bit-identical seeds to the
    NumPy reference loop (backend-independent seeding, same invariant as
    the capped triangle sampler). Compares against seeding's OWN fallback
    (_covering_walk_numpy), not a copy."""
    native = pytest.importorskip("bigclam_tpu.graph.native")
    from bigclam_tpu.config import BigClamConfig
    from bigclam_tpu.ops import seeding
    from bigclam_tpu.ops.seeding import _covering_walk_numpy

    g = facebook_graph
    cfg = BigClamConfig(num_communities=50, seeding_degree_cap=16)
    phi = seeding.conductance(g, backend="numpy")
    order = seeding.covering_order(g, phi, cfg)   # the production prep
    for hops in (1, 2):
        # facebook has hub nodes, so the cap/stride paths are exercised
        got = native.select_seeds_covering(g, order, 50, hops, 16)
        want = _covering_walk_numpy(g, order, 50, hops, 16)
        np.testing.assert_array_equal(got, want, err_msg=f"hops={hops}")
