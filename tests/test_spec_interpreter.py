"""Spec-interpreter unit oracles (SURVEY.md §4.1): closed-form LLH on tiny
graphs, folded gradient vs jax.grad autodiff, invariants of the line-search
update."""

import numpy as np
import pytest

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.graph.ingest import graph_from_edges
from bigclam_tpu.spec import interpreter as spec


CFG = BigClamConfig(num_communities=4)


def _rand_F(rng, n, k, lo=0.2, hi=1.0):
    return rng.uniform(lo, hi, size=(n, k))


def test_llh_triangle_closed_form(toy_graphs):
    """Hand-computed LLH on the triangle with constant F."""
    g = toy_graphs["triangle"]
    k = 2
    F = np.full((3, k), 0.5)
    sumF = F.sum(0)
    # every pair is an edge; x = F_u.F_v = 0.5 for all pairs (incl. self-dot)
    x = 0.5
    p = np.clip(np.exp(-x), CFG.min_p, CFG.max_p)
    # per node: 2 neighbors * (log(1-p)+x) - Fu.sumF + Fu.Fu
    per_node = 2 * (np.log(1 - p) + x) - (0.5 * 3 * 2 * 0.5) + x
    expected = 3 * per_node
    got = spec.loglikelihood(F, sumF, g, CFG)
    assert np.isclose(got, expected, rtol=1e-12)


def test_grad_matches_autodiff(rng, toy_graphs):
    """The folded gradient (Bigclamv2.scala:131-132) must equal the autodiff
    gradient of the global LLH (which double-counts each unordered pair, so
    d(global)/dF = 2 * per-node block gradient) when clipping is inactive."""
    import jax

    g = toy_graphs["two_cliques"]
    n, k = g.num_nodes, 3
    F = _rand_F(rng, n, k)
    cfg = CFG  # with F in [0.2,1], x in [0.12, 3]; exp(-x) in (0.05, 0.89): no clip
    src, dst = g.src, g.dst

    def llh_fn(F):
        import jax.numpy as jnp

        x = jnp.einsum("ek,ek->e", F[src], F[dst])
        p = jnp.clip(jnp.exp(-x), cfg.min_p, cfg.max_p)
        sumF = F.sum(0)
        tail = -F @ sumF + jnp.einsum("nk,nk->n", F, F)
        return (jnp.log(1 - p) + x).sum() + tail.sum()

    auto = jax.grad(llh_fn)(F)
    grad, node_llh = spec.grad_llh(F, F.sum(0), g, cfg)
    np.testing.assert_allclose(np.asarray(auto), 2.0 * grad, rtol=1e-9, atol=1e-9)
    assert np.isclose(float(llh_fn(F)), node_llh.sum(), rtol=1e-12)


def test_line_search_invariants(rng, toy_graphs):
    """Property tests (SURVEY.md §4.5): F stays in the box, sumF == colsum(F),
    LLH does not decrease on an accepted full-batch step."""
    g = toy_graphs["two_cliques"]
    n, k = g.num_nodes, 4
    F = _rand_F(rng, n, k)
    sumF = F.sum(0)
    llh0 = spec.loglikelihood(F, sumF, g, CFG)
    F1, sumF1, llh1 = spec.line_search_step(F, sumF, g, CFG)
    assert F1.min() >= CFG.min_f and F1.max() <= CFG.max_f
    np.testing.assert_allclose(sumF1, F1.sum(0), rtol=1e-12)
    assert llh1 >= llh0 - 1e-9


def test_unaccepted_nodes_unchanged(toy_graphs):
    """A node whose 16 candidates all fail Armijo keeps its row. Force this
    with an alpha so large no candidate can pass."""
    g = toy_graphs["triangle"]
    rng = np.random.default_rng(1)
    F = _rand_F(rng, 3, 2)
    cfg = CFG.replace(alpha=1e12)
    F1, _, _ = spec.line_search_step(F, F.sum(0), g, cfg)
    np.testing.assert_array_equal(F1, F)


def test_max_accepted_step_is_chosen(rng):
    """On a path graph with benign F, eta=1 typically passes Armijo; verify
    the chosen step reproduces clip(F + 1.0*grad) for nodes where the largest
    candidate is accepted (max-accepted-step rule, Bigclamv2.scala:145)."""
    g = graph_from_edges([(0, 1), (1, 2)])
    F = _rand_F(rng, 3, 2, lo=0.4, hi=0.8)
    cfg = CFG
    grad, node_llh = spec.grad_llh(F, F.sum(0), g, cfg)
    gg = (grad * grad).sum(1)
    # manually evaluate eta=1 acceptance for node 0
    eta = 1.0
    newF0 = np.clip(F[0] + eta * grad[0], cfg.min_f, cfg.max_f)
    nbrs = g.neighbors(0)
    x = newF0 @ F[nbrs].T
    p = np.clip(np.exp(-x), cfg.min_p, cfg.max_p)
    sf_adj = F.sum(0) - F[0] + newF0
    cand = (np.log(1 - p) + x).sum() - newF0 @ sf_adj + newF0 @ newF0
    accepted_full = cand >= node_llh[0] + cfg.alpha * eta * gg[0]
    F1, _, _ = spec.line_search_step(F, F.sum(0), g, cfg)
    if accepted_full:
        np.testing.assert_allclose(F1[0], newF0, rtol=1e-12)


def test_fit_converges_two_cliques(toy_graphs):
    """End-to-end: fit on two cliques + bridge converges and improves LLH."""
    g = toy_graphs["two_cliques"]
    rng = np.random.default_rng(2)
    F0 = rng.uniform(0.1, 0.9, size=(g.num_nodes, 2))
    sumF0 = F0.sum(0)
    llh0 = spec.loglikelihood(F0, sumF0, g, CFG)
    st = spec.fit(F0, g, CFG)
    assert st.llh > llh0
    assert st.num_iters < CFG.max_iters
    np.testing.assert_allclose(st.sumF, st.F.sum(0), rtol=1e-10)


def test_permutation_invariance(rng):
    """Relabeling nodes must not change the fitted LLH (SURVEY.md §4.5)."""
    edges = [(0, 1), (1, 2), (2, 3), (3, 0), (0, 2)]
    g1 = graph_from_edges(edges)
    perm = np.array([2, 0, 3, 1])
    g2 = graph_from_edges([(perm[u], perm[v]) for u, v in edges])
    F0 = _rand_F(rng, 4, 2)
    st1 = spec.fit(F0, g1, CFG)
    # row for new id perm[u] must equal F0[u] -> permute with argsort(perm)
    st2 = spec.fit(F0[np.argsort(perm)], g2, CFG)
    assert np.isclose(st1.llh, st2.llh, rtol=1e-8)
