"""Store-native compute tests (ISSUE 9): every stage of a store-backed fit
— edge blocks, blocked-CSR tiles, ring buckets/tiles, and seeding — builds
from HostShard local rows, bit-identical to the host-global builders.

The correctness bar throughout is EXACT equality: the tiles/buckets encode
the same edges, only who builds them changes. The 2-process worker modes
(tests/test_multihost.py) pin the files_read isolation contract; here the
same contract is pinned with fake hosts (load_shard_range slices) so the
suite runs on jax 0.4.37 too."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.graph.ingest import build_graph, graph_from_edges
from bigclam_tpu.graph.store import (
    MANIFEST_NAME,
    GraphStore,
    compile_graph_cache,
)
from bigclam_tpu.ops import csr_tiles as ct
from bigclam_tpu.ops import seeding


def _write_edges(path, pairs):
    with open(path, "w") as f:
        for u, v in np.asarray(pairs).tolist():
            f.write(f"{u} {v}\n")
    return str(path)


@pytest.fixture(scope="module")
def problem(tmp_path_factory):
    """A messy-degree 37-node graph + its 4-shard cache (rows_per_shard=10
    — divisible by the small interpret-mode tile blocks used below)."""
    tmp = tmp_path_factory.mktemp("store_native")
    rng = np.random.default_rng(0)
    edges = set()
    while len(edges) < 400:
        u, v = (int(x) for x in rng.integers(0, 37, 2))
        if u != v:
            edges.add((min(u, v), max(u, v)))
    edges = sorted(edges)
    text = _write_edges(tmp / "g.txt", edges)
    g = graph_from_edges(edges, num_nodes=37)
    store = compile_graph_cache(
        text, str(tmp / "cache"), num_shards=4, chunk_bytes=128
    )
    return g, store, text, tmp


@pytest.fixture(scope="module")
def clique_problem(tmp_path_factory):
    """The multihost worker's two-clique problem + 4-shard cache (float64
    trajectory-identity fits)."""
    tmp = tmp_path_factory.mktemp("store_native_fit")
    edges = []
    for base in (0, 12):
        for i in range(12):
            for j in range(i + 1, 12):
                edges.append((base + i, base + j))
    edges.append((11, 12))
    g = graph_from_edges(edges, num_nodes=24)
    text = _write_edges(tmp / "g.txt", edges)
    store = compile_graph_cache(
        text, str(tmp / "cache"), num_shards=4, chunk_bytes=64
    )
    F0 = np.random.default_rng(5).uniform(0.1, 1.0, size=(24, 2))
    return g, store, F0


# --------------------------------------------------------------------------
# builders: store-built == host-global, exactly
# --------------------------------------------------------------------------


def test_store_block_tiles_match_host_global(problem):
    g, store, _, _ = problem
    dp, block_b, tile_t = 4, 5, 8
    n_pad = dp * store.rows_per_shard
    ref = ct.shard_block_tiles(g, dp, n_pad, block_b, tile_t)
    hs = store.load_shard_range(0, 4)
    got = ct.shard_block_tiles_local(hs, dp, n_pad, block_b, tile_t)
    for f in ("src_local", "dst", "mask", "block_id"):
        np.testing.assert_array_equal(getattr(got, f), getattr(ref, f))
    assert (got.n_blocks, got.shard_rows) == (ref.n_blocks, ref.shard_rows)


def test_store_block_tiles_two_host_fake_isolation(problem):
    """Each fake host's tile rows equal the matching host-global rows, the
    cross-host pad (max of local maxima) equals the true global max, and
    files_read covers exactly the host's own shard blobs."""
    g, store, _, _ = problem
    dp, block_b, tile_t = 4, 5, 8
    n_pad = dp * store.rows_per_shard
    ref = ct.shard_block_tiles(g, dp, n_pad, block_b, tile_t)
    halves, local_max = [], []
    for h in range(2):
        hs = store.load_shard_range(2 * h, 2 * h + 2)
        own = {
            os.path.basename(p)
            for s in hs.shard_ids
            for p in store.shard_files(s)
        }
        assert set(hs.files_read) == own
        parts = ct.local_block_tile_parts(hs, dp, n_pad, block_b, tile_t)
        halves.append(parts)
        local_max.append(max(p.n_tiles for p in parts))
    pad = max(local_max)                  # == multihost.global_max_int
    assert pad == ref.n_tiles
    stacked = [ct.stack_block_tile_parts(p, pad) for p in halves]
    for f in ("src_local", "dst", "mask", "block_id"):
        np.testing.assert_array_equal(
            np.concatenate([getattr(s, f) for s in stacked]),
            getattr(ref, f),
        )
    with pytest.raises(ValueError, match="below this host"):
        ct.stack_block_tile_parts(halves[0], local_max[0] - 1)


def test_store_ring_tiles_match_host_global(problem):
    g, store, _, _ = problem
    dp, block_b, tile_t = 4, 5, 8
    n_pad = dp * store.rows_per_shard
    ref = ct.ring_block_tiles(g, dp, n_pad, block_b, tile_t)
    got = ct.ring_block_tiles_local(
        store.load_shard_range(0, 4), dp, n_pad, block_b, tile_t
    )
    for f in ("src_local", "dst_local", "mask", "block_id"):
        np.testing.assert_array_equal(getattr(got, f), getattr(ref, f))
    # per-fake-host halves concatenate to the global layout
    rp = [
        ct.local_ring_tile_parts(
            store.load_shard_range(2 * h, 2 * h + 2), dp, n_pad,
            block_b, tile_t,
        )
        for h in range(2)
    ]
    pad = max(p.n_tiles for half in rp for ps in half for p in ps)
    assert pad == ref.src_local.shape[2]
    stacked = [ct.stack_ring_tile_parts(p, pad) for p in rp]
    np.testing.assert_array_equal(
        np.concatenate([s.dst_local for s in stacked]), ref.dst_local
    )


def test_store_ring_buckets_match_host_global(problem):
    from bigclam_tpu.parallel.ring import (
        ring_bucket_imbalance,
        ring_bucket_local_max,
        ring_shard_edges,
        ring_shard_edges_local,
    )

    g, store, _, _ = problem
    dp = 4
    cfg = BigClamConfig(num_communities=2)
    n_pad = dp * store.rows_per_shard
    ref = ring_shard_edges(g, cfg, dp, n_pad, np.float32, chunk_bound=16)
    hs = store.load_shard_range(0, 4)
    assert ring_bucket_local_max(hs, dp, n_pad) == ring_bucket_imbalance(
        g, dp, n_pad
    )[0]
    got = ring_shard_edges_local(
        hs, cfg, dp, n_pad, np.float32, chunk_bound=16
    )
    for f in ("src", "dst", "mask"):
        np.testing.assert_array_equal(getattr(got, f), getattr(ref, f))
    # fake-host halves: local rows equal the matching global rows under
    # the globally-agreed max bucket count
    mx = ring_bucket_imbalance(g, dp, n_pad)[0]
    for h in range(2):
        half = store.load_shard_range(2 * h, 2 * h + 2)
        loc = ring_shard_edges_local(
            half, cfg, dp, n_pad, np.float32, chunk_bound=16, max_count=mx
        )
        np.testing.assert_array_equal(loc.src, ref.src[2 * h : 2 * h + 2])
        np.testing.assert_array_equal(loc.dst, ref.dst[2 * h : 2 * h + 2])


# --------------------------------------------------------------------------
# trajectory identity: store-backed CSR / ring fits == in-memory
# --------------------------------------------------------------------------


def _csr_cfg(**kw):
    base = dict(
        num_communities=2, dtype="float32", max_iters=6, conv_tol=0.0,
        use_pallas_csr=True, pallas_interpret=True, csr_block_b=3,
        csr_tile_t=8,
    )
    base.update(kw)
    return BigClamConfig(**base)


def test_store_sharded_csr_matches_in_memory(clique_problem):
    """use_pallas_csr=True on StoreShardedBigClamModel (the lifted ISSUE 9
    refusal): same interpret-mode kernels, same tiles, bit-identical
    trajectory to the in-memory sharded CSR run."""
    from bigclam_tpu.parallel import (
        ShardedBigClamModel,
        StoreShardedBigClamModel,
        make_mesh,
    )

    g, store, F0 = clique_problem
    cfg = _csr_cfg()
    mesh = make_mesh((4, 1), jax.devices()[:4])
    refm = ShardedBigClamModel(g, cfg, mesh)
    assert refm.engaged_path == "csr_fused", refm.path_reason
    ref = refm.fit(F0)
    m = StoreShardedBigClamModel(store, cfg, mesh)
    assert m.engaged_path == "csr_fused", m.path_reason
    got = m.fit(F0)
    np.testing.assert_allclose(got.F, ref.F, rtol=0, atol=0)
    assert got.llh_history == ref.llh_history


def test_store_sharded_csr_explicit_pad_tiles(clique_problem):
    """cfg.csr_store_pad_tiles: an explicit (over-)pad keeps the
    trajectory bit-identical (padding tiles are fully masked); a pad below
    the true tile count is a loud error."""
    from bigclam_tpu.parallel import (
        ShardedBigClamModel,
        StoreShardedBigClamModel,
        make_mesh,
    )

    g, store, F0 = clique_problem
    mesh = make_mesh((4, 1), jax.devices()[:4])
    ref = ShardedBigClamModel(g, _csr_cfg(), mesh).fit(F0)
    sbt = ct.shard_block_tiles(g, 4, 4 * store.rows_per_shard, 3, 8)
    over = _csr_cfg(csr_store_pad_tiles=sbt.n_tiles + 3)
    got = StoreShardedBigClamModel(store, over, mesh).fit(F0)
    np.testing.assert_allclose(got.F, ref.F, rtol=0, atol=0)
    with pytest.raises(ValueError, match="below this host"):
        StoreShardedBigClamModel(
            store, _csr_cfg(csr_store_pad_tiles=1), mesh
        )


def test_store_ring_matches_in_memory(clique_problem):
    from bigclam_tpu.parallel import (
        RingBigClamModel,
        StoreRingBigClamModel,
        make_mesh,
    )

    g, store, F0 = clique_problem
    cfg = BigClamConfig(
        num_communities=2, dtype="float64", max_iters=8, conv_tol=0.0,
        use_pallas_csr=False,
    )
    mesh = make_mesh((4, 1), jax.devices()[:4])
    ref = RingBigClamModel(g, cfg, mesh, balance=False).fit(F0)
    m = StoreRingBigClamModel(store, cfg, mesh)
    assert m.engaged_path == "xla"
    got = m.fit(F0)
    np.testing.assert_allclose(got.F, ref.F, rtol=0, atol=0)
    assert got.llh_history == ref.llh_history


@pytest.mark.parametrize("kb", [0, 1])
def test_store_ring_csr_matches_in_memory(clique_problem, kb):
    """Ring CSR (flat and K-blocked phases) on store-built tile buckets ==
    the in-memory ring CSR trajectory, bit for bit."""
    from bigclam_tpu.parallel import (
        RingBigClamModel,
        StoreRingBigClamModel,
        make_mesh,
    )

    g, store, F0 = clique_problem
    cfg = _csr_cfg(csr_k_block=kb)
    mesh = make_mesh((4, 1), jax.devices()[:4])
    refm = RingBigClamModel(g, cfg, mesh, balance=False)
    want = "csr_ring_fused_kb" if kb else "csr_ring_fused"
    assert refm.engaged_path == want, refm.path_reason
    ref = refm.fit(F0)
    m = StoreRingBigClamModel(store, cfg, mesh)
    assert m.engaged_path == want, m.path_reason
    got = m.fit(F0)
    np.testing.assert_allclose(got.F, ref.F, rtol=0, atol=0)
    assert got.llh_history == ref.llh_history


def test_store_csr_refusals_consistent(clique_problem):
    """The lifted refusal keeps the shared wording families: row/block
    misalignment and the K-blocked grouped layout refuse under
    use_pallas_csr=True with actionable messages, and FALL BACK with the
    same text as the recorded reason otherwise."""
    from bigclam_tpu.parallel import StoreShardedBigClamModel, make_mesh

    _, store, _ = clique_problem
    mesh = make_mesh((4, 1), jax.devices()[:4])
    with pytest.raises(ValueError, match="not a multiple of"):
        StoreShardedBigClamModel(store, _csr_cfg(csr_block_b=4), mesh)
    m = StoreShardedBigClamModel(
        store, _csr_cfg(csr_block_b=4, use_pallas_csr=None), mesh
    )
    assert m.engaged_path == "xla"
    assert "not a multiple of" in m.path_reason
    # the K-blocked layout ENGAGES on the fused default (flat store
    # tiles, ISSUE 13 — the closed grouped/K-blocked store gap); only the
    # explicit split override still refuses, with the actionable hint
    m_kb = StoreShardedBigClamModel(store, _csr_cfg(csr_k_block=1), mesh)
    assert m_kb.engaged_path == "csr_fused_kb", m_kb.path_reason
    with pytest.raises(ValueError, match="not store-native on the split"):
        StoreShardedBigClamModel(
            store, _csr_cfg(csr_k_block=1, csr_fused=False), mesh
        )


# --------------------------------------------------------------------------
# ingest-baked seeding
# --------------------------------------------------------------------------


def test_baked_seed_scores_bit_identical_exact(problem):
    g, store, _, _ = problem
    ss = store.load_seed_scores()
    np.testing.assert_array_equal(
        ss.phi, seeding.conductance(g, backend="numpy")
    )
    assert ss.cap is None
    # per-range loads read ONLY those shards' phi blobs (files_read)
    half = store.load_seed_scores(0, 2)
    np.testing.assert_array_equal(half.phi, ss.phi[half.lo : half.hi])
    assert set(half.files_read) == {
        "shard_00000.phi.npy", "shard_00001.phi.npy"
    }
    # and the ranking from baked phi equals the streamed ranking
    cfg = BigClamConfig(num_communities=5)
    np.testing.assert_array_equal(
        seeding.conductance_seeds(g, cfg, phi=ss.phi),
        seeding.conductance_seeds(g, cfg, backend="numpy"),
    )


def test_baked_seed_scores_capped_matches_sampled(problem, tmp_path):
    g, store, text, _ = problem
    cap = 6
    st = compile_graph_cache(
        text, str(tmp_path / "capped.cache"), num_shards=3,
        chunk_bytes=256, seed_cap=cap, seed=0,
    )
    phi_ref = seeding.conductance(
        g, backend="sampled", degree_cap=cap,
        rng=np.random.default_rng(0),
    )
    got = st.load_seed_scores()
    assert got.cap == cap
    np.testing.assert_allclose(got.phi, phi_ref, rtol=1e-9)
    # cap >= max degree: the estimator is exact and the bake bit-matches
    st2 = compile_graph_cache(
        text, str(tmp_path / "exactcap.cache"), num_shards=2,
        seed_cap=int(g.degrees.max()),
    )
    np.testing.assert_array_equal(
        st2.load_seed_scores().phi, seeding.conductance(g, backend="numpy")
    )


def test_baked_seed_scores_match_metadata(problem, tmp_path):
    """ShardSeedScores.matches: baked scores are only trusted when the
    bake's estimator (cap + stream seed) agrees with the run's seeding
    config — a capped bake must not silently stand in for an exact (or
    differently-seeded) fit-time ranking."""
    _, store, text, _ = problem
    exact = store.load_seed_scores()
    assert exact.matches(None, 0) and exact.matches(None, 7)
    assert not exact.matches(8, 0)
    capped = compile_graph_cache(
        text, str(tmp_path / "meta.cache"), num_shards=2, seed_cap=8,
        seed=3,
    ).load_seed_scores()
    assert capped.matches(8, 3)
    assert not capped.matches(8, 0)        # different sample stream
    assert not capped.matches(None, 3)     # exact wanted, capped baked


def test_baked_seed_scores_balanced_cache(problem, tmp_path):
    """Balanced caches bake phi in FINAL (relabeled) node order — the
    order the trainer rows and load_graph use."""
    _, _, text, _ = problem
    st = compile_graph_cache(
        text, str(tmp_path / "bal.cache"), num_shards=4, balance=True
    )
    gb = st.load_graph()
    np.testing.assert_array_equal(
        st.load_seed_scores().phi, seeding.conductance(gb, backend="numpy")
    )


def test_exact_bake_work_guard_skips_with_hint(problem, tmp_path, capsys,
                                               monkeypatch):
    """An uncapped ingest whose exact triangle pass would exceed the work
    bound SKIPS the bake (with a --seed-cap hint) instead of walling —
    the cache still compiles, scores just refuse with the re-ingest
    message. A capped ingest on the same graph is unaffected."""
    from bigclam_tpu.graph import store as store_mod

    _, _, text, _ = problem
    monkeypatch.setattr(store_mod, "SEED_BAKE_EXACT_MAX_WORK", 1.0)
    st = compile_graph_cache(
        text, str(tmp_path / "guard.cache"), num_shards=2
    )
    assert "re-run ingest with --seed-cap" in capsys.readouterr().err
    assert st.manifest["seed_scores"] == {
        "baked": False, "skipped": "exact_work",
    }
    with pytest.raises(ValueError, match="re-ingest to bake seeds"):
        st.load_seed_scores()
    capped = compile_graph_cache(
        text, str(tmp_path / "guard_cap.cache"), num_shards=2, seed_cap=8
    )
    assert capped.manifest["seed_scores"]["baked"] is True


def test_unbaked_cache_clear_error_and_manifest_migration(problem, tmp_path):
    g, _, text, _ = problem
    st = compile_graph_cache(
        text, str(tmp_path / "nb.cache"), num_shards=2, seed_bake=False
    )
    with pytest.raises(ValueError, match="re-ingest to bake seeds"):
        st.load_seed_scores()

    # format v1 (pre-seed-scores): the GRAPH still loads (graceful
    # migration), only the seed-score accessor refuses
    v2 = str(tmp_path / "v1.cache")
    st2 = compile_graph_cache(text, v2, num_shards=2)
    mpath = os.path.join(v2, MANIFEST_NAME)
    with open(mpath) as f:
        manifest = json.load(f)
    manifest["format_version"] = 1
    for e in manifest["shards"]:
        e.pop("phi", None)
        e["crc32"].pop("phi", None)
    manifest.pop("seed_scores", None)
    with open(mpath, "w") as f:
        json.dump(manifest, f)
    old = GraphStore.open(v2)
    np.testing.assert_array_equal(old.load_graph().indices, g.indices)
    with pytest.raises(ValueError, match="re-ingest to bake seeds"):
        old.load_seed_scores()

    # unknown future versions still reject at open (v3 = the closure
    # bake is a real, supported version now — 4 is the next unknown)
    manifest["format_version"] = 4
    with open(mpath, "w") as f:
        json.dump(manifest, f)
    with pytest.raises(ValueError, match="format version"):
        GraphStore.open(v2)


def test_quarantine_rebuild_keeps_phi_crc(problem, tmp_path):
    """A shard rebuild re-stamps only the indptr/indices crcs — the phi
    blob's stamp survives and the scores still verify."""
    _, _, text, _ = problem
    st = compile_graph_cache(
        text, str(tmp_path / "heal.cache"), num_shards=2, chunk_bytes=256
    )
    _, indices_path = st.shard_files(1)
    with open(indices_path, "r+b") as f:
        f.seek(os.path.getsize(indices_path) - 3)
        b = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([b[0] ^ 0xFF]))
    healer = GraphStore.open(st.directory, self_heal=True)
    healer.load_graph()                       # quarantine + rebuild
    fresh = GraphStore.open(st.directory)
    assert "phi" in fresh.manifest["shards"][1]["crc32"]
    fresh.load_seed_scores()                  # crc still verifies


def test_load_host_seed_scores_single_process(problem):
    from bigclam_tpu.parallel.multihost import load_host_seed_scores

    _, store, _, _ = problem
    ss = load_host_seed_scores(store)
    assert (ss.lo, ss.hi) == (0, store.num_nodes)
    assert len(ss.files_read) == store.num_shards


def test_global_max_int_single_process():
    from bigclam_tpu.parallel.multihost import global_max_int

    assert global_max_int(7) == 7


# --------------------------------------------------------------------------
# CLI: ingest stage telemetry + report rendering
# --------------------------------------------------------------------------


def _run_cli(*argv):
    return subprocess.run(
        [sys.executable, "-m", "bigclam_tpu.cli", *argv],
        capture_output=True, text=True, timeout=600, cwd="/root/repo",
    )


def test_cli_ingest_emits_seed_bake_stage_and_report(problem, tmp_path):
    _, _, text, _ = problem
    cache = str(tmp_path / "cli.cache")
    tdir = str(tmp_path / "telemetry")
    r = _run_cli(
        "ingest", "--graph", text, "--cache-dir", cache, "--shards", "2",
        "--telemetry-dir", tdir,
    )
    assert r.returncode == 0, r.stderr
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["seed_baked"] is True
    assert "seed_bake" in rec["seconds"]
    # the stage event landed in the telemetry (jax-free entry) and the
    # report renders its time
    events = [
        json.loads(ln)
        for ln in open(os.path.join(tdir, "events.jsonl"))
    ]
    assert any(
        e["kind"] == "stage" and e["name"] == "seed_bake" for e in events
    )
    rep = _run_cli("report", tdir)
    assert rep.returncode == 0, rep.stdout + rep.stderr
    assert "seed_bake" in rep.stdout


def test_cli_fit_baked_backend_requires_cache(problem, tmp_path):
    _, _, text, _ = problem
    r = _run_cli(
        "fit", "--graph", text, "--k", "2", "--max-iters", "2",
        "--platform", "cpu", "--seed-backend", "baked", "--quiet",
    )
    assert r.returncode != 0
    assert "baked" in r.stderr
