"""The membership server (ISSUE 14 tentpole): three query families at
batch QPS over an immutable, hot-swappable snapshot.

    server = MembershipServer("snaps/", store=GraphStore.open("g.cache"))
    server.query({"family": "communities_of", "u": 12})
    server.query({"family": "members_of", "c": 3})
    server.query({"family": "suggest_for", "u": 12})
    server.hot_swap()            # after a new publish(); drops no queries

Families:
  * communities_of u — threshold read of F[u] (ops.extraction semantics,
    answered straight off the ServingSnapshot);
  * members_of c     — the load-time inverted index, fronted by the
    Zipf-aware HotCommunityCache;
  * suggest_for u    — FOLD-IN: optimize u's row against the frozen F
    (ops.foldin — the trainer's own per-node update as the serving hot
    loop, batched + donated). `u` may be a graph node (neighbors come
    from the store/graph adjacency) or absent with an explicit
    "neighbors" list (a brand-new node — the live-graph roadmap item).

All families flow through ONE RequestBatcher (serve.batcher): a batch
flushes at max_batch or when the latency budget closes. The handler holds
the swap lock for the whole batch, so `hot_swap` = load the new snapshot
off to the side, take the lock (this drains the in-flight batch), swap
the pointer, reset the caches — queued and future queries see the new
generation, and nothing is ever dropped (the serve gate proves a
mid-load swap answers every query).

Observability rides the existing obs stack: each batch emits a `serve`
event (family counts, batch size, exec seconds) under a serve/batch
span; swaps emit `snapshot_swap`; stats() produces the p50/p99/QPS/
cache-hit figures `cli serve` stamps into the telemetry final so the
perf ledger records — and `cli perf diff` verdicts — serve p99 like any
other regression axis.

jax-free at import: the FoldInEngine imports jax lazily on the first
suggest query, so a membership-only server (and `cli serve` answering
only read families) never pays the jax import (tests/test_cli_jaxfree).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from bigclam_tpu.obs import telemetry as _obs
from bigclam_tpu.obs import trace as _trace
from bigclam_tpu.obs.ledger import _percentile
from bigclam_tpu.serve.batcher import (
    Future,
    OverloadedError,
    Request,
    RequestBatcher,
)
from bigclam_tpu.serve.snapshot import (
    FOLDIN_CFG_FIELDS,
    ServingSnapshot,
    SnapshotError,
    pad_neighbor_batch,
)
from bigclam_tpu.utils.checkpoint import CheckpointManager

FAMILIES = ("communities_of", "members_of", "suggest_for")


def _pow2(x: int, lo: int = 1) -> int:
    return max(1 << max(int(x) - 1, 0).bit_length(), lo)


class HotCommunityCache:
    """Members-of-c cache, Zipf-aware (ISSUE 14).

    Under Zipf traffic a community's query popularity tracks its size,
    and size IS the mass share sumF_c / sum(sumF) — the per-community
    resolution of the health pack's top_mass_share signal
    (ops.diagnostics). So instead of LRU (which thrashes on the long
    tail), the cache is KEYED by mass share: at reset it pre-warms the
    top-share communities, and a miss is only admitted by evicting a
    resident with a LOWER share. The resident set converges to the hot
    head of the Zipf curve and stays there."""

    def __init__(self, slots: int):
        self.slots = max(int(slots), 0)
        self.share: Optional[np.ndarray] = None
        self.data: Dict[int, np.ndarray] = {}
        self.hits = 0
        self.misses = 0

    def reset(self, snapshot: ServingSnapshot) -> None:
        """Rebind to a snapshot generation: drop everything (the member
        lists changed), pre-warm the top-mass communities."""
        self.share = snapshot.mass_share
        self.data = {}
        self.hits = 0
        self.misses = 0
        for c in snapshot.top_mass_communities(self.slots):
            self.data[int(c)] = snapshot.members_of(int(c))

    def get(self, c: int) -> Optional[np.ndarray]:
        got = self.data.get(c)
        if got is None:
            self.misses += 1
        else:
            self.hits += 1
        return got

    def put(self, c: int, members: np.ndarray) -> None:
        if self.slots <= 0 or self.share is None:
            return
        if len(self.data) < self.slots:
            self.data[c] = members
            return
        coldest = min(self.data, key=lambda r: self.share[r])
        if self.share[c] > self.share[coldest]:
            del self.data[coldest]
            self.data[c] = members

    @property
    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0


class FoldInEngine:
    """The suggest family's device side (lazy jax): frozen snapshot
    arrays pushed to the device once per generation, one jitted batched
    fold-in (ops.foldin.make_foldin_fit — per-node Armijo ascent with
    per-node convergence inside a single while_loop, rows donated).
    Batch and neighbor axes pad to powers of two so jit's shape cache
    serves every request mix with a handful of compilations."""

    def __init__(
        self,
        snapshot: ServingSnapshot,
        max_iters: int = 200,
        conv_tol: Optional[float] = None,
        pad_b_to: int = 8,
    ):
        import jax.numpy as jnp

        from bigclam_tpu.config import BigClamConfig
        from bigclam_tpu.ops import foldin as fi

        self._jnp = jnp
        self._fi = fi
        self.snapshot = snapshot
        meta = snapshot.meta
        cfg = BigClamConfig(
            num_communities=snapshot.k,
            **{f: meta[f] for f in FOLDIN_CFG_FIELDS if f in meta},
        )
        self.cfg = cfg
        self.pad_b_to = max(int(pad_b_to), 1)
        if snapshot.representation == "dense":
            self._F = jnp.asarray(snapshot.F)
            self._ids = self._w = None
        else:
            self._ids = jnp.asarray(snapshot.ids)
            self._w = jnp.asarray(snapshot.w)
            self._F = None
        self._sumF = jnp.asarray(snapshot.sumF)
        self._fit = fi.make_foldin_fit(
            cfg,
            max_iters=max_iters,
            conv_tol=(
                conv_tol if conv_tol is not None else cfg.conv_tol
            ),
        )

    def suggest_batch(
        self,
        items: Sequence[Tuple[np.ndarray, Optional[int]]],
        top_n: int = 20,
    ) -> List[dict]:
        """items: (internal neighbor ids, own internal row or None for a
        brand-new node). Returns per item the folded row's communities
        above delta (argmax fallback — extraction semantics), ranked by
        weight, plus the fold-in LLH and iteration count."""
        jnp, fi = self._jnp, self._fi
        snap = self.snapshot
        b = len(items)
        bp = _pow2(b, self.pad_b_to)
        d = _pow2(max((len(nbr) for nbr, _ in items), default=1))
        nbr_ids = np.zeros((bp, d), np.int32)
        mask = np.zeros((bp, d), np.float32)
        own = np.full(bp, -1, np.int64)
        for i, (nbr, row) in enumerate(items):
            nbr_ids[i, : len(nbr)] = nbr
            mask[i, : len(nbr)] = 1.0
            if row is not None:
                own[i] = row
        dt = snap.sumF.dtype
        nbr_dev = jnp.asarray(nbr_ids)
        mask_dev = jnp.asarray(mask, dt)
        if self._F is not None:
            nbr_rows = fi.gather_neighbor_rows(self._F, nbr_dev)
            own_rows = jnp.where(
                (own >= 0)[:, None],
                self._F[jnp.asarray(np.maximum(own, 0))],
                jnp.zeros((bp, snap.k), dt),
            )
        else:
            nbr_rows = fi.densify_member_rows(
                self._ids, self._w, nbr_dev, snap.k
            )
            own_rows = jnp.where(
                (own >= 0)[:, None],
                fi.densify_rows(
                    self._ids, self._w,
                    jnp.asarray(np.maximum(own, 0)), snap.k,
                ),
                jnp.zeros((bp, snap.k), dt),
            )
        sumF_others = self._sumF[None, :] - own_rows
        # warm-start policy (see models.bigclam.foldin_rows): an
        # existing node refines its OWN trained row (fixed point =
        # training parity, fewest iterations); a brand-new node starts
        # from its neighbor mean (the only information it has) — and so
        # does an existing node whose trained row froze at ZERO (an
        # all-zero row is a fixed point the ascent can never leave, and
        # those are precisely the nodes suggest exists for)
        has_own = (own >= 0) & np.asarray(
            jnp.max(own_rows, axis=1) > 0
        )
        rows0 = jnp.where(
            jnp.asarray(has_own)[:, None],
            own_rows,
            fi.neighbor_mean_rows(nbr_rows, mask_dev),
        )
        rows, llh, iters = self._fit(
            rows0, nbr_rows, mask_dev, sumF_others
        )
        return self._postprocess(rows, llh, iters, b, top_n)

    def suggest_batch_rows(
        self,
        items: Sequence[Tuple[np.ndarray, Optional[np.ndarray]]],
        top_n: int = 20,
    ) -> List[dict]:
        """items: (neighbor ROWS as a (d_i, K) array, own (K,) row or
        None for a brand-new node). The fleet's two-phase suggest path
        (serve.fleet): the owner shard only holds its own row range, so
        non-local neighbor rows arrive pre-gathered from sibling shards
        by the router, and the fold-in runs against the GLOBAL sumF
        (the sumF_global array every shard archive carries) — identical
        math to the id-addressed suggest_batch, different addressing."""
        jnp, fi = self._jnp, self._fi
        snap = self.snapshot
        b = len(items)
        bp = _pow2(b, self.pad_b_to)
        d = _pow2(max((len(nr) for nr, _ in items), default=1))
        dt = snap.sumF.dtype
        nbr_rows = np.zeros((bp, d, snap.k), dt)
        mask = np.zeros((bp, d), np.float32)
        own_rows = np.zeros((bp, snap.k), dt)
        has_own = np.zeros(bp, bool)
        for i, (nr, own) in enumerate(items):
            nr = np.asarray(nr, dt).reshape(-1, snap.k)
            nbr_rows[i, : len(nr)] = nr
            mask[i, : len(nr)] = 1.0
            if own is not None:
                own_rows[i] = np.asarray(own, dt)
                # same warm-start policy as suggest_batch: a frozen
                # all-zero trained row restarts from the neighbor mean
                has_own[i] = bool(own_rows[i].max() > 0)
        nbr_dev = jnp.asarray(nbr_rows)
        mask_dev = jnp.asarray(mask, dt)
        own_dev = jnp.asarray(own_rows)
        sumF_others = self._sumF[None, :] - own_dev
        rows0 = jnp.where(
            jnp.asarray(has_own)[:, None],
            own_dev,
            fi.neighbor_mean_rows(nbr_dev, mask_dev),
        )
        rows, llh, iters = self._fit(
            rows0, nbr_dev, mask_dev, sumF_others
        )
        return self._postprocess(rows, llh, iters, b, top_n)

    def _postprocess(
        self, rows, llh, iters, b: int, top_n: int
    ) -> List[dict]:
        snap = self.snapshot
        rows = np.asarray(rows)
        llh = np.asarray(llh)
        iters = np.asarray(iters)
        out = []
        for i in range(b):
            r = rows[i]
            cids = np.nonzero(r >= snap.delta)[0]
            if cids.size == 0 and r.size:
                cids = np.asarray([int(np.argmax(r))])
            order = np.argsort(-r[cids], kind="stable")[:top_n]
            cids = cids[order]
            out.append(
                {
                    "suggested": [
                        [int(c), float(r[c])] for c in cids
                    ],
                    "llh": float(llh[i]),
                    "iters": int(iters[i]),
                }
            )
        return out


class MembershipServer:
    """See module docstring. Thread-safe; close() releases the batcher
    and watcher threads."""

    def __init__(
        self,
        snapshot_dir: str,
        store=None,
        graph=None,
        max_batch: int = 64,
        budget_s: float = 0.005,
        cache_slots: int = 64,
        foldin_max_iters: int = 200,
        foldin_conv_tol: Optional[float] = None,
        foldin_max_deg: int = 4096,
        watch_interval_s: float = 0.0,
        max_queue_depth: int = 0,
        shed_wait_s: float = 0.0,
    ):
        self.snapshot_dir = snapshot_dir
        self._store = store
        self._graph = graph
        self._adj: Optional[Tuple[np.ndarray, np.ndarray]] = None
        self._foldin_max_iters = foldin_max_iters
        self._foldin_conv_tol = foldin_conv_tol
        self._foldin_max_deg = foldin_max_deg
        self._lock = threading.RLock()
        self._snapshot = ServingSnapshot.load(snapshot_dir, store=store)
        self._engine: Optional[FoldInEngine] = None
        self._cache = HotCommunityCache(cache_slots)
        self._cache.reset(self._snapshot)
        self._latencies: Dict[str, List[float]] = {
            f: [] for f in FAMILIES
        }
        self._errors = 0
        self._swaps = 0
        self._truncated_neighbors = 0
        self._t_first: Optional[float] = None
        self._t_last: Optional[float] = None
        self._batcher = RequestBatcher(
            self._handle_batch,
            max_batch=max_batch,
            budget_s=budget_s,
            max_depth=max_queue_depth,
            shed_wait_s=shed_wait_s,
        ).start()
        self._watch_stop = threading.Event()
        self._watcher: Optional[threading.Thread] = None
        if watch_interval_s > 0:
            self._watcher = threading.Thread(
                target=self._watch_loop,
                args=(watch_interval_s,),
                name="bigclam-serve-watch",
                daemon=True,
            )
            self._watcher.start()

    # ------------------------------------------------------- lifecycle
    def close(self) -> None:
        self._watch_stop.set()
        if self._watcher is not None:
            self._watcher.join(timeout=10.0)
            self._watcher = None
        self._batcher.stop()

    def __enter__(self) -> "MembershipServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ----------------------------------------------------- hot swap
    @property
    def generation(self) -> int:
        return self._snapshot.step

    def _refresh_store(self) -> None:
        """Re-open the graph cache's manifest before a swap (ISSUE 15):
        the continuous delta pipeline mutates the cache UNDER a running
        server (edge counts, delta_seq), and verifying a post-delta
        snapshot against the stale in-memory manifest would refuse
        every new generation. One JSON parse when a store is attached;
        the suggest adjacency cache is dropped only when the graph
        actually changed (delta_seq moved)."""
        if self._store is None:
            return
        from bigclam_tpu.graph.store import GraphStore

        try:
            fresh = GraphStore.open(
                self._store.directory, self_heal=self._store.self_heal
            )
        except ValueError:
            return          # torn manifest mid-delta: retry next poll
        # store swap + adjacency invalidation under ONE lock hold: a
        # suggest batch racing between them could rebuild _adj from the
        # OLD store and cache the stale adjacency forever
        with self._lock:
            changed = fresh.manifest.get(
                "delta_seq", 0
            ) != self._store.manifest.get("delta_seq", 0)
            self._store = fresh
            if changed:
                self._adj = None            # adjacency changed

    def hot_swap(self, step: Optional[int] = None) -> int:
        """Swap to the latest (or a named) published snapshot. The load
        + index build happens OUTSIDE the lock; taking the lock then
        drains the in-flight batch, so queries keep queueing throughout
        and none is dropped. Returns the new generation's step."""
        self._refresh_store()
        new = ServingSnapshot.load(
            self.snapshot_dir, step=step, store=self._store
        )
        return self._install(new)

    def _install(self, new: ServingSnapshot) -> int:
        with self._lock:
            previous = self._snapshot.step
            self._snapshot = new
            self._engine = None          # rebuilt lazily per generation
            self._cache.reset(new)
            self._swaps += 1
        tel = _obs.current()
        if tel is not None:
            tel.event(
                "snapshot_swap", step=int(new.step),
                previous=int(previous),
            )
        return new.step

    def maybe_reload(self) -> Optional[int]:
        """Hot-swap iff a NEWER snapshot is published (the watcher's
        poll; the cheap no-change case is one latest.json read). The
        load goes through the FALLBACK path (step=None), so a corrupt
        newest publication resolves to the best loadable snapshot —
        which may be the one already serving (then: no swap). The
        generation NEVER moves backward (ISSUE 15 satellite): a stale
        latest.json racing a newer snap_ archive — or a pointer rolled
        back by a crashed publisher — resolves to an older step, and an
        older step is never installed over the one already serving."""
        latest = CheckpointManager(self.snapshot_dir).latest()
        if latest is None or latest <= self._snapshot.step:
            return None
        self._refresh_store()
        new = ServingSnapshot.load(self.snapshot_dir, store=self._store)
        if new.step <= self._snapshot.step:
            return None     # newest publication unreadable/stale: keep
        return self._install(new)

    def _watch_loop(self, interval: float) -> None:
        while not self._watch_stop.wait(interval):
            try:
                self.maybe_reload()
            except Exception:   # noqa: BLE001 — the watcher must outlive
                # any transient publication state (torn pointer, corrupt
                # archive, store mismatch mid-publish): keep serving the
                # current snapshot and poll again next interval
                pass

    # ------------------------------------------------------- queries
    def submit(self, query: Dict[str, Any]) -> Future:
        return self._batcher.submit(query)

    def query(
        self, query: Dict[str, Any], timeout: float = 60.0
    ) -> Dict[str, Any]:
        return self.submit(query).result(timeout)

    def run_queries(
        self,
        queries: Sequence[Dict[str, Any]],
        timeout: float = 600.0,
        collect: bool = True,
    ) -> List[Optional[Dict[str, Any]]]:
        """Open-loop driver (the `cli serve --queries` path): submit
        everything, wait for everything. Per-query failures come back as
        {"error": ...} results, never exceptions."""
        futures = [self.submit(q) for q in queries]
        out: List[Optional[Dict[str, Any]]] = []
        for fut in futures:
            try:
                res = fut.result(timeout)
            except OverloadedError:
                # admission-control shed: a deliberate fast answer, NOT
                # a serve error (the batcher already counted it)
                res = {"error": "overloaded"}
            except Exception as e:   # noqa: BLE001 — batch infra failure
                self._errors += 1
                res = {"error": f"{type(e).__name__}: {e}"}
            out.append(res if collect else None)
        return out

    # ------------------------------------------------------- handler
    def _adjacency(self) -> Tuple[np.ndarray, np.ndarray]:
        if self._adj is None:
            if self._graph is not None:
                self._adj = (self._graph.indptr, self._graph.indices)
            elif self._store is not None:
                # re-open the manifest first: the delta pipeline may
                # have rewritten shard blobs since this handle was
                # opened, and reading them against a stale manifest
                # would raise (or worse, self-heal-revert a writer's
                # work — which is why serve opens stores read-only)
                self._refresh_store()
                g = self._store.load_graph()
                self._adj = (g.indptr, g.indices)
            else:
                raise SnapshotError(
                    "suggest_for a graph node needs adjacency — pass a "
                    "graph/store to the server, or send an explicit "
                    "'neighbors' list"
                )
        return self._adj

    def _answer_read(
        self, snap: ServingSnapshot, q: Dict[str, Any]
    ) -> Dict[str, Any]:
        fam = q["family"]
        if fam == "communities_of":
            row = snap.row_of(int(q["u"]))
            cids, weights = snap.communities_of(row)
            return {
                "u": int(q["u"]),
                "communities": [
                    [int(c), float(v)] for c, v in zip(cids, weights)
                ],
            }
        c = int(q["c"])
        members = self._cache.get(c)
        cached = members is not None
        if members is None:
            members = snap.members_of(c)
            self._cache.put(c, members)
        return {
            "c": c,
            "members": [int(u) for u in members],
            "cached": cached,
        }

    def _handle_batch(self, batch: List[Request]) -> None:
        t0 = time.perf_counter()
        families: Dict[str, int] = {}
        suggests: List[Request] = []
        with self._lock, _trace.span("serve/batch", emit=False):
            snap = self._snapshot
            for req in batch:
                q = req.payload if isinstance(req.payload, dict) else {}
                fam = q.get("family")
                # telemetry key: always a string (a malformed query with
                # family None/12 must not make sorted()/join() throw and
                # lose the whole batch's serve event)
                families[str(fam)] = families.get(str(fam), 0) + 1
                if fam == "suggest_for":
                    suggests.append(req)
                    continue
                try:
                    if fam not in FAMILIES:
                        raise KeyError(f"unknown family {fam!r}")
                    req.future.set_result(self._answer_read(snap, q))
                except Exception as e:   # noqa: BLE001 — per-query
                    self._errors += 1
                    req.future.set_result(
                        {"error": f"{type(e).__name__}: {e}"}
                    )
            if suggests:
                self._handle_suggests(snap, suggests)
        self._record_latencies(batch)
        depth = self._batcher.depth()
        tel = _obs.current()
        if tel is not None:
            # queue depth rides the telemetry object so heartbeat stall
            # events can embed it next to the span stack (obs.heartbeat)
            tel.last_queue_depth = depth
            age = self._snapshot.age_s()
            tel.event(
                "serve",
                family="|".join(sorted(families)),
                batch=len(batch),
                seconds=round(time.perf_counter() - t0, 6),
                step=int(snap.step),
                queue_depth=depth,
                **(
                    {"gen_age_s": round(age, 3)} if age is not None
                    else {}
                ),
                **{f"n_{k}": v for k, v in families.items()},
            )

    def _handle_suggests(
        self, snap: ServingSnapshot, reqs: List[Request]
    ) -> None:
        items = []
        live: List[Request] = []
        for req in reqs:
            q = req.payload
            try:
                if "neighbors" in q:
                    nbr = np.asarray(
                        [snap.row_of(int(v)) for v in q["neighbors"]],
                        np.int64,
                    )
                    row = (
                        snap.row_of(int(q["u"])) if "u" in q else None
                    )
                else:
                    row = snap.row_of(int(q["u"]))
                    indptr, indices = self._adjacency()
                    lo, hi = int(indptr[row]), int(indptr[row + 1])
                    if hi - lo > self._foldin_max_deg:
                        self._truncated_neighbors += 1
                        hi = lo + self._foldin_max_deg
                    nbr = indices[lo:hi].astype(np.int64)
                items.append((nbr, row))
                live.append(req)
            except Exception as e:   # noqa: BLE001 — per-query
                self._errors += 1
                req.future.set_result(
                    {"error": f"{type(e).__name__}: {e}"}
                )
        if not live:
            return
        if self._engine is None:
            self._engine = FoldInEngine(
                snap,
                max_iters=self._foldin_max_iters,
                conv_tol=self._foldin_conv_tol,
            )
        try:
            results = self._engine.suggest_batch(items)
        except Exception as e:   # noqa: BLE001 — whole sub-batch
            for req in live:
                self._errors += 1
                req.future.set_result(
                    {"error": f"{type(e).__name__}: {e}"}
                )
            return
        for req, res in zip(live, results):
            q = req.payload
            if "u" in q:
                res = {"u": int(q["u"]), **res}
            req.future.set_result(res)

    def _record_latencies(self, batch: List[Request]) -> None:
        now = time.perf_counter()
        for req in batch:
            fam = (
                req.payload.get("family")
                if isinstance(req.payload, dict) else None
            )
            lat = req.future.latency_s
            if fam in self._latencies and lat is not None:
                self._latencies[fam].append(lat)
            t_sub = req.future.t_submit
            if self._t_first is None or t_sub < self._t_first:
                self._t_first = t_sub
        if self._t_last is None or now > self._t_last:
            self._t_last = now

    # --------------------------------------------------------- stats
    def reset_stats(self) -> None:
        """Zero the latency/error/cache counters (gates warm the engine
        compile caches first, then measure a clean window; the snapshot,
        caches' CONTENTS, and compiled fold-in stay warm)."""
        self._batcher.drain()
        self._latencies = {f: [] for f in FAMILIES}
        self._errors = 0
        self._truncated_neighbors = 0
        self._t_first = self._t_last = None
        self._cache.hits = self._cache.misses = 0
        self._batcher.batches = 0
        self._batcher.flushed_full = 0
        self._batcher.flushed_deadline = 0
        self._batcher.shed_depth = 0
        self._batcher.shed_deadline = 0
        self._batcher.depth_peak = 0

    def stats(self) -> Dict[str, Any]:
        """The serving scoreboard `cli serve` stamps into the telemetry
        final: obs.ledger records serve_p99_s/serve_qps/cache_hit_rate
        per run and `cli perf diff` verdicts them (a p99 regression
        fails CI like a step-time regression would)."""
        lats = [v for fam in FAMILIES for v in self._latencies[fam]]
        total = len(lats)
        wall = (
            max(self._t_last - self._t_first, 1e-9)
            if self._t_first is not None and self._t_last is not None
            else 0.0
        )
        mix = "|".join(
            f"{fam}:{len(self._latencies[fam]) / total:.2f}"
            for fam in FAMILIES
            if self._latencies[fam]
        )
        out = {
            "serve_queries": total,
            "serve_errors": self._errors,
            "serve_by_family": {
                fam: len(self._latencies[fam])
                for fam in FAMILIES
                if self._latencies[fam]
            },
            "serve_mix": mix,
            "serve_p50_s": _percentile(lats, 50),
            "serve_p99_s": _percentile(lats, 99),
            "serve_qps": (total / wall) if wall else None,
            "cache_hit_rate": round(self._cache.hit_rate, 4),
            "snapshot_step": int(self._snapshot.step),
            "snapshot_swaps": self._swaps,
            "batches": self._batcher.batches,
            "batches_full": self._batcher.flushed_full,
            "batches_deadline": self._batcher.flushed_deadline,
            "foldin_truncated": self._truncated_neighbors,
            "serve_shed": self._batcher.shed,
            "serve_shed_rate": round(
                self._batcher.shed / (total + self._batcher.shed), 4
            ) if (total + self._batcher.shed) else 0.0,
            "queue_depth_peak": self._batcher.depth_peak,
        }
        age = self._snapshot.age_s()
        if age is not None:
            out["generation_age_s"] = round(age, 3)
        for key in ("serve_p50_s", "serve_p99_s", "serve_qps"):
            if out[key] is not None:
                out[key] = round(out[key], 6)
        return out
