"""Serving snapshots: publication, crc-verified loading, membership index.

A serving snapshot is a published F artifact (utils.checkpoint.publish —
fsync-rename archive + per-array crc32 sidecar + atomic latest.json
pointer, the SAME publication primitive the fit side uses) holding either
the dense (N, K) F or the sparse (ids, w) member lists, the raw node ids,
and the objective constants the fold-in engine needs to reproduce the
trainer's semantics.

Loading builds the full query surface for two of the three families:

  * "communities of u" — a threshold read of F[u] with EXACTLY the
    ops.extraction membership semantics (delta = sqrt(-log(1-eps)) and
    the argmax-tie fallback, Bigclamv2.scala:226-229);
  * "members of c" — a community -> member CSR inverted at load (one
    argsort over the membership pairs; sparse-representation aware: the
    pairs come straight from the member lists, no dense N*K detour).

The third family (fold-in "suggested communities") runs in
serve.server.FoldInEngine — the only jax-touching path. This module is
deliberately jax-free: a membership-only server answers from numpy alone
(pinned by tests/test_cli_jaxfree.py).

The per-community MASS SHARE (sumF_c / sum(sumF) — the same signal as the
health pack's top_mass_share, ops.diagnostics) is computed at load and
keys the Zipf-aware hot-community cache (serve.server.HotCommunityCache).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from bigclam_tpu.ops.extraction import delta_threshold, membership_mask
from bigclam_tpu.utils.checkpoint import CheckpointManager


class SnapshotError(ValueError):
    """No loadable published snapshot, or one that does not match the
    serving graph."""


# objective constants stamped into the snapshot meta so the fold-in
# engine rebuilds the trainer's exact semantics (cfg fields of the same
# names — conv_tol included: `cli serve` defaults its fold-in stop rule
# to the TRAINER's tolerance, so it must ride the snapshot); everything
# else about BigClamConfig is a training knob
FOLDIN_CFG_FIELDS = (
    "alpha", "beta", "max_backtracks", "min_p", "max_p", "min_f", "max_f",
    "conv_tol",
)


def publish_snapshot(
    directory: str,
    step: Optional[int] = None,
    F: Optional[np.ndarray] = None,
    ids: Optional[np.ndarray] = None,
    w: Optional[np.ndarray] = None,
    raw_ids: Optional[np.ndarray] = None,
    num_edges: int = 0,
    cfg=None,
    meta: Optional[dict] = None,
) -> str:
    """Publish a serving snapshot (dense: F; sparse: ids + w) through the
    checkpoint manager's atomic publish(). `cfg` (a BigClamConfig) stamps
    the objective constants; `num_edges` feeds the delta threshold.
    step=None takes the NEXT generation under the publish lock
    (CheckpointManager.publish_next — the continuous refit loop's
    strictly-monotonic publication path, ISSUE 15)."""
    if (F is None) == (ids is None or w is None):
        raise ValueError("publish_snapshot needs F (dense) XOR ids+w (sparse)")
    arrays: Dict[str, np.ndarray] = {}
    if F is not None:
        F = np.asarray(F)
        n, k = F.shape
        arrays["F"] = F
        rep = "dense"
    else:
        ids = np.asarray(ids)
        w = np.asarray(w)
        n = ids.shape[0]
        if meta and "k" in meta:
            k = int(meta["k"])
        elif cfg is not None:
            k = int(cfg.num_communities)
        else:
            raise ValueError(
                "sparse publish_snapshot needs k (via cfg or meta) — the "
                "member-id sentinel makes it unrecoverable from ids alone"
            )
        arrays["ids"] = ids
        arrays["w"] = w
        rep = "sparse"
    arrays["raw_ids"] = (
        np.asarray(raw_ids) if raw_ids is not None
        else np.arange(n, dtype=np.int64)
    )
    m = {
        "representation": rep,
        "n": int(n),
        "k": int(k),
        "num_edges": int(num_edges),
        "delta": delta_threshold(n, num_edges),
        # wall-clock publication instant: serving surfaces "generation
        # age" from this (ISSUE 18 satellite — how stale is serving)
        "published_ts": time.time(),
        **(meta or {}),
    }
    if cfg is not None:
        for f in FOLDIN_CFG_FIELDS:
            m[f] = getattr(cfg, f)
        m.setdefault("k", cfg.num_communities)
    cm = CheckpointManager(directory)
    if step is None:
        return cm.publish_next(arrays, meta=m)[1]
    return cm.publish(step, arrays, meta=m)


def publish_fleet_snapshot(
    directory: str,
    shard_ranges: Sequence[Tuple[int, int]],
    F: Optional[np.ndarray] = None,
    ids: Optional[np.ndarray] = None,
    w: Optional[np.ndarray] = None,
    raw_ids: Optional[np.ndarray] = None,
    num_edges: int = 0,
    cfg=None,
    meta: Optional[dict] = None,
) -> Tuple[int, str]:
    """Publish ONE serving generation as per-shard row-range archives +
    a fleet manifest (ISSUE 18 tentpole): shard s gets rows
    [lo_s, hi_s) of F (dense) or of the member lists (sparse — M-sized
    slots, never a densified N*K block), its raw-id slice, and the
    GLOBAL sumF vector (K floats — the fold-in tail term is global even
    when the rows are sharded). Runs under the same publish-lock
    monotonicity as publish_snapshot (CheckpointManager.publish_fleet_
    next — one primitive, fleet-wide). Returns (step, manifest_path).

    On a pod each host calls this with only ITS row range materialized;
    this single-host entry takes the full arrays and slices — the CLI's
    `fit --publish-shards` path for store-backed fits."""
    if (F is None) == (ids is None or w is None):
        raise ValueError(
            "publish_fleet_snapshot needs F (dense) XOR ids+w (sparse)"
        )
    if not shard_ranges:
        raise ValueError("publish_fleet_snapshot needs >= 1 shard range")
    if F is not None:
        F = np.asarray(F)
        n, k = F.shape
        rep = "dense"
        sumF = F.sum(axis=0)
    else:
        ids = np.asarray(ids)
        w = np.asarray(w)
        n = ids.shape[0]
        if meta and "k" in meta:
            k = int(meta["k"])
        elif cfg is not None:
            k = int(cfg.num_communities)
        else:
            raise ValueError(
                "sparse publish_fleet_snapshot needs k (via cfg or meta)"
            )
        rep = "sparse"
        sumF = np.zeros(k, w.dtype)
        valid = ids < k
        np.add.at(sumF, ids[valid].astype(np.int64), w[valid])
    raw = (
        np.asarray(raw_ids) if raw_ids is not None
        else np.arange(n, dtype=np.int64)
    )
    if int(shard_ranges[0][0]) != 0 or int(shard_ranges[-1][1]) != n:
        raise ValueError(
            f"shard ranges {shard_ranges[0]}..{shard_ranges[-1]} do not "
            f"cover [0, {n})"
        )
    common = {
        "representation": rep,
        "n_global": int(n),
        "num_shards": len(shard_ranges),
        "k": int(k),
        "num_edges": int(num_edges),
        # delta from the GLOBAL n/E: membership semantics must not
        # depend on which shard answers
        "delta": delta_threshold(n, num_edges),
        "published_ts": time.time(),
        **(meta or {}),
    }
    if cfg is not None:
        for f in FOLDIN_CFG_FIELDS:
            common[f] = getattr(cfg, f)
    shard_arrays: List[Dict[str, np.ndarray]] = []
    shard_meta: List[dict] = []
    for s, (lo, hi) in enumerate(shard_ranges):
        lo, hi = int(lo), int(hi)
        raw_s = raw[lo:hi]
        arrays: Dict[str, np.ndarray] = {
            "raw_ids": raw_s,
            "sumF_global": np.asarray(sumF),
        }
        if rep == "dense":
            arrays["F"] = F[lo:hi]
        else:
            arrays["ids"] = ids[lo:hi]
            arrays["w"] = w[lo:hi]
        shard_arrays.append(arrays)
        shard_meta.append(
            {
                **common,
                "shard": s,
                "n": hi - lo,
                "lo": lo,
                "hi": hi,
                # raw-id interval for the router's range map: disjoint
                # intervals (unpermuted cache) route a raw id with one
                # bisect; overlapping ones (balanced/permuted cache)
                # make the router probe every containing shard
                "raw_lo": int(raw_s.min()) if raw_s.size else 0,
                "raw_hi": int(raw_s.max()) if raw_s.size else -1,
            }
        )
    manifest_meta = dict(common)
    return CheckpointManager(directory).publish_fleet_next(
        shard_arrays, shard_meta, meta=manifest_meta
    )


def load_fleet_shard(
    directory: str,
    shard: int,
    step: Optional[int] = None,
    manifest: Optional[dict] = None,
) -> "ServingSnapshot":
    """Load + index ONE shard of a published fleet generation. The
    snapshot's n/rows are the SHARD's; delta/sumF/k are global (stamped
    at publish), so every query family answers with fleet-wide
    semantics over local rows only."""
    cm = CheckpointManager(directory)
    if manifest is None:
        manifest = cm.load_fleet_manifest(step)
    if manifest is None:
        raise SnapshotError(
            f"{directory}: no published fleet generation (fit with "
            "--publish-dir --publish-shards, or publish_fleet_snapshot())"
        )
    got = cm.load_fleet_shard(manifest, shard)
    return ServingSnapshot.from_arrays(*got)


def pad_neighbor_batch(
    indptr: np.ndarray,
    indices: np.ndarray,
    nodes: Sequence[int],
    max_deg: Optional[int] = None,
    pad_deg_to: Optional[int] = None,
) -> Tuple[np.ndarray, np.ndarray, int]:
    """Padded (B, D) neighbor batch for fold-in from a CSR adjacency.

    D = max degree in the batch, clipped to `max_deg` (hub queries keep
    their FIRST max_deg neighbors — CSR order, deterministic; the
    truncated count is returned so callers can report the approximation)
    and rounded up to `pad_deg_to` when given (compile-cache reuse).
    Padding slots: id 0, mask 0 (ops.foldin padding conventions)."""
    nodes = np.asarray(nodes, np.int64)
    degs = (indptr[nodes + 1] - indptr[nodes]).astype(np.int64)
    capped = degs if max_deg is None else np.minimum(degs, max_deg)
    truncated = int((degs - capped).sum())
    d = max(int(capped.max(initial=0)), 1)
    if pad_deg_to:
        d = ((d + pad_deg_to - 1) // pad_deg_to) * pad_deg_to
    b = len(nodes)
    nbr = np.zeros((b, d), np.int32)
    mask = np.zeros((b, d), np.float32)
    for i, (u, du) in enumerate(zip(nodes, capped)):
        lo = int(indptr[u])
        nbr[i, :du] = indices[lo : lo + int(du)]
        mask[i, :du] = 1.0
    return nbr, mask, truncated


def _sparse_membership_pairs(
    ids: np.ndarray, w: np.ndarray, k: int, delta: float
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(nodes, comms, weights) membership pairs from member lists,
    without a dense N*K detour: above-threshold slots plus the row-max
    fallback among the node's OWN member slots (a node whose every slot
    is empty has no membership — the dense path's all-zero-row
    "member of everything" corner has no sparse representation, a
    documented deviation)."""
    valid = ids < k
    above = valid & (w >= delta)
    row_max = np.where(valid, w, -np.inf).max(axis=1)
    has_valid = valid.any(axis=1)
    fallback = (
        valid
        & (row_max[:, None] < delta)
        & (w == row_max[:, None])
        & has_valid[:, None]
    )
    sel = above | fallback
    ni, si = np.nonzero(sel)
    return ni, ids[ni, si].astype(np.int64), w[ni, si]


@dataclasses.dataclass
class ServingSnapshot:
    """A loaded, indexed snapshot: everything the read-side query
    families need, immutable — hot-swap replaces the whole object."""

    step: int
    representation: str
    n: int
    k: int
    num_edges: int
    delta: float
    F: Optional[np.ndarray]
    ids: Optional[np.ndarray]
    w: Optional[np.ndarray]
    sumF: np.ndarray
    raw_ids: np.ndarray
    meta: dict
    comm_indptr: np.ndarray      # (K+1,) member-index row pointers
    comm_members: np.ndarray     # member RAW ids, per-community sorted
    mass_share: np.ndarray       # (K,) sumF_c / sum(sumF)
    _raw_order: np.ndarray = dataclasses.field(repr=False, default=None)
    # raw_ids[_raw_order], materialized ONCE at load: row_of is on the
    # hot read path and must stay O(log N), not re-gather O(N) per query
    _raw_sorted: np.ndarray = dataclasses.field(repr=False, default=None)

    # ------------------------------------------------------------- load
    @classmethod
    def load(
        cls,
        directory: str,
        step: Optional[int] = None,
        store=None,
        chunk_rows: int = 1 << 16,
    ) -> "ServingSnapshot":
        """Load + index the published snapshot (latest when step=None,
        falling back past corrupt ones — utils.checkpoint). With a
        GraphStore, the snapshot is verified against the manifest (node
        count + edge count must agree: a snapshot from another graph
        must refuse, not silently serve wrong members)."""
        got = CheckpointManager(directory).load_published(step)
        if got is None:
            raise SnapshotError(
                f"{directory}: no published snapshot (fit with "
                "--publish-dir, or publish_snapshot())"
            )
        step, arrays, meta = got
        return cls.from_arrays(
            step, arrays, meta, store=store, chunk_rows=chunk_rows
        )

    @classmethod
    def from_arrays(
        cls,
        step: int,
        arrays: Dict[str, np.ndarray],
        meta: dict,
        store=None,
        chunk_rows: int = 1 << 16,
    ) -> "ServingSnapshot":
        """Build + index a snapshot from already-loaded arrays — the
        shared back half of load() and the per-shard fleet loader
        (serve.snapshot.load_fleet_shard). A `sumF_global` array (fleet
        shards stamp it) overrides the locally-summed sumF: mass share,
        delta context, and the fold-in tail term are global quantities
        even when this snapshot holds one shard's rows."""
        directory = "<arrays>"
        rep = meta.get("representation", "dense")
        n = int(meta.get("n", 0))
        k = int(meta.get("k", 0))
        num_edges = int(meta.get("num_edges", 0))
        F = ids = w = None
        if rep == "dense":
            if "F" not in arrays:
                raise SnapshotError(
                    f"{directory}: dense snapshot {step} has no F array"
                )
            F = np.asarray(arrays["F"])
            n = n or F.shape[0]
            k = k or F.shape[1]
            sumF = F[:n, :k].sum(axis=0)
        elif rep == "sparse":
            if "ids" not in arrays or "w" not in arrays:
                raise SnapshotError(
                    f"{directory}: sparse snapshot {step} missing ids/w"
                )
            ids = np.asarray(arrays["ids"])
            w = np.asarray(arrays["w"])
            n = n or ids.shape[0]
            if not k:
                raise SnapshotError(
                    f"{directory}: sparse snapshot {step} meta has no k"
                )
            sumF = np.zeros(k, w.dtype)
            valid = ids[:n] < k
            np.add.at(
                sumF, ids[:n][valid].astype(np.int64), w[:n][valid]
            )
        else:
            raise SnapshotError(
                f"{directory}: unknown representation {rep!r}"
            )
        if "sumF_global" in arrays:
            sumF = np.asarray(arrays["sumF_global"])
        raw = arrays.get("raw_ids")
        raw_ids = (
            np.asarray(raw)[:n] if raw is not None
            else np.arange(n, dtype=np.int64)
        )
        if store is not None:
            if store.num_nodes != n or (
                num_edges and store.num_directed_edges != 2 * num_edges
            ):
                raise SnapshotError(
                    f"snapshot {step} ({n} nodes, {num_edges} edges) does "
                    f"not match the store ({store.num_nodes} nodes, "
                    f"{store.num_directed_edges // 2} edges) — wrong "
                    "graph cache for this snapshot"
                )
        delta = float(meta.get("delta", delta_threshold(n, num_edges)))
        # ---- membership pairs -> community->members CSR (load-time
        # index; the "members of c" family is then one slice per query)
        if rep == "dense":
            pnodes: List[np.ndarray] = []
            pcomms: List[np.ndarray] = []
            for lo in range(0, n, max(chunk_rows, 1)):
                hi = min(lo + max(chunk_rows, 1), n)
                mask = membership_mask(F[lo:hi, :k], delta)
                ni, ci = np.nonzero(mask)
                pnodes.append(ni + lo)
                pcomms.append(ci)
            nodes_i = np.concatenate(pnodes) if pnodes else np.zeros(0, int)
            comms_i = np.concatenate(pcomms) if pcomms else np.zeros(0, int)
        else:
            nodes_i, comms_i, _ = _sparse_membership_pairs(
                ids[:n], w[:n], k, delta
            )
        # sort pairs by (community, RAW id) — not internal row: balanced
        # caches permute rows, and the members_of contract (matching
        # ops.extraction._group_pairs) is raw-id-sorted member lists
        member_raw = raw_ids[nodes_i]
        order = np.lexsort((member_raw, comms_i))
        comm_members = member_raw[order]
        counts = np.bincount(comms_i, minlength=k)
        comm_indptr = np.zeros(k + 1, np.int64)
        np.cumsum(counts, out=comm_indptr[1:])
        total = float(sumF.sum())
        mass_share = (
            sumF / total if total > 0 else np.zeros(k, np.float64)
        )
        raw_order = np.argsort(raw_ids, kind="stable")
        return cls(
            step=step, representation=rep, n=n, k=k, num_edges=num_edges,
            delta=delta, F=F, ids=ids, w=w, sumF=np.asarray(sumF),
            raw_ids=raw_ids, meta=meta, comm_indptr=comm_indptr,
            comm_members=comm_members, mass_share=np.asarray(mass_share),
            _raw_order=raw_order, _raw_sorted=raw_ids[raw_order],
        )

    # ---------------------------------------------------------- queries
    def row_of(self, raw_id: int) -> int:
        """Internal row of a raw node id (binary search over the
        load-time sorted raw-id view; raises KeyError on unknown ids)."""
        pos = np.searchsorted(self._raw_sorted, raw_id)
        if pos >= self.n or self._raw_sorted[pos] != raw_id:
            raise KeyError(f"unknown node id {raw_id}")
        return int(self._raw_order[pos])

    def row_weights(self, row: int) -> Tuple[np.ndarray, np.ndarray]:
        """(community ids, weights) of a node's POSITIVE affiliations."""
        if self.representation == "dense":
            r = self.F[row, : self.k]
            nz = np.nonzero(r > 0)[0]
            return nz, r[nz]
        valid = (self.ids[row] < self.k) & (self.w[row] > 0)
        return (
            self.ids[row][valid].astype(np.int64),
            self.w[row][valid],
        )

    def communities_of(self, row: int) -> Tuple[np.ndarray, np.ndarray]:
        """Threshold read of one row — ops.extraction.membership_mask
        semantics (>= delta, argmax-tie fallback), sorted by weight
        descending."""
        if self.representation == "dense":
            mask = membership_mask(
                self.F[row : row + 1, : self.k], self.delta
            )[0]
            cids = np.nonzero(mask)[0]
            weights = self.F[row, cids]
        else:
            ni, cids, weights = _sparse_membership_pairs(
                self.ids[row : row + 1], self.w[row : row + 1],
                self.k, self.delta,
            )
        order = np.argsort(-weights, kind="stable")
        return cids[order], weights[order]

    def members_of(self, c: int) -> np.ndarray:
        """Sorted raw member ids of community c (the load-time inverted
        index; one slice per query)."""
        if not 0 <= c < self.k:
            raise KeyError(f"community {c} out of range [0, {self.k})")
        return self.comm_members[
            self.comm_indptr[c] : self.comm_indptr[c + 1]
        ]

    def top_mass_communities(self, count: int) -> np.ndarray:
        """Communities by descending mass share — the Zipf-aware cache's
        admission ranking (serve.server.HotCommunityCache)."""
        count = max(min(count, self.k), 0)
        return np.argsort(-self.mass_share, kind="stable")[:count]

    # ------------------------------------------- shard / fleet context
    @property
    def lo(self) -> int:
        """First GLOBAL internal row this snapshot holds (0 on a
        single-archive snapshot; the shard's range start on a fleet
        shard). Global row g lives at local row g - lo."""
        return int(self.meta.get("lo", 0))

    @property
    def n_global(self) -> int:
        """Fleet-wide node count (== n on a single-archive snapshot)."""
        return int(self.meta.get("n_global", self.n))

    @property
    def published_ts(self) -> Optional[float]:
        ts = self.meta.get("published_ts")
        return float(ts) if isinstance(ts, (int, float)) else None

    def age_s(self) -> Optional[float]:
        """Wall-clock seconds since this generation was published — the
        'how stale is serving' number (None on pre-r22 snapshots that
        carry no published_ts)."""
        ts = self.published_ts
        return max(time.time() - ts, 0.0) if ts is not None else None
