"""The serving fleet's jax-free query router (ISSUE 18 tentpole).

`FleetRouter` fronts N replicas × S shards of a published fleet
generation (serve.fleet protocol) and answers the same three query
families as the single-process MembershipServer, with the same answer
shapes:

  * communities_of / suggest_for route BY NODE from the manifest's
    raw-id range map: disjoint raw intervals (unpermuted cache) resolve
    with one bisect; overlapping intervals (balanced/permuted cache)
    probe every containing shard and the owner answers (`not_owner`
    elsewhere);
  * members_of scatter-gathers every shard's local inverted index and
    merges with np.unique — ascending raw-id dedup, which IS the
    single-process sorted-by-raw-id contract (each node lives in
    exactly one shard, so the union is the full member list);
  * suggest_for is two-phase: the owner returns its neighbors' GLOBAL
    internal rows (phase 1), the router gathers their dense rows by
    DISJOINT row range across shards (order preserved), and the owner
    folds in against the global sumF (phase 2) — bit-for-bit the
    single-process batch math, different addressing.

Replica choice is pick-least-loaded over health-checked replicas: every
fleet answer piggybacks the replica's live queue depth, and `refresh()`
(the health poll) re-reads status from everyone.

Barrier-free rollout: the router serves generation g until EVERY
healthy replica of EVERY shard reports g+1 loaded (intersection of
generation sets), then flips — and never backward. Each query captures
the serving generation at submit and pins every sub-query to it;
replicas echo the generation that answered, so a mixed-generation
answer is a counted tripwire (`mixed_generation`, asserted zero by
scripts/fleet_gate.py), not a silent wrong answer. A shard one
generation behind simply keeps the whole fleet pinned at g — correct,
not an error (tests/test_fleet.py).

Distributed query tracing (ISSUE 19): with telemetry installed, every
routed query opens a trace — the router stamps a `trace` marker on each
sub-query, replicas echo a per-hop timing block (serve.fleet), and the
router assembles the cross-process decomposition. Sub-sends within one
route() call are SEQUENTIAL, so the identity

    total_s = sum(wire_s over hops) + merge_s

holds exactly (merge_s is router-side work: bucketing, np.unique, the
fold-in row gather bookkeeping), and each hop's wire_s further splits
into transport_s (wire minus replica receipt-to-answer) + decode_s +
queue_s + batch_wait_s + execute_s. Per-hop means aggregate fleet-wide
and per-shard into stats() (the perf ledger verdicts them — "the
router got slower" and "shard 3 got slower" are different regressions),
the slowest TRACE_TOP traces per TRACE_WINDOW completed queries are
emitted as schema'd `qtrace` exemplar events, and `freshness` events
sample generation age (ROADMAP 3a). Tracing is off-path-free: with no
telemetry installed no marker is stamped, replicas attach nothing, and
answers are bit-identical to an untraced run.

Self-healing (ISSUE 20): the router is also a long-lived tier.
`RouterServer` (`cli route --daemon`) serves route() over the same
newline-framed JSON wire; each query gets an optional wall DEADLINE,
idempotent read sub-queries get bounded refresh+retry rounds after a
whole replica set fails (the window in which the FleetSupervisor
restarts a kill -9'd replica — the client sees a retried answer, not an
error), and optional tail-latency HEDGING duplicates a slow read to a
second replica after a p99-derived delay (winner counted, loser's
socket shut down). With `members_file` the endpoint set is a watched
membership document (supervisor-published, serve.supervise): refresh()
reconciles it, so add-replica and drain work mid-stream with zero
drops. Counters: router_retries / hedged / hedge_wins /
deadline_exceeded / membership_reloads, rate-verdicted in the perf
ledger. For hedged queries the sequential trace identity above becomes
an inequality (two hops overlap in time); hedged hops are marked.

Entirely jax-free: routing is bisect + np.unique; the device work stays
on the replicas.
"""

from __future__ import annotations

import json
import queue as _queuemod
import socket
import socketserver
import threading
import time
from bisect import bisect_right
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from heapq import heappush, heappushpop
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from bigclam_tpu.obs import telemetry as _obs
from bigclam_tpu.obs.ledger import _percentile
from bigclam_tpu.obs.trace import new_trace_id
from bigclam_tpu.resilience.faults import maybe_fire
from bigclam_tpu.utils.checkpoint import CheckpointManager

FAMILIES = ("communities_of", "members_of", "suggest_for")

# sub-query families the router may RE-dispatch after every replica of a
# shard failed (a refresh + bounded retry round) and may HEDGE: the
# idempotent cheap reads. suggest_rows — the fold-in execute — is
# excluded on purpose: duplicating device work amplifies exactly the
# overload that makes replicas slow, and the transport failover (send
# failed, no work started) already covers it (DESIGN.md "Fleet failure
# model").
_RETRY_FAMILIES = frozenset(
    ("communities_of", "members_of", "rows_of", "suggest_for")
)

# rolling window of sub-query wire latencies feeding the p99-derived
# hedge delay (bounded: old samples age out under any load)
_WIRE_WINDOW = 512

# slow-query exemplar log: keep the TRACE_TOP slowest traces per
# TRACE_WINDOW completed traced queries, emit them as `qtrace` events,
# reset — bounded event volume under any load
TRACE_WINDOW = 1000
TRACE_TOP = 5

# replica-echoed hop fields (serve.fleet) + the router-derived transport
# split, in decomposition order; `merge` (router-side) joins them in the
# fleet-wide accumulators
_HOP_NAMES = ("transport", "decode", "queue", "batch_wait", "execute")


class RouterError(RuntimeError):
    """No serving generation, or no healthy replica for a shard."""


class _Shed(Exception):
    """A sub-query was shed by replica admission control — the whole
    routed query degrades to one fast {"error": "overloaded"} answer."""


class _DeadlineExceeded(Exception):
    """The per-query deadline ran out mid-route — the whole query
    degrades to one {"error": "deadline_exceeded"} answer (counted;
    the ledger verdicts the rate)."""


class TcpReplica:
    """Client transport to one ReplicaServer endpoint: persistent
    JSON-lines connections (a small pool, so concurrent router workers
    don't serialize on one socket). On an I/O error — including a TORN
    answer frame (peer killed mid-write) or a garbage line — the
    connection is dropped and the request retried once on a fresh one; a
    second failure propagates (the router marks the endpoint unhealthy).
    A read TIMEOUT is different: the socket is closed and TimeoutError
    raised immediately — a stalled replica costs at most one timeout,
    never a blind same-budget retry (ISSUE 20 satellite).

    Hedging support: pass a `handle` dict and the in-flight connection
    is tracked in it; `cancel(handle)` shutdown()s that socket, which
    reliably wakes a blocked recv so a hedge loser stops consuming a
    connection the moment the winner answers."""

    def __init__(
        self, host: str, port: int, timeout_s: float = 60.0, pool: int = 4
    ):
        self.host = host
        self.port = int(port)
        self.timeout_s = float(timeout_s)
        self.shard: Optional[int] = None   # filled by router discovery
        self.depth = 0
        self._pool: List[Any] = []
        self._pool_lock = threading.Lock()
        self._pool_max = max(int(pool), 1)
        self._closed = False

    def _connect(self):
        spec = maybe_fire(
            "wire.connect", endpoint=f"{self.host}:{self.port}"
        )
        if spec is not None and spec.get("kind") == "connect_refuse":
            raise ConnectionRefusedError(
                f"injected connect_refuse to {self.host}:{self.port}"
            )
        sock = socket.create_connection(
            (self.host, self.port), timeout=self.timeout_s
        )
        return (sock, sock.makefile("rb"))

    def _acquire(self):
        with self._pool_lock:
            if self._pool:
                return self._pool.pop()
        return self._connect()

    def _release(self, conn) -> None:
        with self._pool_lock:
            if not self._closed and len(self._pool) < self._pool_max:
                self._pool.append(conn)
                return
        self._discard(conn)

    @staticmethod
    def _discard(conn) -> None:
        try:
            conn[1].close()
            conn[0].close()
        except OSError:
            pass

    def _handle_set(self, handle, conn) -> None:
        if handle is not None:
            with self._pool_lock:
                handle["conn"] = conn

    def request(
        self,
        q: Dict[str, Any],
        timeout: Optional[float] = None,
        handle: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        payload = (json.dumps(q) + "\n").encode()
        budget = timeout if timeout is not None else self.timeout_s
        last: Optional[BaseException] = None
        for attempt in range(2):
            if handle is not None and handle.get("cancelled"):
                raise ConnectionError("request cancelled (hedge loser)")
            conn = None
            try:
                conn = self._acquire()
                sock, rfile = conn
                sock.settimeout(budget)
                self._handle_set(handle, conn)
                sock.sendall(payload)
                line = rfile.readline()
                if not line:
                    raise ConnectionError("replica closed the connection")
                if not line.endswith(b"\n"):
                    # torn frame: the peer died mid-write (or the read
                    # was cancelled) — never hand a partial frame to the
                    # json decoder as if it were an answer
                    raise ConnectionError("torn answer frame")
                # parse BEFORE releasing: a garbage line must discard
                # this connection, never park it back in the pool
                res = json.loads(line)
                self._handle_set(handle, None)
                self._release(conn)
                return res
            except socket.timeout as e:
                # bounded read: close the wedged socket and surface the
                # timeout NOW — the caller (router) owns the deadline
                # and decides whether another replica gets a try
                self._handle_set(handle, None)
                if conn is not None:
                    self._discard(conn)
                raise TimeoutError(
                    f"replica {self.host}:{self.port} timed out "
                    f"after {budget:.3f}s"
                ) from e
            except (OSError, ValueError, ConnectionError) as e:
                last = e
                self._handle_set(handle, None)
                if conn is not None:
                    self._discard(conn)
        raise ConnectionError(
            f"replica {self.host}:{self.port} unreachable: {last}"
        )

    def cancel(self, handle: Dict[str, Any]) -> None:
        """Wake a blocked hedge-loser read NOW: shutdown() the in-flight
        socket (a plain close() does not reliably interrupt a blocked
        recv; shutdown does)."""
        with self._pool_lock:
            handle["cancelled"] = True
            conn = handle.get("conn")
        if conn is not None:
            try:
                conn[0].shutdown(socket.SHUT_RDWR)
            except OSError:
                pass

    def close(self) -> None:
        with self._pool_lock:
            self._closed = True
            pool, self._pool = self._pool, []
        for conn in pool:
            self._discard(conn)


class FleetRouter:
    """See module docstring. Transports need `.request(dict) -> dict`,
    `.shard` (set by discovery from their status answer), and `.depth`
    (updated from piggybacked answers) — TcpReplica and
    serve.fleet.LocalReplica both qualify."""

    def __init__(
        self,
        directory: str,
        endpoints: Sequence[Any] = (),
        max_workers: int = 16,
        health_interval_s: float = 0.0,
        request_timeout_s: float = 60.0,
        deadline_s: float = 0.0,
        retry_rounds: int = 1,
        hedge: bool = False,
        hedge_delay_s: float = 0.0,
        hedge_min_samples: int = 64,
        members_file: Optional[str] = None,
    ):
        self.directory = directory
        self._cm = CheckpointManager(directory)
        self.endpoints = list(endpoints)
        self.request_timeout_s = float(request_timeout_s)
        # --- fleet self-healing knobs (ISSUE 20; module docstring) ---
        # deadline_s: per-query wall budget (0 = off); retry_rounds: how
        # many refresh+re-dispatch rounds a read sub-query gets after
        # EVERY replica of its shard failed (the window in which the
        # supervisor restarts a kill -9'd replica); hedge: duplicate a
        # slow read sub-query to a second replica after hedge_delay_s
        # (0 = derive from the rolling wire p99 once hedge_min_samples
        # accumulated), first answer wins, loser cancelled.
        self._deadline_s = max(float(deadline_s), 0.0)
        self._retry_rounds = max(int(retry_rounds), 0)
        self._hedge = bool(hedge)
        self._hedge_delay_s = max(float(hedge_delay_s), 0.0)
        self._hedge_min_samples = max(int(hedge_min_samples), 1)
        self._hedge_pool: Optional[ThreadPoolExecutor] = None
        self._wire_window: deque = deque(maxlen=_WIRE_WINDOW)
        self._members_file = members_file
        self._membership_seq: Optional[int] = None
        self.membership_reloads = 0
        self.retried = 0
        self.hedged = 0
        self.hedge_wins = 0
        self.deadline_exceeded = 0
        self._deadline_local = threading.local()
        self._tables: Dict[int, Dict[str, Any]] = {}
        self._by_shard: Dict[int, List[Any]] = {}
        self._down: set = set()
        self._serving: Optional[int] = None
        self._lock = threading.Lock()
        self._latencies: Dict[str, List[float]] = {
            f: [] for f in FAMILIES
        }
        self._shard_lat: Dict[int, List[float]] = {}
        self._errors = 0
        self._shed = 0
        self.mixed_generation = 0
        # failover tripwires (ISSUE 19 satellite): how often a sub-query
        # moved past a replica because its transport failed vs because it
        # had pruned the pinned generation — surfaced in stats()/report
        # instead of dying as a local error string
        self.pruned_generation = 0
        self.transport_failovers = 0
        self.rollouts = 0
        self._t_first: Optional[float] = None
        self._t_last: Optional[float] = None
        # --- distributed query tracing (ISSUE 19; module docstring) ---
        self._trace_local = threading.local()   # per-thread open trace
        self._inflight: Dict[str, float] = {}   # trace_id -> t0 (perf)
        self._traced = 0
        self._hop_sum: Dict[str, float] = {}
        self._hop_n: Dict[str, int] = {}
        self._shard_hops: Dict[int, Dict[str, List[float]]] = {}
        self._trace_heap: List[Any] = []        # (total_s, seq, record)
        self._trace_seq = 0
        self._trace_seen = 0                    # window fill counter
        self._pool = ThreadPoolExecutor(
            max_workers=max(int(max_workers), 1),
            thread_name_prefix="bigclam-route",
        )
        self.refresh()
        if self._serving is None:
            raise RouterError(
                f"{directory}: no common generation across healthy "
                "replicas — is the fleet up?"
            )
        self._health_stop = threading.Event()
        self._health_thread: Optional[threading.Thread] = None
        if health_interval_s > 0:
            self._health_thread = threading.Thread(
                target=self._health_loop,
                args=(float(health_interval_s),),
                name="bigclam-route-health",
                daemon=True,
            )
            self._health_thread.start()

    # ------------------------------------------------------ range table
    def _table(self, step: int) -> Dict[str, Any]:
        t = self._tables.get(step)
        if t is not None:
            return t
        man = self._cm.load_fleet_manifest(step)
        if man is None:
            raise RouterError(
                f"{self.directory}: fleet manifest for generation "
                f"{step} is unreadable"
            )
        entries = sorted(man["shards"], key=lambda e: int(e["lo"]))
        raw_sorted = sorted(
            entries, key=lambda e: int(e.get("raw_lo", 0))
        )
        disjoint = all(
            int(raw_sorted[i]["raw_hi"])
            < int(raw_sorted[i + 1]["raw_lo"])
            for i in range(len(raw_sorted) - 1)
        )
        t = {
            "row_lo": [int(e["lo"]) for e in entries],
            "row_shard": [int(e["shard"]) for e in entries],
            "shard_ids": [int(e["shard"]) for e in man["shards"]],
            "raw_lo": [int(e.get("raw_lo", 0)) for e in raw_sorted],
            "raw_hi": [int(e.get("raw_hi", -1)) for e in raw_sorted],
            "raw_shard": [int(e["shard"]) for e in raw_sorted],
            "raw_disjoint": disjoint,
            "published_ts": man.get("published_ts"),
        }
        self._tables[step] = t
        return t

    def _owners_of_raw(self, u: int, step: int) -> List[int]:
        """Shards that may own raw id u: one (bisect) when the raw-id
        intervals are disjoint, every containing interval otherwise."""
        t = self._table(step)
        if t["raw_disjoint"]:
            i = bisect_right(t["raw_lo"], u) - 1
            if i >= 0 and u <= t["raw_hi"][i]:
                return [t["raw_shard"][i]]
            return []
        hits = [
            s
            for lo, hi, s in zip(
                t["raw_lo"], t["raw_hi"], t["raw_shard"]
            )
            if lo <= u <= hi
        ]
        return hits or list(t["shard_ids"])

    def _shard_of_row(self, g: int, step: int) -> int:
        t = self._table(step)
        i = bisect_right(t["row_lo"], g) - 1
        return t["row_shard"][max(i, 0)]

    # --------------------------------------------------- health/rollout
    def _reload_membership(self) -> None:
        """Re-read the watched membership file (supervisor-published,
        atomic tmp+rename) and reconcile the endpoint set: members in
        state "up" are admitted (existing TcpReplica objects — and their
        warm connection pools — are kept by endpoint), everything else
        (draining/quarantined/removed) is dropped and closed. A torn or
        missing file keeps the current set: membership only ever moves
        on a complete document."""
        try:
            with open(self._members_file) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return
        seq = doc.get("seq")
        if seq is not None and seq == self._membership_seq:
            return
        want: Dict[str, dict] = {}
        for m in doc.get("members", []):
            ep = m.get("endpoint")
            if ep and m.get("state") == "up":
                want[str(ep)] = m
        have = {
            f"{t.host}:{t.port}": t
            for t in self.endpoints
            if isinstance(t, TcpReplica)
        }
        if set(want) != set(have):
            new_eps: List[Any] = []
            for ep in want:
                t = have.get(ep)
                if t is None:
                    host, port = ep.rsplit(":", 1)
                    t = TcpReplica(
                        host, int(port), timeout_s=self.request_timeout_s
                    )
                new_eps.append(t)
            dropped = [t for ep, t in have.items() if ep not in want]
            with self._lock:
                self.endpoints = new_eps
            for t in dropped:
                # idle pooled connections close here; a sub-query already
                # in flight on this transport holds its connection checked
                # out and completes — that is the zero-drop half the
                # router owns during a drain
                try:
                    t.close()
                except Exception:   # noqa: BLE001 — best effort
                    pass
            self.membership_reloads += 1
            tel = _obs.current()
            if tel is not None:
                tel.event(
                    "membership",
                    seq=int(seq or 0),
                    members=len(new_eps),
                )
        self._membership_seq = seq

    def refresh(self) -> Optional[int]:
        """Health-check every endpoint, rebuild the per-shard replica
        sets, and advance the serving generation iff every healthy
        replica of every shard holds a newer common one. Never moves
        backward. With a membership file the endpoint set itself is
        reconciled first (elastic membership, ISSUE 20)."""
        if self._members_file:
            self._reload_membership()
        by_shard: Dict[int, List[Any]] = {}
        common: Optional[set] = None
        down = set()
        for t in self.endpoints:
            try:
                st = t.request({"family": "status"}, timeout=10.0)
            except Exception:   # noqa: BLE001 — endpoint down
                down.add(id(t))
                continue
            t.shard = int(st.get("shard", -1))
            t.depth = int(st.get("depth", 0))
            by_shard.setdefault(t.shard, []).append(t)
            gens = set(int(g) for g in st.get("generations", []))
            common = gens if common is None else (common & gens)
        with self._lock:
            self._by_shard = by_shard
            self._down = down
            if common:
                cand = max(common)
                if self._serving is None or cand > self._serving:
                    previous = self._serving
                    self._serving = cand
                    if previous is not None:
                        self.rollouts += 1
                        tel = _obs.current()
                        if tel is not None:
                            tel.event("rollout", step=int(cand))
        self._emit_freshness()
        return self._serving

    def _health_loop(self, interval: float) -> None:
        while not self._health_stop.wait(interval):
            try:
                self.refresh()
            except Exception:   # noqa: BLE001 — poller must live
                pass

    @property
    def serving_generation(self) -> Optional[int]:
        return self._serving

    def generation_age_s(self) -> Optional[float]:
        if self._serving is None:
            return None
        ts = self._table(self._serving).get("published_ts")
        if not isinstance(ts, (int, float)):
            return None
        return max(time.time() - float(ts), 0.0)

    def _emit_freshness(self) -> None:
        """One schema'd `freshness` sample — serving staleness (ROADMAP
        3a) as an event stream instead of a number that dies with the
        process. Emitted at every refresh and after each run_queries
        batch; no-op without telemetry."""
        tel = _obs.current()
        if tel is None or self._serving is None:
            return
        age = self.generation_age_s()
        if age is None:
            return
        tel.event(
            "freshness",
            generation_age_s=round(age, 3),
            step=int(self._serving),
            rollouts=int(self.rollouts),
        )

    # --------------------------------------------------------- dispatch
    def _deadline(self) -> Optional[float]:
        return getattr(self._deadline_local, "t", None)

    @staticmethod
    def _check_deadline(deadline: Optional[float]) -> None:
        if deadline is not None and time.perf_counter() >= deadline:
            raise _DeadlineExceeded()

    def _remaining(
        self, deadline: Optional[float], slack: float = 2.0
    ) -> float:
        """Wall budget left for waiting on an in-flight attempt: the
        attempt's own socket timeout plus slack when no deadline is set,
        else the remaining deadline plus slack (the attempt thread is
        itself bounded — the slack only covers its return)."""
        if deadline is None:
            return self.request_timeout_s + slack
        rem = deadline - time.perf_counter()
        if rem <= 0:
            raise _DeadlineExceeded()
        return rem + slack

    def _attempt(
        self,
        t: Any,
        shard: int,
        q: Dict[str, Any],
        deadline: Optional[float],
        tr: Optional[Dict[str, Any]],
        handle: Optional[Dict[str, Any]] = None,
        hedged: bool = False,
    ) -> Tuple[str, Any]:
        """One sub-query to one replica, bounded by min(request timeout,
        remaining deadline). Returns ("ok", answer), ("fail", why) — a
        transport failure, replica marked down — or ("skip", why) — a
        live replica that cannot serve this query (pruned generation,
        malformed answer)."""
        timeout = self.request_timeout_s
        if deadline is not None:
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                raise _DeadlineExceeded()
            timeout = min(timeout, remaining)
        t0 = time.perf_counter()
        try:
            if handle is not None:
                res = t.request(q, timeout=timeout, handle=handle)
            else:
                res = t.request(q, timeout=timeout)
        except Exception as e:   # noqa: BLE001 — fail over
            if handle is not None and handle.get("cancelled"):
                # a hedge loser dying AFTER cancellation is the plan
                # working, not a sick replica — no down-mark, no counter
                return "cancelled", f"{type(e).__name__}: {e}"
            self.transport_failovers += 1
            with self._lock:
                self._down.add(id(t))
                if t in self._by_shard.get(shard, ()):
                    self._by_shard[shard].remove(t)
            return "fail", f"{type(e).__name__}: {e}"
        wire_s = time.perf_counter() - t0
        self._shard_lat.setdefault(shard, []).append(wire_s)
        self._wire_window.append(wire_s)
        if not isinstance(res, dict):
            return "skip", f"non-dict answer {type(res).__name__}"
        t.depth = int(res.get("depth", getattr(t, "depth", 0)))
        if res.get("error") == "unknown_generation":
            self.pruned_generation += 1
            return "skip", f"replica pruned generation {q.get('gen')}"
        pin = q.get("gen")
        if (
            pin is not None
            and "gen" in res
            and int(res["gen"]) != int(pin)
        ):
            # the tripwire the gate asserts ZERO on — an answer
            # from a generation the query was not pinned to
            self.mixed_generation += 1
        if tr is not None:
            hop: Dict[str, Any] = {
                "shard": int(shard), "wire_s": wire_s,
            }
            if hedged:
                hop["hedged"] = 1
            hb = res.get("hops")
            if isinstance(hb, (list, tuple)) and len(hb) == 5:
                # compact wire form (see serve.fleet): integer
                # microseconds [decode, queue, batch_wait, execute,
                # replica] — expanded to named float seconds here so
                # only the hot wire path pays for compactness
                hop["decode_s"] = hb[0] / 1e6
                hop["queue_s"] = hb[1] / 1e6
                hop["batch_wait_s"] = hb[2] / 1e6
                hop["execute_s"] = hb[3] / 1e6
                rs = hb[4] / 1e6
                hop["replica_s"] = rs
                # wire time the replica never saw: connect +
                # serialize + kernel/network transit
                hop["transport_s"] = max(wire_s - rs, 0.0)
            tr["hops"].append(hop)
        return "ok", res

    def _hedge_delay(self) -> Optional[float]:
        """The delay before duplicating a read sub-query: explicit when
        configured, else the p99 of the rolling wire-latency window —
        None (no hedge) until enough samples exist to derive one."""
        if self._hedge_delay_s > 0:
            return self._hedge_delay_s
        if len(self._wire_window) < self._hedge_min_samples:
            return None
        p99 = _percentile(list(self._wire_window), 99)
        return max(float(p99), 1e-3) if p99 is not None else None

    def _hedge_pool_get(self) -> ThreadPoolExecutor:
        with self._lock:
            if self._hedge_pool is None:
                self._hedge_pool = ThreadPoolExecutor(
                    max_workers=32,
                    thread_name_prefix="bigclam-route-hedge",
                )
            return self._hedge_pool

    def _request_hedged(
        self,
        primary: Any,
        secondary: Any,
        q: Dict[str, Any],
        deadline: Optional[float],
        shard: int,
        tr: Optional[Dict[str, Any]],
        delay: float,
    ) -> Tuple[Optional[Dict[str, Any]], int, Optional[str]]:
        """Tail-latency hedging: dispatch to the primary; if no answer
        within `delay`, duplicate to the secondary and take whichever
        answers first, cancelling the loser (its socket is shut down so
        it stops consuming a connection). A primary that FAILS before
        the delay fast-forwards to the secondary — that is plain
        failover, only the duplicate-while-in-flight counts as hedged."""
        outq: "_queuemod.Queue" = _queuemod.Queue()
        handles = ({"cancelled": False}, {"cancelled": False})
        transports = (primary, secondary)

        def run(idx: int) -> None:
            try:
                kind, val = self._attempt(
                    transports[idx], shard, q, deadline, tr,
                    handle=handles[idx], hedged=bool(idx),
                )
            except _DeadlineExceeded:
                kind, val = "fail", "deadline exceeded"
            except Exception as e:   # noqa: BLE001 — thread must return
                kind, val = "fail", f"{type(e).__name__}: {e}"
            outq.put((idx, kind, val))

        pool = self._hedge_pool_get()
        pool.submit(run, 0)
        launched = 1
        pending = 1
        failures = 0
        last: Optional[str] = None
        wait = delay
        while pending:
            try:
                idx, kind, val = outq.get(timeout=wait)
            except _queuemod.Empty:
                if launched == 1:
                    self.hedged += 1
                    pool.submit(run, 1)
                    launched = 2
                    pending += 1
                    wait = self._remaining(deadline)
                    continue
                # both bounded attempts in flight past their budget —
                # only a blown deadline can get here
                raise _DeadlineExceeded()
            pending -= 1
            if kind == "ok":
                if launched == 2 and idx == 1:
                    self.hedge_wins += 1
                loser = 1 - idx
                if loser < launched:
                    handles[loser]["cancelled"] = True
                    cancel = getattr(transports[loser], "cancel", None)
                    if cancel is not None:
                        try:
                            cancel(handles[loser])
                        except Exception:   # noqa: BLE001 — best effort
                            pass
                return val, failures, None
            if kind == "fail":
                failures += 1
            last = val
            if launched == 1:
                # primary failed before the hedge delay: straight to
                # the secondary (failover, not a hedge)
                pool.submit(run, 1)
                launched = 2
                pending += 1
            wait = self._remaining(deadline)
        return None, failures, last

    def _send_once(
        self,
        shard: int,
        q: Dict[str, Any],
        deadline: Optional[float],
        tr: Optional[Dict[str, Any]],
    ) -> Tuple[Optional[Dict[str, Any]], int, Optional[str]]:
        """One pass over the shard's healthy replicas, least-loaded
        first (with an optional hedged first attempt). Returns (answer,
        transport-failure count, last failure reason)."""
        with self._lock:
            reps = list(self._by_shard.get(shard, ()))
        if not reps:
            return None, 0, f"no healthy replica for shard {shard}"
        reps.sort(key=lambda r: getattr(r, "depth", 0))
        failures = 0
        last: Optional[str] = None
        start = 0
        if (
            self._hedge
            and len(reps) >= 2
            and q.get("family") in _RETRY_FAMILIES
        ):
            delay = self._hedge_delay()
            if delay is not None:
                res, nfail, why = self._request_hedged(
                    reps[0], reps[1], q, deadline, shard, tr, delay
                )
                failures += nfail
                if res is not None:
                    return res, failures, None
                last = why
                start = 2
        for t in reps[start:]:
            self._check_deadline(deadline)
            kind, val = self._attempt(t, shard, q, deadline, tr)
            if kind == "ok":
                return val, failures, None
            if kind == "fail":
                failures += 1
            last = val
        return None, failures, last

    def _send(
        self, shard: int, q: Dict[str, Any]
    ) -> Dict[str, Any]:
        """One sub-query to the least-loaded healthy replica of a shard;
        a transport failure or an unknown_generation answer (the replica
        pruned the pinned generation) fails over to the next replica.
        When EVERY replica of the shard fails, idempotent read families
        get `retry_rounds` refresh+re-dispatch rounds (bounded by the
        query deadline) — the window in which a supervisor restart or a
        membership change heals the fleet; a sub-query that answers
        after any failure increments `retried` (a kill -9 mid-query
        surfaces as a retried answer, not a client error)."""
        deadline = self._deadline()
        fam = q.get("family")
        rounds = 1 + (
            self._retry_rounds if fam in _RETRY_FAMILIES else 0
        )
        tr = getattr(self._trace_local, "tr", None)
        if tr is not None:
            # stamp the trace marker at the ONE place every sub-query
            # passes through — replicas echo a `hops` block only when
            # they see it (off-path contract: untraced wire answers are
            # byte-identical to pre-trace builds)
            q = dict(q)
            q["trace"] = 1
        failures = 0
        last: Optional[str] = None
        for rnd in range(rounds):
            if rnd:
                # the whole replica set failed: one bounded chance for
                # the fleet to heal before the query errors — re-read
                # membership + health, small backoff within the deadline
                self._check_deadline(deadline)
                time.sleep(min(0.05 * rnd, 0.25))
                try:
                    self.refresh()
                except Exception:   # noqa: BLE001 — retry is best effort
                    pass
            res, nfail, why = self._send_once(shard, q, deadline, tr)
            failures += nfail
            if res is not None:
                if failures or rnd:
                    self.retried += 1
                    if tr is not None and tr["hops"]:
                        tr["hops"][-1]["retried"] = max(failures, 1)
                return res
            last = why
        raise RouterError(
            f"every replica of shard {shard} failed: {last}"
        )

    @staticmethod
    def _strip(res: Dict[str, Any]) -> Dict[str, Any]:
        return {
            k: v for k, v in res.items()
            if k not in ("gen", "depth", "cached", "not_owner", "hops")
        }

    def _route_communities(
        self, q: Dict[str, Any], gen: int
    ) -> Dict[str, Any]:
        u = int(q["u"])
        for s in self._owners_of_raw(u, gen):
            res = self._send(
                s, {"family": "communities_of", "u": u, "gen": gen}
            )
            if not res.get("not_owner"):
                return self._strip(res)
        return {"error": f"KeyError: 'unknown node id {u}'"}

    def _route_members(
        self, q: Dict[str, Any], gen: int
    ) -> Dict[str, Any]:
        c = int(q["c"])
        parts: List[np.ndarray] = []
        for s in self._table(gen)["shard_ids"]:
            res = self._send(
                s, {"family": "members_of", "c": c, "gen": gen}
            )
            if "error" in res:
                return self._strip(res)
            parts.append(np.asarray(res.get("members", []), np.int64))
        merged = (
            np.unique(np.concatenate(parts))
            if parts else np.zeros(0, np.int64)
        )
        return {"c": c, "members": [int(u) for u in merged]}

    def _gather_rows(
        self, rows: Sequence[int], gen: int
    ) -> List[List[float]]:
        """Dense K-vectors of GLOBAL internal rows, gathered by disjoint
        row range across shards, returned in the REQUESTED order (the
        fold-in's neighbor order must match the CSR order)."""
        buckets: Dict[int, List[int]] = {}
        for i, g in enumerate(rows):
            buckets.setdefault(
                self._shard_of_row(int(g), gen), []
            ).append(i)
        out: List[Optional[List[float]]] = [None] * len(rows)
        for s, idxs in buckets.items():
            res = self._send(
                s,
                {
                    "family": "rows_of",
                    "rows": [int(rows[i]) for i in idxs],
                    "gen": gen,
                },
            )
            if res.get("error") == "overloaded":
                raise _Shed()
            if "error" in res:
                raise RouterError(
                    f"rows_of on shard {s}: {res['error']}"
                )
            for i, r in zip(idxs, res["rows"]):
                out[i] = r
        return out   # type: ignore[return-value]

    def _route_suggest(
        self, q: Dict[str, Any], gen: int
    ) -> Dict[str, Any]:
        if "neighbors" in q:
            return self._route_suggest_explicit(q, gen)
        u = int(q["u"])
        phase1 = None
        owner = None
        for s in self._owners_of_raw(u, gen):
            res = self._send(
                s, {"family": "suggest_for", "u": u, "gen": gen}
            )
            if not res.get("not_owner"):
                phase1, owner = res, s
                break
        if phase1 is None:
            return {"error": f"KeyError: 'unknown node id {u}'"}
        if "error" in phase1:
            return self._strip(phase1)
        rows = self._gather_rows(phase1.get("needs_rows", []), gen)
        res = self._send(
            owner,
            {
                "family": "suggest_rows",
                "u": u,
                "gen": gen,
                "neighbor_rows": rows,
                "own_row": phase1.get("own_row"),
            },
        )
        return self._strip(res)

    def _route_suggest_explicit(
        self, q: Dict[str, Any], gen: int
    ) -> Dict[str, Any]:
        """suggest_for with an explicit raw-id neighbor list (the
        brand-new-node path): resolve each neighbor's dense row by
        probing its owner shards, then phase 2 on the query node's owner
        (or the least-loaded first shard for a node not in the graph)."""
        raw = [int(v) for v in q["neighbors"]]
        need: Dict[int, List[int]] = {}
        for u in raw:
            for s in self._owners_of_raw(u, gen):
                need.setdefault(s, []).append(u)
        rows_by_raw: Dict[int, List[float]] = {}
        for s, ids in need.items():
            res = self._send(
                s, {"family": "rows_of", "raw": ids, "gen": gen}
            )
            for key, row in res.get("raw_rows", {}).items():
                rows_by_raw[int(key)] = row
        missing = [u for u in raw if u not in rows_by_raw]
        if missing:
            return {
                "error": f"KeyError: 'unknown node id {missing[0]}'"
            }
        own_row = None
        owner = self._table(gen)["shard_ids"][0]
        if "u" in q:
            u = int(q["u"])
            for s in self._owners_of_raw(u, gen):
                res = self._send(
                    s, {"family": "rows_of", "raw": [u], "gen": gen}
                )
                got = res.get("raw_rows", {}).get(str(u))
                if got is not None:
                    own_row, owner = got, s
                    break
        sub = {
            "family": "suggest_rows",
            "gen": gen,
            "neighbor_rows": [rows_by_raw[u] for u in raw],
            "own_row": own_row,
        }
        if "u" in q:
            sub["u"] = int(q["u"])
        return self._strip(self._send(owner, sub))

    # ---------------------------------------------------------- queries
    def route(self, q: Dict[str, Any]) -> Dict[str, Any]:
        """One fully-routed query -> one answer with the single-process
        MembershipServer's answer shape. The serving generation is
        captured HERE and pinned through every sub-query — a rollout
        mid-query cannot mix generations in one answer."""
        gen = self._serving
        if gen is None:
            return {"error": "RouterError: no serving generation"}
        fam = q.get("family") if isinstance(q, dict) else None
        t0 = time.perf_counter()
        # per-query deadline, pinned here and read by every sub-send
        # (thread-local like the trace: route() runs one query per
        # worker thread end to end)
        self._deadline_local.t = (
            t0 + self._deadline_s if self._deadline_s > 0 else None
        )
        tr: Optional[Dict[str, Any]] = None
        if _obs.current() is not None:
            # tracing is exactly telemetry-installed: one dict + one
            # registry entry per query, nothing on the untraced path
            tr = {"id": new_trace_id(), "family": str(fam), "hops": []}
            self._trace_local.tr = tr
            with self._lock:
                self._inflight[tr["id"]] = t0
        try:
            if fam == "communities_of":
                res = self._route_communities(q, gen)
            elif fam == "members_of":
                res = self._route_members(q, gen)
            elif fam == "suggest_for":
                res = self._route_suggest(q, gen)
            else:
                res = {"error": f"KeyError: 'unknown family {fam!r}'"}
        except _Shed:
            res = {"error": "overloaded"}
        except _DeadlineExceeded:
            self.deadline_exceeded += 1
            res = {"error": "deadline_exceeded"}
        except Exception as e:   # noqa: BLE001 — per-query isolation
            res = {"error": f"{type(e).__name__}: {e}"}
        self._deadline_local.t = None
        if tr is not None:
            self._trace_local.tr = None
        lat = time.perf_counter() - t0
        exemplars = None
        with self._lock:
            if res.get("error") == "overloaded":
                self._shed += 1
            elif "error" in res:
                self._errors += 1
            if fam in self._latencies:
                self._latencies[fam].append(lat)
            if self._t_first is None or t0 < self._t_first:
                self._t_first = t0
            end = t0 + lat
            if self._t_last is None or end > self._t_last:
                self._t_last = end
            if tr is not None:
                self._inflight.pop(tr["id"], None)
                exemplars = self._absorb_trace_locked(tr, lat)
        if exemplars:
            self._emit_exemplars(exemplars)
        return res

    # ---------------------------------------------------------- tracing
    def _absorb_trace_locked(
        self, tr: Dict[str, Any], total_s: float
    ) -> Optional[List[Any]]:
        """Fold one completed trace into the hop accumulators + the
        slow-query exemplar heap (caller holds the lock). Returns the
        window's exemplar items when this trace closed a TRACE_WINDOW,
        else None — the caller emits them OUTSIDE the lock."""
        self._traced += 1
        wire = 0.0
        for hop in tr["hops"]:
            w = hop.get("wire_s")
            if isinstance(w, (int, float)):
                wire += float(w)
            per = self._shard_hops.setdefault(int(hop["shard"]), {})
            for name in _HOP_NAMES:
                v = hop.get(name + "_s")
                if not isinstance(v, (int, float)):
                    continue
                self._hop_sum[name] = self._hop_sum.get(name, 0.0) + v
                self._hop_n[name] = self._hop_n.get(name, 0) + 1
                acc = per.setdefault(name, [0.0, 0])
                acc[0] += v
                acc[1] += 1
        # sequential sub-sends: total == sum(wire) + merge exactly, so
        # merge (router-side work) is the closing residual
        merge_s = max(total_s - wire, 0.0)
        self._hop_sum["merge"] = self._hop_sum.get("merge", 0.0) + merge_s
        self._hop_n["merge"] = self._hop_n.get("merge", 0) + 1
        heap = self._trace_heap
        if len(heap) < TRACE_TOP or total_s > heap[0][0]:
            # only build the rounded exemplar record when this trace
            # actually enters the top-N — the common (fast) trace pays
            # one comparison here, not a dict rebuild
            rec = {
                "trace_id": tr["id"],
                "family": tr["family"],
                "total_s": round(total_s, 6),
                "merge_s": round(merge_s, 6),
                "hops": [
                    {
                        k: (round(v, 6) if isinstance(v, float) else v)
                        for k, v in hop.items()
                    }
                    for hop in tr["hops"]
                ],
            }
            self._trace_seq += 1
            item = (total_s, self._trace_seq, rec)
            if len(heap) < TRACE_TOP:
                heappush(heap, item)
            else:
                heappushpop(heap, item)
        self._trace_seen += 1
        if self._trace_seen < TRACE_WINDOW:
            return None
        heap, self._trace_heap = self._trace_heap, []
        self._trace_seen = 0
        return heap

    @staticmethod
    def _emit_exemplars(heap: List[Any]) -> None:
        tel = _obs.current()
        if tel is None:
            return
        for _, _, rec in sorted(heap, key=lambda it: -it[0]):
            tel.event("qtrace", **rec)

    def flush_traces(self) -> None:
        """Emit the current window's slow-query exemplars now (the end
        of a route run / router shutdown — a part-filled window must
        not die with the process)."""
        with self._lock:
            heap, self._trace_heap = self._trace_heap, []
            self._trace_seen = 0
        if heap:
            self._emit_exemplars(heap)

    def open_trace_count(self) -> int:
        """Routed queries currently in flight (traced) — embedded in
        heartbeat stall events (ISSUE 19 satellite)."""
        with self._lock:
            return len(self._inflight)

    def oldest_inflight_s(self) -> float:
        """Age of the oldest in-flight routed query (0.0 when idle) —
        the 'is one query wedged' number stall events carry."""
        with self._lock:
            if not self._inflight:
                return 0.0
            return time.perf_counter() - min(self._inflight.values())

    def run_queries(
        self,
        queries: Sequence[Dict[str, Any]],
        collect: bool = True,
    ) -> List[Optional[Dict[str, Any]]]:
        """Open-loop driver (the `cli route --queries` path): fan the
        queries over the worker pool, preserve order, never raise
        per-query."""
        futures = [self._pool.submit(self.route, q) for q in queries]
        out: List[Optional[Dict[str, Any]]] = []
        for fut in futures:
            res = fut.result()
            out.append(res if collect else None)
        tel = _obs.current()
        if tel is not None:
            tel.event(
                "route",
                queries=len(queries),
                shards=len(self._by_shard),
            )
            self._emit_freshness()
        return out

    # ------------------------------------------------------------ stats
    def reset_stats(self) -> None:
        with self._lock:
            self._latencies = {f: [] for f in FAMILIES}
            self._shard_lat = {}
            self._errors = 0
            self._shed = 0
            self._t_first = self._t_last = None
            # the self-healing counters are rate-verdicted per measured
            # pass (ledger), so a warmup reset clears them too
            self.retried = 0
            self.hedged = 0
            self.hedge_wins = 0
            self.deadline_exceeded = 0
            # warmup traces must not pollute the measured pass
            self._traced = 0
            self._hop_sum = {}
            self._hop_n = {}
            self._shard_hops = {}
            self._trace_heap = []
            self._trace_seen = 0

    def stats(self) -> Dict[str, Any]:
        """The router scoreboard, key-compatible with
        MembershipServer.stats() where the meaning coincides (so
        obs.ledger harvests both with one code path) plus the
        fleet-only axes: shards/replicas, per-shard latency tables, the
        rollout/mixed-generation counters, and the shed rate."""
        with self._lock:
            lats = [
                v for fam in FAMILIES for v in self._latencies[fam]
            ]
            by_family = {
                fam: len(self._latencies[fam])
                for fam in FAMILIES
                if self._latencies[fam]
            }
            shard_lat = {
                s: list(v) for s, v in self._shard_lat.items()
            }
            traced = self._traced
            hop_sum = dict(self._hop_sum)
            hop_n = dict(self._hop_n)
            shard_hops = {
                s: {k: (acc[0], acc[1]) for k, acc in per.items()}
                for s, per in self._shard_hops.items()
            }
            errors, shed = self._errors, self._shed
            t_first, t_last = self._t_first, self._t_last
            shards = len(self._by_shard)
            replicas = (
                len(
                    [
                        t for t in self.endpoints
                        if id(t) not in self._down
                    ]
                )
                // max(shards, 1)
            )
        total = len(lats)
        wall = (
            max(t_last - t_first, 1e-9)
            if t_first is not None and t_last is not None
            else 0.0
        )
        mix = "|".join(
            f"{fam}:{n / total:.2f}" for fam, n in by_family.items()
        )
        out = {
            "serve_queries": total,
            "serve_errors": errors,
            "serve_by_family": by_family,
            "serve_mix": mix,
            "serve_p50_s": _percentile(lats, 50),
            "serve_p99_s": _percentile(lats, 99),
            "serve_qps": (total / wall) if wall else None,
            "serve_shed": shed,
            "serve_shed_rate": (
                round(shed / (total + shed), 4)
                if (total + shed) else 0.0
            ),
            "serve_shards": shards,
            "serve_replicas": replicas,
            "serve_shard_stats": {
                str(s): {
                    "queries": len(v),
                    "p50_s": _percentile(v, 50),
                    "p99_s": _percentile(v, 99),
                    "qps": (
                        round(len(v) / wall, 2) if wall else None
                    ),
                }
                for s, v in sorted(shard_lat.items())
            },
            "serving_generation": self._serving,
            "snapshot_step": self._serving,
            "mixed_generation": self.mixed_generation,
            "pruned_generation": self.pruned_generation,
            "transport_failovers": self.transport_failovers,
            "rollouts": self.rollouts,
            "traced_queries": traced,
            # self-healing scoreboard (ISSUE 20): retried = sub-queries
            # that answered after at least one failure (the kill -9
            # drill's "not a client error" proof); the rates are what
            # the perf ledger verdicts
            "router_retries": self.retried,
            "hedged": self.hedged,
            "hedge_wins": self.hedge_wins,
            "hedged_rate": (
                round(self.hedged / total, 4) if total else 0.0
            ),
            "deadline_exceeded": self.deadline_exceeded,
            "deadline_exceeded_rate": (
                round(self.deadline_exceeded / total, 4) if total else 0.0
            ),
            "membership_reloads": self.membership_reloads,
        }
        # fleet-wide per-hop latency means (traced queries only): the
        # decomposition the ledger verdicts — a transport regression and
        # an execute regression are different findings
        for name in _HOP_NAMES + ("merge",):
            n = hop_n.get(name, 0)
            if n:
                out[f"serve_hop_{name}_s"] = round(
                    hop_sum.get(name, 0.0) / n, 6
                )
        for s, per in shard_hops.items():
            st = out["serve_shard_stats"].get(str(s))
            if st is not None and per:
                st["hops"] = {
                    name: round(tot / n, 6)
                    for name, (tot, n) in sorted(per.items())
                    if n
                }
        age = self.generation_age_s()
        if age is not None:
            out["generation_age_s"] = round(age, 3)
        for key in ("serve_p50_s", "serve_p99_s", "serve_qps"):
            if out[key] is not None:
                out[key] = round(out[key], 6)
        for st in out["serve_shard_stats"].values():
            for key in ("p50_s", "p99_s"):
                if st[key] is not None:
                    st[key] = round(st[key], 6)
        return out

    # -------------------------------------------------------- lifecycle
    def close(self) -> None:
        self.flush_traces()
        self._health_stop.set()
        if self._health_thread is not None:
            self._health_thread.join(timeout=10.0)
            self._health_thread = None
        self._pool.shutdown(wait=False)
        if self._hedge_pool is not None:
            self._hedge_pool.shutdown(wait=False)
        for t in self.endpoints:
            try:
                t.close()
            except Exception:   # noqa: BLE001 — best effort
                pass


class RouterServer:
    """`cli route --daemon` (ISSUE 20): the router itself as a
    long-lived tier on the SAME newline-framed JSON TCP wire the
    replicas speak — one query dict per line in, one answer dict per
    line out. Clients send the three public families verbatim; two
    control ops ride the same framing: `{"family": "status"}` answers
    router.stats() (the self-healing scoreboard) and `{"family":
    "stop"}` acks then shuts the daemon down — so `cli route --stop`
    pointed at a router daemon does exactly what it does to a replica.
    Per-connection threads call route() directly: client connections ARE
    the concurrency, no second worker pool."""

    def __init__(
        self, router: FleetRouter, host: str = "127.0.0.1", port: int = 0
    ):
        self.router = router
        self._stopped = threading.Event()
        outer = self

        class _Handler(socketserver.StreamRequestHandler):
            def handle(self):
                for line in self.rfile:
                    line = line.strip()
                    if not line:
                        continue
                    q: Any = None
                    try:
                        q = json.loads(line)
                    except ValueError:
                        res: Dict[str, Any] = {"error": "bad json"}
                    else:
                        fam = (
                            q.get("family") if isinstance(q, dict) else None
                        )
                        if fam == "status":
                            res = outer.router.stats()
                        elif fam == "stop":
                            res = {"ok": True}
                        else:
                            res = outer.router.route(q)
                    try:
                        self.wfile.write(
                            (json.dumps(res) + "\n").encode()
                        )
                        self.wfile.flush()
                    except OSError:
                        return   # client went away mid-answer
                    if (
                        isinstance(q, dict)
                        and q.get("family") == "stop"
                    ):
                        # ack first, shut down from a fresh thread
                        # (shutdown() deadlocks called from a handler)
                        threading.Thread(
                            target=outer.close, daemon=True
                        ).start()
                        return

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True
            request_queue_size = 128

        self._srv = _Server((host, int(port)), _Handler)
        self.host, self.port = self._srv.server_address[:2]
        self._thread = threading.Thread(
            target=self._srv.serve_forever,
            name="bigclam-route-daemon",
            daemon=True,
        )
        self._thread.start()

    def serve_until_stopped(
        self, timeout: Optional[float] = None
    ) -> bool:
        return self._stopped.wait(timeout)

    def close(self) -> None:
        if self._stopped.is_set():
            return
        self._stopped.set()
        self._srv.shutdown()
        self._srv.server_close()
        self.router.close()
