"""The serving fleet's jax-free query router (ISSUE 18 tentpole).

`FleetRouter` fronts N replicas × S shards of a published fleet
generation (serve.fleet protocol) and answers the same three query
families as the single-process MembershipServer, with the same answer
shapes:

  * communities_of / suggest_for route BY NODE from the manifest's
    raw-id range map: disjoint raw intervals (unpermuted cache) resolve
    with one bisect; overlapping intervals (balanced/permuted cache)
    probe every containing shard and the owner answers (`not_owner`
    elsewhere);
  * members_of scatter-gathers every shard's local inverted index and
    merges with np.unique — ascending raw-id dedup, which IS the
    single-process sorted-by-raw-id contract (each node lives in
    exactly one shard, so the union is the full member list);
  * suggest_for is two-phase: the owner returns its neighbors' GLOBAL
    internal rows (phase 1), the router gathers their dense rows by
    DISJOINT row range across shards (order preserved), and the owner
    folds in against the global sumF (phase 2) — bit-for-bit the
    single-process batch math, different addressing.

Replica choice is pick-least-loaded over health-checked replicas: every
fleet answer piggybacks the replica's live queue depth, and `refresh()`
(the health poll) re-reads status from everyone.

Barrier-free rollout: the router serves generation g until EVERY
healthy replica of EVERY shard reports g+1 loaded (intersection of
generation sets), then flips — and never backward. Each query captures
the serving generation at submit and pins every sub-query to it;
replicas echo the generation that answered, so a mixed-generation
answer is a counted tripwire (`mixed_generation`, asserted zero by
scripts/fleet_gate.py), not a silent wrong answer. A shard one
generation behind simply keeps the whole fleet pinned at g — correct,
not an error (tests/test_fleet.py).

Entirely jax-free: routing is bisect + np.unique; the device work stays
on the replicas.
"""

from __future__ import annotations

import json
import socket
import threading
import time
from bisect import bisect_right
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from bigclam_tpu.obs import telemetry as _obs
from bigclam_tpu.obs.ledger import _percentile
from bigclam_tpu.utils.checkpoint import CheckpointManager

FAMILIES = ("communities_of", "members_of", "suggest_for")


class RouterError(RuntimeError):
    """No serving generation, or no healthy replica for a shard."""


class _Shed(Exception):
    """A sub-query was shed by replica admission control — the whole
    routed query degrades to one fast {"error": "overloaded"} answer."""


class TcpReplica:
    """Client transport to one ReplicaServer endpoint: persistent
    JSON-lines connections (a small pool, so concurrent router workers
    don't serialize on one socket). On an I/O error the connection is
    dropped and the request retried once on a fresh one; a second
    failure propagates (the router marks the endpoint unhealthy)."""

    def __init__(
        self, host: str, port: int, timeout_s: float = 60.0, pool: int = 4
    ):
        self.host = host
        self.port = int(port)
        self.timeout_s = float(timeout_s)
        self.shard: Optional[int] = None   # filled by router discovery
        self.depth = 0
        self._pool: List[Any] = []
        self._pool_lock = threading.Lock()
        self._pool_max = max(int(pool), 1)

    def _connect(self):
        sock = socket.create_connection(
            (self.host, self.port), timeout=self.timeout_s
        )
        return (sock, sock.makefile("rb"))

    def _acquire(self):
        with self._pool_lock:
            if self._pool:
                return self._pool.pop()
        return self._connect()

    def _release(self, conn) -> None:
        with self._pool_lock:
            if len(self._pool) < self._pool_max:
                self._pool.append(conn)
                return
        self._discard(conn)

    @staticmethod
    def _discard(conn) -> None:
        try:
            conn[1].close()
            conn[0].close()
        except OSError:
            pass

    def request(
        self, q: Dict[str, Any], timeout: Optional[float] = None
    ) -> Dict[str, Any]:
        payload = (json.dumps(q) + "\n").encode()
        last: Optional[BaseException] = None
        for attempt in range(2):
            conn = None
            try:
                conn = self._acquire()
                sock, rfile = conn
                if timeout is not None:
                    sock.settimeout(timeout)
                sock.sendall(payload)
                line = rfile.readline()
                if not line:
                    raise ConnectionError("replica closed the connection")
                self._release(conn)
                return json.loads(line)
            except (OSError, ValueError, ConnectionError) as e:
                last = e
                if conn is not None:
                    self._discard(conn)
        raise ConnectionError(
            f"replica {self.host}:{self.port} unreachable: {last}"
        )

    def close(self) -> None:
        with self._pool_lock:
            pool, self._pool = self._pool, []
        for conn in pool:
            self._discard(conn)


class FleetRouter:
    """See module docstring. Transports need `.request(dict) -> dict`,
    `.shard` (set by discovery from their status answer), and `.depth`
    (updated from piggybacked answers) — TcpReplica and
    serve.fleet.LocalReplica both qualify."""

    def __init__(
        self,
        directory: str,
        endpoints: Sequence[Any],
        max_workers: int = 16,
        health_interval_s: float = 0.0,
        request_timeout_s: float = 60.0,
    ):
        self.directory = directory
        self._cm = CheckpointManager(directory)
        self.endpoints = list(endpoints)
        self.request_timeout_s = float(request_timeout_s)
        self._tables: Dict[int, Dict[str, Any]] = {}
        self._by_shard: Dict[int, List[Any]] = {}
        self._down: set = set()
        self._serving: Optional[int] = None
        self._lock = threading.Lock()
        self._latencies: Dict[str, List[float]] = {
            f: [] for f in FAMILIES
        }
        self._shard_lat: Dict[int, List[float]] = {}
        self._errors = 0
        self._shed = 0
        self.mixed_generation = 0
        self.rollouts = 0
        self._t_first: Optional[float] = None
        self._t_last: Optional[float] = None
        self._pool = ThreadPoolExecutor(
            max_workers=max(int(max_workers), 1),
            thread_name_prefix="bigclam-route",
        )
        self.refresh()
        if self._serving is None:
            raise RouterError(
                f"{directory}: no common generation across healthy "
                "replicas — is the fleet up?"
            )
        self._health_stop = threading.Event()
        self._health_thread: Optional[threading.Thread] = None
        if health_interval_s > 0:
            self._health_thread = threading.Thread(
                target=self._health_loop,
                args=(float(health_interval_s),),
                name="bigclam-route-health",
                daemon=True,
            )
            self._health_thread.start()

    # ------------------------------------------------------ range table
    def _table(self, step: int) -> Dict[str, Any]:
        t = self._tables.get(step)
        if t is not None:
            return t
        man = self._cm.load_fleet_manifest(step)
        if man is None:
            raise RouterError(
                f"{self.directory}: fleet manifest for generation "
                f"{step} is unreadable"
            )
        entries = sorted(man["shards"], key=lambda e: int(e["lo"]))
        raw_sorted = sorted(
            entries, key=lambda e: int(e.get("raw_lo", 0))
        )
        disjoint = all(
            int(raw_sorted[i]["raw_hi"])
            < int(raw_sorted[i + 1]["raw_lo"])
            for i in range(len(raw_sorted) - 1)
        )
        t = {
            "row_lo": [int(e["lo"]) for e in entries],
            "row_shard": [int(e["shard"]) for e in entries],
            "shard_ids": [int(e["shard"]) for e in man["shards"]],
            "raw_lo": [int(e.get("raw_lo", 0)) for e in raw_sorted],
            "raw_hi": [int(e.get("raw_hi", -1)) for e in raw_sorted],
            "raw_shard": [int(e["shard"]) for e in raw_sorted],
            "raw_disjoint": disjoint,
            "published_ts": man.get("published_ts"),
        }
        self._tables[step] = t
        return t

    def _owners_of_raw(self, u: int, step: int) -> List[int]:
        """Shards that may own raw id u: one (bisect) when the raw-id
        intervals are disjoint, every containing interval otherwise."""
        t = self._table(step)
        if t["raw_disjoint"]:
            i = bisect_right(t["raw_lo"], u) - 1
            if i >= 0 and u <= t["raw_hi"][i]:
                return [t["raw_shard"][i]]
            return []
        hits = [
            s
            for lo, hi, s in zip(
                t["raw_lo"], t["raw_hi"], t["raw_shard"]
            )
            if lo <= u <= hi
        ]
        return hits or list(t["shard_ids"])

    def _shard_of_row(self, g: int, step: int) -> int:
        t = self._table(step)
        i = bisect_right(t["row_lo"], g) - 1
        return t["row_shard"][max(i, 0)]

    # --------------------------------------------------- health/rollout
    def refresh(self) -> Optional[int]:
        """Health-check every endpoint, rebuild the per-shard replica
        sets, and advance the serving generation iff every healthy
        replica of every shard holds a newer common one. Never moves
        backward."""
        by_shard: Dict[int, List[Any]] = {}
        common: Optional[set] = None
        down = set()
        for t in self.endpoints:
            try:
                st = t.request({"family": "status"}, timeout=10.0)
            except Exception:   # noqa: BLE001 — endpoint down
                down.add(id(t))
                continue
            t.shard = int(st.get("shard", -1))
            t.depth = int(st.get("depth", 0))
            by_shard.setdefault(t.shard, []).append(t)
            gens = set(int(g) for g in st.get("generations", []))
            common = gens if common is None else (common & gens)
        with self._lock:
            self._by_shard = by_shard
            self._down = down
            if common:
                cand = max(common)
                if self._serving is None or cand > self._serving:
                    previous = self._serving
                    self._serving = cand
                    if previous is not None:
                        self.rollouts += 1
                        tel = _obs.current()
                        if tel is not None:
                            tel.event("rollout", step=int(cand))
            return self._serving

    def _health_loop(self, interval: float) -> None:
        while not self._health_stop.wait(interval):
            try:
                self.refresh()
            except Exception:   # noqa: BLE001 — poller must live
                pass

    @property
    def serving_generation(self) -> Optional[int]:
        return self._serving

    def generation_age_s(self) -> Optional[float]:
        if self._serving is None:
            return None
        ts = self._table(self._serving).get("published_ts")
        if not isinstance(ts, (int, float)):
            return None
        return max(time.time() - float(ts), 0.0)

    # --------------------------------------------------------- dispatch
    def _send(
        self, shard: int, q: Dict[str, Any]
    ) -> Dict[str, Any]:
        """One sub-query to the least-loaded healthy replica of a shard;
        a transport failure or an unknown_generation answer (the replica
        pruned the pinned generation) fails over to the next replica."""
        with self._lock:
            reps = list(self._by_shard.get(shard, ()))
        if not reps:
            raise RouterError(f"no healthy replica for shard {shard}")
        last: Optional[str] = None
        for t in sorted(reps, key=lambda r: getattr(r, "depth", 0)):
            t0 = time.perf_counter()
            try:
                res = t.request(q, timeout=self.request_timeout_s)
            except Exception as e:   # noqa: BLE001 — fail over
                last = f"{type(e).__name__}: {e}"
                with self._lock:
                    self._down.add(id(t))
                    if t in self._by_shard.get(shard, ()):
                        self._by_shard[shard].remove(t)
                continue
            self._shard_lat.setdefault(shard, []).append(
                time.perf_counter() - t0
            )
            if not isinstance(res, dict):
                last = f"non-dict answer {type(res).__name__}"
                continue
            t.depth = int(res.get("depth", getattr(t, "depth", 0)))
            if res.get("error") == "unknown_generation":
                last = f"replica pruned generation {q.get('gen')}"
                continue
            pin = q.get("gen")
            if (
                pin is not None
                and "gen" in res
                and int(res["gen"]) != int(pin)
            ):
                # the tripwire the gate asserts ZERO on — an answer
                # from a generation the query was not pinned to
                self.mixed_generation += 1
            return res
        raise RouterError(
            f"every replica of shard {shard} failed: {last}"
        )

    @staticmethod
    def _strip(res: Dict[str, Any]) -> Dict[str, Any]:
        return {
            k: v for k, v in res.items()
            if k not in ("gen", "depth", "cached", "not_owner")
        }

    def _route_communities(
        self, q: Dict[str, Any], gen: int
    ) -> Dict[str, Any]:
        u = int(q["u"])
        for s in self._owners_of_raw(u, gen):
            res = self._send(
                s, {"family": "communities_of", "u": u, "gen": gen}
            )
            if not res.get("not_owner"):
                return self._strip(res)
        return {"error": f"KeyError: 'unknown node id {u}'"}

    def _route_members(
        self, q: Dict[str, Any], gen: int
    ) -> Dict[str, Any]:
        c = int(q["c"])
        parts: List[np.ndarray] = []
        for s in self._table(gen)["shard_ids"]:
            res = self._send(
                s, {"family": "members_of", "c": c, "gen": gen}
            )
            if "error" in res:
                return self._strip(res)
            parts.append(np.asarray(res.get("members", []), np.int64))
        merged = (
            np.unique(np.concatenate(parts))
            if parts else np.zeros(0, np.int64)
        )
        return {"c": c, "members": [int(u) for u in merged]}

    def _gather_rows(
        self, rows: Sequence[int], gen: int
    ) -> List[List[float]]:
        """Dense K-vectors of GLOBAL internal rows, gathered by disjoint
        row range across shards, returned in the REQUESTED order (the
        fold-in's neighbor order must match the CSR order)."""
        buckets: Dict[int, List[int]] = {}
        for i, g in enumerate(rows):
            buckets.setdefault(
                self._shard_of_row(int(g), gen), []
            ).append(i)
        out: List[Optional[List[float]]] = [None] * len(rows)
        for s, idxs in buckets.items():
            res = self._send(
                s,
                {
                    "family": "rows_of",
                    "rows": [int(rows[i]) for i in idxs],
                    "gen": gen,
                },
            )
            if res.get("error") == "overloaded":
                raise _Shed()
            if "error" in res:
                raise RouterError(
                    f"rows_of on shard {s}: {res['error']}"
                )
            for i, r in zip(idxs, res["rows"]):
                out[i] = r
        return out   # type: ignore[return-value]

    def _route_suggest(
        self, q: Dict[str, Any], gen: int
    ) -> Dict[str, Any]:
        if "neighbors" in q:
            return self._route_suggest_explicit(q, gen)
        u = int(q["u"])
        phase1 = None
        owner = None
        for s in self._owners_of_raw(u, gen):
            res = self._send(
                s, {"family": "suggest_for", "u": u, "gen": gen}
            )
            if not res.get("not_owner"):
                phase1, owner = res, s
                break
        if phase1 is None:
            return {"error": f"KeyError: 'unknown node id {u}'"}
        if "error" in phase1:
            return self._strip(phase1)
        rows = self._gather_rows(phase1.get("needs_rows", []), gen)
        res = self._send(
            owner,
            {
                "family": "suggest_rows",
                "u": u,
                "gen": gen,
                "neighbor_rows": rows,
                "own_row": phase1.get("own_row"),
            },
        )
        return self._strip(res)

    def _route_suggest_explicit(
        self, q: Dict[str, Any], gen: int
    ) -> Dict[str, Any]:
        """suggest_for with an explicit raw-id neighbor list (the
        brand-new-node path): resolve each neighbor's dense row by
        probing its owner shards, then phase 2 on the query node's owner
        (or the least-loaded first shard for a node not in the graph)."""
        raw = [int(v) for v in q["neighbors"]]
        need: Dict[int, List[int]] = {}
        for u in raw:
            for s in self._owners_of_raw(u, gen):
                need.setdefault(s, []).append(u)
        rows_by_raw: Dict[int, List[float]] = {}
        for s, ids in need.items():
            res = self._send(
                s, {"family": "rows_of", "raw": ids, "gen": gen}
            )
            for key, row in res.get("raw_rows", {}).items():
                rows_by_raw[int(key)] = row
        missing = [u for u in raw if u not in rows_by_raw]
        if missing:
            return {
                "error": f"KeyError: 'unknown node id {missing[0]}'"
            }
        own_row = None
        owner = self._table(gen)["shard_ids"][0]
        if "u" in q:
            u = int(q["u"])
            for s in self._owners_of_raw(u, gen):
                res = self._send(
                    s, {"family": "rows_of", "raw": [u], "gen": gen}
                )
                got = res.get("raw_rows", {}).get(str(u))
                if got is not None:
                    own_row, owner = got, s
                    break
        sub = {
            "family": "suggest_rows",
            "gen": gen,
            "neighbor_rows": [rows_by_raw[u] for u in raw],
            "own_row": own_row,
        }
        if "u" in q:
            sub["u"] = int(q["u"])
        return self._strip(self._send(owner, sub))

    # ---------------------------------------------------------- queries
    def route(self, q: Dict[str, Any]) -> Dict[str, Any]:
        """One fully-routed query -> one answer with the single-process
        MembershipServer's answer shape. The serving generation is
        captured HERE and pinned through every sub-query — a rollout
        mid-query cannot mix generations in one answer."""
        gen = self._serving
        if gen is None:
            return {"error": "RouterError: no serving generation"}
        fam = q.get("family") if isinstance(q, dict) else None
        t0 = time.perf_counter()
        try:
            if fam == "communities_of":
                res = self._route_communities(q, gen)
            elif fam == "members_of":
                res = self._route_members(q, gen)
            elif fam == "suggest_for":
                res = self._route_suggest(q, gen)
            else:
                res = {"error": f"KeyError: 'unknown family {fam!r}'"}
        except _Shed:
            res = {"error": "overloaded"}
        except Exception as e:   # noqa: BLE001 — per-query isolation
            res = {"error": f"{type(e).__name__}: {e}"}
        lat = time.perf_counter() - t0
        with self._lock:
            if res.get("error") == "overloaded":
                self._shed += 1
            elif "error" in res:
                self._errors += 1
            if fam in self._latencies:
                self._latencies[fam].append(lat)
            if self._t_first is None or t0 < self._t_first:
                self._t_first = t0
            end = t0 + lat
            if self._t_last is None or end > self._t_last:
                self._t_last = end
        return res

    def run_queries(
        self,
        queries: Sequence[Dict[str, Any]],
        collect: bool = True,
    ) -> List[Optional[Dict[str, Any]]]:
        """Open-loop driver (the `cli route --queries` path): fan the
        queries over the worker pool, preserve order, never raise
        per-query."""
        futures = [self._pool.submit(self.route, q) for q in queries]
        out: List[Optional[Dict[str, Any]]] = []
        for fut in futures:
            res = fut.result()
            out.append(res if collect else None)
        tel = _obs.current()
        if tel is not None:
            tel.event(
                "route",
                queries=len(queries),
                shards=len(self._by_shard),
            )
        return out

    # ------------------------------------------------------------ stats
    def reset_stats(self) -> None:
        with self._lock:
            self._latencies = {f: [] for f in FAMILIES}
            self._shard_lat = {}
            self._errors = 0
            self._shed = 0
            self._t_first = self._t_last = None

    def stats(self) -> Dict[str, Any]:
        """The router scoreboard, key-compatible with
        MembershipServer.stats() where the meaning coincides (so
        obs.ledger harvests both with one code path) plus the
        fleet-only axes: shards/replicas, per-shard latency tables, the
        rollout/mixed-generation counters, and the shed rate."""
        with self._lock:
            lats = [
                v for fam in FAMILIES for v in self._latencies[fam]
            ]
            by_family = {
                fam: len(self._latencies[fam])
                for fam in FAMILIES
                if self._latencies[fam]
            }
            shard_lat = {
                s: list(v) for s, v in self._shard_lat.items()
            }
            errors, shed = self._errors, self._shed
            t_first, t_last = self._t_first, self._t_last
            shards = len(self._by_shard)
            replicas = (
                len(
                    [
                        t for t in self.endpoints
                        if id(t) not in self._down
                    ]
                )
                // max(shards, 1)
            )
        total = len(lats)
        wall = (
            max(t_last - t_first, 1e-9)
            if t_first is not None and t_last is not None
            else 0.0
        )
        mix = "|".join(
            f"{fam}:{n / total:.2f}" for fam, n in by_family.items()
        )
        out = {
            "serve_queries": total,
            "serve_errors": errors,
            "serve_by_family": by_family,
            "serve_mix": mix,
            "serve_p50_s": _percentile(lats, 50),
            "serve_p99_s": _percentile(lats, 99),
            "serve_qps": (total / wall) if wall else None,
            "serve_shed": shed,
            "serve_shed_rate": (
                round(shed / (total + shed), 4)
                if (total + shed) else 0.0
            ),
            "serve_shards": shards,
            "serve_replicas": replicas,
            "serve_shard_stats": {
                str(s): {
                    "queries": len(v),
                    "p50_s": _percentile(v, 50),
                    "p99_s": _percentile(v, 99),
                    "qps": (
                        round(len(v) / wall, 2) if wall else None
                    ),
                }
                for s, v in sorted(shard_lat.items())
            },
            "serving_generation": self._serving,
            "snapshot_step": self._serving,
            "mixed_generation": self.mixed_generation,
            "rollouts": self.rollouts,
        }
        age = self.generation_age_s()
        if age is not None:
            out["generation_age_s"] = round(age, 3)
        for key in ("serve_p50_s", "serve_p99_s", "serve_qps"):
            if out[key] is not None:
                out[key] = round(out[key], 6)
        for st in out["serve_shard_stats"].values():
            for key in ("p50_s", "p99_s"):
                if st[key] is not None:
                    st[key] = round(st[key], 6)
        return out

    # -------------------------------------------------------- lifecycle
    def close(self) -> None:
        self._health_stop.set()
        if self._health_thread is not None:
            self._health_thread.join(timeout=10.0)
            self._health_thread = None
        self._pool.shutdown(wait=False)
        for t in self.endpoints:
            try:
                t.close()
            except Exception:   # noqa: BLE001 — best effort
                pass
