"""Request batcher with a latency budget (ISSUE 14 tentpole).

Single-query device dispatch would waste the accelerator: a fold-in step
over one row costs the same launch overhead as over 64. The batcher
coalesces requests the way the trainers coalesce edges — the first
request in an empty queue opens a WINDOW of `budget_s` seconds; the batch
flushes when either `max_batch` requests have accumulated or the window
closes, whichever comes first. Under load the batch fills instantly
(throughput mode: amortized dispatch); when idle a lone query pays at
most the budget in added latency (the p99 knob `cli serve
--latency-budget-ms` turns).

Thread model: one flusher thread; submit() is thread-safe and returns a
Future. Handler exceptions fail that batch's futures, never the thread.
`drain()` blocks until the queue is empty AND no handler is mid-flight —
the hot-swap barrier (serve.server swaps snapshots between batches, so a
swap drains in-flight batches and drops zero queries).

Admission control (ISSUE 18 tentpole): overload must degrade p99, not
OOM. Two watermarks, both off by default:

  * DEPTH — `max_depth` bounds the queue: a submit() finding the queue
    full fails its future IMMEDIATELY with OverloadedError (the caller
    gets a fast "overloaded" answer instead of a slot in an unbounded
    deque whose memory and wait time grow without limit);
  * DEADLINE — `shed_wait_s` bounds queue AGE: requests that waited
    longer than the watermark by the time their batch is taken are shed
    at flush (they would blow the latency SLO anyway; answering them
    late just steals capacity from requests that can still make it).

Shed counts (`shed_depth` / `shed_deadline`) and the live `depth()` ride
the server stats and telemetry, so an overload burst is a verdicted
shed-rate + bounded-p99 curve in the ledger (scripts/fleet_gate.py).

jax-free: pure threading + deque; the handler decides what touches a
device.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, List, Optional


class OverloadedError(RuntimeError):
    """Request shed by admission control (queue past the depth/deadline
    watermark, or the door closed for a drain). Servers map this to a
    fast {"error": "overloaded"} answer — by design the CHEAPEST
    possible response."""


class BatcherStopped(RuntimeError):
    """The batcher shut down with this request still queued (or the
    submit arrived after stop). Typed so clients can tell "server going
    down" from overload or a handler bug — a fail-fast signal, never a
    hang (ISSUE 20). Graceful shutdown that must NOT strand requests is
    close_door() + drain() + stop()."""


class Future:
    """Minimal single-assignment result slot (no concurrent.futures
    executor semantics needed — the batcher owns the lifecycle)."""

    __slots__ = ("_ev", "_value", "_error", "t_submit", "t_taken", "t_done")

    def __init__(self):
        self._ev = threading.Event()
        self._value = None
        self._error: Optional[BaseException] = None
        self.t_submit = time.perf_counter()
        # stamped when the flusher pops the request into a batch: splits
        # queue wait (submit->taken) from batch-window wait + execution
        # (taken->done) for the per-hop trace block (ISSUE 19)
        self.t_taken: Optional[float] = None
        self.t_done: Optional[float] = None

    def set_result(self, value: Any) -> None:
        self._value = value
        self.t_done = time.perf_counter()
        self._ev.set()

    def set_error(self, error: BaseException) -> None:
        self._error = error
        self.t_done = time.perf_counter()
        self._ev.set()

    def done(self) -> bool:
        return self._ev.is_set()

    def result(self, timeout: Optional[float] = None) -> Any:
        if not self._ev.wait(timeout):
            raise TimeoutError("request not completed within timeout")
        if self._error is not None:
            raise self._error
        return self._value

    @property
    def latency_s(self) -> Optional[float]:
        return (
            None if self.t_done is None else self.t_done - self.t_submit
        )


class Request:
    __slots__ = ("payload", "future")

    def __init__(self, payload: Any):
        self.payload = payload
        self.future = Future()


class RequestBatcher:
    """See module docstring. handler(batch: List[Request]) must set every
    request's future (the batcher backstops: an unset future after a
    clean handler return gets a RuntimeError, and a handler exception
    fails every still-unset future in the batch)."""

    def __init__(
        self,
        handler: Callable[[List[Request]], None],
        max_batch: int = 64,
        budget_s: float = 0.005,
        max_depth: int = 0,
        shed_wait_s: float = 0.0,
    ):
        self.handler = handler
        self.max_batch = max(int(max_batch), 1)
        self.budget_s = max(float(budget_s), 0.0)
        # admission control (module docstring): 0 = unbounded/off
        self.max_depth = max(int(max_depth), 0)
        self.shed_wait_s = max(float(shed_wait_s), 0.0)
        self._q: deque = deque()
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._inflight = 0
        self._stop = False
        self._door_closed = False
        self._thread: Optional[threading.Thread] = None
        self.batches = 0
        self.flushed_full = 0       # batches flushed by max_batch
        self.flushed_deadline = 0   # batches flushed by the budget window
        self.shed_depth = 0         # submits rejected at the depth bound
        self.shed_deadline = 0      # requests shed stale at flush
        self.shed_door = 0          # submits rejected while draining
        self.depth_peak = 0         # high-water queue depth observed

    # ------------------------------------------------------- lifecycle
    def start(self) -> "RequestBatcher":
        if self._thread is None:
            self._stop = False
            self._thread = threading.Thread(
                target=self._loop, name="bigclam-serve-batcher", daemon=True
            )
            self._thread.start()
        return self

    def stop(self, timeout: float = 30.0) -> None:
        """Fail-fast shutdown: everything still QUEUED fails immediately
        with BatcherStopped — before the flusher is joined, so a wedged
        handler can never hold stranded futures hostage. The batch the
        handler is currently executing finishes normally (its futures
        belong to the handler). Callers that must not strand requests
        drain first: close_door() + drain() + stop()."""
        with self._cond:
            self._stop = True
            stranded = list(self._q)
            self._q.clear()
            self._cond.notify_all()
        for req in stranded:
            if not req.future.done():
                req.future.set_error(
                    BatcherStopped(
                        f"batcher stopped with {len(stranded)} "
                        "request(s) queued"
                    )
                )
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None

    def close_door(self) -> None:
        """Stop admitting: every later submit() sheds fast with
        OverloadedError. This is the drain protocol's first step
        (ISSUE 20) — already-queued and in-flight requests finish
        normally, drain() then observes a quiescent batcher, stop()
        finds nothing to strand."""
        with self._cond:
            self._door_closed = True

    @property
    def draining(self) -> bool:
        return self._door_closed

    # --------------------------------------------------------- clients
    def submit(self, payload: Any) -> Future:
        req = Request(payload)
        with self._cond:
            if self._stop or self._thread is None:
                raise BatcherStopped("batcher is not running")
            if self._door_closed:
                self.shed_door += 1
                req.future.set_error(
                    OverloadedError("admission door closed (draining)")
                )
                return req.future
            if self.max_depth and len(self._q) >= self.max_depth:
                # shed at the door (depth watermark): the future fails
                # NOW — callers see the same Future surface either way
                self.shed_depth += 1
                req.future.set_error(
                    OverloadedError(
                        f"queue depth {len(self._q)} at the "
                        f"max_depth={self.max_depth} watermark"
                    )
                )
                return req.future
            self._q.append(req)
            if len(self._q) > self.depth_peak:
                self.depth_peak = len(self._q)
            self._cond.notify_all()
        return req.future

    def depth(self) -> int:
        """Live queue depth (requests admitted, not yet taken into a
        batch) — the number heartbeat stall events and serve telemetry
        embed."""
        with self._lock:
            return len(self._q)

    def pending_payloads(self) -> List[Any]:
        """Snapshot of the queued payloads (per-family depth metrics —
        the server buckets them; O(depth) under the lock, called once
        per flushed batch)."""
        with self._lock:
            return [r.payload for r in self._q]

    @property
    def shed(self) -> int:
        return self.shed_depth + self.shed_deadline + self.shed_door

    def drain(self, timeout: float = 60.0) -> None:
        """Block until the queue is empty and no batch is executing —
        the hot-swap barrier. Requests submitted DURING a drain simply
        extend it; nothing is rejected or dropped."""
        deadline = time.perf_counter() + timeout
        with self._cond:
            while self._q or self._inflight:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    raise TimeoutError("batcher did not drain in time")
                self._cond.wait(remaining)

    # ----------------------------------------------------------- flush
    def _take_batch_locked(self) -> List[Request]:
        batch = []
        now = time.perf_counter()
        while self._q and len(batch) < self.max_batch:
            req = self._q.popleft()
            req.future.t_taken = now
            batch.append(req)
        return batch

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._q and not self._stop:
                    self._cond.wait()
                if self._stop and not self._q:
                    return
                # window opens at the first queued request; fill until
                # max_batch or the deadline
                deadline = time.perf_counter() + self.budget_s
                while (
                    len(self._q) < self.max_batch and not self._stop
                ):
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        break
                    self._cond.wait(remaining)
                full = len(self._q) >= self.max_batch
                batch = self._take_batch_locked()
                if not batch:
                    # stop() failed + cleared the queue out from under
                    # the open window — nothing left to execute
                    return
                self._inflight += 1
                self.batches += 1
                if full:
                    self.flushed_full += 1
                else:
                    self.flushed_deadline += 1
            if self.shed_wait_s > 0.0:
                # deadline watermark: requests older than shed_wait_s by
                # flush time would blow the SLO anyway — shed them fast
                # and spend the batch slot on requests that can make it
                now = time.perf_counter()
                fresh: List[Request] = []
                for req in batch:
                    if now - req.future.t_submit > self.shed_wait_s:
                        self.shed_deadline += 1
                        req.future.set_error(
                            OverloadedError(
                                "request waited past the "
                                f"shed_wait_s={self.shed_wait_s:.3f} "
                                "watermark"
                            )
                        )
                    else:
                        fresh.append(req)
                batch = fresh
                if not batch:
                    with self._cond:
                        self._inflight -= 1
                        self._cond.notify_all()
                    continue
            try:
                self.handler(batch)
                for req in batch:
                    if not req.future.done():
                        req.future.set_error(
                            RuntimeError("handler left request unanswered")
                        )
            except BaseException as e:   # noqa: BLE001 — thread must live
                for req in batch:
                    if not req.future.done():
                        req.future.set_error(e)
            finally:
                with self._cond:
                    self._inflight -= 1
                    self._cond.notify_all()
