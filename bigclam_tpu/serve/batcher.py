"""Request batcher with a latency budget (ISSUE 14 tentpole).

Single-query device dispatch would waste the accelerator: a fold-in step
over one row costs the same launch overhead as over 64. The batcher
coalesces requests the way the trainers coalesce edges — the first
request in an empty queue opens a WINDOW of `budget_s` seconds; the batch
flushes when either `max_batch` requests have accumulated or the window
closes, whichever comes first. Under load the batch fills instantly
(throughput mode: amortized dispatch); when idle a lone query pays at
most the budget in added latency (the p99 knob `cli serve
--latency-budget-ms` turns).

Thread model: one flusher thread; submit() is thread-safe and returns a
Future. Handler exceptions fail that batch's futures, never the thread.
`drain()` blocks until the queue is empty AND no handler is mid-flight —
the hot-swap barrier (serve.server swaps snapshots between batches, so a
swap drains in-flight batches and drops zero queries).

jax-free: pure threading + deque; the handler decides what touches a
device.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, List, Optional


class Future:
    """Minimal single-assignment result slot (no concurrent.futures
    executor semantics needed — the batcher owns the lifecycle)."""

    __slots__ = ("_ev", "_value", "_error", "t_submit", "t_done")

    def __init__(self):
        self._ev = threading.Event()
        self._value = None
        self._error: Optional[BaseException] = None
        self.t_submit = time.perf_counter()
        self.t_done: Optional[float] = None

    def set_result(self, value: Any) -> None:
        self._value = value
        self.t_done = time.perf_counter()
        self._ev.set()

    def set_error(self, error: BaseException) -> None:
        self._error = error
        self.t_done = time.perf_counter()
        self._ev.set()

    def done(self) -> bool:
        return self._ev.is_set()

    def result(self, timeout: Optional[float] = None) -> Any:
        if not self._ev.wait(timeout):
            raise TimeoutError("request not completed within timeout")
        if self._error is not None:
            raise self._error
        return self._value

    @property
    def latency_s(self) -> Optional[float]:
        return (
            None if self.t_done is None else self.t_done - self.t_submit
        )


class Request:
    __slots__ = ("payload", "future")

    def __init__(self, payload: Any):
        self.payload = payload
        self.future = Future()


class RequestBatcher:
    """See module docstring. handler(batch: List[Request]) must set every
    request's future (the batcher backstops: an unset future after a
    clean handler return gets a RuntimeError, and a handler exception
    fails every still-unset future in the batch)."""

    def __init__(
        self,
        handler: Callable[[List[Request]], None],
        max_batch: int = 64,
        budget_s: float = 0.005,
    ):
        self.handler = handler
        self.max_batch = max(int(max_batch), 1)
        self.budget_s = max(float(budget_s), 0.0)
        self._q: deque = deque()
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._inflight = 0
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self.batches = 0
        self.flushed_full = 0       # batches flushed by max_batch
        self.flushed_deadline = 0   # batches flushed by the budget window

    # ------------------------------------------------------- lifecycle
    def start(self) -> "RequestBatcher":
        if self._thread is None:
            self._stop = False
            self._thread = threading.Thread(
                target=self._loop, name="bigclam-serve-batcher", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None
        # fail anything still queued (stop during load is a caller bug,
        # but futures must never hang)
        while self._q:
            req = self._q.popleft()
            if not req.future.done():
                req.future.set_error(
                    RuntimeError("batcher stopped with request queued")
                )

    # --------------------------------------------------------- clients
    def submit(self, payload: Any) -> Future:
        req = Request(payload)
        with self._cond:
            if self._stop or self._thread is None:
                raise RuntimeError("batcher is not running")
            self._q.append(req)
            self._cond.notify_all()
        return req.future

    def drain(self, timeout: float = 60.0) -> None:
        """Block until the queue is empty and no batch is executing —
        the hot-swap barrier. Requests submitted DURING a drain simply
        extend it; nothing is rejected or dropped."""
        deadline = time.perf_counter() + timeout
        with self._cond:
            while self._q or self._inflight:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    raise TimeoutError("batcher did not drain in time")
                self._cond.wait(remaining)

    # ----------------------------------------------------------- flush
    def _take_batch_locked(self) -> List[Request]:
        batch = []
        while self._q and len(batch) < self.max_batch:
            batch.append(self._q.popleft())
        return batch

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._q and not self._stop:
                    self._cond.wait()
                if self._stop and not self._q:
                    return
                # window opens at the first queued request; fill until
                # max_batch or the deadline
                deadline = time.perf_counter() + self.budget_s
                while (
                    len(self._q) < self.max_batch and not self._stop
                ):
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        break
                    self._cond.wait(remaining)
                full = len(self._q) >= self.max_batch
                batch = self._take_batch_locked()
                self._inflight += 1
                self.batches += 1
                if full:
                    self.flushed_full += 1
                else:
                    self.flushed_deadline += 1
            try:
                self.handler(batch)
                for req in batch:
                    if not req.future.done():
                        req.future.set_error(
                            RuntimeError("handler left request unanswered")
                        )
            except BaseException as e:   # noqa: BLE001 — thread must live
                for req in batch:
                    if not req.future.done():
                        req.future.set_error(e)
            finally:
                with self._cond:
                    self._inflight -= 1
                    self._cond.notify_all()
