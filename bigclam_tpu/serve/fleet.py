"""The serving fleet's replica side (ISSUE 18 tentpole).

A fleet generation is one `publish_fleet_snapshot` publication: per-shard
row-range archives plus a manifest (utils.checkpoint.publish_fleet_next —
the same exclusive-lock monotonic counter as single-archive publishes).
This module is the process that HOLDS one such shard:

  * `ShardReplica` — loads shard s of the latest fleet generation,
    answers the routed sub-query protocol (below), and can hold up to
    two generations at once so a fleet-wide rollout never drops an
    in-flight query: the router keeps pinning generation g until every
    replica of every shard reports g+1 loaded, then flips — queries
    pinned to g keep answering from the retained g snapshot.
  * `ReplicaServer` — a JSON-lines-over-TCP front (one request dict per
    line, one answer dict per line) feeding a RequestBatcher with
    admission control; every answer piggybacks the live queue `depth`
    so the router's pick-least-loaded dispatch needs no extra probe.
  * `LocalReplica` — the same `.request()` transport surface with no
    socket (unit tests and single-process drills); answers round-trip
    through json to enforce the wire contract.

Sub-query protocol (all answers echo `gen` — the generation that
actually answered, the router's mixed-generation tripwire):

  status                          -> shard, generations held, depth
  communities_of u gen            -> membership read, or {"not_owner"}
  members_of c gen                -> THIS shard's member raw ids (the
                                     router merges across shards)
  rows_of rows=[global rows] gen  -> dense K-vectors (fleet suggest's
                                     neighbor-row gather; global
                                     internal row ranges are disjoint
                                     by construction, so each row has
                                     exactly one owner)
  rows_of raw=[raw ids] gen       -> {raw id: K-vector} for ids this
                                     shard owns (probe semantics)
  suggest_for u gen               -> phase 1: the owner returns the
                                     neighbor GLOBAL row ids + its own
                                     row ({"needs_rows", "own_row"})
  suggest_rows ... gen            -> phase 2: fold-in over the
                                     router-gathered neighbor rows
                                     against the GLOBAL sumF — the only
                                     jax-touching op, lazy per
                                     generation

jax-free at import; FoldInEngine is built lazily on the first
suggest_rows of a generation (serve.server semantics, same engine).
"""

from __future__ import annotations

import json
import os
import socketserver
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from bigclam_tpu.resilience.faults import apply_wire_fault, maybe_fire
from bigclam_tpu.serve.batcher import (
    OverloadedError,
    Request,
    RequestBatcher,
)
from bigclam_tpu.serve.server import HotCommunityCache
from bigclam_tpu.serve.snapshot import (
    ServingSnapshot,
    SnapshotError,
    load_fleet_shard,
)
from bigclam_tpu.utils.checkpoint import CheckpointManager

# current + next: enough for a barrier-free rollout (queries pin at most
# one generation back), small enough that a replica's RAM is ~2 shards
MAX_HELD_GENERATIONS = 2


class ShardReplica:
    """One shard's query brain (see module docstring). Thread-safe:
    answer() may be called from many transport threads; generation
    installs swap immutable ServingSnapshot objects under a lock."""

    def __init__(
        self,
        snapshot_dir: str,
        shard: int,
        store=None,
        cache_slots: int = 64,
        foldin_max_iters: int = 200,
        foldin_conv_tol: Optional[float] = None,
        foldin_max_deg: int = 4096,
        watch_interval_s: float = 0.0,
        step: Optional[int] = None,
    ):
        self.snapshot_dir = snapshot_dir
        self.shard = int(shard)
        self._store = store
        self._cache_slots = int(cache_slots)
        self._foldin_max_iters = foldin_max_iters
        self._foldin_conv_tol = foldin_conv_tol
        self._foldin_max_deg = int(foldin_max_deg)
        self._lock = threading.RLock()
        self._gens: Dict[int, ServingSnapshot] = {}
        self._caches: Dict[int, HotCommunityCache] = {}
        self._engines: Dict[int, Any] = {}
        self._adj: Optional[Tuple[Tuple[int, int], Any]] = None
        self.queries = 0
        self.errors = 0
        self.truncated = 0
        self._install(load_fleet_shard(snapshot_dir, self.shard, step=step))
        self._watch_stop = threading.Event()
        self._watcher: Optional[threading.Thread] = None
        if watch_interval_s > 0:
            self._watcher = threading.Thread(
                target=self._watch_loop,
                args=(float(watch_interval_s),),
                name=f"bigclam-fleet-watch-s{self.shard}",
                daemon=True,
            )
            self._watcher.start()

    # ------------------------------------------------------ generations
    def _install(self, snap: ServingSnapshot) -> int:
        with self._lock:
            self._gens[snap.step] = snap
            cache = HotCommunityCache(self._cache_slots)
            cache.reset(snap)
            self._caches[snap.step] = cache
            while len(self._gens) > MAX_HELD_GENERATIONS:
                dead = min(self._gens)
                del self._gens[dead]
                self._caches.pop(dead, None)
                self._engines.pop(dead, None)
        return snap.step

    @property
    def generations(self) -> List[int]:
        with self._lock:
            return sorted(self._gens)

    def maybe_load_next(self) -> Optional[int]:
        """Load the newest published fleet generation if it is newer
        than everything held (the watcher's poll — never backward, same
        contract as MembershipServer.maybe_reload). Holding BOTH the
        old and new generation is the point: the router only flips once
        every replica holds the new one."""
        latest = CheckpointManager(self.snapshot_dir).latest_fleet()
        with self._lock:
            head = max(self._gens) if self._gens else -1
        if latest is None or latest <= head:
            return None
        return self._install(
            load_fleet_shard(self.snapshot_dir, self.shard, step=latest)
        )

    def _watch_loop(self, interval: float) -> None:
        while not self._watch_stop.wait(interval):
            try:
                self.maybe_load_next()
            except Exception:   # noqa: BLE001 — outlive torn publishes
                pass

    def close(self) -> None:
        self._watch_stop.set()
        if self._watcher is not None:
            self._watcher.join(timeout=10.0)
            self._watcher = None

    # -------------------------------------------------------- adjacency
    def _adjacency(self, snap: ServingSnapshot):
        """Local CSR over this shard's global row range [lo, lo+n) —
        cache shards covering the range, assembled once and reused
        across generations with the same range."""
        key = (snap.lo, snap.lo + snap.n)
        if self._adj is not None and self._adj[0] == key:
            return self._adj[1]
        if self._store is None:
            raise SnapshotError(
                "fleet suggest_for needs adjacency — start the replica "
                "with the graph store (`cli serve --fleet ... <cache>`)"
            )
        lo, hi = key
        S = self._store.num_shards
        first = next(
            s for s in range(S) if self._store.node_range(s)[1] > lo
        )
        last = (
            next(
                s for s in range(S - 1, -1, -1)
                if self._store.node_range(s)[0] < hi
            )
            + 1
        )
        hs = self._store.load_shard_range(first, last)
        if hs.lo > lo or hs.hi < hi:
            raise SnapshotError(
                f"cache shards [{first}, {last}) cover [{hs.lo}, {hs.hi}) "
                f"— does not contain the fleet shard range [{lo}, {hi})"
            )
        self._adj = (key, hs)
        return hs

    # ------------------------------------------------------------ reads
    @staticmethod
    def _dense_row(snap: ServingSnapshot, row: int) -> np.ndarray:
        if snap.representation == "dense":
            return np.asarray(snap.F[row, : snap.k], dtype=snap.sumF.dtype)
        r = np.zeros(snap.k, snap.sumF.dtype)
        valid = snap.ids[row] < snap.k
        r[snap.ids[row][valid].astype(np.int64)] = snap.w[row][valid]
        return r

    def _engine_for(self, snap: ServingSnapshot):
        with self._lock:
            eng = self._engines.get(snap.step)
            if eng is None:
                from bigclam_tpu.serve.server import FoldInEngine

                eng = FoldInEngine(
                    snap,
                    max_iters=self._foldin_max_iters,
                    conv_tol=self._foldin_conv_tol,
                )
                self._engines[snap.step] = eng
        return eng

    def status(self) -> Dict[str, Any]:
        with self._lock:
            steps = sorted(self._gens)
            head = self._gens[steps[-1]] if steps else None
        out = {
            "shard": self.shard,
            "generations": steps,
            "queries": self.queries,
            "errors": self.errors,
        }
        if head is not None:
            out["lo"] = head.lo
            out["hi"] = head.lo + head.n
            age = head.age_s()
            if age is not None:
                out["gen_age_s"] = round(age, 3)
        return out

    # ---------------------------------------------------------- answer
    def answer(self, q: Dict[str, Any]) -> Dict[str, Any]:
        """One routed sub-query -> one answer dict; per-query failures
        come back as {"error": ...}, never exceptions (the transport
        thread and the batcher must outlive any bad query)."""
        self.queries += 1
        try:
            return self._answer(q if isinstance(q, dict) else {})
        except Exception as e:   # noqa: BLE001 — per-query isolation
            self.errors += 1
            return {"error": f"{type(e).__name__}: {e}"}

    def _answer(self, q: Dict[str, Any]) -> Dict[str, Any]:
        fam = q.get("family")
        if fam == "status":
            return self.status()
        with self._lock:
            gens = dict(self._gens)
        gen = q.get("gen")
        step = int(gen) if gen is not None else max(gens)
        snap = gens.get(step)
        if snap is None:
            # the router retries another replica that still holds the
            # pinned generation — this is a signal, not a failure
            return {"error": "unknown_generation", "gen": step}
        if fam == "communities_of":
            try:
                row = snap.row_of(int(q["u"]))
            except KeyError:
                return {"not_owner": True, "gen": step}
            cids, weights = snap.communities_of(row)
            return {
                "u": int(q["u"]),
                "communities": [
                    [int(c), float(v)] for c, v in zip(cids, weights)
                ],
                "gen": step,
            }
        if fam == "members_of":
            c = int(q["c"])
            cache = self._caches.get(step)
            members = cache.get(c) if cache is not None else None
            if members is None:
                members = snap.members_of(c)
                if cache is not None:
                    cache.put(c, members)
            return {
                "c": c,
                "members": [int(u) for u in members],
                "gen": step,
            }
        if fam == "rows_of":
            if "rows" in q:
                lo, hi = snap.lo, snap.lo + snap.n
                rows = []
                for g in q["rows"]:
                    g = int(g)
                    if not lo <= g < hi:
                        return {
                            "error": (
                                f"row {g} outside shard range [{lo}, {hi})"
                            ),
                            "gen": step,
                        }
                    rows.append(
                        [float(v) for v in self._dense_row(snap, g - lo)]
                    )
                return {"rows": rows, "gen": step}
            raw_rows = {}
            for u in q.get("raw", []):
                try:
                    row = snap.row_of(int(u))
                except KeyError:
                    continue
                raw_rows[str(int(u))] = [
                    float(v) for v in self._dense_row(snap, row)
                ]
            return {"raw_rows": raw_rows, "gen": step}
        if fam == "suggest_for":
            try:
                row = snap.row_of(int(q["u"]))
            except KeyError:
                return {"not_owner": True, "gen": step}
            hs = self._adjacency(snap)
            g = snap.lo + row
            a = int(hs.indptr[g - hs.lo])
            b = int(hs.indptr[g - hs.lo + 1])
            if b - a > self._foldin_max_deg:
                self.truncated += 1
                b = a + self._foldin_max_deg
            return {
                "u": int(q["u"]),
                # neighbor GLOBAL internal rows in CSR order — the
                # router gathers their dense rows by disjoint row range
                # and resends as suggest_rows (order preserved, so the
                # fold-in matches the single-process batch exactly)
                "needs_rows": [int(v) for v in hs.indices[a:b]],
                "own_row": [
                    float(v) for v in self._dense_row(snap, row)
                ],
                "gen": step,
            }
        if fam == "suggest_rows":
            engine = self._engine_for(snap)
            nbr = np.asarray(
                q.get("neighbor_rows", []), snap.sumF.dtype
            ).reshape(-1, snap.k)
            own = q.get("own_row")
            own_row = (
                np.asarray(own, snap.sumF.dtype) if own is not None
                else None
            )
            res = engine.suggest_batch_rows([(nbr, own_row)])[0]
            if "u" in q:
                res = {"u": int(q["u"]), **res}
            res["gen"] = step
            return res
        return {"error": f"unknown family {fam!r}"}


class LocalReplica:
    """In-process transport: the TcpReplica `.request()` surface with no
    socket. Answers round-trip through json so unit tests exercise the
    exact wire contract the TCP path serializes."""

    def __init__(self, replica: ShardReplica):
        self.replica = replica
        self.shard = replica.shard
        self.depth = 0

    def request(
        self,
        q: Dict[str, Any],
        timeout: Optional[float] = None,
        handle: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        traced = isinstance(q, dict) and q.get("trace")
        t0 = time.perf_counter()
        res = self.replica.answer(q)
        if traced and isinstance(res, dict):
            # no socket, no batcher: decode/queue/batch hops are zero
            # by construction, execute is the whole replica-side time —
            # the same compact hop block as ReplicaServer (integer
            # microseconds [decode, queue, batch_wait, execute,
            # replica]) so single-process tests exercise full trace
            # assembly
            us = int((time.perf_counter() - t0) * 1e6 + 0.5)
            res = dict(res)
            res["hops"] = [0, 0, 0, us, us]
        return json.loads(json.dumps(res))

    def cancel(self, handle: Dict[str, Any]) -> None:
        """No socket to shut down — hedged in-process losers just finish
        and get ignored (the TcpReplica surface, for hedging tests)."""
        handle["cancelled"] = True

    def close(self) -> None:
        pass


class ReplicaServer:
    """JSON-lines TCP front of one ShardReplica: one request dict per
    line in, one answer dict per line out, every answer piggybacking the
    live queue `depth`. Query ops flow through a RequestBatcher WITH
    admission control (serve.batcher watermarks) — an overload burst
    sheds fast `{"error": "overloaded"}` answers instead of growing an
    unbounded queue; `status`/`stop` bypass the batcher (health checks
    must answer even when the query queue is saturated).

    Distributed tracing (ISSUE 19): a sub-query carrying the router's
    `trace` marker gets a compact `hops` timing block on its answer —
    an integer-microsecond array [decode, queue, batch_wait, execute,
    replica]: decode (transport json decode), queue (deque wait until
    the batch flushed), batch_wait (intra-batch serialization behind
    batch-mates), execute (ShardReplica.answer), replica (receipt to
    answer, the wire-vs-replica split the router subtracts). Integers,
    not named floats: the block rides EVERY traced answer, and the
    tracing overhead pin (<2% of routed wall, scripts/qtrace_gate.py)
    is won or lost on wire bytes + float formatting — the router
    expands it to named `*_s` seconds at assembly. The block exists
    ONLY on traced requests: untraced answers are byte-identical to
    pre-trace builds (the off-path contract), and the router strips
    `hops` with the other transport fields before returning answers to
    callers.

    Fault injection (scripts/qtrace_gate.py): the BIGCLAM_QTRACE_FAULT
    env var — a JSON object {"hop": "execute"|"decode", "delay_s": X}
    — plants a delay into the named hop of THIS replica, so the gate
    can prove a planted slowdown is attributed to the right (shard,
    hop) and that a clean run attributes nothing."""

    def __init__(
        self,
        replica: ShardReplica,
        host: str = "127.0.0.1",
        port: int = 0,
        max_batch: int = 64,
        budget_s: float = 0.002,
        max_queue_depth: int = 0,
        shed_wait_s: float = 0.0,
    ):
        self.replica = replica
        # fleet-member identity (supervisor-assigned via env): fault
        # specs match on it, so a chaos drill can hit ONE slot of a
        # fleet that shares a single BIGCLAM_FAULTS env
        self.member = os.environ.get("BIGCLAM_FLEET_MEMBER", "")
        self._batcher = RequestBatcher(
            self._handle,
            max_batch=max_batch,
            budget_s=budget_s,
            max_depth=max_queue_depth,
            shed_wait_s=shed_wait_s,
        ).start()
        self._stopped = threading.Event()
        self._fault_hop = None
        self._fault_delay_s = 0.0
        fault = os.environ.get("BIGCLAM_QTRACE_FAULT")
        if fault:
            try:
                fobj = json.loads(fault)
                self._fault_hop = str(fobj.get("hop", "execute"))
                self._fault_delay_s = max(float(fobj.get("delay_s", 0.0)), 0.0)
            except (ValueError, TypeError):
                pass
        outer = self

        class _Handler(socketserver.StreamRequestHandler):
            def handle(self):
                for line in self.rfile:
                    line = line.strip()
                    if not line:
                        continue
                    t_recv = time.perf_counter()
                    if outer._fault_hop == "decode" and outer._fault_delay_s:
                        time.sleep(outer._fault_delay_s)
                    q = None
                    try:
                        q = json.loads(line)
                    except ValueError:
                        res = {"error": "bad json"}
                    else:
                        res = outer._dispatch(
                            q,
                            t_recv=t_recv,
                            decode_s=time.perf_counter() - t_recv,
                        )
                    fam = q.get("family") if isinstance(q, dict) else None
                    payload = (json.dumps(res) + "\n").encode()
                    try:
                        wired = None
                        if fam not in ("status", "stop", "drain"):
                            # the wire-fault chokepoint (ISSUE 20): every
                            # QUERY answer frame passes here; control ops
                            # are exempt so health checks and teardown
                            # stay drillable under an active fault plan
                            spec = maybe_fire(
                                "replica.answer_write",
                                family=str(fam),
                                shard=outer.replica.shard,
                                member=outer.member,
                            )
                            if spec is not None:
                                wired = apply_wire_fault(
                                    spec, self.wfile, payload
                                )
                        if wired == "close":
                            return   # torn frame: hang up mid-answer
                        if wired != "skip":
                            self.wfile.write(payload)
                            self.wfile.flush()
                    except OSError:
                        return       # client went away mid-answer
                    if fam in ("stop", "drain"):
                        # shutdown AFTER the ack is flushed (and from a
                        # fresh thread — shutdown() deadlocks called
                        # from a handler): acking first is what keeps
                        # `route --stop` from racing the process exit
                        # and miscounting a clean stop as unreachable.
                        # drain and stop share the teardown: close()
                        # shuts the admission door, drains in-flight,
                        # then stops — the zero-drop part of a DRAIN is
                        # the protocol around it (the supervisor flips
                        # membership and waits the router-reload grace
                        # BEFORE sending this op).
                        threading.Thread(
                            target=outer.close, daemon=True
                        ).start()
                        return

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True
            # an overload burst churns router connections (pool-capped
            # clients reconnect constantly) — the default backlog of 5
            # turns that into SYN-retransmit latency spikes
            request_queue_size = 128

        self._srv = _Server((host, int(port)), _Handler)
        self.host, self.port = self._srv.server_address[:2]
        self._thread = threading.Thread(
            target=self._srv.serve_forever,
            name=f"bigclam-replica-s{replica.shard}",
            daemon=True,
        )
        self._thread.start()

    # --------------------------------------------------------- dispatch
    def _handle(self, batch: List[Request]) -> None:
        for req in batch:
            traced = (
                isinstance(req.payload, dict) and req.payload.get("trace")
            )
            if not traced:
                if self._fault_hop == "execute" and self._fault_delay_s:
                    time.sleep(self._fault_delay_s)
                req.future.set_result(self.replica.answer(req.payload))
                continue
            # execute hop + intra-batch serialization wait: this loop
            # runs the batch serially, so a request's batch_wait is the
            # gap between the batch being taken and ITS answer starting
            t0 = time.perf_counter()
            if self._fault_hop == "execute" and self._fault_delay_s:
                # inside the timed window: the planted fault must be
                # ATTRIBUTED to the execute hop, that is what the gate
                # proves
                time.sleep(self._fault_delay_s)
            res = self.replica.answer(req.payload)
            if isinstance(res, dict):
                taken = req.future.t_taken
                # seconds here; _dispatch converts the assembled block
                # to the compact integer-microsecond wire form
                res["hops"] = (
                    t0 - (taken if taken is not None else t0),
                    time.perf_counter() - t0,
                )
            req.future.set_result(res)

    def _dispatch(
        self,
        q: Dict[str, Any],
        t_recv: Optional[float] = None,
        decode_s: float = 0.0,
    ) -> Dict[str, Any]:
        fam = q.get("family") if isinstance(q, dict) else None
        if fam == "status":
            st = self.replica.status()
            st["depth"] = self._batcher.depth()
            st["shed"] = self._batcher.shed
            st["depth_peak"] = self._batcher.depth_peak
            if self._batcher.draining:
                st["draining"] = True
            return st
        if fam == "stop":
            # the HANDLER schedules close() after flushing this ack
            return {"ok": True}
        if fam == "drain":
            # same teardown as stop (the handler schedules close());
            # the distinct op exists so the supervisor's drain protocol
            # reads as intent on the wire and in logs
            return {"ok": True, "draining": True}
        fut = None
        try:
            fut = self._batcher.submit(q)
            res = fut.result(60.0)
        except OverloadedError:
            res = {"error": "overloaded"}
        except Exception as e:   # noqa: BLE001 — transport must live
            res = {"error": f"{type(e).__name__}: {e}"}
        if isinstance(res, dict):
            res.setdefault("depth", self._batcher.depth())
            if isinstance(q, dict) and q.get("trace"):
                bw, ex = res.pop("hops", None) or (0.0, 0.0)
                queue_s = (
                    fut.t_taken - fut.t_submit
                    if fut is not None and fut.t_taken is not None
                    else 0.0
                )
                replica_s = (
                    time.perf_counter() - t_recv
                    if t_recv is not None else 0.0
                )
                # compact wire form: integer microseconds
                # [decode, queue, batch_wait, execute, replica]
                res["hops"] = [
                    int(decode_s * 1e6 + 0.5),
                    int(queue_s * 1e6 + 0.5),
                    int(bw * 1e6 + 0.5),
                    int(ex * 1e6 + 0.5),
                    int(replica_s * 1e6 + 0.5),
                ]
        return res

    # -------------------------------------------------------- lifecycle
    def serve_until_stopped(
        self, timeout: Optional[float] = None
    ) -> bool:
        """Block until a `stop` op arrives (the replica-process main
        loop of `cli serve --fleet --listen`)."""
        return self._stopped.wait(timeout)

    def close(self) -> None:
        if self._stopped.is_set():
            return
        self._stopped.set()
        self._srv.shutdown()
        self._srv.server_close()
        # graceful order (ISSUE 20): door first (late submits shed fast
        # instead of hanging), drain what was admitted (zero drops),
        # THEN stop — stop() alone fail-fasts queued futures with
        # BatcherStopped, which is the crash path, not this one
        self._batcher.close_door()
        try:
            self._batcher.drain(timeout=30.0)
        except TimeoutError:
            pass
        self._batcher.stop()
        self.replica.close()
