"""Membership serving (ISSUE 14): batched fold-in inference, snapshot
hot-swap, and the query surface behind `cli serve`.

LAZY attribute re-exports (PEP 562, same rationale as bigclam_tpu.ops):
the package init must not decide for its submodules what gets imported —
`cli serve` answering only membership reads stays jax-free end to end
(serve.snapshot / serve.batcher / serve.server import no jax at module
scope; the FoldInEngine pulls jax on the first suggest query).
"""

_LAZY = {
    "FOLDIN_CFG_FIELDS": (
        "bigclam_tpu.serve.snapshot", "FOLDIN_CFG_FIELDS",
    ),
    "ServingSnapshot": ("bigclam_tpu.serve.snapshot", "ServingSnapshot"),
    "SnapshotError": ("bigclam_tpu.serve.snapshot", "SnapshotError"),
    "pad_neighbor_batch": (
        "bigclam_tpu.serve.snapshot", "pad_neighbor_batch",
    ),
    "publish_snapshot": (
        "bigclam_tpu.serve.snapshot", "publish_snapshot",
    ),
    "publish_fleet_snapshot": (
        "bigclam_tpu.serve.snapshot", "publish_fleet_snapshot",
    ),
    "load_fleet_shard": (
        "bigclam_tpu.serve.snapshot", "load_fleet_shard",
    ),
    "Future": ("bigclam_tpu.serve.batcher", "Future"),
    "OverloadedError": ("bigclam_tpu.serve.batcher", "OverloadedError"),
    "RequestBatcher": ("bigclam_tpu.serve.batcher", "RequestBatcher"),
    "ShardReplica": ("bigclam_tpu.serve.fleet", "ShardReplica"),
    "ReplicaServer": ("bigclam_tpu.serve.fleet", "ReplicaServer"),
    "LocalReplica": ("bigclam_tpu.serve.fleet", "LocalReplica"),
    "FleetRouter": ("bigclam_tpu.serve.router", "FleetRouter"),
    "TcpReplica": ("bigclam_tpu.serve.router", "TcpReplica"),
    "RouterError": ("bigclam_tpu.serve.router", "RouterError"),
    "FAMILIES": ("bigclam_tpu.serve.server", "FAMILIES"),
    "FoldInEngine": ("bigclam_tpu.serve.server", "FoldInEngine"),
    "HotCommunityCache": (
        "bigclam_tpu.serve.server", "HotCommunityCache",
    ),
    "MembershipServer": ("bigclam_tpu.serve.server", "MembershipServer"),
}

__all__ = sorted(_LAZY)


def __getattr__(name):
    try:
        mod_name, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        ) from None
    import importlib

    return getattr(importlib.import_module(mod_name), attr)


def __dir__():
    return sorted(set(globals()) | set(_LAZY))
