"""FleetSupervisor: the serving fleet's process parent (ISSUE 20).

PR 5 gave the TRAINING path a failure model — supervisor resume, the
transient/fatal taxonomy, rollback, quarantine. The fleet shipped in
PRs 18–19 had none of it: a `cli serve --fleet` replica that dies stays
dead, and the router's endpoint set is frozen at start. This module is
the fleet's `resilience/supervisor.py`:

  * it SPAWNS every replica process (`cli serve --fleet --listen host:0`,
    endpoint discovered from the replica's hello line), tagging each with
    a member id ``s{shard}r{idx}`` via the BIGCLAM_FLEET_MEMBER env;
  * it RESTARTS a replica on unplanned exit, backing off with the PR 5
    ``RetryPolicy`` schedule (deterministic per-member jitter, seeded by
    crc32 of the member id — the same discipline call_with_retry uses);
    a restarted replica rejoins at the NEWEST generation because every
    replica runs with ``--watch-snapshots``;
  * it QUARANTINES a crash-looping slot: more than ``quarantine_after``
    consecutive failures (a success = surviving ``stable_s`` seconds)
    parks the member in state "quarantined" — the fleet degrades to its
    surviving replicas instead of burning CPU on a doomed respawn loop;
  * it PUBLISHES the roster to a membership file (atomic tmp+rename,
    monotonic ``seq``) that the router watches — elastic membership:
    ``add_replica`` and ``drain`` reshape the fleet mid-stream with zero
    dropped queries (drain = flip the member to "draining", wait one
    router reload interval so new dispatch stops, then send the wire
    ``drain`` op — the replica closes its admission door, finishes
    in-flight batches, and exits clean);
  * it ANSWERS a control socket (same newline-framed JSON wire) with
    ops ``status`` / ``add_replica`` / ``drain`` / ``down`` — what
    ``cli fleet status/add-replica/drain/down`` talk to.

Membership file (version 1):

    {"version": 1, "seq": 7, "control": "127.0.0.1:4444",
     "members": [{"id": "s0r0", "shard": 0, "endpoint": "127.0.0.1:4567",
                  "state": "up", "pid": 31337, "restarts": 1}, ...]}

States: starting → up → (restarting → up)* | quarantined | draining →
stopped. The router admits only state == "up".

Telemetry: schema'd ``replica_restart`` / ``replica_quarantined`` /
``membership`` events; the fleet final carries ``replica_restarts`` and
``quarantined`` for the perf ledger.

jax-free: subprocess + threading + json + numpy only — `cli fleet` must
never drag a jax import into a process-herding parent.
"""

from __future__ import annotations

import json
import os
import socket
import socketserver
import subprocess
import sys
import threading
import time
import zlib
from typing import Any, Dict, List, Optional

import numpy as np

from bigclam_tpu.resilience.retry import RetryPolicy

MEMBER_ENV = "BIGCLAM_FLEET_MEMBER"
MEMBERSHIP_VERSION = 1


def _tel_event(kind: str, **fields) -> None:
    from bigclam_tpu.obs import telemetry as _obs

    tel = _obs.current()
    if tel is not None:
        tel.event(kind, **fields)


class _MemberSlot:
    """One replica slot: the process, its lifecycle state, and its
    failure ledger. All mutation happens under the supervisor lock."""

    __slots__ = (
        "id", "shard", "proc", "endpoint", "state", "pid", "restarts",
        "failures", "started_at", "next_attempt_at", "stopping", "rng",
        "log_fh",
    )

    def __init__(self, member_id: str, shard: int, rng):
        self.id = member_id
        self.shard = int(shard)
        self.proc: Optional[subprocess.Popen] = None
        self.endpoint: Optional[str] = None
        self.state = "starting"
        self.pid: Optional[int] = None
        self.restarts = 0          # lifetime respawn count for this slot
        self.failures = 0          # CONSECUTIVE failures (reset by uptime)
        self.started_at = 0.0
        self.next_attempt_at = 0.0
        self.stopping = False      # planned exit (drain/down): not a fault
        self.rng = rng
        self.log_fh = None

    def roster_entry(self) -> Dict[str, Any]:
        return {
            "id": self.id,
            "shard": self.shard,
            "endpoint": self.endpoint,
            "state": self.state,
            "pid": self.pid,
            "restarts": self.restarts,
        }


class FleetSupervisor:
    """See module docstring. Lifecycle: ``up()`` spawns the fleet +
    monitor + control server; ``down()`` (or the wire ``down`` op) tears
    everything back out. The membership file at ``members_path`` is the
    only thing the router needs."""

    def __init__(
        self,
        fleet_dir: str,
        members_path: str,
        shards: int = 1,
        replicas: int = 1,
        host: str = "127.0.0.1",
        control_port: int = 0,
        policy: Optional[RetryPolicy] = None,
        quarantine_after: int = 3,
        stable_s: float = 5.0,
        poll_s: float = 0.25,
        drain_grace_s: float = 0.5,
        hello_timeout_s: float = 60.0,
        replica_args: Optional[List[str]] = None,
        graph: Optional[str] = None,
        watch_snapshots_s: float = 1.0,
        log_dir: Optional[str] = None,
        seed: int = 0,
    ):
        self.fleet_dir = fleet_dir
        self.members_path = members_path
        self.host = host
        self.policy = policy or RetryPolicy(base_s=0.25, max_s=10.0,
                                            seed=seed)
        self.quarantine_after = max(int(quarantine_after), 1)
        self.stable_s = max(float(stable_s), 0.0)
        self.poll_s = max(float(poll_s), 0.05)
        self.drain_grace_s = max(float(drain_grace_s), 0.0)
        self.hello_timeout_s = float(hello_timeout_s)
        self.replica_args = list(replica_args or [])
        self.graph = graph
        self.watch_snapshots_s = float(watch_snapshots_s)
        self.log_dir = log_dir
        self.seed = int(seed)
        self._lock = threading.RLock()
        self._slots: List[_MemberSlot] = []
        self._next_idx: Dict[int, int] = {}   # shard -> next replica idx
        self._seq = 0
        self._stop_ev = threading.Event()
        self._down_ev = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        self._control: Optional[_ControlServer] = None
        self.control_port = int(control_port)
        self.total_restarts = 0
        self.total_quarantined = 0
        for s in range(max(int(shards), 1)):
            for _ in range(max(int(replicas), 1)):
                self._new_slot(s)

    # ------------------------------------------------------------ slots
    def _new_slot(self, shard: int) -> _MemberSlot:
        idx = self._next_idx.get(shard, 0)
        self._next_idx[shard] = idx + 1
        member_id = f"s{shard}r{idx}"
        rng = np.random.default_rng(
            [self.policy.seed, zlib.crc32(member_id.encode())]
        )
        slot = _MemberSlot(member_id, shard, rng)
        self._slots.append(slot)
        return slot

    def _spawn(self, slot: _MemberSlot) -> None:
        """Launch one replica process and hand its hello line to a reader
        thread (a crash before hello closes stdout → failure; the monitor
        thread sees the exit)."""
        argv = [
            sys.executable, "-m", "bigclam_tpu.cli", "serve",
            "--fleet", self.fleet_dir,
            "--fleet-shard", str(slot.shard),
            "--listen", f"{self.host}:0",
            "--quiet",
        ]
        if self.watch_snapshots_s > 0:
            argv += ["--watch-snapshots", str(self.watch_snapshots_s)]
        if self.graph:
            argv += ["--graph", self.graph]
        argv += self.replica_args
        env = dict(os.environ)
        env[MEMBER_ENV] = slot.id
        stderr = subprocess.DEVNULL
        if self.log_dir:
            os.makedirs(self.log_dir, exist_ok=True)
            if slot.log_fh is None:
                slot.log_fh = open(
                    os.path.join(self.log_dir, f"{slot.id}.log"), "ab"
                )
            stderr = slot.log_fh
        slot.proc = subprocess.Popen(
            argv, stdout=subprocess.PIPE, stderr=stderr, env=env
        )
        slot.pid = slot.proc.pid
        slot.endpoint = None
        slot.started_at = time.monotonic()
        slot.state = "starting"
        threading.Thread(
            target=self._read_hello, args=(slot, slot.proc),
            name=f"bigclam-fleet-hello-{slot.id}", daemon=True,
        ).start()

    def _read_hello(self, slot: _MemberSlot, proc: subprocess.Popen) -> None:
        line = b""
        try:
            line = proc.stdout.readline()
        except Exception:
            pass
        hello = None
        try:
            hello = json.loads(line.decode())
        except Exception:
            pass
        with self._lock:
            if slot.proc is proc and hello and hello.get("listening"):
                slot.endpoint = str(hello["listening"])
                slot.state = "up"
                self._publish_locked()
        # keep draining stdout so the replica's exit prints never block
        # it on a full pipe
        try:
            while proc.stdout.read(65536):
                pass
        except Exception:
            pass

    # ------------------------------------------------------- membership
    def _publish_locked(self) -> None:
        self._seq += 1
        doc = {
            "version": MEMBERSHIP_VERSION,
            "seq": self._seq,
            "control": f"{self.host}:{self.control_port}",
            "members": [s.roster_entry() for s in self._slots
                        if s.state != "stopped"],
        }
        tmp = self.members_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, self.members_path)
        _tel_event(
            "membership", seq=self._seq, members=len(doc["members"]),
            roster=[
                {"id": m["id"], "shard": m["shard"], "state": m["state"],
                 "restarts": m["restarts"]}
                for m in doc["members"]
            ],
        )

    def publish(self) -> None:
        with self._lock:
            self._publish_locked()

    # ---------------------------------------------------------- monitor
    def _monitor_loop(self) -> None:
        while not self._stop_ev.wait(self.poll_s):
            with self._lock:
                now = time.monotonic()
                for slot in self._slots:
                    if slot.state in ("quarantined", "stopped", "draining"):
                        continue
                    if slot.state == "restarting":
                        if now >= slot.next_attempt_at:
                            slot.restarts += 1
                            self.total_restarts += 1
                            _tel_event("replica_restart", member=slot.id,
                                       shard=slot.shard,
                                       restarts=slot.restarts)
                            self._spawn(slot)
                            self._publish_locked()
                        continue
                    proc = slot.proc
                    if proc is None or proc.poll() is None:
                        continue
                    if slot.stopping:
                        slot.state = "stopped"
                        self._publish_locked()
                        continue
                    # unplanned exit: a fault, an OOM kill, a crash
                    uptime = now - slot.started_at
                    slot.failures = (1 if uptime >= self.stable_s
                                     else slot.failures + 1)
                    slot.endpoint = None
                    slot.pid = None
                    if slot.failures > self.quarantine_after:
                        slot.state = "quarantined"
                        self.total_quarantined += 1
                        _tel_event("replica_quarantined", member=slot.id,
                                   shard=slot.shard,
                                   failures=slot.failures)
                        print(
                            f"[fleet] {slot.id} crash-looped "
                            f"({slot.failures} consecutive failures): "
                            "QUARANTINED",
                            file=sys.stderr, flush=True,
                        )
                        self._publish_locked()
                        continue
                    backoff = self.policy.backoff_s(
                        slot.failures - 1, slot.rng
                    )
                    slot.state = "restarting"
                    slot.next_attempt_at = now + backoff
                    print(
                        f"[fleet] {slot.id} exited "
                        f"(rc={proc.returncode}, uptime={uptime:.2f}s): "
                        f"restart in {backoff:.2f}s",
                        file=sys.stderr, flush=True,
                    )
                    self._publish_locked()

    # -------------------------------------------------------- lifecycle
    def up(self) -> "FleetSupervisor":
        with self._lock:
            for slot in self._slots:
                self._spawn(slot)
            self._control = _ControlServer(self, self.host,
                                           self.control_port)
            self.control_port = self._control.port
            self._publish_locked()
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="bigclam-fleet-monitor",
            daemon=True,
        )
        self._monitor.start()
        return self

    def wait_all_up(self, timeout: float = 60.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                pending = [s for s in self._slots
                           if s.state in ("starting", "restarting")]
            if not pending:
                return True
            time.sleep(0.05)
        return False

    def status(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "seq": self._seq,
                "control": f"{self.host}:{self.control_port}",
                "members": [s.roster_entry() for s in self._slots],
                "replica_restarts": self.total_restarts,
                "quarantined": self.total_quarantined,
            }

    def add_replica(self, shard: int) -> Dict[str, Any]:
        with self._lock:
            slot = self._new_slot(int(shard))
            self._spawn(slot)
            self._publish_locked()
            return slot.roster_entry()

    def _wire_op(self, endpoint: str, op: dict,
                 timeout: float = 10.0) -> Optional[dict]:
        host, port = endpoint.rsplit(":", 1)
        try:
            with socket.create_connection((host, int(port)),
                                          timeout=timeout) as sock:
                sock.settimeout(timeout)
                sock.sendall((json.dumps(op) + "\n").encode())
                f = sock.makefile("rb")
                line = f.readline()
            return json.loads(line.decode()) if line else None
        except (OSError, ValueError):
            return None

    def drain(self, member_id: str, timeout: float = 30.0) -> bool:
        """Zero-drop detach: flip to "draining" + publish (the router
        stops dispatching within one reload interval), wait the grace,
        then the wire drain op — the replica closes its admission door,
        finishes in-flight, and exits. Ack'd only after the exit."""
        with self._lock:
            slot = next((s for s in self._slots if s.id == member_id),
                        None)
            if slot is None or slot.state != "up" or not slot.endpoint:
                return False
            slot.state = "draining"
            slot.stopping = True
            endpoint = slot.endpoint
            proc = slot.proc
            self._publish_locked()
        time.sleep(self.drain_grace_s)
        self._wire_op(endpoint, {"family": "drain"})
        try:
            proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=5.0)
        with self._lock:
            slot.state = "stopped"
            slot.endpoint = None
            slot.pid = None
            self._publish_locked()
        return True

    def down(self, timeout: float = 30.0) -> Dict[str, Any]:
        """Tear the fleet out: stop ops to live replicas, SIGKILL any
        straggler, publish the emptied roster, leave counters for the
        caller's telemetry final."""
        self._stop_ev.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5.0)
            self._monitor = None
        with self._lock:
            live = [s for s in self._slots
                    if s.proc is not None and s.proc.poll() is None]
            for slot in live:
                slot.stopping = True
        for slot in live:
            if slot.endpoint:
                self._wire_op(slot.endpoint, {"family": "stop"},
                              timeout=5.0)
        deadline = time.monotonic() + timeout
        for slot in live:
            rem = max(deadline - time.monotonic(), 0.1)
            try:
                slot.proc.wait(timeout=rem)
            except subprocess.TimeoutExpired:
                slot.proc.kill()
                try:
                    slot.proc.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    pass
        with self._lock:
            for slot in self._slots:
                if slot.state != "quarantined":
                    slot.state = "stopped"
                slot.endpoint = None
                slot.pid = None
            self._publish_locked()
            for slot in self._slots:
                if slot.log_fh is not None:
                    slot.log_fh.close()
                    slot.log_fh = None
        if self._control is not None:
            self._control.close()
            self._control = None
        return {
            "replica_restarts": self.total_restarts,
            "quarantined": self.total_quarantined,
        }

    def wait_down(self, timeout: Optional[float] = None) -> bool:
        """Block until a wire `down` op (or signal handler) tears the
        fleet out — what `cli fleet up` parks on."""
        return self._down_ev.wait(timeout)


class _ControlServer:
    """Newline-framed JSON control wire (the same framing the replicas
    and the router daemon speak): status / add_replica / drain / down."""

    def __init__(self, sup: FleetSupervisor, host: str, port: int):
        outer = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                for raw in self.rfile:
                    try:
                        op = json.loads(raw.decode())
                    except (ValueError, UnicodeDecodeError):
                        self._reply({"error": "bad json"})
                        continue
                    name = op.get("op")
                    if name == "status":
                        self._reply(sup.status())
                    elif name == "add_replica":
                        entry = sup.add_replica(int(op.get("shard", 0)))
                        self._reply({"ok": True, "member": entry})
                    elif name == "drain":
                        ok = sup.drain(str(op.get("member", "")))
                        self._reply({"ok": ok})
                    elif name == "down":
                        self._reply({"ok": True})
                        threading.Thread(
                            target=outer._do_down, daemon=True
                        ).start()
                        return
                    else:
                        self._reply({"error": f"unknown op {name!r}"})

            def _reply(self, doc):
                self.wfile.write((json.dumps(doc) + "\n").encode())
                self.wfile.flush()

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self.sup = sup
        self._srv = Server((host, int(port)), Handler)
        self.port = self._srv.server_address[1]
        self._thread = threading.Thread(
            target=self._srv.serve_forever,
            name="bigclam-fleet-control", daemon=True,
        )
        self._thread.start()

    def _do_down(self):
        self.sup.down()
        self.sup._down_ev.set()

    def close(self):
        self._srv.shutdown()
        self._srv.server_close()


def control_op(control: str, op: dict, timeout: float = 60.0) -> dict:
    """One request/response round-trip against a supervisor's control
    endpoint (`cli fleet status/down/add-replica/drain`)."""
    host, port = control.rsplit(":", 1)
    with socket.create_connection((host, int(port)),
                                  timeout=timeout) as sock:
        sock.settimeout(timeout)
        sock.sendall((json.dumps(op) + "\n").encode())
        f = sock.makefile("rb")
        line = f.readline()
    if not line:
        raise ConnectionError(f"no answer from control {control}")
    return json.loads(line.decode())
