"""tpu-bigclam: a TPU-native framework for overlapping community detection.

Re-implements the capabilities of thangdnsf/BigCLAM-ApacheSpark (BigCLAM,
Yang & Leskovec WSDM'13, on Apache Spark) as an idiomatic JAX/XLA/Pallas/pjit
framework: the node x community affiliation matrix F lives as a sharded device
array, the per-node gradient (sparse neighbor sum + global sumF term) runs as
edge-parallel fused kernels with `psum` over ICI, and the whole optimization
loop (conductance seeding -> Armijo backtracking gradient ascent -> K
selection -> delta-threshold extraction) stays on device.

See SURVEY.md for the structural analysis of the reference this build follows.
"""

__version__ = "0.1.0"

from bigclam_tpu.config import BigClamConfig

__all__ = ["BigClamConfig", "__version__"]
