// Native host-side graph kernels for tpu-bigclam.
//
// The reference (thangdnsf/BigCLAM-ApacheSpark) has no native code at all —
// its ingest was Spark GraphLoader (JVM) and its two-hop conductance sweep a
// Spark map over broadcast neighbor lists (Bigclamv2.scala:14,42-59). These
// are the framework's host-side hot paths (device kernels are JAX/Pallas):
//
//   bc_parse_edge_list — streaming SNAP edge-list parser ('#' comments,
//       whitespace-separated integer pairs); one pass, no line splitting.
//   bc_triangle_counts — tri(u) = #edges among N(u), the masked-SpGEMM-style
//       two-hop pass behind the conductance closed forms (ops/seeding.py);
//       OpenMP over nodes with per-thread flag arrays, O(sum deg^2) work.
//
// Exposed to Python via ctypes (see __init__.py); NumPy fallbacks exist for
// every entry point, so the .so is an accelerator, not a dependency.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

extern "C" {

// Returns a malloc'd buffer of 2*n_pairs int64 values (caller frees with
// bc_free). On failure returns nullptr with *n_pairs_out = -1 (parse error:
// odd token count or non-integer token) or -2 (I/O error).
int64_t* bc_parse_edge_list(const char* path, int64_t* n_pairs_out) {
  *n_pairs_out = -2;
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  fseek(f, 0, SEEK_END);
  long sz = ftell(f);
  fseek(f, 0, SEEK_SET);
  char* buf = (char*)malloc((size_t)sz + 1);
  if (!buf) {
    fclose(f);
    return nullptr;
  }
  if (sz > 0 && fread(buf, 1, (size_t)sz, f) != (size_t)sz) {
    free(buf);
    fclose(f);
    return nullptr;
  }
  fclose(f);
  buf[sz] = '\0';

  std::vector<int64_t> vals;
  vals.reserve(1 << 20);
  const char* p = buf;
  const char* end = buf + sz;
  bool line_has_token = false;  // '#' only starts a comment at line start,
                                // matching the NumPy fallback's semantics
  while (p < end) {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\r' || *p == '\n')) {
      if (*p == '\n') line_has_token = false;
      p++;
    }
    if (p >= end) break;
    if (*p == '#') {
      if (line_has_token) {  // mid-line '#': malformed, as in NumPy path
        free(buf);
        *n_pairs_out = -1;
        return nullptr;
      }
      while (p < end && *p != '\n') p++;
      continue;
    }
    line_has_token = true;
    bool neg = false;
    if (*p == '-' || *p == '+') {
      neg = (*p == '-');
      p++;
    }
    if (p >= end || *p < '0' || *p > '9') {
      free(buf);
      *n_pairs_out = -1;
      return nullptr;
    }
    int64_t v = 0;
    while (p < end && *p >= '0' && *p <= '9') {
      v = v * 10 + (*p - '0');
      p++;
    }
    vals.push_back(neg ? -v : v);
  }
  free(buf);
  if (vals.size() % 2 != 0) {
    *n_pairs_out = -1;
    return nullptr;
  }
  int64_t* out = (int64_t*)malloc(vals.size() * sizeof(int64_t));
  if (!out) {
    *n_pairs_out = -2;
    return nullptr;
  }
  if (!vals.empty()) memcpy(out, vals.data(), vals.size() * sizeof(int64_t));
  *n_pairs_out = (int64_t)(vals.size() / 2);
  return out;
}

void bc_free(void* p) { free(p); }

// tri(u) = #edges among N(u): mark N(u) in a flag array, then count flagged
// entries across the neighbor lists of every v in N(u); each intra-
// neighborhood edge is seen twice.
void bc_triangle_counts(const int64_t* indptr, const int32_t* indices,
                        int64_t n, int64_t* out) {
#pragma omp parallel
  {
    std::vector<uint8_t> flags((size_t)n, 0);
#pragma omp for schedule(dynamic, 64)
    for (int64_t u = 0; u < n; u++) {
      int64_t lo = indptr[u], hi = indptr[u + 1];
      for (int64_t i = lo; i < hi; i++) flags[indices[i]] = 1;
      int64_t hits = 0;
      for (int64_t i = lo; i < hi; i++) {
        int32_t v = indices[i];
        for (int64_t j = indptr[v]; j < indptr[v + 1]; j++)
          hits += flags[indices[j]];
      }
      for (int64_t i = lo; i < hi; i++) flags[indices[i]] = 0;
      out[u] = hits / 2;
    }
  }
}

// Degree-capped triangle-count ESTIMATOR (ops/seeding.py documents the
// math): each node keeps a uniform sample of at most `cap` neighbors
// (partial Fisher-Yates, per-node splitmix64 stream, O(E) total); hits are
// weighted by deg(v)/|S_v| and the per-node total rescaled by
// C(deg,2)/C(|S|,2). With cap >= max degree this equals the exact count.
// Work O(n * cap^2) instead of the exact pass's O(sum deg^2).
static inline uint64_t bc_splitmix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  return x ^ (x >> 31);
}

void bc_triangle_counts_capped(const int64_t* indptr, const int32_t* indices,
                               int64_t n, int64_t cap, uint64_t seed,
                               double* out) {
  std::vector<int64_t> cptr((size_t)n + 1, 0);
  for (int64_t u = 0; u < n; u++) {
    int64_t d = indptr[u + 1] - indptr[u];
    cptr[u + 1] = cptr[u] + (d < cap ? d : cap);
  }
  std::vector<int32_t> cind((size_t)cptr[n]);
#pragma omp parallel
  {
    std::vector<int32_t> scratch;
#pragma omp for schedule(dynamic, 256)
    for (int64_t u = 0; u < n; u++) {
      int64_t lo = indptr[u], d = indptr[u + 1] - lo;
      int64_t cd = cptr[u + 1] - cptr[u];
      if (d <= cap) {
        for (int64_t i = 0; i < d; i++) cind[cptr[u] + i] = indices[lo + i];
        continue;
      }
      scratch.assign(indices + lo, indices + lo + d);
      uint64_t s = bc_splitmix64(seed ^ (uint64_t)u * 0x2545f4914f6cdd1dULL);
      for (int64_t i = 0; i < cd; i++) {  // partial Fisher-Yates
        s = bc_splitmix64(s);
        int64_t j = i + (int64_t)(s % (uint64_t)(d - i));
        int32_t tmp = scratch[i];
        scratch[i] = scratch[j];
        scratch[j] = tmp;
        cind[cptr[u] + i] = scratch[i];
      }
    }
  }
#pragma omp parallel
  {
    std::vector<uint8_t> flags((size_t)n, 0);
#pragma omp for schedule(dynamic, 64)
    for (int64_t u = 0; u < n; u++) {
      int64_t lo = cptr[u], hi = cptr[u + 1];
      int64_t cd = hi - lo;
      int64_t d = indptr[u + 1] - indptr[u];
      if (cd < 2) {
        out[u] = 0.0;
        continue;
      }
      for (int64_t i = lo; i < hi; i++) flags[cind[i]] = 1;
      double hits = 0.0;
      for (int64_t i = lo; i < hi; i++) {
        int32_t v = cind[i];
        int64_t vd = indptr[v + 1] - indptr[v];
        int64_t vc = cptr[v + 1] - cptr[v];
        double w = vc ? (double)vd / (double)vc : 0.0;
        for (int64_t j = cptr[v]; j < cptr[v + 1]; j++)
          if (flags[cind[j]]) hits += w;
      }
      for (int64_t i = lo; i < hi; i++) flags[cind[i]] = 0;
      double scale =
          (double)d * (double)(d - 1) / ((double)cd * (double)(cd - 1));
      out[u] = hits / 2.0 * scale;
    }
  }
}

// Greedy coverage-aware seed selection (quality mode's seeding rule;
// Python reference implementation: ops/seeding.select_seeds_covering).
// `order` is the caller-prepared candidate ranking (locally-minimal
// nominees first, then the remaining nodes by ascending phi); the walk
// skips candidates already covered by a chosen seed's hops-neighborhood.
// The hops=2 fan caps (stride subsample of N(s), first-`cap` prefix of
// each N(v)) replicate the NumPy slicing bit-exactly so both backends
// choose identical seeds. Returns the number of seeds written (<= k).
int64_t bc_select_seeds_covering(const int64_t* indptr,
                                 const int32_t* indices, int64_t n,
                                 const int64_t* order, int64_t n_order,
                                 int64_t k, int64_t hops, int64_t cap,
                                 int64_t* seeds_out) {
  std::vector<uint8_t> covered(n, 0);
  int64_t cnt = 0;
  for (int64_t oi = 0; oi < n_order && cnt < k; ++oi) {
    int64_t s = order[oi];
    if (s < 0 || s >= n || covered[s]) continue;
    seeds_out[cnt++] = s;
    covered[s] = 1;
    int64_t lo = indptr[s], hi = indptr[s + 1], deg = hi - lo;
    for (int64_t e = lo; e < hi; ++e) covered[indices[e]] = 1;
    if (hops >= 2) {
      // nbrs[::max(deg//cap, 1)][:cap] when deg > cap, else all of N(s)
      int64_t step = 1, limit = deg;
      if (deg > cap) {
        step = deg / cap;
        if (step < 1) step = 1;
        limit = cap;
      }
      int64_t taken = 0;
      for (int64_t e = lo; e < hi && taken < limit; e += step, ++taken) {
        int64_t v = indices[e];
        int64_t vlo = indptr[v], vcnt = indptr[v + 1] - vlo;
        if (vcnt > cap) vcnt = cap;                  // row[:cap]
        for (int64_t f = vlo; f < vlo + vcnt; ++f) covered[indices[f]] = 1;
      }
    }
  }
  return cnt;
}

}  // extern "C"
