"""ctypes bindings for the native host-side kernels (native.cpp).

Importing this module loads ``libbigclam_native.so`` next to it, building it
with `make` on first use if the toolchain is available. Callers
(graph.ingest, ops.seeding) guard the import and fall back to NumPy, so a
missing compiler degrades performance, not functionality.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libbigclam_native.so")


def _load() -> ctypes.CDLL:
    src = os.path.join(_DIR, "native.cpp")
    stale = os.path.exists(_SO) and os.path.exists(src) and (
        os.path.getmtime(_SO) < os.path.getmtime(src)
    )
    if stale:
        os.remove(_SO)   # rebuild below; dlopen caching makes reload unsafe
    if not os.path.exists(_SO):
        try:
            subprocess.run(
                ["make", "-C", _DIR],
                check=True,
                capture_output=True,
                timeout=120,
            )
        except (subprocess.SubprocessError, FileNotFoundError) as e:
            raise ImportError(f"cannot build native library: {e}") from e
    try:
        lib = ctypes.CDLL(_SO)
    except OSError as e:
        raise ImportError(f"cannot load {_SO}: {e}") from e
    try:
        return _register(lib)
    except AttributeError:
        # stale prebuilt .so missing a symbol (the mtime check can be fooled
        # by copied artifacts): rebuild once, then register or give up
        try:
            os.remove(_SO)
            subprocess.run(
                ["make", "-C", _DIR], check=True, capture_output=True,
                timeout=120,
            )
            return _register(ctypes.CDLL(_SO))
        except (subprocess.SubprocessError, FileNotFoundError, OSError,
                AttributeError) as e:
            raise ImportError(f"stale {_SO} and rebuild failed: {e}") from e


def _register(lib: ctypes.CDLL) -> ctypes.CDLL:
    """Declare every exported symbol's signature (AttributeError = stale)."""
    lib.bc_parse_edge_list.restype = ctypes.POINTER(ctypes.c_int64)
    lib.bc_parse_edge_list.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.bc_free.restype = None
    lib.bc_free.argtypes = [ctypes.c_void_p]
    lib.bc_triangle_counts.restype = None
    lib.bc_triangle_counts.argtypes = [
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.bc_triangle_counts_capped.restype = None
    lib.bc_triangle_counts_capped.argtypes = [
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_double),
    ]
    lib.bc_select_seeds_covering.restype = ctypes.c_int64
    lib.bc_select_seeds_covering.argtypes = [
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64),
    ]
    return lib


_lib = _load()


def parse_edge_list(path: str) -> np.ndarray:
    """Parse a SNAP edge-list file into an (M, 2) int64 array."""
    n_pairs = ctypes.c_int64(0)
    ptr = _lib.bc_parse_edge_list(path.encode(), ctypes.byref(n_pairs))
    if not ptr:
        if n_pairs.value == -1:
            raise ValueError(
                f"{path}: malformed edge list (odd or non-integer tokens)"
            )
        raise OSError(f"{path}: cannot read")
    try:
        m = n_pairs.value
        out = np.ctypeslib.as_array(ptr, shape=(m, 2)).copy() if m else np.empty(
            (0, 2), np.int64
        )
    finally:
        _lib.bc_free(ptr)
    return out


def triangle_counts(g) -> np.ndarray:
    """tri(u) = #edges among N(u) via the OpenMP two-hop pass."""
    indptr = np.ascontiguousarray(g.indptr, dtype=np.int64)
    indices = np.ascontiguousarray(g.indices, dtype=np.int32)
    n = g.num_nodes
    out = np.zeros(n, dtype=np.int64)
    _lib.bc_triangle_counts(
        indptr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ctypes.c_int64(n),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    return out


def select_seeds_covering(
    g, order: np.ndarray, k: int, hops: int, cap: int
) -> np.ndarray:
    """Greedy covering walk over the prepared candidate `order` (semantics
    and slicing bit-identical to ops.seeding.select_seeds_covering's NumPy
    loop — backend-independent seed choices)."""
    indptr = np.ascontiguousarray(g.indptr, dtype=np.int64)
    indices = np.ascontiguousarray(g.indices, dtype=np.int32)
    order = np.ascontiguousarray(order, dtype=np.int64)
    out = np.empty(max(int(k), 1), dtype=np.int64)
    cnt = _lib.bc_select_seeds_covering(
        indptr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ctypes.c_int64(g.num_nodes),
        order.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ctypes.c_int64(order.size),
        ctypes.c_int64(int(k)),
        ctypes.c_int64(int(hops)),
        ctypes.c_int64(int(cap)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    return out[:cnt].copy()


def triangle_counts_capped(g, cap: int, seed: int = 0) -> np.ndarray:
    """Degree-capped tri(u) estimator (O(n*cap^2); exact when cap >= max
    degree). Semantics documented in ops.seeding.triangle_counts_sampled."""
    indptr = np.ascontiguousarray(g.indptr, dtype=np.int64)
    indices = np.ascontiguousarray(g.indices, dtype=np.int32)
    n = g.num_nodes
    out = np.zeros(n, dtype=np.float64)
    _lib.bc_triangle_counts_capped(
        indptr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ctypes.c_int64(n),
        ctypes.c_int64(int(cap)),
        ctypes.c_uint64(int(seed) & (2**64 - 1)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
    )
    return out
