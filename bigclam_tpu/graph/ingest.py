"""SNAP edge-list ingest: parse -> remap -> symmetrize -> dedup -> CSR.

Replaces the reference's ``GraphLoader.edgeListFile`` + driver-side edge
collection (C1/C2; Bigclamv2.scala:14-20 — which `collect`ed the whole edge
list onto the Spark driver, SURVEY.md Q9). Parsing streams the file in
newline-snapped byte-range chunks (graph/stream.py) so transient parse state
is O(chunk), not O(file); ``bigclam_tpu.graph.native`` (C++ fast path, used
when its shared library has been built) takes over when importable; the
result is a deduplicated symmetric CSR ready to be sliced into
node-contiguous shards and ``device_put``.

``build_graph`` is a thin wrapper over the graph store (graph/store.py): a
cache directory produced by ``cli ingest`` reloads from binary shards
(mmap'd, no parse/remap/dedup); a text path takes the in-memory pipeline
below. Out-of-core builds that never materialize the edge set go through
``store.compile_graph_cache``.

Format: SNAP edge lists — ``#``-prefixed comment header lines, then one
whitespace-separated integer pair per line (one edge per line). Self-loops
are dropped; duplicate edges (including files that list both directions,
like Email-Enron) are deduplicated.
"""

from __future__ import annotations

import numpy as np

from bigclam_tpu.graph.csr import Graph
from bigclam_tpu.graph.stream import load_edge_list_streaming


def load_edge_list(path: str) -> np.ndarray:
    """Parse a SNAP edge-list file into an (M, 2) int64 array of raw id pairs."""
    try:
        from bigclam_tpu.graph.native import parse_edge_list as _native_parse

        pairs = _native_parse(path)
        if pairs is not None:
            return pairs
    except ImportError:
        pass
    return load_edge_list_streaming(path)


def dedup_directed(both: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Sort directed (src, dst) pairs lexicographically and drop duplicate
    rows; returns (src, dst) int64 in CSR order.

    Replaces the seed's single-int64 packed key (``src * n + dst``), whose
    comment-only ``n < 2^31`` assumption silently corrupts the dedup past
    ~2.1B nodes: a row-wise lexsort has no node-count ceiling (the parity
    test against the packed path lives in tests/test_ingest.py). Shared by
    the in-memory pipeline below and the store's per-bucket out-of-core
    dedup (duplicates of an edge always share a src, so bucket-local dedup
    composes to the global one).
    """
    both = np.asarray(both, dtype=np.int64).reshape(-1, 2)
    if both.shape[0] == 0:
        return both[:, 0], both[:, 1]
    order = np.lexsort((both[:, 1], both[:, 0]))
    both = both[order]
    keep = np.empty(both.shape[0], dtype=bool)
    keep[0] = True
    np.any(both[1:] != both[:-1], axis=1, out=keep[1:])
    both = both[keep]
    return both[:, 0].copy(), both[:, 1].copy()


def graph_from_edges(pairs: np.ndarray, num_nodes: int | None = None) -> Graph:
    """Build a symmetric deduplicated CSR from raw (u, v) id pairs.

    Raw ids are remapped to contiguous [0, N) by ascending raw id (C10's
    remap; GraphX tolerated sparse ids, we normalize them away). If
    ``num_nodes`` is given, ids are assumed already contiguous in [0, N).
    """
    pairs = np.asarray(pairs, dtype=np.int64).reshape(-1, 2)
    if num_nodes is None:
        raw_ids, remapped = np.unique(pairs, return_inverse=True)
        pairs = remapped.reshape(-1, 2)
        n = int(raw_ids.shape[0])
    else:
        n = int(num_nodes)
        raw_ids = np.arange(n, dtype=np.int64)
        if pairs.size and (pairs.min() < 0 or pairs.max() >= n):
            raise ValueError("edge endpoint out of range for given num_nodes")

    # drop self-loops
    keep = pairs[:, 0] != pairs[:, 1]
    pairs = pairs[keep]

    if n > np.iinfo(np.int32).max:
        # the dedup itself has no ceiling now, but Graph stores indices as
        # int32 — refuse loudly instead of wrapping ids negative
        raise ValueError(
            f"num_nodes={n} exceeds the int32 CSR indices bound (2^31-1)"
        )

    # symmetrize: every edge in both directions, then dedup directed pairs
    both = np.concatenate([pairs, pairs[:, ::-1]], axis=0)
    src, dst = dedup_directed(both)

    # CSR: dedup_directed returns (src, dst)-sorted pairs
    counts = np.bincount(src, minlength=n)
    indptr = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(counts, out=indptr[1:])
    return Graph(indptr=indptr, indices=dst.astype(np.int32), raw_ids=raw_ids)


def build_graph(path: str, self_heal: bool = False) -> Graph:
    """Load a graph: a SNAP edge-list file (parse + remap + dedup) or a
    graph-cache directory compiled by ``cli ingest`` (binary fast reload).
    `self_heal` lets a cache dir quarantine + rebuild a crc-failed shard
    from its source edge list (graph.store.GraphStore) instead of
    rejecting the whole cache — the CLI's default."""
    from bigclam_tpu.graph.store import GraphStore, is_cache_dir

    if is_cache_dir(path):
        return GraphStore.open(path, self_heal=self_heal).load_graph()
    return graph_from_edges(load_edge_list(path))
