"""SNAP edge-list ingest: parse -> remap -> symmetrize -> dedup -> CSR.

Replaces the reference's ``GraphLoader.edgeListFile`` + driver-side edge
collection (C1/C2; Bigclamv2.scala:14-20 — which `collect`ed the whole edge
list onto the Spark driver, SURVEY.md Q9). Parsing is a vectorized bulk pass
on host; ``bigclam_tpu.graph.native`` (C++ fast path, used when its shared
library has been built) takes over when importable; the result is a
deduplicated symmetric CSR
ready to be sliced into node-contiguous shards and ``device_put``.

Format: SNAP edge lists — ``#``-prefixed comment header lines, then one
whitespace-separated integer pair per line (one edge per line). Self-loops
are dropped; duplicate edges (including files that list both directions,
like Email-Enron) are deduplicated.
"""

from __future__ import annotations

import numpy as np

from bigclam_tpu.graph.csr import Graph


def load_edge_list(path: str) -> np.ndarray:
    """Parse a SNAP edge-list file into an (M, 2) int64 array of raw id pairs."""
    try:
        from bigclam_tpu.graph.native import parse_edge_list as _native_parse

        pairs = _native_parse(path)
        if pairs is not None:
            return pairs
    except ImportError:
        pass
    return _numpy_parse(path)


def _numpy_parse(path: str) -> np.ndarray:
    with open(path, "rb") as f:
        data = f.read()
    # Strip '#' comment lines, then bulk-parse all integers at once.
    lines = data.split(b"\n")
    body = b" ".join(ln for ln in lines if ln and not ln.lstrip().startswith(b"#"))
    flat = np.array(body.split(), dtype=np.int64)
    if flat.size % 2 != 0:
        raise ValueError(
            f"{path}: expected an even number of integers, got {flat.size}"
        )
    return flat.reshape(-1, 2)


def graph_from_edges(pairs: np.ndarray, num_nodes: int | None = None) -> Graph:
    """Build a symmetric deduplicated CSR from raw (u, v) id pairs.

    Raw ids are remapped to contiguous [0, N) by ascending raw id (C10's
    remap; GraphX tolerated sparse ids, we normalize them away). If
    ``num_nodes`` is given, ids are assumed already contiguous in [0, N).
    """
    pairs = np.asarray(pairs, dtype=np.int64).reshape(-1, 2)
    if num_nodes is None:
        raw_ids, remapped = np.unique(pairs, return_inverse=True)
        pairs = remapped.reshape(-1, 2)
        n = int(raw_ids.shape[0])
    else:
        n = int(num_nodes)
        raw_ids = np.arange(n, dtype=np.int64)
        if pairs.size and (pairs.min() < 0 or pairs.max() >= n):
            raise ValueError("edge endpoint out of range for given num_nodes")

    # drop self-loops
    keep = pairs[:, 0] != pairs[:, 1]
    pairs = pairs[keep]

    # symmetrize: every edge in both directions, then dedup directed pairs
    both = np.concatenate([pairs, pairs[:, ::-1]], axis=0)
    # dedup via a single int64 key (n < 2^31 assumed for the key packing)
    key = both[:, 0] * np.int64(n) + both[:, 1]
    key = np.unique(key)
    src = (key // n).astype(np.int32)
    dst = (key % n).astype(np.int32)

    # CSR: keys are sorted by (src, dst) already
    counts = np.bincount(src, minlength=n)
    indptr = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(counts, out=indptr[1:])
    return Graph(indptr=indptr, indices=dst, raw_ids=raw_ids)


def build_graph(path: str) -> Graph:
    """Load a SNAP edge-list file into a symmetric CSR Graph."""
    return graph_from_edges(load_edge_list(path))
