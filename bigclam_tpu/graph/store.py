"""Out-of-core sharded graph store: compile once, load per host.

The north-star run (com-Friendster on a v5e-64) cannot afford the seed data
path — every host parsing ~30 GB of text and materializing the full CSR
before the first device step. This module is the input-pipeline layer between
raw SNAP text and the device trainers:

* ``compile_graph_cache`` builds a write-once **binary shard cache** from an
  edge list without ever holding the edge set in RAM: the streaming scanner
  (graph/stream.py) spills parsed pairs chunk by chunk, a scatter pass
  buckets directed edges by owner node range, a per-bucket lexsort dedups
  (no packed-key node-count ceiling — see graph/ingest.dedup_directed), and
  the result is written as per-node-range packed CSR shards
  (``indptr``/``indices`` npy blobs). Peak RSS is O(chunk + bucket + N),
  never O(E) or O(file). With ``balance=True`` the degree-balance
  permutation (parallel/balance.py) is baked into the shards at compile
  time, so a multi-host job loads already-balanced node ranges.
* a versioned JSON **manifest** records the format version, N/E, the shard
  table (node ranges + per-shard directed-edge counts) and a crc32 per blob;
  loads verify the version and checksums, so a stale or corrupted cache is
  rejected instead of silently mis-training.
* ``GraphStore.load_shard`` / ``load_shard_range`` give **per-host loading**:
  a host reads exactly the shard files for the node-contiguous ranges its
  devices own (wired through parallel/multihost.load_host_shard and the
  store-backed trainer in parallel/sharded.py) — no host ever assembles the
  global CSR. ``load_graph`` assembles the full ``Graph`` (bit-identical to
  ``build_graph`` on the same text for unbalanced caches) for single-host
  runs and as the mmap-backed fast reload behind ``cli --cache-dir``.

Cache directory layout::

    manifest.json
    raw_ids.npy                  original node id of each compact id
    perm.npy                     (balanced caches) old id -> new id
    shard_00000.indptr.npy       per-shard local CSR row pointers (rebased)
    shard_00000.indices.npy      per-shard neighbor lists (global int32 ids)
    ...
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
import sys
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from bigclam_tpu.graph.csr import Graph
from bigclam_tpu.graph.ingest import dedup_directed
from bigclam_tpu.graph.stream import DEFAULT_CHUNK_BYTES, stream_edge_list

MANIFEST_VERSION = 1
MANIFEST_NAME = "manifest.json"
QUARANTINE_DIR = "quarantine"


class ShardCorruption(ValueError):
    """A cache blob failed its manifest crc32 (or rebuild could not
    reproduce it). Carries the shard index when the blob belongs to one,
    so the self-heal path knows what to quarantine."""

    def __init__(self, msg: str, shard: Optional[int] = None):
        super().__init__(msg)
        self.shard = shard


def is_cache_dir(path: str) -> bool:
    """True when `path` is a graph-cache directory (has a manifest)."""
    return os.path.isdir(path) and os.path.exists(
        os.path.join(path, MANIFEST_NAME)
    )


def _crc32_file(path: str, bufsize: int = 1 << 22) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            buf = f.read(bufsize)
            if not buf:
                return crc
            crc = zlib.crc32(buf, crc)


def _shard_files(s: int) -> Tuple[str, str]:
    return f"shard_{s:05d}.indptr.npy", f"shard_{s:05d}.indices.npy"


@dataclasses.dataclass(frozen=True)
class HostShard:
    """The node-contiguous slice of a cached graph one host loads.

    ``indptr`` is rebased to 0 at ``lo`` (length hi - lo + 1); ``indices``
    keep GLOBAL destination ids, so device code slices F rows without any
    further translation. ``shard_edge_counts`` covers ALL shards (from the
    manifest), letting every host agree on padded edge-block geometry
    without touching another host's files — ``files_read`` records exactly
    which blobs were opened, so tests can pin the isolation contract.
    """

    lo: int
    hi: int
    indptr: np.ndarray
    indices: np.ndarray
    num_nodes: int
    num_directed_edges: int
    rows_per_shard: int
    shard_ids: Tuple[int, ...]
    shard_edge_counts: Tuple[int, ...]
    files_read: Tuple[str, ...]

    @property
    def num_local_nodes(self) -> int:
        return self.hi - self.lo


class GraphStore:
    """Handle on a compiled cache directory (validated manifest).

    With ``self_heal=True`` (ISSUE 5: shard quarantine + re-ingest) a
    crc32-failed SHARD blob is moved to ``quarantine/``, rebuilt from the
    source edge list for just its node range (``rebuild_shard``), the
    manifest re-stamped, and the load retried — a pod run degrades and
    heals instead of dying. Default False: library opens keep the strict
    reject-on-mismatch contract; the CLI turns healing on.
    """

    def __init__(self, directory: str, manifest: dict,
                 self_heal: bool = False):
        self.directory = directory
        self.manifest = manifest
        self.self_heal = self_heal

    @classmethod
    def open(cls, directory: str, self_heal: bool = False) -> "GraphStore":
        mpath = os.path.join(directory, MANIFEST_NAME)
        try:
            with open(mpath) as f:
                manifest = json.load(f)
        except (OSError, ValueError) as e:
            raise ValueError(f"{directory}: not a graph cache ({e})") from e
        version = manifest.get("format_version")
        if version != MANIFEST_VERSION:
            raise ValueError(
                f"{directory}: cache format version {version!r} != "
                f"{MANIFEST_VERSION} (stale cache; re-run "
                "`python -m bigclam_tpu.cli ingest`)"
            )
        for key in ("num_nodes", "num_directed_edges", "num_shards",
                    "rows_per_shard", "shards", "files"):
            if key not in manifest:
                raise ValueError(f"{directory}: manifest missing {key!r}")
        if len(manifest["shards"]) != manifest["num_shards"]:
            raise ValueError(
                f"{directory}: shard table has {len(manifest['shards'])} "
                f"entries for num_shards={manifest['num_shards']}"
            )
        return cls(directory, manifest, self_heal=self_heal)

    # --- manifest accessors ---
    @property
    def num_nodes(self) -> int:
        return int(self.manifest["num_nodes"])

    @property
    def num_directed_edges(self) -> int:
        return int(self.manifest["num_directed_edges"])

    @property
    def num_shards(self) -> int:
        return int(self.manifest["num_shards"])

    @property
    def rows_per_shard(self) -> int:
        return int(self.manifest["rows_per_shard"])

    @property
    def balanced(self) -> bool:
        return bool(self.manifest.get("balanced", False))

    def shard_files(self, s: int) -> Tuple[str, str]:
        """Absolute (indptr, indices) blob paths of shard s."""
        entry = self.manifest["shards"][s]
        return (
            os.path.join(self.directory, entry["indptr"]),
            os.path.join(self.directory, entry["indices"]),
        )

    def node_range(self, s: int) -> Tuple[int, int]:
        entry = self.manifest["shards"][s]
        return int(entry["lo"]), int(entry["hi"])

    # --- loading ---
    def _load_blob(
        self,
        relname: str,
        crc: Optional[int],
        verify: bool,
        mmap: bool,
        files_read: List[str],
        shard: Optional[int] = None,
    ) -> np.ndarray:
        path = os.path.join(self.directory, relname)
        if verify:
            got = _crc32_file(path)
            if got != crc:
                raise ShardCorruption(
                    f"{path}: checksum mismatch (expected {crc}, got {got}) "
                    "— cache corrupted; re-run ingest",
                    shard=shard,
                )
        files_read.append(relname)
        return np.load(path, mmap_mode="r" if mmap else None)

    def _load_shard_blobs(
        self, s: int, verify: bool, mmap: bool, files_read: List[str]
    ) -> Tuple[np.ndarray, np.ndarray]:
        """One shard's (indptr, indices), crc-checked; the self-heal path
        quarantines+rebuilds on a checksum failure and retries ONCE (a
        rebuild that still mismatches propagates — the source is bad)."""
        entry = self.manifest["shards"][s]
        # fault-injection site (resilience.faults): corrupt this shard's
        # indices blob just before the crc check
        from bigclam_tpu.resilience import faults as _faults

        spec = _faults.maybe_fire("store.load_shard", shard=s)
        if spec is not None and spec["kind"] == "corrupt_shard":
            _faults.apply_file_fault(
                spec, os.path.join(self.directory, entry["indices"])
            )
        try:
            return self._read_shard_blobs(s, entry, verify, mmap, files_read)
        except ShardCorruption as e:
            if not self.self_heal:
                raise
            self.quarantine_and_rebuild(s, reason=str(e))
            entry = self.manifest["shards"][s]    # crc may be re-stamped
            return self._read_shard_blobs(s, entry, verify, mmap, files_read)

    def _read_shard_blobs(self, s, entry, verify, mmap, files_read):
        ip = self._load_blob(
            entry["indptr"], entry["crc32"]["indptr"], verify, mmap,
            files_read, shard=s,
        ).astype(np.int64, copy=False)
        dp = self._load_blob(
            entry["indices"], entry["crc32"]["indices"], verify, mmap,
            files_read, shard=s,
        )
        return ip, dp

    def load_shard_range(
        self,
        first_shard: int,
        last_shard: int,
        verify: bool = True,
        mmap: bool = False,
    ) -> HostShard:
        """Assemble shards [first_shard, last_shard) into one contiguous
        HostShard, reading ONLY those shards' blobs."""
        S = self.num_shards
        if not (0 <= first_shard < last_shard <= S):
            raise ValueError(
                f"shard range [{first_shard}, {last_shard}) outside [0, {S})"
            )
        files_read: List[str] = []
        entries = self.manifest["shards"][first_shard:last_shard]
        iparts, dparts = [], []
        for off in range(first_shard, last_shard):
            ip, dp = self._load_shard_blobs(off, verify, mmap, files_read)
            iparts.append(ip)
            dparts.append(dp)
        lo = int(entries[0]["lo"])
        hi = int(entries[-1]["hi"])
        indptr = np.zeros(hi - lo + 1, dtype=np.int64)
        offset = 0
        row = 0
        for part in iparts:
            rows = part.shape[0] - 1
            indptr[row : row + rows + 1] = part + offset
            offset = int(indptr[row + rows])
            row += rows
        indices = (
            np.concatenate(dparts)
            if len(dparts) > 1
            else np.asarray(dparts[0])
        ).astype(np.int32, copy=False)
        if indptr[-1] != indices.shape[0]:
            raise ValueError(
                f"{self.directory}: shard range [{first_shard}, "
                f"{last_shard}) indptr/indices length mismatch "
                f"({int(indptr[-1])} vs {indices.shape[0]})"
            )
        return HostShard(
            lo=lo,
            hi=hi,
            indptr=indptr,
            indices=indices,
            num_nodes=self.num_nodes,
            num_directed_edges=self.num_directed_edges,
            rows_per_shard=self.rows_per_shard,
            shard_ids=tuple(range(first_shard, last_shard)),
            shard_edge_counts=tuple(
                int(e["edges"]) for e in self.manifest["shards"]
            ),
            files_read=tuple(files_read),
        )

    def load_shard(
        self, host_id: int, num_hosts: int, verify: bool = True
    ) -> HostShard:
        """The node-contiguous shard slice host `host_id` of `num_hosts`
        owns (requires num_shards % num_hosts == 0 — compile the cache with
        one shard per node-shard of the target mesh)."""
        S = self.num_shards
        if num_hosts <= 0 or S % num_hosts != 0:
            raise ValueError(
                f"num_shards={S} not divisible by num_hosts={num_hosts}"
            )
        if not (0 <= host_id < num_hosts):
            raise ValueError(f"host_id={host_id} outside [0, {num_hosts})")
        per = S // num_hosts
        return self.load_shard_range(
            host_id * per, (host_id + 1) * per, verify=verify
        )

    def load_raw_ids(self, verify: bool = True) -> np.ndarray:
        entry = self.manifest["files"]["raw_ids"]
        return np.asarray(
            self._load_blob(entry["name"], entry["crc32"], verify, False, [])
        )

    def load_perm(self, verify: bool = True) -> Optional[np.ndarray]:
        """The baked-in balance permutation (old id -> new id), or None for
        unbalanced caches."""
        entry = self.manifest["files"].get("perm")
        if entry is None:
            return None
        return np.asarray(
            self._load_blob(entry["name"], entry["crc32"], verify, False, [])
        )

    def load_graph(self, verify: bool = True, mmap: bool = True) -> Graph:
        """Assemble the full Graph from every shard (the fast single-host
        reload path: binary blobs, optionally mmap-read — no text parse,
        no remap, no dedup)."""
        import time

        t0 = time.perf_counter()
        hs = self.load_shard_range(0, self.num_shards, verify=verify,
                                   mmap=mmap)
        g = Graph(
            indptr=hs.indptr,
            indices=np.ascontiguousarray(hs.indices),
            raw_ids=self.load_raw_ids(verify=verify),
        )
        from bigclam_tpu.obs import telemetry as _obs

        tel = _obs.current()
        if tel is not None:
            tel.event(
                "graph_load",
                source="cache",
                path=self.directory,
                nodes=self.num_nodes,
                directed_edges=self.num_directed_edges,
                seconds=round(time.perf_counter() - t0, 4),
                mmap=bool(mmap),
            )
        return g

    # --- quarantine + re-ingest (ISSUE 5) ---
    def quarantine_and_rebuild(self, s: int, reason: str = "") -> None:
        """Rebuild shard `s` from the source edge list for just its node
        range, move the corrupt blobs to quarantine/, and re-stamp the
        manifest. The rebuild runs FIRST (_rebuild_shard_arrays): when it
        is impossible (source missing / changed / raw-id table corrupt)
        the ShardCorruption propagates with the cache left exactly as
        found — still diagnosable by its checksum error, never stripped
        of files the manifest references. Emits one `quarantine`
        telemetry event on success."""
        entry = self.manifest["shards"][s]
        local_indptr, indices = self._rebuild_shard_arrays(s)
        qdir = os.path.join(self.directory, QUARANTINE_DIR)
        os.makedirs(qdir, exist_ok=True)
        moved = []
        for rel in (entry["indptr"], entry["indices"]):
            src = os.path.join(self.directory, rel)
            if os.path.exists(src):
                dst = os.path.join(qdir, rel)
                n = 0
                while os.path.exists(dst):      # keep every incident
                    n += 1
                    dst = os.path.join(qdir, f"{rel}.{n}")
                os.replace(src, dst)
                moved.append(os.path.basename(dst))
        restamped = self._write_shard_blobs(s, local_indptr, indices)
        print(
            f"warning: shard {s} of {self.directory} quarantined and "
            f"rebuilt from source ({reason or 'checksum mismatch'})",
            file=sys.stderr,
        )
        from bigclam_tpu.obs import telemetry as _obs

        tel = _obs.current()
        if tel is not None:
            tel.event(
                "quarantine",
                shard=s,
                reason=reason[:200],
                quarantined=moved,
                crc_restamped=restamped,
                cache_dir=self.directory,
            )

    def rebuild_shard(self, s: int) -> bool:
        """Re-ingest shard `s` alone and write fresh blobs in place.
        Returns True when the manifest crc had to be re-stamped (a
        byte-identical rebuild leaves it untouched)."""
        local_indptr, indices = self._rebuild_shard_arrays(s)
        return self._write_shard_blobs(s, local_indptr, indices)

    def _rebuild_shard_arrays(self, s: int):
        """Re-ingest shard `s` IN MEMORY: stream the source edge list,
        remap raw ids through the cache's raw-id table (covers balanced
        caches — raw_ids.npy is stored in final node order), keep
        directed edges whose source row falls in this shard's node range,
        dedup, and validate against the manifest's edge count. Touches no
        cache files, so callers can sequence it before any destructive
        step."""
        entry = self.manifest["shards"][s]
        source = self.manifest.get("source", {}).get("path")
        if not source or not os.path.exists(source):
            raise ShardCorruption(
                f"{self.directory}: shard {s} corrupt and the source edge "
                f"list is unavailable ({source!r}) — cannot rebuild; "
                "re-run ingest",
                shard=s,
            )
        raw_final = self.load_raw_ids(verify=True)   # corrupt table: raise
        order = np.argsort(raw_final, kind="stable")
        raw_sorted = raw_final[order]
        n = self.num_nodes
        lo, hi = int(entry["lo"]), int(entry["hi"])
        parts: List[np.ndarray] = []
        for pairs in stream_edge_list(source, DEFAULT_CHUNK_BYTES):
            if pairs.size == 0:
                continue
            pos = np.searchsorted(raw_sorted, pairs)
            known = raw_sorted[np.minimum(pos, n - 1)] == pairs
            if not known.all():
                raise ShardCorruption(
                    f"{source}: contains node ids absent from the cache's "
                    "raw-id table — source changed since ingest; re-run "
                    "ingest",
                    shard=s,
                )
            mapped = order[pos]
            mapped = mapped[mapped[:, 0] != mapped[:, 1]]
            both = np.concatenate([mapped, mapped[:, ::-1]], axis=0)
            keep = both[(both[:, 0] >= lo) & (both[:, 0] < hi)]
            if keep.size:
                parts.append(keep)
        both = (
            np.concatenate(parts, axis=0)
            if parts
            else np.empty((0, 2), dtype=np.int64)
        )
        src, dst = dedup_directed(both)
        local_indptr = np.zeros(hi - lo + 1, dtype=np.int64)
        if src.size:
            np.cumsum(
                np.bincount(src - lo, minlength=hi - lo),
                out=local_indptr[1:],
            )
        indices = dst.astype(np.int32)
        if int(indices.shape[0]) != int(entry["edges"]):
            raise ShardCorruption(
                f"{self.directory}: shard {s} rebuild produced "
                f"{indices.shape[0]} directed edges, manifest says "
                f"{entry['edges']} — source changed since ingest; re-run "
                "ingest",
                shard=s,
            )
        return local_indptr, indices

    def _write_shard_blobs(
        self, s: int, local_indptr: np.ndarray, indices: np.ndarray
    ) -> bool:
        """Atomically install rebuilt blobs for shard `s` and re-stamp
        the manifest crc when the bytes differ; True iff re-stamped."""
        entry = self.manifest["shards"][s]
        for rel, arr in ((entry["indptr"], local_indptr),
                         (entry["indices"], indices)):
            path = os.path.join(self.directory, rel)
            tmp = f"{path}.{os.getpid()}.tmp"
            with open(tmp, "wb") as f:
                np.save(f, arr)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        new_crc = {
            "indptr": _crc32_file(
                os.path.join(self.directory, entry["indptr"])
            ),
            "indices": _crc32_file(
                os.path.join(self.directory, entry["indices"])
            ),
        }
        restamped = new_crc != entry["crc32"]
        if restamped:
            entry["crc32"] = new_crc
            _atomic_json(
                os.path.join(self.directory, MANIFEST_NAME), self.manifest
            )
        return restamped


# --------------------------------------------------------------------------
# compile: text -> cache, out of core
# --------------------------------------------------------------------------


class _BucketWriter:
    """Append-only int64 pair spill files, one per node-range bucket."""

    def __init__(self, directory: str, num_buckets: int, tag: str):
        os.makedirs(directory, exist_ok=True)
        self.paths = [
            os.path.join(directory, f"{tag}_{b:05d}.bin")
            for b in range(num_buckets)
        ]
        self._handles = [open(p, "ab") for p in self.paths]

    def append(self, bucket: int, pairs: np.ndarray) -> None:
        if pairs.size:
            self._handles[bucket].write(
                np.ascontiguousarray(pairs, dtype=np.int64).tobytes()
            )

    def close(self) -> None:
        for h in self._handles:
            h.close()

    def read(self, bucket: int) -> np.ndarray:
        return np.fromfile(self.paths[bucket], dtype=np.int64).reshape(-1, 2)


def _scatter_by_bucket(
    pairs: np.ndarray, rows: int, writer: _BucketWriter
) -> None:
    """Append each directed pair to the bucket owning its source node."""
    if pairs.shape[0] == 0:
        return
    bidx = pairs[:, 0] // rows
    order = np.argsort(bidx, kind="stable")
    pairs = pairs[order]
    bidx = bidx[order]
    bounds = np.flatnonzero(np.diff(bidx)) + 1
    starts = np.concatenate([[0], bounds])
    ends = np.concatenate([bounds, [pairs.shape[0]]])
    for s, e in zip(starts, ends):
        writer.append(int(bidx[s]), pairs[s:e])


def _merge_sorted_unique(table: np.ndarray, chunk: np.ndarray) -> np.ndarray:
    """Fold a chunk's ids into the sorted unique id table WITHOUT re-sorting
    the table (np.union1d re-sorts all N ids per chunk — O(chunks * N log N)
    across a Friendster-scale scan): unique the chunk, drop ids already in
    the table via searchsorted, merge-insert the rest. O(N + m) per chunk.
    """
    ids = np.unique(chunk)
    if table.size == 0:
        return ids
    if ids.size == 0:
        return table
    pos = np.searchsorted(table, ids)
    known = table[np.minimum(pos, table.size - 1)] == ids
    fresh = ids[~known]
    if fresh.size == 0:
        return table
    return np.insert(table, np.searchsorted(table, fresh), fresh)


def _atomic_json(path: str, obj: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=2, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def compile_graph_cache(
    text_path: str,
    cache_dir: str,
    num_shards: int = 8,
    chunk_bytes: int = DEFAULT_CHUNK_BYTES,
    workers: int = 0,
    balance: bool = False,
    overwrite: bool = False,
    profile=None,
) -> GraphStore:
    """Compile a SNAP edge list into a binary shard cache, out of core.

    Stages (each a `profile` stage when an IngestProfile is passed):
      scan     stream newline-snapped chunks, spill parsed raw pairs to
               disk, merge the sorted unique raw-id table (O(chunk + N) RSS)
      scatter  remap raw ids -> compact [0, N), drop self-loops, symmetrize,
               bucket directed pairs by owner node range
      dedup    per-bucket lexsort + duplicate-row drop (duplicates of an
               edge always land in the same bucket, so local dedup is
               globally exact); exact deduped degrees fall out here
      shards   (balance=True: relabel through the balance permutation and
               re-scatter first) write per-shard packed CSR blobs + the
               versioned manifest with per-blob crc32s

    Shard s owns node rows [s*rows, (s+1)*rows) with
    rows = ceil(max(N, num_shards) / num_shards) — exactly the contiguous
    ranges the sharded trainers slice on a dp=num_shards mesh, so a baked
    balance permutation (balance_permutation(degrees, num_shards, rows *
    num_shards)) is the same relabeling ShardedBigClamModel(balance=True)
    would compute at model build.
    """
    if num_shards <= 0:
        raise ValueError(f"num_shards must be positive, got {num_shards}")
    manifest_path = os.path.join(cache_dir, MANIFEST_NAME)
    if os.path.exists(manifest_path):
        if not overwrite:
            raise FileExistsError(
                f"{cache_dir}: cache already compiled (pass overwrite=True "
                "/ --overwrite to rebuild)"
            )
        # drop the OLD manifest (and its blobs) before rebuilding: a crash
        # mid-rebuild must leave an unrecognizable directory, never an
        # old manifest validating over mixed old/new shard files
        os.unlink(manifest_path)
        for name in os.listdir(cache_dir):
            if name.endswith(".npy") and (
                name.startswith("shard_") or name in ("raw_ids.npy",
                                                      "perm.npy")
            ):
                os.unlink(os.path.join(cache_dir, name))
    os.makedirs(cache_dir, exist_ok=True)
    spill_dir = os.path.join(cache_dir, "_spill")
    if os.path.exists(spill_dir):
        shutil.rmtree(spill_dir)
    os.makedirs(spill_dir)

    if profile is None:
        from bigclam_tpu.utils.profiling import IngestProfile

        profile = IngestProfile()

    try:
        return _compile(
            text_path, cache_dir, spill_dir, manifest_path, num_shards,
            chunk_bytes, workers, balance, profile,
        )
    finally:
        shutil.rmtree(spill_dir, ignore_errors=True)


def _compile(
    text_path, cache_dir, spill_dir, manifest_path, num_shards,
    chunk_bytes, workers, balance, profile,
) -> GraphStore:
    # --- scan: parse chunks, spill raw pairs, merge unique raw ids ---
    chunk_paths: List[str] = []
    raw_ids = np.empty(0, dtype=np.int64)
    raw_edges = 0
    with profile.stage("scan"):
        for i, pairs in enumerate(
            stream_edge_list(text_path, chunk_bytes, workers)
        ):
            cpath = os.path.join(spill_dir, f"chunk_{i:06d}.bin")
            pairs.tofile(cpath)
            chunk_paths.append(cpath)
            raw_edges += pairs.shape[0]
            raw_ids = _merge_sorted_unique(raw_ids, pairs)
            profile.count("chunks")
            profile.count("raw_edges", pairs.shape[0])
            profile.sample_rss()
    n = int(raw_ids.shape[0])
    if n > np.iinfo(np.int32).max:
        # dedup/remap are ceiling-free, but shard indices are int32 (the
        # Graph container's dtype): refuse instead of wrapping negative
        raise ValueError(
            f"num_nodes={n} exceeds the int32 CSR indices bound (2^31-1)"
        )
    rows = -(-max(n, num_shards) // num_shards)    # == trainers' n_pad // dp

    # --- scatter: remap, drop loops, symmetrize, bucket by src range ---
    buckets = _BucketWriter(spill_dir, num_shards, "bucket")
    with profile.stage("scatter"):
        for cpath in chunk_paths:
            pairs = np.fromfile(cpath, dtype=np.int64).reshape(-1, 2)
            os.unlink(cpath)
            pairs = np.searchsorted(raw_ids, pairs)
            pairs = pairs[pairs[:, 0] != pairs[:, 1]]
            both = np.concatenate([pairs, pairs[:, ::-1]], axis=0)
            _scatter_by_bucket(both, rows, buckets)
            profile.sample_rss()
    buckets.close()

    # --- dedup: per-bucket lexsort + unique rows; exact degrees ---
    degrees = np.zeros(max(n, 1), dtype=np.int64)
    deduped = _BucketWriter(spill_dir, num_shards, "dedup")
    with profile.stage("dedup"):
        for b in range(num_shards):
            both = buckets.read(b)
            os.unlink(buckets.paths[b])
            src, dst = dedup_directed(both)
            lo, hi = min(b * rows, n), min((b + 1) * rows, n)
            if src.size:
                degrees[lo:hi] += np.bincount(src - lo, minlength=hi - lo)
            deduped.append(b, np.stack([src, dst], axis=1))
            profile.sample_rss()
    deduped.close()

    # --- balance permutation (baked at compile time) ---
    perm = None
    if balance:
        # lazy: parallel/__init__ pulls in jax, which the default ingest
        # path must not pay for (RSS + import time on data-prep hosts)
        from bigclam_tpu.parallel.balance import balance_permutation

        perm = balance_permutation(degrees[:n], num_shards, rows * num_shards)

    # --- shards: (relabel + re-scatter when balanced,) write CSR blobs ---
    final = deduped
    if perm is not None:
        final = _BucketWriter(spill_dir, num_shards, "final")
        with profile.stage("shards"):
            for b in range(num_shards):
                arr = deduped.read(b)
                os.unlink(deduped.paths[b])
                _scatter_by_bucket(perm[arr], rows, final)
                profile.sample_rss()
        final.close()

    shard_table = []
    total_directed = 0
    with profile.stage("shards"):
        for s in range(num_shards):
            arr = final.read(s)
            os.unlink(final.paths[s])
            lo, hi = min(s * rows, n), min((s + 1) * rows, n)
            if perm is not None and arr.size:
                # re-scattered buckets are unsorted; dedup already happened
                order = np.lexsort((arr[:, 1], arr[:, 0]))
                arr = arr[order]
            local_indptr = np.zeros(hi - lo + 1, dtype=np.int64)
            if arr.size:
                np.cumsum(
                    np.bincount(arr[:, 0] - lo, minlength=hi - lo),
                    out=local_indptr[1:],
                )
            indices = arr[:, 1].astype(np.int32)
            iname, dname = _shard_files(s)
            np.save(os.path.join(cache_dir, iname), local_indptr)
            np.save(os.path.join(cache_dir, dname), indices)
            total_directed += int(indices.shape[0])
            shard_table.append(
                {
                    "lo": lo,
                    "hi": hi,
                    "edges": int(indices.shape[0]),
                    "indptr": iname,
                    "indices": dname,
                    "crc32": {
                        "indptr": _crc32_file(
                            os.path.join(cache_dir, iname)
                        ),
                        "indices": _crc32_file(
                            os.path.join(cache_dir, dname)
                        ),
                    },
                }
            )
            profile.count("directed_edges", int(indices.shape[0]))
            profile.sample_rss()

        # raw_ids in FINAL node order (balanced caches relabel rows)
        if perm is not None:
            raw_final = np.empty_like(raw_ids)
            raw_final[perm] = raw_ids
        else:
            raw_final = raw_ids
        np.save(os.path.join(cache_dir, "raw_ids.npy"), raw_final)
        files: Dict[str, dict] = {
            "raw_ids": {
                "name": "raw_ids.npy",
                "crc32": _crc32_file(os.path.join(cache_dir, "raw_ids.npy")),
            }
        }
        if perm is not None:
            np.save(os.path.join(cache_dir, "perm.npy"), perm)
            files["perm"] = {
                "name": "perm.npy",
                "crc32": _crc32_file(os.path.join(cache_dir, "perm.npy")),
            }

    manifest = {
        "format_version": MANIFEST_VERSION,
        "num_nodes": n,
        "num_directed_edges": total_directed,
        "num_undirected_edges": total_directed // 2,
        "num_shards": num_shards,
        "rows_per_shard": rows,
        "balanced": perm is not None,
        "dtypes": {"indptr": "int64", "indices": "int32",
                   "raw_ids": "int64"},
        "shards": shard_table,
        "files": files,
        "source": {
            "path": os.path.abspath(text_path),
            "bytes": os.path.getsize(text_path),
            "raw_pairs": raw_edges,
        },
    }
    _atomic_json(manifest_path, manifest)
    from bigclam_tpu.obs import telemetry as _obs

    tel = _obs.current()
    if tel is not None:
        tel.event(
            "ingest",
            edges=total_directed // 2,
            nodes=n,
            shards=num_shards,
            balanced=perm is not None,
            cache_dir=cache_dir,
        )
    return GraphStore(cache_dir, manifest)
