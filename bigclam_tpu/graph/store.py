"""Out-of-core sharded graph store: compile once, load per host.

The north-star run (com-Friendster on a v5e-64) cannot afford the seed data
path — every host parsing ~30 GB of text and materializing the full CSR
before the first device step. This module is the input-pipeline layer between
raw SNAP text and the device trainers:

* ``compile_graph_cache`` builds a write-once **binary shard cache** from an
  edge list without ever holding the edge set in RAM: the streaming scanner
  (graph/stream.py) spills parsed pairs chunk by chunk, a scatter pass
  buckets directed edges by owner node range, a per-bucket lexsort dedups
  (no packed-key node-count ceiling — see graph/ingest.dedup_directed), and
  the result is written as per-node-range packed CSR shards
  (``indptr``/``indices`` npy blobs). Peak RSS is O(chunk + bucket + N),
  never O(E) or O(file). With ``balance=True`` the degree-balance
  permutation (parallel/balance.py) is baked into the shards at compile
  time, so a multi-host job loads already-balanced node ranges.
* a versioned JSON **manifest** records the format version, N/E, the shard
  table (node ranges + per-shard directed-edge counts) and a crc32 per blob;
  loads verify the version and checksums, so a stale or corrupted cache is
  rejected instead of silently mis-training.
* ``GraphStore.load_shard`` / ``load_shard_range`` give **per-host loading**:
  a host reads exactly the shard files for the node-contiguous ranges its
  devices own (wired through parallel/multihost.load_host_shard and the
  store-backed trainer in parallel/sharded.py) — no host ever assembles the
  global CSR. ``load_graph`` assembles the full ``Graph`` (bit-identical to
  ``build_graph`` on the same text for unbalanced caches) for single-host
  runs and as the mmap-backed fast reload behind ``cli --cache-dir``.

Cache directory layout::

    manifest.json
    raw_ids.npy                  original node id of each compact id
    perm.npy                     (balanced caches) old id -> new id
    shard_00000.indptr.npy       per-shard local CSR row pointers (rebased)
    shard_00000.indices.npy      per-shard neighbor lists (global int32 ids)
    shard_00000.phi.npy          per-shard ingest-baked seed scores
                                 (ego-net conductance, float64; format v2)
    ...
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
import sys
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from bigclam_tpu.graph.csr import Graph
from bigclam_tpu.graph.ingest import dedup_directed
from bigclam_tpu.graph.stream import (
    DEFAULT_CHUNK_BYTES,
    BoundedBlobCache,
    stream_edge_list,
)

# v2 (ISSUE 9): ingest-baked per-node seed scores (shard_*.phi.npy +
# per-entry "phi" crc). v1 caches still LOAD (graceful migration — the
# graph bytes are identical); only load_seed_scores refuses on them, with
# a re-ingest hint, and fit-time seeding falls back to the streaming
# conductance pass.
# v3 (ISSUE 16): ingest-baked neighborhood-closure gather lists
# (shard_*.closure.npy + per-entry "closure" crc) — the per-shard-pair
# touched-row ids the 2D edge-block partition exchanges instead of a full
# F all_gather. Same migration contract: v1/v2 caches still LOAD; only
# load_closure_lists refuses on them (re-ingest hint), and the 2D
# trainers fall back to streaming the lists from the host's own CSR.
MANIFEST_VERSION = 3
SUPPORTED_MANIFEST_VERSIONS = (1, 2, 3)
MANIFEST_NAME = "manifest.json"
QUARANTINE_DIR = "quarantine"

# The EXACT seed-bake triangle pass expands sum_v deg(v)^2 two-hop entries
# — edge-quadratic on hubs, which SURVEY.md §7 flags as infeasible at the
# graph scale the store targets. Past this many entries an uncapped ingest
# SKIPS the bake with a --seed-cap hint instead of silently walling for
# hours (~a few minutes of vectorized sweep at the threshold).
SEED_BAKE_EXACT_MAX_WORK = 2e10


class ShardCorruption(ValueError):
    """A cache blob failed its manifest crc32 (or rebuild could not
    reproduce it). Carries the shard index when the blob belongs to one,
    so the self-heal path knows what to quarantine."""

    def __init__(self, msg: str, shard: Optional[int] = None):
        super().__init__(msg)
        self.shard = shard


def rows_of_raw_ids(values: np.ndarray, order: np.ndarray,
                    raw_sorted: np.ndarray):
    """Internal rows of raw node ids via the sorted raw-id table: the
    ONE remap used by the quarantine rebuild, the delta re-ingest, and
    the refit's touched-row discovery (models.refit) — the unknown-id
    clamp must never diverge between them. Returns (rows, known) with
    `rows` valid only where `known`; callers decide how an unknown id
    errors (source-changed vs delta-cannot-grow-N)."""
    pos = np.searchsorted(raw_sorted, values)
    clamped = np.minimum(pos, raw_sorted.size - 1)
    known = raw_sorted[clamped] == values
    return order[clamped], known


def is_cache_dir(path: str) -> bool:
    """True when `path` is a graph-cache directory (has a manifest)."""
    return os.path.isdir(path) and os.path.exists(
        os.path.join(path, MANIFEST_NAME)
    )


def _crc32_file(path: str, bufsize: int = 1 << 22) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            buf = f.read(bufsize)
            if not buf:
                return crc
            crc = zlib.crc32(buf, crc)


def _shard_files(s: int) -> Tuple[str, str]:
    return f"shard_{s:05d}.indptr.npy", f"shard_{s:05d}.indices.npy"


@dataclasses.dataclass(frozen=True)
class HostShard:
    """The node-contiguous slice of a cached graph one host loads.

    ``indptr`` is rebased to 0 at ``lo`` (length hi - lo + 1); ``indices``
    keep GLOBAL destination ids, so device code slices F rows without any
    further translation. ``shard_edge_counts`` covers ALL shards (from the
    manifest), letting every host agree on padded edge-block geometry
    without touching another host's files — ``files_read`` records exactly
    which blobs were opened, so tests can pin the isolation contract.
    """

    lo: int
    hi: int
    indptr: np.ndarray
    indices: np.ndarray
    num_nodes: int
    num_directed_edges: int
    rows_per_shard: int
    shard_ids: Tuple[int, ...]
    shard_edge_counts: Tuple[int, ...]
    files_read: Tuple[str, ...]

    @property
    def num_local_nodes(self) -> int:
        return self.hi - self.lo


@dataclasses.dataclass(frozen=True)
class ShardSeedScores:
    """A node-contiguous slice of the ingest-baked per-node seed scores
    (ego-net conductance phi, float64). Same files_read isolation contract
    as HostShard: a host reads exactly the phi blobs of its own shards.
    `cap`/`seed` echo the bake's estimator parameters so fit-time callers
    can check they match the run's seeding config before trusting the
    scores (cli._init_F falls back to the streaming pass on mismatch)."""

    lo: int
    hi: int
    phi: np.ndarray
    cap: Optional[int]
    seed: Optional[int]
    files_read: Tuple[str, ...]

    def matches(self, cap: Optional[int], seed: int) -> bool:
        """True when the baked estimator agrees with a fit that would
        stream with `seeding_degree_cap=cap, seed=seed` (the stream seed
        only matters once a cap engages the sampler)."""
        return self.cap == cap and (cap is None or self.seed == seed)


@dataclasses.dataclass(frozen=True)
class ShardClosure:
    """One shard's baked neighborhood-closure gather lists (ISSUE 16).

    Per peer shard b (== a 2D trainer node block when num_shards == R*C):

      * ``out_ids[b]``   — sorted unique GLOBAL dst ids this shard's edges
        touch inside b: the rows this shard must GATHER from b's owner.
      * ``in_ids[b]``    — sorted unique GLOBAL row ids of THIS shard that
        have >= 1 edge into b: the rows this shard must SEND to b's owner.
        By edge symmetry in_ids(s)[b] == out_ids(b)[s] — each side of an
        exchange derives its half from its OWN blob, which is what keeps
        the per-host files_read isolation contract intact.
      * ``edge_counts[b]`` — directed edges from this shard into b, so
        every host agrees on padded 2D edge-block geometry manifest-only.

    A ``None`` list is the capped-buffer overflow sentinel (the bake's
    per-pair cap was exceeded): consumers degrade that pair to the full
    dst block — correctness is never cap-dependent, only bytes."""

    out_ids: Tuple[Optional[np.ndarray], ...]
    in_ids: Tuple[Optional[np.ndarray], ...]
    edge_counts: Tuple[int, ...]


@dataclasses.dataclass(frozen=True)
class ShardClosureLists:
    """Closure lists for a host's shard range, same files_read isolation
    contract as HostShard: exactly the owned shards' closure blobs are
    opened. ``cap`` echoes the bake's per-pair cap (0 = uncapped)."""

    shards: Dict[int, "ShardClosure"]
    cap: int
    files_read: Tuple[str, ...]


class GraphStore:
    """Handle on a compiled cache directory (validated manifest).

    With ``self_heal=True`` (ISSUE 5: shard quarantine + re-ingest) a
    crc32-failed SHARD blob is moved to ``quarantine/``, rebuilt from the
    source edge list for just its node range (``rebuild_shard``), the
    manifest re-stamped, and the load retried — a pod run degrades and
    heals instead of dying. Default False: library opens keep the strict
    reject-on-mismatch contract; the CLI turns healing on.
    """

    def __init__(self, directory: str, manifest: dict,
                 self_heal: bool = False):
        self.directory = directory
        self.manifest = manifest
        self.self_heal = self_heal

    @classmethod
    def open(cls, directory: str, self_heal: bool = False) -> "GraphStore":
        mpath = os.path.join(directory, MANIFEST_NAME)
        try:
            with open(mpath) as f:
                manifest = json.load(f)
        except (OSError, ValueError) as e:
            raise ValueError(f"{directory}: not a graph cache ({e})") from e
        version = manifest.get("format_version")
        if version not in SUPPORTED_MANIFEST_VERSIONS:
            raise ValueError(
                f"{directory}: cache format version {version!r} not in "
                f"{SUPPORTED_MANIFEST_VERSIONS} (stale cache; re-run "
                "`python -m bigclam_tpu.cli ingest`)"
            )
        for key in ("num_nodes", "num_directed_edges", "num_shards",
                    "rows_per_shard", "shards", "files"):
            if key not in manifest:
                raise ValueError(f"{directory}: manifest missing {key!r}")
        if len(manifest["shards"]) != manifest["num_shards"]:
            raise ValueError(
                f"{directory}: shard table has {len(manifest['shards'])} "
                f"entries for num_shards={manifest['num_shards']}"
            )
        return cls(directory, manifest, self_heal=self_heal)

    # --- manifest accessors ---
    @property
    def num_nodes(self) -> int:
        return int(self.manifest["num_nodes"])

    @property
    def num_directed_edges(self) -> int:
        return int(self.manifest["num_directed_edges"])

    @property
    def num_shards(self) -> int:
        return int(self.manifest["num_shards"])

    @property
    def rows_per_shard(self) -> int:
        return int(self.manifest["rows_per_shard"])

    @property
    def balanced(self) -> bool:
        return bool(self.manifest.get("balanced", False))

    @property
    def delta_seq(self) -> int:
        """How many edge deltas have been applied since compile (ISSUE
        15): 0 on a freshly compiled cache, bumped by every
        ``apply_delta``. Part of the cache's workload identity — two
        caches at different delta_seq hold different graphs."""
        return int(self.manifest.get("delta_seq", 0))

    def shard_files(self, s: int) -> Tuple[str, str]:
        """Absolute (indptr, indices) blob paths of shard s."""
        entry = self.manifest["shards"][s]
        return (
            os.path.join(self.directory, entry["indptr"]),
            os.path.join(self.directory, entry["indices"]),
        )

    def node_range(self, s: int) -> Tuple[int, int]:
        entry = self.manifest["shards"][s]
        return int(entry["lo"]), int(entry["hi"])

    def node_ranges(self) -> List[Tuple[int, int]]:
        """[(lo, hi)] of every cache shard, manifest order — the
        manifest-driven range map the serving fleet's publication and
        routing table derive from (ISSUE 18)."""
        return [self.node_range(s) for s in range(self.num_shards)]

    def host_ranges(self, num_hosts: int) -> List[Tuple[int, int]]:
        """Node ranges of an even `num_hosts` split of the cache shards
        (load_shard geometry: num_shards % num_hosts == 0) — the row
        ranges `cli fit --publish-shards` publishes one fleet shard
        archive per."""
        S = self.num_shards
        if num_hosts <= 0 or S % num_hosts != 0:
            raise ValueError(
                f"num_shards={S} not divisible by num_hosts={num_hosts}"
            )
        per = S // num_hosts
        return [
            (
                self.node_range(h * per)[0],
                self.node_range((h + 1) * per - 1)[1],
            )
            for h in range(num_hosts)
        ]

    # --- loading ---
    def _load_blob(
        self,
        relname: str,
        crc: Optional[int],
        verify: bool,
        mmap: bool,
        files_read: List[str],
        shard: Optional[int] = None,
    ) -> np.ndarray:
        path = os.path.join(self.directory, relname)
        if verify:
            got = _crc32_file(path)
            if got != crc:
                raise ShardCorruption(
                    f"{path}: checksum mismatch (expected {crc}, got {got}) "
                    "— cache corrupted; re-run ingest",
                    shard=shard,
                )
        files_read.append(relname)
        return np.load(path, mmap_mode="r" if mmap else None)

    def _load_shard_blobs(
        self, s: int, verify: bool, mmap: bool, files_read: List[str]
    ) -> Tuple[np.ndarray, np.ndarray]:
        """One shard's (indptr, indices), crc-checked; the self-heal path
        quarantines+rebuilds on a checksum failure and retries ONCE (a
        rebuild that still mismatches propagates — the source is bad)."""
        entry = self.manifest["shards"][s]
        # fault-injection site (resilience.faults): corrupt this shard's
        # indices blob just before the crc check
        from bigclam_tpu.resilience import faults as _faults

        spec = _faults.maybe_fire("store.load_shard", shard=s)
        if spec is not None and spec["kind"] == "corrupt_shard":
            _faults.apply_file_fault(
                spec, os.path.join(self.directory, entry["indices"])
            )
        try:
            return self._read_shard_blobs(s, entry, verify, mmap, files_read)
        except ShardCorruption as e:
            if not self.self_heal:
                raise
            self.quarantine_and_rebuild(s, reason=str(e))
            entry = self.manifest["shards"][s]    # crc may be re-stamped
            return self._read_shard_blobs(s, entry, verify, mmap, files_read)

    def _read_shard_blobs(self, s, entry, verify, mmap, files_read):
        ip = self._load_blob(
            entry["indptr"], entry["crc32"]["indptr"], verify, mmap,
            files_read, shard=s,
        ).astype(np.int64, copy=False)
        dp = self._load_blob(
            entry["indices"], entry["crc32"]["indices"], verify, mmap,
            files_read, shard=s,
        )
        return ip, dp

    def workload(self) -> Dict:
        """Manifest-only workload numbers for the jax-free capacity
        preflight (`cli preflight` / obs.memory.preflight): sizes, the
        shard geometry, and the per-shard directed-edge counts — read
        without touching any blob, so the answer costs one JSON parse
        even for a Friendster-scale cache."""
        return {
            "n": self.num_nodes,
            "directed_edges": self.num_directed_edges,
            "num_shards": self.num_shards,
            "rows_per_shard": self.rows_per_shard,
            "balanced": self.balanced,
            "shard_edge_counts": [
                int(e["edges"]) for e in self.manifest["shards"]
            ],
            # 2D-partition closure summary (ISSUE 16): per-pair touched-row
            # counts straight off the manifest so `cli preflight` prices
            # the closure exchange exactly (-1 = capped-overflow pair ->
            # consumers degrade it to the full dst block).
            "closure": (
                {
                    "baked": True,
                    "cap": int(
                        self.manifest.get("closure", {}).get("cap", 0)
                    ),
                    "pair_counts": [
                        [int(c) for c in e["closure"]["out_counts"]]
                        for e in self.manifest["shards"]
                    ],
                }
                if all("closure" in e for e in self.manifest["shards"])
                else {"baked": False}
            ),
        }

    def load_shard_range(
        self,
        first_shard: int,
        last_shard: int,
        verify: bool = True,
        mmap: bool = False,
    ) -> HostShard:
        """Assemble shards [first_shard, last_shard) into one contiguous
        HostShard, reading ONLY those shards' blobs."""
        S = self.num_shards
        if not (0 <= first_shard < last_shard <= S):
            raise ValueError(
                f"shard range [{first_shard}, {last_shard}) outside [0, {S})"
            )
        files_read: List[str] = []
        entries = self.manifest["shards"][first_shard:last_shard]
        iparts, dparts = [], []
        for off in range(first_shard, last_shard):
            ip, dp = self._load_shard_blobs(off, verify, mmap, files_read)
            iparts.append(ip)
            dparts.append(dp)
        lo = int(entries[0]["lo"])
        hi = int(entries[-1]["hi"])
        indptr = np.zeros(hi - lo + 1, dtype=np.int64)
        offset = 0
        row = 0
        for part in iparts:
            rows = part.shape[0] - 1
            indptr[row : row + rows + 1] = part + offset
            offset = int(indptr[row + rows])
            row += rows
        indices = (
            np.concatenate(dparts)
            if len(dparts) > 1
            else np.asarray(dparts[0])
        ).astype(np.int32, copy=False)
        if indptr[-1] != indices.shape[0]:
            raise ValueError(
                f"{self.directory}: shard range [{first_shard}, "
                f"{last_shard}) indptr/indices length mismatch "
                f"({int(indptr[-1])} vs {indices.shape[0]})"
            )
        return HostShard(
            lo=lo,
            hi=hi,
            indptr=indptr,
            indices=indices,
            num_nodes=self.num_nodes,
            num_directed_edges=self.num_directed_edges,
            rows_per_shard=self.rows_per_shard,
            shard_ids=tuple(range(first_shard, last_shard)),
            shard_edge_counts=tuple(
                int(e["edges"]) for e in self.manifest["shards"]
            ),
            files_read=tuple(files_read),
        )

    def load_shard(
        self, host_id: int, num_hosts: int, verify: bool = True
    ) -> HostShard:
        """The node-contiguous shard slice host `host_id` of `num_hosts`
        owns (requires num_shards % num_hosts == 0 — compile the cache with
        one shard per node-shard of the target mesh)."""
        S = self.num_shards
        if num_hosts <= 0 or S % num_hosts != 0:
            raise ValueError(
                f"num_shards={S} not divisible by num_hosts={num_hosts}"
            )
        if not (0 <= host_id < num_hosts):
            raise ValueError(f"host_id={host_id} outside [0, {num_hosts})")
        per = S // num_hosts
        return self.load_shard_range(
            host_id * per, (host_id + 1) * per, verify=verify
        )

    def load_seed_scores(
        self,
        first_shard: int = 0,
        last_shard: Optional[int] = None,
        verify: bool = True,
    ) -> ShardSeedScores:
        """The ingest-baked per-node conductance scores of shards
        [first_shard, last_shard), reading ONLY those shards' phi blobs.

        Raises ValueError with a re-ingest hint on caches compiled before
        the seed bake existed (format v1) or with the bake disabled —
        callers (cli seeding) degrade to the streaming conductance pass."""
        S = self.num_shards
        last = S if last_shard is None else last_shard
        if not (0 <= first_shard < last <= S):
            raise ValueError(
                f"shard range [{first_shard}, {last}) outside [0, {S})"
            )
        entries = self.manifest["shards"][first_shard:last]
        if any("phi" not in e for e in entries):
            raise ValueError(
                f"{self.directory}: cache has no baked seed scores "
                "(compiled before format v2, or with the seed bake "
                "disabled) — re-ingest to bake seeds "
                "(`python -m bigclam_tpu.cli ingest`), or use a "
                "streaming --seed-backend"
            )
        files_read: List[str] = []
        parts = [
            np.asarray(
                self._load_blob(
                    e["phi"], e["crc32"].get("phi"), verify, False,
                    files_read, shard=first_shard + i,
                ),
                np.float64,
            )
            for i, e in enumerate(entries)
        ]
        meta = self.manifest.get("seed_scores", {})
        return ShardSeedScores(
            lo=int(entries[0]["lo"]),
            hi=int(entries[-1]["hi"]),
            phi=np.concatenate(parts) if len(parts) > 1 else parts[0],
            cap=meta.get("cap"),
            seed=meta.get("seed"),
            files_read=tuple(files_read),
        )

    def load_closure_lists(
        self,
        first_shard: int = 0,
        last_shard: Optional[int] = None,
        verify: bool = True,
    ) -> ShardClosureLists:
        """The ingest-baked neighborhood-closure gather lists of shards
        [first_shard, last_shard), reading ONLY those shards' closure
        blobs (ISSUE 16 — the 2D partition's per-host exchange sets).

        Raises ValueError with a re-ingest hint on caches compiled before
        format v3 or with the closure bake disabled — the 2D trainers
        degrade to streaming the lists from the host's own CSR instead."""
        S = self.num_shards
        last = S if last_shard is None else last_shard
        if not (0 <= first_shard < last <= S):
            raise ValueError(
                f"shard range [{first_shard}, {last}) outside [0, {S})"
            )
        entries = self.manifest["shards"][first_shard:last]
        if any("closure" not in e for e in entries):
            raise ValueError(
                f"{self.directory}: cache has no baked closure gather "
                "lists (compiled before format v3, or with the closure "
                "bake disabled) — re-ingest to bake closures "
                "(`python -m bigclam_tpu.cli ingest`); the 2D trainers "
                "fall back to streaming the lists from the cached CSR"
            )
        files_read: List[str] = []
        cap = int(self.manifest.get("closure", {}).get("cap", 0))
        shards: Dict[int, ShardClosure] = {}
        for i, e in enumerate(entries):
            meta = e["closure"]
            ids = np.asarray(
                self._load_blob(
                    meta["ids"], e["crc32"].get("closure"), verify, False,
                    files_read, shard=first_shard + i,
                ),
                np.int32,
            )
            out_counts = np.asarray(meta["out_counts"], dtype=np.int64)
            in_counts = np.asarray(meta["in_counts"], dtype=np.int64)
            lens = np.concatenate(
                [np.maximum(out_counts, 0), np.maximum(in_counts, 0)]
            )
            if int(lens.sum()) != ids.shape[0]:
                raise ShardCorruption(
                    f"{self.directory}: shard {first_shard + i} closure "
                    f"blob holds {ids.shape[0]} ids, manifest counts sum "
                    f"to {int(lens.sum())} — cache corrupted; re-run "
                    "ingest",
                    shard=first_shard + i,
                )
            bounds = np.concatenate([[0], np.cumsum(lens)])
            parts = [
                ids[bounds[j]:bounds[j + 1]] for j in range(lens.size)
            ]
            shards[first_shard + i] = ShardClosure(
                out_ids=tuple(
                    None if c < 0 else parts[b]
                    for b, c in enumerate(out_counts)
                ),
                in_ids=tuple(
                    None if c < 0 else parts[S + b]
                    for b, c in enumerate(in_counts)
                ),
                edge_counts=tuple(int(c) for c in meta["edge_counts"]),
            )
        return ShardClosureLists(
            shards=shards, cap=cap, files_read=tuple(files_read)
        )

    def load_raw_ids(self, verify: bool = True) -> np.ndarray:
        entry = self.manifest["files"]["raw_ids"]
        return np.asarray(
            self._load_blob(entry["name"], entry["crc32"], verify, False, [])
        )

    def load_perm(self, verify: bool = True) -> Optional[np.ndarray]:
        """The baked-in balance permutation (old id -> new id), or None for
        unbalanced caches."""
        entry = self.manifest["files"].get("perm")
        if entry is None:
            return None
        return np.asarray(
            self._load_blob(entry["name"], entry["crc32"], verify, False, [])
        )

    def load_graph(self, verify: bool = True, mmap: bool = True) -> Graph:
        """Assemble the full Graph from every shard (the fast single-host
        reload path: binary blobs, optionally mmap-read — no text parse,
        no remap, no dedup)."""
        import time

        t0 = time.perf_counter()
        hs = self.load_shard_range(0, self.num_shards, verify=verify,
                                   mmap=mmap)
        g = Graph(
            indptr=hs.indptr,
            indices=np.ascontiguousarray(hs.indices),
            raw_ids=self.load_raw_ids(verify=verify),
        )
        from bigclam_tpu.obs import telemetry as _obs

        tel = _obs.current()
        if tel is not None:
            tel.event(
                "graph_load",
                source="cache",
                path=self.directory,
                nodes=self.num_nodes,
                directed_edges=self.num_directed_edges,
                seconds=round(time.perf_counter() - t0, 4),
                mmap=bool(mmap),
            )
        return g

    # --- quarantine + re-ingest (ISSUE 5) ---
    def quarantine_and_rebuild(self, s: int, reason: str = "") -> None:
        """Rebuild shard `s` from the source edge list for just its node
        range, move the corrupt blobs to quarantine/, and re-stamp the
        manifest. The rebuild runs FIRST (_rebuild_shard_arrays): when it
        is impossible (source missing / changed / raw-id table corrupt)
        the ShardCorruption propagates with the cache left exactly as
        found — still diagnosable by its checksum error, never stripped
        of files the manifest references. Emits one `quarantine`
        telemetry event on success."""
        entry = self.manifest["shards"][s]
        local_indptr, indices = self._rebuild_shard_arrays(s)
        qdir = os.path.join(self.directory, QUARANTINE_DIR)
        os.makedirs(qdir, exist_ok=True)
        moved = []
        for rel in (entry["indptr"], entry["indices"]):
            src = os.path.join(self.directory, rel)
            if os.path.exists(src):
                dst = os.path.join(qdir, rel)
                n = 0
                while os.path.exists(dst):      # keep every incident
                    n += 1
                    dst = os.path.join(qdir, f"{rel}.{n}")
                os.replace(src, dst)
                moved.append(os.path.basename(dst))
        restamped = self._write_shard_blobs(s, local_indptr, indices)
        print(
            f"warning: shard {s} of {self.directory} quarantined and "
            f"rebuilt from source ({reason or 'checksum mismatch'})",
            file=sys.stderr,
        )
        from bigclam_tpu.obs import telemetry as _obs

        tel = _obs.current()
        if tel is not None:
            tel.event(
                "quarantine",
                shard=s,
                reason=reason[:200],
                quarantined=moved,
                crc_restamped=restamped,
                cache_dir=self.directory,
            )

    def rebuild_shard(self, s: int) -> bool:
        """Re-ingest shard `s` alone and write fresh blobs in place.
        Returns True when the manifest crc had to be re-stamped (a
        byte-identical rebuild leaves it untouched)."""
        local_indptr, indices = self._rebuild_shard_arrays(s)
        return self._write_shard_blobs(s, local_indptr, indices)

    def _raw_id_order(self):
        """(order, raw_sorted) of the cache's raw-id table — the raw ->
        internal-row translation every range-scoped edge source shares
        (covers balanced caches: raw_ids.npy is in FINAL node order)."""
        raw_final = self.load_raw_ids(verify=True)   # corrupt table: raise
        order = np.argsort(raw_final, kind="stable")
        return order, raw_final[order]

    def _mapped_range_pairs(
        self,
        path: str,
        lo: int,
        hi: int,
        order: np.ndarray,
        raw_sorted: np.ndarray,
        shard: Optional[int] = None,
        what: str = "source",
    ) -> np.ndarray:
        """RANGE-SCOPED edge source (ISSUE 15 satellite): stream ONE edge
        file and return the directed internal pairs whose source row
        falls in [lo, hi) — raw ids remapped through the cache's table,
        self-loops dropped, symmetrized. Shared by the quarantine rebuild
        (source + every recorded delta file) and apply_delta's touched-
        row discovery; unknown raw ids refuse with a re-ingest hint (the
        file changed since it was ingested)."""
        parts: List[np.ndarray] = []
        for pairs in stream_edge_list(path, DEFAULT_CHUNK_BYTES):
            if pairs.size == 0:
                continue
            mapped, known = rows_of_raw_ids(pairs, order, raw_sorted)
            if not known.all():
                raise ShardCorruption(
                    f"{path}: contains node ids absent from the cache's "
                    f"raw-id table — {what} changed since ingest; re-run "
                    "ingest",
                    shard=shard,
                )
            mapped = mapped[mapped[:, 0] != mapped[:, 1]]
            both = np.concatenate([mapped, mapped[:, ::-1]], axis=0)
            keep = both[(both[:, 0] >= lo) & (both[:, 0] < hi)]
            if keep.size:
                parts.append(keep)
        return (
            np.concatenate(parts, axis=0)
            if parts
            else np.empty((0, 2), dtype=np.int64)
        )

    def _delta_entries(self) -> List[dict]:
        """Manifest records of every applied delta (ISSUE 15): the
        quarantine rebuild must replay them on top of the source, and
        each is verified against its recorded size first (a delta file
        that changed since apply cannot reproduce the cache)."""
        return list(self.manifest.get("deltas", []))

    def _rebuild_shard_arrays(self, s: int):
        """Re-ingest shard `s` IN MEMORY: stream the source edge list
        PLUS every recorded delta file through the range-scoped edge
        source (_mapped_range_pairs), dedup, and validate against the
        manifest's edge count. Touches no cache files, so callers can
        sequence it before any destructive step."""
        entry = self.manifest["shards"][s]
        source = self.manifest.get("source", {}).get("path")
        if not source or not os.path.exists(source):
            raise ShardCorruption(
                f"{self.directory}: shard {s} corrupt and the source edge "
                f"list is unavailable ({source!r}) — cannot rebuild; "
                "re-run ingest",
                shard=s,
            )
        order, raw_sorted = self._raw_id_order()
        lo, hi = int(entry["lo"]), int(entry["hi"])
        parts = [
            self._mapped_range_pairs(
                source, lo, hi, order, raw_sorted, shard=s
            )
        ]
        for d in self._delta_entries():
            dpath = d.get("path")
            if not dpath or not os.path.exists(dpath):
                raise ShardCorruption(
                    f"{self.directory}: shard {s} rebuild needs applied "
                    f"delta file {dpath!r}, which is unavailable — "
                    "re-run ingest",
                    shard=s,
                )
            if "bytes" in d and os.path.getsize(dpath) != int(d["bytes"]):
                raise ShardCorruption(
                    f"{dpath}: size changed since it was applied "
                    f"({os.path.getsize(dpath)} vs {d['bytes']} bytes) — "
                    "delta file changed; re-run ingest",
                    shard=s,
                )
            parts.append(
                self._mapped_range_pairs(
                    dpath, lo, hi, order, raw_sorted, shard=s,
                    what="applied delta",
                )
            )
        both = np.concatenate([p for p in parts if p.size], axis=0) if any(
            p.size for p in parts
        ) else np.empty((0, 2), dtype=np.int64)
        src, dst = dedup_directed(both)
        local_indptr = np.zeros(hi - lo + 1, dtype=np.int64)
        if src.size:
            np.cumsum(
                np.bincount(src - lo, minlength=hi - lo),
                out=local_indptr[1:],
            )
        indices = dst.astype(np.int32)
        if int(indices.shape[0]) != int(entry["edges"]):
            raise ShardCorruption(
                f"{self.directory}: shard {s} rebuild produced "
                f"{indices.shape[0]} directed edges, manifest says "
                f"{entry['edges']} — source changed since ingest; re-run "
                "ingest",
                shard=s,
            )
        return local_indptr, indices

    def _write_shard_blobs(
        self, s: int, local_indptr: np.ndarray, indices: np.ndarray
    ) -> bool:
        """Atomically install rebuilt blobs for shard `s` and re-stamp
        the manifest crc when the bytes differ; True iff re-stamped."""
        entry = self.manifest["shards"][s]
        for rel, arr in ((entry["indptr"], local_indptr),
                         (entry["indices"], indices)):
            path = os.path.join(self.directory, rel)
            tmp = f"{path}.{os.getpid()}.tmp"
            with open(tmp, "wb") as f:
                np.save(f, arr)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        new_crc = {
            # start from the existing stamps: a shard rebuild must not strip
            # the phi blob's crc (the seed scores are untouched by it)
            **entry["crc32"],
            "indptr": _crc32_file(
                os.path.join(self.directory, entry["indptr"])
            ),
            "indices": _crc32_file(
                os.path.join(self.directory, entry["indices"])
            ),
        }
        restamped = new_crc != entry["crc32"]
        if restamped:
            entry["crc32"] = new_crc
            _atomic_json(
                os.path.join(self.directory, MANIFEST_NAME), self.manifest
            )
        return restamped

    # ------------------------------ incremental edge deltas (ISSUE 15)
    def _shard_pairs_from_blobs(
        self, s: int, files_read: List[str]
    ) -> np.ndarray:
        """Shard `s`'s directed pairs from its OWN blobs — the O(shard)
        half of a delta merge (the quarantine path re-streams the full
        source; a delta rebuild must not). crc-verified; self-heal
        quarantines + retries once like any load."""
        entry = self.manifest["shards"][s]
        try:
            ip, dx = self._read_shard_blobs(
                s, entry, True, False, files_read
            )
        except ShardCorruption as e:
            if not self.self_heal:
                raise
            self.quarantine_and_rebuild(s, reason=str(e))
            entry = self.manifest["shards"][s]
            ip, dx = self._read_shard_blobs(
                s, entry, True, False, files_read
            )
        lo = int(entry["lo"])
        src = lo + np.repeat(
            np.arange(ip.shape[0] - 1, dtype=np.int64), np.diff(ip)
        )
        return np.stack([src, dx.astype(np.int64)], axis=1)

    def apply_delta(
        self,
        delta_path: str,
        seed_rebake: bool = True,
        profile=None,
    ) -> Dict:
        """Append an edge file to this cache by rebuilding ONLY the
        touched node ranges (ISSUE 15 tentpole — the delta re-ingest).

        The delta is parsed once (it is small), mapped through the raw-id
        table, and scattered to the shards owning its endpoints; each
        touched shard is rebuilt as existing-blob pairs + delta pairs ->
        dedup -> fresh blobs (O(shard + delta), never O(source text) —
        the range-scoped edge source satellite). Untouched shard blobs
        are left BYTE-IDENTICAL. The manifest bumps `delta_seq`, records
        the delta file (so quarantine rebuilds replay it), re-stamps the
        touched shards' crcs and edge counts, and — when seed scores are
        baked — re-bakes phi for the touched shards only (their
        conductance sees the updated graph exactly; untouched shards
        keep their pre-delta phi blobs, a documented staleness).

        New NODES refuse with a re-ingest hint: the shard geometry is
        sized to N at compile time, and growing N re-shards everything —
        that is a full `cli ingest`, not a delta.

        Returns the delta report: edges_added (directed), touched shard
        ids, touched internal rows, touched_frac, files_read (the
        isolation contract — only touched shards' blobs are opened), and
        seconds. A crash mid-apply leaves crc mismatches the self-heal
        path repairs back to the PRE-delta cache (the manifest — written
        last — still describes it), after which the delta can simply be
        re-applied."""
        import time

        t0 = time.perf_counter()
        if not os.path.exists(delta_path):
            raise ValueError(f"{delta_path}: no such delta edge file")
        files_read: List[str] = ["raw_ids.npy"]
        order, raw_sorted = self._raw_id_order()
        n = self.num_nodes
        rows = self.rows_per_shard
        # parse ONCE, raw -> internal, loops dropped, symmetrized
        raw_pairs = 0
        parts: List[np.ndarray] = []
        for pairs in stream_edge_list(delta_path, DEFAULT_CHUNK_BYTES):
            if pairs.size == 0:
                continue
            raw_pairs += int(pairs.shape[0])
            mapped, known = rows_of_raw_ids(pairs, order, raw_sorted)
            if not known.all():
                bad = pairs[~known.all(axis=1)][:3].tolist()
                raise ValueError(
                    f"{delta_path}: contains node ids absent from the "
                    f"cache (e.g. {bad}) — deltas cannot grow N (the "
                    "shard geometry is sized at compile time); re-run "
                    "`cli ingest` on the merged edge list"
                )
            mapped = mapped[mapped[:, 0] != mapped[:, 1]]
            if mapped.size:
                parts.append(
                    np.concatenate([mapped, mapped[:, ::-1]], axis=0)
                )
        both = (
            np.concatenate(parts, axis=0)
            if parts
            else np.empty((0, 2), dtype=np.int64)
        )
        if both.size == 0:
            # nothing to merge (empty file / self-loops only): a pure
            # no-op — recording it would make the quarantine rebuild
            # depend on a file that contributes nothing
            return {
                "delta_path": os.path.abspath(delta_path),
                "delta_seq": self.delta_seq,
                "raw_pairs": raw_pairs,
                "edges_added": 0,
                "num_directed_edges": self.num_directed_edges,
                "touched_shards": [],
                "touched_rows": np.empty(0, dtype=np.int64),
                "touched_frac": 0.0,
                "phi_rebaked_shards": [],
                "closure_rebaked_shards": [],
                "files_read": tuple(files_read),
                "seconds": round(time.perf_counter() - t0, 4),
            }
        touched_rows = np.unique(both[:, 0])
        touched_shards = sorted(
            {int(r // rows) for r in touched_rows.tolist()}
        )
        old_total = self.num_directed_edges
        # merge each touched shard: existing blob pairs + delta pairs
        for s in touched_shards:
            entry = self.manifest["shards"][s]
            lo, hi = int(entry["lo"]), int(entry["hi"])
            add = both[(both[:, 0] >= lo) & (both[:, 0] < hi)]
            existing = self._shard_pairs_from_blobs(s, files_read)
            src, dst = dedup_directed(
                np.concatenate([existing, add], axis=0)
            )
            local_indptr = np.zeros(hi - lo + 1, dtype=np.int64)
            if src.size:
                np.cumsum(
                    np.bincount(src - lo, minlength=hi - lo),
                    out=local_indptr[1:],
                )
            indices = dst.astype(np.int32)
            # write fresh blobs atomically but stamp the MANIFEST only
            # once at the end: a crash mid-apply then reads as crc
            # mismatches against the old manifest, and the self-heal
            # rebuild (source + previously recorded deltas) restores the
            # pre-delta cache instead of a half-applied one
            for rel, arr in ((entry["indptr"], local_indptr),
                             (entry["indices"], indices)):
                path = os.path.join(self.directory, rel)
                tmp = f"{path}.{os.getpid()}.tmp"
                with open(tmp, "wb") as f:
                    np.save(f, arr)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
            entry["edges"] = int(indices.shape[0])
            entry["crc32"] = {
                **entry["crc32"],
                "indptr": _crc32_file(
                    os.path.join(self.directory, entry["indptr"])
                ),
                "indices": _crc32_file(
                    os.path.join(self.directory, entry["indices"])
                ),
            }
            if profile is not None:
                profile.sample_rss()
        new_total = sum(
            int(e["edges"]) for e in self.manifest["shards"]
        )
        self.manifest["num_directed_edges"] = new_total
        self.manifest["num_undirected_edges"] = new_total // 2
        seq = self.delta_seq + 1
        self.manifest["delta_seq"] = seq
        self.manifest.setdefault("deltas", []).append({
            "path": os.path.abspath(delta_path),
            "bytes": os.path.getsize(delta_path),
            "raw_pairs": raw_pairs,
            "seq": seq,
            "touched_shards": touched_shards,
        })
        # touched-shard phi re-bake: exact conductance on the UPDATED
        # graph for touched rows (degrees re-read from every indptr blob
        # — O(N) ints; the pair sweep reads neighbor shards' indices, so
        # the strict only-touched files_read contract applies to caches
        # without baked seeds)
        rebaked: List[int] = []
        if (
            seed_rebake
            and touched_shards
            and self.manifest.get("seed_scores", {}).get("baked")
        ):
            meta = self.manifest["seed_scores"]
            deg_final = np.zeros(max(n, 1), dtype=np.int64)
            for e in self.manifest["shards"]:
                lo, hi = int(e["lo"]), int(e["hi"])
                if hi <= lo:
                    continue
                ip = np.load(os.path.join(self.directory, e["indptr"]))
                deg_final[lo:hi] = np.diff(ip)
            bake_seed_scores(
                self.directory, self.manifest["shards"], deg_final[:n],
                new_total, cap=meta.get("cap"), seed=meta.get("seed") or 0,
                profile=profile, only_shards=set(touched_shards),
            )
            rebaked = touched_shards
        # touched-shard closure re-bake: EXACT (a shard's closure depends
        # only on its own edge lists, and deltas symmetrize, so every
        # shard whose lists changed is in touched_shards)
        closure_rebaked: List[int] = []
        if touched_shards and self.manifest.get("closure", {}).get("baked"):
            bake_closure_lists(
                self.directory, self.manifest["shards"],
                self.rows_per_shard,
                cap=int(self.manifest["closure"].get("cap", 0)),
                profile=profile, only_shards=set(touched_shards),
            )
            closure_rebaked = touched_shards
        _atomic_json(
            os.path.join(self.directory, MANIFEST_NAME), self.manifest
        )
        seconds = time.perf_counter() - t0
        out = {
            "delta_path": os.path.abspath(delta_path),
            "delta_seq": seq,
            "raw_pairs": raw_pairs,
            "edges_added": new_total - old_total,
            "num_directed_edges": new_total,
            "touched_shards": touched_shards,
            "touched_rows": touched_rows,
            "touched_frac": (
                round(touched_rows.size / n, 6) if n else 0.0
            ),
            "phi_rebaked_shards": rebaked,
            "closure_rebaked_shards": closure_rebaked,
            "files_read": tuple(files_read),
            "seconds": round(seconds, 4),
        }
        from bigclam_tpu.obs import telemetry as _obs

        tel = _obs.current()
        if tel is not None:
            tel.event(
                "delta_ingest",
                edges_added=int(out["edges_added"]),
                touched_shards=len(touched_shards),
                shards=touched_shards,
                touched_rows=int(touched_rows.size),
                touched_frac=out["touched_frac"],
                delta_seq=seq,
                phi_rebaked=len(rebaked),
                cache_dir=self.directory,
                seconds=out["seconds"],
            )
        return out


# --------------------------------------------------------------------------
# ingest-time seed bake (ISSUE 9): conductance scores next to the shards
# --------------------------------------------------------------------------


def _phi_name(s: int) -> str:
    return f"shard_{s:05d}.phi.npy"


def _gather_rows(indptr_b: np.ndarray, data_b: np.ndarray, rows: np.ndarray):
    """Concatenate CSR rows `rows` of (indptr_b, data_b) — the two-hop
    expansion of the shard-pair sweeps. Returns (values, counts)."""
    starts = indptr_b[rows]
    cnts = indptr_b[rows + 1] - starts
    total = int(cnts.sum())
    if total == 0:
        return data_b[:0], cnts
    take = np.repeat(starts, cnts) + (
        np.arange(total, dtype=np.int64)
        - np.repeat(np.concatenate([[0], np.cumsum(cnts[:-1])]), cnts)
    )
    return data_b[take], cnts


def bake_seed_scores(
    cache_dir: str,
    shard_table: List[dict],
    deg_final: np.ndarray,
    num_directed_edges: int,
    cap: Optional[int] = None,
    seed: int = 0,
    profile=None,
    only_shards=None,
) -> None:
    """Compute per-node ego-net conductance OUT OF CORE over the written
    shard blobs and bake per-shard phi blobs next to them (mutates
    `shard_table` entries in place with "phi" names + crcs; the caller
    writes the manifest).

    The fit-time scorer streams the whole graph again (triangle pass +
    neighbor-degree sums); here both passes run at ingest, where the shard
    blobs are already hot, as SHARD-PAIR sweeps: tri(u) needs N(v) for
    v in N(u), so for each ordered shard pair (a, b) the sweep intersects
    shard a's rows with the neighbor lists v owned by shard b — at most
    two shard blobs (BoundedBlobCache) plus O(N) flag/degree vectors are
    resident, never the global CSR. With cap=None the counts are exact
    integers and the baked phi is BIT-IDENTICAL to
    ops.seeding.conductance(g, backend="numpy"); with a degree cap the
    capped lists come from the same splitmix64 sampler
    (seeding.capped_neighbor_lists keyed by GLOBAL row id), so the
    estimates match triangle_counts_sampled up to float summation order.

    `only_shards` (ISSUE 15: the delta re-ingest's touched-shard phi
    refresh) restricts the OUTER sweeps and the phi writes to those
    shards: their scores see the whole updated graph (inner pair sweeps
    still read neighbor shards), every other shard's phi blob is left
    byte-identical.
    """
    # lazy: ops.seeding is imported only here so the default ingest path
    # stays jax-free AND cheap to import (seeding's module deps are numpy
    # + config only, but keep the contract explicit)
    from bigclam_tpu.ops.seeding import (
        capped_neighbor_lists,
        phi_from_counts,
    )

    n = int(deg_final.size)
    blobs = BoundedBlobCache(capacity=4)

    def shard_csr(entry):
        ip = np.asarray(
            blobs.get(os.path.join(cache_dir, entry["indptr"])), np.int64
        )
        dx = blobs.get(os.path.join(cache_dir, entry["indices"]))
        return ip, dx

    # --- pass 1: S1(u) = sum of neighbor degrees, one shard at a time ---
    s1 = np.zeros(n, dtype=np.float64)
    for s, e in enumerate(shard_table):
        if only_shards is not None and s not in only_shards:
            continue
        lo, hi = int(e["lo"]), int(e["hi"])
        if hi <= lo:
            continue
        ip, dx = shard_csr(e)
        rows = np.repeat(
            np.arange(hi - lo, dtype=np.int64), np.diff(ip)
        )
        s1[lo:hi] = np.bincount(
            rows, weights=deg_final[dx].astype(np.float64),
            minlength=hi - lo,
        )
        if profile is not None:
            profile.sample_rss()

    # --- pass 2: triangle counts via ordered shard-pair sweeps ---
    # Vectorized (no per-row Python loop — O(N*S) iterations would wall an
    # ingest at real shard counts): per pair (a, b), membership "w in
    # N(u)" is a searchsorted against shard a's globally-sorted ego keys
    # u*n + w (CSR rows ascending, neighbor lists ascending — the same
    # trick as triangle_counts_sampled), with the two-hop expansion
    # processed in bounded entry chunks.
    chunk_entries = 1 << 22
    scratch = None
    if cap is None:
        tri_acc = np.zeros(n, dtype=np.int64)
    else:
        tri_acc = np.zeros(n, dtype=np.float64)
        # same stream-seed derivation as triangle_counts_sampled(rng)
        stream_seed = int(np.random.default_rng(seed).integers(2**63))
        cdeg_all = np.minimum(deg_final, cap)
        inner_w = deg_final / np.maximum(cdeg_all, 1)
        # capped lists are computed ONCE per shard and spilled to scratch
        # blobs riding the same BoundedBlobCache as the raw CSR: the pair
        # sweep reads each shard O(S) times, and the per-hub Fisher-Yates
        # sampler (a Python loop) must not rerun per pair. Computed
        # LAZILY on first read — a touched-shard delta rebake
        # (only_shards) then samples only the shards its sweeps actually
        # touch, not the whole graph per delta (ISSUE 15)
        import tempfile

        # system tmp, not cache_dir: a crashed bake must not leave scratch
        # blobs inside a directory the manifest will later validate
        scratch = tempfile.mkdtemp(prefix="bigclam_seed_bake_")

        def capped_csr_of(idx: int) -> tuple:
            ipath = os.path.join(scratch, f"{idx}.indptr.npy")
            dpath = os.path.join(scratch, f"{idx}.indices.npy")
            if not os.path.exists(ipath):
                ip, dx = shard_csr(shard_table[idx])
                ip_c, dx_c = capped_neighbor_lists(
                    ip, dx, cap, stream_seed,
                    row_offset=int(shard_table[idx]["lo"]),
                )
                np.save(ipath, ip_c)
                np.save(dpath, dx_c)
                if profile is not None:
                    profile.sample_rss()
            return (
                np.asarray(blobs.get(ipath), np.int64),
                blobs.get(dpath),
            )

    try:
        for a, ea in enumerate(shard_table):
            if only_shards is not None and a not in only_shards:
                continue
            lo_a, hi_a = int(ea["lo"]), int(ea["hi"])
            if hi_a <= lo_a:
                continue
            # shard a's arrays and its derived ego keys depend only on the
            # OUTER shard: hoisted out of the pair loop (local refs keep
            # them alive past any cache eviction by the inner-b reads)
            ipa, dxa = shard_csr(ea) if cap is None else capped_csr_of(a)
            rows_a = hi_a - lo_a
            ego_src = np.repeat(
                np.arange(rows_a, dtype=np.int64), np.diff(ipa)
            )
            ego_keys = (ego_src + lo_a) * n + dxa       # sorted ascending
            for b, eb in enumerate(shard_table):
                lo_b, hi_b = int(eb["lo"]), int(eb["hi"])
                if hi_b <= lo_b:
                    continue
                # intersect FIRST: shard b's (possibly lazily sampled)
                # arrays are only loaded when shard a actually has
                # neighbors there
                sel = np.flatnonzero((dxa >= lo_b) & (dxa < hi_b))
                if sel.size == 0:
                    continue
                ipb, dxb = shard_csr(eb) if cap is None else capped_csr_of(b)
                v_rows = dxa[sel].astype(np.int64) - lo_b
                cnt_v = (ipb[v_rows + 1] - ipb[v_rows]).astype(np.int64)
                # chunk the selected edges so the expansion stays bounded
                cum = np.cumsum(cnt_v)
                splits = np.searchsorted(
                    cum,
                    np.arange(chunk_entries, int(cum[-1]) + chunk_entries,
                              chunk_entries),
                )
                starts = np.concatenate(
                    [[0], np.minimum(splits + 1, sel.size)]
                )
                for c0, c1 in zip(starts[:-1], starts[1:]):
                    if c0 >= c1:
                        continue
                    piece = sel[c0:c1]
                    z, cnts = _gather_rows(
                        ipb, dxb, dxa[piece].astype(np.int64) - lo_b
                    )
                    if z.size == 0:
                        continue
                    z_u = np.repeat(ego_src[piece], cnts)
                    cand = (z_u + lo_a) * n + z
                    idx = np.searchsorted(ego_keys, cand)
                    hit = (idx < ego_keys.size) & (
                        ego_keys[np.minimum(idx, ego_keys.size - 1)]
                        == cand
                    )
                    if cap is None:
                        tri_acc[lo_a:hi_a] += np.bincount(
                            z_u[hit], minlength=rows_a
                        )
                    else:
                        w = np.repeat(inner_w[dxa[piece]], cnts)
                        tri_acc[lo_a:hi_a] += np.bincount(
                            z_u[hit], weights=w[hit], minlength=rows_a
                        )
                if profile is not None:
                    profile.sample_rss()
    finally:
        if scratch is not None:
            shutil.rmtree(scratch, ignore_errors=True)
    if cap is None:
        tri = tri_acc // 2
    else:
        pairs = cdeg_all * (cdeg_all - 1)
        scale = np.where(
            pairs > 0,
            deg_final * (deg_final - 1) / np.maximum(pairs, 1),
            0.0,
        )
        tri = tri_acc / 2.0 * scale

    phi = phi_from_counts(
        deg_final.astype(np.int64), s1, tri, float(num_directed_edges)
    )

    # --- write per-shard phi blobs, stamp the table in place ---
    for s, e in enumerate(shard_table):
        if only_shards is not None and s not in only_shards:
            continue
        lo, hi = int(e["lo"]), int(e["hi"])
        name = _phi_name(s)
        np.save(os.path.join(cache_dir, name), phi[lo:hi])
        e["phi"] = name
        e["crc32"]["phi"] = _crc32_file(os.path.join(cache_dir, name))


# --------------------------------------------------------------------------
# closure bake (ISSUE 16): per-shard-pair gather lists next to the shards
# --------------------------------------------------------------------------


def _closure_name(s: int) -> str:
    return f"shard_{s:05d}.closure.npy"


def closure_pair_lists(
    lo: int,
    indptr: np.ndarray,
    indices: np.ndarray,
    rows_per_shard: int,
    num_shards: int,
    cap: int = 0,
):
    """Per dst-shard closure lists of ONE shard's CSR — the single
    derivation shared by the ingest bake and the 2D trainers' v2
    streaming fallback (they must never diverge: the send side of an
    exchange is the mirror of some other shard's gather side).

    Returns (out_ids, in_ids, edge_counts) over peer shards b:
    out_ids[b] = sorted unique GLOBAL dst ids in b, in_ids[b] = sorted
    unique GLOBAL src rows of this shard with an edge into b,
    edge_counts[b] = directed edges into b. cap > 0 replaces any list
    longer than cap with None (the overflow sentinel — consumers degrade
    that pair to the full dst block)."""
    S = num_shards
    dx = np.asarray(indices, dtype=np.int64)
    src = lo + np.repeat(
        np.arange(indptr.shape[0] - 1, dtype=np.int64), np.diff(indptr)
    )
    dshard = dx // rows_per_shard
    order = np.argsort(dshard, kind="stable")
    dx_s, src_s, dshard_s = dx[order], src[order], dshard[order]
    bounds = np.searchsorted(dshard_s, np.arange(S + 1))
    out_ids: List[Optional[np.ndarray]] = []
    in_ids: List[Optional[np.ndarray]] = []
    edge_counts: List[int] = []
    for b in range(S):
        sl = slice(int(bounds[b]), int(bounds[b + 1]))
        edge_counts.append(sl.stop - sl.start)
        out = np.unique(dx_s[sl])
        ins = np.unique(src_s[sl])
        out_ids.append(None if cap and out.size > cap else out)
        in_ids.append(None if cap and ins.size > cap else ins)
    return out_ids, in_ids, edge_counts


def bake_closure_lists(
    cache_dir: str,
    shard_table: List[dict],
    rows_per_shard: int,
    cap: int = 0,
    profile=None,
    only_shards=None,
) -> None:
    """Bake per-shard closure blobs next to the CSR blobs (mutates
    `shard_table` entries in place with "closure" metadata + crcs; the
    caller writes the manifest).

    One sweep per shard over its OWN blobs only — O(S) blob loads total,
    and a touched-shard delta rebake (`only_shards`, mirroring the phi
    rebake contract) is exact because a shard's closure depends on
    nothing but its own edge lists. The blob is a single int32 npy:
    concat(out lists for b=0..S-1, then in lists), with lengths in the
    manifest entry (-1 marks a capped-overflow pair whose list is
    omitted)."""
    S = len(shard_table)
    for s, e in enumerate(shard_table):
        if only_shards is not None and s not in only_shards:
            continue
        lo, hi = int(e["lo"]), int(e["hi"])
        ip = np.load(os.path.join(cache_dir, e["indptr"])).astype(
            np.int64, copy=False
        )
        dx = np.load(os.path.join(cache_dir, e["indices"]))
        out_ids, in_ids, edge_counts = closure_pair_lists(
            lo, ip, dx, rows_per_shard, S, cap=cap
        )
        parts = [a for a in out_ids + in_ids if a is not None]
        blob = (
            np.concatenate(parts)
            if parts
            else np.empty(0, dtype=np.int64)
        ).astype(np.int32)
        name = _closure_name(s)
        np.save(os.path.join(cache_dir, name), blob)
        e["closure"] = {
            "ids": name,
            "out_counts": [
                -1 if a is None else int(a.size) for a in out_ids
            ],
            "in_counts": [
                -1 if a is None else int(a.size) for a in in_ids
            ],
            "edge_counts": [int(c) for c in edge_counts],
        }
        e["crc32"]["closure"] = _crc32_file(os.path.join(cache_dir, name))
        if profile is not None:
            profile.sample_rss()


# --------------------------------------------------------------------------
# compile: text -> cache, out of core
# --------------------------------------------------------------------------


class _BucketWriter:
    """Append-only int64 pair spill files, one per node-range bucket."""

    def __init__(self, directory: str, num_buckets: int, tag: str):
        os.makedirs(directory, exist_ok=True)
        self.paths = [
            os.path.join(directory, f"{tag}_{b:05d}.bin")
            for b in range(num_buckets)
        ]
        self._handles = [open(p, "ab") for p in self.paths]

    def append(self, bucket: int, pairs: np.ndarray) -> None:
        if pairs.size:
            self._handles[bucket].write(
                np.ascontiguousarray(pairs, dtype=np.int64).tobytes()
            )

    def close(self) -> None:
        for h in self._handles:
            h.close()

    def read(self, bucket: int) -> np.ndarray:
        return np.fromfile(self.paths[bucket], dtype=np.int64).reshape(-1, 2)


def _scatter_by_bucket(
    pairs: np.ndarray, rows: int, writer: _BucketWriter
) -> None:
    """Append each directed pair to the bucket owning its source node."""
    if pairs.shape[0] == 0:
        return
    bidx = pairs[:, 0] // rows
    order = np.argsort(bidx, kind="stable")
    pairs = pairs[order]
    bidx = bidx[order]
    bounds = np.flatnonzero(np.diff(bidx)) + 1
    starts = np.concatenate([[0], bounds])
    ends = np.concatenate([bounds, [pairs.shape[0]]])
    for s, e in zip(starts, ends):
        writer.append(int(bidx[s]), pairs[s:e])


def _merge_sorted_unique(table: np.ndarray, chunk: np.ndarray) -> np.ndarray:
    """Fold a chunk's ids into the sorted unique id table WITHOUT re-sorting
    the table (np.union1d re-sorts all N ids per chunk — O(chunks * N log N)
    across a Friendster-scale scan): unique the chunk, drop ids already in
    the table via searchsorted, merge-insert the rest. O(N + m) per chunk.
    """
    ids = np.unique(chunk)
    if table.size == 0:
        return ids
    if ids.size == 0:
        return table
    pos = np.searchsorted(table, ids)
    known = table[np.minimum(pos, table.size - 1)] == ids
    fresh = ids[~known]
    if fresh.size == 0:
        return table
    return np.insert(table, np.searchsorted(table, fresh), fresh)


def _atomic_json(path: str, obj: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=2, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def compile_graph_cache(
    text_path: str,
    cache_dir: str,
    num_shards: int = 8,
    chunk_bytes: int = DEFAULT_CHUNK_BYTES,
    workers: int = 0,
    balance: bool = False,
    overwrite: bool = False,
    profile=None,
    seed_bake: bool = True,
    seed_cap: Optional[int] = None,
    seed: int = 0,
    closure_bake: bool = True,
    closure_cap: int = 0,
) -> GraphStore:
    """Compile a SNAP edge list into a binary shard cache, out of core.

    Stages (each a `profile` stage when an IngestProfile is passed):
      scan     stream newline-snapped chunks, spill parsed raw pairs to
               disk, merge the sorted unique raw-id table (O(chunk + N) RSS)
      scatter  remap raw ids -> compact [0, N), drop self-loops, symmetrize,
               bucket directed pairs by owner node range
      dedup    per-bucket lexsort + duplicate-row drop (duplicates of an
               edge always land in the same bucket, so local dedup is
               globally exact); exact deduped degrees fall out here
      shards   (balance=True: relabel through the balance permutation and
               re-scatter first) write per-shard packed CSR blobs + the
               versioned manifest with per-blob crc32s
      seed_bake (seed_bake=True, the default) per-node conductance scores
               baked next to the shards (bake_seed_scores: shard-pair
               sweeps over the just-written blobs, O(2 shards + N) RSS),
               so fit-time seeding on a cache reads scores instead of
               re-streaming the graph. seed_cap engages the degree-capped
               splitmix64 estimator (exact when cap >= max degree); `seed`
               is the cfg-level PRNG seed its stream derives from
      closure_bake (closure_bake=True, the default) per-shard-pair
               neighborhood-closure gather lists baked next to the shards
               (bake_closure_lists — one sweep per shard over its own
               blobs), so the 2D-partition trainers read exchange sets
               instead of re-deriving them. closure_cap bounds the
               per-pair list length (0 = uncapped; overflow pairs degrade
               to full-block exchange)

    Shard s owns node rows [s*rows, (s+1)*rows) with
    rows = ceil(max(N, num_shards) / num_shards) — exactly the contiguous
    ranges the sharded trainers slice on a dp=num_shards mesh, so a baked
    balance permutation (balance_permutation(degrees, num_shards, rows *
    num_shards)) is the same relabeling ShardedBigClamModel(balance=True)
    would compute at model build.
    """
    if num_shards <= 0:
        raise ValueError(f"num_shards must be positive, got {num_shards}")
    manifest_path = os.path.join(cache_dir, MANIFEST_NAME)
    if os.path.exists(manifest_path):
        if not overwrite:
            raise FileExistsError(
                f"{cache_dir}: cache already compiled (pass overwrite=True "
                "/ --overwrite to rebuild)"
            )
        # drop the OLD manifest (and its blobs) before rebuilding: a crash
        # mid-rebuild must leave an unrecognizable directory, never an
        # old manifest validating over mixed old/new shard files
        os.unlink(manifest_path)
        for name in os.listdir(cache_dir):
            if name.endswith(".npy") and (
                name.startswith("shard_") or name in ("raw_ids.npy",
                                                      "perm.npy")
            ):
                os.unlink(os.path.join(cache_dir, name))
    os.makedirs(cache_dir, exist_ok=True)
    spill_dir = os.path.join(cache_dir, "_spill")
    if os.path.exists(spill_dir):
        shutil.rmtree(spill_dir)
    os.makedirs(spill_dir)

    if profile is None:
        from bigclam_tpu.utils.profiling import IngestProfile

        profile = IngestProfile()

    try:
        return _compile(
            text_path, cache_dir, spill_dir, manifest_path, num_shards,
            chunk_bytes, workers, balance, profile, seed_bake, seed_cap,
            seed, closure_bake, closure_cap,
        )
    finally:
        shutil.rmtree(spill_dir, ignore_errors=True)


def _compile(
    text_path, cache_dir, spill_dir, manifest_path, num_shards,
    chunk_bytes, workers, balance, profile, seed_bake, seed_cap, seed,
    closure_bake, closure_cap,
) -> GraphStore:
    # --- scan: parse chunks, spill raw pairs, merge unique raw ids ---
    chunk_paths: List[str] = []
    raw_ids = np.empty(0, dtype=np.int64)
    raw_edges = 0
    with profile.stage("scan"):
        for i, pairs in enumerate(
            stream_edge_list(text_path, chunk_bytes, workers)
        ):
            cpath = os.path.join(spill_dir, f"chunk_{i:06d}.bin")
            pairs.tofile(cpath)
            chunk_paths.append(cpath)
            raw_edges += pairs.shape[0]
            raw_ids = _merge_sorted_unique(raw_ids, pairs)
            profile.count("chunks")
            profile.count("raw_edges", pairs.shape[0])
            profile.sample_rss()
    n = int(raw_ids.shape[0])
    if n > np.iinfo(np.int32).max:
        # dedup/remap are ceiling-free, but shard indices are int32 (the
        # Graph container's dtype): refuse instead of wrapping negative
        raise ValueError(
            f"num_nodes={n} exceeds the int32 CSR indices bound (2^31-1)"
        )
    rows = -(-max(n, num_shards) // num_shards)    # == trainers' n_pad // dp

    # --- scatter: remap, drop loops, symmetrize, bucket by src range ---
    buckets = _BucketWriter(spill_dir, num_shards, "bucket")
    with profile.stage("scatter"):
        for cpath in chunk_paths:
            pairs = np.fromfile(cpath, dtype=np.int64).reshape(-1, 2)
            os.unlink(cpath)
            pairs = np.searchsorted(raw_ids, pairs)
            pairs = pairs[pairs[:, 0] != pairs[:, 1]]
            both = np.concatenate([pairs, pairs[:, ::-1]], axis=0)
            _scatter_by_bucket(both, rows, buckets)
            profile.sample_rss()
    buckets.close()

    # --- dedup: per-bucket lexsort + unique rows; exact degrees ---
    degrees = np.zeros(max(n, 1), dtype=np.int64)
    deduped = _BucketWriter(spill_dir, num_shards, "dedup")
    with profile.stage("dedup"):
        for b in range(num_shards):
            both = buckets.read(b)
            os.unlink(buckets.paths[b])
            src, dst = dedup_directed(both)
            lo, hi = min(b * rows, n), min((b + 1) * rows, n)
            if src.size:
                degrees[lo:hi] += np.bincount(src - lo, minlength=hi - lo)
            deduped.append(b, np.stack([src, dst], axis=1))
            profile.sample_rss()
    deduped.close()

    # --- balance permutation (baked at compile time) ---
    perm = None
    if balance:
        # lazy: parallel/__init__ pulls in jax, which the default ingest
        # path must not pay for (RSS + import time on data-prep hosts)
        from bigclam_tpu.parallel.balance import balance_permutation

        perm = balance_permutation(degrees[:n], num_shards, rows * num_shards)

    # --- shards: (relabel + re-scatter when balanced,) write CSR blobs ---
    final = deduped
    if perm is not None:
        final = _BucketWriter(spill_dir, num_shards, "final")
        with profile.stage("shards"):
            for b in range(num_shards):
                arr = deduped.read(b)
                os.unlink(deduped.paths[b])
                _scatter_by_bucket(perm[arr], rows, final)
                profile.sample_rss()
        final.close()

    shard_table = []
    total_directed = 0
    deg_final = np.zeros(max(n, 1), dtype=np.int64)  # FINAL node order
    with profile.stage("shards"):
        for s in range(num_shards):
            arr = final.read(s)
            os.unlink(final.paths[s])
            lo, hi = min(s * rows, n), min((s + 1) * rows, n)
            if perm is not None and arr.size:
                # re-scattered buckets are unsorted; dedup already happened
                order = np.lexsort((arr[:, 1], arr[:, 0]))
                arr = arr[order]
            local_indptr = np.zeros(hi - lo + 1, dtype=np.int64)
            if arr.size:
                np.cumsum(
                    np.bincount(arr[:, 0] - lo, minlength=hi - lo),
                    out=local_indptr[1:],
                )
            deg_final[lo:hi] = np.diff(local_indptr)
            indices = arr[:, 1].astype(np.int32)
            iname, dname = _shard_files(s)
            np.save(os.path.join(cache_dir, iname), local_indptr)
            np.save(os.path.join(cache_dir, dname), indices)
            total_directed += int(indices.shape[0])
            shard_table.append(
                {
                    "lo": lo,
                    "hi": hi,
                    "edges": int(indices.shape[0]),
                    "indptr": iname,
                    "indices": dname,
                    "crc32": {
                        "indptr": _crc32_file(
                            os.path.join(cache_dir, iname)
                        ),
                        "indices": _crc32_file(
                            os.path.join(cache_dir, dname)
                        ),
                    },
                }
            )
            profile.count("directed_edges", int(indices.shape[0]))
            profile.sample_rss()

        # raw_ids in FINAL node order (balanced caches relabel rows)
        if perm is not None:
            raw_final = np.empty_like(raw_ids)
            raw_final[perm] = raw_ids
        else:
            raw_final = raw_ids
        np.save(os.path.join(cache_dir, "raw_ids.npy"), raw_final)
        files: Dict[str, dict] = {
            "raw_ids": {
                "name": "raw_ids.npy",
                "crc32": _crc32_file(os.path.join(cache_dir, "raw_ids.npy")),
            }
        }
        if perm is not None:
            np.save(os.path.join(cache_dir, "perm.npy"), perm)
            files["perm"] = {
                "name": "perm.npy",
                "crc32": _crc32_file(os.path.join(cache_dir, "perm.npy")),
            }

    # --- seed bake: conductance scores next to the shards (ISSUE 9) ---
    bake_skipped = None
    if seed_bake and seed_cap is None:
        exact_work = float(
            np.square(deg_final[:n].astype(np.float64)).sum()
        )
        if exact_work > SEED_BAKE_EXACT_MAX_WORK:
            seed_bake = False
            bake_skipped = "exact_work"
            print(
                f"warning: skipping the seed bake — the exact triangle "
                f"pass would expand {exact_work:.2e} two-hop entries "
                f"(> {SEED_BAKE_EXACT_MAX_WORK:.0e}); re-run ingest with "
                "--seed-cap to bake the degree-capped estimator instead",
                file=sys.stderr,
            )
    if seed_bake:
        with profile.stage("seed_bake"):
            bake_seed_scores(
                cache_dir, shard_table, deg_final[:n], total_directed,
                cap=seed_cap, seed=seed, profile=profile,
            )
            profile.sample_rss()

    # --- closure bake: 2D-partition gather lists (ISSUE 16) ---
    if closure_bake:
        with profile.stage("closure_bake"):
            bake_closure_lists(
                cache_dir, shard_table, rows, cap=closure_cap,
                profile=profile,
            )
            profile.sample_rss()

    manifest = {
        "format_version": MANIFEST_VERSION,
        "num_nodes": n,
        "num_directed_edges": total_directed,
        "num_undirected_edges": total_directed // 2,
        "num_shards": num_shards,
        "rows_per_shard": rows,
        "balanced": perm is not None,
        "dtypes": {"indptr": "int64", "indices": "int32",
                   "raw_ids": "int64"},
        "shards": shard_table,
        "files": files,
        "seed_scores": (
            {"baked": True, "cap": seed_cap, "seed": seed}
            if seed_bake
            else {"baked": False, "skipped": bake_skipped}
            if bake_skipped
            else {"baked": False}
        ),
        "closure": (
            {"baked": True, "cap": int(closure_cap)}
            if closure_bake
            else {"baked": False}
        ),
        "source": {
            "path": os.path.abspath(text_path),
            "bytes": os.path.getsize(text_path),
            "raw_pairs": raw_edges,
        },
    }
    _atomic_json(manifest_path, manifest)
    from bigclam_tpu.obs import telemetry as _obs

    tel = _obs.current()
    if tel is not None:
        tel.event(
            "ingest",
            edges=total_directed // 2,
            nodes=n,
            shards=num_shards,
            balanced=perm is not None,
            seed_baked=bool(seed_bake),
            cache_dir=cache_dir,
        )
    return GraphStore(cache_dir, manifest)
