from bigclam_tpu.graph.csr import Graph
from bigclam_tpu.graph.ingest import load_edge_list, build_graph, graph_from_edges

__all__ = ["Graph", "load_edge_list", "build_graph", "graph_from_edges"]
