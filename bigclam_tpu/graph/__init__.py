from bigclam_tpu.graph.csr import Graph
from bigclam_tpu.graph.ingest import (
    build_graph,
    dedup_directed,
    graph_from_edges,
    load_edge_list,
)
from bigclam_tpu.graph.store import (
    GraphStore,
    compile_graph_cache,
    is_cache_dir,
)
from bigclam_tpu.graph.stream import load_edge_list_streaming, stream_edge_list

__all__ = [
    "Graph",
    "GraphStore",
    "build_graph",
    "compile_graph_cache",
    "dedup_directed",
    "graph_from_edges",
    "is_cache_dir",
    "load_edge_list",
    "load_edge_list_streaming",
    "stream_edge_list",
]
