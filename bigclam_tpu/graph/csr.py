"""Host-side graph container: symmetric CSR over contiguous node ids.

Replaces the reference's L1 graph layer (SURVEY.md C1-C3): GraphX
``collectNeighborIds(Either)`` materialized per-node neighbor arrays AND a
full driver-side broadcast copy on every executor (Bigclamv2.scala:33-34).
Here the graph is a deduplicated, symmetrized CSR (``indptr``/``indices``)
over node ids remapped to [0, N); device code consumes flat directed-edge
arrays (``src``/``dst``) so the hot kernels are edge-parallel, and shards are
node-contiguous ranges (no replication).

Node-id remapping to contiguous [0, N) also removes the reference's
missing-row fallback lookup (C10, bigclamv3-7.scala:94-104): every id in
range is a real row.
"""

from __future__ import annotations

import dataclasses
import functools

import numpy as np


@dataclasses.dataclass(frozen=True)
class Graph:
    """Undirected simple graph in CSR form.

    Attributes:
      indptr:  (N+1,) int64 — CSR row pointers.
      indices: (2E,) int32 — concatenated sorted neighbor lists.
      raw_ids: (N,) original node ids from the input file (raw_ids[i] is the
               id that was remapped to i); identity for synthetic graphs.
    """

    indptr: np.ndarray
    indices: np.ndarray
    raw_ids: np.ndarray

    @property
    def num_nodes(self) -> int:
        return int(self.indptr.shape[0] - 1)

    @property
    def num_edges(self) -> int:
        """Number of undirected edges E (indices stores both directions)."""
        return int(self.indices.shape[0] // 2)

    @property
    def num_directed_edges(self) -> int:
        return int(self.indices.shape[0])

    @functools.cached_property
    def degrees(self) -> np.ndarray:
        return np.diff(self.indptr).astype(np.int64)

    @functools.cached_property
    def src(self) -> np.ndarray:
        """(2E,) int32 source node of each directed edge, aligned with indices."""
        return np.repeat(
            np.arange(self.num_nodes, dtype=np.int32), self.degrees
        )

    @property
    def dst(self) -> np.ndarray:
        """(2E,) int32 destination node of each directed edge (= indices)."""
        return self.indices

    def neighbors(self, u: int) -> np.ndarray:
        return self.indices[self.indptr[u] : self.indptr[u + 1]]

    def permute(self, perm: np.ndarray) -> "Graph":
        """Relabel nodes: old id u becomes perm[u]. Returns a new CSR graph
        over the same edges (used by the shard-balance transform,
        parallel/balance.py). perm must be a permutation of [0, N)."""
        n = self.num_nodes
        perm = np.asarray(perm)
        assert perm.shape == (n,)
        new_src = perm[self.src].astype(np.int64)
        new_dst = perm[self.dst].astype(np.int64)
        order = np.lexsort((new_dst, new_src))
        indices = new_dst[order].astype(np.int32)
        degrees = np.bincount(new_src, minlength=n)
        indptr = np.zeros(n + 1, dtype=np.int64)
        np.cumsum(degrees, out=indptr[1:])
        inv = np.empty(n, dtype=np.int64)
        inv[perm] = np.arange(n)
        return Graph(indptr=indptr, indices=indices, raw_ids=self.raw_ids[inv])

    def validate(self) -> None:
        n = self.num_nodes
        assert self.indptr[0] == 0 and self.indptr[-1] == self.indices.shape[0]
        assert np.all(np.diff(self.indptr) >= 0)
        if self.indices.size:
            assert self.indices.min() >= 0 and self.indices.max() < n
        # symmetry: the reversed edge set must equal the forward edge set
        s, d = self.src, self.dst
        fwd = np.stack([s, d], axis=1)
        rev = np.stack([d, s], axis=1)
        fwd_sorted = fwd[np.lexsort((fwd[:, 1], fwd[:, 0]))]
        rev_sorted = rev[np.lexsort((rev[:, 1], rev[:, 0]))]
        assert np.array_equal(fwd_sorted, rev_sorted), "CSR is not symmetric"
