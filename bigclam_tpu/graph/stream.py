"""Memory-bounded streaming parse of SNAP edge lists.

The seed ingest (`ingest._numpy_parse`) read the WHOLE file into host RAM and
bulk-split it into one Python token per integer — at com-Friendster scale
(~30 GB of text, 3.6B tokens) that is hours of parse and an O(file) resident
set on EVERY host of a multi-host job before the first device step runs.
Here the file is scanned in fixed-size byte-range chunks whose boundaries are
snapped to newlines, so peak RSS is O(chunk_bytes) (times a small tokenizer
constant), not O(file): each chunk is parsed independently (``#``-comment
aware, same grammar as the bulk parser) and either yielded to a consumer
(the graph store's out-of-core compile, graph/store.py) or concatenated for
an in-memory build.

Chunks are independent, so the scan parallelizes across a spawn-based
process pool (`workers > 1`); results are yielded IN FILE ORDER with at most
`workers` chunks in flight, keeping the parent's memory bound intact. The
pool uses the spawn context: the parent typically has jax (and its thread
pools) loaded, and forking a threaded process is undefined behavior.
"""

from __future__ import annotations

import collections
import os
from typing import Iterator, List, Tuple

import numpy as np

DEFAULT_CHUNK_BYTES = 64 << 20
# bound on how far a chunk boundary scans forward for its newline; SNAP
# edge-list lines are two integers, so 1 MiB is beyond generous
_MAX_LINE_BYTES = 1 << 20


def byte_ranges(path: str, chunk_bytes: int) -> List[Tuple[int, int]]:
    """Partition the file into ~chunk_bytes [start, end) spans snapped to
    newlines: every boundary except 0/EOF sits just after a ``\\n``, so no
    span starts or ends mid-line (and therefore never mid-token)."""
    if chunk_bytes <= 0:
        raise ValueError(f"chunk_bytes must be positive, got {chunk_bytes}")
    size = os.path.getsize(path)
    if size == 0:
        return []
    cuts = [0]
    with open(path, "rb") as f:
        target = chunk_bytes
        while target < size:
            f.seek(target)
            buf = f.read(_MAX_LINE_BYTES)
            nl = buf.find(b"\n")
            if nl < 0:
                if len(buf) == _MAX_LINE_BYTES:
                    # a >1 MiB line is not a SNAP edge list; falling back
                    # to one giant span would silently void the O(chunk)
                    # RSS contract, so refuse instead
                    raise ValueError(
                        f"{path}: no newline within {_MAX_LINE_BYTES} "
                        f"bytes of offset {target} — not a SNAP edge list?"
                    )
                break                       # short read: inside the final
                                            # (unterminated) line, bounded
            cut = target + nl + 1
            if cut >= size:
                break
            cuts.append(cut)
            target = cut + chunk_bytes
    cuts.append(size)
    return list(zip(cuts[:-1], cuts[1:]))


def parse_bytes(data: bytes, where: str = "") -> np.ndarray:
    """Parse whole lines of a SNAP edge list into an (M, 2) int64 array
    (``#``-prefixed comment lines and blank lines dropped)."""
    lines = data.split(b"\n")
    body = b" ".join(
        ln for ln in lines if ln.strip() and not ln.lstrip().startswith(b"#")
    )
    if not body:
        return np.empty((0, 2), dtype=np.int64)
    flat = np.array(body.split(), dtype=np.int64)
    if flat.size % 2 != 0:
        raise ValueError(
            f"{where or 'edge list'}: expected an even number of integers, "
            f"got {flat.size}"
        )
    return flat.reshape(-1, 2)


def parse_span(path: str, start: int, end: int) -> np.ndarray:
    """Parse one newline-snapped byte range of the file (the process-pool
    work unit: workers re-open the file and read only their span)."""
    with open(path, "rb") as f:
        f.seek(start)
        data = f.read(end - start)
    return parse_bytes(data, where=f"{path}[{start}:{end}]")


def stream_edge_list(
    path: str,
    chunk_bytes: int = DEFAULT_CHUNK_BYTES,
    workers: int = 0,
) -> Iterator[np.ndarray]:
    """Yield (m, 2) int64 raw-id pair arrays chunk by chunk, in file order.

    workers <= 1 parses in-process; workers > 1 fans the chunks across a
    spawn process pool with a bounded in-flight window (ordered yields, at
    most `workers` parsed chunks resident at once).
    """
    spans = byte_ranges(path, chunk_bytes)
    if workers <= 1 or len(spans) <= 1:
        for start, end in spans:
            yield parse_span(path, start, end)
        return

    import collections
    import multiprocessing as mp
    from concurrent.futures import ProcessPoolExecutor

    ctx = mp.get_context("spawn")
    with ProcessPoolExecutor(max_workers=workers, mp_context=ctx) as ex:
        pending: collections.deque = collections.deque()
        it = iter(spans)
        for start, end in it:
            pending.append(ex.submit(parse_span, path, start, end))
            if len(pending) >= workers:
                break
        for start, end in it:
            yield pending.popleft().result()
            pending.append(ex.submit(parse_span, path, start, end))
        while pending:
            yield pending.popleft().result()


def scan_edge_files(directory: str, seen=()) -> List[str]:
    """Unprocessed edge files of a delta directory, in NAME order (the
    continuous fit->publish->serve loop's watch primitive, ISSUE 15):
    plain files not in `seen` (absolute paths), skipping dotfiles and
    in-flight temporaries (`.tmp`/`.part` suffixes — publish deltas by
    writing to a temp name and renaming, the same atomicity discipline
    as the snapshot publisher). Name order IS the application order, so
    producers should use sortable names (delta_000001.txt ...)."""
    seen = set(seen)
    out: List[str] = []
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return out
    for name in names:
        if name.startswith(".") or name.endswith((".tmp", ".part")):
            continue
        path = os.path.abspath(os.path.join(directory, name))
        if path in seen or not os.path.isfile(path):
            continue
        out.append(path)
    return out


class BoundedBlobCache:
    """np.load results keyed by path with at most `capacity` blobs resident
    (LRU). The ingest-time seed bake (graph/store.bake_seed_scores) sweeps
    shard PAIRS — each shard's blobs are re-read O(num_shards) times — and
    this keeps the sweep's residency at O(capacity * shard bytes) while the
    hot outer-loop shard never re-reads. Same O(shard)-not-O(E) contract as
    the chunked parse above, applied to the binary blobs."""

    def __init__(self, capacity: int = 4):
        assert capacity >= 1
        self.capacity = capacity
        self._cache: "collections.OrderedDict[str, np.ndarray]" = (
            collections.OrderedDict()
        )

    def get(self, path: str) -> np.ndarray:
        hit = self._cache.get(path)
        if hit is not None:
            self._cache.move_to_end(path)
            return hit
        arr = np.load(path)
        self._cache[path] = arr
        while len(self._cache) > self.capacity:
            self._cache.popitem(last=False)
        return arr


def load_edge_list_streaming(
    path: str,
    chunk_bytes: int = DEFAULT_CHUNK_BYTES,
    workers: int = 0,
) -> np.ndarray:
    """In-memory (M, 2) pairs via the streaming scanner: O(chunk) transient
    parse state instead of the seed's whole-file token blowup (the pairs
    array itself is still O(E) — out-of-core callers use the graph store)."""
    parts = [
        p for p in stream_edge_list(path, chunk_bytes, workers) if p.size
    ]
    if not parts:
        return np.empty((0, 2), dtype=np.int64)
    if len(parts) == 1:
        return parts[0]
    return np.concatenate(parts, axis=0)
