"""Multi-host runtime: jax.distributed init, DCN x ICI hybrid meshes, and
process-local array placement.

Replaces the scale-out half of C20 (SURVEY.md §2/§5): the reference scaled
out by pointing spark-shell at a 36-core cluster + HDFS (bigclam4-7.scala:14,
45) with the Spark driver coordinating every collective as a TCP round trip.
Here scale-out is the standard JAX multi-controller model: every host runs
the same program, `jax.distributed.initialize` forms the process group, the
mesh places the "nodes" axis so that node shards within a slice exchange F
rows over ICI while only the slice-boundary hops cross DCN, and XLA
schedules the collectives — no driver in the data path (Q9).

Host-side data never materializes globally on every process at scale:
`put_sharded` gives each process only the rows its addressable devices own
(`jax.make_array_from_process_local_data`), the multi-host analog of the
reference's HDFS-partitioned RDD loads.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding

from bigclam_tpu.parallel.mesh import K_AXIS, NODES_AXIS, make_mesh

# env vars understood by initialize_distributed (standard JAX names first)
_COORD_ENVS = ("JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS")


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids: Optional[Sequence[int]] = None,
) -> bool:
    """Join the jax.distributed process group; returns True if initialized.

    Resolution order: explicit args > env vars (JAX_COORDINATOR_ADDRESS /
    COORDINATOR_ADDRESS + JAX_NUM_PROCESSES + JAX_PROCESS_ID) > no-op.
    On TPU pods jax.distributed can auto-detect everything, but we only
    auto-call it when a coordinator is named so that single-host runs (and
    the CPU test fake) never try to open a coordination channel. Idempotent:
    re-initialization is detected and skipped.
    """
    from bigclam_tpu.utils.compat import distributed_is_initialized

    def _commit_telemetry_gate():
        # the single-writer event-log gate was deferred until membership is
        # known (obs.RunTelemetry auto_gate=False); it is decidable on
        # EVERY exit of this function — including the no-coordinator
        # fallback (single process), where leaving it deferred would
        # buffer the whole run's events (stall heartbeats included) in
        # memory until finalize
        from bigclam_tpu.obs import telemetry as _obs

        t = _obs.current()
        if t is not None:
            t.commit_gate()

    if distributed_is_initialized():
        _commit_telemetry_gate()
        return True
    if coordinator_address is None:
        for k in _COORD_ENVS:
            if os.environ.get(k):
                coordinator_address = os.environ[k]
                break
    if coordinator_address is None:
        _commit_telemetry_gate()
        return False
    if num_processes is None and os.environ.get("JAX_NUM_PROCESSES"):
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and os.environ.get("JAX_PROCESS_ID"):
        process_id = int(os.environ["JAX_PROCESS_ID"])
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )
    from bigclam_tpu.obs import telemetry as _obs

    tel = _obs.current()
    if tel is not None:
        # membership is now known: commit the single-writer event-log gate
        # (events buffered since RunTelemetry construction flush here) and
        # record the join
        tel.commit_gate()
        tel.event(
            "distributed_init",
            processes=jax.process_count(),
            coordinator=coordinator_address,
        )
    return True


def slice_groups(devices: Sequence) -> Dict[int, List]:
    """Group devices by ICI slice (TPU `slice_index`; hosts/platforms without
    the attribute form one group — a single ICI domain)."""
    groups: Dict[int, List] = {}
    for d in devices:
        groups.setdefault(getattr(d, "slice_index", 0), []).append(d)
    return groups


def make_multihost_mesh(
    shape: Optional[Tuple[int, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build the (nodes, k) mesh across every process's devices.

    shape = (node_shards, k_shards); default ((num_devices, 1)). On a single
    slice this is parallel/mesh.make_mesh. Across slices the "nodes" axis is
    laid out slice-major (mesh_utils.create_hybrid_device_mesh with the DCN
    axis on "nodes"), so the ring/all-gather of F shards does consecutive
    hops over ICI and only slice boundaries cross DCN; "k" (whose collective
    is the small psum of per-edge partial dots and sumF) stays inside a
    slice.
    """
    devices = list(devices if devices is not None else jax.devices())
    if shape is None:
        shape = (len(devices), 1)
    dp, tp = shape
    if dp * tp != len(devices):
        raise ValueError(
            f"mesh shape {shape} needs {dp * tp} devices, got {len(devices)}"
        )
    groups = slice_groups(devices)
    n_slices = len(groups)
    if n_slices == 1:
        return make_mesh(shape, devices)
    if dp % n_slices != 0:
        raise ValueError(
            f"node_shards={dp} must be a multiple of the {n_slices} slices"
        )
    from jax.experimental import mesh_utils

    dev_mesh = mesh_utils.create_hybrid_device_mesh(
        mesh_shape=(dp // n_slices, tp),
        dcn_mesh_shape=(n_slices, 1),
        devices=devices,
    )
    return Mesh(dev_mesh, (NODES_AXIS, K_AXIS))


def addressable_row_bounds(
    sharding: NamedSharding, global_shape: Tuple[int, ...]
) -> Tuple[int, int]:
    """[lo, hi) rows of a dim-0-sharded global array that this process's
    devices own. Requires the process's row coverage to be contiguous (true
    for slice-major meshes, where consecutive node shards live on one host);
    raises otherwise rather than silently mis-slicing."""
    n_rows = global_shape[0]
    idx_map = sharding.addressable_devices_indices_map(global_shape)
    intervals = set()
    for idx in idx_map.values():
        r = idx[0] if idx else slice(None)
        intervals.add((r.start or 0, n_rows if r.stop is None else r.stop))
    ordered = sorted(intervals)
    lo, hi = ordered[0][0], ordered[-1][1]
    end = lo
    for s, e in ordered:       # distinct intervals must tile [lo, hi)
        if s != end:
            raise ValueError(
                "process's addressable row shards are not contiguous; "
                "use a slice-major mesh (make_multihost_mesh)"
            )
        end = e
    return lo, hi


def put_process_local(host_array: np.ndarray, sharding: NamedSharding):
    """Place a dim-0-sharded array giving jax only this process's rows."""
    lo, hi = addressable_row_bounds(sharding, host_array.shape)
    return jax.make_array_from_process_local_data(
        sharding, np.ascontiguousarray(host_array[lo:hi]), host_array.shape
    )


def host_shard_ids(
    num_shards: int,
    process_index: Optional[int] = None,
    process_count: Optional[int] = None,
) -> range:
    """Store-shard indices this process owns under the slice-major
    contiguous layout (host h of H owns shards [h*S/H, (h+1)*S/H))."""
    pid = jax.process_index() if process_index is None else process_index
    pc = jax.process_count() if process_count is None else process_count
    if pc <= 0 or num_shards % pc != 0:
        raise ValueError(
            f"num_shards={num_shards} not divisible by "
            f"process_count={pc}; compile the cache with one shard per "
            "node-shard of the mesh"
        )
    per = num_shards // pc
    return range(pid * per, (pid + 1) * per)


def load_host_shard(
    store,
    process_index: Optional[int] = None,
    process_count: Optional[int] = None,
    verify: bool = True,
):
    """This process's node-contiguous slice of a graph cache
    (graph/store.GraphStore): reads ONLY the shard files for the ranges
    this host's devices own — the multi-host ingest analog of
    put_process_local, and the reason no host ever materializes the global
    CSR on the store-backed path (parallel/sharded.py).

    The read runs under the resilience retry policy: on shared filesystems
    (GCS/NFS) a shard blob can transiently 404/stall right after ingest
    publishes it, and one wedged host read kills a gang-scheduled pod job.
    Deterministic checksum failures are NOT retried here — they classify
    fatal unless the store was opened self-healing, in which case the
    store itself quarantines and rebuilds inside the attempt."""
    from bigclam_tpu.resilience.retry import call_with_retry

    ids = host_shard_ids(store.num_shards, process_index, process_count)
    return call_with_retry(
        lambda: store.load_shard_range(ids.start, ids.stop, verify=verify),
        site="store.load_host_shard",
    )


def global_max_int(value: int) -> int:
    """Cross-process max of one host-side integer (a tiny allgather).

    The store-native tile/bucket builders (ISSUE 9) pad per-shard tile
    counts to the GLOBAL maximum so shard_map stays SPMD, but each host
    can count only its own shards' tiles — this exchanges exactly one
    int64 per process, never graph data (the files_read isolation
    contract is about bytes on disk, not the process group's metadata
    agreement). Single-process: identity, no collective."""
    if jax.process_count() == 1:
        return int(value)
    from jax.experimental import multihost_utils

    return int(
        np.max(
            multihost_utils.process_allgather(
                np.asarray([value], dtype=np.int64)
            )
        )
    )


def load_host_seed_scores(
    store,
    process_index: Optional[int] = None,
    process_count: Optional[int] = None,
    verify: bool = True,
):
    """This process's slice of the ingest-baked seed scores
    (graph/store.GraphStore.load_seed_scores): reads ONLY the phi blobs of
    the shards this host's devices own — the seeding analog of
    load_host_shard, under the same transient-retry policy."""
    from bigclam_tpu.resilience.retry import call_with_retry

    ids = host_shard_ids(store.num_shards, process_index, process_count)
    return call_with_retry(
        lambda: store.load_seed_scores(ids.start, ids.stop, verify=verify),
        site="store.load_host_seed_scores",
    )


def put_host_local(
    local_rows: np.ndarray, sharding: NamedSharding, global_shape
):
    """Place a dim-0-sharded global array from ONLY this process's rows.

    Unlike put_process_local (which slices a host-global array), the global
    array never exists anywhere: the caller hands exactly the rows this
    process's devices own (e.g. edge blocks built from a per-host graph
    shard) and the result is assembled as a global jax.Array across
    processes. Raises when the row count disagrees with the sharding's
    addressable bounds rather than silently mis-placing.
    """
    global_shape = tuple(global_shape)
    lo, hi = addressable_row_bounds(sharding, global_shape)
    local_rows = np.ascontiguousarray(local_rows)
    if local_rows.shape != (hi - lo,) + global_shape[1:]:
        raise ValueError(
            f"local rows shape {local_rows.shape} != addressable block "
            f"{(hi - lo,) + global_shape[1:]} of global {global_shape}"
        )
    return jax.make_array_from_process_local_data(
        sharding, local_rows, global_shape
    )


def put_sharded(host_array: np.ndarray, sharding: NamedSharding):
    """device_put that works under multi-controller: single-process runs use
    plain jax.device_put; multi-process runs hand each process only its own
    rows (the host_array is still parsed per host — cheap CSR ints — but
    device HBM only ever holds the local shard)."""
    if jax.process_count() == 1:
        return jax.device_put(host_array, sharding)
    return put_process_local(np.asarray(host_array), sharding)


def fetch_global(x: jax.Array) -> np.ndarray:
    """np.asarray that works under multi-controller: a globally-sharded array
    spans devices this process cannot address, so multi-process runs
    all-gather it across hosts first (every host gets the full array — fine
    for results/checkpoints, which are O(N*K) host RAM by construction)."""
    if jax.process_count() == 1:
        return np.asarray(x)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(x, tiled=True))
