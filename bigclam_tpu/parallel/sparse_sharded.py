"""Sharded sparse-representation BigCLAM trainer: top-M member lists
over the "nodes" mesh axis + the sparse allreduce (ISSUE 7 tentpole).

The dense sharded trainers exchange O(K) per node pair step: one
all_gather of the (N_loc, K) F shard plus a (K,) psum of sumF. On the
sparse representation both collectives scale with M instead:

  state     ids/w (N_pad, M) sharded P("nodes") — per-shard HBM is
            O(N_loc * M), K appears only in the (K,) sumF accumulator
  exchange  all_gather of the (N_loc, M) id/weight shards (the edge
            sweeps look up neighbor rows in the gathered copy), and
            parallel.sparse_collectives.sparse_allreduce_sum for sumF:
            only the TOUCHED community ids travel, in fixed (cap,)
            buffers sized from the initial per-shard touched counts
            (cfg.sparse_comm_cap / sparse_cap_slack) — the pattern of
            "Sparse Allreduce" (arXiv:1312.3020) for power-law data

Above the density threshold (cfg.sparse_dense_fallback) the capped
exchange would move more bytes than the (K,) psum, so the step is built
with the dense psum instead (STATIC choice, recorded in engaged_path);
a runtime admission burst past the cap falls back to the dense psum for
that step only (the overflow cond inside sparse_allreduce_sum).
Exchange-volume counters ride the state (comm_ids = max touched ids
over shards, comm_dense = 1 when a step fell back) so gates can assert
the wire volume, not just the result.

The K axis is NOT sharded here: sparse rows have no K dimension to
split (that is the point), and sumF is O(K) — the axis K-sharding
existed to shrink is gone. A mesh with tp > 1 is refused.

Math: identical to models.sparse.SparseBigClamModel per iteration
(support update -> sparse grad/LLH -> candidates -> Armijo), with the
per-shard sums psum'd exactly like parallel.sharded does for the dense
path — trajectories match the single-chip sparse trainer to float
summation order (pinned by tests/test_sparse.py).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.graph.csr import Graph
from bigclam_tpu.models.bigclam import (
    _round_up,
    attach_donating,
    edge_chunk_bound,
    log_engaged_path,
    step_cfg_key,
)
from bigclam_tpu.models.sparse import SparseBigClamModel
from bigclam_tpu.ops import sparse_members as sm
from bigclam_tpu.ops.objective import EdgeChunks
from bigclam_tpu.ops.sparse_members import SparseTrainState
from bigclam_tpu.parallel.mesh import K_AXIS, NODES_AXIS
from bigclam_tpu.parallel.multihost import fetch_global, put_sharded
from bigclam_tpu.parallel.sharded import shard_edges
from bigclam_tpu.parallel.sparse_collectives import (
    auto_cap,
    sparse_allreduce_sum,
    static_mode,
)
from bigclam_tpu.utils.compat import shard_map


def shard_touched_counts(ids: np.ndarray, dp: int, k_pad: int) -> np.ndarray:
    """(dp,) number of distinct communities present in each shard's rows
    of a host (n_pad, M) id array — the figure the sparse-allreduce cap
    is sized from (auto_cap over the max)."""
    n_pad = ids.shape[0]
    rows = n_pad // dp
    return np.array(
        [
            np.unique(
                ids[i * rows : (i + 1) * rows][
                    ids[i * rows : (i + 1) * rows] < k_pad
                ]
            ).size
            for i in range(dp)
        ],
        dtype=np.int64,
    )


def make_sparse_sharded_step(
    mesh: Mesh,
    edges: EdgeChunks,
    blocks,
    cfg: BigClamConfig,
    k_pad: int,
    m: int,
    cap: int,
    mode: str,
    block_b: int,
    n_live: Optional[int] = None,
):
    """One jitted sharded sparse iteration. `blocks` is the
    (src_local, dst, mask) triple of (dp, blocks_per_shard, eb) support
    arrays (dst GLOBAL — it indexes the gathered rows); `mode` is the
    static collective choice from sparse_collectives.static_mode;
    `n_live` the LIVE node count for the support-churn denominator
    (None falls back to the padded row count)."""
    sup_every = max(int(cfg.support_every), 1)
    use_sparse = mode == "sparse"
    from bigclam_tpu.ops import diagnostics as dx

    dp = mesh.shape[NODES_AXIS]

    def allreduce(vals, pres):
        if use_sparse:
            return sparse_allreduce_sum(vals, pres, cap, NODES_AXIS, k_pad)
        return (
            lax.psum(vals, NODES_AXIS),
            lax.pmax(pres.sum().astype(jnp.int32), NODES_AXIS),
            jnp.ones((), jnp.int32),
        )

    def step_shard(ids_loc, w_loc, it, esrc, edst, emask, bsl, bdd, bmm):
        esrc, edst, emask = esrc[0], edst[0], emask[0]
        bsl, bdd, bmm = bsl[0], bdd[0], bmm[0]
        ids0 = ids_loc

        def do_support(op):
            i0, w0 = op
            # the admission pass scores against PRE-update neighbor
            # rows, exactly like the single-chip path's defaulted
            # ids_nbr — gathered here because neighbors live on other
            # shards. The predicate is replicated over shards, so the
            # branch collectives are uniform.
            i_full = lax.all_gather(i0, NODES_AXIS, axis=0, tiled=True)
            wn_full = lax.all_gather(w0, NODES_AXIS, axis=0, tiled=True)
            blk = sm.SupportBlocks(
                src_local=bsl, dst=bdd, mask=bmm, block_b=block_b
            )
            return sm.support_update(
                i0, w0, blk, m, k_pad, ids_nbr=i_full, w_nbr=wn_full
            )

        ids_loc, w_loc = lax.cond(
            it % sup_every == 0, do_support, lambda op: op, (ids_loc, w_loc)
        )
        # ONE post-support gather pair feeds the grad AND all 16
        # candidate sweeps (the dense trainers' single all_gather of F,
        # at M columns instead of K)
        ids_full = lax.all_gather(ids_loc, NODES_AXIS, axis=0, tiled=True)
        w_full = lax.all_gather(w_loc, NODES_AXIS, axis=0, tiled=True)
        pres = sm.presence(ids_loc, k_pad)
        sumF, cnt, fb = allreduce(
            sm.sparse_sumF(ids_loc, w_loc, k_pad), pres
        )
        ec = EdgeChunks(src=esrc, dst=edst, mask=emask)
        grad, node_llh = sm.sparse_grad_llh(
            ids_loc, w_loc, sumF, ec, cfg, k_pad,
            ids_dst=ids_full, w_dst=w_full,
        )
        llh_cur = lax.psum(node_llh.sum(), NODES_AXIS)
        cand_nbr = sm.sparse_candidates(
            ids_loc, w_loc, grad, ec, cfg, k_pad,
            ids_dst=ids_full, w_dst=w_full,
        )
        w_new, hist = sm.sparse_armijo_update(
            ids_loc, w_loc, sumF, grad, node_llh, cand_nbr, cfg, k_pad
        )
        hist = lax.psum(hist, NODES_AXIS)
        # state sumF from the UPDATED weights (ids unchanged since the
        # support pass, so the touched set — and the cap pressure — is
        # the same; counters take the max over both exchanges)
        sumF_new, cnt2, fb2 = allreduce(
            sm.sparse_sumF(ids_loc, w_new, k_pad), pres
        )
        if dx.health_on(cfg):
            gstats = dx.gated_grad_stats(cfg, it, grad, node_axis=NODES_AXIS)
            # fraction of LIVE member-id slots the support admission
            # rewrote, over ALL shards' rows (psum of local changed
            # counts over a static global slot count; padding rows have
            # no edges and never admit, so the padded count would
            # dilute it) — computed EVERY step (one cheap comparison +
            # psum) so the wrapper's latch can carry off-cadence bursts
            # to the next sample; the O(N*M) grad reductions above are
            # cadence-gated instead
            slots = float(max(n_live or ids_loc.shape[0] * dp, 1) * m)
            churn = lax.psum(
                jnp.sum((ids_loc != ids0).astype(jnp.float32)), NODES_AXIS
            ) / slots
        else:
            gstats = dx.zero_grad_stats()
            churn = jnp.zeros((), jnp.float32)
        return (
            w_new,
            ids_loc,
            sumF_new,
            llh_cur.astype(w_loc.dtype),
            it + 1,
            hist,
            jnp.maximum(cnt, cnt2),
            jnp.maximum(fb, fb2),
            gstats,
            churn,
        )

    espec = P(NODES_AXIS, None, None)

    def step(state: SparseTrainState, esrc, edst, emask, bsl, bdd, bmm):
        # check_vma=False: the shared sparse kernels build their scan
        # carries/scatter targets as replicated zeros accumulated with
        # shard-varying values, which the replication checker cannot
        # type; the semantics are pinned by the single-chip-equivalence
        # tests (tests/test_sparse.py)
        w, ids, sumF, llh, it, hist, cnt, fb, gstats, churn = shard_map(
            step_shard,
            mesh=mesh,
            in_specs=(
                P(NODES_AXIS, None),
                P(NODES_AXIS, None),
                P(),
                espec, espec, espec,
                espec, espec, espec,
            ),
            out_specs=(
                P(NODES_AXIS, None), P(NODES_AXIS, None),
                P(), P(), P(), P(), P(), P(), P(), P(),
            ),
            check_vma=False,
        )(state.ids, state.F, state.it, esrc, edst, emask, bsl, bdd, bmm)
        health = None
        if dx.health_on(cfg):
            extras = {"support_churn": churn}
            if use_sparse:
                # comm-cap pressure (the figure that validates the build-
                # time cap guess, arXiv:1312.3020): touched ids vs the
                # static cap, plus the runtime dense-psum fallback flag.
                # NA in static-psum mode — there is no cap to overflow
                extras["cap_occupancy"] = cnt.astype(jnp.float32) / float(
                    max(cap, 1)
                )
                extras["dense_fallback"] = fb.astype(jnp.float32)
                extras["exchanged_ids"] = cnt.astype(jnp.float32)
            # max-since-last-sample latch riding state.health: a dense
            # fallback / cap spike / admission burst on an OFF-cadence
            # step still shows in the next emitted sample
            extras, carry = dx.latch_extras(state.health, extras)
            health = dx.health_pack(
                cfg, state.it, state.F, w, sumF, hist, gstats,
                extras=extras, skip_carry=carry,
            )
        return SparseTrainState(
            F=w, ids=ids, sumF=sumF, llh=llh, it=it,
            accept_hist=hist, comm_ids=cnt, comm_dense=fb, health=health,
        )

    # edge/block arrays as jit ARGUMENTS (multi-controller: no closing
    # over non-addressable-device arrays; see make_sharded_train_step)
    jitted = jax.jit(step)
    fixed = (
        edges.src, edges.dst, edges.mask,
        blocks[0], blocks[1], blocks[2],
    )

    def step_fn(state):
        return jitted(state, *fixed)

    step_fn.jitted = jitted
    step_fn.jit_args = fixed
    return attach_donating(step_fn, step, fixed_args=fixed)


class SparseShardedBigClamModel(SparseBigClamModel):
    """Multi-chip sparse-representation trainer over the "nodes" axis.

    Usage:
        mesh = make_mesh((dp, 1))
        model = SparseShardedBigClamModel(graph, cfg, mesh)
        result = model.fit(F0)       # F0: dense (N, K) init, sparsified
    """

    def __init__(
        self, g: Graph, cfg: BigClamConfig, mesh: Mesh, dtype=None,
        balance: bool = False,
    ):
        if mesh.shape[K_AXIS] != 1:
            raise ValueError(
                "the sparse representation does not shard the K axis "
                f"(mesh has tp={mesh.shape[K_AXIS]}): member rows are "
                "M-wide regardless of K — use a (dp, 1) mesh"
            )
        if balance:
            raise ValueError(
                "balance=True is not supported on the sparse sharded "
                "trainer yet; pre-balance at ingest (cli ingest "
                "--balance) instead"
            )
        self.mesh = mesh
        self.dp = mesh.shape[NODES_AXIS]
        super().__init__(g, cfg, dtype=dtype)

    def _path_reason(self) -> str:
        return (
            f"representation=sparse M={self.m} comm={self.comm_mode} "
            f"cap={self.comm_cap}"
        )

    # ------------------------------------------------------------ build
    def _setup(self) -> None:
        g, cfg, dp = self.g, self.cfg, self.dp
        # support blocks cannot straddle shards: cap the block size at
        # the per-shard row count (the parent sized it against the whole
        # graph, which would hand shard 0 every row on small graphs)
        self.block_b = sm.pick_block_b(
            cfg.sparse_score_block, -(-g.num_nodes // dp), self.m,
            g.num_directed_edges / max(g.num_nodes, 1),
        )
        # whole support blocks per shard: every shard owns an equal
        # number of block_b-row blocks
        self.n_pad = _round_up(max(g.num_nodes, dp), dp * self.block_b)
        espec = NamedSharding(self.mesh, P(NODES_AXIS, None, None))
        bound = edge_chunk_bound(cfg, self.m, self.dtype)
        eh = shard_edges(
            g, cfg, dp, self.n_pad, np.float32, chunk_bound=bound
        )
        self._edges = EdgeChunks(
            src=put_sharded(eh.src, espec),
            dst=put_sharded(eh.dst, espec),
            mask=put_sharded(eh.mask.astype(self.dtype), espec),
        )
        sl, dd, mm = sm.support_blocks_host(g, self.n_pad, self.block_b)
        bps = (self.n_pad // self.block_b) // dp
        eb = sl.shape[1]
        self._blocks = (
            put_sharded(sl.reshape(dp, bps, eb), espec),
            put_sharded(dd.reshape(dp, bps, eb), espec),
            put_sharded(mm.reshape(dp, bps, eb).astype(self.dtype), espec),
        )
        # collective capacity: a build-time guess of one M row per shard
        # with slack; _on_init_sparsified refines it from the REAL
        # initial touched counts and rebuilds the step when it moves
        self._set_comm(max(self.m, 8))
        self._step, self.engaged_path = self._make_step()
        # per-shard balance telemetry (obs.comms, ISSUE 10): same skew
        # accounting as the dense sharded trainers — member-list rows do
        # not change who owns which edges. Guarded like the dense path:
        # the O(E) mask sum + searchsorted are only worth paying when a
        # telemetry run will receive the event
        from bigclam_tpu.obs import comms as _comms
        from bigclam_tpu.obs import telemetry as _obs

        if _obs.current() is not None:
            from bigclam_tpu.ops.csr_tiles import tile_pad_stats
            from bigclam_tpu.parallel.sharded import shard_edge_counts

            _comms.emit_shard_balance(
                "shard_edges",
                shard_edge_counts(g.src, self.n_pad, dp), dp,
                process_count=jax.process_count(),
                hint="pre-balance at ingest (cli ingest --balance)",
                model=type(self).__name__, dp=dp,
                **tile_pad_stats(eh.mask),
            )

    def _set_comm(self, touched_per_shard: int) -> None:
        cfg = self.cfg
        if cfg.sparse_comm_cap > 0:
            self.comm_cap = min(
                _round_up(cfg.sparse_comm_cap, 8), self.k_pad
            )
        else:
            self.comm_cap = auto_cap(
                touched_per_shard, self.k_pad, cfg.sparse_cap_slack, self.m
            )
        self.comm_mode = static_mode(
            self.comm_cap, self.k_pad, cfg.sparse_dense_fallback
        )
        self._emit_comm_event(touched_per_shard)
        # bytes-per-step model of the collective layout just committed
        # (obs.comms, ISSUE 10). Rebuilt — and re-emitted, overwriting
        # the per-site totals — whenever the cap refinement moves the
        # layout, so the run report prices the step that actually runs.
        from bigclam_tpu.obs import comms as _comms

        self.comms = self._build_comms_model()
        _comms.emit_model(self.comms)
        # memory model rides the collective layout (obs.memory, ISSUE
        # 12): re-bake + re-emit (reset_model) when the cap refinement
        # moves it, so the run report prices the step that actually
        # runs. Skipped during _setup — the parent bakes the first
        # model once the step exists.
        if getattr(self, "memory", None) is not None:
            self._bake_memory_model()

    def _emit_comm_event(self, touched_per_shard: int) -> None:
        """ISSUE 8 satellite: the sparse-collective layout (cap, static
        mode, the touched-count it was sized from) as a `sparse_comm`
        telemetry event — before this it existed only in the fit-output
        dict and never reached events.jsonl or `cli report`. Emitted at
        build AND again when _on_init_sparsified refines the auto cap, so
        the event log records the layout the compiled step actually
        uses; the PER-STEP fallback/occupancy counters ride the `health`
        events (cap_occupancy / dense_fallback / exchanged_ids slots)."""
        from bigclam_tpu.obs import telemetry as _obs

        tel = _obs.current()
        if tel is not None:
            tel.event(
                "sparse_comm",
                comm_cap=int(self.comm_cap),
                comm_mode=str(self.comm_mode),
                touched_per_shard=int(touched_per_shard),
                k=int(self.k_pad),
                m=int(self.m),
                dp=int(self.dp),
            )

    def _graph_device_arrays(self) -> dict:
        e = self._edges
        sl, dd, mm = self._blocks
        return {
            "graph/edges_src": e.src,
            "graph/edges_dst": e.dst,
            "graph/edges_mask": e.mask,
            "graph/support_src": sl,
            "graph/support_dst": dd,
            "graph/support_mask": mm,
        }

    def _build_comms_model(self):
        from bigclam_tpu.obs import comms as _comms

        return _comms.sparse_step_model(
            n_pad=self.n_pad,
            m=self.m,
            k_pad=self.k_pad,
            dp=self.dp,
            itemsize=jnp.dtype(self.dtype).itemsize,
            num_candidates=len(self.cfg.step_candidates),
            cap=self.comm_cap,
            mode=self.comm_mode,
            support_every=self.cfg.support_every,
            health_every=self.cfg.health_every,
            model=type(self).__name__,
            health_participants=self.mesh.size,
        )

    def comms_measured(self, state: SparseTrainState):
        """Reconcile the static model against the RUNTIME exchange
        counters riding the state (obs.comms.sparse_measured): the
        member-gather payload from the live buffers, the allreduce from
        the exchanged-ids / dense-fallback counters — the dynamic half
        the dense trainers do not have."""
        from bigclam_tpu.obs import comms as _comms

        return _comms.sparse_measured(self.comms, state)

    def _make_step(self):
        from bigclam_tpu.ops.sparse_members import merge_pallas_want

        _merge_pallas = merge_pallas_want(self.cfg)
        return (
            make_sparse_sharded_step(
                self.mesh, self._edges, self._blocks, self.cfg,
                self.k_pad, self.m, self.comm_cap, self.comm_mode,
                self.block_b, n_live=self.g.num_nodes,
            ),
            "sparse_{}_{}".format(
                "merge_pallas" if _merge_pallas else "xla",
                "spall" if self.comm_mode == "sparse" else "psum",
            ),
        )

    def _step_key(self):
        # the collective layout is baked into the compiled step but not
        # into the config (auto cap): key it explicitly so rebuild_step
        # caches per (cfg, cap, mode)
        return (step_cfg_key(self.cfg), self.comm_cap, self.comm_mode)

    def _on_init_sparsified(self, ids: np.ndarray) -> None:
        """Size the exchange cap from the initial per-shard touched
        counts (sparse_cap_slack headroom for support growth), then
        rebuild the step if the collective layout moved."""
        counts = shard_touched_counts(ids, self.dp, self.k_pad)
        worst = int(counts.max()) if counts.size else 1
        old = (self.comm_cap, self.comm_mode)
        self._set_comm(worst)
        if (self.comm_cap, self.comm_mode) != old:
            self.rebuild_step()
            self.path_reason = (
                f"representation=sparse M={self.m} comm={self.comm_mode} "
                f"cap={self.comm_cap} (auto from {worst} touched/shard)"
            )
            log_engaged_path(
                type(self).__name__, self.engaged_path, self.path_reason
            )

    # ------------------------------------------------------------ state
    def _place(self, ids: np.ndarray, w: np.ndarray):
        spec = NamedSharding(self.mesh, P(NODES_AXIS, None))
        return (
            put_sharded(np.asarray(ids, np.int32), spec),
            put_sharded(np.asarray(w, self.dtype), spec),
        )

    def extract_F(self, state: SparseTrainState) -> np.ndarray:
        return sm.to_dense(
            fetch_global(state.ids), fetch_global(state.F),
            self.g.num_nodes, self.cfg.num_communities,
        )

    def last_comm(self, state: SparseTrainState):
        """(max touched ids exchanged, dense-fallback flag) of the last
        step — the exchange-volume counters the gates assert."""
        return int(state.comm_ids), bool(int(state.comm_dense))

    # ------------------------------------------------------ checkpoints
    def _ckpt_meta(self) -> dict:
        meta = super()._ckpt_meta()
        # a different shard count pads rows differently; slot arrays are
        # cropped nowhere, so refuse rather than re-pad
        meta["node_shards"] = self.dp
        return meta

    def _state_to_arrays(self, state: SparseTrainState) -> dict:
        return {
            "F": fetch_global(state.F),
            "ids": fetch_global(state.ids),
            "sumF": np.asarray(state.sumF),
            "llh": np.asarray(state.llh),
            "it": np.asarray(state.it),
        }

    def _state_from_arrays(self, arrays: dict) -> SparseTrainState:
        if "ids" not in arrays:
            raise ValueError(
                "checkpoint holds no member-id array: dense-representation "
                "checkpoints cannot resume a sparse fit"
            )
        ids, w = self._place(arrays["ids"], arrays["F"])
        from bigclam_tpu.ops import diagnostics as dx

        return SparseTrainState(
            F=w,
            ids=ids,
            sumF=jnp.asarray(arrays["sumF"], self.dtype),
            llh=jnp.asarray(arrays["llh"], self.dtype),
            it=jnp.asarray(arrays["it"], jnp.int32),
            accept_hist=jnp.zeros(
                len(self.cfg.step_candidates) + 1, jnp.int32
            ),
            comm_ids=jnp.zeros((), jnp.int32),
            comm_dense=jnp.zeros((), jnp.int32),
            health=dx.init_health(self.cfg),
        )
