"""Sparse allreduce for power-law membership data (arXiv:1312.3020).

The dense sharded trainers psum a (K,) sumF every iteration. On the
sparse representation each shard's contribution touches only the
communities present in ITS member lists — power-law sparse — so the
collective here exchanges (id, value) pairs of the touched communities
only, in fixed-capacity buffers:

    compact:   local dense (K,) contribution -> (cap,) touched ids +
               values (jnp.nonzero with a static size; sentinel-padded)
    exchange:  ONE all_gather of the (cap,) id/value buffers over the
               "nodes" axis — 2 * cap * dp slots on the wire instead of
               the K-length psum lattice
    combine:   scatter-add every shard's pairs into a local dense (K,)
               accumulator (O(K) scratch is fine — sumF itself is O(K);
               it is the WIRE and the O(N*K) state that sparsity wins)

The result equals lax.psum(vals) up to float summation order (exactly,
for exactly-representable sums — pinned by tests/test_sparse.py).

OVERFLOW: the touched set only changes at support updates, but a
runtime admission burst can exceed the build-time cap. The compact pass
counts its touched ids, a pmax replicates the worst shard's count, and
a lax.cond falls back to the dense psum FOR THAT STEP — correctness
never depends on the cap, only the exchange volume does. Callers above
a density threshold (cfg.sparse_dense_fallback) should not build the
sparse collective at all (static_mode below decides).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def exchange_payload_bytes(cap: int, itemsize: int = 4) -> int:
    """Local bytes ONE shard contributes to the sparse-allreduce
    exchange: the (cap,) int32 id buffer plus the (cap,) value buffer.
    The WIRE cost of the sparse branch is this cap-sized pair regardless
    of how many ids are actually touched (occupancy below cap is
    headroom, not saved bytes) — the single payload formula the comms
    accounting (obs.comms) and the trainer's modeled-vs-measured
    reconciliation both price the exchange with."""
    return int(cap) * (4 + int(itemsize))


def auto_cap(
    touched_per_shard: int, k_pad: int, slack: float, m: int
) -> int:
    """Exchange-buffer capacity from the initial worst-shard touched
    count: slack headroom for support growth, at least one M row, never
    beyond K (cap == K degenerates to a dense-sized exchange)."""
    est = max(int(touched_per_shard), 1)
    return min(k_pad, _round_up(max(int(slack * est), m, 8), 8))


def static_mode(cap: int, k_pad: int, density_threshold: float) -> str:
    """'sparse' when the capped exchange is worth it, 'dense' when the
    cap already covers >= density_threshold of K (the psum moves fewer
    bytes than 2*cap id/value pairs would)."""
    if k_pad <= 0 or cap >= max(1.0, density_threshold * k_pad):
        return "dense"
    return "sparse"


def compact_touched(
    vals: jax.Array, pres: jax.Array, cap: int, k_pad: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(touched ids (cap,) int32 sentinel-padded with k_pad, their
    values (cap,), touched count). Ids beyond cap are DROPPED here —
    the caller's overflow cond is what keeps that correct."""
    (tids,) = jnp.nonzero(pres, size=cap, fill_value=k_pad)
    tids = tids.astype(jnp.int32)
    ok = tids < k_pad
    tvals = jnp.where(
        ok, vals[jnp.minimum(tids, k_pad - 1)], jnp.zeros((), vals.dtype)
    )
    return tids, tvals, pres.sum().astype(jnp.int32)


def sparse_allreduce_sum(
    vals: jax.Array,
    pres: jax.Array,
    cap: int,
    axis_name: str,
    k_pad: int,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Allreduce of per-shard dense (K_pad,) contributions exchanging
    only touched community ids (shard_map body helper; `pres` is this
    shard's presence mask). Returns (global sums (K_pad,), max touched
    count over shards, dense_fallback flag) — the last two are the
    exchange-volume counters the gates assert on.
    """
    tids, tvals, count = compact_touched(vals, pres, cap, k_pad)

    def dense_branch(_):
        return lax.psum(vals, axis_name)

    def sparse_branch(_):
        ai = lax.all_gather(tids, axis_name)        # (dp, cap)
        av = lax.all_gather(tvals, axis_name)
        return (
            jnp.zeros(k_pad, vals.dtype)
            .at[ai.reshape(-1)]
            .add(av.reshape(-1), mode="drop")
        )

    return capped_exchange(dense_branch, sparse_branch, count, cap, axis_name)


def capped_exchange(
    dense_fn, sparse_fn, count: jax.Array, cap: int, axis_name: str
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """The shared touched-ids exchange skeleton: pmax the per-shard
    touched `count` over `axis_name`, run `sparse_fn` when every shard
    fits the build-time `cap`, fall back to `dense_fn` FOR THIS STEP
    otherwise — one compiled step, no retrace on overflow. Both
    branches take the ignored cond operand. Returns (result, max count
    over shards, dense_fallback flag int32) — the counter pair every
    capped collective (sumF sparse-allreduce, 2D closure grad exchange)
    surfaces to its gates."""
    max_count = lax.pmax(count, axis_name)
    overflow = max_count > cap
    out = lax.cond(overflow, dense_fn, sparse_fn, operand=None)
    return out, max_count, overflow.astype(jnp.int32)


def closure_grad_allreduce(
    partial: jax.Array,
    out_tab: jax.Array,
    in_tab: jax.Array,
    count: jax.Array,
    cap: int,
    axis_name: str,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Touched-rows-only replacement for the 2D trainer's dense
    neighbor-grad psum over the cols axis (ISSUE 17 second leg — the
    arXiv:1312.3020 insight promoted from the sparse representation to
    the dense backward path via the baked closure lists).

    Every chip of a processor row holds a dense `partial` (n_row, K) of
    neighbor-grad contributions, but its edges only touch the rows its
    baked closure lists name. Instead of psumming the full row band:

      phase A (reduce):  chip j sends, for each peer c, the partial
                 rows of BLOCK c its edges touched (`out_tab[c]`,
                 group-local ids, sentinel >= n_row) — one all_to_all —
                 and scatter-adds what it receives (`in_tab`,
                 block-local ids, sentinel >= n_blk) into its own
                 (n_blk, K) block accumulator, which then holds the
                 cols-complete sums for its own rows.
      phase B (broadcast): the reverse routes: chip j sends peer c the
                 summed rows c touched (`in_tab[c]` again), receives
                 the complete sums for the rows IT touched
                 (`out_tab`), scatters them into a dense (n_row, K)
                 and overwrites its own block slot with the exact
                 accumulator (rows a chip touched in its OWN block
                 would otherwise be double-counted by the scatter).

    Untouched rows come back as their local partial — exactly 0.0,
    never written by the segment-sum — so the result equals
    lax.psum(partial, axis_name) up to float summation order, and
    bit-exactly when each row's contributions are unchanged in count
    (pinned by tests/test_fused2d.py). Tables are baked host-side
    ((C, cap) int32 each); `count` is this chip's true worst pair
    size, so an explicit cap below it degrades to the dense psum per
    step via `capped_exchange` — same counters, no recompile."""
    from bigclam_tpu.utils.compat import pcast_varying, vma_of

    n_row, k = partial.shape
    cols = out_tab.shape[0]
    n_blk = n_row // cols
    zero = jnp.zeros((), partial.dtype)

    def dense_fn(_):
        # the psum result is invariant over axis_name but the sparse
        # branch is genuinely varying (each chip keeps different rows);
        # cast so the cond branches agree in the VMA type system
        out = lax.psum(partial, axis_name)
        return (
            pcast_varying(out, (axis_name,))
            if axis_name not in vma_of(out) else out
        )

    def sparse_fn(_):
        j = lax.axis_index(axis_name)
        # phase A: route touched partials to their owner column
        send = jnp.where(
            (out_tab < n_row)[..., None],
            partial[jnp.minimum(out_tab, n_row - 1).reshape(-1)]
            .reshape(cols, cap, k),
            zero,
        )
        recv = lax.all_to_all(send, axis_name, split_axis=0, concat_axis=0)
        recv = jnp.where((in_tab < n_blk)[..., None], recv, zero)
        blk = (
            jnp.zeros((n_blk, k), partial.dtype)
            .at[in_tab.reshape(-1)]
            .add(recv.reshape(-1, k), mode="drop")
        )
        # phase B: route the complete sums back to every toucher
        send2 = jnp.where(
            (in_tab < n_blk)[..., None],
            blk[jnp.minimum(in_tab, n_blk - 1).reshape(-1)]
            .reshape(cols, cap, k),
            zero,
        )
        recv2 = lax.all_to_all(send2, axis_name, split_axis=0, concat_axis=0)
        recv2 = jnp.where((out_tab < n_row)[..., None], recv2, zero)
        # rows this chip never touched are read by nothing downstream
        # (the cand scan only gathers at its own src rows) and stay 0 —
        # the same value their dense-psum sum would be in partial
        full = (
            jnp.zeros((n_row, k), partial.dtype)
            .at[out_tab.reshape(-1)]
            .add(recv2.reshape(-1, k), mode="drop")
        )
        # the own-block slot must come from the phase-A accumulator:
        # rows of MY block touched only by OTHER columns are absent
        # from my out_tab but still need their complete sums
        return lax.dynamic_update_slice_in_dim(full, blk, j * n_blk, axis=0)

    return capped_exchange(dense_fn, sparse_fn, count, cap, axis_name)
