"""Sparse allreduce for power-law membership data (arXiv:1312.3020).

The dense sharded trainers psum a (K,) sumF every iteration. On the
sparse representation each shard's contribution touches only the
communities present in ITS member lists — power-law sparse — so the
collective here exchanges (id, value) pairs of the touched communities
only, in fixed-capacity buffers:

    compact:   local dense (K,) contribution -> (cap,) touched ids +
               values (jnp.nonzero with a static size; sentinel-padded)
    exchange:  ONE all_gather of the (cap,) id/value buffers over the
               "nodes" axis — 2 * cap * dp slots on the wire instead of
               the K-length psum lattice
    combine:   scatter-add every shard's pairs into a local dense (K,)
               accumulator (O(K) scratch is fine — sumF itself is O(K);
               it is the WIRE and the O(N*K) state that sparsity wins)

The result equals lax.psum(vals) up to float summation order (exactly,
for exactly-representable sums — pinned by tests/test_sparse.py).

OVERFLOW: the touched set only changes at support updates, but a
runtime admission burst can exceed the build-time cap. The compact pass
counts its touched ids, a pmax replicates the worst shard's count, and
a lax.cond falls back to the dense psum FOR THAT STEP — correctness
never depends on the cap, only the exchange volume does. Callers above
a density threshold (cfg.sparse_dense_fallback) should not build the
sparse collective at all (static_mode below decides).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def exchange_payload_bytes(cap: int, itemsize: int = 4) -> int:
    """Local bytes ONE shard contributes to the sparse-allreduce
    exchange: the (cap,) int32 id buffer plus the (cap,) value buffer.
    The WIRE cost of the sparse branch is this cap-sized pair regardless
    of how many ids are actually touched (occupancy below cap is
    headroom, not saved bytes) — the single payload formula the comms
    accounting (obs.comms) and the trainer's modeled-vs-measured
    reconciliation both price the exchange with."""
    return int(cap) * (4 + int(itemsize))


def auto_cap(
    touched_per_shard: int, k_pad: int, slack: float, m: int
) -> int:
    """Exchange-buffer capacity from the initial worst-shard touched
    count: slack headroom for support growth, at least one M row, never
    beyond K (cap == K degenerates to a dense-sized exchange)."""
    est = max(int(touched_per_shard), 1)
    return min(k_pad, _round_up(max(int(slack * est), m, 8), 8))


def static_mode(cap: int, k_pad: int, density_threshold: float) -> str:
    """'sparse' when the capped exchange is worth it, 'dense' when the
    cap already covers >= density_threshold of K (the psum moves fewer
    bytes than 2*cap id/value pairs would)."""
    if k_pad <= 0 or cap >= max(1.0, density_threshold * k_pad):
        return "dense"
    return "sparse"


def compact_touched(
    vals: jax.Array, pres: jax.Array, cap: int, k_pad: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(touched ids (cap,) int32 sentinel-padded with k_pad, their
    values (cap,), touched count). Ids beyond cap are DROPPED here —
    the caller's overflow cond is what keeps that correct."""
    (tids,) = jnp.nonzero(pres, size=cap, fill_value=k_pad)
    tids = tids.astype(jnp.int32)
    ok = tids < k_pad
    tvals = jnp.where(
        ok, vals[jnp.minimum(tids, k_pad - 1)], jnp.zeros((), vals.dtype)
    )
    return tids, tvals, pres.sum().astype(jnp.int32)


def sparse_allreduce_sum(
    vals: jax.Array,
    pres: jax.Array,
    cap: int,
    axis_name: str,
    k_pad: int,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Allreduce of per-shard dense (K_pad,) contributions exchanging
    only touched community ids (shard_map body helper; `pres` is this
    shard's presence mask). Returns (global sums (K_pad,), max touched
    count over shards, dense_fallback flag) — the last two are the
    exchange-volume counters the gates assert on.
    """
    tids, tvals, count = compact_touched(vals, pres, cap, k_pad)
    max_count = lax.pmax(count, axis_name)
    overflow = max_count > cap

    def dense_branch(_):
        return lax.psum(vals, axis_name)

    def sparse_branch(_):
        ai = lax.all_gather(tids, axis_name)        # (dp, cap)
        av = lax.all_gather(tvals, axis_name)
        return (
            jnp.zeros(k_pad, vals.dtype)
            .at[ai.reshape(-1)]
            .add(av.reshape(-1), mode="drop")
        )

    out = lax.cond(overflow, dense_branch, sparse_branch, operand=None)
    return out, max_count, overflow.astype(jnp.int32)
