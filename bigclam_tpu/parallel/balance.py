"""Degree-balanced node relabeling: even out per-shard edge counts before
sharding.

Why: the SPMD edge layouts (parallel/sharded.py, parallel/ring.py) pad every
shard's edge bucket to the global max for static shapes, so with contiguous
node ranges a power-law graph (SNAP graphs concentrate hubs at low ids) makes
one shard own most edges and every other shard compute on padding. The
reference had the same skew as Spark partition stragglers and did nothing
about it (SURVEY.md C21; its RDD partitioning was also id-range based). Here
a host-side snake (boustrophedon) assignment — sort nodes by degree, deal
them across the dp shards alternating direction each round — relabels nodes
once at model build; the trainers run on the relabeled graph and results are
mapped back, so the transform is invisible to callers (exact up to float
summation order — neighbor lists re-sort under the new ids).

Shard row ranges are fixed by the trainers (rows = n_pad/dp, padding rows at
the tail), so per-shard node counts are forced; the snake balances the
*degree* sums within that constraint, fully vectorized (a per-node greedy
LPT loop would serialize multi-minute Python startup at Friendster scale).
Each direction-alternating round pair cancels the within-round monotone
skew; in practice the max/mean per-shard edge ratio on SNAP graphs drops
from 2-4x to ~1.0.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from bigclam_tpu.graph.csr import Graph


def balance_permutation(degrees: np.ndarray, dp: int, n_pad: int) -> np.ndarray:
    """Snake node->shard assignment; returns perm (old id -> new id).

    New ids are compact [0, N): shard i owns ids [i*rows, min((i+1)*rows, N))
    — the same contiguous ranges the trainers shard on — and receives exactly
    that many nodes, dealt heaviest-first in direction-alternating rounds.
    Shards whose capacity is exhausted (tail shards of the padded range) drop
    out; active shards are always an id-prefix because capacities are
    non-increasing in shard id.
    """
    n = int(degrees.shape[0])
    assert n_pad % dp == 0 and n_pad >= n, (n_pad, dp, n)
    rows = n_pad // dp
    caps = np.minimum(np.arange(1, dp + 1) * rows, n) - np.minimum(
        np.arange(dp) * rows, n
    )
    order = np.argsort(degrees, kind="stable")[::-1]      # heaviest first
    perm = np.empty(n, dtype=np.int64)
    remaining = caps.copy()
    start = 0                                             # nodes dealt so far
    round_no = 0                                          # global snake parity
    while start < n:
        active = np.flatnonzero(remaining > 0)
        m = active.size
        full_rounds = min(int(remaining[active].min()), (n - start) // m)
        if full_rounds > 0:
            blk = order[start : start + full_rounds * m].reshape(
                full_rounds, m
            ).copy()
            odd = (round_no + np.arange(full_rounds)) % 2 == 1
            blk[odd] = blk[odd, ::-1]                     # snake direction
            filled = (caps[active] - remaining[active])[None, :]
            slots = active[None, :] * rows + filled + np.arange(
                full_rounds
            )[:, None]
            perm[blk] = slots
            remaining[active] -= full_rounds
            start += full_rounds * m
            round_no += full_rounds
        else:                                             # final partial round
            rem = n - start
            act = active[::-1] if round_no % 2 else active
            sel = act[:rem]
            perm[order[start:]] = sel * rows + (caps[sel] - remaining[sel])
            remaining[sel] -= 1
            start = n
    return perm


def balance_graph(g: Graph, dp: int, n_pad: int) -> Tuple[Graph, np.ndarray]:
    """(relabeled graph, perm). F rows map as F_new[perm[u]] = F_old[u];
    map device results back with F_old = F_new[perm]."""
    perm = balance_permutation(g.degrees, dp, n_pad)
    return g.permute(perm), perm


def shard_edge_counts(g: Graph, dp: int, n_pad: int) -> np.ndarray:
    """Directed-edge count owned by each of the dp contiguous row shards."""
    rows = n_pad // dp
    return np.bincount(g.src // rows, minlength=dp)[:dp]
